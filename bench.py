"""Headline benchmark: simulated-seconds per wall-second on the north-star
workload — 10k-host tgen-TCP all-to-all on a 2D torus (BASELINE.json's
"10k-host tgen all-to-all"; bulk Reno TCP flows between every host pair).

The reference publishes no benchmark tables (SURVEY.md §6) and its scheduler
cannot run here (it requires real managed Linux processes), so `vs_baseline`
is the TPU engine's ratio over the SAME engine executed on the host CPU —
the stand-in for the reference's thread-per-core CPU scheduler that the
north star targets (>=10x on v5e).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "events",
"phold_10k_sim_s_per_wall_s"} — the last key keeps the PHOLD headline
tracked since round 1 as a secondary continuity metric.

Usage: python bench.py                    (full: TPU + CPU-subprocess baseline)
       python bench.py --config N [--cpu] (one BASELINE config, 1-12)
       python bench.py --self [--cpu]     (bare PHOLD ratio, prints a float)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

SMALL = bool(os.environ.get("SHADOW_TPU_BENCH_SMALL"))
NUM_HOSTS = 512 if SMALL else 10_000
# long enough that the steady-state rate dominates: PHOLD's initial burst
# (population x hosts maturing within ~1 sim-s) and the compile chunk are
# excluded, but a short stop_time would still truncate measurement to a
# couple of chunks. Both legs are wall-budget-bounded either way.
SIM_S = 2 if SMALL else 120
CPU_SIM_S = 1 if SMALL else 60  # ratio is time-normalized; budget-bounded


# The reference's PHOLD topology (src/test/phold/phold.yaml: one graph node,
# 50 ms latency, 1 Gbit): the 50 ms lookahead is what makes PHOLD a fair
# PDES benchmark — windows span 50 ms of simulated time per barrier.
PHOLD_GML = """
graph [
  directed 0
  node [
    id 0
    host_bandwidth_down "1 Gbit"
    host_bandwidth_up "1 Gbit"
  ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def bench_config(num_hosts: int, stop_s: int, rounds_per_chunk: int = 512) -> dict:
    # PHOLD (SURVEY.md §4.4: the reference's in-repo PDES workload) scaled to
    # the 10k-host point: every host holds jobs, matures them after an
    # exponential delay, and forwards to a uniform-random peer — pure
    # steady-state round-loop + cross-shard exchange stress.
    return {
        "general": {"stop_time": f"{stop_s} s", "seed": 1},
        "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
        "experimental": {
            # static shapes sized to the workload: Poisson(~0.5) events per
            # host per 50 ms window, budgeted with head-room
            "event_queue_capacity": 16,
            "sends_per_host_round": 6,
            # many rounds per dispatch: at ~2.5 ms/round the per-chunk
            # dispatch overhead (~100 ms through a tunneled device) would
            # dominate at the old 32-round chunks. The CPU baseline leg picks
            # its own setting — the knob tunes dispatch amortization, not
            # simulation semantics.
            "rounds_per_chunk": rounds_per_chunk,
            # urgency-shed is the framework's default overflow contract;
            # measured round-2: urgency and append are within noise on this
            # workload (~46 ms/round both), so the bench runs the default
            "overflow_shed": "urgency",
        },
        "hosts": {
            "node": {
                "count": num_hosts,
                "network_node_id": 0,
                "processes": [
                    {
                        "model": "phold",
                        "model_args": {
                            "population": 2,
                            "mean_delay": "200 ms",
                            "size_bytes": 64,
                        },
                    }
                ],
            }
        },
    }


def torus_gml(side: int, lat_ms: int = 10) -> str:
    """2D torus of side x side nodes (BASELINE config 2). Every node also
    carries a self-loop at the SAME latency so same-node host pairs route
    and the conservative lookahead stays at `lat_ms` (runahead = min path
    latency — a faster self-loop would shrink every window)."""
    lines = ["graph [", "  directed 0"]
    for i in range(side * side):
        lines.append(
            f'  node [ id {i} host_bandwidth_down "1 Gbit" '
            f'host_bandwidth_up "1 Gbit" ]'
        )
    for r in range(side):
        for c in range(side):
            i = r * side + c
            right = r * side + (c + 1) % side
            down = ((r + 1) % side) * side + c
            lines.append(f'  edge [ source {i} target {i} latency "{lat_ms} ms" ]')
            if right != i:
                lines.append(
                    f'  edge [ source {i} target {right} latency "{lat_ms} ms" ]'
                )
            if down != i:
                lines.append(
                    f'  edge [ source {i} target {down} latency "{lat_ms} ms" ]'
                )
    lines.append("]")
    return "\n".join(lines)


def baseline_config(n: int, small: bool) -> tuple[dict, str, int]:
    """BASELINE.json benchmark configs; returns (config, metric_name, stop_s).

    1: 1k-host udp-echo on the basic graph        (tgen-echo analogue)
    2: 10k-host PHOLD all-to-all on a 2D torus    (routing-gather stress)
    3: 100k-host gossip flood, sparse adjacency   (CSR-in-HBM stress)
    4: 5k-relay Tor-like circuit mix              (packets + continuations)
    5: 1M-host timer-only                         (sort + barrier stress)
    6: 10k-host tgen-TCP all-to-all on the torus  (THE north-star workload:
       bulk Reno TCP flows between every host pair, BASELINE.json target)
    7: PHOLD under host churn + a lossy window    (fault-plane robustness:
       crash/restart masks, fault loss draws, and the run supervisor's
       periodic snapshots all inside the measured loop)
    8: R-replica PHOLD seed sweep (ensemble plane) (one vmapped program
       advances R replicas per dispatch; the row reports aggregate
       replica-rounds/s and the wall-clock ratio vs R sequential solo
       runs — the dispatch-amortization evidence for core/ensemble.py)
    """
    if n == 1:
        hosts = 64 if small else 1000
        cfg = {
            "general": {"stop_time": "60 s", "seed": 1},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": {"event_queue_capacity": 16,
                             "rounds_per_chunk": 512},
            "hosts": {
                "server": {
                    "network_node_id": 0,
                    "processes": [{"model": "udp_echo",
                                   "model_args": {"role": "server"}}],
                },
                "cli": {
                    "count": hosts - 1,
                    "network_node_id": 0,
                    "processes": [{
                        "model": "udp_echo",
                        "model_args": {"role": "client", "peer": "server",
                                       "interval": "100 ms",
                                       "size_bytes": 512},
                    }],
                },
            },
        }
        return cfg, "echo_1k_sim_seconds_per_wall_second", 60
    if n == 2:
        side = 4 if small else 10
        per_node = 8 if small else 100  # 10k hosts on 100 nodes
        host_groups = {
            f"n{i:03d}": {
                "count": per_node,
                "network_node_id": i,
                "processes": [{
                    "model": "phold",
                    "model_args": {"population": 2, "mean_delay": "200 ms",
                                   "size_bytes": 64},
                }],
            }
            for i in range(side * side)
        }
        # 50 ms edges to match the single-node PHOLD lookahead: the rate
        # delta vs config 0 then isolates the routing-gather cost instead of
        # being dominated by 5x more barrier rounds per simulated second
        cfg = {
            "general": {"stop_time": "120 s", "seed": 1},
            "network": {"graph": {"type": "gml",
                                  "inline": torus_gml(side, lat_ms=50)}},
            "experimental": {"event_queue_capacity": 16,
                             "sends_per_host_round": 6,
                             "rounds_per_chunk": 512,
                             # adaptive merge gears (PR 4): PHOLD at
                             # population 2 stages ~1 send per host per
                             # 50 ms window against a 6-wide budget, so
                             # most chunks should run well below full
                             # merge width — the BENCH row's gear
                             # histogram (counters.gears/gear_rounds) is
                             # the low-occupancy evidence; digests stay
                             # bit-identical by the shed-exact replay
                             "merge_gears": "auto"},
            "hosts": host_groups,
        }
        return cfg, "phold_10k_torus_sim_seconds_per_wall_second", 120
    if n == 3:
        hosts = 2048 if small else 100_000
        cfg = {
            "general": {"stop_time": "30 s", "seed": 1},
            "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
            "experimental": {"event_queue_capacity": 32,
                             "sends_per_host_round": 10,
                             "rounds_per_chunk": 64},
            "hosts": {
                "pub": {
                    "network_node_id": 0,
                    # repeated floods: a fresh generation every 2 s makes
                    # this a steady-state pubsub measurement instead of a
                    # compile-dominated one-shot
                    "processes": [{"model": "gossip",
                                   "model_args": {"fanout": 8,
                                                  "publisher": True,
                                                  "publish_interval": "2 s"}}],
                },
                "sub": {
                    "count": hosts - 1,
                    "network_node_id": 0,
                    "processes": [{"model": "gossip",
                                   "model_args": {"fanout": 8}}],
                },
            },
        }
        return cfg, "gossip_100k_events_per_wall_second", 30
    if n == 4:
        n_relays = 64 if small else 5000
        n_clients = 32 if small else 2500
        cfg = {
            "general": {"stop_time": "60 s", "seed": 1},
            "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
            "experimental": {"event_queue_capacity": 32,
                             "sends_per_host_round": 8,
                             "rounds_per_chunk": 256},
            "hosts": {
                "relay": {
                    "count": n_relays,
                    "network_node_id": 0,
                    "processes": [{"model": "circuit",
                                   "model_args": {"role": "relay"}}],
                },
                "cli": {
                    "count": n_clients,
                    "network_node_id": 0,
                    "processes": [{"model": "circuit",
                                   "model_args": {"role": "client",
                                                  "interval": "400 ms"}}],
                },
            },
        }
        return cfg, "circuit_5k_relay_sim_seconds_per_wall_second", 60
    if n == 5:
        hosts = 4096 if small else 1_000_000
        # NO static-shape overrides (r4, VERDICT r3 weak #9): capacities/
        # budget/chunk length auto-size from the host count
        # (ExperimentalOptions.resolve_shapes) — at 1M lanes that derives
        # the measured-good 4/1/8 (HBM fit + the XLA while-loop pathology
        # documented in BASELINE.md) from a plain config. merge_gears is
        # not a shape: it picks among programs of identical state shapes.
        cfg = {
            "general": {"stop_time": "30 s", "seed": 1},
            "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
            # adaptive merge gears on THE low-occupancy workload: timers
            # never send, so every chunk's outbox high-water is 0 and the
            # controller settles at the bottom gear — the BENCH row's gear
            # histogram (counters.gears) is the "majority of chunks below
            # full merge width" evidence. At the true 1M point the auto
            # send budget is 1, the ladder collapses, and gears self-
            # disable (resolve_gear_ladder returns []) — exactly right,
            # there is no width to shed there.
            "experimental": {"merge_gears": "auto"},
            "hosts": {
                "t": {
                    "count": hosts,
                    "network_node_id": 0,
                    # the small leg ticks 10x faster so the run spans
                    # several chunks (30 rounds is ONE 64-round chunk —
                    # the gear controller, which starts at the top and
                    # downshifts after two low chunks, would never move)
                    "processes": [{"model": "timer",
                                   "model_args": {"interval": (
                                       "100 ms" if small else "1 s")}}],
                },
            },
        }
        return cfg, "timer_1m_sim_seconds_per_wall_second", 30
    if n == 6:
        side = 4 if small else 10
        per_node = 8 if small else 100  # 10k hosts on 100 torus nodes
        # K-way microstep fold (r6): swept with tools/bench_popk.py. On
        # the CPU backend the e2e winner is K=1 — the microstep loop is
        # HANDLER-dispatch bound there (decomposed in BASELINE.md r6:
        # ~15 ms handler vs ~3 ms queue work per microstep at 10k hosts),
        # and folding grows the full-width handler-dispatch count. On TPU
        # the r5 on-chip trace shows the opposite balance (slab passes
        # dominate the ~0.5 ms microstep), which is the regime the fold
        # amortizes — K=4 is the r5-trace-predicted winner there, to be
        # measured the next time a chip is reachable. Digests are
        # bit-identical either way (tests/test_popk.py), so this knob is
        # purely a perf lever and the trajectory stays comparable.
        import jax as _jax

        microstep_events = 1 if _jax.default_backend() == "cpu" else 4
        host_groups = {
            f"n{i:03d}": {
                "count": per_node,
                "network_node_id": i,
                "processes": [{
                    "model": "tgen_tcp",
                    # enough flow cycles to keep every client busy for the
                    # whole horizon (a drained sim would fast-forward and
                    # inflate the rate); cwnd_cap stands in for the peer's
                    # advertised window (models/tgen.py divergence notes)
                    "model_args": {"flows": 8 if small else 64,
                                   "flow_segs": 20 if small else 100,
                                   "cwnd_cap": 16, "mss": 1460,
                                   "flow_gap": "50 ms",
                                   # scanned 1/2/3/4/8 on v5e: 2 is the
                                   # sweet spot between TX-event count and
                                   # per-segment engine work
                                   "tx_batch": 2},
                }],
            }
            for i in range(side * side)
        }
        cfg = {
            "general": {"stop_time": "120 s", "seed": 1},
            "network": {"graph": {"type": "gml",
                                  "inline": torus_gml(side, lat_ms=50)}},
            "experimental": {
                # Every slab pass and the merge sort scale with cap x H and
                # B x H, so both are tuned to the measured drop cliff plus
                # ~15% margin: cap 24 / B 20 drop (cap 26 is margin-free);
                # 28/24 runs the FULL 120 sim-s with zero queue/budget
                # drops and digests identical to the roomy 64/40 shapes,
                # at 10.3 vs 18.1 ms/round. Retune against the drop
                # counters if the workload changes (drops act as loss —
                # protocol-visible).
                "event_queue_capacity": 28,
                # two-level bucketed queue: 4 blocks of 7 slots (B ~ sqrt(C)
                # balances the [H, C/B] + [H, B] levels). Digests are
                # bit-identical to the flat queue (tests/test_bucketq.py);
                # the microstep pop/push pair stops paying full-capacity
                # reductions — see tools/bench_bucketq.py for the sweep.
                "event_queue_block": 7,
                "microstep_events": microstep_events,
                "sends_per_host_round": 24,
                "rounds_per_chunk": 256,
                # merge_rows deliberately unset: measured on this workload
                # (66k sends/round avg, >121k peaks) a 196k truncation was
                # behavior-clean but 2 ms/round SLOWER than the full 410k
                # permute — an XLA scheduling artifact, A/B-verified twice.
                # 128k and below shed (protocol-visible). See BASELINE.md.
            },
            "hosts": host_groups,
        }
        return cfg, "tgen_tcp_10k_torus_sim_seconds_per_wall_second", 120
    if n == 7:
        # fault-plane bench (PR 5): the PHOLD workload with ~30% of hosts
        # crash-restarting mid-run (queue-hold), a mid-run lossy/slow
        # window, and the crash-resilient supervisor snapshotting every 4
        # chunks. Measures what robustness costs on the steady-state round
        # loop: the up/down mask adds one [H, W] pass per microstep, the
        # fault window one draw per send, the supervisor one device copy
        # per 4 chunks. BENCH counters carry faults_dropped/faults_delayed
        # + the supervisor's snapshot/retry counts.
        hosts = 256 if small else 4096
        cfg = {
            "general": {"stop_time": "30 s", "seed": 1},
            "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
            "experimental": {"event_queue_capacity": 16,
                             "sends_per_host_round": 6,
                             "rounds_per_chunk": 128},
            "faults": {
                "seed": 7,
                "restart_queue": "hold",
                "host_churn": {"prob": 0.3, "mean_downtime": "2 s"},
                "loss_windows": [{"start": "10 s", "end": "15 s",
                                  "loss": 0.2, "latency_factor": 1.5}],
                "supervisor": {"snapshot_every_chunks": 4},
            },
            "hosts": {
                "node": {
                    "count": hosts,
                    "network_node_id": 0,
                    "processes": [{
                        "model": "phold",
                        "model_args": {"population": 2,
                                       "mean_delay": "200 ms",
                                       "size_bytes": 64},
                    }],
                }
            },
        }
        return cfg, "phold_churn_sim_seconds_per_wall_second", 30
    if n == 8:
        # ensemble-plane bench (PR 6): R=4 PHOLD replicas differing only
        # in seed, advanced by ONE vmapped chunk program. The comparison
        # leg runs the same four scenarios as sequential solo runs — the
        # delta is pure dispatch/fixed-cost amortization (BASELINE.md r6:
        # ~83% of the CPU microstep is full-width handler dispatch,
        # identical work per replica).
        # small leg H=8: the scenario-SCREENING shape, where per-replica
        # work is small enough for the fixed dispatch cost to dominate.
        # Measured on this box (per-chunk walls, compile chunk excluded,
        # 12-chunk runs): R=4 ensemble 15.4-16.1k replica-rounds/s vs
        # 12.5k solo => 1.24-1.29x; R=8 reaches ~17k (~1.35x). The win
        # SHRINKS as per-replica work grows — 1.12x at H=12, parity at
        # H=40, and at H>=64 the CPU backend is data-bound (ops scale
        # linearly with R) and solo runs win. Same honest posture as the
        # K-way fold (config 6): the CPU crossover is documented, the
        # dispatch-bound TPU regime (BASELINE.md r5: ~100 ms per
        # tunneled dispatch) is the predicted big winner, to be measured
        # when a chip is reachable.
        hosts = 8 if small else 4096
        stop_s = 40 if small else 30
        cfg = {
            "general": {"stop_time": f"{stop_s} s", "seed": 1},
            "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
            "experimental": {"event_queue_capacity": 16,
                             "sends_per_host_round": 6,
                             "rounds_per_chunk": 64},
            "campaign": {"seeds": [1, 2, 3, 4], "ledger_file": None},
            "hosts": {
                "node": {
                    "count": hosts,
                    "network_node_id": 0,
                    "processes": [{
                        "model": "phold",
                        "model_args": {"population": 2,
                                       "mean_delay": "200 ms",
                                       "size_bytes": 64},
                    }],
                }
            },
        }
        return cfg, "phold_seed_sweep_replica_rounds_per_second", stop_s
    if n == 9:
        # pressure-plane bench (PR 8): PHOLD with a DELIBERATELY
        # undersized queue capacity (population 6 against 8 slots — the
        # seed shapes would shed silently) under `pressure: escalate`.
        # Measures what drop-free operation costs: the first pressured
        # chunk aborts in-jit, replays once at a grown slab, and the
        # proactive headroom check absorbs further growth at chunk
        # boundaries — the BENCH row carries the regrow/replay counters
        # (counters.pressure) plus the zero drop totals that prove the
        # escalation did its job.
        hosts = 256 if small else 4096
        cfg = {
            "general": {"stop_time": "30 s", "seed": 1},
            "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
            "experimental": {"event_queue_capacity": 8,
                             "sends_per_host_round": 6,
                             "rounds_per_chunk": 128},
            "pressure": {"policy": "escalate", "max_capacity": 64},
            "hosts": {
                "node": {
                    "count": hosts,
                    "network_node_id": 0,
                    "processes": [{
                        "model": "phold",
                        "model_args": {"population": 6,
                                       "mean_delay": "200 ms",
                                       "size_bytes": 64},
                    }],
                }
            },
        }
        return cfg, "phold_pressure_sim_seconds_per_wall_second", 30
    if n == 10:
        # integrity-sentinel bench (PR 11): the flagship tgen-TCP torus
        # shapes (config 6) with the in-jit invariant guards ON — what
        # always-on SDC detection costs on the north-star workload. The
        # guards are a handful of reductions per ROUND (one [H, C]
        # compare for the slab floor + per-lane monotonicity compares),
        # amortized over the round's microsteps; the BENCH row carries
        # the integrity{transients,replays} counters so a box's scribble
        # waves show up as counted, survived events instead of silent
        # poison, and tools/bench_compare.py fails the diff if a
        # deterministic violation ever appears.
        cfg, _, stop_s = baseline_config(6, small)
        cfg["integrity"] = {"enabled": True}
        return cfg, "tgen_tcp_integrity_sim_seconds_per_wall_second", stop_s
    if n == 11:
        # timer-wheel + sort-free calendar merge bench (PR 12): the
        # flagship tgen-TCP torus (config 6) with the device timer wheel
        # and the scatter merge ON. What moves and why:
        #   - RTO/DELACK timers (10.9% of small-leg events, dominant at
        #     1M-flow scale per tools/net_report.py) leave the event
        #     queue for the [H, S] wheel, so every [H, C] slab pass
        #     (pop reductions, push free-ranking, merge free-ranking)
        #     runs at a SMALLER C — the queue no longer has to hold
        #     pending timers: capacity drops 28 -> 14 (the measured
        #     no-drop high-water 13 + 1 margin, same tuning rule as
        #     config 6's drop cliff; digests identical to the roomy
        #     shapes);
        #   - non-shedding exchange merges skip the (dst, t, order)
        #     sort entirely (merge_scatter_free's scatter-add peeling;
        #     the sort was ~70% of full-width merge cost per
        #     tools/bench_merge_gears.py).
        # Measured on this box (CPU small leg, 3 paired subprocess runs,
        # digests bit-identical to config 6's 28/7 trajectory, zero
        # drops): base 28/7 median 13.24 sim-s/wall-s vs wheel-4 +
        # cap 14/7 median 13.87 — the wheel wins every paired rep
        # (+1.2/+6.4/+3.7%) because the queue runs at HALF the slab
        # capacity (q_occ_hwm 13 with timers off-queue; cap 14 = the
        # measured no-drop high-water + 1, and a drop would be loud:
        # counted counters + bench_compare FCT gates). Wheel slots 4 =
        # measured occupancy hwm (1-2) with margin; spills are exact
        # and counted. merge_scatter stays OFF here: measured -5% on
        # this leg (the XLA-CPU sort beats scatter-peeling at 240k-row
        # full-width fan-in; the scatter's regime is low-occupancy/
        # geared rounds — tests gate its exactness either way).
        # microstep_events pins 1 (the wheel's K-fold composition is
        # follow-up work; K=1 is also config 6's measured CPU winner).
        cfg, _, stop_s = baseline_config(6, small)
        ex = cfg["experimental"]
        ex["timer_wheel"] = 4
        ex["event_queue_capacity"] = 14
        ex["event_queue_block"] = 7
        ex["microstep_events"] = 1
        cfg["observability"] = {"network": True}
        return cfg, "tgen_tcp_wheel_sim_seconds_per_wall_second", stop_s
    if n == 12:
        # fluid-traffic-plane bench (PR 13): the flagship tgen-TCP torus
        # (config 6) as the packet-exact FOREGROUND plus a flash-crowd
        # BACKGROUND schedule on the fluid plane — the first ISP/CDN-
        # scale scenario shape the pure packet engine cannot reach
        # (emulating the crowd packet-exactly would blow the event
        # budget). Four staggered background classes converge on torus
        # node 0 from t=5s (the flash ramp — EARLY, inside the
        # foreground's active phase: the fluid plane is passive, it
        # generates no events, so a drained foreground ends the sim
        # regardless of pending background windows), each demanding most
        # of a 2 Gbit access link, so background bytes dwarf the tgen
        # foreground byte volume while
        # the DropTail clip and the >= 1.0x latency coupling stay
        # honest: coupling is latency-only here (loss_max 0), so the
        # foreground sees congestion as inflated RTTs — zero unexplained
        # drops — and the FCT distribution (network{} block) quantifies
        # the foreground cost against config 6's fluid-off calibration.
        # The fluid{} block carries bg_bytes/bg_dropped for
        # tools/bench_compare.py's coverage gates.
        cfg, _, stop_s = baseline_config(6, small)
        cfg["observability"] = {"network": True}
        # shorter chunks than config 6's 256: on this box the documented
        # jaxlib-0.4.37 corruption (docs/corruption.md) hits the
        # inflated-RTT execution profile's LONG single dispatches at a
        # very high per-attempt rate (rpc=256 aborted ~9/10 attempts
        # with glibc "corrupted double-linked list"; rpc<=128 completes
        # with BIT-IDENTICAL results — bg/digest equal across every
        # surviving rpc, so this is dispatch-length exposure, not a
        # results change). 128 keeps the leg inside the classify-then-
        # retry posture's budget.
        cfg["experimental"]["rounds_per_chunk"] = 128
        cfg["fluid"] = {
            "link_capacity": "2 Gbit",
            "latency_factor_max": 1.5,
            "util_threshold": 0.5,
            "classes": [
                {"name": f"crowd{i}", "src_zone": z, "dst_zone": 0,
                 "rate": "1500 Mbit", "start": f"{5 + i} s"}
                for i, z in enumerate((1, 2, 3, 5))
            ],
        }
        return cfg, "tgen_tcp_fluid_sim_seconds_per_wall_second", stop_s
    raise SystemExit(f"unknown --config {n} (1-12 supported)")


def _campaign_worker(leg: str, small: bool, wall_budget_s: float) -> dict:
    """One bench-8 measurement leg, run in a FRESH subprocess (see
    measure_campaign for why): per-chunk walls so the parent can exclude
    the compile chunk without an extra warmup dispatch. `leg` is
    "ensemble" (the whole R-replica vmapped campaign) or "solo:<i>" (ONE
    replica built and run exactly as a solo simulation)."""
    import jax
    import numpy as _np

    from tools.campaign import build_campaign, expand_replicas, replica_config_dict
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    from shadow_tpu.obs.runtime import CompileLedger

    cfg_dict, _, _ = baseline_config(8, small)
    rpc = cfg_dict["experimental"]["rounds_per_chunk"]
    t_build = time.monotonic()
    # runtime observatory: the compile ledger records each leg's
    # program compiles precisely (jax.monitoring), so the parent's
    # runtime{} block carries measured compile wall, not an estimate
    rt_compiles = CompileLedger()
    if leg == "ensemble":
        c = build_campaign(cfg_dict)
        c.engine.attach_compile_ledger(rt_compiles)
        state, params = c.state, None
        run_chunk = c.engine.run_chunk
        r_count = c.num_replicas

        def _done(st):
            return bool(_np.asarray(jax.device_get(st.done)).all())
    else:
        idx = int(leg.split(":", 1)[1])
        spec = expand_replicas(ConfigOptions.from_dict(cfg_dict))[idx]
        sim = Simulation(
            ConfigOptions.from_dict(replica_config_dict(cfg_dict, spec)),
            world=1,
        )
        sim.engine.attach_compile_ledger(rt_compiles)
        state, params = sim.state, sim.params
        run_chunk = sim.engine.run_chunk
        r_count = 1

        def _done(st):
            return bool(st.done)
    build_s = time.monotonic() - t_build
    walls: list[float] = []
    t_run = time.monotonic()
    while not _done(state):
        t0 = time.monotonic()
        state = (
            run_chunk(state) if params is None else run_chunk(state, params)
        )
        jax.block_until_ready(state)
        walls.append(time.monotonic() - t0)
        # budget the post-compile window (walls[0] carries the compile)
        if time.monotonic() - t_run - walls[0] >= wall_budget_s:
            break
    s = jax.device_get(state.stats)
    # per-replica digests and rounds: the parent's poison gate. This
    # box's documented corruption can scribble device state WITHOUT
    # crashing (tools/soak.py classifies the same mode) — a poisoned
    # solo run yields wrong dynamics and a garbage rate, so the parent
    # accepts a solo leg only when its digest/rounds equal its ensemble
    # lane's (the vmap-vs-solo bit-identity property makes the ensemble
    # leg the free ground truth).
    digests = _np.asarray(s.digest).reshape(r_count, -1)
    rounds_arr = _np.asarray(s.rounds).reshape(r_count)
    # memory observatory: one post-run sample (modeled fallback on CPU)
    # so the config-8 BENCH row carries an hbm block like every other.
    # The ensemble leg's params live on the campaign engine (the solo
    # leg passes them explicitly).
    from shadow_tpu.obs.memory import (
        MemoryMonitor, modeled_shard_bytes, tree_bytes,
    )

    live_params = params if params is not None else c.engine._params
    memmon = MemoryMonitor([jax.devices()[0]])
    memmon.sample(modeled_bytes=modeled_shard_bytes(state, live_params))
    return {
        "hbm": memmon.report(),
        "state_bytes": tree_bytes(state),
        "leg": leg,
        "replicas": r_count,
        "rpc": rpc,
        # runtime observatory: measured compile walls + the sim horizon
        # the leg reached (feeds the parent row's runtime{} block)
        "compiles": rt_compiles.summary(),
        "sim_ns": int(_np.asarray(jax.device_get(state.now)).max()),
        "walls": [round(w, 5) for w in walls],
        "rounds": int(_np.asarray(s.rounds).sum()),
        "replica_rounds": [int(r) for r in rounds_arr],
        "replica_digests": [
            f"{int(_np.bitwise_xor.reduce(d)):016x}" for d in digests
        ],
        "events": int(_np.asarray(s.events).sum()),
        "done": _done(state),
        "build_s": round(build_s, 2),
        "queue_occupancy_hwm": int(_np.asarray(s.q_occ_hwm).max()),
        "outbox_send_hwm": int(_np.asarray(s.outbox_hwm).max()),
    }


def _corruption_rcs() -> tuple[int, ...]:
    """Worker exit signatures of this box's documented jaxlib-0.4.37
    compiled-run corruption (CHANGES.md env notes). tools/corruption.py
    owns the canonical taxonomy (stdlib-only — no test infra, no JAX)."""
    from tools.corruption import HEAP_CORRUPTION_RCS

    return HEAP_CORRUPTION_RCS


def _run_campaign_leg(leg: str, small: bool, wall_budget_s: float,
                      attempts: int = 6, timeout_s: float = 420.0,
                      validate=None) -> dict:
    """Spawn `_campaign_worker(leg)` in a fresh subprocess, retrying the
    known corruption signatures AND results `validate` rejects (the
    silent-scribble flavor: a worker that completes with poisoned device
    state — validate returns a reason string, or None to accept).
    Returns the worker's JSON dict, or {"skipped": reason} when every
    attempt died or was rejected."""
    cmd = [sys.executable, os.path.abspath(__file__),
           "--campaign-worker", leg,
           "--campaign-budget", str(wall_budget_s)]
    if small:
        cmd.append("--small")
    last = None
    for attempt in range(attempts):
        if attempt:
            time.sleep(3)  # the corruption is phase-y; spacing helps
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=timeout_s,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
        except subprocess.TimeoutExpired:
            last = "timeout"
            continue
        out = proc.stdout.strip()
        if proc.returncode == 0 and out:
            result = json.loads(out.splitlines()[-1])
            reason = validate(result) if validate is not None else None
            if reason is None:
                return result
            last = f"poisoned: {reason}"
            continue
        if proc.returncode in _corruption_rcs():
            last = f"rc={proc.returncode}"
            continue
        # any other failure is a REAL bug in the worker path (ConfigError,
        # ImportError, ...) — fail loudly, never classify it as the
        # environment
        raise RuntimeError(
            f"campaign worker {leg} failed rc={proc.returncode}: "
            f"{proc.stderr[-500:]}"
        )
    return {"leg": leg, "skipped":
            f"{attempts} attempts died of the known corruption ({last})"}


def post_compile_stats(
    walls: list[float], rounds: int | None = None, rpc: int = 0,
    replicas: int = 1,
) -> tuple[float, int | None]:
    """THE shared compile-chunk exclusion rule (runtime-observatory
    satellite): given per-chunk walls, return (post-compile wall,
    post-compile rounds). walls[0] carries the jit compile, and its
    chunk always retires the full rounds_per_chunk x replicas (no
    replica can finish before its first chunk ends), so both are
    excluded exactly. Every bench path routes its exclusion through
    here — `measure` (the --self PHOLD legs), `measure_campaign` (the
    config-8 subprocess legs this rule generalizes), and the
    runtime{} block's ex-compile rates — so sim-s/wall-s never
    silently folds a cold compile in. When the whole run fit inside
    the compile chunk, that chunk IS the measurement (counted)."""
    if len(walls) < 2:
        return max(sum(walls), 1e-9), rounds
    w = max(sum(walls[1:]), 1e-9)
    if rounds is None:
        return w, None
    return w, rounds - rpc * replicas


def _leg_run_stats(w: dict) -> tuple[float, int]:
    """(post-compile wall, post-compile rounds) for one config-8 worker
    result, via the shared `post_compile_stats` rule."""
    wall, rounds = post_compile_stats(
        w["walls"], w["rounds"], w["rpc"], w["replicas"]
    )
    return wall, rounds


def measure_campaign(small: bool, wall_budget_s: float = 120.0) -> dict:
    """BASELINE config 8: the ensemble-plane leg. Runs the R-replica
    vmapped campaign AND R sequential solo runs, each leg in a FRESH
    subprocess (this box's documented jaxlib-0.4.37 corruption targets
    exactly the solo small-dispatch pattern — tools/soak.py posture:
    retry the signature, classify honestly, never let a poisoned process
    fabricate a number), with per-chunk walls so the compile chunk drops
    out of both legs identically. Reports aggregate replica-rounds per
    wall-second and the solo/ensemble rate ratio."""
    cfg_dict, metric, stop_s = baseline_config(8, small)
    r_count = len(cfg_dict["campaign"]["seeds"])
    ens = _run_campaign_leg("ensemble", small, wall_budget_s,
                            timeout_s=wall_budget_s + 300)
    if "skipped" in ens:
        # no ensemble measurement = no metric AND no ground truth for the
        # solo poison gate — skip the solo legs entirely (each would cost
        # up to `attempts` full subprocess runs) and report the
        # classification instead of a number (soak.py SKIP posture)
        return {
            "metric": metric,
            "unit": "replica_rounds/wall_s",
            "sim_seconds": stop_s,
            "counters": {"replicas": r_count},
            "value": None,
            "skipped": ens["skipped"],
        }

    def _solo_gate(i):
        # accept a solo worker only when it reproduced its ensemble
        # lane bit-exactly (digest + rounds) — both legs done. A
        # budget-truncated leg can't be digest-checked; accept it.
        def check(w):
            if not (w["done"] and ens["done"]):
                return None
            if w["replica_rounds"][0] != ens["replica_rounds"][i]:
                return (f"rounds {w['replica_rounds'][0]} != ensemble "
                        f"lane {ens['replica_rounds'][i]}")
            if w["replica_digests"][0] != ens["replica_digests"][i]:
                return "digest mismatch vs ensemble lane"
            return None
        return check

    solos = [
        _run_campaign_leg(f"solo:{i}", small, wall_budget_s,
                          timeout_s=wall_budget_s + 300,
                          validate=_solo_gate(i))
        for i in range(r_count)
    ]
    row = {
        "metric": metric,
        "unit": "replica_rounds/wall_s",
        "sim_seconds": stop_s,
        "counters": {"replicas": r_count},
    }
    wall_ens, rounds_ens = _leg_run_stats(ens)
    row.update({
        "value": round(rounds_ens / wall_ens, 3),
        "events": ens["events"],
        "wall_seconds_ensemble": round(wall_ens, 4),
        "first_chunk_s": round(ens["walls"][0], 1),
        "build_s": ens["build_s"],
    })
    row["counters"].update({
        "rounds": ens["rounds"],
        "chunks": len(ens["walls"]),
        "queue_occupancy_hwm": ens["queue_occupancy_hwm"],
        "outbox_send_hwm": ens["outbox_send_hwm"],
    })
    if "hbm" in ens:
        # R replicas multiply the state. Deliberately NOT under the
        # `total_bytes` key other rows use (their figure is per-shard
        # state+params from static_model; this one is the stacked
        # replica-state total) — a shared key with different semantics
        # would poison cross-row diffs in tools/bench_compare.py.
        row["hbm"] = {
            **ens["hbm"],
            "model": {
                "stacked_state_bytes": ens.get("state_bytes"),
                "per_replica_state_bytes": (
                    ens.get("state_bytes", 0) // max(r_count, 1)
                ),
                "replicas": r_count,
            },
        }
    # runtime{} block (runtime observatory): the worker's compile
    # ledger + the leg's realtime factor, with the ex-compile rate so
    # sim-s/wall-s never silently folds a cold compile in (the shares
    # split needs the driver's WallLedger, which the minimal worker
    # loop does not carry — per-phase shares live on configs 1-12)
    comp = ens.get("compiles") or {}
    total_wall = max(sum(ens["walls"]), 1e-9)
    sim_s = ens.get("sim_ns", 0) / 1e9
    cw = comp.get("compile_wall_s", 0.0)
    row["runtime"] = {
        "compile_wall_s": cw,
        # the whole leg is the measured window here, so every compile
        # the worker's ledger recorded landed inside it — the factor
        # below folds them in, the ex-compile factor is the clean one
        # (bench_runtime_block semantics: window == the measured span)
        "compile_in_window_s": cw,
        "compile_programs": comp.get("programs", 0),
        "cache_hits": comp.get("cache_hits", 0),
        "realtime_factor": round(sim_s / total_wall, 4),
        "realtime_factor_ex_compile": round(
            sim_s / max(total_wall - cw, 1e-9), 4,
        ),
    }
    ok_solos = [w for w in solos if "skipped" not in w]
    if ok_solos:
        # rate ratio over the measured solos (fair even when some solo
        # workers died: rates, not raw walls, so a missing replica does
        # not deflate the solo side)
        wall_solo = sum(_leg_run_stats(w)[0] for w in ok_solos)
        rounds_solo = sum(_leg_run_stats(w)[1] for w in ok_solos)
        solo_rate = rounds_solo / wall_solo
        row.update({
            "wall_seconds_solo_total": round(wall_solo, 4),
            "solo_replicas_measured": len(ok_solos),
            "solo_replica_rounds_per_s": round(solo_rate, 3),
            "solo_over_ensemble": round(row["value"] / solo_rate, 3),
        })
    else:
        row["solo_leg_skipped"] = solos[0].get("skipped", "no solo results")
    return row


def _bench_network(sim, state, s, netcol) -> dict:
    """The BENCH row's compact network{} block: the SAME shared assembly
    sim-stats uses (obs/netobs.assemble_network_report), compacted to
    the diffable bench shape — rows cannot drift from sim-stats."""
    import numpy as _np

    from shadow_tpu.obs.netobs import (
        assemble_network_report, bench_network_block, node_map,
    )

    n = sim._num_real
    import jax as _jax

    model_view = _jax.tree.map(
        lambda a: _np.asarray(a)[:n], _jax.device_get(state.model)
    )
    return bench_network_block(assemble_network_report(
        stats=s,
        num_real=n,
        rounds=int(s.rounds),
        node_of=node_map(sim.hosts, n),
        model=sim.model,
        model_state=model_view,
        flow_ledger=sim.engine_cfg.flow_ledger_active,
        collector=netcol,
    ))


def _bench_fluid(sim, state, s) -> dict:
    """The BENCH row's compact fluid{} block: the SAME shared assembly
    sim-stats uses (net/fluid.assemble_fluid_report), compacted to the
    diffable bench shape — rows cannot drift from sim-stats."""
    import jax as _jax

    from shadow_tpu.net.fluid import assemble_fluid_report, bench_fluid_block

    return bench_fluid_block(assemble_fluid_report(
        stats=s,
        fluid_state=_jax.device_get(state.fluid),
        cfg=sim.engine_cfg,
    ))


def measure_config(n: int, small: bool, wall_budget_s: float = 120.0) -> dict:
    """Run one BASELINE config; returns the JSON-able result row."""
    if n == 8:
        # the ensemble leg has its own two-leg harness (vmapped campaign
        # vs sequential solos) — everything below assumes one Simulation
        return measure_campaign(small, wall_budget_s)
    import jax

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    from shadow_tpu.obs import RoundTracer

    cfg_dict, metric, stop_s = baseline_config(n, small)
    # the round tracer rides along (PR 3 observability): digests and event
    # counts are bit-identical with it on (tests/test_tracer.py), and the
    # drained ring hands future perf PRs the per-round decomposition the
    # first two PRs had to reconstruct by hand (BASELINE.md r5/r6).
    # Measurement note: tracing is now part of the measured configuration
    # (BENCH rows from this round on include it). Its cost inside the wall
    # window is one extra row write per round in-jit plus a per-chunk
    # device_get of the [1, R, F] i64 ring (F = tracer.TRACE_COLS,
    # ~tens of KB against a
    # multi-second 256-512-round chunk; the block_until_ready was already
    # there) — well under the run-to-run noise floor.
    cfg_dict.setdefault("observability", {})["trace"] = True
    # network observatory (PR 10): measured-in like the tracer — digests
    # are bit-identical with it on (tests/test_netobs.py), its in-jit
    # cost is a handful of [H] masks+sums per event, and the BENCH row
    # gains the network{} block (timer-event share, FCT p50/p99, link
    # hot-spot) tools/bench_compare.py diffs for flow-behavior
    # regressions, not just wall-clock ones.
    cfg_dict["observability"]["network"] = True
    cfg = ConfigOptions.from_dict(cfg_dict)
    t_build = time.monotonic()
    sim = Simulation(cfg, world=1)
    state, params, engine = sim.state, sim.params, sim.engine
    # runtime observatory (obs/runtime.py): measured in like the tracer
    # and the network observatory — host-side only, digest-identical by
    # the same gates. The compile ledger records every program the run
    # compiles (base + gear variants + pressure rungs), the WallLedger
    # splits each chunk's wall into spans, and the row gains the
    # runtime{} block tools/bench_compare.py diffs (realtime-factor
    # drop or compile-wall growth = regression).
    from shadow_tpu.obs.runtime import (
        CompileLedger, WallLedger, bench_runtime_block,
    )

    rt_compiles = CompileLedger()
    engine.attach_compile_ledger(rt_compiles)
    wallled = WallLedger()
    rt_compiles.wall = wallled
    tracer = RoundTracer(sim.engine_cfg.rounds_per_chunk)
    from shadow_tpu.obs.netobs import FlowCollector

    netcol = (
        FlowCollector(sim.engine_cfg.flow_records)
        if sim.engine_cfg.flow_ledger_active else None
    )
    # adaptive merge gears (PR 4): when the config opts in, drive chunks
    # through the same shed-exact controller loop the Simulation driver
    # uses — the BENCH row then carries the gear histogram (chunks per
    # gear + rounds per gear from the trace ring)
    from shadow_tpu.core.gears import GearController
    from shadow_tpu.core.pressure import PressureAbort, ResilienceController
    from shadow_tpu.core.supervisor import SupervisorAbort

    # HBM observatory (obs/memory.py): per-shard live sampling folded
    # into the BENCH row's `hbm` block — peak bytes per shard, the
    # static model's predicted bytes, and headroom where the backend
    # has an allocator limit (CPU backends fall back to the exact
    # modeled live bytes, so the high-water is honest, never zero).
    # Sampling is one memory_stats call + a metadata pytree walk per
    # chunk — noise-floor cost; the per-rung compiled ledger is NOT
    # computed here (it recompiles programs, which would perturb the
    # measured window).
    from shadow_tpu.obs.memory import (
        MemoryMonitor, modeled_shard_bytes, static_model,
    )

    memmon = MemoryMonitor([jax.devices()[0]])

    def _sample_memory(st):
        memmon.sample(modeled_bytes=modeled_shard_bytes(
            st, params, sim.engine_cfg.world
        ))

    gearctl = GearController(sim._gear_ladder) if sim._gear_ladder else None
    # the shared snapshot-replay loop (core/pressure.py): gears and/or
    # pressure escalation, exactly as the Simulation driver wires it —
    # config 9's BENCH row measures drop-free-under-pressure end to end
    resil = None
    if gearctl is not None or cfg.pressure.active or cfg.integrity.enabled:
        resil = ResilienceController(
            gearctl=gearctl,
            pressure=cfg.pressure if cfg.pressure.active else None,
            integrity=cfg.integrity if cfg.integrity.enabled else None,
            queue_block=sim.engine_cfg.queue_block,
            wall=wallled,
        )
    ob_hwm_run = 0  # run-wide outbox high-water (gear runs reset the
    # device counter per chunk, so the run max is folded host-side)
    # crash-resilient supervisor (PR 5): when the config opts in, chunks
    # dispatch through the same snapshot/retry loop the Simulation driver
    # uses, so the BENCH row measures robustness-on (and carries the
    # snapshot/retry counts in `counters.supervisor`)
    sup = None
    if cfg.faults.supervisor.enabled:
        from shadow_tpu.core.supervisor import ChunkSupervisor

        sup = ChunkSupervisor(
            snapshot_every_chunks=cfg.faults.supervisor.snapshot_every_chunks,
            max_retries=cfg.faults.supervisor.max_retries,
            backoff_base_s=cfg.faults.supervisor.backoff_base_ms / 1000.0,
            wall=wallled,
        )
        sup.note_state(state)

    def _step_raw(state):
        nonlocal ob_hwm_run
        if resil is None:
            state = engine.run_chunk(state, params)
            jax.block_until_ready(state)
            return state

        def dispatch(st, gear, cap, budget):
            return engine.run_chunk_resized(st, params, gear, cap, budget)

        state, _, hwm = resil.run_chunk(state, dispatch)
        ob_hwm_run = max(ob_hwm_run, hwm)
        return state

    sup_aborted = False
    press_aborted = False
    integ_aborted = False

    from shadow_tpu.core.integrity import IntegrityAbort

    def step(state):
        nonlocal sup_aborted, press_aborted, integ_aborted
        try:
            if sup is None:
                return _step_raw(state)
            return sup.run_chunk(state, _step_raw)
        except IntegrityAbort as e:
            # deterministic violation (or a persistently non-reproducing
            # one): export the last good pre-chunk snapshot — the
            # violating attempt's state is by definition corrupt — and
            # let the row carry the abort naming for bench_compare
            print(f"[integrity] aborting bench run: {e}", file=sys.stderr)
            integ_aborted = True
            sup_aborted = True  # stops the measurement loops
            good = resil.abort_export_state()
            return good if good is not None else state
        except PressureAbort as e:
            # same honest-artifacts posture as the drivers: abort policy
            # exports the dropping state, escalate-cornered the last
            # good pre-chunk snapshot (abort_export_state docs this)
            print(f"[pressure] aborting bench run: {e}", file=sys.stderr)
            press_aborted = True
            sup_aborted = True  # stops the measurement loops
            good = resil.abort_export_state()
            return good if good is not None else state
        except SupervisorAbort as e:
            # same graceful-abort contract as the drivers: the BENCH row
            # carries the completed prefix's counters, exported from the
            # supervisor's snapshot (abort_export_state docs the
            # poisoned/donation rationale; supervisor.aborted flags it)
            print(f"[supervisor] aborting bench run: {e}", file=sys.stderr)
            sup_aborted = True
            good = sup.abort_export_state()
            return good if good is not None else state

    t0 = time.monotonic()
    build_s = t0 - t_build  # capture BEFORE t0 is reused for measurement
    wallled.sync_sim(int(state.now))
    wallled.chunk_start()
    with wallled.span("dispatch"):
        state = step(state)  # compile + first chunk (controller at top)
    compile_s = time.monotonic() - t0
    with wallled.span("export"):
        tracer.drain(state.trace, wall_t0=t0, wall_t1=time.monotonic())
        if netcol is not None:
            netcol.drain(state.flows)
        _sample_memory(state)
    wallled.chunk_end(int(state.now))
    if gearctl is not None:
        # pre-warm the LOWER gear programs outside the timed window: the
        # controller reaches them only a few chunks in, and their
        # first-call jit compile would otherwise land inside the measured
        # loop and be charged to sim-s/wall-s (each runs one chunk on a
        # throwaway snapshot copy — the real state is untouched)
        from shadow_tpu.core.checkpoint import snapshot_state

        for g in sim._gear_ladder[:-1]:
            jax.block_until_ready(
                engine.run_chunk_gear(snapshot_state(state), params, g)
            )
    sim0 = int(state.now)
    ev0 = int(jax.device_get(state.stats.events).sum())
    t0 = time.monotonic()
    while not bool(state.done) and not sup_aborted:
        t_c = time.monotonic()
        wallled.chunk_start()
        with wallled.span("dispatch"):
            state = step(state)
        with wallled.span("export"):
            tracer.drain(
                state.trace, wall_t0=t_c, wall_t1=time.monotonic()
            )
            if netcol is not None:
                netcol.drain(state.flows)
            _sample_memory(state)
        wallled.chunk_end(int(state.now))
        if time.monotonic() - t0 >= wall_budget_s:
            break
    wall = max(time.monotonic() - t0, 1e-9)
    sim_adv = (int(state.now) - sim0) / 1e9
    ev_adv = int(jax.device_get(state.stats.events).sum()) - ev0
    if sim_adv <= 0 and ev_adv <= 0 and not sup_aborted:
        # whole sim fit inside the compile chunk: rebuild FRESH STATE but
        # drive it with the ALREADY-COMPILED engine (a new Engine would
        # build a new jit closure and silently recompile — the "clean"
        # run would time a second compile, which is exactly the artifact
        # this branch exists to exclude)
        sim2 = Simulation(cfg, world=1)
        state = sim2.state
        tracer = RoundTracer(sim.engine_cfg.rounds_per_chunk)  # fresh cursor
        if netcol is not None:
            netcol = FlowCollector(sim.engine_cfg.flow_records)
        if sup is not None:
            # re-arm on the FRESH state: without this, a dispatch failure
            # in the rerun loop would restore the finished first run's
            # near-done snapshot and the row would report its totals over
            # the rerun's tiny wall time
            sup.note_state(state)
        wallled.sync_sim(int(state.now))
        t0 = time.monotonic()
        while not bool(state.done) and not sup_aborted:
            t_c = time.monotonic()
            wallled.chunk_start()
            with wallled.span("dispatch"):
                state = step(state)
            with wallled.span("export"):
                tracer.drain(
                    state.trace, wall_t0=t_c, wall_t1=time.monotonic()
                )
                if netcol is not None:
                    netcol.drain(state.flows)
                _sample_memory(state)
            wallled.chunk_end(int(state.now))
        wall = max(time.monotonic() - t0, 1e-9)
        sim_adv = int(state.now) / 1e9
        ev_adv = int(jax.device_get(state.stats.events).sum())
    if sup_aborted:
        # chunks that succeeded after the supervisor's snapshot were
        # already drained, but the exported state rewound past them —
        # drop their rows so the row's trace-derived numbers cover
        # exactly the rewound prefix (truncate_to_round docs this); the
        # flow collector follows the same contract against the rewound
        # state's OWN ledger cursor, or the row's network{} block would
        # report flows the exported prefix never completed
        tracer.truncate_to_round(int(state.stats.rounds))
        if netcol is not None:
            import numpy as _np_t

            netcol.truncate_to_cursor(
                _np_t.asarray(jax.device_get(state.flows.cursor))
            )
    value = (ev_adv / wall) if "events_per" in metric else (sim_adv / wall)
    # event-density telemetry (the K-way microstep's target): how many
    # dispatches a round serializes into, and how many events each
    # dispatch retires — tracked in the BENCH trajectory from round 6 on
    import numpy as _np

    s = jax.device_get(state.stats)
    msteps = int(_np.asarray(s.microsteps).sum())
    rounds = int(s.rounds)
    events_total = int(_np.asarray(s.events).sum())
    return {
        "metric": metric,
        "value": round(value, 3),
        "unit": "events/wall_s" if "events_per" in metric else "sim_s/wall_s",
        "sim_seconds": round(sim_adv, 3),
        "events": ev_adv,
        "microsteps_per_round": round(msteps / max(rounds, 1), 2),
        "events_per_microstep": round(events_total / max(msteps, 1), 2),
        # counters snapshot (PR 3): the decomposition future perf PRs read
        # straight from the BENCH row instead of re-deriving by hand —
        # rounds_per_chunk comes from the drained trace ring (wall-paired
        # chunk records), the rest from the device counters
        "counters": {
            "rounds": rounds,
            "ici_bytes": int(_np.asarray(s.ici_bytes).sum()),
            "bq_rebuilds": int(_np.asarray(s.bq_rebuilds).sum()),
            "popk_deferred": int(_np.asarray(s.popk_deferred).sum()),
            "queue_occupancy_hwm": int(_np.asarray(s.q_occ_hwm).max()),
            "outbox_send_hwm": max(
                int(_np.asarray(s.outbox_hwm).max()), ob_hwm_run
            ),
            "rounds_per_chunk": tracer.summary()["rounds_per_chunk"],
            # fault-plane counters (PR 5): zero on fault-free configs,
            # the robustness evidence on config 7
            "faults_dropped": int(_np.asarray(s.faults_dropped).sum()),
            "faults_delayed": int(_np.asarray(s.faults_delayed).sum()),
            # pressure-plane counters (PR 8): config 9's evidence — the
            # regrow/replay accounting plus the drop totals escalation
            # kept at zero (and the capacity the run ended at)
            **(
                {
                    "pressure": {
                        **resil.report(),
                        "capacity": state.queue.t.shape[1],
                        "outbox": state.outbox.t.shape[1],
                    },
                    "pressure_regrows": (
                        resil.regrows + resil.proactive_regrows
                    ),
                    "pressure_replays": resil.replays,
                    "queue_overflow_dropped": int(
                        _np.asarray(
                            jax.device_get(state.queue.dropped)
                        ).sum()
                    ),
                }
                if resil is not None and cfg.pressure.active else {}
            ),
            # integrity-sentinel counters (PR 11): config 10's evidence
            # — transient SDC survived + sentinel replays (zero on a
            # clean box), and the deterministic-violation naming when
            # the sentinel aborted the run
            **(
                {"integrity": resil.integrity_report()}
                if resil is not None and cfg.integrity.enabled else {}
            ),
            **(
                {"supervisor": sup.report()} if sup is not None else {}
            ),
            # timer-wheel counters (PR 12): config 11's evidence — the
            # occupancy high-water + spill count (the slot-sizing
            # signal; spills are exact, never a loss) and the invariant-
            # zero wheel drop total
            **(
                {"wheel": {
                    "slots": sim.engine_cfg.wheel_slots,
                    "occupancy_hwm": int(
                        _np.asarray(s.wheel_occ_hwm).max()
                    ),
                    "spilled": int(_np.asarray(s.wheel_spilled).sum()),
                    "dropped": int(_np.asarray(
                        jax.device_get(state.wheel.dropped)
                    ).sum()),
                }}
                if sim.engine_cfg.wheel_active else {}
            ),
            # hierarchical-exchange tier counters (PR 17): the two-tier
            # byte split — intra is on-shard compaction staging, inter is
            # the wire tier ici_bytes above carries; tools/bench_compare.py
            # gates the inter tier against regressing toward the flat
            # alltoall cost
            **(
                {"exchange": {
                    "kind": "hierarchical",
                    "block": sim.engine_cfg.hier_block_size,
                    "ici_intra_bytes": int(
                        _np.asarray(s.ici_intra).sum()
                    ),
                    "ici_inter_bytes": int(
                        _np.asarray(s.ici_inter).sum()
                    ),
                }}
                if sim.engine_cfg.hier_active else {}
            ),
            # gear histogram (adaptive-exchange runs): accepted chunks per
            # gear from the controller, rounds per gear from the trace
            # ring — the low-occupancy acceptance evidence
            **(
                {"gears": gearctl.report(),
                 "gear_rounds": {str(g): n for g, n
                                 in tracer.gear_histogram().items()}}
                if gearctl is not None else {}
            ),
        },
        "first_chunk_s": round(compile_s, 1),
        "build_s": round(build_s, 1),
        # runtime block (runtime observatory, PR 14): measured compile
        # wall (ledger-precise, incl. mid-run pressure-rung compiles
        # inside the measured window), per-phase shares, and the
        # realtime factor with in-window compiles excluded — diffed by
        # tools/bench_compare.py (rt drop / compile-wall growth =
        # regression, lost block = coverage warning)
        "runtime": bench_runtime_block(
            rt_compiles, wallled, sim_adv, wall, window=(t0, t0 + wall)
        ),
        # fluid block (fluid traffic plane, PR 13): the background
        # byte/drop accounting and hot-link utilization — diffed by
        # tools/bench_compare.py as background-coverage gates (the
        # foreground cost shows up in the network{} FCT gates)
        **(
            {"fluid": _bench_fluid(sim, state, s)}
            if sim.engine_cfg.fluid_active else {}
        ),
        # network block (network observatory, PR 10): the timer-vs-packet
        # event share ROADMAP item 2's timer-wheel decision gates on, the
        # FCT distribution, and the per-link hot-spot — diffed by
        # tools/bench_compare.py so flow-behavior regressions fail the
        # comparison even when wall-clock holds
        "network": _bench_network(sim, state, s, netcol),
        # HBM block (memory observatory): per-shard peak bytes + the
        # static model's prediction + headroom — the BENCH/MULTICHIP
        # telemetry ROADMAP item 1 asks for; tools/bench_compare.py
        # diffs it across rounds
        "hbm": {
            **memmon.report(),
            "model": {
                k: v
                for k, v in static_model(
                    sim.engine_cfg, state, params
                ).items()
                if k in ("components", "state_bytes", "params_bytes",
                         "total_bytes", "per_host_bytes")
            },
        },
        # the row-level integrity block (like network/hbm): what
        # tools/bench_compare.py diffs — a deterministic violation
        # appearing in NEW is a regression, transient growth a warning
        **(
            {"integrity": resil.integrity_report()}
            if resil is not None and cfg.integrity.enabled else {}
        ),
        **({"aborted": True} if sup_aborted else {}),
        **({"pressure_aborted": True} if press_aborted else {}),
        **({"integrity_aborted": True} if integ_aborted else {}),
    }


def measure(
    num_hosts: int,
    stop_s: int,
    wall_budget_s: float = 90.0,
    rounds_per_chunk: int = 512,
) -> float:
    """sim-seconds advanced per wall-second, excluding the compile chunk.

    Bounded by `wall_budget_s` of measurement wall time so the bench always
    terminates regardless of platform speed — the rate is the metric, so a
    partial run measures the same quantity."""
    import jax

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    cfg = ConfigOptions.from_dict(
        bench_config(num_hosts, stop_s, rounds_per_chunk)
    )
    sim = Simulation(cfg, world=1)
    state, params, engine = sim.state, sim.params, sim.engine
    walls: list[float] = []
    t_c = time.monotonic()
    state = engine.run_chunk(state, params)  # compile + first chunk
    jax.block_until_ready(state)
    walls.append(time.monotonic() - t_c)
    if bool(state.done):
        # whole sim fit in the compile chunk: rebuild (compile is cached)
        # and time a clean full run
        sim = Simulation(cfg, world=1)
        t0 = time.monotonic()
        state = sim.state
        while not bool(state.done):
            state = sim.engine.run_chunk(state, sim.params)
            jax.block_until_ready(state)
        return stop_s / max(time.monotonic() - t0, 1e-9)
    sim0 = int(state.now)
    t0 = time.monotonic()
    t_c = t0
    while not bool(state.done):
        state = engine.run_chunk(state, params)
        jax.block_until_ready(state)
        now = time.monotonic()
        walls.append(now - t_c)
        t_c = now
        if now - t0 >= wall_budget_s:
            break
    # the shared compile-exclusion rule (post_compile_stats): the same
    # walls[0]-carries-the-compile convention every bench path uses
    wall, _ = post_compile_stats(walls)
    sim_advanced_s = (int(state.now) - sim0) / 1e9
    return sim_advanced_s / wall


def main() -> int:
    if "--campaign-worker" in sys.argv:
        # hidden: one subprocess-isolated config-8 measurement leg
        import jax

        jax.config.update("jax_platforms", "cpu")
        leg = sys.argv[sys.argv.index("--campaign-worker") + 1]
        budget = (
            float(sys.argv[sys.argv.index("--campaign-budget") + 1])
            if "--campaign-budget" in sys.argv else 120.0
        )
        print(json.dumps(_campaign_worker(
            leg, SMALL or "--small" in sys.argv, wall_budget_s=budget
        )))
        return 0
    if "--config" in sys.argv:
        n = int(sys.argv[sys.argv.index("--config") + 1])
        if "--cpu" in sys.argv:
            import jax

            jax.config.update("jax_platforms", "cpu")
            # baseline leg: print the bare rate for the parent to consume
            print(measure_config(n, SMALL or "--small" in sys.argv,
                                 wall_budget_s=60.0)["value"])
            return 0
        print(json.dumps(measure_config(n, SMALL or "--small" in sys.argv)))
        return 0
    if "--self" in sys.argv:
        if "--cpu" in sys.argv:
            import jax

            jax.config.update("jax_platforms", "cpu")
            print(
                measure(
                    NUM_HOSTS, CPU_SIM_S, wall_budget_s=60.0, rounds_per_chunk=128
                )
            )
        else:
            print(measure(NUM_HOSTS, SIM_S))
        return 0

    # Primary metric (round 5, VERDICT r4 #2): the north-star workload —
    # 10k-host tgen-TCP all-to-all on the 2D torus. vs_baseline is the SAME
    # engine + workload on this box's (one-core) CPU backend, as before.
    res = measure_config(6, SMALL)
    value = res["value"]
    vs = 1.0
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--config", "6", "--cpu"],
            capture_output=True,
            text=True,
            timeout=1200,  # covers CPU-backend compile + first chunk too
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        cpu_ratio = float(out.stdout.strip().splitlines()[-1])
        if cpu_ratio > 0:
            vs = value / cpu_ratio
    except Exception as e:  # baseline leg is best-effort; headline still valid
        print(f"# cpu baseline failed: {e}", file=sys.stderr)
    # secondary: the PHOLD headline tracked since round 1 (continuity)
    phold = None
    try:
        phold = round(measure(NUM_HOSTS, SIM_S, wall_budget_s=60.0), 3)
    except Exception as e:
        print(f"# phold secondary failed: {e}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "tgen_tcp_10k_torus_sim_seconds_per_wall_second",
                "value": round(value, 3),
                "unit": "sim_s/wall_s",
                "vs_baseline": round(vs, 3),
                "events": res.get("events"),
                "microsteps_per_round": res.get("microsteps_per_round"),
                "events_per_microstep": res.get("events_per_microstep"),
                "counters": res.get("counters"),
                "phold_10k_sim_s_per_wall_s": phold,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
