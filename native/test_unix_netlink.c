/* AF_UNIX stream sockets (abstract namespace, cross-process via fork) and
 * a raw rtnetlink RTM_GETADDR dump — the startup paths real network tools
 * touch. (Reference: socket/unix.rs + abstract_unix_ns.rs, netlink.rs.) */
#define _GNU_SOURCE
#include <errno.h>
#include <linux/netlink.h>
#include <linux/rtnetlink.h>
#include <arpa/inet.h>
#include <stddef.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>

static int unix_pair_test(void) {
    int lfd = socket(AF_UNIX, SOCK_STREAM, 0);
    struct sockaddr_un a;
    memset(&a, 0, sizeof a);
    a.sun_family = AF_UNIX;
    a.sun_path[0] = 0; /* abstract */
    strcpy(a.sun_path + 1, "shadow-test");
    socklen_t alen = (socklen_t)(offsetof(struct sockaddr_un, sun_path) + 1 +
                                 strlen("shadow-test"));
    if (bind(lfd, (struct sockaddr *)&a, alen)) { perror("bind"); return 1; }
    if (listen(lfd, 4)) { perror("listen"); return 1; }

    pid_t pid = fork();
    if (pid < 0) { perror("fork"); return 1; }
    if (pid == 0) {
        int c = socket(AF_UNIX, SOCK_STREAM, 0);
        if (connect(c, (struct sockaddr *)&a, alen)) { perror("connect"); _exit(2); }
        if (send(c, "ping", 4, 0) != 4) { perror("send"); _exit(3); }
        char buf[16];
        ssize_t n = recv(c, buf, sizeof buf, 0);
        if (n != 4 || memcmp(buf, "pong", 4)) { _exit(4); }
        _exit(0);
    }
    int s = accept(lfd, NULL, NULL);
    if (s < 0) { perror("accept"); return 1; }
    char buf[16];
    ssize_t n = recv(s, buf, sizeof buf, 0);
    if (n != 4 || memcmp(buf, "ping", 4)) { fprintf(stderr, "bad ping\n"); return 1; }
    if (send(s, "pong", 4, 0) != 4) { perror("send"); return 1; }
    int st = 0;
    waitpid(pid, &st, 0);
    if (!WIFEXITED(st) || WEXITSTATUS(st) != 0) {
        fprintf(stderr, "child failed %d\n", st);
        return 1;
    }
    /* rebinding the same abstract name while held must EADDRINUSE */
    int dup2fd = socket(AF_UNIX, SOCK_STREAM, 0);
    if (bind(dup2fd, (struct sockaddr *)&a, alen) == 0 || errno != EADDRINUSE) {
        fprintf(stderr, "expected EADDRINUSE\n");
        return 1;
    }
    printf("unix ok\n");
    return 0;
}

static int netlink_test(void) {
    int fd = socket(AF_NETLINK, SOCK_RAW, NETLINK_ROUTE);
    if (fd < 0) { perror("nl socket"); return 1; }
    struct sockaddr_nl sa;
    memset(&sa, 0, sizeof sa);
    sa.nl_family = AF_NETLINK;
    if (bind(fd, (struct sockaddr *)&sa, sizeof sa)) { perror("nl bind"); return 1; }
    struct {
        struct nlmsghdr nh;
        struct ifaddrmsg ifa;
    } req;
    memset(&req, 0, sizeof req);
    req.nh.nlmsg_len = NLMSG_LENGTH(sizeof(struct ifaddrmsg));
    req.nh.nlmsg_type = RTM_GETADDR;
    req.nh.nlmsg_flags = NLM_F_REQUEST | NLM_F_DUMP;
    req.nh.nlmsg_seq = 7;
    req.ifa.ifa_family = AF_INET;
    if (send(fd, &req, req.nh.nlmsg_len, 0) < 0) { perror("nl send"); return 1; }
    char buf[8192];
    int found = 0, done = 0;
    while (!done) {
        ssize_t n = recv(fd, buf, sizeof buf, 0);
        if (n <= 0) { perror("nl recv"); return 1; }
        for (struct nlmsghdr *nh = (struct nlmsghdr *)buf; NLMSG_OK(nh, n);
             nh = NLMSG_NEXT(nh, n)) {
            if (nh->nlmsg_type == NLMSG_DONE) { done = 1; break; }
            if (nh->nlmsg_type != RTM_NEWADDR) continue;
            struct ifaddrmsg *ifa = NLMSG_DATA(nh);
            int rlen = (int)IFA_PAYLOAD(nh);
            char label[32] = "?", addr[32] = "?";
            for (struct rtattr *rta = IFA_RTA(ifa); RTA_OK(rta, rlen);
                 rta = RTA_NEXT(rta, rlen)) {
                if (rta->rta_type == IFA_LABEL)
                    snprintf(label, sizeof label, "%s", (char *)RTA_DATA(rta));
                if (rta->rta_type == IFA_ADDRESS)
                    inet_ntop(AF_INET, RTA_DATA(rta), addr, sizeof addr);
            }
            printf("addr %s %s\n", label, addr);
            found++;
        }
    }
    printf("netlink ok found=%d\n", found);
    return found >= 2 ? 0 : 1;
}

static int unix_dgram_test(void) {
    /* named dgram (the syslog /dev/log shape) */
    int srv = socket(AF_UNIX, SOCK_DGRAM, 0);
    struct sockaddr_un a;
    memset(&a, 0, sizeof a);
    a.sun_family = AF_UNIX;
    a.sun_path[0] = 0;
    strcpy(a.sun_path + 1, "dgram-log");
    socklen_t alen = (socklen_t)(offsetof(struct sockaddr_un, sun_path) + 1 +
                                 strlen("dgram-log"));
    if (bind(srv, (struct sockaddr *)&a, alen)) { perror("bind"); return 1; }
    int cli = socket(AF_UNIX, SOCK_DGRAM, 0);
    if (connect(cli, (struct sockaddr *)&a, alen)) { perror("connect"); return 1; }
    /* two sends = two datagrams; boundaries must be preserved */
    if (send(cli, "first", 5, 0) != 5) { perror("send"); return 1; }
    if (sendto(cli, "second!", 7, 0, (struct sockaddr *)&a, alen) != 7) {
        perror("sendto");
        return 1;
    }
    char buf[64];
    ssize_t n1 = recv(srv, buf, sizeof buf, 0);
    if (n1 != 5 || memcmp(buf, "first", 5)) { fprintf(stderr, "dg1\n"); return 1; }
    ssize_t n2 = recv(srv, buf, sizeof buf, 0);
    if (n2 != 7 || memcmp(buf, "second!", 7)) { fprintf(stderr, "dg2\n"); return 1; }

    /* dgram socketpair */
    int sv[2];
    if (socketpair(AF_UNIX, SOCK_DGRAM, 0, sv)) { perror("socketpair"); return 1; }
    if (write(sv[0], "abc", 3) != 3) { perror("write"); return 1; }
    if (write(sv[0], "de", 2) != 2) { perror("write"); return 1; }
    if (read(sv[1], buf, sizeof buf) != 3) { fprintf(stderr, "sp1\n"); return 1; }
    if (read(sv[1], buf, sizeof buf) != 2) { fprintf(stderr, "sp2\n"); return 1; }
    printf("dgram ok\n");
    return 0;
}

int main(int argc, char **argv) {
    if (argc > 1 && !strcmp(argv[1], "netlink"))
        return netlink_test();
    if (argc > 1 && !strcmp(argv[1], "dgram"))
        return unix_dgram_test();
    return unix_pair_test();
}
