/* Real-binary UDP client: sends pings to a server over the SIMULATED
 * network and verifies the echoed replies + the simulated RTT. */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000L + ts.tv_nsec;
}

int main(int argc, char **argv) {
    const char *ip = argc > 1 ? argv[1] : "127.0.0.1";
    int port = argc > 2 ? atoi(argv[2]) : 9000;
    int count = argc > 3 ? atoi(argv[3]) : 3;
    long interval_ms = argc > 4 ? atol(argv[4]) : 100;
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in dst = {0};
    dst.sin_family = AF_INET;
    dst.sin_port = htons(port);
    if (inet_pton(AF_INET, ip, &dst.sin_addr) != 1) {
        /* not a dotted quad: resolve through the simulator's DNS */
        struct addrinfo hints = {0}, *res;
        hints.ai_family = AF_INET;
        hints.ai_socktype = SOCK_DGRAM;
        if (getaddrinfo(ip, NULL, &hints, &res) != 0 || !res) {
            perror("getaddrinfo");
            return 1;
        }
        dst.sin_addr = ((struct sockaddr_in *)res->ai_addr)->sin_addr;
        freeaddrinfo(res);
    }
    if (connect(fd, (struct sockaddr *)&dst, sizeof dst)) { perror("connect"); return 1; }
    char buf[512];
    for (int i = 0; i < count; i++) {
        char msg[64];
        int n = snprintf(msg, sizeof msg, "ping %d", i);
        long t0 = now_ns();
        if (send(fd, msg, n, 0) != n) { perror("send"); return 1; }
        ssize_t got = recv(fd, buf, sizeof buf, 0);
        if (got < 0) { perror("recv"); return 1; }
        long rtt = now_ns() - t0;
        buf[got] = 0;
        printf("reply %d: %s rtt_ns=%ld\n", i, buf, rtt);
        fflush(stdout);
        struct timespec d = {interval_ms / 1000,
                             (interval_ms % 1000) * 1000000};
        nanosleep(&d, NULL);
    }
    printf("client done\n");
    return 0;
}
