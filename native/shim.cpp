/* The in-process shim: LD_PRELOADed into every managed process.
 *
 * Reference surface being rebuilt (not ported): src/lib/shim/ —
 * seccomp filter install + SIGSYS interposition (shim_seccomp.c:36-68,
 * 189-250), local handling of hot time syscalls from the shared simulated
 * clock (shim_sys.c:25-114), the syscall dispatch loop (shim_syscall.c),
 * the clone trampoline that starts a new managed thread by rebuilding the
 * interrupted register context on the new stack (src/lib/shim/src/clone.rs),
 * and the preload-libc symbol overrides (lib/preload-libc) for vdso-destined
 * time calls that raw seccomp cannot trap.
 *
 * Mechanism:
 *   1. constructor maps the IPC block (path in SHADOW_SHM_PATH), builds a
 *      one-page syscall trampoline, installs the SIGSYS handler, then a
 *      seccomp filter that ALLOWs rt_sigreturn and any syscall issued from
 *      the trampoline page and TRAPs everything else;
 *   2. trapped syscalls hit handle_sigsys(): time syscalls answered from
 *      IpcBlock.sim_time_ns with no context switch; everything else is
 *      shipped over the thread's futex channel and either completed with the
 *      simulator's return value or re-executed natively via the trampoline
 *      when the simulator answers MSG_SYSCALL_NATIVE;
 *   3. thread clones (CLONE_VM) are a three-step handshake: the simulator
 *      allocates a channel slot, the parent re-issues the real clone onto a
 *      private bootstrap stack, and the child claims the slot, checks in
 *      (MSG_THREAD_START), then restores the interrupted context with rax=0
 *      so execution resumes inside the caller's own clone wrapper — the
 *      caller's calling convention never matters (clone.rs's approach).
 */

#define _GNU_SOURCE 1
#include <errno.h>
#include <fcntl.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/futex.h>
#include <linux/seccomp.h>
#include <sched.h>
#include <signal.h>
#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/personality.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <sys/uio.h>
#include <sys/ucontext.h>
#include <time.h>
#include <unistd.h>

#include "ipc.h"

static IpcBlock *g_ipc = nullptr;
typedef long (*raw_syscall_fn)(long n, long a, long b, long c, long d, long e,
                               long f);
static raw_syscall_fn g_raw = nullptr;
static uintptr_t g_tramp_page = 0;

/* this thread's channel slot; initial-exec TLS so no lazy __tls_get_addr
 * allocation can run inside the SIGSYS handler */
static __thread int t_slot __attribute__((tls_model("initial-exec"))) = 0;

/* ----------------------------------------------------------- trampoline */

/* mov rax,rdi; mov rdi,rsi; mov rsi,rdx; mov rdx,rcx; mov r10,r8;
 * mov r8,r9; mov r9,[rsp+8]; syscall; ret
 * (48 89 ca = mov rdx,rcx — NOT 48 89 ce, which is mov rsi,rcx and
 * silently swaps syscall args 2/3: write(fd,n,buf), openat(fd,NULL,path),
 * futex(addr,val,op) — i.e. every pointer re-issue EFAULTs and every
 * shim-side futex is a no-op) */
static const unsigned char TRAMP_CODE[] = {
    0x48, 0x89, 0xf8, 0x48, 0x89, 0xf7, 0x48, 0x89, 0xd6, 0x48, 0x89,
    0xca, 0x4d, 0x89, 0xc2, 0x4d, 0x89, 0xc8, 0x4c, 0x8b, 0x4c, 0x24,
    0x08, 0x0f, 0x05, 0xc3,
};

static int build_trampoline(void) {
    void *page = mmap(nullptr, 4096, PROT_READ | PROT_WRITE,
                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (page == MAP_FAILED)
        return -1;
    memcpy(page, TRAMP_CODE, sizeof(TRAMP_CODE));
    if (mprotect(page, 4096, PROT_READ | PROT_EXEC))
        return -1;
    g_tramp_page = (uintptr_t)page;
    g_raw = (raw_syscall_fn)page;
    return 0;
}

/* ------------------------------------------------------------- channel */

static void futex_wake(uint32_t *addr) {
    g_raw(SYS_futex, (long)addr, FUTEX_WAKE, 1 << 30, 0, 0, 0);
}

static void futex_wait(uint32_t *addr, uint32_t val) {
    g_raw(SYS_futex, (long)addr, FUTEX_WAIT, val, 0, 0, 0);
}

static void ring_doorbell(void) {
    __atomic_fetch_add(&g_ipc->doorbell, 1, __ATOMIC_RELEASE);
    futex_wake(&g_ipc->doorbell);
}

static void chan_send(ShimChan *c, const ShimMsg *m) {
    /* ping-pong: our previous message was consumed before we send again */
    while (__atomic_load_n(&c->state, __ATOMIC_ACQUIRE) == CHAN_FULL)
        futex_wait(&c->state, CHAN_FULL);
    c->msg = *m;
    __atomic_store_n(&c->state, CHAN_FULL, __ATOMIC_RELEASE);
    futex_wake(&c->state);
    ring_doorbell();
}

static int chan_recv(ShimChan *c, ShimMsg *out) {
    uint32_t s;
    while ((s = __atomic_load_n(&c->state, __ATOMIC_ACQUIRE)) != CHAN_FULL) {
        if (s == CHAN_CLOSED)
            return -1;
        futex_wait(&c->state, s);
    }
    *out = c->msg;
    __atomic_store_n(&c->state, CHAN_EMPTY, __ATOMIC_RELEASE);
    futex_wake(&c->state);
    return 0;
}

static ShimChan *to_shadow(int slot) { return &g_ipc->thread[slot].to_shadow; }
static ShimChan *to_shim(int slot) { return &g_ipc->thread[slot].to_shim; }

/* ----------------------------------------------------- time-from-shmem */

static int64_t sim_now(void) {
    return __atomic_load_n(&g_ipc->sim_time_ns, __ATOMIC_ACQUIRE);
}

static long emulate_time_syscall(long num, long a, long b) {
    int64_t now = sim_now();
    switch (num) {
    case SYS_clock_gettime: {
        struct timespec *ts = (struct timespec *)b;
        if (ts) {
            ts->tv_sec = now / 1000000000;
            ts->tv_nsec = now % 1000000000;
        }
        return 0;
    }
    case SYS_gettimeofday: {
        struct timeval *tv = (struct timeval *)a;
        if (tv) {
            tv->tv_sec = now / 1000000000;
            tv->tv_usec = (now % 1000000000) / 1000;
        }
        return 0;
    }
    case SYS_time: {
        long secs = now / 1000000000;
        if (a)
            *(long *)a = secs;
        return secs;
    }
    }
    return -ENOSYS;
}

/* --------------------------------------------------------------- sigsys */

/* unblocked-latency escape shared by the SIGSYS path and the libc
 * interposers (ONE per-thread counter): every Nth locally-answered time
 * call goes to the simulator so it can charge CPU latency — otherwise a
 * spin-on-clock loop would never advance simulated time */
static bool time_escape(void) {
    static __thread uint32_t cnt
        __attribute__((tls_model("initial-exec"))) = 0;
    uint32_t flags = __atomic_load_n(&g_ipc->flags, __ATOMIC_RELAXED);
    if ((flags & 1) && ++cnt >= (flags >> 1)) {
        cnt = 0;
        return true;
    }
    return false;
}

/* ------------------------------------------------- descriptor fast path
 * Answer write(2) on captured-stdio fds from a shared ring without a
 * context switch. The simulator owns entry registration (it re-syncs on
 * every fd-table-mutating syscall BEFORE replying, and the guest cannot
 * observe new fd meanings until that reply) and drains rings at every
 * trap, so an active entry here is always current while this code runs. */
static long fast_write(long fd, const void *buf, unsigned long len,
                       bool *hit) {
    *hit = false;
    if (!__atomic_load_n(&g_ipc->fast_enabled, __ATOMIC_ACQUIRE))
        return 0;
    for (int i = 0; i < FASTFD_MAX; i++) {
        struct FastFd *e = &g_ipc->fast[i];
        if (__atomic_load_n(&e->vfd, __ATOMIC_ACQUIRE) != fd ||
            e->kind != FAST_TX_STREAM)
            continue;
        uint64_t head = __atomic_load_n(&e->head, __ATOMIC_ACQUIRE);
        uint64_t tail = e->tail; /* we are the only producer */
        uint64_t space = FASTFD_RING_CAP - (tail - head);
        if (len > space)
            return 0; /* full: forward; the simulator drains first */
        /* every-Nth escape (shared counter with the time path) so
         * write-only loops still advance sim time under the latency
         * model: the forwarded call gets charged and drains the ring */
        if (time_escape())
            return 0;
        if (len > 0) {
            /* copy via the KERNEL, not memcpy: a bad guest buffer must
             * come back as a miss (the simulator replies -EFAULT like
             * the slow path), not SIGSEGV inside this SIGSYS handler.
             * process_vm_readv on ourselves does probe+copy atomically
             * — and note a devnull write-probe would NOT work here:
             * /dev/null's write path never reads the buffer. getpid is
             * raw (trampoline-allowed → real pid), kept uncached so
             * fork children need no refresh hook. */
            uint8_t *ring = g_ipc->fast_rings[i];
            uint64_t off = tail % FASTFD_RING_CAP;
            uint64_t first = FASTFD_RING_CAP - off;
            if (first > len)
                first = len;
            struct iovec liov[2];
            liov[0].iov_base = ring + off;
            liov[0].iov_len = first;
            int nl = 1;
            if (len > first) {
                liov[1].iov_base = ring;
                liov[1].iov_len = len - first;
                nl = 2;
            }
            struct iovec riov;
            riov.iov_base = (void *)buf;
            riov.iov_len = len;
            long self = g_raw(SYS_getpid, 0, 0, 0, 0, 0, 0);
            if (g_raw(SYS_process_vm_readv, self, (long)liov, nl,
                      (long)&riov, 1, 0) != (long)len)
                return 0; /* EFAULT/partial: simulator owns the errno */
            __atomic_store_n(&e->tail, tail + len, __ATOMIC_RELEASE);
        }
        __atomic_fetch_add(&g_ipc->fast_calls, 1, __ATOMIC_RELAXED);
        *hit = true;
        return (long)len;
    }
    return 0;
}

static long forward_msg(int kind, long num, const long args[6]) {
    ShimMsg req, resp;
    memset(&req, 0, sizeof req);
    req.kind = kind;
    req.num = num;
    if (args)
        for (int i = 0; i < 6; i++)
            req.args[i] = args[i];
    chan_send(to_shadow(t_slot), &req);
    for (;;) {
        if (chan_recv(to_shim(t_slot), &resp) != 0) {
            /* simulator went away: die quietly (ProcessDeath analogue) */
            g_raw(SYS_exit_group, 1, 0, 0, 0, 0, 0);
        }
        if (resp.kind != MSG_RUN_SIGNAL)
            break;
        /* deliver an emulated signal at this syscall boundary (the
         * reference invokes handlers under simulator control the same
         * way: handler/signal.rs). Nested handler syscalls trap and
         * forward on this same channel — the simulator services them
         * until we report MSG_SIGNAL_DONE. */
        int sig = (int)resp.num;
        if (resp.args[1]) { /* SA_SIGINFO: pass a zeroed siginfo */
            siginfo_t si;
            memset(&si, 0, sizeof si);
            si.si_signo = sig;
            ((void (*)(int, siginfo_t *, void *))resp.args[0])(sig, &si,
                                                               nullptr);
        } else {
            ((void (*)(int))resp.args[0])(sig);
        }
        ShimMsg done;
        memset(&done, 0, sizeof done);
        done.kind = MSG_SIGNAL_DONE;
        chan_send(to_shadow(t_slot), &done);
    }
    if (resp.kind == MSG_SYSCALL_NATIVE)
        return g_raw(num, args[0], args[1], args[2], args[3], args[4], args[5]);
    return resp.ret;
}

static long forward_syscall(long num, const long args[6]) {
    return forward_msg(MSG_SYSCALL, num, args);
}

/* ------------------------------------------------------- clone trampoline
 *
 * The child of a raw clone resumes at the instruction after `syscall` with
 * rax=0 on the caller-provided stack. Re-issuing clone from the SIGSYS
 * handler would resume the child inside OUR trampoline instead of the
 * app's clone wrapper, with the wrapper's child-path code skipped. So the
 * child first runs on a private bootstrap stack, checks in with the
 * simulator on its new channel slot, and then restores the complete
 * interrupted register context with rax=0 — execution continues at the
 * app's own `syscall` return point on the app-provided child stack, for
 * any caller convention (glibc clone.S, musl, raw syscall()). Reference:
 * src/lib/shim/src/clone.rs.
 */

struct CloneBoot {
    uint64_t regs[16]; /* indexed by BOOT_* below */
    int slot;
};

enum {
    B_R8, B_R9, B_R10, B_R11, B_R12, B_R13, B_R14, B_R15,
    B_RDI, B_RSI, B_RBP, B_RBX, B_RDX, B_RCX, B_RSP, B_RIP,
};

/* ------------------------------------------------- MemoryMapper window
 * Reference memory_mapper.rs:84-110: remap the program-break heap onto a
 * shared tmpfs file so the simulator reads/writes managed buffers with a
 * local memcpy instead of process_vm_readv/writev. brk(2) is handled
 * SHIM-LOCALLY from then on: growth maps further pages of the file
 * (MAP_SHARED) — or anonymous pages after a fork privatized the heap. */
static long g_heap_fd = -1;
static uintptr_t g_heap_start = 0;  /* first heap byte */
static uintptr_t g_heap_cur = 0;    /* current program break */
static uintptr_t g_heap_mapped = 0; /* page-aligned end of the mapping */
static uint32_t g_heap_lock = 0;    /* brk is rare; tiny spinlock */

static void heap_lock(void) {
    while (__atomic_exchange_n(&g_heap_lock, 1, __ATOMIC_ACQUIRE))
        ;
}
static void heap_unlock(void) {
    __atomic_store_n(&g_heap_lock, 0, __ATOMIC_RELEASE);
}

static long forward_syscall(long num, const long args[6]);

static long do_brk(long addr_l) {
    uintptr_t addr = (uintptr_t)addr_l;
    if (!g_heap_start) { /* window setup failed: plain passthrough */
        long a[6] = {addr_l, 0, 0, 0, 0, 0};
        return forward_syscall(SYS_brk, a);
    }
    heap_lock();
    uintptr_t cur = g_heap_cur;
    if (addr == 0 || addr < g_heap_start ||
        addr > g_heap_start + SHADOW_HEAP_MAX) {
        heap_unlock();
        return (long)cur; /* query or out-of-range: report current break */
    }
    if (addr > g_heap_mapped) {
        uintptr_t page_end = (addr + 4095) & ~(uintptr_t)4095;
        long rc;
        if (g_heap_fd >= 0)
            rc = g_raw(SYS_mmap, (long)g_heap_mapped,
                       (long)(page_end - g_heap_mapped),
                       PROT_READ | PROT_WRITE, MAP_SHARED | MAP_FIXED,
                       g_heap_fd, (long)(g_heap_mapped - g_heap_start));
        else
            rc = g_raw(SYS_mmap, (long)g_heap_mapped,
                       (long)(page_end - g_heap_mapped),
                       PROT_READ | PROT_WRITE,
                       MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED, -1, 0);
        if ((unsigned long)rc >= (unsigned long)-4095) {
            heap_unlock();
            return (long)cur; /* growth failed: break unchanged */
        }
        g_heap_mapped = page_end;
    }
    if (addr < cur) {
        /* kernel brk SHRINK frees whole pages, so a later regrowth sees
         * zeros — glibc's sysmalloc asserts on that (top-chunk invariant
         * blew up in fork children when stale bytes reappeared). Keep the
         * pages mapped but zero them like the kernel would. */
        uintptr_t lo = (addr + 4095) & ~(uintptr_t)4095;
        uintptr_t hi = (cur + 4095) & ~(uintptr_t)4095;
        if (hi > lo && hi <= g_heap_mapped)
            memset((void *)lo, 0, hi - lo);
    }
    g_heap_cur = addr;
    if (g_ipc && g_heap_fd >= 0)
        __atomic_store_n(&g_ipc->heap_cur, (uint64_t)addr, __ATOMIC_RELEASE);
    heap_unlock();
    return (long)addr;
}

static CloneBoot *g_pending_boot = nullptr; /* one clone in flight at a time
                                             * (the simulator defers the
                                             * parent's clone return until
                                             * the child has claimed this) */
/* bootstrap page per slot: reclaimed when the simulator recycles the slot
 * for a new thread (the previous occupant has fully exited by then) */
static void *g_boot_pages[IPC_MAX_THREADS] = {nullptr};
static char g_shm_base[256]; /* SHADOW_SHM_PATH; fork children map
                              * "<base>.f<id>" for their own block */

extern "C" void shadow_restore_ctx(CloneBoot *b);
/* restore every register from the saved context, set rax=0 (clone's child
 * return value), and jump to the interrupted rip on the app child stack */
asm(".text\n"
    ".globl shadow_restore_ctx\n"
    "shadow_restore_ctx:\n"
    "  movq 0x70(%rdi), %rsp\n"  /* B_RSP: app-provided child stack */
    "  pushq 0x78(%rdi)\n"       /* B_RIP: return target */
    "  movq 0x00(%rdi), %r8\n"
    "  movq 0x08(%rdi), %r9\n"
    "  movq 0x10(%rdi), %r10\n"
    "  movq 0x18(%rdi), %r11\n"
    "  movq 0x20(%rdi), %r12\n"
    "  movq 0x28(%rdi), %r13\n"
    "  movq 0x30(%rdi), %r14\n"
    "  movq 0x38(%rdi), %r15\n"
    "  movq 0x48(%rdi), %rsi\n"
    "  movq 0x50(%rdi), %rbp\n"
    "  movq 0x58(%rdi), %rbx\n"
    "  movq 0x60(%rdi), %rdx\n"
    "  movq 0x68(%rdi), %rcx\n"
    "  movq 0x40(%rdi), %rdi\n"
    "  xorl %eax, %eax\n"
    "  ret\n");

extern "C" void shadow_clone_child_entry(void) {
    CloneBoot *b = g_pending_boot;
    t_slot = b->slot; /* TLS valid: CLONE_SETTLS ran before any child code */
    ShimMsg m, resp;
    memset(&m, 0, sizeof m);
    m.kind = MSG_THREAD_START;
    m.num = g_raw(SYS_gettid, 0, 0, 0, 0, 0, 0);
    chan_send(to_shadow(t_slot), &m);
    if (chan_recv(to_shim(t_slot), &resp) != 0 || resp.kind != MSG_START_OK)
        g_raw(SYS_exit, 1, 0, 0, 0, 0, 0);
    shadow_restore_ctx(b);
    __builtin_unreachable();
}

static long do_thread_clone(const long args[6], greg_t *regs) {
    /* 1. simulator allocates the channel slot (or refuses) */
    long slot = forward_syscall(SYS_clone, args);
    if (slot < 0)
        return slot;

    /* 2. bootstrap area: one RW page = CloneBoot at the base, the rest is
     * the child's temporary stack (its real stack is restored in step 3) */
    if (g_boot_pages[slot]) {
        g_raw(SYS_munmap, (long)g_boot_pages[slot], 16384, 0, 0, 0, 0);
        g_boot_pages[slot] = nullptr;
    }
    void *page = mmap(nullptr, 16384, PROT_READ | PROT_WRITE,
                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (page == MAP_FAILED) {
        long done_args[6] = {-ENOMEM, slot, 0, 0, 0, 0};
        forward_msg(MSG_CLONE_DONE, SYS_clone, done_args);
        return -ENOMEM;
    }
    CloneBoot *boot = (CloneBoot *)page;
    g_boot_pages[slot] = page;
    boot->slot = (int)slot;
    boot->regs[B_R8] = regs[REG_R8];
    boot->regs[B_R9] = regs[REG_R9];
    boot->regs[B_R10] = regs[REG_R10];
    boot->regs[B_R11] = regs[REG_R11];
    boot->regs[B_R12] = regs[REG_R12];
    boot->regs[B_R13] = regs[REG_R13];
    boot->regs[B_R14] = regs[REG_R14];
    boot->regs[B_R15] = regs[REG_R15];
    boot->regs[B_RDI] = regs[REG_RDI];
    boot->regs[B_RSI] = regs[REG_RSI];
    boot->regs[B_RBP] = regs[REG_RBP];
    boot->regs[B_RBX] = regs[REG_RBX];
    boot->regs[B_RDX] = regs[REG_RDX];
    boot->regs[B_RCX] = regs[REG_RCX];
    boot->regs[B_RSP] = args[1]; /* the app-provided child stack */
    boot->regs[B_RIP] = regs[REG_RIP]; /* after the trapped syscall insn */
    g_pending_boot = boot;

    /* child bootstrap stack: plant the entry address so the raw clone's
     * child pops it from the trampoline's `ret` */
    uint64_t *tos = (uint64_t *)((char *)page + 16384 - 64);
    tos[0] = (uint64_t)&shadow_clone_child_entry;

    /* 3. the real clone: original flags/ptid/ctid/tls, our bootstrap stack */
    long tid = g_raw(SYS_clone, args[0], (long)tos, args[2], args[3], args[4],
                     0);
    /* 4. report the result; the simulator orders parent-then-child resume */
    long done_args[6] = {tid, slot, 0, 0, 0, 0};
    return forward_msg(MSG_CLONE_DONE, SYS_clone, done_args);
}

/* ------------------------------------------------------------------- fork
 *
 * Fork-style clones (no CLONE_VM) get a whole new IPC block: the simulator
 * creates "<base>.f<id>" and replies with the id; both sides map it before
 * the fork so the child can check in on it (slot 0) while the parent keeps
 * its own block. CLONE_VFORK is downgraded to plain fork semantics (copied
 * memory, parent continues) — posix_spawn-style users exec immediately and
 * never notice. Reference: Shadow emulates fork/vfork in handle_clone
 * (host/syscall/handler/process.rs) with the same downgrade. */

static long do_fork(long num, const long args[6]) {
    size_t bl = strlen(g_shm_base);
    /* each fork generation appends ".f<id>"; refuse before either the
     * local path buffer or the child's g_shm_base copy could overflow */
    if (bl + 26 >= sizeof(g_shm_base))
        return -ENAMETOOLONG;

    long fork_id = forward_msg(MSG_SYSCALL, num, args);
    if (fork_id < 0)
        return fork_id;

    char path[300];
    memcpy(path, g_shm_base, bl);
    path[bl] = '.';
    path[bl + 1] = 'f';
    /* decimal fork_id */
    char digits[24];
    int nd = 0;
    long v = fork_id;
    do {
        digits[nd++] = (char)('0' + (v % 10));
        v /= 10;
    } while (v);
    for (int i = 0; i < nd; i++)
        path[bl + 2 + i] = digits[nd - 1 - i];
    path[bl + 2 + nd] = 0;

    long fd = g_raw(SYS_open, (long)path, O_RDWR | O_CLOEXEC, 0, 0, 0, 0);
    if (fd < 0) {
        long done_args[6] = {-ENOMEM, fork_id, 1, 0, 0, 0};
        return forward_msg(MSG_CLONE_DONE, num, done_args);
    }
    long mem = g_raw(SYS_mmap, 0, sizeof(IpcBlock), PROT_READ | PROT_WRITE,
                     MAP_SHARED, fd, 0);
    g_raw(SYS_close, fd, 0, 0, 0, 0, 0);
    if ((unsigned long)mem >= (unsigned long)-4095) {
        long done_args[6] = {-ENOMEM, fork_id, 1, 0, 0, 0};
        return forward_msg(MSG_CLONE_DONE, num, done_args);
    }
    IpcBlock *nb = (IpcBlock *)mem;

    /* plain fork; keep glibc's tid-cache flags if the caller passed them */
    long keep = 0;
    long ctid = 0;
    if (num == SYS_clone) {
        keep = args[0] &
               (CLONE_CHILD_SETTID | CLONE_CHILD_CLEARTID | 0xffl);
        ctid = args[3];
    } else {
        keep = SIGCHLD;
    }
    long rc = g_raw(SYS_clone, keep, 0, 0, ctid, 0, 0);
    if (rc == 0) {
        /* child: fresh block, main slot, check in as a new process */
        g_ipc = nb;
        t_slot = 0;
        if (g_heap_fd >= 0) {
            /* PRIVATIZE the heap: a MAP_SHARED heap would couple parent
             * and child memory, breaking fork's COW contract. Copy out,
             * remap anonymous, copy back; brk growth continues shim-local
             * via anonymous pages; the simulator window stays OFF for
             * this child (nb->heap_start is zero). */
            size_t hlen = g_heap_mapped - g_heap_start;
            if (hlen) {
                long tmp = g_raw(SYS_mmap, 0, (long)hlen,
                                 PROT_READ | PROT_WRITE,
                                 MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
                if ((unsigned long)tmp < (unsigned long)-4095) {
                    memcpy((void *)tmp, (void *)g_heap_start, hlen);
                    g_raw(SYS_mmap, (long)g_heap_start, (long)hlen,
                          PROT_READ | PROT_WRITE,
                          MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED, -1, 0);
                    memcpy((void *)g_heap_start, (void *)tmp, hlen);
                    g_raw(SYS_munmap, tmp, (long)hlen, 0, 0, 0, 0);
                }
            }
            g_raw(SYS_close, g_heap_fd, 0, 0, 0, 0, 0);
            g_heap_fd = -1;
        }
        /* release the parent: our heap is private now (see ipc.h) */
        __atomic_store_n(&nb->fork_sync, 1u, __ATOMIC_RELEASE);
        g_raw(SYS_futex, (long)&nb->fork_sync, 1 /*FUTEX_WAKE*/, 1, 0, 0, 0);
        ShimMsg m, resp;
        memset(&m, 0, sizeof m);
        m.kind = MSG_START;
        m.num = g_raw(SYS_getpid, 0, 0, 0, 0, 0, 0);
        chan_send(to_shadow(0), &m);
        if (chan_recv(to_shim(0), &resp) != 0 || resp.kind != MSG_START_OK)
            g_raw(SYS_exit_group, 96, 0, 0, 0, 0, 0);
        /* Only g_shm_base needs the new path (further forks derive from
         * it). Deliberately NOT setenv(): malloc-backed and async-signal-
         * unsafe — another thread holding the allocator lock at fork time
         * would deadlock this child before check-in. The stale env var is
         * harmless: execve is serviced simulator-side, which constructs
         * the new image's SHADOW_SHM_PATH from its own records. */
        memcpy(g_shm_base, path, strlen(path) + 1);
        return 0;
    }
    /* parent: WAIT for the child's heap privatization before touching the
     * (momentarily shared) heap again — bounded so a child that dies
     * pre-handshake cannot wedge us (see ipc.h fork_sync) */
    if (rc > 0 && g_heap_start) {
        struct timespec ts = {1, 0};
        for (int i = 0;
             i < 10 && !__atomic_load_n(&nb->fork_sync, __ATOMIC_ACQUIRE);
             i++)
            g_raw(SYS_futex, (long)&nb->fork_sync, 0 /*FUTEX_WAIT*/, 0,
                  (long)&ts, 0, 0);
    }
    /* drop the child's mapping, report the real pid */
    g_raw(SYS_munmap, mem, sizeof(IpcBlock), 0, 0, 0, 0);
    long done_args[6] = {rc, fork_id, 1, 0, 0, 0};
    return forward_msg(MSG_CLONE_DONE, num, done_args);
}

extern "C" void shadow_shim_handle_sigsys(int sig, siginfo_t *info,
                                          void *ucontext) {
    (void)sig;
    (void)info;
    ucontext_t *uc = (ucontext_t *)ucontext;
    greg_t *regs = uc->uc_mcontext.gregs;
    long num = regs[REG_RAX];
    long args[6] = {(long)regs[REG_RDI], (long)regs[REG_RSI],
                    (long)regs[REG_RDX], (long)regs[REG_R10],
                    (long)regs[REG_R8],  (long)regs[REG_R9]};
    long ret;
    switch (num) {
    case SYS_clock_gettime:
    case SYS_gettimeofday:
    case SYS_time:
        ret = time_escape() ? forward_syscall(num, args)
                            : emulate_time_syscall(num, args[0], args[1]);
        break;
    case SYS_getpid:
    case SYS_getppid:
    case SYS_getuid:
    case SYS_geteuid:
    case SYS_getgid:
    case SYS_getegid:
        /* identity fast path: virtual ids from shared memory, no round
         * trip (ids are constant between set*id calls, which the
         * simulator mirrors into the block). Same Nth-call escape as the
         * time path so a getpid busy-loop cannot freeze simulated time. */
        if (__atomic_load_n(&g_ipc->ids_valid, __ATOMIC_ACQUIRE) &&
            !time_escape()) {
            switch (num) {
            case SYS_getpid: ret = g_ipc->virt_pid; break;
            case SYS_getppid: ret = g_ipc->virt_ppid; break;
            case SYS_getuid:
            case SYS_geteuid: ret = g_ipc->virt_uid; break;
            default: ret = g_ipc->virt_gid; break;
            }
        } else {
            ret = forward_syscall(num, args);
        }
        break;
    case SYS_write: {
        bool hit = false;
        ret = fast_write(args[0], (const void *)args[1],
                         (unsigned long)args[2], &hit);
        if (!hit)
            ret = forward_syscall(num, args);
        break;
    }
    case SYS_clock_getres: {
        struct timespec *ts = (struct timespec *)args[1];
        if (ts) {
            ts->tv_sec = 0;
            ts->tv_nsec = 1;
        }
        ret = 0;
        break;
    }
    case SYS_clone3:
        /* glibc falls back to clone(2) on ENOSYS; one trap path to handle */
        ret = -ENOSYS;
        break;
    case SYS_brk:
        ret = do_brk(args[0]);
        break;
    case SYS_clone:
        if ((args[0] & CLONE_VM) && !(args[0] & CLONE_VFORK)) {
            /* the child claims its channel slot through TLS; without
             * CLONE_SETTLS it would share the parent's TLS and corrupt the
             * parent's slot binding (pthreads always pass SETTLS) */
            ret = (args[0] & CLONE_SETTLS) ? do_thread_clone(args, regs)
                                           : -ENOSYS;
        } else {
            ret = do_fork(num, args);
        }
        break;
    case SYS_fork:
    case SYS_vfork:
        ret = do_fork(num, args);
        break;
    default:
        ret = forward_syscall(num, args);
        break;
    }
    regs[REG_RAX] = ret;
}

/* ----------------------------------------------------- libc interposers
 * vdso-backed time functions never produce a syscall instruction, so the
 * seccomp filter cannot see them; exporting the symbols from the preload
 * library routes PLT calls here instead (lib/preload-libc's INTERPOSE). */

extern "C" int clock_gettime(clockid_t clk, struct timespec *ts) {
    if (!g_ipc)
        return (int)syscall(SYS_clock_gettime, clk, ts);
    if (time_escape()) {
        long args[6] = {(long)clk, (long)ts, 0, 0, 0, 0};
        return (int)forward_syscall(SYS_clock_gettime, args);
    }
    int64_t now = sim_now();
    if (ts) {
        ts->tv_sec = now / 1000000000;
        ts->tv_nsec = now % 1000000000;
    }
    return 0;
}

extern "C" int gettimeofday(struct timeval *tv, void *tz) {
    (void)tz;
    if (!g_ipc)
        return (int)syscall(SYS_gettimeofday, tv, tz);
    if (time_escape()) {
        long args[6] = {(long)tv, (long)tz, 0, 0, 0, 0};
        return (int)forward_syscall(SYS_gettimeofday, args);
    }
    int64_t now = sim_now();
    if (tv) {
        tv->tv_sec = now / 1000000000;
        tv->tv_usec = (now % 1000000000) / 1000;
    }
    return 0;
}

extern "C" time_t time(time_t *tloc) {
    if (!g_ipc)
        return (time_t)syscall(SYS_time, tloc);
    if (time_escape()) {
        long args[6] = {(long)tloc, 0, 0, 0, 0, 0};
        return (time_t)forward_syscall(SYS_time, args);
    }
    time_t secs = sim_now() / 1000000000;
    if (tloc)
        *tloc = secs;
    return secs;
}

/* ----------------------------------------------------------------- rdtsc
 *
 * The time-syscall interposition above cannot see `rdtsc`/`rdtscp` — they
 * read the cycle counter in userspace, leaking wall time into the
 * simulation. prctl(PR_SET_TSC, PR_TSC_SIGSEGV) makes them fault; the
 * SIGSEGV handler decodes the instruction and synthesizes a deterministic
 * counter from the simulated clock at a nominal 1 GHz (1 tick = 1 ns).
 * Reference: src/lib/shim/shim_rdtsc.c + src/lib/tsc. */

extern "C" void shadow_shim_handle_sigsegv(int sig, siginfo_t *info,
                                           void *ucontext) {
    (void)sig;
    (void)info;
    ucontext_t *uc = (ucontext_t *)ucontext;
    greg_t *regs = uc->uc_mcontext.gregs;
    const unsigned char *ip = (const unsigned char *)regs[REG_RIP];
    if (ip && ip[0] == 0x0f && ip[1] == 0x31) { /* rdtsc */
        uint64_t tsc = (uint64_t)sim_now();
        regs[REG_RAX] = (greg_t)(tsc & 0xffffffffu);
        regs[REG_RDX] = (greg_t)(tsc >> 32);
        regs[REG_RIP] += 2;
        return;
    }
    if (ip && ip[0] == 0x0f && ip[1] == 0x01 && ip[2] == 0xf9) { /* rdtscp */
        uint64_t tsc = (uint64_t)sim_now();
        regs[REG_RAX] = (greg_t)(tsc & 0xffffffffu);
        regs[REG_RDX] = (greg_t)(tsc >> 32);
        regs[REG_RCX] = 0; /* IA32_TSC_AUX: cpu 0 */
        regs[REG_RIP] += 3;
        return;
    }
    /* genuine fault: restore the default disposition VIA THE TRAMPOLINE
     * (libc sigaction would be seccomp-trapped and answered by the
     * emulated rt_sigaction, which never changes the kernel state — the
     * faulting instruction would re-enter this handler forever) and
     * return, so the re-fault crashes for real */
    struct {
        uint64_t handler, flags, restorer, mask;
    } kact = {0, 0, 0, 0};
    g_raw(SYS_rt_sigaction, SIGSEGV, (long)&kact, 0, 8, 0, 0);
}

/* ------------------------------------------------------------ vdso patch
 *
 * The vDSO computes clock_gettime from a live rdtsc against vvar's
 * real-TSC calibration — both a wall-time leak (for callers that bypass
 * our interposed PLT symbols, e.g. glibc-internal __clock_gettime) and,
 * once PR_SET_TSC synthesizes sim-time TSC values, a source of garbage
 * timestamps. Overwrite every vDSO entry point with `mov eax, <nr>;
 * syscall; ret` so they become real (seccomp-trapped, emulated) syscalls.
 * Reference: src/lib/shim/patch_vdso.c. */

#include <elf.h>
#include <sys/auxv.h>

static int patch_vdso(void) {
    unsigned long base = getauxval(AT_SYSINFO_EHDR);
    if (!base)
        return -1;
    const Elf64_Ehdr *eh = (const Elf64_Ehdr *)base;
    const Elf64_Phdr *ph = (const Elf64_Phdr *)(base + eh->e_phoff);
    const Elf64_Dyn *dyn = nullptr;
    unsigned long size = 0;
    for (int i = 0; i < eh->e_phnum; i++) {
        if (ph[i].p_type == PT_DYNAMIC)
            dyn = (const Elf64_Dyn *)(base + ph[i].p_offset);
        if (ph[i].p_type == PT_LOAD && ph[i].p_vaddr + ph[i].p_memsz > size)
            size = ph[i].p_vaddr + ph[i].p_memsz;
    }
    if (!dyn || !size)
        return -1;
    /* vDSO dynamic pointers may be link-time (unrelocated) addresses */
    auto fix = [base, size](unsigned long p) -> unsigned long {
        return (p < size) ? base + p : p;
    };
    const Elf64_Sym *symtab = nullptr;
    const char *strtab = nullptr;
    const uint32_t *hash = nullptr;
    for (const Elf64_Dyn *d = dyn; d->d_tag != DT_NULL; d++) {
        if (d->d_tag == DT_SYMTAB)
            symtab = (const Elf64_Sym *)fix(d->d_un.d_ptr);
        else if (d->d_tag == DT_STRTAB)
            strtab = (const char *)fix(d->d_un.d_ptr);
        else if (d->d_tag == DT_HASH)
            hash = (const uint32_t *)fix(d->d_un.d_ptr);
    }
    if (!symtab || !strtab || !hash)
        return -1;
    uint32_t nsyms = hash[1]; /* nchain */

    unsigned long pagesz = 4096;
    unsigned long start = base & ~(pagesz - 1);
    unsigned long len = ((base + size + pagesz - 1) & ~(pagesz - 1)) - start;
    if (mprotect((void *)start, len, PROT_READ | PROT_WRITE | PROT_EXEC))
        return -1;

    static const struct {
        const char *name;
        int nr;
    } targets[] = {
        {"__vdso_clock_gettime", SYS_clock_gettime},
        {"clock_gettime", SYS_clock_gettime},
        {"__vdso_gettimeofday", SYS_gettimeofday},
        {"gettimeofday", SYS_gettimeofday},
        {"__vdso_time", SYS_time},
        {"time", SYS_time},
        {"__vdso_clock_getres", SYS_clock_getres},
        {"clock_getres", SYS_clock_getres},
        {"__vdso_getcpu", SYS_getcpu},
        {"getcpu", SYS_getcpu},
    };
    for (uint32_t i = 0; i < nsyms && i < 4096; i++) {
        const char *nm = strtab + symtab[i].st_name;
        if (!symtab[i].st_value)
            continue;
        for (const auto &t : targets) {
            if (strcmp(nm, t.name) != 0)
                continue;
            unsigned char *fn =
                (unsigned char *)(symtab[i].st_value < size
                                      ? base + symtab[i].st_value
                                      : symtab[i].st_value);
            fn[0] = 0xb8; /* mov eax, imm32 */
            memcpy(fn + 1, &t.nr, 4);
            fn[5] = 0x0f; /* syscall */
            fn[6] = 0x05;
            fn[7] = 0xc3; /* ret */
            break;
        }
    }
    mprotect((void *)start, len, PROT_READ | PROT_EXEC);
    return 0;
}

/* ---------------------------------------------- OpenSSL RNG determinism
 *
 * Any TLS-using binary pulls entropy through OpenSSL's RAND_*; routing it
 * to the (seccomp-trapped, simulator-seeded) getrandom syscall keeps two
 * runs bit-identical. LD_PRELOAD makes these definitions win over
 * libcrypto's. Reference: src/lib/preload-openssl. */

static int shadow_rand_fill(unsigned char *buf, int n) {
    int off = 0;
    while (off < n) {
        long got = syscall(SYS_getrandom, buf + off, (long)(n - off), 0);
        if (got <= 0)
            return 0;
        off += (int)got;
    }
    return 1;
}

extern "C" int RAND_bytes(unsigned char *buf, int n) {
    return shadow_rand_fill(buf, n);
}
extern "C" int RAND_priv_bytes(unsigned char *buf, int n) {
    return shadow_rand_fill(buf, n);
}
extern "C" int RAND_pseudo_bytes(unsigned char *buf, int n) {
    return shadow_rand_fill(buf, n);
}
extern "C" int RAND_status(void) { return 1; }
extern "C" int RAND_poll(void) { return 1; }
extern "C" void RAND_seed(const void *buf, int num) {
    (void)buf;
    (void)num;
}
extern "C" void RAND_add(const void *buf, int num, double entropy) {
    (void)buf;
    (void)num;
    (void)entropy;
}

/* ------------------------------------------------- addrinfo / ifaddrs
 *
 * glibc's getaddrinfo reads the REAL /etc/hosts + resolver config through
 * NSS (all file reads are native passthrough), so a simulated hostname can
 * never resolve through it. These interposers answer from the simulator's
 * DNS registry via the SHADOW_SYS_RESOLVE custom syscall instead.
 * Reference: src/lib/shim/shim_api_addrinfo.c (453 LoC) + shim_api_ifaddrs.c.
 * Normal library context (not a signal handler): malloc/dlsym are fine. */

#include <arpa/inet.h>
#include <dlfcn.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>

static int parse_ipv4(const char *s, uint32_t *out_be) {
    unsigned a, b, c, d;
    char tail;
    if (sscanf(s, "%u.%u.%u.%u%c", &a, &b, &c, &d, &tail) != 4)
        return -1;
    if (a > 255 || b > 255 || c > 255 || d > 255)
        return -1;
    *out_be = htonl((a << 24) | (b << 16) | (c << 8) | d);
    return 0;
}

static int resolve_port(const char *service, const struct addrinfo *hints,
                        int *port_out) {
    if (!service) {
        *port_out = 0;
        return 0;
    }
    char *end = nullptr;
    long p = strtol(service, &end, 10);
    if (end && *end == 0 && p >= 0 && p <= 65535) {
        *port_out = (int)p;
        return 0;
    }
    if (hints && (hints->ai_flags & AI_NUMERICSERV))
        return EAI_NONAME;
    static const struct { const char *name; int port; } WELL_KNOWN[] = {
        {"http", 80}, {"https", 443}, {"ftp", 21}, {"ssh", 22},
        {"domain", 53}, {"telnet", 23}, {"smtp", 25},
    };
    for (const auto &w : WELL_KNOWN) {
        if (!strcmp(service, w.name)) {
            *port_out = w.port;
            return 0;
        }
    }
    return EAI_SERVICE;
}

static struct addrinfo *mk_ai(int socktype, int protocol, uint32_t addr_be,
                              int port, const char *canon) {
    auto *ai = (struct addrinfo *)calloc(1, sizeof(struct addrinfo));
    auto *sa = (struct sockaddr_in *)calloc(1, sizeof(struct sockaddr_in));
    if (!ai || !sa) {
        free(ai);
        free(sa);
        return nullptr;
    }
    sa->sin_family = AF_INET;
    sa->sin_port = htons((uint16_t)port);
    sa->sin_addr.s_addr = addr_be;
    ai->ai_family = AF_INET;
    ai->ai_socktype = socktype;
    ai->ai_protocol = protocol;
    ai->ai_addrlen = sizeof(struct sockaddr_in);
    ai->ai_addr = (struct sockaddr *)sa;
    if (canon)
        ai->ai_canonname = strdup(canon);
    return ai;
}

extern "C" int getaddrinfo(const char *node, const char *service,
                           const struct addrinfo *hints,
                           struct addrinfo **res) {
    if (!g_ipc) { /* not under the simulator: defer to the real libc */
        static int (*real)(const char *, const char *, const struct addrinfo *,
                           struct addrinfo **) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "getaddrinfo");
        return real ? real(node, service, hints, res) : EAI_SYSTEM;
    }
    if (hints && hints->ai_family != AF_UNSPEC && hints->ai_family != AF_INET)
        return EAI_NONAME; /* simulated network is IPv4-only */
    int port = 0;
    int perr = resolve_port(service, hints, &port);
    if (perr)
        return perr;
    uint32_t addr_be = 0;
    if (!node) {
        addr_be = (hints && (hints->ai_flags & AI_PASSIVE))
                      ? htonl(INADDR_ANY)
                      : htonl(INADDR_LOOPBACK);
    } else if (parse_ipv4(node, &addr_be) != 0) {
        if (!strcmp(node, "localhost")) {
            addr_be = htonl(INADDR_LOOPBACK);
        } else {
            if (hints && (hints->ai_flags & AI_NUMERICHOST))
                return EAI_NONAME;
            long rc = syscall(SHADOW_SYS_RESOLVE, node, &addr_be);
            if (rc != 0)
                return EAI_NONAME;
        }
    }
    int want = hints ? hints->ai_socktype : 0;
    const char *canon =
        (hints && (hints->ai_flags & AI_CANONNAME)) ? node : nullptr;
    struct addrinfo *head = nullptr, **tail = &head;
    struct {
        int st, proto;
    } kinds[2] = {{SOCK_STREAM, IPPROTO_TCP}, {SOCK_DGRAM, IPPROTO_UDP}};
    for (const auto &k : kinds) {
        if (want && want != k.st)
            continue;
        struct addrinfo *ai = mk_ai(k.st, k.proto, addr_be, port, canon);
        if (!ai) {
            if (head)
                freeaddrinfo(head);
            return EAI_MEMORY;
        }
        canon = nullptr; /* canonname only on the first entry, like glibc */
        *tail = ai;
        tail = &ai->ai_next;
    }
    if (!head)
        return EAI_SOCKTYPE;
    *res = head;
    return 0;
}

extern "C" void freeaddrinfo(struct addrinfo *ai) {
    if (!g_ipc) { /* list came from the real getaddrinfo (our !g_ipc
                   * fallback): it is ONE glibc allocation with interior
                   * pointers — must be freed by the real deallocator */
        static void (*real)(struct addrinfo *) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "freeaddrinfo");
        if (real)
            real(ai);
        return;
    }
    while (ai) {
        struct addrinfo *next = ai->ai_next;
        free(ai->ai_addr);
        free(ai->ai_canonname);
        free(ai);
        ai = next;
    }
}

extern "C" struct hostent *gethostbyname(const char *name) {
    static struct hostent he;
    static struct in_addr haddr;
    static char *addr_list[2];
    static char hname[256];
    if (!g_ipc) {
        static struct hostent *(*real)(const char *) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "gethostbyname");
        return real ? real(name) : nullptr;
    }
    uint32_t addr_be = 0;
    if (!name)
        return nullptr;
    if (parse_ipv4(name, &addr_be) != 0) {
        if (!strcmp(name, "localhost")) {
            addr_be = htonl(INADDR_LOOPBACK);
        } else if (syscall(SHADOW_SYS_RESOLVE, name, &addr_be) != 0) {
            h_errno = HOST_NOT_FOUND;
            return nullptr;
        }
    }
    haddr.s_addr = addr_be;
    addr_list[0] = (char *)&haddr;
    addr_list[1] = nullptr;
    strncpy(hname, name, sizeof hname - 1);
    hname[sizeof hname - 1] = 0;
    he.h_name = hname;
    he.h_aliases = addr_list + 1; /* empty, NULL-terminated */
    he.h_addrtype = AF_INET;
    he.h_length = 4;
    he.h_addr_list = addr_list;
    return &he;
}

/* Reverse lookups: glibc's gethostbyaddr/getnameinfo go through NSS and
 * the REAL resolver (queries leak into the simulated network and time out
 * — python's HTTPServer calls getfqdn() at startup and would stall 10
 * simulated seconds). Answer from the simulator's registry instead.
 * Reference: shim_api_addrinfo.c covers the same family. */
extern "C" struct hostent *gethostbyaddr(const void *addr, socklen_t len,
                                         int type) {
    static struct hostent he;
    static struct in_addr haddr;
    static char *addr_list[2];
    static char hname[256];
    if (!g_ipc) {
        static struct hostent *(*real)(const void *, socklen_t, int) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "gethostbyaddr");
        return real ? real(addr, len, type) : nullptr;
    }
    if (type != AF_INET || len < 4 || !addr) {
        h_errno = HOST_NOT_FOUND;
        return nullptr;
    }
    uint32_t addr_be;
    memcpy(&addr_be, addr, 4);
    if (syscall(SHADOW_SYS_RESOLVE_REV, (long)addr_be, hname,
                (long)sizeof hname) != 0) {
        h_errno = HOST_NOT_FOUND;
        return nullptr;
    }
    haddr.s_addr = addr_be;
    addr_list[0] = (char *)&haddr;
    addr_list[1] = nullptr;
    he.h_name = hname;
    he.h_aliases = addr_list + 1; /* empty, NULL-terminated */
    he.h_addrtype = AF_INET;
    he.h_length = 4;
    he.h_addr_list = addr_list;
    return &he;
}

/* CPython's socketmodule (and other NSS clients) use the reentrant _r
 * forms; glibc's go through NSS/DNS, so they need the same interposition */
extern "C" int gethostbyaddr_r(const void *addr, socklen_t len, int type,
                               struct hostent *ret, char *buf, size_t buflen,
                               struct hostent **result, int *h_errnop) {
    if (!g_ipc) {
        static int (*real)(const void *, socklen_t, int, struct hostent *,
                           char *, size_t, struct hostent **, int *) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "gethostbyaddr_r");
        return real ? real(addr, len, type, ret, buf, buflen, result, h_errnop)
                    : ENOSYS;
    }
    *result = nullptr;
    if (type != AF_INET || len < 4 || !addr) {
        if (h_errnop)
            *h_errnop = HOST_NOT_FOUND;
        return EINVAL;
    }
    char name[256];
    uint32_t addr_be;
    memcpy(&addr_be, addr, 4);
    if (syscall(SHADOW_SYS_RESOLVE_REV, (long)addr_be, name,
                (long)sizeof name) != 0) {
        if (h_errnop)
            *h_errnop = HOST_NOT_FOUND;
        return 0; /* glibc convention: 0 with *result == NULL */
    }
    /* layout into the caller's buffer: name cstr + 4-byte addr + ptr array */
    size_t nlen = strlen(name) + 1;
    size_t need = nlen + 4 + 3 * sizeof(char *) + 16 /* alignment slack */;
    if (buflen < need) {
        if (h_errnop)
            *h_errnop = NETDB_INTERNAL;
        return ERANGE;
    }
    char *p = buf;
    memcpy(p, name, nlen);
    char *nm = p;
    p += nlen;
    p += (8 - ((uintptr_t)p & 7)) & 7;
    memcpy(p, &addr_be, 4);
    char *ab = p;
    p += 8;
    char **ptrs = (char **)p;
    ptrs[0] = ab;
    ptrs[1] = nullptr;
    ptrs[2] = nullptr;
    ret->h_name = nm;
    ret->h_aliases = ptrs + 1;
    ret->h_addrtype = AF_INET;
    ret->h_length = 4;
    ret->h_addr_list = ptrs;
    *result = ret;
    return 0;
}

extern "C" int gethostbyname_r(const char *name, struct hostent *ret,
                               char *buf, size_t buflen,
                               struct hostent **result, int *h_errnop) {
    if (!g_ipc) {
        static int (*real)(const char *, struct hostent *, char *, size_t,
                           struct hostent **, int *) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "gethostbyname_r");
        return real ? real(name, ret, buf, buflen, result, h_errnop) : ENOSYS;
    }
    *result = nullptr;
    uint32_t addr_be = 0;
    if (!name || buflen < 64) {
        if (h_errnop)
            *h_errnop = NETDB_INTERNAL;
        return name ? ERANGE : EINVAL;
    }
    if (parse_ipv4(name, &addr_be) != 0) {
        if (!strcmp(name, "localhost")) {
            addr_be = htonl(INADDR_LOOPBACK);
        } else if (syscall(SHADOW_SYS_RESOLVE, name, &addr_be) != 0) {
            if (h_errnop)
                *h_errnop = HOST_NOT_FOUND;
            return 0;
        }
    }
    size_t nlen = strlen(name) + 1;
    if (buflen < nlen + 4 + 3 * sizeof(char *) + 16) {
        if (h_errnop)
            *h_errnop = NETDB_INTERNAL;
        return ERANGE;
    }
    char *p = buf;
    memcpy(p, name, nlen);
    char *nm = p;
    p += nlen;
    p += (8 - ((uintptr_t)p & 7)) & 7;
    memcpy(p, &addr_be, 4);
    char *ab = p;
    p += 8;
    char **ptrs = (char **)p;
    ptrs[0] = ab;
    ptrs[1] = nullptr;
    ptrs[2] = nullptr;
    ret->h_name = nm;
    ret->h_aliases = ptrs + 1;
    ret->h_addrtype = AF_INET;
    ret->h_length = 4;
    ret->h_addr_list = ptrs;
    *result = ret;
    return 0;
}

extern "C" int getnameinfo(const struct sockaddr *sa, socklen_t salen,
                           char *host, socklen_t hostlen, char *serv,
                           socklen_t servlen, int flags) {
    if (!g_ipc) {
        static int (*real)(const struct sockaddr *, socklen_t, char *,
                           socklen_t, char *, socklen_t, int) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "getnameinfo");
        return real ? real(sa, salen, host, hostlen, serv, servlen, flags)
                    : EAI_SYSTEM;
    }
    if (!sa || salen < (socklen_t)sizeof(struct sockaddr_in))
        return EAI_FAMILY;
    if (sa->sa_family != AF_INET) {
        /* non-IPv4 (axon's own event loop binds ::1): numeric-only via the
         * real implementation — NI_NUMERICHOST keeps NSS/DNS out of it */
        static int (*real)(const struct sockaddr *, socklen_t, char *,
                           socklen_t, char *, socklen_t, int) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "getnameinfo");
        return real ? real(sa, salen, host, hostlen, serv, servlen,
                           flags | NI_NUMERICHOST)
                    : EAI_FAMILY;
    }
    const struct sockaddr_in *sin = (const struct sockaddr_in *)sa;
    if (serv && servlen > 0)
        snprintf(serv, servlen, "%u", (unsigned)ntohs(sin->sin_port));
    if (host && hostlen > 0) {
        char name[256];
        if (!(flags & NI_NUMERICHOST) &&
            syscall(SHADOW_SYS_RESOLVE_REV, (long)sin->sin_addr.s_addr, name,
                    (long)sizeof name) == 0) {
            snprintf(host, hostlen, "%s", name);
        } else if (!(flags & NI_NAMEREQD)) {
            uint32_t a = ntohl(sin->sin_addr.s_addr);
            snprintf(host, hostlen, "%u.%u.%u.%u", (a >> 24) & 255,
                     (a >> 16) & 255, (a >> 8) & 255, a & 255);
        } else {
            return EAI_NONAME;
        }
    }
    return 0;
}

/* two interfaces, like every simulated host: lo + eth0 (reference
 * namespace.rs builds exactly these) */
extern "C" int getifaddrs(struct ifaddrs **ifap) {
    if (!g_ipc) {
        static int (*real)(struct ifaddrs **) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "getifaddrs");
        return real ? real(ifap) : -1;
    }
    uint32_t self_be = 0;
    syscall(SHADOW_SYS_SELF_IP, &self_be);
    struct Blk {
        struct ifaddrs ifa;
        struct sockaddr_in addr, mask;
        char name[8];
    };
    auto *lo = (Blk *)calloc(1, sizeof(Blk));
    auto *eth = (Blk *)calloc(1, sizeof(Blk));
    if (!lo || !eth) {
        free(lo);
        free(eth);
        return -1;
    }
    auto fill = [](Blk *b, const char *nm, uint32_t addr_be, uint32_t mask_be,
                   unsigned flags) {
        strcpy(b->name, nm);
        b->addr.sin_family = AF_INET;
        b->addr.sin_addr.s_addr = addr_be;
        b->mask.sin_family = AF_INET;
        b->mask.sin_addr.s_addr = mask_be;
        b->ifa.ifa_name = b->name;
        b->ifa.ifa_flags = flags;
        b->ifa.ifa_addr = (struct sockaddr *)&b->addr;
        b->ifa.ifa_netmask = (struct sockaddr *)&b->mask;
    };
    /* IFF_UP|IFF_RUNNING (+IFF_LOOPBACK for lo) */
    fill(lo, "lo", htonl(INADDR_LOOPBACK), htonl(0xff000000u), 0x49);
    fill(eth, "eth0", self_be, htonl(0xffffff00u), 0x41);
    lo->ifa.ifa_next = &eth->ifa;
    *ifap = &lo->ifa;
    return 0;
}

extern "C" void freeifaddrs(struct ifaddrs *ifa) {
    if (!g_ipc) { /* same single-allocation concern as freeaddrinfo */
        static void (*real)(struct ifaddrs *) = nullptr;
        if (!real)
            real = (decltype(real))dlsym(RTLD_NEXT, "freeifaddrs");
        if (real)
            real(ifa);
        return;
    }
    while (ifa) {
        struct ifaddrs *next = ifa->ifa_next;
        free(ifa);
        ifa = next;
    }
}

/* -------------------------------------------------------------- seccomp */

static int install_seccomp(void) {
    uint32_t lo = (uint32_t)(g_tramp_page & 0xffffffffu);
    uint32_t hi = (uint32_t)(g_tramp_page >> 32);
    struct sock_filter filter[] = {
        /* arch check */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, arch)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, 1, 0),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS),
        /* rt_sigreturn always allowed (signal handler unwind) */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS, offsetof(struct seccomp_data, nr)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, SYS_rt_sigreturn, 5, 0),
        /* instruction pointer inside the trampoline page -> allow;
         * anything else -> TRAP (indices: 10 = ALLOW, 11 = TRAP) */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer) + 4),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, hi, 0, 4),   /* !=hi -> TRAP */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer)),
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, lo, 0, 2),   /* <lo  -> TRAP */
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, lo + 4096, 1, 0), /* >=end -> TRAP */
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_TRAP),
    };
    struct sock_fprog prog;
    prog.len = sizeof(filter) / sizeof(filter[0]);
    prog.filter = filter;
    if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0))
        return -1;
    if (syscall(SYS_seccomp, SECCOMP_SET_MODE_FILTER, 0, &prog))
        return -1;
    return 0;
}

/* ------------------------------------------------------------------ init */

/* execve fd-table preservation: the simulator-side respawn grabbed the
 * old image's fds (pidfd_getfd) and passed them to this process parked
 * at numbers >= 3000; SHADOW_FD_MAP ("tgt:src,...") says where each one
 * belongs. Applied before ANYTHING else touches fds. */
static void apply_fd_map(void) {
    const char *map = getenv("SHADOW_FD_MAP");
    if (!map || !*map)
        return;
    const char *p = map;
    while (*p) {
        char *end = nullptr;
        long tgt = strtol(p, &end, 10);
        p = end;
        if (*p == ':')
            p++;
        long src = strtol(p, &end, 10);
        p = end;
        if (*p == ',')
            p++;
        if (src >= 0 && tgt >= 0 && src != tgt) {
            dup2((int)src, (int)tgt);
            close((int)src);
        }
    }
}

/* Runs pre-seccomp in the constructor (plain syscalls OK). Finds the
 * [heap] segment, copies its live contents into the shared tmpfs file,
 * and maps the file over it MAP_FIXED — addresses and bytes unchanged,
 * but now the simulator can map the same file. tmpfs shared pages ARE
 * the page cache, so glibc's MADV_DONTNEED on freed chunks stays safe. */
static void setup_heap_window(void) {
    int mfd = open("/proc/self/maps", O_RDONLY | O_CLOEXEC);
    if (mfd < 0)
        return;
    static char mbuf[65536];
    ssize_t n = 0, got;
    while ((got = read(mfd, mbuf + n, sizeof(mbuf) - 1 - n)) > 0)
        n += got;
    close(mfd);
    if (n < 0)
        return;
    mbuf[n] = 0;
    uintptr_t start = 0, end = 0;
    char *h = strstr(mbuf, "[heap]");
    if (h) {
        while (h > mbuf && h[-1] != '\n')
            h--;
        if (sscanf(h, "%lx-%lx", &start, &end) != 2)
            start = end = 0;
    }
    if (!start) { /* no heap segment yet: window begins at current break */
        start = end = (uintptr_t)syscall(SYS_brk, 0);
        if (!start || (start & 4095))
            return;
    }
    char hpath[300];
    size_t bl = strlen(g_shm_base);
    if (bl + 6 >= sizeof hpath)
        return;
    memcpy(hpath, g_shm_base, bl);
    memcpy(hpath + bl, ".heap", 6);
    int fd = open(hpath, O_RDWR | O_CREAT | O_CLOEXEC, 0600);
    if (fd < 0)
        return;
    if (ftruncate(fd, SHADOW_HEAP_MAX) != 0) {
        close(fd);
        return;
    }
    uintptr_t len = (end - start + 4095) & ~(uintptr_t)4095;
    if (len) {
        size_t off = 0;
        while (off < len) {
            ssize_t w = pwrite(fd, (char *)start + off, len - off, off);
            if (w <= 0) {
                close(fd);
                return;
            }
            off += (size_t)w;
        }
        if (mmap((void *)start, len, PROT_READ | PROT_WRITE,
                 MAP_SHARED | MAP_FIXED, fd, 0) == MAP_FAILED) {
            close(fd);
            return;
        }
    }
    g_heap_fd = fd;
    g_heap_start = start;
    g_heap_cur = end;
    g_heap_mapped = start + len;
    g_ipc->heap_start = start;
    g_ipc->heap_cur = end;
}

__attribute__((constructor)) static void shadow_shim_init(void) {
    const char *path = getenv("SHADOW_SHM_PATH");
    if (!path)
        return; /* not under the simulator: run natively */

    /* ADDR_NO_RANDOMIZE (reference shadow.rs:428-429): if this image was
     * laid out with ASLR, flip the personality and re-exec once so every
     * mapping is at its fixed address. The flag survives exec, so the
     * second pass falls through. */
#ifndef ADDR_NO_RANDOMIZE
#define ADDR_NO_RANDOMIZE 0x0040000
#endif
    int pers = personality(0xffffffff);
    if (pers >= 0 && !(pers & ADDR_NO_RANDOMIZE)) {
        personality(pers | ADDR_NO_RANDOMIZE);
        static char cmdbuf[16384];
        int cfd = open("/proc/self/cmdline", O_RDONLY);
        if (cfd >= 0) {
            ssize_t n = read(cfd, cmdbuf, sizeof(cmdbuf));
            close(cfd);
            /* only re-exec with a FULL argv: a truncated command line
             * (n == bufsize, or more args than the table) must not be
             * silently re-run with different arguments */
            if (n > 0 && n < (ssize_t)sizeof(cmdbuf) &&
                cmdbuf[n - 1] == 0) {
                static char *cargv[512];
                int argc = 0;
                char *p = cmdbuf;
                while (p < cmdbuf + n && argc < 511) {
                    cargv[argc++] = p;
                    p += strlen(p) + 1;
                }
                if (p >= cmdbuf + n) { /* consumed every argument */
                    cargv[argc] = nullptr;
                    execv("/proc/self/exe", cargv);
                }
            }
        }
        /* exec failed or argv too large: continue with ASLR (best effort) */
    }

    size_t plen = strlen(path);
    if (plen >= sizeof(g_shm_base) - 8)
        _exit(90);
    memcpy(g_shm_base, path, plen + 1);
    int fd = open(path, O_RDWR | O_CLOEXEC);
    if (fd < 0)
        _exit(91);
    void *mem =
        mmap(nullptr, sizeof(IpcBlock), PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED)
        _exit(92);
    g_ipc = (IpcBlock *)mem;
    if (build_trampoline())
        _exit(93);

    struct sigaction sa;
    memset(&sa, 0, sizeof sa);
    sa.sa_sigaction = shadow_shim_handle_sigsys;
    sa.sa_flags = SA_SIGINFO | SA_NODEFER;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGSYS, &sa, nullptr))
        _exit(94);

    /* rdtsc interposition: trap the instruction, emulate from sim time.
     * Only armed when the vDSO was successfully rewritten to real
     * syscalls — otherwise the vDSO's own rdtsc-based clock math would
     * compute garbage from the synthesized counter. */
    struct sigaction sv;
    memset(&sv, 0, sizeof sv);
    sv.sa_sigaction = shadow_shim_handle_sigsegv;
    sv.sa_flags = SA_SIGINFO | SA_NODEFER;
    sigemptyset(&sv.sa_mask);
    if (sigaction(SIGSEGV, &sv, nullptr))
        _exit(94);
#ifndef PR_SET_TSC
#define PR_SET_TSC 26
#endif
#ifndef PR_TSC_SIGSEGV
#define PR_TSC_SIGSEGV 2
#endif
    if (patch_vdso() == 0)
        prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0);

    apply_fd_map(); /* execve-preserved fds back to their numbers */
    setup_heap_window(); /* best-effort: failure leaves brk passthrough */

    /* StartReq/StartRes handshake (managed_thread.rs:135-243) */
    ShimMsg start, resp;
    memset(&start, 0, sizeof start);
    start.kind = MSG_START;
    start.num = getpid();
    if (install_seccomp())
        _exit(95);
    chan_send(to_shadow(0), &start);
    if (chan_recv(to_shim(0), &resp) != 0 || resp.kind != MSG_START_OK)
        _exit(96);
}
