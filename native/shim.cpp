/* The in-process shim: LD_PRELOADed into every managed process.
 *
 * Reference surface being rebuilt (not ported): src/lib/shim/ —
 * seccomp filter install + SIGSYS interposition (shim_seccomp.c:36-68,
 * 189-250), local handling of hot time syscalls from the shared simulated
 * clock (shim_sys.c:25-114), the syscall dispatch loop (shim_syscall.c),
 * and the preload-libc symbol overrides (lib/preload-libc) for
 * vdso-destined time calls that raw seccomp cannot trap.
 *
 * Mechanism:
 *   1. constructor maps the IPC block (path in SHADOW_SHM_PATH), builds a
 *      one-page syscall trampoline, installs the SIGSYS handler, then a
 *      seccomp filter that ALLOWs rt_sigreturn and any syscall issued from
 *      the trampoline page and TRAPs everything else;
 *   2. trapped syscalls hit handle_sigsys(): time syscalls answered from
 *      IpcBlock.sim_time_ns with no context switch; everything else is
 *      shipped over the futex channel and either completed with the
 *      simulator's return value or re-executed natively via the trampoline
 *      when the simulator answers MSG_SYSCALL_NATIVE.
 */

#define _GNU_SOURCE 1
#include <errno.h>
#include <fcntl.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/futex.h>
#include <linux/seccomp.h>
#include <signal.h>
#include <stddef.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/prctl.h>
#include <sys/syscall.h>
#include <sys/ucontext.h>
#include <time.h>
#include <unistd.h>

#include "ipc.h"

static IpcBlock *g_ipc = nullptr;
typedef long (*raw_syscall_fn)(long n, long a, long b, long c, long d, long e,
                               long f);
static raw_syscall_fn g_raw = nullptr;
static uintptr_t g_tramp_page = 0;

/* ----------------------------------------------------------- trampoline */

/* mov rax,rdi; mov rdi,rsi; mov rsi,rdx; mov rdx,rcx; mov r10,r8;
 * mov r8,r9; mov r9,[rsp+8]; syscall; ret
 * (48 89 ca = mov rdx,rcx — NOT 48 89 ce, which is mov rsi,rcx and
 * silently swaps syscall args 2/3: write(fd,n,buf), openat(fd,NULL,path),
 * futex(addr,val,op) — i.e. every pointer re-issue EFAULTs and every
 * shim-side futex is a no-op) */
static const unsigned char TRAMP_CODE[] = {
    0x48, 0x89, 0xf8, 0x48, 0x89, 0xf7, 0x48, 0x89, 0xd6, 0x48, 0x89,
    0xca, 0x4d, 0x89, 0xc2, 0x4d, 0x89, 0xc8, 0x4c, 0x8b, 0x4c, 0x24,
    0x08, 0x0f, 0x05, 0xc3,
};

static int build_trampoline(void) {
    void *page = mmap(nullptr, 4096, PROT_READ | PROT_WRITE,
                      MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (page == MAP_FAILED)
        return -1;
    memcpy(page, TRAMP_CODE, sizeof(TRAMP_CODE));
    if (mprotect(page, 4096, PROT_READ | PROT_EXEC))
        return -1;
    g_tramp_page = (uintptr_t)page;
    g_raw = (raw_syscall_fn)page;
    return 0;
}

/* ------------------------------------------------------------- channel */

static void futex_wake(uint32_t *addr) {
    g_raw(SYS_futex, (long)addr, FUTEX_WAKE, 1 << 30, 0, 0, 0);
}

static void futex_wait(uint32_t *addr, uint32_t val) {
    g_raw(SYS_futex, (long)addr, FUTEX_WAIT, val, 0, 0, 0);
}

static void chan_send(ShimChan *c, const ShimMsg *m) {
    /* ping-pong: our previous message was consumed before we send again */
    while (__atomic_load_n(&c->state, __ATOMIC_ACQUIRE) == CHAN_FULL)
        futex_wait(&c->state, CHAN_FULL);
    c->msg = *m;
    __atomic_store_n(&c->state, CHAN_FULL, __ATOMIC_RELEASE);
    futex_wake(&c->state);
}

static int chan_recv(ShimChan *c, ShimMsg *out) {
    uint32_t s;
    while ((s = __atomic_load_n(&c->state, __ATOMIC_ACQUIRE)) != CHAN_FULL) {
        if (s == CHAN_CLOSED)
            return -1;
        futex_wait(&c->state, s);
    }
    *out = c->msg;
    __atomic_store_n(&c->state, CHAN_EMPTY, __ATOMIC_RELEASE);
    futex_wake(&c->state);
    return 0;
}

/* ----------------------------------------------------- time-from-shmem */

static int64_t sim_now(void) {
    return __atomic_load_n(&g_ipc->sim_time_ns, __ATOMIC_ACQUIRE);
}

static long emulate_time_syscall(long num, long a, long b) {
    int64_t now = sim_now();
    switch (num) {
    case SYS_clock_gettime: {
        struct timespec *ts = (struct timespec *)b;
        if (ts) {
            ts->tv_sec = now / 1000000000;
            ts->tv_nsec = now % 1000000000;
        }
        return 0;
    }
    case SYS_gettimeofday: {
        struct timeval *tv = (struct timeval *)a;
        if (tv) {
            tv->tv_sec = now / 1000000000;
            tv->tv_usec = (now % 1000000000) / 1000;
        }
        return 0;
    }
    case SYS_time: {
        long secs = now / 1000000000;
        if (a)
            *(long *)a = secs;
        return secs;
    }
    }
    return -ENOSYS;
}

/* --------------------------------------------------------------- sigsys */

static long forward_syscall(long num, const long args[6]) {
    ShimMsg req, resp;
    memset(&req, 0, sizeof req);
    req.kind = MSG_SYSCALL;
    req.num = num;
    for (int i = 0; i < 6; i++)
        req.args[i] = args[i];
    chan_send(&g_ipc->to_shadow, &req);
    if (chan_recv(&g_ipc->to_shim, &resp) != 0) {
        /* simulator went away: die quietly (ProcessDeath analogue) */
        g_raw(SYS_exit_group, 1, 0, 0, 0, 0, 0);
    }
    if (resp.kind == MSG_SYSCALL_NATIVE)
        return g_raw(num, args[0], args[1], args[2], args[3], args[4], args[5]);
    return resp.ret;
}

extern "C" void shadow_shim_handle_sigsys(int sig, siginfo_t *info,
                                          void *ucontext) {
    (void)sig;
    (void)info;
    ucontext_t *uc = (ucontext_t *)ucontext;
    greg_t *regs = uc->uc_mcontext.gregs;
    long num = regs[REG_RAX];
    long args[6] = {(long)regs[REG_RDI], (long)regs[REG_RSI],
                    (long)regs[REG_RDX], (long)regs[REG_R10],
                    (long)regs[REG_R8],  (long)regs[REG_R9]};
    long ret;
    switch (num) {
    case SYS_clock_gettime:
    case SYS_gettimeofday:
    case SYS_time:
        ret = emulate_time_syscall(num, args[0], args[1]);
        break;
    case SYS_clock_getres: {
        struct timespec *ts = (struct timespec *)args[1];
        if (ts) {
            ts->tv_sec = 0;
            ts->tv_nsec = 1;
        }
        ret = 0;
        break;
    }
    default:
        ret = forward_syscall(num, args);
        break;
    }
    regs[REG_RAX] = ret;
}

/* ----------------------------------------------------- libc interposers
 * vdso-backed time functions never produce a syscall instruction, so the
 * seccomp filter cannot see them; exporting the symbols from the preload
 * library routes PLT calls here instead (lib/preload-libc's INTERPOSE). */

extern "C" int clock_gettime(clockid_t clk, struct timespec *ts) {
    if (!g_ipc)
        return (int)syscall(SYS_clock_gettime, clk, ts);
    int64_t now = sim_now();
    if (ts) {
        ts->tv_sec = now / 1000000000;
        ts->tv_nsec = now % 1000000000;
    }
    return 0;
}

extern "C" int gettimeofday(struct timeval *tv, void *tz) {
    (void)tz;
    if (!g_ipc)
        return (int)syscall(SYS_gettimeofday, tv, tz);
    int64_t now = sim_now();
    if (tv) {
        tv->tv_sec = now / 1000000000;
        tv->tv_usec = (now % 1000000000) / 1000;
    }
    return 0;
}

extern "C" time_t time(time_t *tloc) {
    if (!g_ipc)
        return (time_t)syscall(SYS_time, tloc);
    time_t secs = sim_now() / 1000000000;
    if (tloc)
        *tloc = secs;
    return secs;
}

/* -------------------------------------------------------------- seccomp */

static int install_seccomp(void) {
    uint32_t lo = (uint32_t)(g_tramp_page & 0xffffffffu);
    uint32_t hi = (uint32_t)(g_tramp_page >> 32);
    struct sock_filter filter[] = {
        /* arch check */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, arch)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, 1, 0),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS),
        /* rt_sigreturn always allowed (signal handler unwind) */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS, offsetof(struct seccomp_data, nr)),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, SYS_rt_sigreturn, 5, 0),
        /* instruction pointer inside the trampoline page -> allow;
         * anything else -> TRAP (indices: 10 = ALLOW, 11 = TRAP) */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer) + 4),
        BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, hi, 0, 4),   /* !=hi -> TRAP */
        BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                 offsetof(struct seccomp_data, instruction_pointer)),
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, lo, 0, 2),   /* <lo  -> TRAP */
        BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, lo + 4096, 1, 0), /* >=end -> TRAP */
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW),
        BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_TRAP),
    };
    struct sock_fprog prog;
    prog.len = sizeof(filter) / sizeof(filter[0]);
    prog.filter = filter;
    if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0))
        return -1;
    if (syscall(SYS_seccomp, SECCOMP_SET_MODE_FILTER, 0, &prog))
        return -1;
    return 0;
}

/* ------------------------------------------------------------------ init */

__attribute__((constructor)) static void shadow_shim_init(void) {
    const char *path = getenv("SHADOW_SHM_PATH");
    if (!path)
        return; /* not under the simulator: run natively */
    int fd = open(path, O_RDWR | O_CLOEXEC);
    if (fd < 0)
        _exit(91);
    void *mem =
        mmap(nullptr, sizeof(IpcBlock), PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    close(fd);
    if (mem == MAP_FAILED)
        _exit(92);
    g_ipc = (IpcBlock *)mem;
    if (build_trampoline())
        _exit(93);

    struct sigaction sa;
    memset(&sa, 0, sizeof sa);
    sa.sa_sigaction = shadow_shim_handle_sigsys;
    sa.sa_flags = SA_SIGINFO | SA_NODEFER;
    sigemptyset(&sa.sa_mask);
    if (sigaction(SIGSYS, &sa, nullptr))
        _exit(94);

    /* StartReq/StartRes handshake (managed_thread.rs:135-243) */
    ShimMsg start, resp;
    memset(&start, 0, sizeof start);
    start.kind = MSG_START;
    start.num = getpid();
    if (install_seccomp())
        _exit(95);
    chan_send(&g_ipc->to_shadow, &start);
    if (chan_recv(&g_ipc->to_shim, &resp) != 0 || resp.kind != MSG_START_OK)
        _exit(96);
}
