/* flock contention in SIMULATED time: the holder takes LOCK_EX and sleeps;
 * the waiter's blocking flock must park in sim time (not wedge the
 * scheduler) and acquire exactly when the holder releases. */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/file.h>
#include <time.h>
#include <unistd.h>

#define CHECK(c) do { if (!(c)) { \
    fprintf(stderr, "FAIL %s:%d %s errno=%d\n", __FILE__, __LINE__, #c, \
            errno); return 1; } \
} while (0)

static long now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

int main(int argc, char **argv) {
    CHECK(argc >= 3);
    const char *mode = argv[2];
    int fd = open(argv[1], O_RDWR | O_CREAT, 0600);
    CHECK(fd >= 0);
    if (!strcmp(mode, "hold")) {
        long hold_ms = argc > 3 ? atol(argv[3]) : 300;
        CHECK(flock(fd, LOCK_EX) == 0);
        printf("held at %ld\n", now_ms());
        struct timespec ts = { hold_ms / 1000, (hold_ms % 1000) * 1000000 };
        nanosleep(&ts, NULL);
        CHECK(flock(fd, LOCK_UN) == 0);
        printf("released at %ld\n", now_ms());
    } else if (!strcmp(mode, "wait")) {
        /* LOCK_NB must say EWOULDBLOCK while held */
        if (flock(fd, LOCK_EX | LOCK_NB) == 0) {
            printf("nb acquired at %ld\n", now_ms());
            CHECK(flock(fd, LOCK_UN) == 0);
        } else {
            CHECK(errno == EWOULDBLOCK);
            printf("nb busy at %ld\n", now_ms());
        }
        long t0 = now_ms();
        CHECK(flock(fd, LOCK_EX) == 0); /* blocks in sim time */
        printf("acquired at %ld after %ld\n", now_ms(), now_ms() - t0);
        CHECK(flock(fd, LOCK_UN) == 0);
    }
    close(fd);
    return 0;
}
