/* Shared-memory IPC layout between the simulator and managed processes.
 *
 * Reference: the shim event protocol + IPC channel pair of
 * src/lib/shadow-shim-helper-rs (shim_event.rs ShimEventToShadow/ToShim,
 * ipc.rs IPCData = two lock-free channels) and the futex-based SPSC channel
 * of src/lib/vasi-sync/src/scchannel.rs — rebuilt as a C ping-pong channel.
 * The Python side mirrors this layout with struct offsets
 * (shadow_tpu/native_plane.py); keep the two in sync.
 *
 * Protocol: strict ping-pong per thread. Each thread of a managed process
 * owns one channel-pair slot (the reference's one-IPCData-per-ManagedThread,
 * managed_thread.rs:110): the thread writes `to_shadow` only when it is
 * EMPTY (it owns exactly one in-flight request), the simulator replies on
 * `to_shim`. `sim_time_ns` is the shared simulated clock the shim answers
 * time syscalls from without a context switch (HostShmem.sim_time,
 * shim_shmem.rs:91 / shim_sys.c:25-114). `doorbell` is bumped (and
 * futex-woken) after every to_shadow send so the simulator can wait on ONE
 * word for activity from any thread instead of polling every slot.
 */
#ifndef SHADOW_NATIVE_IPC_H
#define SHADOW_NATIVE_IPC_H

#include <stdint.h>

enum MsgKind {
    MSG_NONE = 0,
    MSG_START = 1,            /* shim -> shadow: process is initialized      */
    MSG_SYSCALL = 2,          /* shim -> shadow: trapped syscall             */
    MSG_START_OK = 3,         /* shadow -> shim: begin running               */
    MSG_SYSCALL_COMPLETE = 4, /* shadow -> shim: emulated, ret in `ret`      */
    MSG_SYSCALL_NATIVE = 5,   /* shadow -> shim: execute natively            */
    MSG_THREAD_START = 6,     /* shim(new thread) -> shadow: tid in `num`    */
    MSG_CLONE_DONE = 7,       /* shim(parent) -> shadow: real tid in args[0] */
    MSG_RUN_SIGNAL = 8,       /* shadow -> shim: call handler args[0] with
                               * signal `num` (args[1]=SA_SIGINFO), then send
                               * MSG_SIGNAL_DONE and keep waiting            */
    MSG_SIGNAL_DONE = 9,      /* shim -> shadow: handler returned            */
};

enum ChanState {
    CHAN_EMPTY = 0,
    CHAN_FULL = 1,
    CHAN_CLOSED = 2,
};

typedef struct {
    int32_t kind;
    int32_t _pad;
    int64_t num;     /* syscall number */
    int64_t args[6];
    int64_t ret;
} ShimMsg; /* 72 bytes */

typedef struct {
    uint32_t state; /* ChanState, futex word */
    uint32_t _pad;
    ShimMsg msg;
} ShimChan; /* 80 bytes */

#define IPC_MAX_THREADS 32

/* Custom simulator syscalls, far above the real syscall table (reference
 * custom syscalls shadow_yield / shadow_hostname_to_addr_ipv4,
 * handler/mod.rs:333-337). Issued by shim interposers via syscall(2);
 * seccomp traps and forwards them like any other number. */
#define SHADOW_SYS_RESOLVE 1000001 /* (name cstr ptr, u32be out ptr) -> 0|-errno */
#define SHADOW_SYS_SELF_IP 1000002 /* (u32be out ptr) -> 0 */
#define SHADOW_SYS_RESOLVE_REV 1000003 /* (u32be addr, buf ptr, len) -> 0|-errno */

typedef struct {
    ShimChan to_shadow;
    ShimChan to_shim;
} ShimChanPair; /* 160 bytes */

#define FASTFD_MAX 8
#define FASTFD_RING_CAP 32768

struct FastFd {
    int32_t vfd;   /* guest fd this entry serves; -1 = free */
    uint32_t kind; /* FastKind */
    uint64_t head; /* consumer cursor (free-running byte count) */
    uint64_t tail; /* producer cursor */
}; /* 24 bytes */

enum FastKind {
    FAST_NONE = 0,
    FAST_TX_STREAM = 1, /* shim writes, simulator drains (stdout/stderr) */
};

typedef struct {
    int64_t sim_time_ns; /* simulator-maintained simulated clock */
    uint32_t doorbell;   /* futex word: bumped on every to_shadow send */
    uint32_t flags;      /* bit0: model unblocked-syscall latency; bits1+:
                          * forward every Nth locally-answered time syscall
                          * to the simulator so busy-poll loops advance sim
                          * time (reference handler/mod.rs:268-318) */
    ShimChanPair thread[IPC_MAX_THREADS]; /* slot 0 = main thread */
    /* MemoryMapper window (reference memory_mapper.rs:84-110): the shim
     * remaps [heap_start, heap_cur) onto a shared tmpfs file
     * (SHADOW_SHM_PATH + ".heap") that the simulator maps too; both sides
     * then touch managed heap memory by plain memcpy instead of
     * process_vm_readv/writev (two kernel crossings per buffer).
     * heap_start == 0 means no window (fork children privatize and turn
     * it off; brk growth stays shim-local either way). */
    uint64_t heap_start;
    uint64_t heap_cur;
    /* fork barrier: the child stores 1 + FUTEX_WAKEs once its heap is
     * privatized; the parent FUTEX_WAITs before resuming. Without it the
     * two processes share the MAP_SHARED heap for a moment and parent
     * mallocs tear the child's copy (observed: glibc fastbin aborts). */
    uint32_t fork_sync;
    uint32_t _pad2;
    /* Shim-local identity fast path (r5; extends the shim_sys.c time
     * precedent): constant per-process VIRTUAL ids maintained by the
     * simulator (at spawn/fork/exec and on set*id). `ids_valid` gates the
     * path; identity getters answer from here without a channel round
     * trip (measured 14.25 us each), with the same every-Nth escape the
     * time path uses so identity spin loops still advance sim time. */
    uint32_t ids_valid;
    int32_t virt_pid;
    int32_t virt_ppid;
    int32_t virt_uid;
    int32_t virt_gid;
    uint32_t _pad3;
    /* Descriptor fast path (r5; the "descriptor state in shm" step the
     * syscall microbench pointed at): per-fd ring buffers the shim can
     * serve without a futex round trip. TX_STREAM = shim produces (tail),
     * simulator consumes (head) — captured stdio writes; RX rings are the
     * planned next kind. SAFETY ARGUMENT: exactly one guest thread runs
     * at a time and the simulator is parked while it does, so entries are
     * quiescent during guest execution; the simulator re-syncs entries
     * before replying to any fd-table-mutating syscall and drains rings
     * at every trap — rings are provably empty at every simulator
     * decision point. `fast_calls` counts locally-answered calls so the
     * simulator can fold them into syscall accounting at the next trap. */
    uint32_t fast_enabled;
    uint32_t fast_calls;
    struct FastFd fast[FASTFD_MAX];
    uint8_t fast_rings[FASTFD_MAX][FASTFD_RING_CAP];
} IpcBlock;

#define IPC_FLAGS_OFF 12

#define IPC_DOORBELL_OFF 8
#define IPC_THREADS_OFF 16
#define IPC_CHANPAIR_SIZE 160
#define IPC_TO_SHIM_OFF 80 /* within a pair */
#define IPC_HEAP_START_OFF (IPC_THREADS_OFF + IPC_MAX_THREADS * IPC_CHANPAIR_SIZE)
#define IPC_HEAP_CUR_OFF (IPC_HEAP_START_OFF + 8)
#define SHADOW_HEAP_MAX (256l << 20) /* window file size (sparse tmpfs) */

/* fast-path layout offsets (Python mirrors these; keep in sync) */
#define IPC_IDS_OFF (IPC_HEAP_START_OFF + 16 + 8)
#define IPC_FAST_ENABLED_OFF (IPC_IDS_OFF + 24)
#define IPC_FAST_CALLS_OFF (IPC_FAST_ENABLED_OFF + 4)
#define IPC_FAST_TABLE_OFF (IPC_FAST_CALLS_OFF + 4)
#define IPC_FASTFD_SIZE 24
#define IPC_FAST_RINGS_OFF (IPC_FAST_TABLE_OFF + FASTFD_MAX * IPC_FASTFD_SIZE)

/* the offset macros above are what the Python side mirrors — pin them to
 * the real struct layout so a field insertion breaks the BUILD, not a
 * ring read at runtime */
#include <stddef.h>
#ifdef __cplusplus
#define IPC_STATIC_ASSERT(c, m) static_assert(c, m)
#else
#define IPC_STATIC_ASSERT(c, m) _Static_assert(c, m)
#endif
IPC_STATIC_ASSERT(offsetof(IpcBlock, ids_valid) == IPC_IDS_OFF,
               "ids block offset drifted");
IPC_STATIC_ASSERT(offsetof(IpcBlock, fast_enabled) == IPC_FAST_ENABLED_OFF,
               "fast_enabled offset drifted");
IPC_STATIC_ASSERT(offsetof(IpcBlock, fast_calls) == IPC_FAST_CALLS_OFF,
               "fast_calls offset drifted");
IPC_STATIC_ASSERT(offsetof(IpcBlock, fast) == IPC_FAST_TABLE_OFF,
               "fast table offset drifted");
IPC_STATIC_ASSERT(sizeof(struct FastFd) == IPC_FASTFD_SIZE,
               "FastFd size drifted");
IPC_STATIC_ASSERT(offsetof(IpcBlock, fast_rings) == IPC_FAST_RINGS_OFF,
               "ring arena offset drifted");
IPC_STATIC_ASSERT(sizeof(IpcBlock) ==
                   IPC_FAST_RINGS_OFF + FASTFD_MAX * FASTFD_RING_CAP,
               "IpcBlock size drifted (update Python IPC_SIZE)");
IPC_STATIC_ASSERT(offsetof(IpcBlock, heap_start) == IPC_HEAP_START_OFF,
               "heap window offset drifted");

#endif
