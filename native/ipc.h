/* Shared-memory IPC layout between the simulator and managed processes.
 *
 * Reference: the shim event protocol + IPC channel pair of
 * src/lib/shadow-shim-helper-rs (shim_event.rs ShimEventToShadow/ToShim,
 * ipc.rs IPCData = two lock-free channels) and the futex-based SPSC channel
 * of src/lib/vasi-sync/src/scchannel.rs — rebuilt as a C ping-pong channel.
 * The Python side mirrors this layout with struct offsets
 * (shadow_tpu/native_plane.py); keep the two in sync.
 *
 * Protocol: strict ping-pong per thread. The shim writes `to_shadow` only
 * when it is EMPTY (guaranteed: it owns exactly one in-flight request), the
 * simulator replies on `to_shim`. `sim_time_ns` is the shared simulated
 * clock the shim answers time syscalls from without a context switch
 * (HostShmem.sim_time, shim_shmem.rs:91 / shim_sys.c:25-114).
 */
#ifndef SHADOW_NATIVE_IPC_H
#define SHADOW_NATIVE_IPC_H

#include <stdint.h>

enum MsgKind {
    MSG_NONE = 0,
    MSG_START = 1,            /* shim -> shadow: process is initialized      */
    MSG_SYSCALL = 2,          /* shim -> shadow: trapped syscall             */
    MSG_START_OK = 3,         /* shadow -> shim: begin running               */
    MSG_SYSCALL_COMPLETE = 4, /* shadow -> shim: emulated, ret in `ret`      */
    MSG_SYSCALL_NATIVE = 5,   /* shadow -> shim: execute natively            */
};

enum ChanState {
    CHAN_EMPTY = 0,
    CHAN_FULL = 1,
    CHAN_CLOSED = 2,
};

typedef struct {
    int32_t kind;
    int32_t _pad;
    int64_t num;     /* syscall number */
    int64_t args[6];
    int64_t ret;
} ShimMsg; /* 72 bytes */

typedef struct {
    uint32_t state; /* ChanState, futex word */
    uint32_t _pad;
    ShimMsg msg;
} ShimChan; /* 80 bytes */

typedef struct {
    int64_t sim_time_ns; /* simulator-maintained simulated clock */
    uint32_t _flags;
    uint32_t _pad;
    ShimChan to_shadow; /* offset 16 */
    ShimChan to_shim;   /* offset 96 */
} IpcBlock; /* 176 bytes */

#define IPC_TO_SHADOW_OFF 16
#define IPC_TO_SHIM_OFF 96

#endif
