/* Regular-file write passthrough: a managed binary writing its own output
 * file (logs, results) must hit the native write path, not ENOSYS
 * (reference regular_file.c passthrough policy). Writes via write(2) and
 * writev(2), reads the file back, prints the round-tripped content. */
#define _GNU_SOURCE
#include <fcntl.h>
#include <stdio.h>
#include <string.h>
#include <sys/uio.h>
#include <unistd.h>

int main(int argc, char **argv) {
    const char *path = argc > 1 ? argv[1] : "/tmp/shadow_filewrite.out";
    int fd = open(path, O_CREAT | O_TRUNC | O_WRONLY, 0644);
    if (fd < 0) { perror("open"); return 1; }
    const char *a = "hello ", *b = "file ", *c = "world\n";
    if (write(fd, a, strlen(a)) != (ssize_t)strlen(a)) { perror("write"); return 2; }
    struct iovec iov[2] = {
        {(void *)b, strlen(b)}, {(void *)c, strlen(c)},
    };
    ssize_t n = writev(fd, iov, 2);
    if (n != (ssize_t)(strlen(b) + strlen(c))) { perror("writev"); return 3; }
    if (close(fd)) { perror("close"); return 4; }

    fd = open(path, O_RDONLY);
    if (fd < 0) { perror("reopen"); return 5; }
    char buf[128];
    n = read(fd, buf, sizeof buf - 1);
    if (n < 0) { perror("read"); return 6; }
    buf[n] = 0;
    close(fd);
    unlink(path);
    printf("roundtrip: %s", buf);
    return 0;
}
