/* writev(2) on an emulated socket: a connected-UDP writev with multiple
 * iovs must go out as ONE datagram (and not ENOSYS — review finding). */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

int main(int argc, char **argv) {
    const char *ip = argc > 1 ? argv[1] : "127.0.0.1";
    int port = argc > 2 ? atoi(argv[2]) : 9000;
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in dst = {0};
    dst.sin_family = AF_INET;
    dst.sin_port = htons(port);
    if (inet_pton(AF_INET, ip, &dst.sin_addr) != 1) { perror("inet_pton"); return 1; }
    if (connect(fd, (struct sockaddr *)&dst, sizeof dst)) { perror("connect"); return 1; }
    char *a = "ping", *b = " 0";
    struct iovec iov[2] = {{a, strlen(a)}, {b, strlen(b)}};
    ssize_t n = writev(fd, iov, 2);
    if (n != (ssize_t)(strlen(a) + strlen(b))) { perror("writev"); return 2; }
    char buf[256];
    ssize_t got = recv(fd, buf, sizeof buf - 1, 0);
    if (got < 0) { perror("recv"); return 3; }
    buf[got] = 0;
    printf("echo: %s\n", buf);
    close(fd);
    return 0;
}
