/* Hostname / interface identity under the shim: gethostname (via the
 * virtualized uname), uname nodename, getaddrinfo + gethostbyname against
 * the simulator DNS, getifaddrs (lo + eth0 with the simulated IP).
 * Usage: test_dns <peer-hostname> */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/utsname.h>
#include <unistd.h>

int main(int argc, char **argv) {
    const char *peer = argc > 1 ? argv[1] : "localhost";

    char hn[256] = {0};
    if (gethostname(hn, sizeof hn)) { perror("gethostname"); return 1; }
    printf("hostname=%s\n", hn);

    struct utsname u;
    if (uname(&u)) { perror("uname"); return 1; }
    printf("nodename=%s release=%s\n", u.nodename, u.release);

    struct addrinfo hints = {0}, *res = NULL;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    int rc = getaddrinfo(peer, "http", &hints, &res);
    if (rc != 0) { fprintf(stderr, "getaddrinfo: %s\n", gai_strerror(rc)); return 1; }
    struct sockaddr_in *sa = (struct sockaddr_in *)res->ai_addr;
    printf("gai %s -> %s:%d\n", peer, inet_ntoa(sa->sin_addr),
           ntohs(sa->sin_port));
    freeaddrinfo(res);

    rc = getaddrinfo("no-such-host-xyz", NULL, &hints, &res);
    printf("gai unknown -> %s\n", rc == 0 ? "RESOLVED?!" : "EAI_NONAME");

    struct hostent *he = gethostbyname(peer);
    if (!he) { fprintf(stderr, "gethostbyname failed\n"); return 1; }
    printf("ghbn %s -> %s\n", peer,
           inet_ntoa(*(struct in_addr *)he->h_addr_list[0]));

    struct ifaddrs *ifa = NULL;
    if (getifaddrs(&ifa)) { perror("getifaddrs"); return 1; }
    for (struct ifaddrs *p = ifa; p; p = p->ifa_next) {
        if (!p->ifa_addr || p->ifa_addr->sa_family != AF_INET)
            continue;
        printf("if %s %s\n", p->ifa_name,
               inet_ntoa(((struct sockaddr_in *)p->ifa_addr)->sin_addr));
    }
    freeifaddrs(ifa);
    printf("dns ok\n");
    return 0;
}
