/* Channel-slot exhaustion: IPC_MAX_THREADS (32) bounds concurrent threads
 * per process; the 32nd+ concurrent pthread_create must fail with EAGAIN
 * (counted-and-sane degradation, not a wedge) and succeed again after
 * slots recycle. Usage: test_many_threads <nthreads> */
#define _GNU_SOURCE
#include <errno.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

static void *worker(void *arg) {
    (void)arg;
    struct timespec d = {0, 200 * 1000 * 1000}; /* hold the slot 200 ms */
    nanosleep(&d, NULL);
    return NULL;
}

int main(int argc, char **argv) {
    int want = argc > 1 ? atoi(argv[1]) : 40;
    pthread_t th[256];
    int created = 0, eagain = 0, other = 0;
    for (int i = 0; i < want && i < 256; i++) {
        int rc = pthread_create(&th[created], NULL, worker, NULL);
        if (rc == 0)
            created++;
        else if (rc == EAGAIN)
            eagain++;
        else
            other++;
    }
    for (int i = 0; i < created; i++)
        pthread_join(th[i], NULL);
    printf("created=%d eagain=%d other=%d\n", created, eagain, other);
    /* slots recycled after joins: one more create must succeed */
    pthread_t extra;
    if (pthread_create(&extra, NULL, worker, NULL) != 0) {
        printf("post-join create failed\n");
        return 1;
    }
    pthread_join(extra, NULL);
    printf("post-join create ok\n");
    return other == 0 ? 0 : 1;
}
