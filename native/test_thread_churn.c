/* Sequential create/join churn: more total threads than IPC slots proves
 * slot recycling (and the serialized clone handshake) works. */
#include <pthread.h>
#include <stdio.h>
#include <time.h>

static void *bump(void *arg) {
    long *p = (long *)arg;
    struct timespec d = {0, 1000000}; /* 1ms */
    nanosleep(&d, NULL);
    (*p)++;
    return NULL;
}

int main(void) {
    long counter = 0;
    for (int i = 0; i < 40; i++) {
        pthread_t th;
        if (pthread_create(&th, NULL, bump, &counter)) {
            printf("create %d failed\n", i);
            return 1;
        }
        pthread_join(th, NULL);
    }
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    printf("churn done counter=%ld t=%ldms\n", counter,
           ts.tv_sec * 1000 + ts.tv_nsec / 1000000);
    return 0;
}
