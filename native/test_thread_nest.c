/* Nested/concurrent thread creation: every worker spawns a sub-worker, so
 * clone handshakes from different threads can collide — the simulator must
 * serialize them (one CloneBoot in flight). */
#include <pthread.h>
#include <stdio.h>
#include <time.h>

static pthread_mutex_t lock = PTHREAD_MUTEX_INITIALIZER;
static int total = 0;

static void *leaf(void *arg) {
    (void)arg;
    pthread_mutex_lock(&lock);
    total++;
    pthread_mutex_unlock(&lock);
    return NULL;
}

static void *worker(void *arg) {
    pthread_t sub;
    pthread_create(&sub, NULL, leaf, NULL);
    leaf(arg);
    pthread_join(sub, NULL);
    return NULL;
}

int main(void) {
    pthread_t th[6];
    for (long i = 0; i < 6; i++)
        pthread_create(&th[i], NULL, worker, (void *)i);
    for (long i = 0; i < 6; i++)
        pthread_join(th[i], NULL);
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    printf("nest done total=%d t=%ldns\n", total,
           ts.tv_sec * 1000000000L + ts.tv_nsec);
    return total == 12 ? 0 : 1;
}
