/* Managed-process test binary (the analogue of the reference's dual
 * Linux/Shadow test programs, src/test/*): exercises time (simulated
 * clock), nanosleep (simulated time advance), getrandom (deterministic),
 * stdout writes (captured), and exit status. */
#define _GNU_SOURCE
#include <stdio.h>
#include <stdint.h>
#include <string.h>
#include <sys/random.h>
#include <time.h>
#include <unistd.h>

static int64_t now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (int64_t)ts.tv_sec * 1000000000 + ts.tv_nsec;
}

int main(int argc, char **argv) {
    int sleeps = argc > 1 ? atoi(argv[1]) : 3;
    printf("start t=%ld\n", (long)now_ns());
    fflush(stdout);
    for (int i = 0; i < sleeps; i++) {
        struct timespec d = {0, 250 * 1000 * 1000}; /* 250 ms */
        nanosleep(&d, NULL);
        printf("tick %d t=%ld\n", i, (long)now_ns());
        fflush(stdout);
    }
    unsigned char rnd[8];
    if (getrandom(rnd, sizeof rnd, 0) != sizeof rnd)
        return 2;
    printf("rnd=");
    for (unsigned i = 0; i < sizeof rnd; i++)
        printf("%02x", rnd[i]);
    printf("\nend t=%ld\n", (long)now_ns());
    fflush(stdout);
    return 0;
}
