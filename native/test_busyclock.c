/* Spin on the clock until 5ms of simulated time passes. Without the
 * unblocked-syscall latency model this loops forever (the shim answers
 * clock_gettime from shared memory at zero simulated cost); with it, every
 * Nth call is charged, so the loop terminates deterministically. */
#include <stdio.h>
#include <time.h>

int main(void) {
    struct timespec t0, t;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    long spins = 0;
    for (;;) {
        spins++;
        clock_gettime(CLOCK_MONOTONIC, &t);
        long d = (t.tv_sec - t0.tv_sec) * 1000000000L + (t.tv_nsec - t0.tv_nsec);
        if (d >= 5 * 1000 * 1000)
            break;
    }
    printf("busyclock done spins=%ld\n", spins);
    return 0;
}
