/* Busy-loop + time test: the managed clock must be fully simulator-driven,
 * so a CPU busy-loop consumes ZERO simulated time (the reference models CPU
 * delay only when configured; default frequency matching = no delay). */
#define _GNU_SOURCE
#include <stdio.h>
#include <stdint.h>
#include <time.h>

int main(void) {
    struct timespec a, b;
    clock_gettime(CLOCK_MONOTONIC, &a);
    volatile unsigned long x = 0;
    for (unsigned long i = 0; i < 50UL * 1000 * 1000; i++)
        x += i;
    clock_gettime(CLOCK_MONOTONIC, &b);
    long delta = (b.tv_sec - a.tv_sec) * 1000000000L + (b.tv_nsec - a.tv_nsec);
    printf("busy delta_ns=%ld x=%lu\n", delta, x);
    return delta == 0 ? 0 : 1;
}
