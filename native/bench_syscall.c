/* Syscall round-trip microbenchmark guest (VERDICT r4 #5).
 *
 * Hammers EMULATED syscall arms in a tight loop so the host can measure
 * the full futex-channel round trip (seccomp trap -> shim -> IPC ->
 * Python dispatch -> reply -> resume). Modes:
 *   fcntl  — fcntl(F_GETFL) on an emulated pipe vfd: the minimal arm
 *            (no memory traffic, no blocking) = pure round-trip cost
 *   pipe   — write(1 byte) + read(1 byte) through an emulated pipe:
 *            the hot data-path arms with guest-memory access
 *   clock  — clock_gettime(CLOCK_MONOTONIC): answered SHIM-LOCALLY from
 *            shared memory (reference shim_sys.c precedent) = the
 *            no-round-trip baseline the other modes are compared against
 */
#include <fcntl.h>
#include <stdio.h>
#include <sys/syscall.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: %s <fcntl|pipe|clock> <iters>\n", argv[0]);
        return 2;
    }
    long n = atol(argv[2]);
    int fds[2];
    if (pipe(fds) != 0) {
        perror("pipe");
        return 1;
    }
    if (!strcmp(argv[1], "fcntl")) {
        long acc = 0;
        for (long i = 0; i < n; i++) acc += fcntl(fds[0], F_GETFL);
        printf("fcntl done %ld acc=%ld\n", n, acc);
    } else if (!strcmp(argv[1], "pipe")) {
        char b = 'x';
        for (long i = 0; i < n; i++) {
            if (write(fds[1], &b, 1) != 1 || read(fds[0], &b, 1) != 1) {
                perror("pipe rw");
                return 1;
            }
        }
        printf("pipe done %ld\n", n);
    } else if (!strcmp(argv[1], "getpid")) {
        /* identity fast path: answered shim-locally from the ids block */
        long acc = 0;
        for (long i = 0; i < n; i++) acc += syscall(SYS_getpid);
        printf("getpid done %ld acc=%ld\n", n, acc);
    } else if (!strcmp(argv[1], "stdout")) {
        /* descriptor fast path: write(2) on captured stdout answered
         * shim-locally from the FastFd ring (r5) */
        char line[32];
        long len = (long)snprintf(line, sizeof line, "benchline\n");
        for (long i = 0; i < n; i++) {
            if (write(1, line, len) != len) return 1;
        }
        fprintf(stderr, "stdout done %ld\n", n);
    } else if (!strcmp(argv[1], "clock")) {
        struct timespec ts;
        long acc = 0;
        for (long i = 0; i < n; i++) {
            clock_gettime(CLOCK_MONOTONIC, &ts);
            acc += ts.tv_nsec;
        }
        printf("clock done %ld acc=%ld\n", n, acc);
    } else {
        return 2;
    }
    return 0;
}
