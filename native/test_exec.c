/* execve under the shim: fork a child that execs THIS binary in "worker"
 * mode (fresh image, same virtual pid, stdio capture preserved), plus the
 * documented failure paths erroring in the old image.
 * (Reference: the execve arm handler/mod.rs:401 + process.rs exec tests.) */
#define _GNU_SOURCE
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
    if (argc > 1 && !strcmp(argv[1], "worker")) {
        /* the post-exec image: prove time + pid virtualization still hold */
        struct timespec ts;
        clock_gettime(CLOCK_MONOTONIC, &ts);
        printf("worker pid=%d arg=%s t=%ld\n", getpid(),
               argc > 2 ? argv[2] : "?", ts.tv_sec);
        fflush(stdout);
        return 42;
    }

    /* failure paths stay in the old image */
    char *bad[] = {"nope", NULL};
    if (execve("/no/such/file", bad, NULL) == 0 || errno != ENOENT) {
        fprintf(stderr, "ENOENT path failed\n");
        return 1;
    }
    if (execve("/etc", bad, NULL) == 0 || errno != EACCES) {
        fprintf(stderr, "EACCES path failed\n");
        return 1;
    }

    pid_t pid = fork();
    if (pid < 0) { perror("fork"); return 1; }
    if (pid == 0) {
        char *args[] = {argv[0], (char *)"worker", (char *)"hi", NULL};
        char *env[] = {(char *)"MARKER=yes", NULL};
        execve(argv[0], args, env);
        perror("execve");
        _exit(9);
    }
    int st = 0;
    if (waitpid(pid, &st, 0) != pid) { perror("waitpid"); return 1; }
    if (!WIFEXITED(st) || WEXITSTATUS(st) != 42) {
        fprintf(stderr, "bad child status %d\n", st);
        return 1;
    }
    printf("parent saw exec'd child exit 42\n");
    return 0;
}
