/* dup/dup2 on emulated sockets, socketpair, FIONREAD/FIONBIO ioctls,
 * sysinfo, getrusage, getpgid family — single-process, no network peers
 * needed (reference: unistd/dup + ioctl + resource test binaries). */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/ioctl.h>
#include <sys/resource.h>
#include <sys/socket.h>
#include <sys/sysinfo.h>
#include <unistd.h>

#define CHECK(c) do { if (!(c)) { \
    fprintf(stderr, "FAIL %s:%d %s\n", __FILE__, __LINE__, #c); return 1; } \
} while (0)

int main(void) {
    /* socketpair: bytes cross, HUP on peer close */
    int sv[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, sv) == 0);
    CHECK(write(sv[0], "hello", 5) == 5);
    int avail = -1;
    CHECK(ioctl(sv[1], FIONREAD, &avail) == 0);
    CHECK(avail == 5);
    char buf[16];
    CHECK(read(sv[1], buf, sizeof buf) == 5 && !memcmp(buf, "hello", 5));

    /* dup of a socketpair end: both fds reach the same stream */
    int d = dup(sv[0]);
    CHECK(d >= 0 && d != sv[0]);
    CHECK(write(d, "viadup", 6) == 6);
    CHECK(read(sv[1], buf, sizeof buf) == 6 && !memcmp(buf, "viadup", 6));
    close(sv[0]);                      /* original closed ... */
    CHECK(write(d, "x", 1) == 1);      /* ... dup keeps the stream alive */
    CHECK(read(sv[1], buf, sizeof buf) == 1);

    /* dup2 onto a chosen number */
    int u = socket(AF_INET, SOCK_DGRAM, 0);
    CHECK(u >= 0);
    int tgt = u + 7;
    CHECK(dup2(u, tgt) == tgt);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(7777);
    a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    CHECK(bind(tgt, (struct sockaddr *)&a, sizeof a) == 0); /* via the dup */

    /* FIONBIO flips nonblocking */
    int one = 1;
    CHECK(ioctl(u, FIONBIO, &one) == 0);
    CHECK(recv(u, buf, sizeof buf, 0) == -1 && errno == EAGAIN);

    /* deterministic machine facts */
    struct sysinfo si;
    CHECK(sysinfo(&si) == 0);
    CHECK(si.totalram == 8ULL << 30 && si.mem_unit == 1);
    struct rusage ru;
    CHECK(getrusage(RUSAGE_SELF, &ru) == 0);
    CHECK(ru.ru_maxrss == 10240);
    CHECK(getpgrp() == getpid());

    printf("misc ok\n");
    fflush(stdout);

    /* 2>&1: after dup2(1, 2), stderr writes must land in the STDOUT
     * capture (the classic shell redirect) */
    CHECK(dup2(1, 2) == 2);
    fprintf(stderr, "redirected-to-stdout\n");
    fflush(stderr);
    return 0;
}
