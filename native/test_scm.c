/* SCM_RIGHTS fd passing + signalfd under the shim.
 * (Reference: socket/unix.rs ancillary handling; handler signalfd arm.)
 *
 * Parent forks a child connected by a unix STREAM socketpair; the parent
 * creates a second socketpair ("payload") and passes one end to the child
 * via SCM_RIGHTS. The child talks back over the passed fd — proving the
 * descriptor object itself crossed processes. Then the parent routes
 * SIGUSR1 into a signalfd and reads the siginfo record. */
#define _GNU_SOURCE
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/signalfd.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <unistd.h>

#define CHECK(c) do { if (!(c)) { \
    fprintf(stderr, "FAIL %s:%d %s errno=%d\n", __FILE__, __LINE__, #c, \
            errno); return 1; } \
} while (0)

static int send_fd(int sock, int fd, const char *tag) {
    struct iovec iov = { (void *)tag, strlen(tag) };
    char cbuf[CMSG_SPACE(sizeof(int))];
    memset(cbuf, 0, sizeof cbuf);
    struct msghdr mh = {0};
    mh.msg_iov = &iov;
    mh.msg_iovlen = 1;
    mh.msg_control = cbuf;
    mh.msg_controllen = sizeof cbuf;
    struct cmsghdr *cm = CMSG_FIRSTHDR(&mh);
    cm->cmsg_level = SOL_SOCKET;
    cm->cmsg_type = SCM_RIGHTS;
    cm->cmsg_len = CMSG_LEN(sizeof(int));
    memcpy(CMSG_DATA(cm), &fd, sizeof(int));
    return sendmsg(sock, &mh, 0) == (ssize_t)strlen(tag) ? 0 : -1;
}

static int recv_fd(int sock, char *tag, size_t taglen) {
    struct iovec iov = { tag, taglen };
    char cbuf[CMSG_SPACE(sizeof(int))];
    struct msghdr mh = {0};
    mh.msg_iov = &iov;
    mh.msg_iovlen = 1;
    mh.msg_control = cbuf;
    mh.msg_controllen = sizeof cbuf;
    ssize_t n = recvmsg(sock, &mh, 0);
    if (n <= 0) return -1;
    tag[n] = 0;
    for (struct cmsghdr *cm = CMSG_FIRSTHDR(&mh); cm;
         cm = CMSG_NXTHDR(&mh, cm)) {
        if (cm->cmsg_level == SOL_SOCKET && cm->cmsg_type == SCM_RIGHTS) {
            int fd;
            memcpy(&fd, CMSG_DATA(cm), sizeof(int));
            return fd;
        }
    }
    return -2;
}

int main(void) {
    int ctl[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, ctl) == 0);
    pid_t pid = fork();
    CHECK(pid >= 0);
    if (pid == 0) {  /* child: receive an fd, use it */
        close(ctl[0]);
        char tag[32];
        int pfd = recv_fd(ctl[1], tag, sizeof tag - 1);
        if (pfd < 0 || strcmp(tag, "payload") != 0) _exit(2);
        if (write(pfd, "via-passed-fd", 13) != 13) _exit(3);
        char ack[16];
        ssize_t n = read(pfd, ack, sizeof ack);
        if (n != 3 || memcmp(ack, "ack", 3) != 0) _exit(4);
        _exit(0);
    }
    close(ctl[1]);
    int pay[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, pay) == 0);
    CHECK(send_fd(ctl[0], pay[1], "payload") == 0);
    close(pay[1]);
    char buf[32];
    ssize_t n = read(pay[0], buf, sizeof buf);
    CHECK(n == 13 && memcmp(buf, "via-passed-fd", 13) == 0);
    CHECK(write(pay[0], "ack", 3) == 3);
    int status = -1;
    CHECK(waitpid(pid, &status, 0) == pid);
    CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
    printf("scm_rights ok\n");

    /* signalfd: route SIGUSR1 to an fd instead of a handler */
    sigset_t mask;
    sigemptyset(&mask);
    sigaddset(&mask, SIGUSR1);
    sigprocmask(SIG_BLOCK, &mask, NULL);
    int sfd = signalfd(-1, &mask, 0);
    CHECK(sfd >= 0);
    CHECK(kill(getpid(), SIGUSR1) == 0);
    struct signalfd_siginfo si;
    CHECK(read(sfd, &si, sizeof si) == sizeof si);
    CHECK(si.ssi_signo == SIGUSR1);
    CHECK(si.ssi_pid == (uint32_t)getpid()); /* sender attribution */
    CHECK(close(sfd) == 0);
    printf("signalfd ok\n");

    /* addressed DGRAM sendmsg with rights: bind two abstract names, send
     * a datagram BY NAME carrying an eventfd; a MSG_PEEK recvmsg must see
     * the bytes but NOT consume the rights; the real recvmsg gets the fd
     * and the sender's name */
    int a = socket(AF_UNIX, SOCK_DGRAM, 0), b2 = socket(AF_UNIX, SOCK_DGRAM, 0);
    CHECK(a >= 0 && b2 >= 0);
    struct sockaddr_un ua = {0}, ub = {0};
    ua.sun_family = ub.sun_family = AF_UNIX;
    memcpy(ua.sun_path, "\0scm-a", 6);
    memcpy(ub.sun_path, "\0scm-b", 6);
    CHECK(bind(a, (struct sockaddr *)&ua, sizeof(sa_family_t) + 6) == 0);
    CHECK(bind(b2, (struct sockaddr *)&ub, sizeof(sa_family_t) + 6) == 0);
    int efd = eventfd(0, 0); /* an EMULATED descriptor (vfds cross; real
                              * kernel fds are refused loudly) */
    CHECK(efd >= 0);
    {
        struct iovec iov = { (void *)"dgram", 5 };
        char cbuf[CMSG_SPACE(sizeof(int))];
        memset(cbuf, 0, sizeof cbuf);
        struct msghdr mh = {0};
        mh.msg_name = &ub;
        mh.msg_namelen = sizeof(sa_family_t) + 6;
        mh.msg_iov = &iov;
        mh.msg_iovlen = 1;
        mh.msg_control = cbuf;
        mh.msg_controllen = sizeof cbuf;
        struct cmsghdr *cm = CMSG_FIRSTHDR(&mh);
        cm->cmsg_level = SOL_SOCKET;
        cm->cmsg_type = SCM_RIGHTS;
        cm->cmsg_len = CMSG_LEN(sizeof(int));
        memcpy(CMSG_DATA(cm), &efd, sizeof(int));
        CHECK(sendmsg(a, &mh, 0) == 5);
    }
    char dbuf[16];
    {   /* peek: bytes visible, rights NOT consumed */
        struct iovec iov = { dbuf, sizeof dbuf };
        char cbuf[CMSG_SPACE(sizeof(int))];
        struct msghdr mh = {0};
        mh.msg_iov = &iov;
        mh.msg_iovlen = 1;
        mh.msg_control = cbuf;
        mh.msg_controllen = sizeof cbuf;
        CHECK(recvmsg(b2, &mh, MSG_PEEK) == 5);
        CHECK(CMSG_FIRSTHDR(&mh) == NULL); /* no rights on the peek */
    }
    {   /* consuming recvmsg: fd + sender name */
        struct sockaddr_un from = {0};
        struct iovec iov = { dbuf, sizeof dbuf };
        char cbuf[CMSG_SPACE(sizeof(int))];
        struct msghdr mh = {0};
        mh.msg_name = &from;
        mh.msg_namelen = sizeof from;
        mh.msg_iov = &iov;
        mh.msg_iovlen = 1;
        mh.msg_control = cbuf;
        mh.msg_controllen = sizeof cbuf;
        CHECK(recvmsg(b2, &mh, 0) == 5 && !memcmp(dbuf, "dgram", 5));
        CHECK(mh.msg_namelen >= sizeof(sa_family_t) + 6);
        CHECK(!memcmp(from.sun_path, "\0scm-a", 6));
        struct cmsghdr *cm = CMSG_FIRSTHDR(&mh);
        CHECK(cm && cm->cmsg_type == SCM_RIGHTS);
        int rfd;
        memcpy(&rfd, CMSG_DATA(cm), sizeof(int));
        CHECK(rfd != efd);
        uint64_t v = 7;
        CHECK(write(efd, &v, 8) == 8); /* write via the original... */
        v = 0;
        CHECK(read(rfd, &v, 8) == 8 && v == 7); /* ...read via the passed */
        close(rfd);
    }
    close(a);
    close(b2);
    close(efd);
    printf("dgram rights ok\n");
    return 0;
}
