/* Real-binary UDP echo server: binds a port, echoes datagrams upper-cased.
 * The analogue of the reference's socket test servers (src/test/socket/). */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <ctype.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    int port = argc > 1 ? atoi(argv[1]) : 9000;
    int count = argc > 2 ? atoi(argv[2]) : 0; /* 0 = serve forever */
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0) { perror("socket"); return 1; }
    struct sockaddr_in addr = {0};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    addr.sin_addr.s_addr = INADDR_ANY;
    if (bind(fd, (struct sockaddr *)&addr, sizeof addr)) { perror("bind"); return 1; }
    printf("listening on %d\n", port);
    fflush(stdout);
    char buf[2048];
    int served = 0;
    while (count == 0 || served < count) {
        struct sockaddr_in src;
        socklen_t slen = sizeof src;
        ssize_t n = recvfrom(fd, buf, sizeof buf, 0, (struct sockaddr *)&src, &slen);
        if (n < 0) { perror("recvfrom"); return 1; }
        for (ssize_t i = 0; i < n; i++) buf[i] = toupper((unsigned char)buf[i]);
        if (sendto(fd, buf, n, 0, (struct sockaddr *)&src, slen) != n) {
            perror("sendto"); return 1;
        }
        served++;
    }
    printf("served %d\n", served);
    return 0;
}
