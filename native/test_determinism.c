/* Determinism hardening workout: rdtsc/rdtscp emulated from sim time,
 * /dev/urandom virtualized onto the seeded host RNG, getrandom emulated,
 * and ASLR disabled (stable addresses). Two runs must be byte-identical.
 * (Reference: shim_rdtsc.c, preload-openssl, shadow.rs ASLR disable.) */
#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <time.h>
#include <unistd.h>
#include <sys/random.h>
#include <sys/syscall.h>

static inline uint64_t rdtsc(void) {
    uint32_t lo, hi;
    __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
    return ((uint64_t)hi << 32) | lo;
}

static inline uint64_t rdtscp_(void) {
    uint32_t lo, hi, aux;
    __asm__ __volatile__("rdtscp" : "=a"(lo), "=d"(hi), "=c"(aux));
    return ((uint64_t)hi << 32) | lo;
}

int main(void) {
    uint64_t t0 = rdtsc();
    struct timespec d = {0, 7 * 1000 * 1000}; /* 7 ms */
    nanosleep(&d, NULL);
    uint64_t t1 = rdtscp_();
    /* 1 tick = 1 ns: the sleep must read as exactly 7e6 ticks */
    printf("tsc start=%lu delta=%lu\n", t0, t1 - t0);

    unsigned char buf[8];
    int fd = open("/dev/urandom", O_RDONLY);
    ssize_t n = read(fd, buf, sizeof buf);
    close(fd);
    printf("urandom n=%zd bytes=%02x%02x%02x%02x%02x%02x%02x%02x\n", n,
           buf[0], buf[1], buf[2], buf[3], buf[4], buf[5], buf[6], buf[7]);

    getrandom(buf, sizeof buf, 0);
    printf("getrandom bytes=%02x%02x%02x%02x%02x%02x%02x%02x\n", buf[0],
           buf[1], buf[2], buf[3], buf[4], buf[5], buf[6], buf[7]);

    int stack_probe = 0;
    printf("stackaddr=%p\n", (void *)&stack_probe);
    return 0;
}
