/* uio + msg syscall family over the simulated network: sendmsg/recvmsg
 * (UDP, with name out-param), readv (TCP scatter), sendmmsg/recvmmsg.
 * Roles: "server <port> <count>" echoes; "client <ip> <port> <count>". */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

static int udp_server(int port, int count) {
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) { perror("bind"); return 1; }
    for (int i = 0; i < count; i++) {
        char h[8], t[56];
        struct iovec iov[2] = {{h, sizeof h}, {t, sizeof t}};
        struct sockaddr_in peer = {0};
        struct msghdr mh = {0};
        mh.msg_name = &peer;
        mh.msg_namelen = sizeof peer;
        mh.msg_iov = iov;
        mh.msg_iovlen = 2;
        ssize_t n = recvmsg(fd, &mh, 0);
        if (n < 0) { perror("recvmsg"); return 1; }
        if (mh.msg_namelen < 8) { fprintf(stderr, "no peer name\n"); return 1; }
        /* echo back through sendmsg with explicit name */
        struct iovec out[2] = {{h, n < 8 ? (size_t)n : 8},
                               {t, n > 8 ? (size_t)(n - 8) : 0}};
        struct msghdr om = {0};
        om.msg_name = &peer;
        om.msg_namelen = mh.msg_namelen;
        om.msg_iov = out;
        om.msg_iovlen = 2;
        if (sendmsg(fd, &om, 0) != n) { perror("sendmsg"); return 1; }
        printf("echoed %zd from %s\n", n, inet_ntoa(peer.sin_addr));
        fflush(stdout);
    }
    printf("server done\n");
    return 0;
}

static int udp_client(const char *ip, int port, int count) {
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in dst = {0};
    dst.sin_family = AF_INET;
    dst.sin_port = htons(port);
    inet_pton(AF_INET, ip, &dst.sin_addr);
    for (int i = 0; i < count; i++) {
        char a[16], b[16];
        int na = snprintf(a, sizeof a, "part1-%d|", i);
        int nb = snprintf(b, sizeof b, "part2-%d", i);
        struct iovec iov[2] = {{a, (size_t)na}, {b, (size_t)nb}};
        struct msghdr mh = {0};
        mh.msg_name = &dst;
        mh.msg_namelen = sizeof dst;
        mh.msg_iov = iov;
        mh.msg_iovlen = 2;
        if (sendmsg(fd, &mh, 0) != na + nb) { perror("sendmsg"); return 1; }
        char r1[8], r2[56];
        struct iovec riov[2] = {{r1, sizeof r1}, {r2, sizeof r2}};
        struct sockaddr_in peer = {0};
        struct msghdr rm = {0};
        rm.msg_name = &peer;
        rm.msg_namelen = sizeof peer;
        rm.msg_iov = riov;
        rm.msg_iovlen = 2;
        ssize_t n = recvmsg(fd, &rm, 0);
        if (n != na + nb) { perror("recvmsg"); return 1; }
        char whole[64];
        memcpy(whole, r1, n < 8 ? (size_t)n : 8);
        if (n > 8) memcpy(whole + 8, r2, (size_t)(n - 8));
        whole[n] = 0;
        printf("reply %d: %s from port %d\n", i, whole, ntohs(peer.sin_port));
        fflush(stdout);
    }
    printf("client done\n");
    return 0;
}

static int tcp_readv_server(int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) { perror("bind"); return 1; }
    if (listen(fd, 4)) { perror("listen"); return 1; }
    int c = accept(fd, NULL, NULL);
    if (c < 0) { perror("accept"); return 1; }
    char h[4], t[60];
    size_t got = 0, want = 32;
    while (got < want) {
        struct iovec iov[2] = {{h, sizeof h}, {t, sizeof t}};
        ssize_t n = readv(c, iov, 2);
        if (n <= 0) { perror("readv"); return 1; }
        got += (size_t)n;
    }
    printf("readv total %zu\n", got);
    const char ok[] = "OK";
    if (write(c, ok, 2) != 2) { perror("write"); return 1; }
    close(c);
    printf("server done\n");
    return 0;
}

static int tcp_writev_client(const char *ip, int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in dst = {0};
    dst.sin_family = AF_INET;
    dst.sin_port = htons(port);
    inet_pton(AF_INET, ip, &dst.sin_addr);
    if (connect(fd, (struct sockaddr *)&dst, sizeof dst)) { perror("connect"); return 1; }
    char a[16], b[16];
    memset(a, 'A', sizeof a);
    memset(b, 'B', sizeof b);
    struct iovec iov[2] = {{a, sizeof a}, {b, sizeof b}};
    if (writev(fd, iov, 2) != 32) { perror("writev"); return 1; }
    char r[4];
    if (read(fd, r, sizeof r) != 2 || r[0] != 'O') { perror("read"); return 1; }
    printf("client done\n");
    return 0;
}

int main(int argc, char **argv) {
    if (argc < 2) return 2;
    if (!strcmp(argv[1], "server"))
        return udp_server(atoi(argv[2]), atoi(argv[3]));
    if (!strcmp(argv[1], "client"))
        return udp_client(argv[2], atoi(argv[3]), atoi(argv[4]));
    if (!strcmp(argv[1], "tserver"))
        return tcp_readv_server(atoi(argv[2]));
    if (!strcmp(argv[1], "tclient"))
        return tcp_writev_client(argv[2], atoi(argv[3]));
    return 2;
}
