/* Kernel value-result semantics for (sockaddr*, socklen_t*): a caller
 * passing a short buffer must not have adjacent memory overwritten, and the
 * true address length must be stored back (accept(2) NOTES). */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(void) {
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in any = {0};
    any.sin_family = AF_INET;
    any.sin_port = htons(7777);
    if (bind(fd, (struct sockaddr *)&any, sizeof any)) { perror("bind"); return 1; }

    struct {
        char addr[8];     /* deliberately too small for sockaddr_in (16) */
        char guard[8];    /* must survive untouched */
    } shortbuf;
    memset(shortbuf.addr, 0, sizeof shortbuf.addr);
    memset(shortbuf.guard, 0xAA, sizeof shortbuf.guard);
    socklen_t len = sizeof shortbuf.addr; /* = 8 */
    if (getsockname(fd, (struct sockaddr *)shortbuf.addr, &len)) {
        perror("getsockname");
        return 2;
    }
    int guard_ok = 1;
    for (unsigned i = 0; i < sizeof shortbuf.guard; i++)
        if ((unsigned char)shortbuf.guard[i] != 0xAA) guard_ok = 0;
    /* the stored-back length is the TRUE size, not the truncated one */
    printf("guard_ok=%d len=%u port=%u\n", guard_ok, (unsigned)len,
           ntohs(((struct sockaddr_in *)shortbuf.addr)->sin_port));

    /* full-size buffer for comparison */
    struct sockaddr_in full = {0};
    socklen_t flen = sizeof full;
    if (getsockname(fd, (struct sockaddr *)&full, &flen)) { perror("full"); return 3; }
    printf("full len=%u port=%u\n", (unsigned)flen, ntohs(full.sin_port));
    close(fd);
    return 0;
}
