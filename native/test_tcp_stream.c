/* Real-binary TCP streamer: `server PORT` accepts one connection and drains
 * it; `client IP PORT BYTES` streams BYTES and half-closes. Exercises the
 * emulated TCP socket surface end to end (handshake, flow control,
 * retransmission under loss, FIN). */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static int serve(int port) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) { perror("bind"); return 1; }
    if (listen(fd, 8)) { perror("listen"); return 1; }
    printf("listening\n");
    fflush(stdout);
    struct sockaddr_in peer;
    socklen_t plen = sizeof peer;
    int c = accept(fd, (struct sockaddr *)&peer, &plen);
    if (c < 0) { perror("accept"); return 1; }
    char ip[32];
    inet_ntop(AF_INET, &peer.sin_addr, ip, sizeof ip);
    long total = 0, sum = 0;
    char buf[65536];
    ssize_t n;
    while ((n = recv(c, buf, sizeof buf, 0)) > 0) {
        total += n;
        for (ssize_t i = 0; i < n; i++) sum += (unsigned char)buf[i];
    }
    if (n < 0) { perror("recv"); return 1; }
    printf("from %s got %ld bytes sum %ld\n", ip, total, sum);
    close(c);
    return 0;
}

static int run_client(const char *ip, int port, long bytes) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    inet_pton(AF_INET, ip, &a.sin_addr);
    if (connect(fd, (struct sockaddr *)&a, sizeof a)) { perror("connect"); return 1; }
    printf("connected\n");
    fflush(stdout);
    char block[16384];
    for (size_t i = 0; i < sizeof block; i++) block[i] = (char)(i % 251);
    long sent = 0, sum = 0;
    while (sent < bytes) {
        size_t want = sizeof block;
        if ((long)want > bytes - sent) want = bytes - sent;
        ssize_t n = send(fd, block, want, 0);
        if (n < 0) { perror("send"); return 1; }
        for (ssize_t i = 0; i < n; i++) sum += (unsigned char)block[i];
        sent += n;
    }
    shutdown(fd, SHUT_WR);
    printf("sent %ld bytes sum %ld\n", sent, sum);
    close(fd);
    return 0;
}

int main(int argc, char **argv) {
    if (argc >= 2 && strcmp(argv[1], "server") == 0)
        return serve(argc > 2 ? atoi(argv[2]) : 8080);
    if (argc >= 4)
        return run_client(argv[1], atoi(argv[2]), atol(argv[3]));
    fprintf(stderr, "usage: %s server PORT | IP PORT BYTES\n", argv[0]);
    return 2;
}
