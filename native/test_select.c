/* select(2)-driven UDP echo: the server multiplexes two sockets with
 * select and a timeout; the client pings both ports. Exercises the
 * emulated fd_set path (reference handler/select.c test family). */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <unistd.h>

static int mk_udp(int port) {
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) { perror("bind"); exit(1); }
    return fd;
}

static int server(int port, int count) {
    int f1 = mk_udp(port), f2 = mk_udp(port + 1);
    int seen = 0, timeouts = 0;
    while (seen < count) {
        fd_set rs;
        FD_ZERO(&rs);
        FD_SET(f1, &rs);
        FD_SET(f2, &rs);
        struct timeval tv = {2, 0};
        int mx = (f1 > f2 ? f1 : f2) + 1;
        int r = select(mx, &rs, NULL, NULL, &tv);
        if (r < 0) { perror("select"); return 1; }
        if (r == 0) { timeouts++; if (timeouts > 5) return 1; continue; }
        for (int fd = 0; fd < 2; fd++) {
            int f = fd ? f2 : f1;
            if (!FD_ISSET(f, &rs)) continue;
            char buf[256];
            struct sockaddr_in peer;
            socklen_t pl = sizeof peer;
            ssize_t n = recvfrom(f, buf, sizeof buf, 0,
                                 (struct sockaddr *)&peer, &pl);
            if (n < 0) { perror("recvfrom"); return 1; }
            sendto(f, buf, (size_t)n, 0, (struct sockaddr *)&peer, pl);
            seen++;
            printf("echo via %s\n", fd ? "second" : "first");
            fflush(stdout);
        }
    }
    printf("server done timeouts=%d\n", timeouts);
    return 0;
}

static int client(const char *ip, int port, int count) {
    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    for (int i = 0; i < count; i++) {
        struct sockaddr_in dst = {0};
        dst.sin_family = AF_INET;
        dst.sin_port = htons(port + (i % 2));
        inet_pton(AF_INET, ip, &dst.sin_addr);
        char msg[32];
        int n = snprintf(msg, sizeof msg, "m%d", i);
        sendto(fd, msg, (size_t)n, 0, (struct sockaddr *)&dst, sizeof dst);
        /* select for the reply too (client side) */
        fd_set rs;
        FD_ZERO(&rs);
        FD_SET(fd, &rs);
        struct timeval tv = {3, 0};
        int r = select(fd + 1, &rs, NULL, NULL, &tv);
        if (r != 1 || !FD_ISSET(fd, &rs)) { fprintf(stderr, "sel=%d\n", r); return 1; }
        char buf[64];
        ssize_t g = recv(fd, buf, sizeof buf, 0);
        if (g != n) { perror("recv"); return 1; }
        printf("reply %d ok\n", i);
        fflush(stdout);
    }
    printf("client done\n");
    return 0;
}

int main(int argc, char **argv) {
    if (argc < 2) return 2;
    if (!strcmp(argv[1], "server"))
        return server(atoi(argv[2]), atoi(argv[3]));
    return client(argv[2], atoi(argv[3]), atoi(argv[4]));
}
