/* The r4 last-stretch dispatch arms: legacy open/stat/pipe, utimes,
 * pwrite, credential setters (emulated no-ops — a NATIVE setuid would
 * strip the simulator's process_vm access), capget/capset,
 * sched_setaffinity, close_range, and waitid. */
#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <linux/capability.h>
#include <sched.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/wait.h>
#include <unistd.h>
#include <utime.h>

#define CHECK(c) do { if (!(c)) { \
    fprintf(stderr, "FAIL %s:%d %s errno=%d\n", __FILE__, __LINE__, #c, \
            errno); return 1; } \
} while (0)

int main(int argc, char **argv) {
    CHECK(argc == 2);
    char path[600];
    snprintf(path, sizeof path, "%s/legacy.txt", argv[1]);

    /* legacy open(2) + pwrite + fstat via stat(2)/lstat(2) */
    int fd = syscall(SYS_open, path, O_CREAT | O_RDWR, 0644);
    CHECK(fd >= 0);
    CHECK(pwrite(fd, "abcdef", 6, 0) == 6);
    CHECK(pwrite(fd, "XY", 2, 2) == 2);
    char buf[8] = {0};
    CHECK(pread(fd, buf, 6, 0) == 6 && !memcmp(buf, "abXYef", 6));
    CHECK(close(fd) == 0);
    struct stat st;
    CHECK(syscall(SYS_stat, path, &st) == 0 && st.st_size == 6);
    CHECK(syscall(SYS_lstat, path, &st) == 0);

    /* utimes: set a deterministic mtime */
    struct timeval tv[2] = {{1000, 0}, {2000, 0}};
    CHECK(utimes(path, tv) == 0);
    CHECK(stat(path, &st) == 0 && st.st_mtime == 2000);
    CHECK(unlink(path) == 0);

    /* pipe(2) (legacy) */
    int pfd[2];
    CHECK(syscall(SYS_pipe, pfd) == 0);
    CHECK(write(pfd[1], "pp", 2) == 2);
    CHECK(read(pfd[0], buf, 2) == 2 && !memcmp(buf, "pp", 2));
    close(pfd[0]);
    close(pfd[1]);

    /* credential setters: emulated success, identity unchanged */
    CHECK(syscall(SYS_setuid, 12345) == 0);
    CHECK(getuid() == geteuid());  /* still whoever we started as */
    CHECK(syscall(SYS_setresgid, 1, 2, 3) == 0);

    /* capget reports empty caps; capset accepted */
    struct __user_cap_header_struct hdr = {_LINUX_CAPABILITY_VERSION_3, 0};
    struct __user_cap_data_struct data[2];
    memset(data, 0xff, sizeof data);
    CHECK(syscall(SYS_capget, &hdr, data) == 0);
    CHECK(data[0].effective == 0 && data[0].permitted == 0);
    CHECK(syscall(SYS_capset, &hdr, data) == 0);

    /* sched_setaffinity accepted on the one-cpu simulated host */
    cpu_set_t set;
    CPU_ZERO(&set);
    CPU_SET(0, &set);
    CHECK(sched_setaffinity(0, sizeof set, &set) == 0);

    /* waitid: fork a child, reap via the siginfo-shaped wait */
    pid_t pid = fork();
    CHECK(pid >= 0);
    if (pid == 0)
        _exit(7);
    siginfo_t si;
    memset(&si, 0, sizeof si);
    CHECK(waitid(P_PID, pid, &si, WEXITED) == 0);
    CHECK(si.si_pid == pid);
    CHECK(si.si_code == CLD_EXITED && si.si_status == 7);

    /* close_range over a span holding an emulated socket vfd */
    int s1 = socket(2 /*AF_INET*/, 2 /*SOCK_DGRAM*/, 0);
    CHECK(s1 >= 0);
    CHECK(syscall(SYS_close_range, (unsigned)s1, (unsigned)s1 + 10, 0) == 0);
    CHECK(write(s1, "x", 1) == -1);  /* really closed */

    /* FD_CLOEXEC bookkeeping on emulated descriptors: creation flags,
     * F_SETFD/F_GETFD round trip, dup3(O_CLOEXEC), dup2 clearing it,
     * close_range(CLOSE_RANGE_CLOEXEC) marking without closing */
    int pcl[2];
    CHECK(syscall(SYS_pipe2, pcl, O_CLOEXEC) == 0);
    CHECK(fcntl(pcl[0], F_GETFD) == FD_CLOEXEC);
    CHECK(fcntl(pcl[0], F_SETFD, 0) == 0);
    CHECK(fcntl(pcl[0], F_GETFD) == 0);
    int d3 = syscall(SYS_dup3, pcl[1], pcl[1] + 7, O_CLOEXEC);
    CHECK(d3 == pcl[1] + 7 && fcntl(d3, F_GETFD) == FD_CLOEXEC);
    int d2 = dup(pcl[1]);  /* plain dup: no CLOEXEC */
    CHECK(fcntl(d2, F_GETFD) == 0);
    /* dup2 onto a CLOEXEC'd number CLEARS the flag on the target */
    CHECK(fcntl(d3, F_SETFD, FD_CLOEXEC) == 0);
    CHECK(dup2(pcl[1], d3) == d3);
    CHECK(fcntl(d3, F_GETFD) == 0);
    CHECK(syscall(SYS_close_range, (unsigned)d2, (unsigned)d2,
                  0x4 /*CLOSE_RANGE_CLOEXEC*/) == 0);
    CHECK(fcntl(d2, F_GETFD) == FD_CLOEXEC);
    CHECK(write(d2, "z", 1) == 1);  /* marked, NOT closed */
    char zb[2];
    CHECK(read(pcl[0], zb, 1) == 1 && zb[0] == 'z');
    close(pcl[0]);
    close(pcl[1]);
    close(d3);
    close(d2);
    /* F_GETFL access modes: glibc fdopen validates them (git's fdopen
     * died EINVAL when every emulated fd claimed O_RDONLY) */
    int pm[2];
    CHECK(syscall(SYS_pipe2, pm, 0) == 0);
    CHECK((fcntl(pm[0], F_GETFL) & O_ACCMODE) == O_RDONLY);
    CHECK((fcntl(pm[1], F_GETFL) & O_ACCMODE) == O_WRONLY);
    FILE *fw = fdopen(pm[1], "w");
    CHECK(fw != NULL);
    fputs("via-stdio\n", fw);
    fflush(fw);
    char lb[16];
    CHECK(read(pm[0], lb, 10) == 10 && !memcmp(lb, "via-stdio\n", 10));
    fclose(fw);
    close(pm[0]);

    printf("misc2 ok\n");
    return 0;
}
