/* Real-binary epoll event loop: UDP echo + periodic timerfd ticks, the
 * canonical production-server shape (reference test families epoll/,
 * timerfd/). Exits after `pings` datagrams and `ticks` timer fires. */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/timerfd.h>
#include <time.h>
#include <unistd.h>

static long now_ns(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000000000L + ts.tv_nsec;
}

int main(int argc, char **argv) {
    int port = argc > 1 ? atoi(argv[1]) : 9000;
    int want_pings = argc > 2 ? atoi(argv[2]) : 2;
    int want_ticks = argc > 3 ? atoi(argv[3]) : 3;

    int sfd = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    a.sin_addr.s_addr = INADDR_ANY;
    if (bind(sfd, (struct sockaddr *)&a, sizeof a)) { perror("bind"); return 1; }

    int tfd = timerfd_create(CLOCK_MONOTONIC, 0);
    struct itimerspec its = {{0, 200 * 1000 * 1000}, {0, 200 * 1000 * 1000}};
    if (timerfd_settime(tfd, 0, &its, NULL)) { perror("timerfd_settime"); return 1; }

    int ep = epoll_create1(0);
    struct epoll_event ev = {0};
    ev.events = EPOLLIN;
    ev.data.fd = sfd;
    if (epoll_ctl(ep, EPOLL_CTL_ADD, sfd, &ev)) { perror("ctl sfd"); return 1; }
    ev.data.fd = tfd;
    if (epoll_ctl(ep, EPOLL_CTL_ADD, tfd, &ev)) { perror("ctl tfd"); return 1; }

    int pings = 0, ticks = 0;
    char buf[2048];
    while (pings < want_pings || ticks < want_ticks) {
        struct epoll_event evs[8];
        int n = epoll_wait(ep, evs, 8, -1);
        if (n < 0) { perror("epoll_wait"); return 1; }
        for (int i = 0; i < n; i++) {
            if (evs[i].data.fd == tfd) {
                uint64_t expir;
                if (read(tfd, &expir, 8) != 8) { perror("read tfd"); return 1; }
                ticks += (int)expir;
                printf("tick %d t=%ld\n", ticks, now_ns());
            } else {
                struct sockaddr_in src;
                socklen_t sl = sizeof src;
                ssize_t g = recvfrom(sfd, buf, sizeof buf, 0,
                                     (struct sockaddr *)&src, &sl);
                if (g < 0) { perror("recvfrom"); return 1; }
                sendto(sfd, buf, g, 0, (struct sockaddr *)&src, sl);
                pings++;
                printf("ping %d t=%ld\n", pings, now_ns());
            }
            fflush(stdout);
        }
    }
    printf("done pings=%d ticks=%d\n", pings, ticks);
    return 0;
}
