/* fork workout: a fork-based one-shot UDP server. The parent binds an
 * emulated UDP socket, forks; the child sends it a datagram (inheriting
 * nothing but the fd table) and exits with a distinctive code; the parent
 * receives in simulated time and reaps the child with wait4. Exercises
 * fork, fd-table inheritance, getpid/getppid virtualization, cross-process
 * emulated sockets, and wait-status plumbing (reference: src/test/clone +
 * fork tests). */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static long now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

int main(void) {
    int srv = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof addr);
    addr.sin_family = AF_INET;
    addr.sin_port = htons(9000);
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    if (bind(srv, (struct sockaddr *)&addr, sizeof addr)) {
        printf("bind failed\n");
        return 1;
    }
    printf("parent %d: bound t=%ldms\n", getpid() > 0, now_ms());

    pid_t child = fork();
    if (child < 0) {
        printf("fork failed\n");
        return 1;
    }
    if (child == 0) {
        /* child: note the inherited fd still works, then message parent */
        struct timespec d = {0, 30 * 1000 * 1000};
        nanosleep(&d, NULL);
        int c = socket(AF_INET, SOCK_DGRAM, 0);
        struct sockaddr_in dst;
        memset(&dst, 0, sizeof dst);
        dst.sin_family = AF_INET;
        dst.sin_port = htons(9000);
        dst.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        char msg[64];
        snprintf(msg, sizeof msg, "hello-from-child ppid_ok=%d",
                 getppid() != getpid());
        sendto(c, msg, strlen(msg), 0, (struct sockaddr *)&dst, sizeof dst);
        close(c);
        printf("child: sent t=%ldms\n", now_ms());
        return 7;
    }

    char buf[128];
    ssize_t n = recvfrom(srv, buf, sizeof buf - 1, 0, NULL, NULL);
    buf[n > 0 ? n : 0] = 0;
    printf("parent: got \"%s\" t=%ldms\n", buf, now_ms());

    int status = 0;
    pid_t got = wait4(-1, &status, 0, NULL);
    printf("parent: reaped match=%d exit=%d t=%ldms\n", got == child,
           WIFEXITED(status) ? WEXITSTATUS(status) : -1, now_ms());
    return 0;
}
