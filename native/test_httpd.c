/* Minimal deterministic HTTP/1.0 server for third-party-client tests:
 * serves `nbytes` of a repeating pattern to `nconns` connections, then
 * exits. The interesting binary in these tests is the CLIENT (unmodified
 * curl/wget from the distro); this side only has to speak enough HTTP.
 * (Reference analogue: examples/apps http servers used to prove real
 * applications run under the simulator.) */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char **argv) {
    int port = argc > 1 ? atoi(argv[1]) : 8080;
    long nbytes = argc > 2 ? atol(argv[2]) : 65536;
    int nconns = argc > 3 ? atoi(argv[3]) : 1;
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    struct sockaddr_in a = {0};
    a.sin_family = AF_INET;
    a.sin_port = htons(port);
    if (bind(fd, (struct sockaddr *)&a, sizeof a)) { perror("bind"); return 1; }
    if (listen(fd, 8)) { perror("listen"); return 1; }
    for (int c = 0; c < nconns; c++) {
        int s = accept(fd, NULL, NULL);
        if (s < 0) { perror("accept"); return 1; }
        char req[4096];
        ssize_t n = 0, got;
        /* read until blank line (HTTP request end) */
        while ((got = read(s, req + n, sizeof req - 1 - (size_t)n)) > 0) {
            n += got;
            req[n] = 0;
            if (strstr(req, "\r\n\r\n") || strstr(req, "\n\n"))
                break;
        }
        if (n <= 0) { fprintf(stderr, "empty request\n"); return 1; }
        char hdr[256];
        int hl = snprintf(hdr, sizeof hdr,
                          "HTTP/1.0 200 OK\r\n"
                          "Content-Type: application/octet-stream\r\n"
                          "Content-Length: %ld\r\n"
                          "Connection: close\r\n\r\n",
                          nbytes);
        if (write(s, hdr, (size_t)hl) != hl) { perror("write hdr"); return 1; }
        char block[4096];
        for (int i = 0; i < (int)sizeof block; i++)
            block[i] = (char)('A' + (i % 26));
        long left = nbytes;
        while (left > 0) {
            size_t w = left > (long)sizeof block ? sizeof block : (size_t)left;
            ssize_t wr = write(s, block, w);
            if (wr < 0) { perror("write body"); return 1; }
            left -= wr;
        }
        close(s);
        printf("served %ld bytes (conn %d)\n", nbytes, c);
        fflush(stdout);
    }
    printf("httpd done\n");
    return 0;
}
