/* pthread workout for the managed-thread plane: create/join, mutex-guarded
 * shared counter, condition-variable handoff, per-thread sleeps reading the
 * simulated clock. Prints a deterministic transcript (reference analogue:
 * src/test/threads + src/test/clone test binaries). */
#include <pthread.h>
#include <stdio.h>
#include <time.h>
#include <unistd.h>

#define NTHREADS 4

static pthread_mutex_t lock = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t cond = PTHREAD_COND_INITIALIZER;
static int counter = 0;
static int turn = 0;

static long now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

static void *worker(void *arg) {
    long id = (long)arg;
    struct timespec d = {0, (id + 1) * 10 * 1000 * 1000}; /* 10ms * (id+1) */
    nanosleep(&d, NULL);

    pthread_mutex_lock(&lock);
    counter += (int)id + 1;
    /* strict turn-taking through the condvar: deterministic order */
    while (turn != id)
        pthread_cond_wait(&cond, &lock);
    printf("worker %ld: counter=%d t=%ldms\n", id, counter, now_ms());
    fflush(stdout);
    turn++;
    pthread_cond_broadcast(&cond);
    pthread_mutex_unlock(&lock);
    return (void *)(id * 7);
}

int main(void) {
    pthread_t th[NTHREADS];
    printf("main: start t=%ldms\n", now_ms());
    for (long i = 0; i < NTHREADS; i++) {
        if (pthread_create(&th[i], NULL, worker, (void *)i)) {
            printf("pthread_create failed\n");
            return 1;
        }
    }
    long sum = 0;
    for (long i = 0; i < NTHREADS; i++) {
        void *ret;
        pthread_join(th[i], &ret);
        sum += (long)ret;
    }
    printf("main: joined counter=%d retsum=%ld t=%ldms\n", counter, sum,
           now_ms());
    return 0;
}
