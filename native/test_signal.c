/* Signal workout: SIGUSR1 handler + fork child kill()ing the parent
 * (nanosleep EINTR semantics), a 10ms-period ITIMER_REAL ticking SIGALRM
 * five times against pause(), and SIGTERM default-terminating a child.
 * (Reference: src/test/signal + src/test/itimer.) */
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/time.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static volatile int usr1 = 0, alrm = 0;

static long now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

static void on_usr1(int sig) { usr1 += (sig == SIGUSR1); }
static void on_alrm(int sig) { alrm += (sig == SIGALRM); }

int main(void) {
    struct sigaction sa;
    memset(&sa, 0, sizeof sa);
    sa.sa_handler = on_usr1;
    sigaction(SIGUSR1, &sa, NULL);

    /* child 1: signals the parent after 20ms, then loops until SIGTERM */
    pid_t c1 = fork();
    if (c1 == 0) {
        struct timespec d = {0, 20 * 1000 * 1000};
        nanosleep(&d, NULL);
        kill(getppid(), SIGUSR1);
        for (;;)
            pause();
    }

    /* the parent's long sleep is interrupted by the handler */
    struct timespec long_sleep = {5, 0};
    long rc = nanosleep(&long_sleep, NULL);
    printf("parent: usr1=%d sleep_interrupted=%d t=%ldms\n", usr1, rc != 0,
           now_ms());

    /* periodic itimer: 5 ticks of 10ms against pause() */
    memset(&sa, 0, sizeof sa);
    sa.sa_handler = on_alrm;
    sigaction(SIGALRM, &sa, NULL);
    struct itimerval itv;
    itv.it_interval.tv_sec = 0;
    itv.it_interval.tv_usec = 10 * 1000;
    itv.it_value = itv.it_interval;
    setitimer(ITIMER_REAL, &itv, NULL);
    while (alrm < 5)
        pause();
    memset(&itv, 0, sizeof itv);
    setitimer(ITIMER_REAL, &itv, NULL); /* disarm */
    printf("parent: alrm=%d t=%ldms\n", alrm, now_ms());

    /* SIGTERM's default action kills the pausing child */
    kill(c1, SIGTERM);
    int status = 0;
    pid_t got = wait4(c1, &status, 0, NULL);
    printf("parent: child_reaped=%d t=%ldms\n", got == c1, now_ms());
    return 0;
}
