# 4-node backbone ring (tor-minimal-scale example): cross-node traffic
# takes 10-20 ms edges; shortest-path routing composes multi-hop paths.
graph [
  directed 0
  node [ id 0 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 1 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 2 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  node [ id 3 host_bandwidth_up "1 Gbit" host_bandwidth_down "1 Gbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 2 target 2 latency "1 ms" ]
  edge [ source 3 target 3 latency "1 ms" ]
  edge [ source 0 target 1 latency "10 ms" ]
  edge [ source 1 target 2 latency "15 ms" ]
  edge [ source 2 target 3 latency "10 ms" ]
  edge [ source 3 target 0 latency "20 ms" ]
]
