#!/usr/bin/env python3
"""Runtime-observatory analyzer: wall-clock attribution verdict, compile
ledger, bridge-stall split, and realtime-factor trend from a run's
exported `runtime{}` block (shadow_tpu/obs/runtime.py).

Answers the questions the next perf PRs are judged against, as sentences
with numbers: *where does the wall clock go* (compile / dispatch /
host-python / snapshot / replay / export shares), *what would a
persistent or async compile cache save* (ROADMAP item 6 — the compile
ledger's total, split by trigger), *is the bridge the bottleneck*
(ROADMAP item 4 — the cosim per-window bridge share), and *is the
realtime factor trending up or down* (Rain's serving-level metric).
Reads the artifact, not the simulation, so report mode runs anywhere.

Usage:
  python tools/rt_report.py DATA_DIR_OR_SIM_STATS [--json]
  python tools/rt_report.py --check            # reconciliation gate (CI)

--check runs small sims in a worker subprocess and asserts the full
observer contract:
  - digests/events bit-identical with `observability.runtime` on vs off
    (modeled pressure-escalate run AND a hybrid cosim window run);
  - attribution reconciles: the WallLedger's attributed wall matches the
    driver's total wall within tolerance;
  - the compile ledger records exactly the programs the engine's
    (gear, capacity, budget) cache compiled, with pressure regrows
    carrying the pressure_regrow trigger;
  - the cosim run carries the bridge split (windows > 0, lanes sum to
    the window wall) and a populated syscall-batch histogram;
  - the live `rt=` heartbeat strict-parses through parse_shadow.
Exit codes: 0 ok (or environment-classified SKIP on this box's
documented jaxlib corruption signature — hbm_report/net_report posture),
2 violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# this box's documented jaxlib-0.4.37 corruption signatures (CHANGES.md
# env notes): ONE taxonomy + the shared --check subprocess scaffold in
# tools/corruption.py — stdlib-only, so a plain report run still
# imports no test infra or JAX
from tools.corruption import run_check_isolated  # noqa: E402

# rt-trend classification band: first-half vs second-half mean within
# +-10% reads as flat
TREND_BAND = 0.10


def load_runtime_block(path: str) -> tuple[dict, dict]:
    """(sim_stats, runtime block) from a data dir or sim-stats.json."""
    if os.path.isdir(path):
        path = os.path.join(path, "sim-stats.json")
    with open(path) as f:
        stats = json.load(f)
    rt = stats.get("runtime")
    if rt is None:
        raise SystemExit(
            f"rt_report: {path} carries no runtime{{}} block — run with "
            f"`observability.runtime: true`"
        )
    return stats, rt


def rt_trend(series: list[float]) -> tuple[str, float | None]:
    """('improving'|'degrading'|'flat'|'n/a', second/first ratio) over
    the chunk realtime-factor series."""
    if len(series) < 4:
        return "n/a", None
    half = len(series) // 2
    first = sum(series[:half]) / half
    second = sum(series[half:]) / (len(series) - half)
    if first <= 0:
        return "n/a", None
    ratio = second / first
    if ratio > 1 + TREND_BAND:
        return "improving", ratio
    if ratio < 1 - TREND_BAND:
        return "degrading", ratio
    return "flat", ratio


def print_report(stats: dict, rt: dict, file=sys.stdout):
    print("# runtime observatory report", file=file)
    spans = rt.get("spans_s") or {}
    shares = rt.get("shares") or {}
    if spans:
        print(f"\n## wall attribution ({rt.get('chunks', 0)} chunks, "
              f"{rt.get('attributed_wall_s', 0)} s attributed"
              + (f" of {rt['total_wall_s']} s total"
                 if rt.get("total_wall_s") else "") + ")",
              file=file)
        for name, sec in sorted(spans.items(), key=lambda kv: -kv[1]):
            share = shares.get(name, 0.0)
            print(f"  {name:<12} {sec:>10.3f} s  ({share * 100:5.1f}%)",
                  file=file)
        top = max(shares.items(), key=lambda kv: kv[1]) if shares else None
        if top:
            # the attribution verdict, stated as a sentence with a
            # number — the BASELINE-r6-style decomposition, mechanical
            print(f"  verdict: {top[0]} dominates the attributed wall "
                  f"({top[1] * 100:.1f}%)", file=file)
    comp = rt.get("compiles")
    if comp:
        print(
            f"\n## compile ledger ({comp.get('programs', 0)} programs, "
            f"{comp.get('cache_hits', 0)} cache hits)\n"
            f"  compile wall  {comp.get('compile_wall_s', 0)} s "
            f"(backend {comp.get('backend_compile_s', 0)} s, "
            f"lower {comp.get('lower_s', 0)} s)\n"
            f"  by trigger    {comp.get('by_trigger', {})}",
            file=file,
        )
        total = rt.get("total_wall_s") or rt.get("attributed_wall_s")
        cw = comp.get("compile_wall_s", 0)
        if total:
            share = cw / max(total, 1e-9)
            verdict = (
                "a persistent/async compile cache is the next lever "
                "(ROADMAP item 6)"
                if share > 0.25 else
                "compiles are not the bottleneck at this shape"
            )
            print(f"  compile share of total wall: {share * 100:.1f}% — "
                  f"{verdict}", file=file)
        for e in sorted(comp.get("entries", []),
                        key=lambda e: -(e.get("compile_s", 0)))[:5]:
            print(f"    {e['kind']}:{e['label']:<24} "
                  f"[{e['trigger']}] compile={e['compile_s']} s "
                  f"hits={e['hits']}", file=file)
    br = rt.get("bridge")
    if br:
        sh = br.get("shares") or {}
        bshare = br.get("bridge_share", 0.0)
        verdict = (
            "bridge-bound — the COREC lock-free ring rebuild "
            "(ROADMAP item 4) has its target"
            if bshare >= max(sh.get("cpu_plane", 0),
                             sh.get("device_plane", 0)) else
            "not bridge-bound at this shape"
        )
        batches = br.get("syscall_batches", {})
        print(
            f"\n## bridge split ({br.get('windows', 0)} windows)\n"
            f"  cpu_plane     {sh.get('cpu_plane', 0) * 100:5.1f}%\n"
            f"  device_plane  {sh.get('device_plane', 0) * 100:5.1f}%\n"
            f"  bridge        {bshare * 100:5.1f}%  — {verdict}\n"
            f"  syscall batches: {batches.get('batches', 0)} "
            f"({batches.get('entries', 0)} staged sends, "
            f"{batches.get('wall_s', 0)} s)",
            file=file,
        )
        edges = batches.get("hist_edges_s") or []
        counts = batches.get("hist_counts") or []
        if counts and sum(counts):
            print("  batch-latency histogram:", file=file)
            lo = 0.0
            for i, c in enumerate(counts):
                hi = edges[i] if i < len(edges) else float("inf")
                if c:
                    print(f"    ({lo * 1e3:g}, {hi * 1e3:g}] ms: {c}",
                          file=file)
                lo = hi
    rf = rt.get("realtime_factor")
    if rf:
        trend, ratio = rt_trend(rf.get("series") or [])
        print(
            f"\n## realtime factor (sim-s / wall-s)\n"
            f"  overall {rf.get('overall')}  p50 {rf.get('p50')}  "
            f"last {rf.get('last')}  "
            f"min {rf.get('min')}  max {rf.get('max')}\n"
            f"  trend: {trend}"
            + (f" (second-half/first-half = {ratio:.2f})"
               if ratio is not None else ""),
            file=file,
        )


# ---------------------------------------------------------------------------
# --check: the reconciliation gate
# ---------------------------------------------------------------------------


def _modeled_config(tmp: str, runtime: bool) -> dict:
    """Small pressure-escalate PHOLD: undersized capacity forces real
    regrows, so the compile-ledger exactness check sees the pressure
    cache actually compile rungs (bench config 9 in miniature)."""
    return {
        "general": {"stop_time": "3 s", "seed": 1, "data_directory": tmp,
                    "heartbeat_interval": "1 s"},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 8,
                         "rounds_per_chunk": 8},
        "observability": {"trace": True, "runtime": runtime},
        "pressure": {"policy": "escalate", "max_capacity": 64},
        "hosts": {"n": {"count": 16, "network_node_id": 0,
                  "processes": [{"model": "phold",
                                 "model_args": {"population": 6,
                                                "mean_delay": "100 ms"}}]}},
    }


def _hybrid_config(runtime: bool) -> dict:
    return {
        "general": {"stop_time": "2 s", "seed": 7,
                    "heartbeat_interval": "500 ms"},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "observability": {"runtime": runtime},
        "hosts": {
            "server": {"network_node_id": 0,
                       "processes": [{"path": "udp_echo_server",
                                      "args": ["port=9000"]}]},
            "client": {"network_node_id": 0,
                       "processes": [{"path": "udp_ping",
                                      "args": ["server=server",
                                               "port=9000", "count=3"]}]},
        },
    }


def run_check(tmp_dir: str) -> int:
    """The reconciliation gate (see module docstring). rc 0 ok, 2 bad."""
    import io

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation
    from shadow_tpu.sim import Simulation
    from tools.parse_shadow import parse_heartbeats

    failures: list[str] = []

    def ck(ok: bool, msg: str):
        if not ok:
            failures.append(msg)

    # ---- modeled leg: exactness + attribution + compile-ledger exactness
    log_on = io.StringIO()
    sim_on = Simulation(ConfigOptions.from_dict(
        _modeled_config(os.path.join(tmp_dir, "on"), True)), world=1)
    rep_on = sim_on.run(progress=False, log=log_on)
    sim_off = Simulation(ConfigOptions.from_dict(
        _modeled_config(os.path.join(tmp_dir, "off"), False)), world=1)
    rep_off = sim_off.run(progress=False, log=io.StringIO())

    ck(rep_on["determinism_digest"] == rep_off["determinism_digest"],
       f"digest changed with observatory on: "
       f"{rep_off['determinism_digest']} -> {rep_on['determinism_digest']}")
    ck(rep_on["events_processed"] == rep_off["events_processed"],
       "event count changed with observatory on")
    rt = rep_on.get("runtime")
    ck(rt is not None, "no runtime block in gated sim-stats")
    rt = rt or {}

    # attribution reconciles: per-chunk span sums equal chunk walls by
    # construction; the cross-check is their TOTAL against the driver's
    # wall (pre/post-loop setup is the only legitimate gap)
    share = rt.get("attributed_share")
    ck(share is not None and 0.85 <= share <= 1.01,
       f"attributed wall does not reconcile with the driver's total: "
       f"share={share}")
    ck(rt.get("chunks", 0) > 0, "no chunks attributed")
    rf = rt.get("realtime_factor") or {}
    ck(bool(rf.get("series")), "no realtime-factor series")

    # compile ledger == exactly the programs the engine's cache compiled
    eng = sim_on.engine
    expect = 1 + len(eng._gear_chunks) + len(eng._resized_chunks)
    comp = rt.get("compiles") or {}
    ck(comp.get("programs") == expect,
       f"compile ledger records {comp.get('programs')} programs, the "
       f"engine cache compiled {expect}")
    regrows = rep_on.get("pressure_regrows", 0)
    ck(regrows > 0, "check scenario produced no pressure regrows")
    by_trigger = comp.get("by_trigger") or {}
    ck(by_trigger.get("cold_start") == 1,
       f"expected exactly one cold_start entry, got {by_trigger}")
    ck(by_trigger.get("pressure_regrow") == len(eng._resized_chunks),
       f"pressure_regrow entries {by_trigger.get('pressure_regrow')} != "
       f"cached rungs {len(eng._resized_chunks)}")
    ck(comp.get("compile_wall_s", 0) > 0, "zero compile wall recorded")

    # live rt= heartbeat strict round-trip
    hb_path = os.path.join(tmp_dir, "hb.log")
    with open(hb_path, "w") as f:
        f.write(log_on.getvalue())
    hbs = parse_heartbeats(hb_path, strict=True)
    ck(any("rt" in h for h in hbs),
       f"no heartbeat carried a parseable rt= field ({len(hbs)} lines)")

    # ---- hybrid leg: bridge split present + exactness
    h_on = HybridSimulation(ConfigOptions.from_dict(_hybrid_config(True)))
    hrep_on = h_on.run(log=io.StringIO())
    h_off = HybridSimulation(ConfigOptions.from_dict(_hybrid_config(False)))
    hrep_off = h_off.run(log=io.StringIO())
    ck(hrep_on["determinism_digest"] == hrep_off["determinism_digest"],
       "hybrid digest changed with observatory on")
    ck(hrep_on["packets_delivered"] == hrep_off["packets_delivered"],
       "hybrid delivery count changed with observatory on")
    hrt = hrep_on.get("runtime") or {}
    br = hrt.get("bridge")
    ck(br is not None, "hybrid runtime block carries no bridge split")
    br = br or {}
    ck(br.get("windows", 0) > 0, "bridge split recorded zero windows")
    spans = br.get("spans_s") or {}
    ck(all(k in spans for k in ("cpu_plane", "device_plane", "bridge")),
       f"bridge split lanes incomplete: {sorted(spans)}")
    batches = br.get("syscall_batches") or {}
    ck(batches.get("batches", 0) > 0, "no syscall batches recorded")
    ck(sum(batches.get("hist_counts") or []) == batches.get("batches"),
       "syscall-batch histogram does not sum to the batch count")

    print(
        f"attributed share {share}, {comp.get('programs')} programs "
        f"({by_trigger}), regrows {regrows}, hybrid windows "
        f"{br.get('windows')}, bridge share {br.get('bridge_share')}"
    )
    if failures:
        for f_ in failures:
            print(f"CHECK FAILED: {f_}", file=sys.stderr)
        return 2
    print("rt_report --check ok")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("path", nargs="?",
                   help="data dir or sim-stats.json with a runtime block")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true",
                   help="attribution/ledger reconciliation gate (CI "
                   "stage); runs the compiled legs in a worker subprocess "
                   "and classifies the known corruption signature as SKIP")
    p.add_argument("--check-worker", action="store_true",
                   help=argparse.SUPPRESS)  # internal: the isolated leg
    args = p.parse_args(argv)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # this box's sitecustomize registers an axon TPU plugin and
        # overrides the env var; pin the backend back (soak.py idiom)
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.check_worker:
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            return run_check(tmp)

    if args.check:
        # the shared hbm_report/net_report posture (ONE scaffold,
        # tools/corruption.run_check_isolated): the compiled legs run
        # in a fresh subprocess; the documented corruption signature
        # (no verdict printed) classifies as SKIP rc 0, not a false
        # FAIL
        return run_check_isolated(
            [sys.executable, os.path.abspath(__file__), "--check-worker"],
            skip_what="an observatory verdict", cwd=_REPO,
        )

    if not args.path:
        p.error("a data dir / sim-stats.json path is required "
                "(or --check)")
    stats, rt = load_runtime_block(args.path)
    if args.json:
        print(json.dumps(rt, indent=2))
    else:
        print_report(stats, rt)
    return 0


if __name__ == "__main__":
    sys.exit(main())
