"""Knockout profile of the 10k-host PHOLD round: where do the 46 ms go?

Times the full chunk, then variants with the exchange merge stubbed out and
with shaping off, to attribute round cost. The round-1 claim 'sort = 85%'
came from operand-slimming experiments, not a measured knockout — the
microbenchmarks (tools/bench_merge_ops.py) time the 60k 3-key sort at ~40 us,
which cannot be 85% of a 46 ms round.
"""

import sys, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import time

import jax

from bench import bench_config
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation


def time_chunks(sim, n=4):
    state, params, engine = sim.state, sim.params, sim.engine
    state = engine.run_chunk(state, params)
    jax.block_until_ready(state)
    now0 = int(state.now)
    r0 = int(state.stats.rounds)
    t0 = time.perf_counter()
    for _ in range(n):
        state = engine.run_chunk(state, params)
        jax.block_until_ready(state)  # per-chunk: tunnel-safe timing
    dt = (time.perf_counter() - t0) / n
    sim_advanced = (int(state.now) - now0) / 1e9
    rounds = max(1, (int(state.stats.rounds) - r0) // n)
    print(f"  sim advanced {sim_advanced:.2f}s over {n} chunks "
          f"({sim_advanced / max(dt * n, 1e-9):.2f} sim-s/wall-s)")
    return dt, dt / rounds * 1e3, state


def build(mutate=None):
    d = bench_config(10_000, 100)
    if mutate:
        mutate(d)
    cfg = ConfigOptions.from_dict(d)
    return Simulation(cfg, world=1)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "base"

    if which == "nomerge":
        import shadow_tpu.ops.merge as m
        import shadow_tpu.core.engine as e

        def fake_merge(q, dst, t, order, kind, payload, valid, max_inserts,
                       shed_urgency=True):
            return q

        m.merge_flat_events = fake_merge
        e.merge_flat_events = fake_merge
        sim = build()
    elif which == "noshaping":
        # strip host bandwidths from the GML -> Simulation auto-elides the
        # whole shaping pipeline (provable no-op path)
        def strip_bw(d):
            d["network"]["graph"]["inline"] = (
                d["network"]["graph"]["inline"]
                .replace('host_bandwidth_down "1 Gbit"', "")
                .replace('host_bandwidth_up "1 Gbit"', "")
            )
        sim = build(strip_bw)
    elif which == "nocodel":
        sim = build(lambda d: d["experimental"].update({"use_codel": False}))
    elif which == "micro1":
        sim = build(lambda d: d["experimental"].update({"microstep_limit": 1}))
    elif which == "urgency":
        sim = build(lambda d: d["experimental"].update({"overflow_shed": "urgency"}))
    elif which == "cap8":
        sim = build(lambda d: d["experimental"].update({"event_queue_capacity": 8}))
    elif which == "chunk1":
        sim = build(lambda d: d["experimental"].update({"rounds_per_chunk": 1}))
    elif which == "chunk128":
        sim = build(lambda d: d["experimental"].update({"rounds_per_chunk": 128}))
    elif which == "sends2":
        sim = build(lambda d: d["experimental"].update({"sends_per_host_round": 2}))
    else:
        sim = build()

    dt, per_round, state = time_chunks(sim)
    print(f"{which}: chunk={dt*1e3:.1f} ms  per-round={per_round:.2f} ms "
          f"rounds={int(state.stats.rounds)} microsteps={int(state.stats.microsteps[0])} "
          f"events={int(jax.numpy.sum(state.stats.events))}")


if __name__ == "__main__":
    main()
