"""Microbench: flat vs two-level bucketed event queue on the microstep pair.

The measured unit is the engine's per-microstep queue work — one `pop_min`
(earliest event per host) followed by one `push_many` (reschedule) — run as a
K-deep `lax.fori_loop` inside a single jit so dispatch overhead is amortized
and XLA sees the same fusion opportunities the round loop gets. The flat
`EventQueue` formulation is compared against `BucketQueue` over a sweep of
block sizes B; both start from the SAME randomly-occupied slab, and the final
slabs are asserted bit-identical (the bench doubles as an equivalence check —
a fast bucketed variant that popped different events would be meaningless).

Defaults match the tgen_tcp_10k regime: H=10k hosts, C=64 slots. Sweep:

    python tools/bench_bucketq.py [--hosts 10000] [--cap 64] [--fill 12]
                                  [--steps 64] [--reps 5] [--blocks 8,16,32,64]
"""

import argparse
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import shadow_tpu  # noqa: F401  (enables x64)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from shadow_tpu.ops.events import (
    EVENT_PAYLOAD_WORDS,
    bucket_rebuild,
    make_queue,
    pack_order,
    pop_min,
    push_many,
    bq_pop_min,
    bq_push_many,
)
from shadow_tpu.simtime import TIME_MAX

DELTA_NS = 1_000_000  # reschedule delay: popped event returns at t + 1 ms


def seed_slab(h: int, c: int, fill: int, seed: int = 7):
    """A flat queue with `fill` live events per host at random slots/times
    (random slot positions matter: they spread load across blocks). Order
    keys are packed in numpy for the whole batch — per-event jax
    `pack_order` + `int()` forces a device sync per key (the same
    construction pathology seed_queue documents)."""
    from shadow_tpu.ops.events import _LOCAL_SHIFT, _SRC_SHIFT

    rng = np.random.default_rng(seed)
    t = np.full((h, c), TIME_MAX, np.int64)
    order = np.full((h, c), (1 << 63) - 1, np.int64)
    kind = np.zeros((h, c), np.int32)
    payload = np.zeros((h, c, EVENT_PAYLOAD_WORDS), np.int32)
    # one random slot permutation per host, first `fill` columns chosen
    slots = np.argsort(rng.random((h, c)), axis=1)[:, :fill]
    hh = np.arange(h)[:, None]
    t[hh, slots] = rng.integers(1, 1_000_000_000, (h, fill))
    order[hh, slots] = (
        (np.int64(1) << _LOCAL_SHIFT)
        | (hh.astype(np.int64) << _SRC_SHIFT)
        | np.arange(fill, dtype=np.int64)[None, :]
    )
    q = make_queue(h, c)
    return q._replace(
        t=jnp.asarray(t), order=jnp.asarray(order),
        kind=jnp.asarray(kind), payload=jnp.asarray(payload),
    )


def make_stepper(h: int, steps: int, pop, push):
    """K chained microstep pairs: pop the per-host min, push it back at
    t + DELTA (occupancy stays constant, times advance, order keys stay
    globally unique via the carried per-host seq counter)."""
    hosts = jnp.arange(h, dtype=jnp.int64)

    def body(_, carry):
        q, seq = carry
        q, ev, active = pop(q, TIME_MAX)
        order = jax.vmap(pack_order, in_axes=(None, 0, 0))(1, hosts, seq)
        q = push(q, [(active, ev.t + DELTA_NS, order, ev.kind, ev.payload)])
        return q, seq + active.astype(jnp.int64)

    def run(q, seq):
        return lax.fori_loop(0, steps, body, (q, seq))

    return jax.jit(run)


def timed(fn, q0, seq0, reps: int):
    out = fn(q0, seq0)  # compile + warm
    jax.block_until_ready(out)
    t0 = time.monotonic()
    for _ in range(reps):
        out = fn(q0, seq0)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / reps, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=10_000)
    ap.add_argument("--cap", type=int, default=64)
    ap.add_argument("--fill", type=int, default=12)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--blocks", default="8,16,32,64")
    args = ap.parse_args()
    h, c = args.hosts, args.cap
    blocks = [int(b) for b in args.blocks.split(",") if int(b) <= c]

    flat0 = seed_slab(h, c, args.fill)
    seq0 = jnp.full((h,), args.fill, jnp.int64)
    print(
        f"backend={jax.default_backend()} H={h} C={c} fill={args.fill} "
        f"steps={args.steps} reps={args.reps}"
    )

    flat_step = make_stepper(h, args.steps, pop_min, push_many)
    t_flat, (qf, _) = timed(flat_step, flat0, seq0, args.reps)
    per = t_flat / args.steps * 1e3
    print(f"flat      pop+push pair: {per:8.3f} ms/step  "
          f"({t_flat * 1e3:8.1f} ms / {args.steps} steps)")

    ref_t = np.asarray(qf.t)
    for b in blocks:
        if c % b:
            print(f"B={b:3d}: skipped (does not divide C={c})")
            continue
        bq0 = bucket_rebuild(flat0, b)
        bq_step = make_stepper(h, args.steps, bq_pop_min, bq_push_many)
        t_b, (qb, _) = timed(bq_step, bq0, seq0, args.reps)
        per_b = t_b / args.steps * 1e3
        same = bool(np.array_equal(np.asarray(qb.t), ref_t))
        print(
            f"bucket B={b:3d} (C/B={c // b:3d}): {per_b:8.3f} ms/step  "
            f"speedup x{t_flat / t_b:5.2f}  slab==flat: {same}"
        )
        if not same:
            raise SystemExit(f"B={b}: bucketed slab diverged from flat")


if __name__ == "__main__":
    main()
