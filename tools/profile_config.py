"""Profile one bench.py config on the real TPU and dump an xplane trace.

Usage: python tools/profile_config.py [config_n] [trace_dir] [--small]
Then:  python tools/parse_xplane.py <trace_dir>
"""

import sys
import time

sys.path.insert(0, ".")

import jax

from bench import baseline_config
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 6
    trace = sys.argv[2] if len(sys.argv) > 2 else "/tmp/cfg_trace"
    small = "--small" in sys.argv
    cfg_dict, metric, stop_s = baseline_config(n, small)
    cfg = ConfigOptions.from_dict(cfg_dict)
    sim = Simulation(cfg, world=1)
    state, params, engine = sim.state, sim.params, sim.engine
    t0 = time.monotonic()
    state = engine.run_chunk(state, params)  # compile + first chunk
    jax.block_until_ready(state)
    print(f"compile+first chunk: {time.monotonic() - t0:.1f}s", flush=True)
    # warm chunk timing (no profiler overhead)
    rounds0 = int(state.stats.rounds)
    sim0 = int(state.now)
    t0 = time.monotonic()
    state = engine.run_chunk(state, params)
    jax.block_until_ready(state)
    dt = time.monotonic() - t0
    dr = int(state.stats.rounds) - rounds0
    dsim = (int(state.now) - sim0) / 1e9
    print(
        f"warm chunk: {dt:.3f}s, {dr} rounds, {dt / max(dr, 1) * 1000:.2f} ms/round, "
        f"{dsim / dt:.2f} sim-s/wall-s",
        flush=True,
    )
    ms = int(jax.device_get(state.stats.microsteps).sum())
    print(f"microsteps so far: {ms} (~{ms / max(int(state.stats.rounds), 1):.1f}/round)")
    jax.profiler.start_trace(trace)
    state = engine.run_chunk(state, params)
    jax.block_until_ready(state)
    jax.profiler.stop_trace()
    print(f"trace written to {trace}")


if __name__ == "__main__":
    main()
