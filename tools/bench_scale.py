"""Weak-scaling sweep: PHOLD hosts-per-device climb on 1 vs 8 devices.

The million-host check this repo keeps cashing in pieces (ROADMAP item 1)
is a WEAK-scaling claim: hold hosts/device fixed, grow the mesh, and the
per-round cost must track per-shard work — not the global host count.
This driver sweeps hosts/device in {10k, 40k, 100k} x world in {1, 8
virtual CPU devices} and emits BENCH-schema rows (counters{} + network{}
+ hbm{} blocks, tools/bench_compare.py-diffable) so the climb is guarded
by the same trend tooling as the headline configs:

  - world-8 legs run `experimental.exchange: hierarchical` with
    `merge_gears: auto` — the two-tier exchange whose inter-shard wire
    bytes shrink with the merge gear (counters.exchange carries the
    ici_intra/ici_inter split; the flat-model comparison rides in
    `flat_alltoall_bytes`);
  - shapes are AUTO-tiered (config/options.resolve_shapes), so the
    100k x 8 = 800k-host leg crosses the >524k boundary where the engine
    clamps the effective rounds-per-chunk to the microstep valve
    (EngineConfig.effective_rounds_per_chunk — the documented rpc=64
    while-loop pathology fix); counters.rounds_per_chunk_configured /
    counters.rounds_per_chunk_effective record the clamp firing.

Each leg runs in a worker subprocess (virtual-device XLA flags are
per-process; the documented jaxlib heap corruption gets the usual
classify-then-SKIP posture, tools/corruption.py). A leg that sheds is
reported, not hidden: drop counters ride in every row.

Usage:
  python tools/bench_scale.py [--smoke] [-o OUT.json]
    --smoke   10k-hosts/device legs only, 1 sim-s — the TIER1_SCALE=1
              stage of tools/check_tier1.sh (exit 0 = both legs ran,
              rows parsed, and the world-8 row's two-tier counters
              reconciled against the cost model)
  python tools/bench_scale.py --worker HPD WORLD STOP   (internal)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

from tools.corruption import classify  # noqa: E402

SHAPES = (10_000, 40_000, 100_000)  # hosts per device
WORLDS = (1, 8)
STOP_S = {10_000: 4, 40_000: 2, 100_000: 1}  # sim horizon per shape
# generous per-leg walls: the big legs are compile-dominated on CPU
TIMEOUT_S = {10_000: 600, 40_000: 900, 100_000: 1500}


def leg_config(hosts: int, world: int, stop_s: int) -> dict:
    """One leg's ConfigOptions dict: bench.py's PHOLD point, shapes
    auto-tiered, observability measured-in (trace + network + memory,
    the same riders the BASELINE configs carry), hierarchical exchange +
    auto gears on the multi-device legs."""
    from bench import PHOLD_GML

    # short chunks so the gear controller gets enough accepted-chunk
    # observations to settle below the top gear inside the sweep horizon
    # (DOWN_LAG hysteresis) — the geared block shrink is the hierarchical
    # wire win the rows exist to track. At the >524k-host legs the engine
    # clamps the EFFECTIVE bound below this (the rpc valve; both numbers
    # ride in counters so the clamp firing is visible in the row). The
    # send budget is pinned with the deliberate safety margin real
    # configs carry (PHOLD's observed per-round high-water here is ~4):
    # the flat alltoall ships blocks sized to the BUDGET, while the
    # hierarchical path's blocks shrink to the gear the controller
    # settles on — the auto ladder {1, 3, 6, 12} gives it a rung with
    # headroom over the observed traffic.
    experimental: dict = {
        "merge_gears": "auto",
        "rounds_per_chunk": 16,
        "sends_per_host_round": 12,
    }
    if world > 1:
        experimental["exchange"] = "hierarchical"
    return {
        "general": {"stop_time": f"{stop_s} s", "seed": 1},
        "network": {"graph": {"type": "gml", "inline": PHOLD_GML}},
        "experimental": experimental,
        "observability": {"trace": True, "network": True, "memory": True},
        "hosts": {
            "node": {
                "count": hosts,
                "network_node_id": 0,
                "processes": [
                    {
                        "model": "phold",
                        "model_args": {
                            "population": 2,
                            "mean_delay": "200 ms",
                            "size_bytes": 64,
                        },
                    }
                ],
            }
        },
    }


def run_leg(hosts_per_device: int, world: int, stop_s: int) -> dict:
    """Worker body: build, run to stop_time, emit one BENCH-schema row."""
    if world > 1:
        from __graft_entry__ import _force_virtual_cpu_mesh

        _force_virtual_cpu_mesh(world)
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    num_hosts = hosts_per_device * world
    cfg = ConfigOptions.from_dict(
        leg_config(num_hosts, world, stop_s)
    )
    t_build = time.monotonic()
    sim = Simulation(cfg, world=world)
    build_s = time.monotonic() - t_build
    report = sim.run(progress=False)
    s = jax.device_get(sim.state.stats)
    ecfg = sim.engine_cfg
    wall = report.get("wall_seconds") or 1e-9
    row = {
        # leg shape baked into the metric name so bench_compare's
        # {metric: row} index keeps every leg distinct across rounds
        "metric": (
            f"phold_weak_scale_{hosts_per_device // 1000}k_x{world}"
            f"_sim_seconds_per_wall_second"
        ),
        "value": round(report["sim_wall_ratio"] or 0.0, 3),
        "unit": "sim_s/wall_s",
        "hosts_per_device": hosts_per_device,
        "world": world,
        "sim_seconds": report["simulated_seconds"],
        "events": report["events_processed"],
        "microsteps_per_round": round(
            report["microsteps"] / max(report["rounds"], 1), 2
        ),
        "build_s": round(build_s, 1),
        "wall_s": round(wall, 1),
        "counters": {
            "rounds": report["rounds"],
            "ici_bytes": report["ici_bytes"],
            "bq_rebuilds": report["bucket_cache_rebuilds"],
            "popk_deferred": report["popk_deferred"],
            "queue_occupancy_hwm": report["queue_occupancy_hwm"],
            "outbox_send_hwm": report["outbox_send_hwm"],
            # the rpc valve evidence: configured vs traced chunk bound —
            # they diverge exactly on the >524k-host legs
            "rounds_per_chunk_configured": ecfg.rounds_per_chunk,
            "rounds_per_chunk_effective": ecfg.effective_rounds_per_chunk,
            # shed accounting stays loud in the scaling rows (auto
            # shapes trade headroom for HBM at the big tiers)
            "queue_overflow_dropped": report["queue_overflow_dropped"],
            "packets_budget_dropped": report["packets_budget_dropped"],
            "outbox_overflow_dropped": report["outbox_overflow_dropped"],
            "alltoall_shed_dropped": report["alltoall_shed_dropped"],
            **(
                {"gears": report["gears"]} if "gears" in report else {}
            ),
            **(
                {"exchange": report["exchange"]}
                if "exchange" in report else {}
            ),
        },
        "determinism_digest": report["determinism_digest"],
    }
    if ecfg.hier_active:
        # the flat-alltoall comparison the inter tier is guarded against
        # (same shapes, full-width blocks) + the cost-model cross-check
        from shadow_tpu.core.engine import (
            exchange_ici_bytes_per_round, exchange_tier_bytes_per_round,
        )

        intra_m, inter_m = exchange_tier_bytes_per_round(ecfg)
        row["counters"]["exchange"]["flat_alltoall_bytes_per_round"] = (
            exchange_ici_bytes_per_round(ecfg, "alltoall")
        )
        row["counters"]["exchange"]["model_intra_bytes_per_round"] = intra_m
        row["counters"]["exchange"]["model_inter_bytes_per_round"] = inter_m
        assert row["counters"]["exchange"]["ici_inter_bytes"] == (
            report["ici_bytes"]
        ), "ici_bytes must carry exactly the inter tier"
    # network{} block: compacted from the SAME shared assembly sim-stats
    # used (bench._bench_network -> obs/netobs.bench_network_block)
    from bench import _bench_network

    row["network"] = _bench_network(
        sim, sim.state, s, getattr(sim, "_flowcol", None)
    )
    # hbm{} block: live per-shard sampling from the run's own monitor +
    # the static model subset the BASELINE rows carry
    from shadow_tpu.obs.memory import static_model

    memmon = getattr(sim, "_memmon", None)
    if memmon is not None:
        sm = static_model(ecfg, sim.state, sim.params)
        row["hbm"] = {
            **memmon.report(),
            "model": {
                k: v for k, v in sm.items()
                if k in ("components", "state_bytes", "params_bytes",
                         "total_bytes", "per_host_bytes")
            },
        }
    return row


def sweep(
    shapes=SHAPES, worlds=WORLDS, *, smoke: bool = False
) -> tuple[list[dict], int]:
    """Run every leg in a worker subprocess; returns (rows, rc)."""
    legs = [(h, w) for h in shapes for w in worlds]
    rows: list[dict] = []
    rc = 0
    for hpd, world in legs:
        stop_s = 1 if smoke else STOP_S[hpd]
        timeout = 300 if smoke else TIMEOUT_S[hpd]
        note = (
            f"weak-scaling leg {hpd} hosts/device x world {world}"
            + (" (hierarchical exchange + auto gears)" if world > 1 else "")
        )
        print(f"== {note} ==", file=sys.stderr)
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--worker", str(hpd), str(world), str(stop_s),
        ]
        timed_out = False
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, timeout=timeout,
                env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
            )
            out_rc, stdout, stderr = out.returncode, out.stdout, out.stderr
        except subprocess.TimeoutExpired as e:
            timed_out = True
            out_rc = None
            stdout = (e.stdout or b"").decode(errors="replace") if isinstance(
                e.stdout, bytes
            ) else (e.stdout or "")
            stderr = ""
        parsed = None
        for line in reversed((stdout or "").splitlines()):
            if line.startswith("BENCH_SCALE "):
                parsed = json.loads(line[len("BENCH_SCALE "):])
                break
        flavor = classify(out_rc, timed_out=timed_out, output=stdout)
        entry = {
            "hosts_per_device": hpd,
            "world": world,
            "note": note,
            "rc": out_rc,
            "parsed": parsed,
        }
        if parsed is None:
            if flavor is not None:
                # the documented corruption signatures are SKIPs, not
                # failures (docs/corruption.md posture) — but never when
                # a verdict line was produced
                entry["skipped"] = flavor
                print(f"  SKIP ({flavor})", file=sys.stderr)
            else:
                entry["tail"] = (stderr or stdout or "")[-2000:]
                rc = rc or 1
                print(f"  FAIL rc={out_rc}", file=sys.stderr)
        else:
            print(
                f"  ok: {parsed['value']} sim_s/wall_s, "
                f"{parsed['events']} events", file=sys.stderr,
            )
        rows.append(entry)
    return rows, rc


def check_rows(rows: list[dict]) -> int:
    """The --smoke gate: beyond "legs ran", assert the scaling row
    contracts — the world-8 hierarchical counters reconcile against the
    two-tier cost model, and the rpc valve columns are present."""
    rc = 0
    for entry in rows:
        row = entry.get("parsed")
        if row is None:
            continue
        c = row["counters"]
        if not (
            c["rounds_per_chunk_effective"] <= c["rounds_per_chunk_configured"]
        ):
            print(
                f"FAIL: effective rpc {c['rounds_per_chunk_effective']} > "
                f"configured {c['rounds_per_chunk_configured']}",
                file=sys.stderr,
            )
            rc = 1
        if "hbm" not in row or "network" not in row:
            print("FAIL: row missing hbm{}/network{} block", file=sys.stderr)
            rc = 1
        if entry["world"] > 1:
            ex = c.get("exchange")
            if not ex:
                print("FAIL: world>1 row missing exchange{}", file=sys.stderr)
                rc = 1
                continue
            if ex["ici_inter_bytes"] != c["ici_bytes"]:
                print(
                    f"FAIL: inter tier {ex['ici_inter_bytes']} != wire "
                    f"counter {c['ici_bytes']}", file=sys.stderr,
                )
                rc = 1
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker", nargs=3, metavar=("HPD", "WORLD", "STOP"))
    p.add_argument("--smoke", action="store_true")
    p.add_argument("-o", "--output")
    args = p.parse_args(argv)
    if args.worker:
        hpd, world, stop_s = (int(x) for x in args.worker)
        row = run_leg(hpd, world, stop_s)
        print("BENCH_SCALE " + json.dumps(row))
        return 0
    shapes = (10_000,) if args.smoke else SHAPES
    rows, rc = sweep(shapes, WORLDS, smoke=args.smoke)
    rc = rc or check_rows(rows)
    text = json.dumps(rows, indent=1)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    return rc


if __name__ == "__main__":
    sys.exit(main())
