"""shadowlint CLI: `python -m tools.lint [options]`.

Default run = stage A (AST rules, no JAX) + stage B (jaxpr audit).
`--ast-only` is the tier-1 pre-stage form: it never imports JAX, so the
known jaxlib heap corruption on some boxes cannot kill it.

Exit codes: 0 clean (suppressed findings allowed), 1 findings, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# allow `python tools/lint/__main__.py` as well as `python -m tools.lint`
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.astlint import Finding, Project, repo_root, run_stage_a  # noqa: E402
from tools.lint.schema import run_schema_rules  # noqa: E402

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load_baseline(path: str) -> list[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f).get("suppressions", [])
    except OSError:
        return []


def split_suppressed(
    findings: list[Finding], suppressions: list[dict]
) -> tuple[list[Finding], list[tuple[Finding, dict]]]:
    active, suppressed = [], []
    for f in findings:
        matched = None
        for s in suppressions:
            if s.get("rule") != f.rule or s.get("path") != f.path:
                continue
            if s.get("contains") and s["contains"] not in f.msg:
                continue
            matched = s
            break
        if matched is None:
            active.append(f)
        else:
            suppressed.append((f, matched))
    return active, suppressed


def check_suppression_policy(suppressions: list[dict]) -> list[str]:
    """Zero suppressions allowed in core/ and ops/ — fix, don't suppress."""
    errs = []
    for s in suppressions:
        p = s.get("path", "")
        if p.startswith("shadow_tpu/core/") or p.startswith("shadow_tpu/ops/"):
            errs.append(
                f"baseline.json suppresses {s.get('rule')} in {p} — the "
                f"engine core and kernels admit no suppressions (fix the "
                f"violation instead)"
            )
    return errs


def write_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "_comment": [
            "shadowlint suppression baseline: pre-existing violations",
            "burned down explicitly, never silently. Policy: EMPTY for",
            "shadow_tpu/core/ and shadow_tpu/ops/ — fix, don't suppress.",
        ],
        "suppressions": [
            {
                "rule": f.rule,
                "path": f.path,
                "contains": f.msg[:60],
                "reason": "TODO: justify or fix",
            }
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2)
        fh.write("\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint", description=__doc__
    )
    ap.add_argument("--root", default=None, help="repo root (default: auto)")
    ap.add_argument(
        "--ast-only", action="store_true",
        help="stage A only — never imports JAX (the tier-1 pre-stage form)",
    )
    ap.add_argument(
        "--jaxpr-only", action="store_true",
        help="stage B only (jaxpr audit; imports JAX, traces on CPU)",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite baseline.json from the current stage-A findings",
    )
    ap.add_argument(
        "--update-fingerprint", action="store_true",
        help="record the jaxpr primitive fingerprint for this jax version",
    )
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    if args.ast_only and args.jaxpr_only:
        ap.error("--ast-only and --jaxpr-only are mutually exclusive")

    root = args.root or repo_root()
    t0 = time.monotonic()
    rc = 0

    if not args.jaxpr_only:
        project = Project(root)
        findings = run_stage_a(root, project=project)
        findings += run_schema_rules(root, project=project)
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.msg))
        if args.update_baseline:
            write_baseline(BASELINE_FILE, findings)
            print(f"baseline.json rewritten with {len(findings)} suppressions")
            findings = []
        suppressions = load_baseline(BASELINE_FILE)
        active, suppressed = split_suppressed(findings, suppressions)
        policy_errs = check_suppression_policy(suppressions)
        for f in active:
            print(f)
        for err in policy_errs:
            print(f"POLICY {err}")
        if not args.quiet:
            n_mod = len(project.modules)
            print(
                f"shadowlint stage A: {n_mod} modules, "
                f"{len(active)} finding(s), {len(suppressed)} suppressed "
                f"({time.monotonic() - t0:.1f}s)"
            )
        if active or policy_errs:
            rc = 1

    if not args.ast_only:
        t1 = time.monotonic()
        from tools.lint.jaxpr_audit import run_audit  # deferred: imports JAX

        audit_findings, report = run_audit(
            root, update=args.update_fingerprint
        )
        for f in audit_findings:
            print(f)
        if not args.quiet:
            for name, r in report.items():
                print(
                    f"shadowlint stage B [{name}]: {r['eqns']} eqns, "
                    f"{r['int64_downcasts']} interior i64->i32 casts, "
                    f"{r['float_scatter_adds']} float scatter-adds, "
                    f"fingerprint {r['fingerprint_status']}"
                )
            print(
                f"shadowlint stage B: {len(audit_findings)} finding(s) "
                f"({time.monotonic() - t1:.1f}s)"
            )
        if audit_findings:
            rc = 1

    return rc


if __name__ == "__main__":
    sys.exit(main())
