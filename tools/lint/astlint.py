"""shadowlint stage A: AST-level rule packs (no JAX import, ever).

The analyzer parses every module under `shadow_tpu/` (plus `tools/`),
builds the call graph reachable from the jitted entry points, and runs
the function-scope rules (R1 purity, R2 lane widths, R4 static-arg
hygiene) over the reachable set. Schema-level rules (R3, R5) live in
tools/lint/schema.py.

Resolution is heuristic but deterministic: direct calls resolve through
the module's own defs and its import table; `self.foo()` resolves within
the class; bare function references (callbacks, functools.partial
arguments) count as edges too, so wrapping a traced function never hides
it. Dynamic dispatch (`model.handle`) is out of reach of stage A — the
jaxpr audit covers what actually gets traced.

The lane registry (shadow_tpu/core/lanes.py) is loaded BY FILE PATH, not
imported as a package: `import shadow_tpu` pulls in jax from its
__init__, and stage A must run on a box whose jaxlib is corrupted.
"""

from __future__ import annotations

import ast
import dataclasses
import importlib.util
import os
import sys

# modules whose use inside jit-reachable code is a purity violation.
# `os`/`sys`/file handles are host I/O; `time`/`datetime` are wall-clock
# reads (the reference's determinism gate exists precisely because sim
# code must never see the host clock); `random`/`numpy.random` are
# stateful RNGs (the engine's RNG is counter-based and carried in
# SimState — ops/rng.py).
BANNED_MODULES = frozenset({
    "time", "random", "datetime", "os", "sys", "io", "pathlib", "shutil",
    "subprocess", "tempfile", "socket", "threading", "multiprocessing",
    "logging",
})
BANNED_DOTTED_PREFIXES = ("numpy.random",)

# the determinism subset: modules that break replay-determinism anywhere
# in the engine's decision path, host-side control planes included
DETERMINISM_MODULES = frozenset({"time", "random", "datetime", "secrets", "uuid"})
BANNED_BUILTINS = frozenset({
    "open", "input", "print", "exec", "eval", "breakpoint", "globals",
})

# dtype widths sourced from the lane registry at load time
NARROWING_METHODS = frozenset({"astype"})
CONSTRUCTORS = {
    # callable name -> index of the dtype positional argument. The *_like
    # family is deliberately absent: it inherits the source array's dtype,
    # which is exactly the registry-preserving behavior.
    "zeros": 1, "ones": 1, "empty": 1, "full": 2,
    "asarray": 1, "array": 1,
}

# hashable static types allowed for EngineConfig fields (R4)
HASHABLE_ANNOTATIONS = frozenset({"int", "bool", "str", "float", "bytes"})


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_lanes(root: str):
    """Load shadow_tpu/core/lanes.py WITHOUT importing shadow_tpu (whose
    __init__ imports jax)."""
    path = os.path.join(root, "shadow_tpu", "core", "lanes.py")
    if not os.path.exists(path):
        # fixture trees (tests) lint against the real registry
        path = os.path.join(repo_root(), "shadow_tpu", "core", "lanes.py")
    spec = importlib.util.spec_from_file_location("_shadowlint_lanes", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "R1".."R5"
    path: str  # repo-relative, forward slashes
    line: int
    msg: str

    def __str__(self) -> str:
        return f"{self.rule} {self.path}:{self.line} {self.msg}"


# --------------------------------------------------------------------------
# module / function index
# --------------------------------------------------------------------------


class ModuleInfo:
    def __init__(self, name: str, path: str, tree: ast.Module):
        self.name = name  # dotted, e.g. "shadow_tpu.core.engine"
        self.path = path  # repo-relative
        self.tree = tree
        self.imports: dict[str, str] = {}  # local alias -> dotted module
        self.from_imports: dict[str, tuple[str, str]] = {}  # local -> (mod, orig)
        self.functions: dict[str, ast.AST] = {}  # qualname -> FunctionDef
        self._index()

    def _index(self):
        for node in self.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._add_import(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.functions[f"{node.name}.{sub.name}"] = sub

    def _add_import(self, node):
        if isinstance(node, ast.Import):
            for a in node.names:
                self.imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        else:
            if node.module is None or node.level:
                return  # relative imports unused in this tree
            for a in node.names:
                if a.name == "*":
                    continue
                self.from_imports[a.asname or a.name] = (node.module, a.name)

    def resolve_local(self, name: str):
        """local name -> ("func", module, qualname) | ("module", dotted) | None"""
        if name in self.functions:
            return ("func", self.name, name)
        if name in self.from_imports:
            mod, orig = self.from_imports[name]
            return ("maybe_func", mod, orig)
        if name in self.imports:
            return ("module", self.imports[name])
        return None


class Project:
    """Parsed view of the repo for stage A."""

    def __init__(self, root: str, extra_dirs: tuple[str, ...] = ("tools",)):
        self.root = root
        self.lanes = load_lanes(root)
        self.modules: dict[str, ModuleInfo] = {}
        self.syntax_errors: list = []
        self._scan_dir("shadow_tpu")
        for d in extra_dirs:
            self._scan_dir(d)

    def _scan_dir(self, rel: str):
        base = os.path.join(self.root, rel)
        if not os.path.isdir(base):
            return
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                relpath = os.path.relpath(full, self.root).replace(os.sep, "/")
                dotted = relpath[:-3].replace("/", ".")
                if dotted.endswith(".__init__"):
                    dotted = dotted[: -len(".__init__")]
                try:
                    with open(full, encoding="utf-8") as f:
                        tree = ast.parse(f.read(), filename=relpath)
                except SyntaxError as e:
                    # surfaced as a finding by run_stage_a
                    tree = ast.Module(body=[], type_ignores=[])
                    self.syntax_errors.append((relpath, e))
                self.modules[dotted] = ModuleInfo(dotted, relpath, tree)

    # ---- call graph -------------------------------------------------------

    def resolve_call(self, mod: ModuleInfo, qual: str, node: ast.AST):
        """Resolve a call/reference AST node to a function key
        "module:qualname", or None."""
        if isinstance(node, ast.Name):
            r = mod.resolve_local(node.id)
            if r and r[0] == "func":
                return f"{r[1]}:{r[2]}"
            if r and r[0] == "maybe_func":
                return self._follow_reexports(r[1], r[2])
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and "." in qual:
                    cls = qual.split(".")[0]
                    key = f"{cls}.{node.attr}"
                    if key in mod.functions:
                        return f"{mod.name}:{key}"
                    return None
                r = mod.resolve_local(base.id)
                if r and r[0] == "module":
                    return self._follow_reexports(r[1], node.attr)
        return None

    def _follow_reexports(self, mod_name: str, fname: str, depth: int = 4):
        """Resolve `fname` in `mod_name`, chasing `from x import y` re-export
        chains (package __init__ facades like shadow_tpu.net)."""
        while depth > 0:
            target = self.modules.get(mod_name)
            if target is None:
                return None
            if fname in target.functions:
                return f"{target.name}:{fname}"
            if fname in target.from_imports:
                mod_name, fname = target.from_imports[fname]
                depth -= 1
                continue
            return None
        return None

    def edges_of(self, key: str) -> set[str]:
        mod_name, qual = key.split(":", 1)
        mod = self.modules[mod_name]
        fn = mod.functions[qual]
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                tgt = self.resolve_call(mod, qual, node.func)
                if tgt:
                    out.add(tgt)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                # bare references: callbacks, functools.partial args — a
                # traced function passed by value is still traced
                tgt = self.resolve_call(mod, qual, node)
                if tgt:
                    out.add(tgt)
        return out

    def reachable(self, entries: list[str]) -> list[str]:
        seen: list[str] = []
        seen_set: set[str] = set()
        stack = [e for e in entries if self._exists(e)]
        while stack:
            key = stack.pop()
            if key in seen_set:
                continue
            seen_set.add(key)
            seen.append(key)
            for nxt in sorted(self.edges_of(key)):
                if nxt not in seen_set:
                    stack.append(nxt)
        return seen

    def _exists(self, key: str) -> bool:
        mod_name, qual = key.split(":", 1)
        m = self.modules.get(mod_name)
        return bool(m and qual in m.functions)

    def expand_entries(self, specs: list[str]) -> list[str]:
        """Entry specs: "module:name" or "module:*" (every function and
        method defined in the module)."""
        out: list[str] = []
        for spec in specs:
            mod_name, qual = spec.split(":", 1)
            m = self.modules.get(mod_name)
            if m is None:
                continue
            if qual == "*":
                out.extend(f"{mod_name}:{q}" for q in sorted(m.functions))
            else:
                out.append(spec)
        return out


# The jitted entry points (ISSUE 7): the chunk bodies the drivers jit
# (vmapped by the ensemble plane — its traced body IS engine._run_chunk),
# the fault plane's jit-side helpers, and every ops kernel. Host-side
# builders (Engine.init_state, compile_faults, seed_queue) are
# deliberately NOT traced entries: they run in Python, where file I/O and
# env reads are legitimate.
DEFAULT_TRACED_ENTRIES = [
    "shadow_tpu.core.engine:_run_chunk",
    "shadow_tpu.core.engine:_run_guarded_chunk",
    "shadow_tpu.core.engine:_round_step_capture",
    "shadow_tpu.core.faults:down_and_resume",
    "shadow_tpu.core.faults:window_effects",
    "shadow_tpu.ops.events:*",
    "shadow_tpu.ops.merge:*",
    "shadow_tpu.ops.rng:*",
]

# The gear/ensemble control planes run host-side between dispatches, but
# their decisions feed the deterministic replay machinery, so wall-clock
# and RNG reads are just as banned (the DETERMINISM subset of R1). Host
# I/O (progress prints to an explicit log) is legitimate there, and R4's
# traced-value checks do not apply — a host driver reading
# `int(state.stats.rounds)` off a concrete array is fine.
DEFAULT_CONTROL_ENTRIES = [
    "shadow_tpu.core.gears:*",
    "shadow_tpu.core.ensemble:*",
]

DEFAULT_ENTRIES = DEFAULT_TRACED_ENTRIES + DEFAULT_CONTROL_ENTRIES

# R2/R4 file scope: the engine core and kernels (plus the tracer module,
# which owns the `cursor` lane). Models and drivers construct lanes only
# through engine/ops entry points, which coerce dtypes explicitly.
LANE_SCOPE_PREFIXES = ("shadow_tpu/core/", "shadow_tpu/ops/", "shadow_tpu/obs/tracer.py")

# tools determinism hygiene (R1, tools scope): stdlib `random` is banned
# in tools/ — every bench/soak draw goes through a seeded
# np.random.default_rng so reruns are reproducible from the CLI seed.
TOOLS_BANNED_IMPORTS = frozenset({"random"})


# --------------------------------------------------------------------------
# R1: jit purity
# --------------------------------------------------------------------------


def _function_local_imports(fn: ast.AST):
    imports: dict[str, str] = {}
    from_imports: dict[str, tuple[str, str]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
            for a in node.names:
                if a.name != "*":
                    from_imports[a.asname or a.name] = (node.module, a.name)
    return imports, from_imports


def _resolve_base_module(name: str, mod: ModuleInfo, local_imports, local_from):
    if name in local_imports:
        return local_imports[name]
    if name in local_from:
        m, orig = local_from[name]
        return f"{m}.{orig}"
    if name in mod.imports:
        return mod.imports[name]
    if name in mod.from_imports:
        m, orig = mod.from_imports[name]
        return f"{m}.{orig}"
    return None


def check_purity(
    project: Project, key: str, io_bans: bool = True
) -> list[Finding]:
    """R1 over one reachable function. `io_bans=False` is the control-plane
    tier (host drivers between dispatches): determinism bans (clock, RNG,
    global mutation) stay, host I/O is allowed."""
    mod_name, qual = key.split(":", 1)
    mod = project.modules[mod_name]
    fn = mod.functions[qual]
    local_imports, local_from = _function_local_imports(fn)
    out: list[Finding] = []
    banned_mods = BANNED_MODULES if io_bans else DETERMINISM_MODULES
    where = "jit-reachable" if io_bans else "replay-deterministic"

    def hit(node, what):
        out.append(Finding(
            "R1", mod.path, node.lineno,
            f"{what} inside {where} `{qual}` — traced code must be "
            f"pure in (state, params)",
        ))

    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
            dotted = _resolve_base_module(
                node.value.id, mod, local_imports, local_from
            )
            if dotted is None:
                continue
            root = dotted.split(".")[0]
            full = f"{dotted}.{node.attr}"
            if root in banned_mods:
                hit(node, f"use of banned module `{dotted}` ({full})")
            elif any(
                full.startswith(p) or dotted.startswith(p)
                for p in BANNED_DOTTED_PREFIXES
            ):
                hit(node, f"use of `{full}` (stateful host RNG)")
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            name = node.func.id
            if io_bans and name in BANNED_BUILTINS and not any(
                name in d for d in (local_imports, local_from,
                                    mod.imports, mod.from_imports)
            ):
                hit(node, f"call to builtin `{name}` (host I/O / global state)")
        elif isinstance(node, ast.Global):
            hit(node, f"`global {', '.join(node.names)}` (global-state mutation)")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] in banned_mods:
                    hit(node, f"function-local `import {a.name}`")
    return out


def check_tools_determinism(project: Project) -> list[Finding]:
    """stdlib `random` in tools/: flagged so every tool draw runs through a
    seeded np.random.default_rng (reproducible from the CLI seed)."""
    out = []
    for mod in project.modules.values():
        if not mod.path.startswith("tools/"):
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] in TOOLS_BANNED_IMPORTS:
                        out.append(Finding(
                            "R1", mod.path, node.lineno,
                            f"stdlib `import {a.name}` in a tool — use a "
                            f"seeded np.random.default_rng so runs are "
                            f"reproducible from the seed argument",
                        ))
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module.split(".")[0] in TOOLS_BANNED_IMPORTS:
                    out.append(Finding(
                        "R1", mod.path, node.lineno,
                        f"stdlib `from {node.module} import ...` in a tool — "
                        f"use a seeded np.random.default_rng",
                    ))
    return out


# --------------------------------------------------------------------------
# R2: lane widths
# --------------------------------------------------------------------------


def _dtype_of_node(node, bits: dict[str, int]) -> str | None:
    """`jnp.int32` / `np.int64` / `"int32"` -> dtype string, else None."""
    if isinstance(node, ast.Attribute):
        return node.attr if node.attr in bits else None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in bits else None
    return None


def _terminal_lane(node, func_return_lanes) -> str | None:
    """Best-effort terminal lane name of an expression: `ev.t` -> "t",
    `ring.cursor[0] % n` -> "cursor", `q_next_time(q)` -> "t"."""
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.BinOp):
            node = node.left
        elif isinstance(node, ast.UnaryOp):
            node = node.operand
        else:
            break
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", None)
        return func_return_lanes.get(fname)
    return None


def _constructor_dtype(call: ast.Call, bits: dict[str, int]) -> tuple[bool, str | None]:
    """(is_constructor, dtype string or None) for jnp/np array builders."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False, None
    name = f.attr
    if name not in CONSTRUCTORS:
        return False, None
    base = f.value
    if not (isinstance(base, ast.Name) and base.id in (
        "jnp", "np", "numpy", "jax"
    )):
        return False, None
    for kw in call.keywords:
        if kw.arg == "dtype":
            return True, _dtype_of_node(kw.value, bits)
    idx = CONSTRUCTORS[name]
    if len(call.args) > idx:
        return True, _dtype_of_node(call.args[idx], bits)
    return True, None


def check_lane_widths(project: Project, mod: ModuleInfo) -> list[Finding]:
    lanes = project.lanes
    widths = lanes.LANE_WIDTHS
    bits = lanes.BITS
    lane_bits = lanes.lane_width_bits
    ret_lanes = lanes.FUNC_RETURN_LANES
    out: list[Finding] = []

    def check_construction(lane: str, call: ast.Call, line: int):
        want = widths.get(lane)
        if want is None:
            return
        is_ctor, dt = _constructor_dtype(call, bits)
        if not is_ctor:
            return
        if dt is None:
            out.append(Finding(
                "R2", mod.path, line,
                f"lane `{lane}` constructed without an explicit dtype "
                f"(registry requires {want}; implicit widths are "
                f"platform-dependent) — shadow_tpu/core/lanes.py",
            ))
        elif bits.get(dt, 64) < bits[want] or (
            want in ("int64", "uint64") and dt.startswith("float")
        ):
            out.append(Finding(
                "R2", mod.path, line,
                f"lane `{lane}` constructed as {dt}, registry requires "
                f"{want} — shadow_tpu/core/lanes.py",
            ))

    for node in ast.walk(mod.tree):
        # narrowing: <lane-expr>.astype(<narrower>)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in NARROWING_METHODS
            and node.args
        ):
            dt = _dtype_of_node(node.args[0], bits)
            if dt is None:
                continue
            lane = _terminal_lane(node.func.value, ret_lanes)
            lb = lane_bits(lane) if lane else None
            if lb and bits.get(dt, 64) < lb:
                out.append(Finding(
                    "R2", mod.path, node.lineno,
                    f"`{lane}.astype({dt})` narrows a registered "
                    f"{widths[lane]} lane — shadow_tpu/core/lanes.py is "
                    f"the only place lane widths change",
                ))
        # construction via keyword: Queue(t=jnp.asarray(...), ...)
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and isinstance(kw.value, ast.Call):
                    check_construction(kw.arg, kw.value, kw.value.lineno)
                elif (
                    kw.arg
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, int)
                    and not isinstance(kw.value.value, bool)
                    and lane_bits(kw.arg) == 64
                ):
                    out.append(Finding(
                        "R2", mod.path, node.lineno,
                        f"bare int literal for 64-bit lane `{kw.arg}` — "
                        f"wrap with an explicit i64 (jnp.int64/np.int64) "
                        f"so the width never floats with the platform",
                    ))
        # construction via assignment: t = jnp.asarray(...); a, b = c(), d()
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt, val = node.targets[0], node.value
            pairs = []
            if isinstance(tgt, ast.Name):
                pairs.append((tgt.id, val))
            elif (
                isinstance(tgt, ast.Tuple)
                and isinstance(val, ast.Tuple)
                and len(tgt.elts) == len(val.elts)
            ):
                for t_el, v_el in zip(tgt.elts, val.elts):
                    if isinstance(t_el, ast.Name):
                        pairs.append((t_el.id, v_el))
            for name, v in pairs:
                if isinstance(v, ast.Call):
                    check_construction(name, v, v.lineno)
    return out


# --------------------------------------------------------------------------
# R4: static-arg hygiene
# --------------------------------------------------------------------------


def check_static_config(project: Project) -> list[Finding]:
    """EngineConfig fields must be hashable scalars (they are jit statics:
    an unhashable field breaks the jit cache; a mutable one makes two
    configs compare equal while tracing differently)."""
    out: list[Finding] = []
    mod = project.modules.get("shadow_tpu.core.engine")
    if mod is None:
        return out
    cls = None
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "EngineConfig":
            cls = node
            break
    if cls is None:
        return [Finding("R4", mod.path, 1, "EngineConfig class not found")]
    for node in cls.body:
        if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            ann = node.annotation
            name = ann.id if isinstance(ann, ast.Name) else (
                ann.value if isinstance(ann, ast.Constant) else None
            )
            if name not in HASHABLE_ANNOTATIONS:
                out.append(Finding(
                    "R4", mod.path, node.lineno,
                    f"EngineConfig.{node.target.id}: static field annotated "
                    f"`{ast.dump(ann) if name is None else name}` — statics "
                    f"must be hashable scalars (int/bool/str/float)",
                ))
    return out


def check_static_derivation(project: Project, key: str) -> list[Finding]:
    """Inside jit-reachable code: no `.item()` and no int()/float() over a
    registered lane — both materialize a traced value into a Python
    scalar, which either fails tracing or (worse) bakes one concrete
    value into the compiled program."""
    mod_name, qual = key.split(":", 1)
    mod = project.modules[mod_name]
    fn = mod.functions[qual]
    lanes = project.lanes
    out: list[Finding] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            out.append(Finding(
                "R4", mod.path, node.lineno,
                f"`.item()` inside jit-reachable `{qual}` — traced values "
                f"cannot become Python scalars",
            ))
        elif (
            isinstance(node.func, ast.Name)
            and node.func.id in ("int", "float", "bool")
            and node.args
        ):
            for sub in ast.walk(node.args[0]):
                term = None
                if isinstance(sub, ast.Attribute):
                    term = sub.attr
                elif isinstance(sub, ast.Name):
                    term = sub.id
                if term and lanes.LANE_WIDTHS.get(term) in ("int64", "uint64"):
                    out.append(Finding(
                        "R4", mod.path, node.lineno,
                        f"`{node.func.id}(...{term}...)` inside "
                        f"jit-reachable `{qual}` — deriving a static from "
                        f"a traced lane bakes one concrete value into the "
                        f"program",
                    ))
                    break
    return out


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------


def run_stage_a(
    root: str | None = None,
    entries: list[str] | None = None,
    traced_entries: list[str] | None = None,
    project: Project | None = None,
) -> list[Finding]:
    """Run the function-scope rule packs (R1, R2, R4). Schema rules (R3,
    R5) are in tools/lint/schema.py; `python -m tools.lint` runs both."""
    root = root or repo_root()
    project = project or Project(root)
    findings: list[Finding] = []
    for path, err in project.syntax_errors:
        findings.append(Finding("R1", path, err.lineno or 1, f"syntax error: {err.msg}"))
    project.syntax_errors = []

    reached = project.reachable(project.expand_entries(
        entries if entries is not None else DEFAULT_ENTRIES
    ))
    if traced_entries is None:
        traced_entries = entries if entries is not None else DEFAULT_TRACED_ENTRIES
    traced = set(project.reachable(project.expand_entries(traced_entries)))
    for key in reached:
        findings.extend(check_purity(project, key, io_bans=key in traced))
        if key in traced:
            findings.extend(check_static_derivation(project, key))

    for mod in project.modules.values():
        if any(mod.path.startswith(p) for p in LANE_SCOPE_PREFIXES):
            findings.extend(check_lane_widths(project, mod))

    findings.extend(check_static_config(project))
    findings.extend(check_tools_determinism(project))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.msg))


if __name__ == "__main__":  # pragma: no cover - debugging aid
    for f in run_stage_a():
        print(f)
    sys.exit(0)
