"""shadowlint stage B: jaxpr-level audit of the jitted round body.

Stage A sees source; this stage sees what JAX will actually compile. For
small echo and phold configs on the CPU backend it traces
`core/engine._run_chunk` (tracing only — nothing is compiled or
executed, so the known jaxlib heap corruption in compiled runs cannot
reach this stage) and asserts:

  1. LANE WIDTHS — the traced carry's output dtypes match the registry
     (shadow_tpu/core/lanes.py STATE_LANES), via jax.eval_shape on the
     real SimState. This is the check ROADMAP item 1's "memory diet"
     will deliberately edit: narrowing a lane means changing lanes.py
     and this assertion follows; an accidental `astype` somewhere in the
     round body fails here even if stage A's heuristics missed it.

  2. CARRY DOWN-CASTS — no `convert_element_type` whose INPUT is one of
     the chunk function's top-level carry lanes registered 64-bit and
     whose output is a narrower integer. Interior casts (e.g. widening a
     bool sum, narrowing a bounded index) are legal and only counted.

  3. FLOAT SCATTER-ADD — scatter-adds with floating dtype are counted
     and pinned; digest-feeding lanes are integer by construction, and
     a float scatter-add appearing where none existed means a reduction
     moved off the deterministic integer path.

  4. PRIMITIVE FINGERPRINT — the multiset of jaxpr primitives (and eqn
     total) per config is recorded in tools/lint/jaxpr_baseline.json,
     keyed by jax version. A mismatch is a compile-surface change:
     deliberate ones re-record with --update-fingerprint; accidental
     ones (a new cond materializing slabs, shape churn forcing
     recompiles) get caught at lint time instead of in a BENCH
     regression.
"""

from __future__ import annotations

import functools
import json
import os
import sys

FINGERPRINT_FILE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "jaxpr_baseline.json"
)

# small, fast-to-trace configs covering the two model classes the digest
# gates lean on: echo (integer-only, packet path) and phold (float
# exponential draws, timer path). Kept tiny — tracing cost only.
AUDIT_CONFIGS = {
    "echo": dict(
        model="udp_echo",
        hosts=[
            dict(host_id=0, name="server", start_time=0,
                 model_args={"role": "server"}),
            dict(host_id=1, name="c1", start_time=0,
                 model_args={"role": "client", "peer": "server",
                             "interval": "100 ms"}),
        ],
        stop=200_000_000,
        kw=dict(qcap=16, trace_rounds=8),
    ),
    "phold": dict(
        model="phold",
        hosts=None,  # mk_hosts(4) below
        stop=200_000_000,
        kw=dict(qcap=16),
    ),
    # network observatory ON (ISSUE 10): event-class lanes, flow ledger,
    # and safe-window telemetry traced in — pins the gated program's
    # compile surface while `echo`/`phold` above pin that the DEFAULT
    # (observatory-off) programs stay byte-unchanged.
    "tgen_netobs": dict(
        model="tgen_tcp",
        hosts="tgen",  # mk_hosts(4, tgen args) below
        stop=400_000_000,
        kw=dict(qcap=16, trace_rounds=8, netobs=True, flow_records=16,
                sends_budget=16),
    ),
    # integrity sentinel ON (ISSUE 11): the per-round invariant guards,
    # the violation lanes, and the dual digest traced in — pins the
    # GATED program's compile surface while `echo`/`phold` above pin
    # that the default (sentinel-off) programs stay byte-unchanged.
    "phold_integrity": dict(
        model="phold",
        hosts=None,  # mk_hosts(4) below
        stop=200_000_000,
        kw=dict(qcap=16, integrity=True),
    ),
    # fluid traffic plane ON (ISSUE 13): the background-flow ODE carry,
    # the per-round forward-Euler advance, the outbox byte fold, and the
    # latency/loss coupling traced in — pins the GATED program's compile
    # surface (and audits the fluid.* f64 lane dtypes) while
    # `echo`/`phold`/`tgen_netobs` above pin that the default
    # (fluid-off) programs stay byte-unchanged.
    "tgen_fluid": dict(
        model="tgen_tcp",
        hosts="tgen",  # mk_hosts(4, tgen args) below
        stop=400_000_000,
        kw=dict(qcap=16, sends_budget=16, fluid={
            "link_capacity": "100 Mbit",
            "latency_factor_max": 1.5,
            "loss_max": 0.05,
            "classes": [{"src_zone": 0, "dst_zone": 0,
                         "rate": "80 Mbit", "start": 0}],
        }),
    ),
    # timer wheel + sort-free calendar merge ON (ISSUE 12): the wheel
    # carry lanes, merged queue∪wheel pops, spill routing, and the
    # scatter-merge fast/fallback cond traced in — pins the GATED
    # program's compile surface (and audits the wheel.* lane dtypes)
    # while `echo`/`phold` above pin that the default (wheel-off)
    # programs stay byte-unchanged.
    "phold_wheel": dict(
        model="phold",
        hosts=None,  # mk_hosts(4) below
        stop=200_000_000,
        kw=dict(qcap=16, wheel_slots=8, merge_scatter=True),
    ),
}


def _audit_findings_cls():
    from tools.lint.astlint import Finding

    return Finding


def _state_lane_paths(lanes):
    """STATE_LANES entries as (attr-chain tuple, dtype string)."""
    return [
        (tuple(path.split(".")), dt) for path, dt in lanes.STATE_LANES.items()
    ]


def _walk_attr(obj, chain):
    for name in chain:
        if obj is None:
            return None
        if isinstance(obj, dict):
            obj = obj.get(name)
        else:
            obj = getattr(obj, name, None)
    return obj


def _flatten_with_paths(tree, prefix=()):
    """(path, leaf) pairs in jax.tree flatten order: NamedTuples and
    tuples/lists positionally (NamedTuples labeled by field name), dicts
    by sorted key — mirrors jax's default pytree registry so the list
    aligns with jaxpr invars."""
    if tree is None:
        return []
    if hasattr(tree, "_fields"):  # NamedTuple
        out = []
        for name in tree._fields:
            out += _flatten_with_paths(getattr(tree, name), prefix + (name,))
        return out
    if isinstance(tree, (tuple, list)):
        out = []
        for i, v in enumerate(tree):
            out += _flatten_with_paths(v, prefix + (str(i),))
        return out
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out += _flatten_with_paths(tree[k], prefix + (str(k),))
        return out
    return [(prefix, tree)]


def _iter_eqns(jaxpr):
    """Every eqn of a jaxpr, recursing into sub-jaxprs (while/cond/scan/
    pjit bodies) wherever they hide in eqn params."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from _iter_eqns(sub)


def _sub_jaxprs(v):
    import jax.core as jcore

    closed = getattr(jcore, "ClosedJaxpr", None)
    if closed is not None and isinstance(v, closed):
        yield v.jaxpr
    elif isinstance(v, jcore.Jaxpr):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def _build(name, spec):
    from tests.engine_harness import build_sim, mk_hosts
    from shadow_tpu.core.engine import Engine

    hosts = spec["hosts"]
    if hosts == "tgen":
        hosts = mk_hosts(
            4, {"flow_segs": 4, "flows": 1, "cwnd_cap": 4}
        )
    elif hosts is None:
        hosts = mk_hosts(4, {"mean_delay": "50 ms", "population": 2})
    cfg, model, params, mstate, events = build_sim(
        spec["model"], hosts, spec["stop"], **spec["kw"]
    )
    eng = Engine(cfg, model)
    state, params = eng.init_state(params, mstate, events, seed=1)
    return cfg, model, state, params


def run_audit(
    root: str | None = None,
    update: bool = False,
    configs: tuple[str, ...] = (
        "echo", "phold", "tgen_netobs", "tgen_fluid", "phold_integrity",
        "phold_wheel",
    ),
    fingerprint_file: str = FINGERPRINT_FILE,
):
    """Returns (findings, report dict per config)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if root is None:
        from tools.lint.astlint import repo_root

        root = repo_root()
    if root not in sys.path:
        sys.path.insert(0, root)

    import jax

    from tools.lint.astlint import load_lanes
    from shadow_tpu.core import engine as engine_mod

    Finding = _audit_findings_cls()
    lanes = load_lanes(root)
    lane_paths = _state_lane_paths(lanes)
    findings: list = []
    report: dict = {}

    try:
        with open(fingerprint_file, encoding="utf-8") as f:
            recorded_all = json.load(f)
    except OSError:
        recorded_all = {}
    ver = jax.__version__
    recorded_ver = recorded_all.get(ver, {})
    changed = False

    for name in configs:
        spec = AUDIT_CONFIGS[name]
        cfg, model, state, params = _build(name, spec)
        fn = functools.partial(engine_mod._run_chunk, cfg, model, None)

        # ---- 1: carry lane widths (the traced OUTPUT SimState)
        out_state = jax.eval_shape(fn, state, params)
        for chain, want in lane_paths:
            leaf = _walk_attr(out_state, chain)
            if leaf is None:
                continue  # optional plane absent in this config
            got = str(leaf.dtype)
            if got != want:
                findings.append(Finding(
                    "RB", "shadow_tpu/core/engine.py", 1,
                    f"[{name}] carry lane {'.'.join(chain)} traced as "
                    f"{got}, registry (core/lanes.py) requires {want}",
                ))

        # ---- 2-4: jaxpr walk
        closed = jax.make_jaxpr(fn)(state, params)
        jaxpr = closed.jaxpr

        # top-level invars <-> (state, params) leaves, jax flatten order
        state_paths = [p for p, _ in _flatten_with_paths(state)]
        n_state = len(state_paths)
        invar_lane: dict = {}
        for i, var in enumerate(jaxpr.invars[:n_state]):
            path = ".".join(state_paths[i])
            want = lanes.STATE_LANES.get(path)
            if want in ("int64", "uint64"):
                invar_lane[var] = (path, want)

        prim_counts: dict[str, int] = {}
        int_downcasts = 0
        float_scatter_adds = 0
        for eqn in _iter_eqns(jaxpr):
            pname = eqn.primitive.name
            prim_counts[pname] = prim_counts.get(pname, 0) + 1
            if pname == "convert_element_type":
                src = eqn.invars[0]
                src_dt = getattr(getattr(src, "aval", None), "dtype", None)
                dst_dt = eqn.params.get("new_dtype")
                if src_dt is None or dst_dt is None:
                    continue
                src_s, dst_s = str(src_dt), str(dst_dt)
                if (
                    src_s in ("int64", "uint64")
                    and dst_s.startswith(("int", "uint"))
                    and dst_s not in ("int64", "uint64")
                ):
                    int_downcasts += 1
                    if src in invar_lane:
                        path, want = invar_lane[src]
                        findings.append(Finding(
                            "RB", "shadow_tpu/core/engine.py", 1,
                            f"[{name}] registered {want} carry lane "
                            f"`{path}` down-cast to {dst_s} inside the "
                            f"round body",
                        ))
            elif pname == "scatter-add":
                out_dt = str(eqn.outvars[0].aval.dtype)
                if out_dt.startswith(("float", "bfloat", "complex")):
                    float_scatter_adds += 1

        fp = {
            "eqns": sum(prim_counts.values()),
            "primitives": dict(sorted(prim_counts.items())),
            "int64_downcasts": int_downcasts,
            "float_scatter_adds": float_scatter_adds,
        }
        rec = recorded_ver.get(name)
        if update:
            recorded_ver[name] = fp
            changed = True
            status = "recorded" if rec is None or rec != fp else "unchanged"
        elif rec is None:
            # never auto-record: a jax upgrade landing together with an
            # accidental compile-surface change must not bless itself
            status = "unrecorded"
            findings.append(Finding(
                "RB", "tools/lint/jaxpr_baseline.json", 1,
                f"[{name}] no primitive fingerprint recorded for "
                f"jax=={ver} — review the compile surface and pin it with "
                f"`python -m tools.lint --jaxpr-only --update-fingerprint`",
            ))
        elif rec != fp:
            status = "MISMATCH"
            diffs = []
            for k in ("eqns", "int64_downcasts", "float_scatter_adds"):
                if rec.get(k) != fp[k]:
                    diffs.append(f"{k} {rec.get(k)} -> {fp[k]}")
            rp, cp = rec.get("primitives", {}), fp["primitives"]
            for prim in sorted(set(rp) | set(cp)):
                if rp.get(prim, 0) != cp.get(prim, 0):
                    diffs.append(f"{prim} {rp.get(prim, 0)} -> {cp.get(prim, 0)}")
            findings.append(Finding(
                "RB", "tools/lint/jaxpr_baseline.json", 1,
                f"[{name}] primitive fingerprint changed for jax=={ver}: "
                f"{'; '.join(diffs[:12])} — if the compile-surface change "
                f"is deliberate, re-record with "
                f"`python -m tools.lint --jaxpr-only --update-fingerprint`",
            ))
        else:
            status = "ok"
        report[name] = {
            "eqns": fp["eqns"],
            "int64_downcasts": int_downcasts,
            "float_scatter_adds": float_scatter_adds,
            "fingerprint_status": status,
        }

    if changed:
        recorded_all[ver] = recorded_ver
        try:
            with open(fingerprint_file, "w", encoding="utf-8") as f:
                json.dump(recorded_all, f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError as e:  # read-only checkout: record-mode is advisory
            print(
                f"shadowlint: could not record jaxpr fingerprint "
                f"({e}); rerun with a writable tree to pin it",
                file=sys.stderr,
            )

    return findings, report


if __name__ == "__main__":  # pragma: no cover - debugging aid
    fs, rep = run_audit(update="--update" in sys.argv)
    for f in fs:
        print(f)
    print(json.dumps(rep, indent=2))
    sys.exit(1 if fs else 0)
