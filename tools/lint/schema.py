"""shadowlint stage A schema rules: R3 (carry/schema consistency) and
R5 (heartbeat format compatibility). Pure AST + stdlib `re` — no JAX.

R3 cross-checks five registries that must agree for exactness to be
observable end-to-end:

  Stats NamedTuple fields  (core/engine.py class Stats)
    == _init_stats(...) construction kwargs
    == state_specs(...) sharding-spec kwargs
    ⊆ lane registry STATE_LANES ("stats.<field>")
    ⊆ sim-stats export (sim.py stats_report reads) ∪ STATS_EXPORT_EXEMPT

  every `stats._replace(field=...)` write in the engine names a real field

  TRACE_FIELDS (obs/tracer.py) is append-only against the checked-in
  ordering (tools/lint/trace_columns.txt): recorded trace files are
  indexed by column position, so reordering or removing a column silently
  corrupts every consumer of an old trace.

R5 statically extracts every `key=` field emitted by the heartbeat
formatters (sim.heartbeat_line + resource_heartbeat, and the hybrid
driver's inline [heartbeat] f-string in cosim.py) and requires each to be
matched by tools/parse_shadow.py's HEARTBEAT_RE — and, in reverse, every
literal `key=` the regex knows to still have an emitter (or an entry in
lanes.HEARTBEAT_LEGACY_KEYS). A checked-in file of literal lines, one
per recorded format generation (tools/lint/heartbeat_generations.txt),
must keep parsing; the runtime round-trip lives in tests/test_lint.py
via `parse_shadow --strict`.
"""

from __future__ import annotations

import ast
import os
import re

from tools.lint.astlint import Finding, Project, repo_root

TRACE_COLUMNS_FILE = os.path.join(os.path.dirname(__file__), "trace_columns.txt")
GENERATIONS_FILE = os.path.join(
    os.path.dirname(__file__), "heartbeat_generations.txt"
)

_KEY_RE = re.compile(r"([A-Za-z_][A-Za-z0-9_/]*)=")


# --------------------------------------------------------------------------
# AST harvest helpers
# --------------------------------------------------------------------------


def _find_class(tree: ast.Module, name: str) -> ast.ClassDef | None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _namedtuple_fields(cls: ast.ClassDef) -> list[str]:
    return [
        n.target.id
        for n in cls.body
        if isinstance(n, ast.AnnAssign) and isinstance(n.target, ast.Name)
    ]


def _call_kwargs_of(fn: ast.AST, callee: str) -> tuple[set[str], int]:
    """Keyword names of the first `callee(...)` call inside `fn`."""
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == callee
        ):
            return {k.arg for k in node.keywords if k.arg}, node.lineno
    return set(), 0


def _literal_parts(node) -> list[str]:
    """All literal string fragments of a str constant / f-string subtree."""
    parts = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            parts.append(sub.value)
    return parts


def _harvest_keys(node) -> set[str]:
    keys: set[str] = set()
    for part in _literal_parts(node):
        keys.update(_KEY_RE.findall(part))
    return keys


def _heartbeat_keys_of_function(fn: ast.AST) -> set[str]:
    """Emitted `key=` tokens of a heartbeat-formatting function: harvested
    from the f-string containing "[heartbeat]" plus any f-strings assigned
    to names interpolated into it (the fault_f/gear_f/rep_f pattern)."""
    hb_nodes = [
        node
        for node in ast.walk(fn)
        if isinstance(node, ast.JoinedStr)
        and any("[heartbeat]" in p for p in _literal_parts(node))
    ]
    if not hb_nodes:
        return set()
    keys: set[str] = set()
    wanted: set[str] = set()
    for hb in hb_nodes:
        keys |= _harvest_keys(hb)
        for sub in ast.walk(hb):
            if isinstance(sub, ast.FormattedValue) and isinstance(
                sub.value, ast.Name
            ):
                wanted.add(sub.value.id)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id in wanted:
                    keys |= _harvest_keys(node.value)
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and node.target.id in wanted:
                keys |= _harvest_keys(node.value)
    return keys


# --------------------------------------------------------------------------
# R3: Stats / trace-ring schema consistency
# --------------------------------------------------------------------------


def check_stats_schema(project: Project) -> list[Finding]:
    out: list[Finding] = []
    eng = project.modules.get("shadow_tpu.core.engine")
    sim = project.modules.get("shadow_tpu.sim")
    lanes = project.lanes
    if eng is None:
        return [Finding("R3", "shadow_tpu/core/engine.py", 1, "module missing")]

    cls = _find_class(eng.tree, "Stats")
    if cls is None:
        return [Finding("R3", eng.path, 1, "Stats NamedTuple not found")]
    fields = _namedtuple_fields(cls)
    fset = set(fields)

    def diff(got: set[str], line: int, what: str):
        for missing in sorted(fset - got):
            out.append(Finding(
                "R3", eng.path, line,
                f"Stats.{missing} missing from {what}",
            ))
        for extra in sorted(got - fset):
            out.append(Finding(
                "R3", eng.path, line,
                f"{what} names `{extra}`, which is not a Stats field",
            ))

    init = eng.functions.get("_init_stats")
    if init is not None:
        got, line = _call_kwargs_of(init, "Stats")
        diff(got, line or init.lineno, "_init_stats construction")
    else:
        out.append(Finding("R3", eng.path, cls.lineno, "_init_stats not found"))

    specs = eng.functions.get("Engine.state_specs")
    if specs is not None:
        got, line = _call_kwargs_of(specs, "Stats")
        diff(got, line or specs.lineno, "Engine.state_specs sharding spec")
    else:
        out.append(Finding("R3", eng.path, cls.lineno, "Engine.state_specs not found"))

    # lane registry: every Stats field needs a declared width
    for f in fields:
        if f"stats.{f}" not in lanes.STATE_LANES:
            out.append(Finding(
                "R3", eng.path, cls.lineno,
                f"Stats.{f} has no entry in shadow_tpu/core/lanes.py "
                f"STATE_LANES (`stats.{f}`) — declare its width so the "
                f"jaxpr audit pins it",
            ))

    # every stats._replace(...) write in the engine names a real field
    for qual, fn in eng.functions.items():
        for node in ast.walk(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_replace"
            ):
                continue
            base = node.func.value
            term = base.attr if isinstance(base, ast.Attribute) else getattr(
                base, "id", None
            )
            if term != "stats":
                continue
            for kw in node.keywords:
                if kw.arg and kw.arg not in fset:
                    out.append(Finding(
                        "R3", eng.path, node.lineno,
                        f"`stats._replace({kw.arg}=...)` in `{qual}` writes "
                        f"a field that does not exist on Stats",
                    ))

    # sim-stats export coverage
    if sim is not None:
        report_fn = sim.functions.get("Simulation.stats_report")
        if report_fn is None:
            out.append(Finding(
                "R3", sim.path, 1, "Simulation.stats_report not found"
            ))
        else:
            read = {
                node.attr
                for node in ast.walk(report_fn)
                if isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "s"
            }
            exempt = lanes.STATS_EXPORT_EXEMPT
            for f in fields:
                if f not in read and f not in exempt:
                    out.append(Finding(
                        "R3", sim.path, report_fn.lineno,
                        f"Stats.{f} is neither exported by stats_report nor "
                        f"listed (with a reason) in lanes.STATS_EXPORT_EXEMPT"
                        f" — counters no one can see rot silently",
                    ))
            for f in sorted(set(exempt) - fset):
                out.append(Finding(
                    "R3", eng.path, cls.lineno,
                    f"lanes.STATS_EXPORT_EXEMPT names `{f}`, not a Stats field",
                ))
    return out


def check_trace_columns(
    project: Project, columns_file: str = TRACE_COLUMNS_FILE
) -> list[Finding]:
    out: list[Finding] = []
    tracer = project.modules.get("shadow_tpu.obs.tracer")
    if tracer is None:
        return [Finding("R3", "shadow_tpu/obs/tracer.py", 1, "module missing")]
    fields = None
    line = 1
    for node in tracer.tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "TRACE_FIELDS"
            and isinstance(node.value, ast.Tuple)
        ):
            line = node.lineno
            fields = [
                el.value
                for el in node.value.elts
                if isinstance(el, ast.Constant) and isinstance(el.value, str)
            ]
    if fields is None:
        return [Finding("R3", tracer.path, 1, "TRACE_FIELDS literal not found")]
    try:
        with open(columns_file, encoding="utf-8") as f:
            recorded = [
                ln.strip() for ln in f
                if ln.strip() and not ln.lstrip().startswith("#")
            ]
    except OSError:
        return [Finding(
            "R3", tracer.path, line,
            f"trace-column registry {os.path.basename(columns_file)} missing",
        )]
    if fields[: len(recorded)] != recorded:
        out.append(Finding(
            "R3", tracer.path, line,
            f"TRACE_FIELDS no longer starts with the checked-in column "
            f"ordering (tools/lint/trace_columns.txt) — trace rings are "
            f"indexed by position, so columns are APPEND-ONLY: first "
            f"divergence at index "
            f"{next(i for i, (a, b) in enumerate(zip(fields, recorded)) if a != b) if any(a != b for a, b in zip(fields, recorded)) else min(len(fields), len(recorded))}",
        ))
    elif len(fields) > len(recorded):
        out.append(Finding(
            "R3", tracer.path, line,
            f"TRACE_FIELDS grew by {len(fields) - len(recorded)} column(s) "
            f"({', '.join(fields[len(recorded):])}) — append them to "
            f"tools/lint/trace_columns.txt in the same commit so the "
            f"ordering is pinned",
        ))
    return out


# --------------------------------------------------------------------------
# R5: heartbeat format compatibility
# --------------------------------------------------------------------------


def _load_heartbeat_re():
    """tools/parse_shadow is stdlib-only — safe to import in stage A."""
    from tools.parse_shadow import HEARTBEAT_RE

    return HEARTBEAT_RE


def emitted_heartbeat_keys(project: Project) -> dict[str, tuple[str, int]]:
    """key -> (path, line) over every heartbeat emitter in the tree."""
    keys: dict[str, tuple[str, int]] = {}
    for mod_name in ("shadow_tpu.sim", "shadow_tpu.cosim"):
        mod = project.modules.get(mod_name)
        if mod is None:
            continue
        for qual, fn in mod.functions.items():
            got = _heartbeat_keys_of_function(fn)
            if qual == "resource_heartbeat":
                # no "[heartbeat]" literal of its own: harvest directly
                for node in ast.walk(fn):
                    if isinstance(node, (ast.JoinedStr, ast.Constant)):
                        got |= _harvest_keys(node)
            for k in got:
                keys.setdefault(k, (mod.path, fn.lineno))
    return keys


def check_heartbeat_compat(
    project: Project,
    heartbeat_re=None,
    generations_file: str = GENERATIONS_FILE,
) -> list[Finding]:
    out: list[Finding] = []
    if heartbeat_re is None:
        heartbeat_re = _load_heartbeat_re()
    pattern = heartbeat_re.pattern
    emitted = emitted_heartbeat_keys(project)
    if not emitted:
        return [Finding(
            "R5", "shadow_tpu/sim.py", 1, "no heartbeat emitters found"
        )]

    # the parser's literal `key=` vocabulary (group-name syntax masked so
    # `(?P<name>` never reads as a key) — exact-set matching, NOT substring:
    # a new `hwm=` emitter must not pass just because `q_hwm=` exists
    parsed_keys = set(_KEY_RE.findall(pattern.replace("(?P<", "(?P~")))

    # forward: every emitted key must be a literal the parser matches
    for key, (path, line) in sorted(emitted.items()):
        if key not in parsed_keys:
            out.append(Finding(
                "R5", path, line,
                f"heartbeat field `{key}=` is emitted but "
                f"tools/parse_shadow.py HEARTBEAT_RE has no `{key}=` "
                f"branch — extend the regex (keeping old generations "
                f"parseable) in the same commit",
            ))

    # reverse: every literal key the parser knows still has an emitter
    legacy = set(project.lanes.HEARTBEAT_LEGACY_KEYS)
    for key in sorted(parsed_keys):
        if key not in emitted and key not in legacy:
            out.append(Finding(
                "R5", "tools/parse_shadow.py", 1,
                f"HEARTBEAT_RE matches `{key}=` but no emitter produces it "
                f"— if the field was retired, record it in "
                f"lanes.HEARTBEAT_LEGACY_KEYS so the parser keeps reading "
                f"old logs deliberately",
            ))

    # recorded generations must keep matching (static half; the runtime
    # strict-parse round-trip is tests/test_lint.py)
    try:
        with open(generations_file, encoding="utf-8") as f:
            lines = [
                ln.rstrip("\n") for ln in f
                if ln.strip() and not ln.lstrip().startswith("#")
            ]
    except OSError:
        lines = None
    if lines is None:
        out.append(Finding(
            "R5", "tools/parse_shadow.py", 1,
            f"heartbeat generations file "
            f"{os.path.basename(generations_file)} missing",
        ))
    else:
        for i, ln in enumerate(lines, 1):
            if not heartbeat_re.search(ln):
                out.append(Finding(
                    "R5", "tools/lint/heartbeat_generations.txt", i,
                    f"recorded generation no longer parses: {ln!r}",
                ))
    return out


# --------------------------------------------------------------------------
# R6: timer-wheel registry lockstep
# --------------------------------------------------------------------------


def check_wheel_registry(project: Project) -> list[Finding]:
    """The timer wheel (ops/wheel.py) reuses the BucketQueue machinery,
    so every wheel array's dtype/width MUST be sourced from the lane
    registry and stay in lockstep with its queue counterpart — the shared
    ops read and write both structures through one code path, and a width
    drifting on one side silently reinterprets bits on the other.

    Checks (all against core/lanes.py, the single source):
      1. every `wheel.*` path in STATE_LANES is paired in
         WHEEL_LANE_OF_QUEUE (and vice versa), the paired `queue.*` path
         exists, and the two registered widths AGREE;
      2. every `wheel.*` path has a STATE_LANE_SHAPES entry (the HBM
         byte model prices the wheel like every other plane);
      3. the field set of the BucketQueue NamedTuple (ops/events.py —
         the wheel's actual layout) equals the set of registered
         `wheel.<field>` paths, so adding a plane to the shared
         structure without registering the wheel's copy fails lint."""
    out: list[Finding] = []
    lanes = project.lanes
    lanes_path = "shadow_tpu/core/lanes.py"
    pairing = getattr(lanes, "WHEEL_LANE_OF_QUEUE", None)
    if pairing is None:
        return [Finding(
            "R6", lanes_path, 1, "WHEEL_LANE_OF_QUEUE registry missing",
        )]
    wheel_paths = {p for p in lanes.STATE_LANES if p.startswith("wheel.")}
    for p in sorted(wheel_paths - set(pairing)):
        out.append(Finding(
            "R6", lanes_path, 1,
            f"{p} is registered in STATE_LANES but has no "
            f"WHEEL_LANE_OF_QUEUE pairing — state which queue lane its "
            f"width mirrors",
        ))
    for wp, qp in sorted(pairing.items()):
        if wp not in lanes.STATE_LANES:
            out.append(Finding(
                "R6", lanes_path, 1,
                f"WHEEL_LANE_OF_QUEUE names `{wp}`, which is not in "
                f"STATE_LANES",
            ))
            continue
        if qp not in lanes.STATE_LANES:
            out.append(Finding(
                "R6", lanes_path, 1,
                f"{wp} pairs to `{qp}`, which is not in STATE_LANES",
            ))
            continue
        if lanes.STATE_LANES[wp] != lanes.STATE_LANES[qp]:
            out.append(Finding(
                "R6", lanes_path, 1,
                f"{wp} ({lanes.STATE_LANES[wp]}) and {qp} "
                f"({lanes.STATE_LANES[qp]}) disagree on width — the "
                f"wheel reuses the queue machinery, widths must move in "
                f"lockstep",
            ))
    for p in sorted(wheel_paths):
        if p not in lanes.STATE_LANE_SHAPES:
            out.append(Finding(
                "R6", lanes_path, 1,
                f"{p} has no STATE_LANE_SHAPES entry — the HBM byte "
                f"model cannot price the wheel plane",
            ))
    ev = project.modules.get("shadow_tpu.ops.events")
    if ev is not None:
        cls = _find_class(ev.tree, "BucketQueue")
        if cls is None:
            out.append(Finding(
                "R6", ev.path, 1, "BucketQueue NamedTuple not found",
            ))
        else:
            fields = set(_namedtuple_fields(cls))
            registered = {p.split(".", 1)[1] for p in wheel_paths}
            for f in sorted(fields - registered):
                out.append(Finding(
                    "R6", lanes_path, 1,
                    f"BucketQueue.{f} (the wheel's layout) has no "
                    f"`wheel.{f}` registry entry — register its "
                    f"width/shape so the audit and byte model see it",
                ))
            for f in sorted(registered - fields):
                out.append(Finding(
                    "R6", lanes_path, 1,
                    f"`wheel.{f}` is registered but BucketQueue has no "
                    f"such field",
                ))
    return out


# --------------------------------------------------------------------------
# R7: lane diet (exchange-wire widths)
# --------------------------------------------------------------------------


def check_lane_diet(project: Project) -> list[Finding]:
    """The lane-diet contract (core/lanes.py LANE_MIN_WIDTH_BITS +
    EXCHANGE_WIRE_LANES): every lane that crosses an exchange collective
    carries a proven minimum exact width, and the registered width honors
    it in BOTH directions.

    Checks (all against core/lanes.py, the single source):
      1. every EXCHANGE_WIRE_LANES member has a LANE_MIN_WIDTH_BITS entry
         (a wire lane without a stated bound cannot be dieted OR defended);
      2. every LANE_MIN_WIDTH_BITS key is a registered lane in LANE_WIDTHS
         (the table must not name phantom lanes) and its registered width
         is >= the minimum (a lane registered NARROWER than its provable
         minimum truncates);
      3. wire lanes whose minimum is <= 32 must be REGISTERED at 32 — the
         diet is real: a bounded counter riding the wire at i64 silently
         doubles the inter-tier byte charge (`stats.ici_inter`);
      4. wire lanes whose minimum is 64 must be time/order/digest lanes —
         the only species with a genuine 64-bit range; anything else
         claiming 64 on the wire needs its bound re-derived here first."""
    out: list[Finding] = []
    lanes = project.lanes
    lanes_path = "shadow_tpu/core/lanes.py"
    min_bits = getattr(lanes, "LANE_MIN_WIDTH_BITS", None)
    wire = getattr(lanes, "EXCHANGE_WIRE_LANES", None)
    if min_bits is None or wire is None:
        return [Finding(
            "R7", lanes_path, 1,
            "LANE_MIN_WIDTH_BITS / EXCHANGE_WIRE_LANES registry missing",
        )]
    wide_ok = lanes.TIME_LANES | lanes.ORDER_LANES | lanes.DIGEST_LANES
    for name in sorted(wire):
        if name not in min_bits:
            out.append(Finding(
                "R7", lanes_path, 1,
                f"exchange-wire lane `{name}` has no LANE_MIN_WIDTH_BITS "
                f"entry — state the capacity/slot bound that caps it (or "
                f"64 with the species that justifies it)",
            ))
    for name, mb in sorted(min_bits.items()):
        reg = lanes.lane_width_bits(name)
        if reg is None:
            out.append(Finding(
                "R7", lanes_path, 1,
                f"LANE_MIN_WIDTH_BITS names `{name}`, which is not a "
                f"registered lane in LANE_WIDTHS",
            ))
            continue
        if reg < mb:
            out.append(Finding(
                "R7", lanes_path, 1,
                f"lane `{name}` is registered at {reg} bits but its "
                f"minimum exact width is {mb} — the registered width "
                f"truncates the lane's proven range",
            ))
        if name in wire:
            if mb <= 32 and reg != 32:
                out.append(Finding(
                    "R7", lanes_path, 1,
                    f"exchange-wire lane `{name}` is provably exact at "
                    f"{mb} bits but registered at {reg} — ride the wire "
                    f"at i32 (the lane diet) or re-derive the bound in "
                    f"LANE_MIN_WIDTH_BITS",
                ))
            if mb >= 64 and name not in wide_ok:
                out.append(Finding(
                    "R7", lanes_path, 1,
                    f"exchange-wire lane `{name}` claims a 64-bit minimum "
                    f"but is not a time/order/digest lane — only those "
                    f"species carry a genuine 64-bit range",
                ))
    return out


def run_schema_rules(
    root: str | None = None, project: Project | None = None
) -> list[Finding]:
    root = root or repo_root()
    project = project or Project(root)
    findings = []
    findings += check_stats_schema(project)
    findings += check_trace_columns(project)
    findings += check_heartbeat_compat(project)
    findings += check_wheel_registry(project)
    findings += check_lane_diet(project)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.msg))
