"""shadowlint — static exactness/purity analyzer for the jitted round body.

Two stages, run via `python -m tools.lint`:

  Stage A (astlint.py + schema.py) — pure-AST rule packs over the repo,
  importing NO JAX (so the tier-1 pre-stage survives the jaxlib
  corruption that can kill compiled runs on some boxes):

    R1 jit purity       no time/random/np.random/datetime/global-state
                        mutation or file I/O reachable from the jitted
                        entry points
    R2 lane widths      time/order/counter lanes stay their registered
                        width (shadow_tpu/core/lanes.py); no astype
                        narrowing, no implicit-dtype construction
    R3 carry/schema     Stats fields consistent across the NamedTuple,
                        _init_stats, sharding specs, lane registry, and
                        sim-stats export; trace-ring columns append-only
    R4 static hygiene   EngineConfig statics hashable; no int()/.item()
                        on lane values inside jitted scope
    R5 format compat    every heartbeat field emitted anywhere is matched
                        by tools/parse_shadow.py, and all recorded
                        heartbeat generations still parse

  Stage B (jaxpr_audit.py) — traces the round body for small echo/phold
  configs on CPU and walks the jaxpr: lane carry dtypes must match the
  registry, no 64->32 integer down-cast on a carry lane, float
  scatter-adds recorded, and a primitive-count fingerprint pinned per
  jax version (compile-surface churn shows up as a diff, not a surprise
  recompile).

Findings carry `rule path:line message`. Pre-existing violations are
burned down through tools/lint/baseline.json — explicit, reviewed
suppressions, kept at ZERO for shadow_tpu/core and shadow_tpu/ops.
"""

from tools.lint.astlint import Finding, run_stage_a  # noqa: F401
