"""Microbench the exchange-merge gather formulations on the real TPU.

Shapes match bench config 6 (H=10k, C=64, B=40 -> M=410001 sorted rows).
Variants for the queue-shaped value materialization g[H, C, W]:

  gather   : g = w_sorted[j]                      (shipped r4 formulation)
  blk_tala : per-host contiguous block slice-gather [H, R, W] then
             take_along_axis on the rank axis
  blk_sel  : block slice-gather then an R-deep select chain
  blk_mm   : block slice-gather then exact one-hot f32 matmul (u16 split)

Plus the truncation lever: w_sorted built from s_idx[:K] instead of [:M].
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

H, C, B, W = 10_000, 64, 40, 9
N = H * B
M = N + H + 1
R = 32  # r_cap for the block variants
K = 65_536

rng = np.random.default_rng(0)


def make_inputs():
    words = jnp.asarray(rng.integers(-(2**31), 2**31, (M, W), np.int64), jnp.int32)
    s_idx = jnp.asarray(rng.permutation(M).astype(np.int32))
    # plausible first[]: ~25k real rows spread over H segments
    seg = rng.multinomial(25_000, np.ones(H) / H)
    first = np.zeros(H + 1, np.int32)
    first[1:] = np.cumsum(seg + 1)
    first_j = jnp.asarray(first)
    free_rank = jnp.asarray(
        np.tile(np.arange(C, dtype=np.int32), (H, 1))
    )  # pretend all slots free
    take = jnp.asarray(rng.random((H, C)) < 0.04)  # ~25k takes
    return words, s_idx, first_j, free_rank, take


def timed(f, *args, n=20):
    # jit ONCE outside the loop (bench_gather_tput.py idiom): re-calling
    # jax.jit(f) per iteration pays the trace-cache lookup + wrapper
    # dispatch every pass, which swamps the smallest kernels under test
    g = jax.jit(f)
    jax.block_until_ready(g(*args))
    t0 = time.monotonic()
    for _ in range(n):
        out = g(*args)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / n * 1000


def main():
    words, s_idx, first, free_rank, take = make_inputs()

    def permute_full(words, s_idx):
        return words[s_idx]

    def permute_k(words, s_idx):
        return words[s_idx[:K]]

    t_pf = timed(permute_full, words, s_idx)
    t_pk = timed(permute_k, words, s_idx)
    print(f"permute [M={M},{W}] random gather : {t_pf:7.3f} ms")
    print(f"permute [K={K},{W}] random gather : {t_pk:7.3f} ms")

    w_sorted = jax.jit(permute_full)(words, s_idx)
    w_k = jnp.pad(jax.jit(permute_k)(words, s_idx), ((0, R), (0, 0)))

    def g_gather(ws, first, free_rank, take):
        jj = first[:-1, None] + 1 + free_rank
        j = jnp.where(take & (jj < M), jj, 0)
        return ws[j]

    def blocks(ws, first):
        start = jnp.clip(first[:-1] + 1, 0, K)

        def one(s):
            return lax.dynamic_slice(ws, (s, 0), (R, W))

        return jax.vmap(one)(start)

    def g_blk_tala(ws, first, free_rank, take):
        blk = blocks(ws, first)
        fr = jnp.clip(free_rank, 0, R - 1)
        return jnp.take_along_axis(blk, fr[:, :, None], axis=1)

    def g_blk_sel(ws, first, free_rank, take):
        blk = blocks(ws, first)
        acc = jnp.zeros((H, C, W), jnp.int32)
        for r in range(R):
            m = (free_rank == r) & take
            acc = jnp.where(m[:, :, None], blk[:, r, :][:, None, :], acc)
        return acc

    def g_blk_mm(ws, first, free_rank, take):
        blk = blocks(ws, first)
        lo = (blk & 0xFFFF).astype(jnp.float32)
        hi = ((blk >> 16) & 0xFFFF).astype(jnp.float32)
        rhs = jnp.concatenate([lo, hi], axis=2)  # [H, R, 2W]
        fr = jnp.clip(free_rank, 0, R - 1)
        oh = (
            (fr[:, :, None] == jnp.arange(R)[None, None, :]) & take[:, :, None]
        ).astype(jnp.float32)
        out = jnp.einsum(
            "hcr,hrw->hcw", oh, rhs, preferred_element_type=jnp.float32
        )
        lo2 = out[..., :W].astype(jnp.int32)
        hi2 = out[..., W:].astype(jnp.int32)
        return (hi2 << 16) | lo2

    t_blocks = timed(blocks, w_k, first)
    print(f"block slice-gather [H,{R},{W}]      : {t_blocks:7.3f} ms")

    for name, f, ws in (
        ("g random-gather (full M src)", g_gather, w_sorted),
        ("g blk+take_along_axis (K src)", g_blk_tala, w_k),
        ("g blk+select-chain   (K src)", g_blk_sel, w_k),
        ("g blk+onehot-matmul  (K src)", g_blk_mm, w_k),
    ):
        t = timed(f, ws, first, free_rank, take)
        print(f"{name:32s}: {t:7.3f} ms")

    # sanity: the three block variants agree where take is set
    a = jax.jit(g_blk_tala)(w_k, first, free_rank, take)
    b = jax.jit(g_blk_sel)(w_k, first, free_rank, take)
    c = jax.jit(g_blk_mm)(w_k, first, free_rank, take)
    tk = np.asarray(take)
    aa, bb, cc = (np.asarray(x)[tk] for x in (a, b, c))
    print("tala==sel where take:", bool((aa == bb).all()),
          " mm==sel where take:", bool((cc == bb).all()))


if __name__ == "__main__":
    main()
