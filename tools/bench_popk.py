"""Microbench: K-way microstep pop+fold vs K single-event pop+push pairs.

Two legs, same harness shape as tools/bench_bucketq.py:

  1. **pop+fold pair** — the engine's per-microstep queue work. The K=1
     unit is `q_pop_min` + `q_push_many`(1 push); the K-way unit is
     `q_pop_k` + `clear_popped` + ONE `q_push_many` with K reserve-tagged
     pushes (exactly the `_microstep_k` queue sequence). Both run as
     jitted `lax.fori_loop`s processing the SAME number of events
     (steps x K singles vs steps K-folds), so the printed ratio is the
     pure queue-side amortization of folding K events into one slab
     round-trip. Swept over K x queue_block.

     Equivalence check: each host's final event multiset (time-sorted
     rows) must match the K=1 reference. Slot POSITIONS legitimately
     differ (K pushes fill freed slots in one pass instead of one at a
     time) and are not observable — full behavioral equality (digests,
     drops, order) is pinned at the engine level by tests/test_popk.py.
     The bench seeds fill=K so batches never span reschedule generations
     and the unguarded fold stays exact (the engine's deferral guard is
     engine logic, not queue logic).

  2. **small tgen end-to-end** (--e2e) — bench.py's config-6 workload at
     the --small scale, swept over microstep_events x event_queue_block,
     reporting sim-s/wall-s so the K that wins the microbench can be
     sanity-checked against real engine rounds before wiring it into the
     bench config.

    python tools/bench_popk.py [--hosts 10000] [--cap 64] [--steps 16]
                               [--reps 3] [--ks 1,2,4,8] [--blocks 0,8]
                               [--e2e]
"""

import argparse
import pathlib
import sys
import time

_HERE = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(_HERE.parent))
sys.path.insert(0, str(_HERE))
import shadow_tpu  # noqa: F401  (enables x64)

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bench_bucketq import seed_slab
from shadow_tpu.ops import (
    bucket_rebuild,
    clear_popped,
    pop_k,
    q_pop_min,
    q_push_many,
)
from shadow_tpu.ops.events import pack_order

DELTA_NS = 2_000_000_000  # > the seeded time range: batches never mix
# reschedule generations, so the unguarded fold is exact (see module doc)


def make_single_stepper(h: int, steps: int):
    hosts = jnp.arange(h, dtype=jnp.int64)

    def body(_, carry):
        q, seq = carry
        q, ev, active = q_pop_min(q, jnp.int64(1) << 62)
        order = jax.vmap(pack_order, in_axes=(None, 0, 0))(1, hosts, seq)
        q = q_push_many(
            q, [(active, ev.t + DELTA_NS, order, ev.kind, ev.payload)]
        )
        return q, seq + active.astype(jnp.int64)

    return jax.jit(lambda q, seq: lax.fori_loop(0, steps, body, (q, seq)))


def make_kway_stepper(h: int, steps: int, k: int):
    hosts = jnp.arange(h, dtype=jnp.int64)

    def body(_, carry):
        q, seq = carry
        popped = pop_k(q, jnp.int64(1) << 62, k)
        m = jnp.sum(popped.active.astype(jnp.int32), axis=1)
        q = clear_popped(q, popped, m)
        pushes = []
        for j in range(k):
            act = popped.active[:, j]
            order = jax.vmap(pack_order, in_axes=(None, 0, 0))(1, hosts, seq)
            seq = seq + act.astype(jnp.int64)
            # reserve = later batch events, as _microstep_k would tag it
            reserve = jnp.sum(
                popped.active[:, j + 1 :].astype(jnp.int32), axis=1
            )
            pushes.append((
                act, popped.t[:, j] + DELTA_NS, order,
                popped.kind[:, j], popped.payload[:, j], reserve,
            ))
        q = q_push_many(q, pushes)
        return q, seq

    return jax.jit(lambda q, seq: lax.fori_loop(0, steps, body, (q, seq)))


def timed(fn, q0, seq0, reps: int):
    out = fn(q0, seq0)  # compile + warm
    jax.block_until_ready(out)
    t0 = time.monotonic()
    for _ in range(reps):
        out = fn(q0, seq0)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / reps, out


def sweep_pair(args):
    h, c = args.hosts, args.cap
    ks = [int(x) for x in args.ks.split(",")]
    blocks = [int(b) for b in args.blocks.split(",")]
    print(
        f"backend={jax.default_backend()} H={h} C={c} steps={args.steps} "
        f"reps={args.reps} (events per leg = steps x K x H)"
    )
    for k in ks:
        fill = min(k, c)
        flat0 = seed_slab(h, c, fill)
        seq0 = jnp.full((h,), fill, jnp.int64)
        single = make_single_stepper(h, args.steps * k)
        t_one, (q_ref, _) = timed(single, flat0, seq0, args.reps)
        ref_sorted = np.sort(np.asarray(q_ref.t), axis=1)
        per_one = t_one / (args.steps * k) * 1e3
        print(f"K={k:2d} singles : {per_one:8.4f} ms/event  "
              f"({t_one * 1e3:8.1f} ms)")
        if k == 1:
            continue
        for b in blocks:
            if b and c % b:
                continue
            q0 = bucket_rebuild(flat0, b) if b else flat0
            fold = make_kway_stepper(h, args.steps, k)
            t_k, (q_k, _) = timed(fold, q0, seq0, args.reps)
            per_k = t_k / (args.steps * k) * 1e3
            same = bool(
                np.array_equal(np.sort(np.asarray(q_k.t), axis=1), ref_sorted)
            )
            print(
                f"K={k:2d} fold B={b:3d}: {per_k:8.4f} ms/event  "
                f"speedup x{t_one / t_k:5.2f}  events==K1: {same}"
            )
            if not same:
                raise SystemExit(f"K={k} B={b}: fold diverged from singles")


def sweep_e2e(args):
    """Small tgen-TCP end-to-end (bench.py config 6, --small scale)."""
    from bench import baseline_config
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    ks = [int(x) for x in args.ks.split(",")]
    blocks = [int(b) for b in args.blocks.split(",")]
    for k in ks:
        for b in blocks:
            cfg_dict, _, _ = baseline_config(6, small=True)
            cfg_dict["general"]["stop_time"] = "20 s"
            cfg_dict["experimental"]["microstep_events"] = k
            cfg_dict["experimental"]["event_queue_block"] = b
            cap = cfg_dict["experimental"]["event_queue_capacity"]
            if b and cap % b:
                continue
            sim = Simulation(ConfigOptions.from_dict(cfg_dict), world=1)
            state, params, engine = sim.state, sim.params, sim.engine
            state = engine.run_chunk(state, params)  # compile chunk
            jax.block_until_ready(state)
            sim0 = int(state.now)
            t0 = time.monotonic()
            if bool(state.done):
                # whole sim fit in the compile chunk: rebuild fresh state
                # and drive it with the ALREADY-COMPILED engine (bench.py's
                # clean-run pattern — a new Engine would recompile)
                state = Simulation(
                    ConfigOptions.from_dict(cfg_dict), world=1
                ).state
                sim0 = 0
                t0 = time.monotonic()
            while not bool(state.done):
                state = engine.run_chunk(state, params)
                jax.block_until_ready(state)
                if time.monotonic() - t0 > args.e2e_budget:
                    break
            wall = max(time.monotonic() - t0, 1e-9)
            s = jax.device_get(state.stats)
            msteps = int(np.asarray(s.microsteps).sum())
            rounds = max(int(s.rounds), 1)
            ev = int(np.asarray(s.events).sum())
            print(
                f"e2e K={k:2d} B={b:3d}: "
                f"{(int(state.now) - sim0) / 1e9 / wall:7.3f} sim_s/wall_s  "
                f"msteps/round={msteps / rounds:5.1f} "
                f"ev/mstep={ev / max(msteps, 1):5.2f} "
                f"digest={int(np.bitwise_xor.reduce(s.digest)):016x}"
            )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=10_000)
    ap.add_argument("--cap", type=int, default=64)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--ks", default="1,2,4,8")
    ap.add_argument("--blocks", default="0,8")
    ap.add_argument("--e2e", action="store_true")
    ap.add_argument("--e2e-budget", type=float, default=60.0)
    args = ap.parse_args()
    if args.e2e:
        sweep_e2e(args)
    else:
        sweep_pair(args)


if __name__ == "__main__":
    main()
