#!/usr/bin/env python3
"""Crash-recovery soak: run a faulty scenario N times, SIGKILL ~1/3 of the
runs mid-flight, resume each from its on-disk checkpoint, and fail on any
final-digest mismatch.

This is the end-to-end gate for the fault plane's recovery contract
(docs/architecture.md "Fault plane"): the engine is deterministic and the
supervisor's checkpoints are chunk-exact, so EVERY iteration — killed or
not, resumed once or several times — must finish with the reference
digest. A mismatch means either the schedule leaked nondeterminism or the
resume path diverged; both are release blockers, not flakes.

Each iteration runs the simulation in a worker SUBPROCESS (python -c) so a
SIGKILL — injected by the parent at a seeded random delay, the same hard
crash this box's jaxlib heap corruption delivers spontaneously — kills a
real process mid-dispatch, not a mocked one. A killed worker is relaunched
in resume mode (builds the same sim, loads the checkpoint if one landed,
runs to completion); a worker that dies without ever checkpointing simply
replays from the start. Known-env note (CHANGES.md PR 2): this box's
jaxlib corruption can scribble device state BEFORE aborting (or complete
with a silently wrong digest), so a checkpoint written near a spontaneous
crash can be poisoned through no fault of the recovery path. The soak
therefore classifies: a mismatch in an iteration whose workers only died
by OUR injected SIGKILL fails hard; a mismatch in an iteration with
spontaneous worker deaths counts as INCONCLUSIVE (reported, not failed).
On a healthy box spontaneous deaths are zero and the gate is strict.

Usage:
  python tools/soak.py [--iters N] [--seed S] [--smoke] [--keep]
    --smoke     2-minute budget variant for tools/check_tier1.sh's optional
                second stage (TIER1_SOAK=1): fewer iterations, small sim
    --sentinel  integrity-sentinel soak (TIER1_INTEGRITY=1 stage): N
                uninterrupted iterations with the in-jit invariant guards
                ON (`integrity.enabled`), asserting zero deterministic
                violations and digest-exactness, and reporting the
                transient-SDC count — upgrading the verdict from "the
                final digest matched" to "every round's invariants held"
    --keep      keep the per-iteration work directories
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, REPO)

# the corruption-signature taxonomy lives in tools/corruption.py (one
# classify() for every consumer; docs/corruption.md is the prose side)
from tools.corruption import classify  # noqa: E402

WORKER = """
import jax; jax.config.update('jax_platforms', 'cpu')
import json, os, sys
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation
from shadow_tpu.core.checkpoint import load_checkpoint

cfgd = json.loads(sys.argv[1])
cfg = ConfigOptions.from_dict(cfgd)
sim = Simulation(cfg, world=1)
ck = os.path.join(cfg.general.data_directory, 'resume.npz')
if len(sys.argv) > 2 and sys.argv[2] == 'resume' and os.path.exists(ck):
    load_checkpoint(ck, sim)
rep = sim.run(log=sys.stderr)
out = {'digest': rep['determinism_digest'],
       'events': rep['events_processed']}
iv = rep.get('integrity')
if iv is not None:
    out['iv_transients'] = iv.get('transients', 0)
    out['iv_replays'] = iv.get('replays', 0)
    out['iv_aborted'] = bool(rep.get('integrity_aborted'))
    out['iv_deterministic'] = iv.get('deterministic')
    out['digest2'] = iv.get('determinism_digest2')
print(json.dumps(out))
"""


def scenario(data_dir: str, *, small: bool, sentinel: bool = False) -> dict:
    """A short faulty PHOLD run: host churn (hold), a lossy window, and
    the supervisor checkpointing every chunk so a kill at any point can
    resume close to where it died.

    Shape note: 12 hosts / capacity 32 deliberately avoids the 8-host /
    capacity-16 phold shape CHANGES.md PR 2 documents as this box's
    jaxlib-0.4.37 corruption kill zone (near-certain malloc_consolidate
    aborts AND silent device-memory scribbles — a scribbled worker writes
    a poisoned checkpoint, which no amount of resume exactness can
    launder back into the reference digest).

    `sentinel` arms the integrity sentinel (ISSUE 11): every round's
    invariant guards run in-jit, violations quarantine-and-replay, and
    the worker reports the transient/deterministic accounting."""
    integrity = {"integrity": {"enabled": True}} if sentinel else {}
    return {
        **integrity,
        "general": {
            "stop_time": "1.5 s" if small else "3 s",
            "seed": 1,
            "heartbeat_interval": None,
            "data_directory": data_dir,
        },
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 32, "rounds_per_chunk": 4},
        "faults": {
            "seed": 7,
            "restart_queue": "hold",
            "host_churn": {"prob": 0.4, "mean_downtime": "0.3 s"},
            "loss_windows": [
                {"start": "0.5 s", "end": "1.0 s", "loss": 0.25,
                 "latency_factor": 1.5}
            ],
            "supervisor": {"snapshot_every_chunks": 1,
                           "checkpoint_file": "resume.npz"},
        },
        "hosts": {
            "node": {
                "count": 12 if small else 24,
                "network_node_id": 0,
                "processes": [{
                    "model": "phold",
                    "model_args": {"population": 2, "mean_delay": "100 ms",
                                   "size_bytes": 64},
                }],
            }
        },
    }


def run_worker(cfg: dict, mode: str | None, kill_after_s: float | None,
               timeout: int):
    """One worker subprocess. Returns (rc, digest-dict | None). With
    `kill_after_s`, SIGKILL the worker at that delay (if still alive)."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        PYTHONPATH=os.pathsep.join([REPO, os.environ.get("PYTHONPATH", "")]),
    )
    argv = [sys.executable, "-c", WORKER, json.dumps(cfg)]
    if mode:
        argv.append(mode)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env, cwd=REPO,
    )
    killed = False
    timed_out = False
    if kill_after_s is not None:
        try:
            proc.wait(timeout=kill_after_s)
        except subprocess.TimeoutExpired:
            proc.send_signal(signal.SIGKILL)
            killed = True
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        timed_out = True
    result = None
    for line in (out or "").strip().splitlines()[::-1]:
        try:
            result = json.loads(line)
            break
        except ValueError:
            continue
    return proc.returncode, result, killed, timed_out


def _eff_timeout(timeout: int, deadline: float | None) -> int:
    """Clamp a per-worker timeout to the remaining wall budget: a worker
    launched near the deadline gets only what is left, so an iteration in
    flight can never outlive the budget by a full worker timeout (and get
    SIGKILLed unclassified by check_tier1.sh's outer `timeout`)."""
    if deadline is None:
        return timeout
    return max(1, min(timeout, int(deadline - time.monotonic())))


def run_iteration(cfg: dict, kill_after_s: float | None, timeout: int,
                  max_resumes: int = 5, deadline: float | None = None):
    """Run once; if killed (or it died on its own — the env's spontaneous
    aborts count), resume from the checkpoint until a digest comes out.

    Returns (result, killed, resumes, spontaneous): `spontaneous` counts
    worker deaths WE did not inject — on this box those are the known
    jaxlib heap-corruption aborts, which can scribble device state before
    crashing and thereby poison the checkpoint the next resume loads, so
    a digest verdict from such an iteration is not conclusive. With a
    `deadline`, every worker's timeout is clamped to the remaining budget
    and the resume loop stops at the deadline (the caller detects the
    truncation: result None + deadline passed)."""
    rc, result, killed, _ = run_worker(
        cfg, None, kill_after_s, _eff_timeout(timeout, deadline)
    )
    spontaneous = 0 if (killed or result is not None) else 1
    resumes = 0
    while result is None and resumes < max_resumes:
        if deadline is not None and time.monotonic() >= deadline:
            break
        resumes += 1
        rc, result, _, _ = run_worker(
            cfg, "resume", None, _eff_timeout(timeout, deadline)
        )
        if result is None:
            spontaneous += 1
    return result, killed, resumes, spontaneous


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iters", type=int, default=9)
    p.add_argument("--seed", type=int, default=1234,
                   help="seed for the kill schedule (NOT the sim seed)")
    p.add_argument("--smoke", action="store_true",
                   help="2-minute budget: 3 iterations, small sim")
    p.add_argument("--sentinel", action="store_true",
                   help="integrity-sentinel mode: guards on, no kill "
                        "injection; zero deterministic violations "
                        "asserted, transient SDC count reported")
    p.add_argument("--timeout", type=int, default=None,
                   help="per-worker timeout (default: 45 with --smoke, "
                        "else 300)")
    p.add_argument("--keep", action="store_true")
    args = p.parse_args(argv)
    iters = 3 if args.smoke else args.iters
    # smoke runs under check_tier1.sh's `timeout 150`: keep per-worker
    # timeouts small and enforce the budget OURSELVES (below) so a hung
    # worker degrades to a truncated-but-classified soak instead of an
    # outer SIGKILL turning tier-1 red with rc=124
    if args.timeout is None:
        args.timeout = 45 if args.smoke else 300
    budget_s = 120 if args.smoke else None
    # seeded numpy Generator — the house idiom for every tool draw
    # (shadowlint R1 bans stdlib `random` in tools/)
    rng = np.random.default_rng(args.seed)

    root = tempfile.mkdtemp(prefix="shadow_tpu_soak_")
    t0 = time.monotonic()
    deadline = (t0 + budget_s) if budget_s is not None else None
    try:
        # reference digest: MUST come from a single uninterrupted worker —
        # a resumed reference could inherit a poisoned checkpoint from a
        # corrupted-then-crashed first attempt (the known env scribble
        # mode) and silently bless the wrong digest for the whole soak
        ref = None
        env_spontaneous = 0  # spontaneous worker deaths across the soak
        ref_rcs = []
        for attempt in range(5):
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                print("soak: budget exhausted during reference attempts",
                      file=sys.stderr)
                break
            ref_dir = os.path.join(root, f"ref{attempt}")
            ref_cfg = scenario(
                ref_dir, small=args.smoke, sentinel=args.sentinel
            )
            rc, ref, _, timed_out = run_worker(
                ref_cfg, None, None, _eff_timeout(args.timeout, deadline)
            )
            if ref is not None and ref.get("iv_aborted"):
                # a reference that integrity-aborted is a truncated
                # last-good PREFIX, never a usable full-run digest —
                # and on this box a poisoned process's replay
                # classifier reproduces its own poisoning (observed:
                # same round-4 signature from independently poisoned
                # workers). Retry fresh.
                env_spontaneous += 1
                ref_rcs.append("iv-aborted")
                print(
                    f"soak: reference attempt {attempt} integrity-"
                    f"aborted ({ref.get('iv_deterministic')}) — "
                    f"poisoned worker; retrying fresh", file=sys.stderr,
                )
                ref = None
                continue
            if ref is not None and args.sentinel:
                # confirm the reference across a SECOND fresh worker
                # (sentinel mode only — the plain soak keeps its
                # single-reference budget): the documented silent
                # flavor can complete rc 0 with a scribbled digest, and
                # a poisoned reference would turn every healthy
                # iteration into a "mismatch" (observed on this box).
                # Two independently-agreeing workers pin it.
                eff2 = _eff_timeout(args.timeout, deadline)
                rc2, ref2, _, timed_out2 = run_worker(
                    ref_cfg, None, None, eff2,
                )
                if ref2 is not None and ref2["digest"] == ref["digest"]:
                    break
                env_spontaneous += 1
                ref_digest_0 = ref["digest"]
                ref = None
                if ref2 is None:
                    # the confirmation worker died/starved without a
                    # result: classify ITS death, never label a missing
                    # second opinion "unconfirmed". A timeout counts as
                    # the corruption's hang flavor ONLY when the worker
                    # had its full budget — a deadline-truncated kill is
                    # a budget condition, labeled so it can never demote
                    # a healthy-but-slow box into the corruption SKIP
                    if timed_out2 and eff2 < args.timeout:
                        ref_rcs.append("deadline-truncated")
                    else:
                        ref_rcs.append("timeout" if timed_out2 else rc2)
                    print(
                        f"soak: reference attempt {attempt} confirmation "
                        f"worker died (rc={rc2}, "
                        f"classified={ref_rcs[-1]}); retrying fresh",
                        file=sys.stderr,
                    )
                    continue
                print(
                    f"soak: reference attempt {attempt} unconfirmed "
                    f"({ref_digest_0} vs {ref2['digest']}) — the silent "
                    f"scribble flavor; retrying fresh", file=sys.stderr,
                )
                ref_rcs.append("unconfirmed")
                continue
            if ref is not None:
                break
            env_spontaneous += 1
            # a silent per-worker timeout is the hang flavor of the same
            # corruption (tests/subproc.py classifies it identically)
            ref_rcs.append("timeout" if timed_out else rc)
            print(f"soak: reference attempt {attempt} died (rc={rc}); "
                  f"retrying fresh", file=sys.stderr)
        if ref is None:
            if ref_rcs and all(
                rc in ("timeout", "unconfirmed", "iv-aborted")
                or classify(rc) is not None
                for rc in ref_rcs
            ):
                # every attempt died the documented corruption death: the
                # box cannot host this soak at all — skip (exit 0, loud),
                # exactly tests/subproc.py's policy for the same signature
                print(
                    "soak: SKIP — all reference attempts died with the "
                    f"known corruption signature (rcs {ref_rcs}; "
                    "CHANGES.md env notes); no verdict possible on this box"
                )
                return 0
            print("soak: no reference attempt completed uninterrupted "
                  f"(rcs {ref_rcs})", file=sys.stderr)
            return 1
        print(f"soak: reference digest {ref['digest']} "
              f"({ref['events']} events)")

        failures = 0
        inconclusive = 0
        completed = 0
        iv_transients_total = 0
        iv_deterministic = 0
        for i in range(iters):
            if budget_s is not None and time.monotonic() - t0 > budget_s:
                print(
                    f"soak: budget ({budget_s}s) exhausted after "
                    f"{completed}/{iters} iterations — stopping early "
                    "(verdict covers the completed prefix)"
                )
                break
            it_dir = os.path.join(root, f"it{i}")
            cfg = scenario(it_dir, small=args.smoke, sentinel=args.sentinel)
            # ~1/3 of iterations get a random mid-run SIGKILL; the
            # sentinel soak runs uninterrupted — it gates the in-jit
            # guards, not the kill-recovery path
            kill = (
                None if args.sentinel
                else rng.uniform(0.5, 3.0) if rng.random() < 1 / 3 else None
            )
            result, killed, resumes, spont = run_iteration(
                cfg, kill, args.timeout, deadline=deadline
            )
            ok = result is not None and result["digest"] == ref["digest"]
            # first-attempt evidence, captured ONLY when a fresh retry
            # actually runs: with the retry skipped (deadline), `result`
            # would still be the first attempt and a self-comparison
            # would fake a cross-worker reproduction from one observation
            first_bad = None
            first_iv_det = None
            if not ok and not (deadline is not None
                               and time.monotonic() >= deadline):
                if result is not None:
                    first_bad = result["digest"]
                    first_iv_det = result.get("iv_deterministic")
                # one fresh retry before judging (a one-off)
                shutil.rmtree(it_dir, ignore_errors=True)
                result, _, r2, s2 = run_iteration(
                    cfg, kill, args.timeout, deadline=deadline
                )
                resumes += r2
                spont += s2
                ok = result is not None and result["digest"] == ref["digest"]
            if (result is None and deadline is not None
                    and time.monotonic() >= deadline):
                # the budget ran out while THIS iteration was in flight:
                # a truncated iteration carries no verdict — stop without
                # judging it (judging would miscount it as a mismatch or
                # inflate the spontaneous-crash tally)
                print(
                    f"soak: budget ({budget_s}s) exhausted mid-iteration "
                    f"{i} — stopping early (verdict covers the completed "
                    "prefix)"
                )
                break
            env_spontaneous += spont
            completed += 1
            if args.sentinel and result is not None:
                # sentinel accounting: transients are SURVIVED events
                # (reported, not failed); a deterministic violation —
                # the engine reproducibly breaking its own invariant —
                # always fails, kills or no kills
                iv_transients_total += result.get("iv_transients", 0)
                if result.get("iv_aborted") or result.get(
                    "iv_deterministic"
                ):
                    det = result.get("iv_deterministic")
                    if first_iv_det is not None and first_iv_det == det:
                        # the violation reproduced with the SAME naming
                        # across two FRESH worker processes. On this box
                        # even that is only probabilistic evidence — the
                        # heap corruption favors the same allocation
                        # targets across independently poisoned
                        # processes (observed: identical round-4
                        # signatures) — so apply the PR 5 three-process
                        # rule: one more fresh iteration; all three
                        # agreeing = a real engine bug.
                        if deadline is not None and (
                            time.monotonic() >= deadline
                        ):
                            inconclusive += 1
                            print(
                                f"soak: iter {i}: integrity abort "
                                f"reproduced twice but the budget "
                                f"expired before the third worker — "
                                f"INCONCLUSIVE (truncated)"
                            )
                            continue
                        shutil.rmtree(it_dir, ignore_errors=True)
                        third, _, _, _ = run_iteration(
                            cfg, None, args.timeout, deadline=deadline
                        )
                        third_det = (
                            third.get("iv_deterministic")
                            if third is not None else None
                        )
                        if third_det == det:
                            iv_deterministic += 1
                            failures += 1
                            print(
                                f"soak: iter {i}: DETERMINISTIC "
                                f"INTEGRITY VIOLATION (reproduced "
                                f"across 3 fresh workers): {det}"
                            )
                            continue
                        env_spontaneous += 1
                        inconclusive += 1
                        print(
                            f"soak: iter {i}: integrity abort did not "
                            f"survive the third fresh worker "
                            f"({det} vs {third_det}) — the corruption's "
                            f"favored-target signature; INCONCLUSIVE "
                            f"(env SDC)"
                        )
                        continue
                    # a single worker's "deterministic" classification
                    # that a fresh worker did not reproduce: persistent
                    # IN-PROCESS poisoning (the replay classifier cannot
                    # see past its own heap) — env, inconclusive
                    env_spontaneous += 1
                    inconclusive += 1
                    print(
                        f"soak: iter {i}: integrity abort did not "
                        f"reproduce across fresh workers (first "
                        f"{first_iv_det}, retry {det}) — in-process "
                        f"poisoning; INCONCLUSIVE (env SDC)"
                    )
                    continue
                if not ok:
                    # digest mismatch with NO violation counted: classify
                    # it with the dual-digest lane (core/integrity.
                    # classify_digest_pair). A primary-only mismatch is
                    # a digest-plane scribble the dual lane CAUGHT —
                    # trajectory identical, attribution proven, demoted
                    # to INCONCLUSIVE. A DIVERGENT pair gets no such
                    # proof and falls through to the plain soak's
                    # mismatch judgment (fail, subject to the existing
                    # spontaneous-death env demotion) — the stage's
                    # advertised digest-exactness gate must not launder
                    # a reproducible determinism regression into "env"
                    from shadow_tpu.core.integrity import (
                        classify_digest_pair,
                    )

                    verdict = classify_digest_pair(
                        int(ref["digest"], 16),
                        int(ref["digest2"], 16) if ref.get("digest2")
                        else None,
                        int(result["digest"], 16),
                        int(result["digest2"], 16)
                        if result.get("digest2") else None,
                    )
                    if verdict == "digest-plane":
                        env_spontaneous += 1  # an SDC event, caught
                        inconclusive += 1
                        print(
                            f"soak: iter {i}: digest mismatch classified "
                            f"'digest-plane' by the dual-digest lane — "
                            f"primary digest plane scribbled, trajectory "
                            f"identical; INCONCLUSIVE (env SDC, caught)"
                        )
                        continue
                    if (
                        first_bad is not None
                        and result is not None
                        and result["digest"] != first_bad
                    ):
                        # two fresh workers mismatched with DIFFERENT
                        # wrong digests: the documented varying-scribble
                        # signature (the PR 5 classification rule), not
                        # a reproducible regression — inconclusive
                        env_spontaneous += 1
                        inconclusive += 1
                        print(
                            f"soak: iter {i}: mismatch varied across "
                            f"fresh workers ({first_bad} then "
                            f"{result['digest']}) — the documented "
                            f"silent scribble; INCONCLUSIVE (env SDC, "
                            f"uncaught by the invariant set)"
                        )
                        continue
                    repro_note = (
                        "REPRODUCED identically across fresh workers"
                        if first_bad is not None
                        and result["digest"] == first_bad
                        else "single observation (no fresh retry ran)"
                    )
                    print(
                        f"soak: iter {i}: digest mismatch classified "
                        f"'{verdict}' by the dual-digest lane, "
                        f"{repro_note} — judged like the plain soak's "
                        f"mismatches"
                    )
            if ok:
                status = "ok"
            elif spont > 0:
                # a worker died a death we did NOT inject: the known env
                # corruption scribbles device state before aborting, so
                # the checkpoint the resume loaded may be poisoned — the
                # verdict says nothing about the recovery path itself
                status = ("INCONCLUSIVE (spontaneous worker crash; env "
                          "corruption can poison pre-crash checkpoints — "
                          "CHANGES.md env notes)")
                inconclusive += 1
            else:
                status = "DIGEST MISMATCH"
                failures += 1
            print(
                f"soak: iter {i}: killed={bool(killed)} resumes={resumes} "
                f"spontaneous_crashes={spont} "
                f"digest={result['digest'] if result else None} {status}"
            )
        wall = time.monotonic() - t0
        print(
            f"soak: {completed - failures - inconclusive}/{completed} "
            f"digest-exact (of {iters} planned), "
            f"{inconclusive} inconclusive (env), {failures} failed "
            f"in {wall:.0f}s"
        )
        if args.sentinel:
            # the sentinel verdict: every round's invariants held (or
            # the transients were quarantined, replayed, and survived)
            print(
                f"soak: sentinel verdict — {iv_deterministic} "
                f"deterministic violation(s), {iv_transients_total} "
                f"transient SDC event(s) survived across "
                f"{completed} iterations"
            )
        if iv_deterministic:
            # a violation that reproduced across three fresh workers —
            # the one outcome the sentinel stage exists to fail on; it
            # must never launder into the env demotion below. On a box
            # in a DEEP corruption wave (env SDC also observed this
            # soak) even three fresh processes can all be poisoned at
            # the corruption's favored target, so name the caveat — but
            # stay red: only a healthy-box rerun can clear it.
            if env_spontaneous:
                print(
                    f"soak: NOTE — the deterministic verdict was reached "
                    f"during an active corruption wave "
                    f"({env_spontaneous} env SDC events this soak); "
                    f"re-run on a healthy box to confirm "
                    f"(docs/corruption.md)"
                )
            return 1
        if failures and env_spontaneous:
            # the box demonstrably corrupts workers (spontaneous deaths
            # seen this soak): even SIGKILL-only iterations may have been
            # scribbled before our kill landed, so the failures cannot be
            # attributed to the recovery path. Loud, not fatal — a clean
            # box keeps the strict exit below.
            print(
                f"soak: WARNING — {failures} mismatch(es) on an "
                f"env-compromised box ({env_spontaneous} spontaneous "
                f"worker deaths); verdict SUSPECT, not failing. Re-run on "
                f"a healthy box to gate."
            )
            return 0
        return 1 if failures else 0
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
