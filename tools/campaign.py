#!/usr/bin/env python3
"""Campaign driver: run R replicas in one vmapped program, ledger + bisect.

The ensemble plane's front door (core/ensemble.py is the engine half).
A `campaign:` config block declares sweep axes — seed lists/ranges,
fault-schedule lists, config-override pairs — and this driver:

  1. expands the cross product into replica configs (dict-level, so an
     override can reach anything in the YAML: model args, bandwidths,
     fault parameters — anything that only changes array VALUES; a delta
     that changes an EngineConfig static is rejected loudly at build);
  2. builds each replica exactly as a solo run would (`Simulation`),
     reconciles fault statics, stacks the states/params, and advances
     ALL replicas one chunk per dispatch through the vmapped engine —
     under the existing crash-resilient supervisor when configured
     (replica-axis-aware snapshots + on-disk ensemble checkpoints);
  3. writes a per-replica DIGEST LEDGER: final per-replica counters and
     digests, per-chunk xor digest signatures, and per-replica trace
     totals when the round tracer is on;
  4. checks every `expect_identical` pair on the full per-host digest
     arrays, and on a divergence BISECTS over chunk boundaries (device
     snapshot + deterministic replay, core/ensemble.bisect_divergence)
     to pinpoint the first divergent chunk.

Usage:
    python tools/campaign.py CONFIG.yaml [-o LEDGER.json] [--resume]
    python tools/campaign.py --smoke     # self-checking tiny campaign
                                         # (TIER1_CAMPAIGN=1 stage)
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from shadow_tpu.config.options import ConfigError, ConfigOptions  # noqa: E402


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """One expanded replica: its axis coordinates and config deltas."""

    index: int
    label: str
    seed: int
    faults: dict | None  # raw faults block; None = base config's
    overrides: dict  # dotted config-dict paths -> values

    def meta(self) -> dict:
        return {
            "index": self.index,
            "label": self.label,
            "seed": self.seed,
            "faults": self.faults,
            "overrides": {k: str(v) for k, v in self.overrides.items()},
        }


def expand_replicas(cfg: ConfigOptions) -> list[ReplicaSpec]:
    """Cross product of the campaign axes, in (seed, fault_schedule,
    override) nesting order — the index formula documented on
    CampaignOptions so ledger rows and expect_identical pairs are stable."""
    camp = cfg.campaign
    if not camp.active:
        raise ConfigError(
            "campaign: no sweep axes declared (seeds / fault_schedules / "
            "overrides)"
        )
    seeds = camp.seeds or [cfg.general.seed]
    scheds: list = camp.fault_schedules or [None]
    ovs = camp.overrides or [{}]
    specs: list[ReplicaSpec] = []
    for si, seed in enumerate(seeds):
        for fi, sched in enumerate(scheds):
            for oi, ov in enumerate(ovs):
                parts = [f"seed={seed}"]
                if camp.fault_schedules:
                    parts.append(f"faults={fi}")
                if camp.overrides:
                    parts.append(f"ov={oi}")
                specs.append(
                    ReplicaSpec(
                        index=len(specs),
                        label=",".join(parts),
                        seed=int(seed),
                        faults=sched,
                        overrides=dict(ov),
                    )
                )
    return specs


def _apply_dict_override(d: dict, dotted: str, value):
    """Set a dotted path inside the raw config mapping; integer segments
    index into lists (e.g. hosts.node.processes.0.model_args.mean_delay)."""
    parts = dotted.split(".")
    cur = d
    for p in parts[:-1]:
        if isinstance(cur, list):
            cur = cur[int(p)]
        else:
            if p not in cur or cur[p] is None:
                cur[p] = {}
            cur = cur[p]
    leaf = parts[-1]
    if isinstance(cur, list):
        cur[int(leaf)] = value
    else:
        cur[leaf] = value


def replica_config_dict(base: dict, spec: ReplicaSpec) -> dict:
    """One replica's raw config mapping: base + seed + fault schedule +
    overrides. The per-replica faults block keeps ONLY the injection
    fields — the supervisor is a campaign-level concern read from the
    base block by the driver, never per replica."""
    d = copy.deepcopy(base)
    d.setdefault("general", {})["seed"] = spec.seed
    if spec.faults is not None:
        d["faults"] = copy.deepcopy(spec.faults)
    d.pop("campaign", None)  # replicas are solo configs
    for k, v in spec.overrides.items():
        try:
            _apply_dict_override(d, k, v)
        except (KeyError, IndexError, ValueError, TypeError) as e:
            raise ConfigError(
                f"campaign override {k!r} does not resolve in the config: {e}"
            ) from e
    return d


class Campaign:
    """Built campaign: the vmapped ensemble plus everything the run loop
    and ledger need. Build via `build_campaign(config_dict)`."""

    def __init__(
        self, base_cfg: ConfigOptions, base_dict: dict,
        capacity_bytes: int | None = None,
    ):
        from shadow_tpu.core.ensemble import build_ensemble
        from shadow_tpu.sim import Simulation, config_is_hybrid

        self.cfg = base_cfg
        camp = base_cfg.campaign
        if config_is_hybrid(base_cfg):
            raise ConfigError(
                "campaign: hybrid (managed-process) simulations cannot "
                "vmap — the CPU plane is one real process per host"
            )
        if base_cfg.experimental.scheduler != "tpu":
            raise ConfigError(
                "campaign: requires the tpu scheduler (the cpu-reference "
                "oracle runs one replica at a time by design)"
            )
        if base_cfg.general.parallelism > 1:
            raise ConfigError(
                "campaign: the ensemble plane runs world=1 this round "
                "(a replica axis over a device mesh is a 2-D mesh "
                "program); set general.parallelism to 1 or shard the "
                "campaign across processes"
            )
        if base_cfg.pressure.active:
            raise ConfigError(
                "campaign: pressure escalate/abort are not supported with "
                "the ensemble plane this round (a capacity migration "
                "would have to re-seat every replica's slab mid-campaign);"
                " keep pressure: drop and size replica capacities up front"
            )
        if base_cfg.experimental.merge_gears:
            raise ConfigError(
                "campaign: experimental.merge_gears is not supported with "
                "the ensemble plane this round (gear replay would need "
                "per-replica shed tracking across the vmap)"
            )
        if base_cfg.integrity.enabled:
            raise ConfigError(
                "campaign: the integrity sentinel is not supported with "
                "the ensemble plane this round (the quarantine-and-replay "
                "classifier would need per-replica violation signatures "
                "across the vmap); disable the integrity block or run the "
                "scenarios solo"
            )
        self.specs = expand_replicas(base_cfg)
        sims: list[Simulation] = []
        for spec in self.specs:
            rcfg = ConfigOptions.from_dict(replica_config_dict(base_dict, spec))
            sims.append(Simulation(rcfg, world=1))
        if any(h.pcap_enabled for s in sims for h in s.hosts):
            raise ConfigError(
                "campaign: pcap capture is not supported on ensemble runs "
                "(the capture path dispatches single un-vmapped rounds)"
            )
        self.num_real = sims[0]._num_real
        self.model = sims[0].model
        self.rounds_per_chunk = sims[0].engine_cfg.rounds_per_chunk
        # memory-informed replica guard (obs/memory.py): R x the
        # per-replica state bytes (exact metadata accounting of the solo
        # state — every state plane is stacked R times) plus the shared
        # broadcast params must fit the device. This replaces the old
        # comment-only HBM rationale on campaign.max_replicas with
        # predicted numbers; the parse-time replica-COUNT cap stays as
        # the cheap first line. `capacity_bytes` overrides the probed
        # device capacity (tests inject small fakes); None + no
        # measurable capacity skips the check (nothing to size against).
        from shadow_tpu.obs.memory import device_capacity_bytes, tree_bytes

        per_replica = tree_bytes(sims[0].state)
        shared = tree_bytes(sims[0].params)
        predicted = per_replica * len(self.specs) + shared
        if capacity_bytes is None:
            capacity_bytes = device_capacity_bytes()
        self.predicted_bytes = predicted
        self.per_replica_bytes = per_replica
        if capacity_bytes is not None and predicted > capacity_bytes:
            raise ConfigError(
                f"campaign: {len(self.specs)} replicas need a predicted "
                f"{predicted} bytes of device memory ({len(self.specs)} x "
                f"{per_replica} per-replica state + {shared} shared "
                f"params), over the device capacity {capacity_bytes} "
                f"bytes — shard the campaign across processes or shrink "
                f"the replica axes (static model: shadow_tpu/obs/memory.py)"
            )
        self.engine, self.state = build_ensemble(
            self.model,
            [(s.engine.cfg, s.state, s.params) for s in sims],
        )
        self.num_replicas = len(self.specs)
        # the per-replica Simulations are scaffolding: their engines are
        # never dispatched (the vmapped program is), so let them go
        del sims

    def fingerprint(self) -> str:
        from shadow_tpu.core.checkpoint import ensemble_fingerprint

        return ensemble_fingerprint(
            self.engine.cfg,
            self.state,
            self.engine._params,
            [s.meta() for s in self.specs],
        )


def build_campaign(
    config_dict: dict, capacity_bytes: int | None = None
) -> Campaign:
    return Campaign(
        ConfigOptions.from_dict(config_dict), config_dict,
        capacity_bytes=capacity_bytes,
    )


def run_campaign(
    config_dict: dict,
    *,
    log=sys.stderr,
    ledger_path: str | None = None,
    resume: bool = False,
    wall_budget_s: float | None = None,
) -> dict:
    """Build + run a campaign; returns (and writes) the digest ledger."""
    import jax
    import numpy as np

    from shadow_tpu.core.checkpoint import (
        load_ensemble_checkpoint,
        save_ensemble_checkpoint,
        snapshot_state,
    )
    from shadow_tpu.core.ensemble import (
        bisect_divergence,
        pair_digests_equal,
        replica_digest_sigs,
        replica_ledger,
    )
    from shadow_tpu.core.supervisor import ChunkSupervisor, SupervisorAbort
    from shadow_tpu.sim import heartbeat_line

    camp_t0 = time.monotonic()
    c = build_campaign(config_dict)
    cfg, camp = c.cfg, c.cfg.campaign
    state = c.state
    ens = c.engine
    r_count = c.num_replicas
    print(
        f"[campaign] {r_count} replicas x {cfg.general.stop_time / 1e9:.3f} "
        f"sim-s, rounds_per_chunk={c.rounds_per_chunk}",
        file=log,
    )

    # supervisor (campaign-level, from the BASE faults block): the same
    # snapshot/retry/abort machinery the solo driver runs — snapshots are
    # plain pytree copies, so the replica axis rides along for free, and
    # the on-disk checkpoint goes through the ensemble-guarded writer
    sup = None
    ckpt_path = None
    fingerprint = None
    so = cfg.faults.supervisor
    if so.enabled:
        fingerprint = c.fingerprint()
        ckpt_path = so.checkpoint_file
        if ckpt_path is not None:
            if not os.path.isabs(ckpt_path):
                ckpt_path = os.path.join(
                    cfg.general.data_directory, ckpt_path
                )
            os.makedirs(os.path.dirname(ckpt_path) or ".", exist_ok=True)
        sup = ChunkSupervisor(
            snapshot_every_chunks=so.snapshot_every_chunks,
            max_retries=so.max_retries,
            backoff_base_s=so.backoff_base_ms / 1000.0,
            checkpoint_path=ckpt_path,
            save_fn=(
                (lambda path, snap: save_ensemble_checkpoint(
                    path, snap, fingerprint
                ))
                if ckpt_path
                else None
            ),
            log=log,
        )
    if resume:
        want = ckpt_path if ckpt_path else None
        if want is None or not os.path.exists(
            want if want.endswith(".npz") else want + ".npz"
        ):
            raise ConfigError(
                "campaign --resume: no ensemble checkpoint found (set "
                "faults.supervisor.checkpoint_file and run once first)"
            )
        real = want if want.endswith(".npz") else want + ".npz"
        state = load_ensemble_checkpoint(
            real, state, fingerprint or c.fingerprint()
        )
        print(f"[campaign] resumed from {real}", file=log)
    if sup is not None:
        sup.note_state(state)

    tracer = None
    if getattr(state, "trace", None) is not None:
        from shadow_tpu.obs.tracer import ReplicaTracer

        tracer = ReplicaTracer(c.rounds_per_chunk, r_count)
        tracer.sync_cursor(state.trace)

    # pre-run snapshot for divergence bisection: chunk 0 of the replay
    # search. Taken only when a divergence could actually be bisected.
    snap0 = None
    if camp.bisect and camp.expect_identical:
        snap0 = snapshot_state(state)

    hb_ns = cfg.general.heartbeat_interval
    next_hb = hb_ns or 0
    chunk_sigs: list[list[str]] = []
    chunks = 0
    aborted = False
    truncated = False
    t0 = time.monotonic()
    while not bool(np.asarray(jax.device_get(state.done)).all()):
        if sup is not None:
            try:
                state = sup.run_chunk(state, ens.run_chunk)
            except SupervisorAbort as e:
                print(f"[campaign] aborting run: {e}", file=log)
                good = sup.abort_export_state()
                if good is not None:
                    state = good
                aborted = True
                break
        else:
            state = ens.run_chunk(state)
        jax.block_until_ready(state)
        # chunk index from the STATE, not the dispatch count: a
        # supervisor recovery may hand back a state rewound to a snapshot
        # several chunks old, and the replayed chunks must overwrite
        # their original (deterministically identical) ledger entries
        # instead of appending shifted duplicates. An unfinished replica
        # retires exactly rounds_per_chunk rounds per chunk, so the
        # most-advanced replica's ceil(rounds / rpc) IS the chunk index.
        rmax = int(np.asarray(jax.device_get(state.stats.rounds)).max())
        chunks = -(-rmax // c.rounds_per_chunk)
        if tracer is not None:
            tracer.drain(state.trace)
        # per-chunk ledger entry: one xor digest signature per replica
        # (cheap summary; the end-of-run pair checks and the bisection
        # both use the full per-host arrays)
        sigs = [
            f"{int(s):016x}" for s in replica_digest_sigs(state, c.num_real)
        ]
        if chunks > len(chunk_sigs):
            chunk_sigs.append(sigs)
        elif chunks:
            chunk_sigs[chunks - 1] = sigs
        if hb_ns:
            now_v = np.asarray(jax.device_get(state.now))
            done_v = np.asarray(jax.device_get(state.done))
            active = now_v[~done_v]
            now_ns = int(active.min() if active.size else now_v.max())
            if now_ns >= next_hb:
                s = jax.device_get(state.stats)
                ev = int(np.asarray(s.events).sum())
                msteps = int(np.asarray(s.microsteps).sum())
                rounds = int(np.asarray(s.rounds).sum())
                fault = None
                if ens.cfg.faults_active:
                    fault = (
                        int(np.asarray(s.faults_dropped).sum()),
                        int(np.asarray(s.faults_delayed).sum()),
                    )
                print(
                    heartbeat_line(
                        now_ns, time.monotonic() - t0, ev, msteps, rounds,
                        int(np.asarray(s.ici_bytes).sum()),
                        int(np.asarray(s.q_occ_hwm).max()),
                        fault=fault,
                        rep=(int(done_v.sum()), r_count),
                    ),
                    file=log,
                )
                next_hb = (now_ns // hb_ns + 1) * hb_ns
        if wall_budget_s is not None and time.monotonic() - t0 > wall_budget_s:
            print("[campaign] wall budget exhausted, stopping", file=log)
            truncated = True
            break
    wall = time.monotonic() - t0

    # ---- ledger ------------------------------------------------------------
    # recompute the chunk index from the EXPORTED state: an abort adopts
    # a snapshot rewound behind the loop's last successful dispatch, so
    # the loop-carried value can overshoot it — then drop sig entries
    # past that chunk (they came from the pre-rewind attempt)
    rmax = int(np.asarray(jax.device_get(state.stats.rounds)).max())
    chunks = -(-rmax // c.rounds_per_chunk)
    chunk_sigs = chunk_sigs[:chunks]
    rows = replica_ledger(
        state, c.num_real, labels=[s.label for s in c.specs]
    )
    if tracer is not None and not aborted:
        # on abort the exported state rewound to the last good snapshot,
        # but chunks drained after it already fed the running totals —
        # ReplicaTracer keeps sums, not rows, so (unlike the solo
        # drivers' RoundTracer.truncate_to_round reconciliation) the
        # overcount cannot be trimmed; omit the trace block rather than
        # ship totals that disagree with the exported counters
        for row, tr in zip(rows, tracer.replica_totals()):
            row["trace"] = tr
    identical, inconclusive, divergences = [], [], []
    if not aborted:
        for pair in camp.expect_identical:
            pair_t = (int(pair[0]), int(pair[1]))
            if pair_digests_equal(state, pair_t, c.num_real):
                # equal digests on a budget-truncated PREFIX prove
                # nothing about the full run — a later-chunk divergence
                # would be missed, so report the pair inconclusive
                # rather than verified-identical. (A divergence on a
                # prefix IS conclusive; those still bisect below.)
                (inconclusive if truncated else identical).append(
                    list(pair_t)
                )
                continue
            entry = {"pair": list(pair_t), "first_divergent_chunk": None}
            if camp.bisect and snap0 is not None:
                if not pair_digests_equal(snap0, pair_t, c.num_real):
                    # resumed run whose pair diverged before the
                    # checkpoint: chunk 0 of the replay search is already
                    # divergent, so there is nothing to bisect — report
                    # that instead of tripping bisect_divergence's
                    # precondition and losing the whole ledger
                    entry["divergent_at_start"] = True
                else:
                    entry["first_divergent_chunk"] = bisect_divergence(
                        ens.run_chunk, snap0, pair_t,
                        hi=chunks, num_real=c.num_real, log=log,
                    )
                    if resume:
                        # chunk indices count from the resume point, not
                        # the campaign's chunk 1
                        entry["relative_to_resume"] = True
            divergences.append(entry)
    ledger = {
        "campaign": {
            "replicas": r_count,
            "labels": [s.label for s in c.specs],
            "seeds": [s.seed for s in c.specs],
            "chunks": chunks,
            "rounds_per_chunk": c.rounds_per_chunk,
            "num_hosts": c.num_real,
            "wall_seconds": round(wall, 4),
            "build_seconds": round(t0 - camp_t0, 4),
            **({"aborted": True} if aborted else {}),
            **({"truncated": True} if truncated else {}),
            **({"supervisor": sup.report()} if sup is not None else {}),
        },
        "replicas": rows,
        "chunk_digest_sigs": chunk_sigs,
        "expect_identical": [list(p) for p in camp.expect_identical],
        "identical": identical,
        **({"inconclusive": inconclusive} if inconclusive else {}),
        "divergences": divergences,
    }
    path = ledger_path
    if path is None and camp.ledger_file:
        os.makedirs(cfg.general.data_directory, exist_ok=True)
        path = os.path.join(cfg.general.data_directory, camp.ledger_file)
    if path:
        with open(path, "w") as f:
            json.dump(ledger, f, indent=2)
        print(f"[campaign] ledger written: {path}", file=log)
    return ledger


# ---------------------------------------------------------------- smoke


_SMOKE_GML = """
graph [
  directed 0
  node [ id 0 host_bandwidth_down "1 Gbit" host_bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def _smoke_base(tmp: str) -> dict:
    # the 50 ms self-loop keeps windows at PHOLD's reference lookahead:
    # 2 sim-s = ~40 rounds = ~5 chunks of 8 — enough chunks for the
    # bisection to genuinely bisect, small enough to stay seconds-scale
    return {
        "general": {"stop_time": "2 s", "seed": 1,
                    "heartbeat_interval": "1 s",
                    "data_directory": tmp},
        "network": {"graph": {"type": "gml", "inline": _SMOKE_GML}},
        "experimental": {"event_queue_capacity": 16,
                         "sends_per_host_round": 4,
                         "rounds_per_chunk": 8},
        "hosts": {
            "node": {
                "count": 8,
                "network_node_id": 0,
                "processes": [{
                    "model": "phold",
                    "model_args": {"population": 2, "mean_delay": "200 ms",
                                   "size_bytes": 64},
                }],
            }
        },
    }


def _smoke_worker(tmp: str) -> dict:
    """The in-process smoke body: an A/A control campaign (pair must hold
    + replica 0 must equal its solo run) and a forced-divergence A/B
    campaign (bisection must land on the linear-scan ground truth)."""
    import numpy as np

    # 1) seed sweep with an A/A control pair
    base = _smoke_base(tmp)
    base["campaign"] = {
        "seeds": [1, 1, 2],
        "expect_identical": [[0, 1], [0, 2]],
        "ledger_file": None,
    }
    led = run_campaign(base, ledger_path=os.path.join(tmp, "aa.json"))
    ok_control = [0, 1] in led["identical"]
    # seeds 1 vs 2 all but surely diverge; the bisected chunk must be a
    # real chunk index when they do
    div = {tuple(d["pair"]): d for d in led["divergences"]}
    ok_seed_div = (
        [0, 2] in led["identical"]
        or (0, 2) in div
        and 1 <= (div[(0, 2)]["first_divergent_chunk"] or 0)
        <= led["campaign"]["chunks"]
    )
    # replica 0 of the vmapped run vs its solo run. The solo Simulation
    # loop is this box's corruption magnet and the scribble can complete
    # WITHOUT crashing, leaving wrong dynamics — rounds are deterministic,
    # so a round-count mismatch means the CONTROL is poisoned, not the
    # ensemble (tests/test_ensemble.py's harness-built gates are the real
    # exactness proof); equal rounds with a differing digest is the real
    # failure this check exists for.
    from shadow_tpu.sim import Simulation

    solo_dict = replica_config_dict(base, expand_replicas(
        ConfigOptions.from_dict(base))[0])
    solo = Simulation(ConfigOptions.from_dict(solo_dict), world=1)
    while not bool(solo.state.done):
        solo.state = solo.engine.run_chunk(solo.state, solo.params)
    solo_poisoned = (
        int(solo.state.stats.rounds) != led["replicas"][0]["rounds"]
    )
    ok_solo = solo_poisoned or led["replicas"][0]["digest"] == (
        f"{int(np.bitwise_xor.reduce(solo.host_digests())):016x}"
    )

    # 2) forced divergence: same seed, two crash schedules differing at
    # 0.6 s — the pair must diverge and the bisection must agree with a
    # linear chunk-by-chunk scan
    ab = _smoke_base(tmp)
    ab["campaign"] = {
        "seeds": [1],
        "fault_schedules": [
            {"crashes": [{"host": 1, "down_at": "0.6 s", "up_at": "0.9 s"}]},
            {"crashes": [{"host": 1, "down_at": "1.4 s", "up_at": "1.7 s"}]},
        ],
        "expect_identical": [[0, 1]],
        "ledger_file": None,
    }
    led2 = run_campaign(ab, ledger_path=os.path.join(tmp, "ab.json"))
    div2 = {tuple(d["pair"]): d for d in led2["divergences"]}
    got = div2.get((0, 1), {}).get("first_divergent_chunk")
    # ground truth from the per-chunk xor signatures the ledger already
    # carries (full-array bisection must agree with the summary scan)
    truth = next(
        (i + 1 for i, sigs in enumerate(led2["chunk_digest_sigs"])
         if sigs[0] != sigs[1]),
        None,
    )
    return {
        "control_pair_identical": ok_control,
        "seed_pair_checked": bool(ok_seed_div),
        "replica0_matches_solo": bool(ok_solo),
        "solo_control_poisoned": bool(solo_poisoned),
        "forced_divergence_chunk": got,
        "linear_scan_chunk": truth,
        "bisect_matches_scan": got is not None and got == truth,
        "ok": bool(
            ok_control and ok_seed_div and ok_solo
            and got is not None and got == truth
        ),
    }


def smoke(timeout_s: float = 300.0) -> int:
    """Subprocess-isolated smoke (the TIER1_CAMPAIGN=1 stage): run the
    worker in a child so this box's documented jaxlib-0.4.37 compiled-run
    corruption (CHANGES.md env notes) can never take the caller down —
    corruption signatures classify as SKIP (rc 0, loudly), like
    tools/soak.py."""
    import subprocess
    import tempfile

    from tools.corruption import HEAP_CORRUPTION_RCS as corruption_rcs
    with tempfile.TemporaryDirectory() as tmp:
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--smoke-worker", tmp,
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=timeout_s,
                env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=_REPO,
            )
        except subprocess.TimeoutExpired:
            print("CAMPAIGN SMOKE: TIMEOUT (worker hung)", file=sys.stderr)
            return 1
    if proc.returncode in corruption_rcs and not proc.stdout.strip():
        print(
            "CAMPAIGN SMOKE: SKIP — worker died of the known "
            f"jaxlib-0.4.37 corruption signature (rc={proc.returncode}); "
            "no verdict",
            file=sys.stderr,
        )
        return 0
    if proc.returncode != 0:
        print(proc.stdout, file=sys.stderr)
        print(proc.stderr, file=sys.stderr)
        print(f"CAMPAIGN SMOKE: FAIL rc={proc.returncode}", file=sys.stderr)
        return 1
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    print(json.dumps(result))
    if not result.get("ok"):
        print("CAMPAIGN SMOKE: FAIL (self-check)", file=sys.stderr)
        return 1
    print("CAMPAIGN SMOKE: OK", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("config", nargs="?", help="YAML config with a campaign: block")
    p.add_argument("-o", "--output", help="ledger path (default: "
                   "data_directory/campaign.ledger_file)")
    p.add_argument("--resume", action="store_true",
                   help="resume from the supervisor's ensemble checkpoint")
    p.add_argument("--wall-budget", type=float, default=None,
                   help="stop after this many wall seconds (partial ledger)")
    p.add_argument("--smoke", action="store_true",
                   help="run the self-checking tiny campaign (CI stage)")
    p.add_argument("--smoke-worker", metavar="TMPDIR",
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.smoke_worker:
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(_smoke_worker(args.smoke_worker)))
        return 0
    if args.smoke:
        return smoke()
    if not args.config:
        p.error("a config file (or --smoke) is required")
    import yaml

    with open(args.config) as f:
        config_dict = yaml.safe_load(f)
    if not isinstance(config_dict, dict):
        raise ConfigError("config must be a YAML mapping")
    ledger = run_campaign(
        config_dict,
        ledger_path=args.output,
        resume=args.resume,
        wall_budget_s=args.wall_budget,
    )
    # compact stdout summary (the full ledger is on disk)
    print(json.dumps({
        "replicas": ledger["campaign"]["replicas"],
        "chunks": ledger["campaign"]["chunks"],
        "wall_seconds": ledger["campaign"]["wall_seconds"],
        "digests": [r["digest"] for r in ledger["replicas"]],
        "identical": ledger["identical"],
        "divergences": ledger["divergences"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
