#!/usr/bin/env python3
"""Parse a simulation data directory (or driver log) into one summary JSON.

Reference: `src/tools/parse-shadow.py` — parses Shadow's log + data dir
into a json blob for plotting. Inputs here: the data dir written by
`shadow_tpu` (sim-stats.json, hosts/<name>/host-stats.json, *.stdout) and
optionally a stderr log with `[heartbeat] ...` lines.

Usage: parse_shadow.py DATA_DIR [--log run.stderr] [-o out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

HEARTBEAT_RE = re.compile(
    r"\[heartbeat\] sim_time=(?P<sim>[\d.]+)s wall=(?P<wall>[\d.]+)s "
    r"(?:events=(?P<events>\d+) )?(?:rounds=(?P<rounds>\d+) |windows=(?P<windows>\d+) )?"
    r"(?:msteps/round=(?P<msteps_per_round>[\d.]+) )?"
    r"(?:ev/mstep=(?P<ev_per_mstep>[\d.]+) )?"
    # PR 3 observability fields; optional so pre-PR-3 logs still parse
    r"(?:ici_bytes=(?P<ici_bytes>\d+) )?"
    r"(?:q_hwm=(?P<q_hwm>\d+) )?"
    # PR 17 hierarchical-exchange field (only emitted on
    # experimental.exchange: hierarchical multi-device runs):
    # xw=<intra>/<inter>, cumulative tier bytes — intra-shard compaction
    # staging vs inter-shard wire (stats.ici_intra / stats.ici_inter)
    r"(?:xw=(?P<xw_intra>\d+)/(?P<xw_inter>\d+) )?"
    # PR 5 fault-plane field (only emitted on faulty runs):
    # faults=<dropped>/<delayed>, cumulative
    r"(?:faults=(?P<faults_dropped>\d+)/(?P<faults_delayed>\d+) )?"
    # PR 4 adaptive-exchange field (only emitted on merge_gears runs)
    r"(?:gear=(?P<gear>\d+) )?"
    # PR 8 pressure-plane field (only emitted on pressure runs): the
    # ACTIVE per-host queue capacity (escalation regrows it mid-run)
    r"(?:cap=(?P<cap>\d+) )?"
    # PR 9 memory-observatory field (only emitted when
    # observability.memory is on): per-shard HBM high-water, bytes
    r"(?:hbm=(?P<hbm>\d+) )?"
    # PR 10 network-observatory fields (only emitted when
    # observability.network is on): ek=<timer events>/<packet events>
    # cumulative; fct=<flows completed> (flow-ledger runs only)
    r"(?:ek=(?P<ek_timer>\d+)/(?P<ek_pkt>\d+) )?"
    r"(?:fct=(?P<fct_done>\d+) )?"
    # PR 13 fluid-traffic-plane field (only emitted when the `fluid:`
    # block declares classes): bg=<background bytes delivered>/<dropped>,
    # cumulative
    r"(?:bg=(?P<bg_bytes>\d+)/(?P<bg_dropped>\d+) )?"
    # PR 11 integrity-sentinel field (only emitted when the `integrity:`
    # block is enabled): iv=<transient SDC survived>/<sentinel replays>,
    # cumulative
    r"(?:iv=(?P<iv_transient>\d+)/(?P<iv_replays>\d+) )?"
    # PR 14 runtime-observatory field (only emitted when
    # observability.runtime is on): rt=<realtime factor> — the LAST
    # chunk's (or cosim window's) sim-s per wall-s, fresh per-chunk
    # rather than the run-cumulative ratio= at the line's end
    r"(?:rt=(?P<rt>[\d.]+) )?"
    # PR 6 ensemble-campaign field (only emitted by tools/campaign.py):
    # rep=<replicas done>/<total replicas>
    r"(?:rep=(?P<rep_done>\d+)/(?P<rep_total>\d+) )?"
    r"ratio=(?P<ratio>[\d.]+)x"
    r"(?: rss_gib=(?P<rss_gib>[\d.]+))?"
    r"(?: utime_min=(?P<utime_min>[\d.]+))?"
    r"(?: stime_min=(?P<stime_min>[\d.]+))?"
    r"(?: mem_avail_gib=(?P<mem_avail_gib>[\d.]+))?"
)


class HeartbeatParseError(ValueError):
    """A `[heartbeat]` line the format regex could not match (strict mode)."""


def parse_heartbeats(path: str, strict: bool = False) -> list[dict]:
    """Parse `[heartbeat]` lines from a driver log.

    Default mode skips unmatched lines silently (logs interleave arbitrary
    stderr). `strict=True` raises HeartbeatParseError on any line that
    CONTAINS the `[heartbeat]` marker but fails the format regex — the
    mode the format-compat gates use (shadowlint R5's runtime cross-check
    and the literal-line tests): a silently skipped heartbeat is exactly
    how a format drift between emitter and parser would hide."""
    out = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            m = HEARTBEAT_RE.search(line)
            if m:
                d = {k: v for k, v in m.groupdict().items() if v is not None}
                out.append(
                    {k: float(v) if "." in v else int(v) for k, v in d.items()}
                )
                # every field up to `ratio=` is position-anchored, so an
                # unknown field there fails the whole match — but a field
                # appended AFTER the matched span would be dropped without
                # a trace. Strict mode refuses that too.
                if strict and re.search(
                    r"[A-Za-z_][A-Za-z0-9_/]*=", line[m.end():]
                ):
                    raise HeartbeatParseError(
                        f"{path}:{lineno}: heartbeat line carries fields "
                        f"past the parsed span ({line[m.end():].strip()!r}) "
                        f"— extend HEARTBEAT_RE: {line.rstrip()!r}"
                    )
            elif strict and "[heartbeat]" in line:
                raise HeartbeatParseError(
                    f"{path}:{lineno}: unparseable heartbeat line: "
                    f"{line.rstrip()!r}"
                )
    return out


def parse_data_dir(data_dir: str) -> dict:
    out: dict = {"data_dir": os.path.abspath(data_dir)}
    stats_path = os.path.join(data_dir, "sim-stats.json")
    if os.path.exists(stats_path):
        out["sim_stats"] = json.load(open(stats_path))
    hosts_dir = os.path.join(data_dir, "hosts")
    hosts = {}
    if os.path.isdir(hosts_dir):
        for name in sorted(os.listdir(hosts_dir)):
            hd = os.path.join(hosts_dir, name)
            entry: dict = {}
            hs = os.path.join(hd, "host-stats.json")
            if os.path.exists(hs):
                entry["stats"] = json.load(open(hs))
            entry["stdout_files"] = sorted(
                f for f in os.listdir(hd) if f.endswith(".stdout")
            )
            entry["strace_files"] = sorted(
                f for f in os.listdir(hd) if f.endswith(".strace")
            )
            entry["pcap_files"] = sorted(
                f for f in os.listdir(hd) if f.endswith(".pcap")
            )
            hosts[name] = entry
    out["hosts"] = hosts
    # network totals across hosts (reference tracker.c counters rolled up:
    # per-socket and per-interface tx/rx byte+packet sums)
    totals = {"tx_pkts": 0, "tx_bytes": 0, "rx_pkts": 0, "rx_bytes": 0}
    per_iface: dict = {}
    n_sockets = 0
    for entry in hosts.values():
        st = entry.get("stats", {})
        for name, ifc in (st.get("interfaces") or {}).items():
            agg = per_iface.setdefault(name, dict(totals))
            for k in totals:
                agg[k] += ifc.get(k, 0)
        for s in st.get("sockets") or []:
            n_sockets += 1
            for k in totals:
                totals[k] += s.get(k, 0)
    out["network_totals"] = {
        "sockets": n_sockets,
        "per_socket_sum": totals,
        "per_interface_sum": per_iface,
    }
    log_path = os.path.join(data_dir, "shadow.log")
    if os.path.exists(log_path):
        # per-host record attribution from the sim-time-stamped logger
        # (shadow_tpu.obs.simlog; reference shadow_logger.rs format role)
        sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        try:
            from shadow_tpu.obs.simlog import parse_log

            out["shadow_log"] = parse_log(log_path)
        except ImportError:
            pass
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("data_dir")
    p.add_argument("--log", help="driver stderr log with [heartbeat] lines")
    p.add_argument("-o", "--output", help="write JSON here (default stdout)")
    p.add_argument(
        "--strict",
        action="store_true",
        help="error (rc 2) on a [heartbeat] line the format regex cannot "
        "parse, instead of silently skipping it",
    )
    args = p.parse_args(argv)
    result = parse_data_dir(args.data_dir)
    if args.log:
        try:
            result["heartbeats"] = parse_heartbeats(args.log, strict=args.strict)
        except HeartbeatParseError as e:
            print(f"parse_shadow: {e}", file=sys.stderr)
            return 2
    text = json.dumps(result, indent=2)
    if args.output:
        open(args.output, "w").write(text + "\n")
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
