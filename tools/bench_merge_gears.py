#!/usr/bin/env python3
"""Sweep merge gear width x send occupancy on the exchange-merge pair.

The adaptive-exchange question (ISSUE 4): how much of the merge's cost is
the (dst, t, order) sort over the STATIC outbox width, and how much does a
gear-truncated width recover at realistic occupancies? This tool times, on
a synthetic [H, B] outbox filled to a per-host occupancy level:

  - sort:   the token sort + segment extraction half (`merge_plan`)
  - gather: the apply half (`merge_apply` slab write)
  - total:  the fused `merge_flat_events` path (what the engine runs on
            this backend)

at each gear width (the flattened input is H x gear columns — exactly the
slice `core/engine._gear_sliced_outbox` feeds the merge). CPU-runnable by
design; on TPU the same sweep maps the gather-path economics.

Usage: python tools/bench_merge_gears.py [--hosts 4096] [--budget 8]
           [--cap 32] [--iters 30] [--occupancy 1,2,4,8] [--json]
Output: one JSON line per (occupancy, gear) with ms per merge and the
sort-vs-gather split, then a summary of the speedup of the best exact
gear over full width per occupancy.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS, make_queue  # noqa: E402
from shadow_tpu.ops.merge import (  # noqa: E402
    merge_apply,
    merge_flat_events,
    merge_plan,
)


def synth_outbox(rng, hosts: int, budget: int, occ: int):
    """[H, B] lanes with each host's first `occ` columns live (the exact
    layout the engine's cursor append produces), random dst/t, unique
    orders."""
    cols = np.arange(budget)[None, :]
    live = cols < occ
    dst = rng.integers(0, hosts, (hosts, budget)).astype(np.int32)
    t = rng.integers(1, 1 << 40, (hosts, budget)).astype(np.int64)
    t = np.where(live, t, np.int64((1 << 62) - 1))  # TIME_MAX-ish empties
    order = (
        np.arange(hosts * budget, dtype=np.int64).reshape(hosts, budget)
        + (1 << 40)
    )
    kind = rng.integers(0, 4, (hosts, budget)).astype(np.int32)
    payload = rng.integers(
        0, 99, (hosts, budget, EVENT_PAYLOAD_WORDS)
    ).astype(np.int32)
    return dst, t, order, kind, payload, live


def flat_at_gear(arrays, gear: int, time_max: int):
    dst, t, order, kind, payload, live = arrays
    g = gear
    fl = lambda a: jnp.asarray(a[:, :g].reshape(-1, *a.shape[2:]))  # noqa: E731
    t_f = fl(t)
    valid = (t_f != time_max) & (fl(dst) >= 0)
    return fl(dst), t_f, fl(order), fl(kind), fl(payload), valid


def timed(fn, *args, iters=30):
    out = fn(*args)  # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3, out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--hosts", type=int, default=4096)
    p.add_argument("--budget", type=int, default=8)
    p.add_argument("--cap", type=int, default=32)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--occupancy", default="1,2,4,8",
                   help="comma list of live sends per host")
    p.add_argument("--json", action="store_true",
                   help="JSON lines only (no human summary)")
    args = p.parse_args(argv)

    from shadow_tpu.simtime import TIME_MAX

    rng = np.random.default_rng(7)
    q = make_queue(args.hosts, args.cap)
    # the engine's own auto ladder (kept in lock-step with core/gears.py;
    # a 1-wide budget collapses the ladder, so full width is re-appended)
    from shadow_tpu.core.gears import resolve_gear_ladder

    gears = resolve_gear_ladder("auto", args.budget) or [args.budget]
    occs = [int(o) for o in args.occupancy.split(",")]
    rows = []
    for occ in occs:
        arrays = synth_outbox(rng, args.hosts, args.budget, min(occ, args.budget))
        for gear in gears:
            if gear < occ:
                continue  # would shed: the engine replays these, skip
            flat = flat_at_gear(arrays, gear, TIME_MAX)

            plan = jax.jit(
                lambda qt, *f: merge_plan(qt, *f, max_inserts=args.cap)
            )
            ms_sort, planned = timed(plan, q.t, *flat, iters=args.iters)
            apply_ = jax.jit(merge_apply)
            ms_gather, _ = timed(apply_, q, *planned, iters=args.iters)
            fused = jax.jit(
                lambda qq, *f: merge_flat_events(
                    qq, *f, max_inserts=args.cap
                )
            )
            ms_total, _ = timed(fused, q, *flat, iters=args.iters)
            row = {
                "hosts": args.hosts, "budget": args.budget, "occ": occ,
                "gear": gear, "rows": args.hosts * gear,
                "sort_ms": round(ms_sort, 3),
                "gather_ms": round(ms_gather, 3),
                "total_ms": round(ms_total, 3),
                "backend": jax.default_backend(),
            }
            rows.append(row)
            print(json.dumps(row))
    if not args.json:
        for occ in occs:
            mine = [r for r in rows if r["occ"] == occ]
            if not mine:
                continue
            full = next(r for r in mine if r["gear"] == args.budget)
            best = min(mine, key=lambda r: r["total_ms"])
            print(
                f"# occ={occ}: full-width {full['total_ms']:.3f} ms -> "
                f"gear {best['gear']} {best['total_ms']:.3f} ms "
                f"({full['total_ms'] / max(best['total_ms'], 1e-9):.2f}x); "
                f"sort share at full width "
                f"{full['sort_ms'] / max(full['total_ms'], 1e-9):.0%}",
                file=sys.stderr,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
