"""The corruption-signature taxonomy: ONE classifier for this box's
documented jaxlib-0.4.37 failure flavors.

Four drifting copies of the signature set used to live in
tests/subproc.py, tools/soak.py, tools/net_report.py, and
tools/hbm_report.py; new flavors (and any rc-set change) now land here
once. docs/corruption.md is the prose companion: which paths are stable
vs corruption magnets, and the classify-then-retry posture every
consumer follows.

The flavors (see also shadow_tpu/core/integrity.py, which detects the
silent flavors IN the round they happen instead of post-mortem):

  malloc-abort   glibc heap-corruption abort (malloc_consolidate /
                 "corrupted size" / "munmap_chunk: invalid pointer"),
                 SIGABRT: rc 134 shell-style or -6 Python-style. Often
                 at interpreter teardown AFTER a valid result printed.
  sigsegv        segmentation fault, rc 139 / -11 — same family, often
                 inside jax array._value or compiled dispatch.
  timeout-hang   the hang flavor: the worker wedges silently and a
                 subprocess timeout fires with no output produced.
  wrong-digest   the SILENT flavor: the run completes rc 0 but device
                 state was scribbled mid-flight and the final digest is
                 wrong. Only detectable by comparison (a replay, a
                 reference digest, or the integrity sentinel's dual
                 digest lane) — `classify` cannot see it from (rc,
                 output); callers use `WRONG_DIGEST` as the flavor name
                 when their own comparison finds it.
  flow-scribble  the counter-scribble flavor: pointer-sized garbage over
                 small model-state buffers (per-host counters reading
                 ~9e13 or negative) while the digest stays intact —
                 `counters_scribbled` is the bounds gate for it.

Stdlib-only by design: tools import it for plain report runs and the
test infra imports it at collection — neither may pull in JAX (the
corruption this module classifies can kill any process that compiles).
"""

from __future__ import annotations

# SIGABRT/SIGSEGV as seen through shell (128+N) and Python (-N)
# conventions — THE canonical rc set (every consumer reads it from here)
MALLOC_ABORT_RCS = (134, -6)
SIGSEGV_RCS = (139, -11)
HEAP_CORRUPTION_RCS = MALLOC_ABORT_RCS + SIGSEGV_RCS

# flavor names (`classify` returns these; WRONG_DIGEST/FLOW_SCRIBBLE are
# comparison-judged by callers, never derivable from an exit status)
MALLOC_ABORT = "malloc-abort"
SIGSEGV = "sigsegv"
TIMEOUT_HANG = "timeout-hang"
WRONG_DIGEST = "wrong-digest"
FLOW_SCRIBBLE = "flow-counter-scribble"


def is_corruption_rc(rc) -> bool:
    """True when `rc` matches the documented abort/segfault signatures."""
    return rc in HEAP_CORRUPTION_RCS


def classify(
    rc=None, *, timed_out: bool = False, output: str | bytes | None = None
) -> str | None:
    """Classify one worker outcome against the documented corruption
    signatures. Returns a flavor name, or None for "not the known
    corruption — judge it as a real result".

    `output` is the worker's verdict-bearing output (usually stdout):
    a worker that produced a verdict before dying got far enough that
    its death is NOT classified away — the caller must surface the
    verdict (or, for a post-result teardown abort, parse it; see
    tests/subproc.py run_isolated_json). Pass None to skip the guard
    when the caller has already applied its own.
    """
    if output is not None:
        text = output.decode(errors="replace") if isinstance(
            output, bytes
        ) else output
        if text.strip():
            return None
    if timed_out:
        return TIMEOUT_HANG
    if rc in MALLOC_ABORT_RCS:
        return MALLOC_ABORT
    if rc in SIGSEGV_RCS:
        return SIGSEGV
    return None


def counters_scribbled(values, lo, hi) -> bool:
    """The flow-counter-scribble gate: True when any counter sits
    outside its physically-possible [lo, hi] bounds — pointer garbage,
    not simulation output (tools/net_report.py's scribble gate and
    bench.py's solo-leg poison gate both judge this way)."""
    return any(v < lo or v > hi for v in values)


def run_check_isolated(
    cmd,
    *,
    skip_what: str,
    cwd=None,
    attempts: int = 3,
    timeout: int = 600,
    retry_rcs: dict | None = None,
) -> int:
    """The `--check` subprocess scaffold every observatory analyzer
    shares (hbm_report / net_report / rt_report): run the worker `cmd`
    up to `attempts` times with JAX pinned to CPU, stream its output
    through, and apply the classify-then-retry posture — a known
    corruption signature WITHOUT a verdict in the output retries, and
    when every attempt dies of it the check SKIPs rc 0 (environment,
    never a false FAIL). `retry_rcs` maps extra worker return codes to
    retry reasons (net_report's poisoned-device self-classification).
    `skip_what` names the verdict the SKIP line disclaims. Any attempt
    that produces a real result returns its rc verbatim."""
    import os
    import subprocess
    import sys

    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=timeout,
                env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=cwd,
            )
        except subprocess.TimeoutExpired:
            # the hang flavor of the documented corruption: same
            # retry/SKIP posture as an aborting worker
            print(f"attempt {attempt + 1}: check worker timed out "
                  f"({timeout}s); retrying", file=sys.stderr)
            continue
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        if retry_rcs and proc.returncode in retry_rcs:
            print(f"attempt {attempt + 1}: "
                  f"{retry_rcs[proc.returncode]}; retrying",
                  file=sys.stderr)
            continue
        flavor = classify(proc.returncode)
        if flavor is not None and (
            "ok" not in proc.stdout and "FAILED" not in proc.stderr
        ):
            print(f"attempt {attempt + 1}: known corruption signature "
                  f"({flavor}, rc={proc.returncode}); retrying",
                  file=sys.stderr)
            continue
        return proc.returncode
    print(f"SKIP: every attempt died of the known jaxlib corruption "
          f"signature (environment, not {skip_what})")
    return 0
