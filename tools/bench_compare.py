#!/usr/bin/env python3
"""Diff two BENCH / MULTICHIP JSONs and flag perf or HBM regressions.

The BENCH_r01 -> r05 trajectory had no comparator: every round's verdict
was eyeballed. This tool makes the comparison mechanical:

  python tools/bench_compare.py OLD.json NEW.json [--threshold 0.10]
                                [--hbm-threshold 0.10] [--json]

Accepted file shapes (auto-detected, mixable):
  - the recorded BENCH wrapper  {"n", "cmd", "rc", "tail", "parsed": row}
  - a raw bench row             {"metric", "value", "unit", ..., "hbm"?}
  - a list of either
  - the MULTICHIP wrapper       {"n_devices", "rc", "ok", ...} — rc/ok
    compared, plus its "hbm" block when present

Verdicts (rc 1 if any REGRESSION, else 0):
  - perf: metric value dropped more than --threshold relative
    (metrics are throughput-style — higher is better)
  - hbm: per-shard peak bytes (or model-predicted bytes where no peak
    was recorded) grew more than --hbm-threshold relative
  - network (PR 10 observatory block): FCT p50/p99 or retransmits grew
    more than --threshold relative, or the link hot-spot max grew more
    than --hbm-threshold — flow BEHAVIOR regressions, not just
    wall-clock. An event-class share drift > 10 points is a warning
    (the mix shifting is signal, not inherently bad); OLD carrying a
    network block NEW lost is a coverage warning.
  - fluid (PR 13 background plane): foreground-FCT drift with fluid on
    regresses through the network gates above; losing the fluid block
    or the background byte volume collapsing is a coverage warning
  - runtime (PR 14 observatory block): the realtime factor dropping
    more than --threshold, or the compile wall growing more than
    --threshold AND more than 1 s absolute (compiles are noisy at the
    sub-second scale), is a regression; OLD carrying a runtime block
    NEW lost is a coverage warning
  - exchange (PR 17 hierarchical two-tier block): the inter-shard
    wire bytes per round growing more than --hbm-threshold relative
    is a regression — that tier is the actual chip-to-chip traffic
    the hierarchy exists to shrink, so growth means the two-tier
    climb is regressing toward the flat alltoall cost. Losing the
    block is a coverage warning, and a NEW row whose measured wire
    exceeds its own recorded flat-model cost warns that gears never
    settled below the top width.
  - a metric present in OLD but missing from NEW is a regression
    (silently dropping a tracked workload is how coverage rots)
"""

from __future__ import annotations

import argparse
import json
import sys


def _rows(blob) -> dict[str, dict]:
    """Normalize a loaded JSON blob to {metric_name: row}."""
    out: dict[str, dict] = {}
    items = blob if isinstance(blob, list) else [blob]
    for item in items:
        if not isinstance(item, dict):
            continue
        if "parsed" in item and isinstance(item["parsed"], dict):
            item = {**item["parsed"],
                    **({"hbm": item["hbm"]} if "hbm" in item else {}),
                    **({"network": item["network"]}
                       if "network" in item else {}),
                    **({"fluid": item["fluid"]}
                       if "fluid" in item else {}),
                    **({"runtime": item["runtime"]}
                       if "runtime" in item else {}),
                    **({"integrity": item["integrity"]}
                       if "integrity" in item else {}),
                    **({"integrity_aborted": True}
                       if item.get("integrity_aborted") else {})}
        if "metric" in item:
            out[str(item["metric"])] = item
        elif "n_devices" in item:
            out[f"multichip_{item['n_devices']}dev"] = item
    return out


def _hbm_peak(row: dict) -> int | None:
    """Comparable HBM figure of one row: the recorded per-shard peak,
    else the model-predicted per-shard bytes."""
    hbm = row.get("hbm")
    if not isinstance(hbm, dict):
        return None
    peaks = hbm.get("per_shard_hwm_bytes")
    if peaks:
        return max(int(p) for p in peaks)
    model = hbm.get("model") or {}
    if model.get("total_bytes"):
        return int(model["total_bytes"])
    return None


def _compare_network(
    add, name: str, o_net: dict, n_net: dict,
    threshold: float, hbm_threshold: float,
):
    """Diff one metric's `network{}` blocks (obs/netobs.py
    bench_network_block shape): flow-behavior regressions fail the
    diff even when wall-clock held."""
    # FCT distribution: lower is better — growth past threshold regresses
    o_fct, n_fct = o_net.get("fct") or {}, n_net.get("fct") or {}
    for q in ("p50_ms", "p99_ms"):
        ov, nv = o_fct.get(q), n_fct.get(q)
        if isinstance(ov, (int, float)) and isinstance(nv, (int, float)):
            if ov > 0:
                rel = (nv - ov) / ov
                if rel > threshold:
                    add("network", name, "regression",
                        f"fct {q} {ov} -> {nv} ms ({rel * 100:+.1f}%, "
                        f"threshold +{threshold * 100:.0f}%)")
                elif rel < -threshold:
                    add("network", name, "improvement",
                        f"fct {q} {ov} -> {nv} ms ({rel * 100:+.1f}%)")
            elif nv > 0:
                # a zero baseline makes relative thresholds meaningless;
                # 0 -> N is still a flow-behavior change, never silent
                add("network", name, "regression",
                    f"fct {q} appeared: 0 -> {nv} ms (zero baseline)")
        elif ov is not None and nv is None:
            add("network", name, "regression",
                f"OLD recorded fct {q}={ov}, NEW recorded none")
    # retransmits: lower is better; 0 -> N is the canonical regression
    # this block exists to catch (a healthy baseline HAS zero rtx)
    orx, nrx = o_net.get("retransmits"), n_net.get("retransmits")
    if isinstance(orx, (int, float)) and isinstance(nrx, (int, float)):
        if orx > 0:
            rel = (nrx - orx) / orx
            if rel > threshold:
                add("network", name, "regression",
                    f"retransmits {orx} -> {nrx} ({rel * 100:+.1f}%, "
                    f"threshold +{threshold * 100:.0f}%)")
        elif nrx > 0:
            add("network", name, "regression",
                f"retransmits appeared: 0 -> {nrx} (zero baseline)")
    # link hot-spot: growth past the hbm-style threshold regresses
    o_hwm = (o_net.get("link_hwm") or {}).get("packets_sent")
    n_hwm = (n_net.get("link_hwm") or {}).get("packets_sent")
    if isinstance(o_hwm, (int, float)) and isinstance(n_hwm, (int, float)) \
            and o_hwm > 0:
        rel = (n_hwm - o_hwm) / o_hwm
        if rel > hbm_threshold:
            add("network", name, "regression",
                f"link hot-spot packets {o_hwm} -> {n_hwm} "
                f"({rel * 100:+.1f}%, threshold "
                f"+{hbm_threshold * 100:.0f}%)")
    # event-class mix drift: signal worth a look, not inherently bad
    o_sh = (o_net.get("event_classes") or {}).get("timer_share")
    n_sh = (n_net.get("event_classes") or {}).get("timer_share")
    if isinstance(o_sh, (int, float)) and isinstance(n_sh, (int, float)):
        if abs(n_sh - o_sh) > 0.10:
            add("network", name, "warning",
                f"timer-event share {o_sh:.2f} -> {n_sh:.2f} "
                f"(mix shifted by {abs(n_sh - o_sh) * 100:.0f} points)")


def _compare_fluid(add, name: str, o_fl: dict | None, n_fl: dict | None,
                   hbm_threshold: float):
    """Diff one metric's `fluid{}` blocks (net/fluid.py
    bench_fluid_block shape). Foreground-FCT drift with fluid on is
    already a REGRESSION through the network{} compare above — a
    fluid-on row carries both blocks, so worsened foreground behavior
    fails the diff on the flow gates. The fluid block itself guards
    background COVERAGE: losing it, or the background byte volume
    collapsing, means the scenario quietly stopped exercising the
    background plane — a warning, not a hard failure (the background is
    modeled load, not a protocol result)."""
    if isinstance(o_fl, dict) and n_fl is None:
        add("fluid", name, "warning",
            "OLD carried a fluid block, NEW has none (background-plane "
            "coverage lost)")
        return
    if not isinstance(n_fl, dict):
        return
    ob = (o_fl or {}).get("bg_bytes", 0) if isinstance(o_fl, dict) else 0
    nb = n_fl.get("bg_bytes", 0)
    if ob > 0:
        rel = (nb - ob) / ob
        if rel < -hbm_threshold:
            add("fluid", name, "warning",
                f"background bytes {ob} -> {nb} ({rel * 100:+.1f}%): the "
                f"fluid plane carries materially less load (coverage "
                f"shrank)")
    od = (o_fl or {}).get("bg_dropped", 0) if isinstance(o_fl, dict) else 0
    nd = n_fl.get("bg_dropped", 0)
    if od == 0 and nd > 0:
        add("fluid", name, "warning",
            f"background drops appeared: 0 -> {nd} (the fluid plane "
            f"started clipping at congestion — capacity or demand "
            f"changed)")


def _exchange_block(row: dict) -> dict | None:
    """One row's hierarchical-exchange block: bench rows carry it under
    counters.exchange, sim-stats reports at the top level."""
    ex = (row.get("counters") or {}).get("exchange")
    if not isinstance(ex, dict):
        ex = row.get("exchange")
    return ex if isinstance(ex, dict) else None


def _compare_exchange(add, name: str, o: dict, n: dict,
                      hbm_threshold: float):
    """Diff one metric's hierarchical-exchange tier counters (PR 17):
    the inter-shard tier is the wire — its per-round bytes growing past
    tolerance is a REGRESSION (the two-tier climb regressing back toward
    the flat alltoall cost), and a row that loses the block loses the
    weak-scaling guard (coverage warning). The intra tier is on-shard
    staging; it rides the HBM gates, not this one."""
    o_ex, n_ex = _exchange_block(o), _exchange_block(n)
    if isinstance(o_ex, dict) and n_ex is None:
        add("exchange", name, "warning",
            "OLD carried a hierarchical-exchange block, NEW has none "
            "(two-tier wire coverage lost)")
        return
    if not isinstance(n_ex, dict):
        return
    o_r = (o.get("counters") or {}).get("rounds") or o.get("rounds") or 0
    n_r = (n.get("counters") or {}).get("rounds") or n.get("rounds") or 0
    ob = (o_ex or {}).get("ici_inter_bytes") if isinstance(o_ex, dict) else None
    nb = n_ex.get("ici_inter_bytes")
    if isinstance(ob, (int, float)) and isinstance(nb, (int, float)) \
            and ob > 0 and o_r and n_r:
        # normalize per round: legs run different horizons
        opr, npr = ob / o_r, nb / n_r
        rel = (npr - opr) / opr
        if rel > hbm_threshold:
            add("exchange", name, "regression",
                f"inter-shard wire bytes/round {opr:.0f} -> {npr:.0f} "
                f"({rel * 100:+.1f}%, threshold "
                f"+{hbm_threshold * 100:.0f}%) — the two-tier exchange "
                f"is regressing toward the flat alltoall cost")
        elif rel < -hbm_threshold:
            add("exchange", name, "improvement",
                f"inter-shard wire bytes/round {opr:.0f} -> {npr:.0f} "
                f"({rel * 100:+.1f}%)")
    # the in-row flat comparison: a NEW row whose wire tier exceeds its
    # own recorded flat-model cost lost the point of the hierarchy
    flat = n_ex.get("flat_alltoall_bytes_per_round")
    if isinstance(flat, (int, float)) and flat > 0 and n_r \
            and isinstance(nb, (int, float)):
        npr = nb / n_r
        # world factor: flat is per shard per round; the counter sums
        # shards — recover the factor from the model fields when present
        model_inter = n_ex.get("model_inter_bytes_per_round")
        if isinstance(model_inter, (int, float)) and model_inter > 0:
            world = max(round(npr / model_inter), 1) if npr > 0 else 1
            # npr/model_inter only equals world when the run never left
            # the top gear; bound it by the byte ratio instead
            if npr > flat * world * 1.05:
                add("exchange", name, "warning",
                    f"measured wire bytes/round {npr:.0f} exceed the "
                    f"recorded flat-alltoall model x{world} "
                    f"({flat * world:.0f}) — gears never settled below "
                    f"the top width on this leg")


# compile-wall growth below this many absolute seconds never regresses:
# sub-second compile walls are dominated by run-to-run XLA noise
COMPILE_WALL_FLOOR_S = 1.0


def _rt_scalar(v):
    """A comparable realtime-factor number from either shape: the bench
    runtime{} block carries a scalar, sim-stats a {overall, p50, ...}
    dict."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v
    if isinstance(v, dict):
        return v.get("overall")
    return None


def _compare_runtime(add, name: str, o_rt, n_rt, threshold: float):
    """Diff one metric's `runtime{}` blocks (obs/runtime.py
    bench_runtime_block shape): a realtime-factor drop or compile-wall
    growth beyond tolerance is a regression, a lost block a coverage
    warning."""
    if isinstance(o_rt, dict) and n_rt is None:
        add("runtime", name, "warning",
            "OLD carried a runtime block, NEW has none "
            "(wall-attribution coverage lost)")
        return
    if not isinstance(n_rt, dict):
        return
    o_rt = o_rt if isinstance(o_rt, dict) else {}
    # prefer the compile-excluded factor when BOTH rows carry it — the
    # gated number must not move with cold-compile noise (the whole
    # point of the block); compile-wall growth has its own gate below
    oex = _rt_scalar(o_rt.get("realtime_factor_ex_compile"))
    nex = _rt_scalar(n_rt.get("realtime_factor_ex_compile"))
    if isinstance(oex, (int, float)) and isinstance(nex, (int, float)):
        ov, nv = oex, nex
    else:
        ov = _rt_scalar(o_rt.get("realtime_factor"))
        nv = _rt_scalar(n_rt.get("realtime_factor"))
    if isinstance(ov, (int, float)) and isinstance(nv, (int, float)) \
            and ov > 0:
        rel = (nv - ov) / ov
        if rel < -threshold:
            add("runtime", name, "regression",
                f"realtime factor {ov} -> {nv} ({rel * 100:+.1f}%, "
                f"threshold -{threshold * 100:.0f}%)")
        elif rel > threshold:
            add("runtime", name, "improvement",
                f"realtime factor {ov} -> {nv} ({rel * 100:+.1f}%)")
    ow, nw = o_rt.get("compile_wall_s"), n_rt.get("compile_wall_s")
    if isinstance(ow, (int, float)) and isinstance(nw, (int, float)) \
            and ow > 0:
        rel = (nw - ow) / ow
        if rel > threshold and (nw - ow) > COMPILE_WALL_FLOOR_S:
            add("runtime", name, "regression",
                f"compile wall {ow} -> {nw} s ({rel * 100:+.1f}%, "
                f"threshold +{threshold * 100:.0f}% and "
                f">{COMPILE_WALL_FLOOR_S} s absolute) — ROADMAP item "
                f"6's compile-cache budget grew")


def compare(old: dict, new: dict, threshold: float, hbm_threshold: float):
    findings: list[dict] = []

    def add(kind, metric, severity, detail):
        findings.append({"kind": kind, "metric": metric,
                         "severity": severity, "detail": detail})

    for name, o in sorted(old.items()):
        n = new.get(name)
        if n is None:
            add("coverage", name, "regression",
                "metric present in OLD but missing from NEW")
            continue
        ov, nv = o.get("value"), n.get("value")
        if isinstance(ov, (int, float)) and isinstance(nv, (int, float)) \
                and ov > 0:
            rel = (nv - ov) / ov
            if rel < -threshold:
                add("perf", name, "regression",
                    f"value {ov} -> {nv} ({rel * 100:+.1f}%, threshold "
                    f"-{threshold * 100:.0f}%)")
            elif rel > threshold:
                add("perf", name, "improvement",
                    f"value {ov} -> {nv} ({rel * 100:+.1f}%)")
        elif ov is not None and nv is None:
            add("perf", name, "regression",
                f"OLD recorded value {ov}, NEW recorded none "
                f"(skipped: {n.get('skipped') or n.get('solo_leg_skipped')})")
        if "ok" in o and "ok" in n and bool(o["ok"]) and not bool(n["ok"]):
            add("multichip", name, "regression",
                f"ok {o['ok']} -> {n['ok']} (rc {n.get('rc')})")
        oh, nh = _hbm_peak(o), _hbm_peak(n)
        if oh and nh and oh > 0:
            rel = (nh - oh) / oh
            if rel > hbm_threshold:
                add("hbm", name, "regression",
                    f"per-shard HBM peak {oh} -> {nh} B "
                    f"({rel * 100:+.1f}%, threshold "
                    f"+{hbm_threshold * 100:.0f}%)")
            elif rel < -hbm_threshold:
                add("hbm", name, "improvement",
                    f"per-shard HBM peak {oh} -> {nh} B "
                    f"({rel * 100:+.1f}%)")
        elif oh and nh is None:
            # OLD carried HBM telemetry, NEW lost it: coverage warning
            # (not a hard regression — older rows predate the block)
            add("hbm", name, "warning",
                "OLD carried an hbm block, NEW has none")
        o_net, n_net = o.get("network"), n.get("network")
        if isinstance(o_net, dict) and isinstance(n_net, dict):
            _compare_network(
                add, name, o_net, n_net, threshold, hbm_threshold
            )
        elif isinstance(o_net, dict) and n_net is None:
            add("network", name, "warning",
                "OLD carried a network block, NEW has none")
        # fluid-traffic-plane block (PR 13, bench config 12): foreground
        # FCT drift under fluid is caught by the network compare above;
        # this guards background coverage (bytes/drops)
        _compare_fluid(add, name, o.get("fluid"), n.get("fluid"),
                       hbm_threshold)
        # runtime-observatory block (PR 14): realtime-factor drop or
        # compile-wall growth beyond tolerance regresses
        _compare_runtime(add, name, o.get("runtime"), n.get("runtime"),
                         threshold)
        # integrity-sentinel block (PR 11, bench config 10): a
        # DETERMINISTIC violation appearing is always a regression — the
        # engine reproducibly broke its own invariant; transient-SDC
        # growth is a warning (an environment getting noisier is signal,
        # and the transients were survived by construction)
        o_iv, n_iv = o.get("integrity"), n.get("integrity")
        if isinstance(n_iv, dict):
            if n_iv.get("deterministic") or n.get("integrity_aborted"):
                add("integrity", name, "regression",
                    f"deterministic integrity violation appeared: "
                    f"{(n_iv.get('deterministic') or {}).get('detail', 'integrity_aborted')}")
            ot = (o_iv or {}).get("transients", 0) if isinstance(
                o_iv, dict
            ) else 0
            nt = n_iv.get("transients", 0)
            if nt > ot:
                add("integrity", name, "warning",
                    f"transient SDC count grew {ot} -> {nt} (survived, "
                    f"but the box is getting noisier)")
        elif isinstance(o_iv, dict) and n_iv is None:
            add("integrity", name, "warning",
                "OLD carried an integrity block, NEW has none "
                "(sentinel coverage lost)")
        # timer-wheel block (PR 12, bench config 11): with the wheel
        # enabled, the event-class accounting must still reconcile
        # EXACTLY (timer + packet + app == total — ec_timer is the
        # wheel's traffic, so drift here means the wheel routing lost or
        # double-counted events), the wheel may never drop (spill
        # routing pre-empts overflow), and spill growth is a sizing
        # warning.
        o_wh = (o.get("counters") or {}).get("wheel")
        n_wh = (n.get("counters") or {}).get("wheel")
        if isinstance(n_wh, dict):
            if n_wh.get("dropped"):
                add("wheel", name, "regression",
                    f"wheel dropped {n_wh['dropped']} events — the "
                    f"spill-to-queue contract makes this structurally "
                    f"zero; the wheel lost events")
            n_ec = (n.get("network") or {}).get("event_classes") or {}
            tot = n_ec.get("total")
            if isinstance(tot, (int, float)):
                parts = (
                    (n_ec.get("timer") or 0)
                    + (n_ec.get("packet") or 0)
                    + (n_ec.get("app") or 0)
                )
                if parts != tot:
                    add("wheel", name, "regression",
                        f"event-class reconciliation drift with the "
                        f"wheel enabled: timer+packet+app = {parts} != "
                        f"total {tot}")
            os_ = (o_wh or {}).get("spilled", 0) if isinstance(
                o_wh, dict
            ) else 0
            ns_ = n_wh.get("spilled", 0)
            if ns_ > os_:
                add("wheel", name, "warning",
                    f"wheel spill count grew {os_} -> {ns_} (exact but "
                    f"paying the queue path — size slots up, "
                    f"tools/bench_wheel.py)")
        elif isinstance(o_wh, dict) and n_wh is None:
            add("wheel", name, "warning",
                "OLD carried a wheel block, NEW has none (wheel "
                "coverage lost)")
        # hierarchical-exchange block (PR 17): the inter-shard tier IS
        # the wire — growth past tolerance is a regression.
        _compare_exchange(add, name, o, n, hbm_threshold)
    for name in sorted(set(new) - set(old)):
        add("coverage", name, "info", "new metric (no baseline)")
    return findings


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("old")
    p.add_argument("new")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="relative perf-drop threshold (default 0.10)")
    p.add_argument("--hbm-threshold", type=float, default=0.10,
                   help="relative HBM-growth threshold (default 0.10)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    with open(args.old) as f:
        old = _rows(json.load(f))
    with open(args.new) as f:
        new = _rows(json.load(f))
    if not old:
        print(f"bench_compare: no comparable rows in {args.old}",
              file=sys.stderr)
        return 2
    findings = compare(old, new, args.threshold, args.hbm_threshold)
    regressions = [f for f in findings if f["severity"] == "regression"]
    if args.json:
        print(json.dumps({
            "findings": findings,
            "regressions": len(regressions),
            "ok": not regressions,
        }, indent=2))
    else:
        for f in findings:
            print(f"[{f['severity']:<11}] {f['kind']:<9} {f['metric']}: "
                  f"{f['detail']}")
        print(f"{len(regressions)} regression(s), "
              f"{len(findings)} finding(s)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
