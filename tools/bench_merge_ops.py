"""Microbenchmark candidate exchange-merge strategies on the real chip.

Round-1 profile: the 3-key lax.sort over the 60k-entry gathered outbox is
~85% of round cost at 10k hosts. Candidates measured here:
  A. status quo: lax.sort (i32 dst, i64 t, i64 order, i32 idx), 3 keys
  B. cheap_shed: lax.sort (i32 dst, i32 idx), 2 keys
  C. packed single-key i32 sort: (dst << 17) | idx
  D. packed 2-key: (dst,t) in one i64 + order i64
  E. rank-based merge, no sort: block-local rank (equality matrix) +
     per-block dst histogram (scatter-add) + cumsum + gathers
"""

import sys, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import shadow_tpu  # noqa: F401  (enables x64)
import functools
import time

import jax
import jax.numpy as jnp
from jax import lax

H = 10_000
N = 60_000
B = 60  # blocks for rank-based
BS = N // B


def timeit(fn, *args, iters=20):
    out = jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3, out


def main():
    print("devices:", jax.devices())
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    dst = jax.random.randint(k1, (N,), 0, H, dtype=jnp.int32)
    t = jax.random.randint(k2, (N,), 0, 1 << 40, dtype=jnp.int64)
    order = jax.random.randint(k3, (N,), 0, 1 << 60, dtype=jnp.int64)
    valid = jnp.arange(N) % 6 < 1  # ~10k valid, like PHOLD
    dst_key = jnp.where(valid, dst, jnp.int32(H))

    @jax.jit
    def sort3(dst_key, t, order):
        return lax.sort((dst_key, t, order, jnp.arange(N, dtype=jnp.int32)), num_keys=3)

    @jax.jit
    def sort2(dst_key):
        return lax.sort((dst_key, jnp.arange(N, dtype=jnp.int32)), num_keys=2)

    @jax.jit
    def sort1_packed(dst_key):
        packed = (dst_key.astype(jnp.int32) << 17) | jnp.arange(N, dtype=jnp.int32)
        s = lax.sort(packed)
        return s >> 17, s & 0x1FFFF

    @jax.jit
    def sort2_packed64(dst_key, t, order):
        k = (dst_key.astype(jnp.int64) << 48) | t  # t < 2^48
        sk, so, si = lax.sort((k, order, jnp.arange(N, dtype=jnp.int32)), num_keys=2)
        return (sk >> 48).astype(jnp.int32), sk & ((1 << 48) - 1), so, si

    @jax.jit
    def rank_merge(dst_key, valid):
        d = dst_key.reshape(B, BS)
        v = valid.reshape(B, BS)
        eq = (d[:, :, None] == d[:, None, :]) & v[:, None, :]
        tri = jnp.tril(jnp.ones((BS, BS), jnp.bool_), -1)
        within = jnp.sum(eq & tri[None], axis=2, dtype=jnp.int32)  # [B, BS]
        hist = jnp.zeros((B, H + 1), jnp.int32).at[
            jnp.arange(N, dtype=jnp.int32) // BS, dst_key.reshape(-1)
        ].add(valid.astype(jnp.int32))
        chist = jnp.cumsum(hist, axis=0) - hist  # exclusive over blocks
        rank = within + chist[jnp.arange(B)[:, None], d]
        return rank.reshape(-1), hist

    @jax.jit
    def hist_only(dst_key, valid):
        return jnp.zeros((B, H + 1), jnp.int32).at[
            jnp.arange(N, dtype=jnp.int32) // BS, dst_key
        ].add(valid.astype(jnp.int32))

    @jax.jit
    def within_only(dst_key, valid):
        d = dst_key.reshape(B, BS)
        v = valid.reshape(B, BS)
        eq = (d[:, :, None] == d[:, None, :]) & v[:, None, :]
        tri = jnp.tril(jnp.ones((BS, BS), jnp.bool_), -1)
        return jnp.sum(eq & tri[None], axis=2, dtype=jnp.int32)

    for name, fn, args in [
        ("A sort3", sort3, (dst_key, t, order)),
        ("B sort2", sort2, (dst_key,)),
        ("C sort1_packed_i32", sort1_packed, (dst_key,)),
        ("D sort2_packed64", sort2_packed64, (dst_key, t, order)),
        ("E rank_merge", rank_merge, (dst_key, valid)),
        ("E1 hist_only", hist_only, (dst_key, valid)),
        ("E2 within_only", within_only, (dst_key, valid)),
    ]:
        ms, _ = timeit(fn, *args)
        print(f"{name:24s} {ms:8.3f} ms")


if __name__ == "__main__":
    main()
