#!/usr/bin/env python3
"""Plot parsed simulation summaries (reference: src/tools/plot-shadow.py).

Takes one or more JSON files from parse_shadow.py and renders:
  - sim-time vs wall-time progress (heartbeats), one line per run
  - per-host packet counters as a bar chart

Usage: plot_shadow.py parsed.json [parsed2.json ...] -o out.png
Requires matplotlib (optional dependency; exits 3 with a message if absent).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("inputs", nargs="+")
    p.add_argument("-o", "--output", default="shadow-plot.png")
    args = p.parse_args(argv)
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib not available; cannot plot", file=sys.stderr)
        return 3

    runs = [(path, json.load(open(path))) for path in args.inputs]
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 4.5))

    for path, data in runs:
        hb = data.get("heartbeats") or []
        if hb:
            ax1.plot(
                [h["wall"] for h in hb],
                [h["sim"] for h in hb],
                marker="o",
                markersize=2.5,
                label=path,
            )
    ax1.set_xlabel("wall time (s)")
    ax1.set_ylabel("simulated time (s)")
    ax1.set_title("progress")
    if any(d.get("heartbeats") for _, d in runs):
        ax1.legend(fontsize=7)

    path, data = runs[0]
    hosts = data.get("hosts") or {}
    names, sent = [], []
    for name, entry in hosts.items():
        st = entry.get("stats") or {}
        key = "packets_sent" if "packets_sent" in st else "pkts_sent"
        if key in st:
            names.append(name)
            sent.append(st[key])
    if names:
        ax2.bar(range(len(names)), sent)
        ax2.set_xticks(range(len(names)), names, rotation=60, fontsize=7)
        ax2.set_ylabel("packets sent")
        ax2.set_title(f"per-host traffic ({path})")

    fig.tight_layout()
    fig.savefig(args.output, dpi=120)
    print(f"wrote {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
