#!/usr/bin/env python3
"""HBM capacity planner: per-component memory breakdown + max-hosts figure.

Answers the ROADMAP item-1 question directly: *given this config, how
many hosts fit one device before OOM?* — from the memory observatory's
three sources (shadow_tpu/obs/memory.py):

  model   — static byte model off the lane registry (per-component,
            per shard and per host; exact for every registered plane)
  ledger  — `Compiled.memory_analysis()` of the chunk program(s): XLA's
            own argument/output/temp/code accounting, which sees the
            temporaries the state model cannot
  live    — `device.memory_stats()` capacity when the backend has an
            allocator limit, else /proc MemAvailable for host-backed
            devices, else the --hbm-gib assumption (v5e default)

The max-hosts figure solves (fixed + hosts * per_host) * safety <= HBM
with per_host = state+params slope + the compiled temp slope, fixed =
replicated tables + code + the per-shard scalars.

Usage:
  python tools/hbm_report.py CONFIG.yaml [options]
  python tools/hbm_report.py --flagship [options]   # bench config 6 shapes
  python tools/hbm_report.py --check [CONFIG.yaml]  # predicted-vs-measured
                                                    # cross-check (CI stage)

Options:
  --hbm-gib F     HBM budget for the planner. Default: the device's
                  measured allocator limit when one exists (TPU/GPU
                  bytes_limit), else 15.75 (one v5e chip). Host
                  MemAvailable is reported but never used as the
                  planning budget — the ROADMAP question is about the
                  chip, not this box's RAM
  --safety F      planner safety factor (default 1.25)
  --replicas R    scale the state for an R-replica ensemble campaign
  --tol F         --check relative tolerance, static model total vs
                  memory_analysis argument bytes (default 0.10)
  --json          print one JSON blob instead of the table

--check exit codes: 0 ok (or environment-classified SKIP on this box's
documented jaxlib corruption signature — soak.py posture), 2 violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# this box's documented jaxlib-0.4.37 corruption signatures live in ONE
# place (tools/corruption.py: taxonomy + the shared --check subprocess
# scaffold), imported lazily in the --check branch so a plain report
# run stays stdlib-only

DEFAULT_HBM_GIB = 15.75  # one v5e chip


def flagship_config_dict(hosts_scale: int = 128) -> dict:
    """The flagship tgen-TCP torus (bench config 6) at a buildable host
    count: SAME capacity shapes (queue 28/block 7, budget 24, rpc 256),
    scaled host count — per-host bytes are shape-determined, so the
    planner's slope at 128 hosts is the 10k-host slope."""
    from bench import baseline_config

    cfg, _, _ = baseline_config(6, small=True)
    return cfg


def build_sim(cfg_dict: dict):
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    return Simulation(ConfigOptions.from_dict(cfg_dict), world=1)


def analyze(cfg_dict: dict, *, replicas: int = 1, ledger: bool = True) -> dict:
    """Build the sim (no chunk ever dispatches) and assemble the three
    sources plus the planner decomposition."""
    import jax

    from shadow_tpu.obs import memory as M

    sim = build_sim(cfg_dict)
    state, params, engine = sim.state, sim.params, sim.engine
    model = M.static_model(engine.cfg, state, params, replicas=replicas)
    out: dict = {
        "num_hosts": engine.cfg.num_hosts,
        "queue_capacity": engine.cfg.queue_capacity,
        "send_budget": engine.cfg.sends_per_host_round,
        "model": model,
    }
    led = M.compiled_ledger(engine, state, params) if ledger else {}
    out["ledger"] = led
    h = engine.cfg.num_hosts
    state_slope, state_fixed = M.per_host_split(state, h)
    params_slope, params_fixed = M.per_host_split(params, h)
    base = led.get("base", {})
    temp = base.get("temp_bytes", 0)
    code = base.get("generated_code_bytes", 0)
    # replicas scale the STATE only: ensemble params broadcast via
    # in_axes=None and are never duplicated (static_model's rule)
    per_host = state_slope * replicas + params_slope + temp // max(h, 1)
    fixed = state_fixed * replicas + params_fixed + code
    out["planner"] = {
        "per_host_bytes": per_host,
        "fixed_bytes": fixed,
        "state_per_host": state_slope,
        "params_per_host": params_slope,
        "temp_per_host": temp // max(h, 1),
    }
    cap = M.device_capacity_bytes(jax.devices()[0])
    out["device_capacity_bytes"] = cap
    out["device_capacity_source"] = (
        "device" if (cap is not None and jax.devices()[0].platform != "cpu")
        else ("host_memavailable" if cap is not None else None)
    )
    return out


def plan(report: dict, hbm_gib: float, safety: float) -> dict:
    from shadow_tpu.obs import memory as M

    hbm = int(hbm_gib * (1 << 30))
    p = report["planner"]
    return {
        "hbm_bytes": hbm,
        "safety_factor": safety,
        "max_hosts_per_device": M.plan_max_hosts(
            p["per_host_bytes"], p["fixed_bytes"], hbm, safety
        ),
        "predicted_bytes_at_config": (
            p["fixed_bytes"] + p["per_host_bytes"] * report["num_hosts"]
        ),
    }


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n} B"


def print_table(report: dict, planned: dict, file=sys.stdout):
    m = report["model"]
    print(f"# HBM report — {report['num_hosts']} hosts, queue "
          f"{report['queue_capacity']}, outbox {report['send_budget']}",
          file=file)
    print("\n## static byte model (per shard)", file=file)
    for comp, b in sorted(
        m["components"].items(), key=lambda kv: -kv[1]
    ):
        print(f"  {comp:<12} {_fmt_bytes(b):>12}  ({b})", file=file)
    print(f"  {'state total':<12} {_fmt_bytes(m['state_bytes']):>12}",
          file=file)
    if "params_bytes" in m:
        print(f"  {'params':<12} {_fmt_bytes(m['params_bytes']):>12}",
              file=file)
    print(f"  {'TOTAL':<12} {_fmt_bytes(m['total_bytes']):>12}  "
          f"(per host {_fmt_bytes(m['per_host_bytes'])})", file=file)
    led = report.get("ledger") or {}
    if led:
        print("\n## compiled-program ledger (memory_analysis)", file=file)
        for key, d in led.items():
            if "argument_bytes" in d:
                print(f"  {key:<24} args {_fmt_bytes(d['argument_bytes'])} "
                      f"out {_fmt_bytes(d['output_bytes'])} "
                      f"temp {_fmt_bytes(d['temp_bytes'])} "
                      f"peak {_fmt_bytes(d['peak_bytes'])}", file=file)
            else:
                print(f"  {key:<24} {d}", file=file)
    p = report["planner"]
    print("\n## planner", file=file)
    print(f"  per-host bytes   {_fmt_bytes(p['per_host_bytes'])} "
          f"(state {_fmt_bytes(p['state_per_host'])} + params "
          f"{_fmt_bytes(p['params_per_host'])} + temps "
          f"{_fmt_bytes(p['temp_per_host'])})", file=file)
    print(f"  fixed bytes      {_fmt_bytes(p['fixed_bytes'])}", file=file)
    cap = report.get("device_capacity_bytes")
    print(f"  device capacity  {_fmt_bytes(cap)} "
          f"({report.get('device_capacity_source') or 'assumed'})",
          file=file)
    print(f"  HBM budget       {_fmt_bytes(planned['hbm_bytes'])} x safety "
          f"{planned['safety_factor']}", file=file)
    print(f"  max hosts/device {planned['max_hosts_per_device']}", file=file)


def run_check(cfg_dict: dict, tol: float) -> int:
    """Predicted-vs-measured cross-check: the static model's state+params
    total must agree with the compiled program's argument bytes within
    `tol` (XLA pads/aligns; the model counts raw lanes), and every
    registered plane's formula bytes must EXACTLY equal the live carry
    leaf's bytes. rc 0 ok, rc 2 violation."""
    from shadow_tpu.obs import memory as M

    sim = build_sim(cfg_dict)
    state, params, engine = sim.state, sim.params, sim.engine
    failures = []
    dims = M.dims_of_config(engine.cfg)
    for comp, paths in M.registered_component_bytes(dims).items():
        for path, want in paths.items():
            obj = state
            for part in path.split("."):
                obj = getattr(obj, part)
            got = M.leaf_nbytes(obj)
            if got != want:
                failures.append(
                    f"{path}: model {want} B != carry leaf {got} B"
                )
    model = M.static_model(engine.cfg, state, params)
    led = M.compiled_ledger(engine, state, params)
    base = led.get("base", {})
    arg = base.get("argument_bytes")
    if arg:
        rel = abs(model["total_bytes"] - arg) / arg
        line = (
            f"static model {model['total_bytes']} B vs memory_analysis "
            f"arguments {arg} B: {rel * 100:.2f}% (tol {tol * 100:.0f}%)"
        )
        print(line)
        if rel > tol:
            failures.append(line)
    else:
        print("memory_analysis unavailable on this backend; "
              "formula-vs-carry check only")
    if failures:
        for f in failures:
            print(f"CHECK FAILED: {f}", file=sys.stderr)
        return 2
    print("hbm_report --check ok")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("config", nargs="?", help="YAML config path")
    p.add_argument("--flagship", action="store_true",
                   help="use the flagship tgen-TCP torus shapes (bench "
                   "config 6, buildable host count)")
    p.add_argument("--hbm-gib", type=float, default=None,
                   help="planner budget in GiB (default: measured device "
                   "allocator limit, else 15.75)")
    p.add_argument("--safety", type=float, default=1.25)
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--wheel-slots", type=int, default=None,
                   help="price the timer wheel at S slots per host "
                   "(overrides experimental.timer_wheel in the config; "
                   "the wheel planes then appear as their own component "
                   "in the capacity plan)")
    p.add_argument("--tol", type=float, default=0.10)
    p.add_argument("--no-ledger", action="store_true")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true",
                   help="predicted-vs-measured cross-check (CI stage); "
                   "runs the compiled leg in a worker subprocess and "
                   "classifies the known corruption signature as SKIP")
    p.add_argument("--check-worker", action="store_true",
                   help=argparse.SUPPRESS)  # internal: the isolated leg
    args = p.parse_args(argv)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # this box's sitecustomize registers an axon TPU plugin and
        # overrides the env var; pin the backend back (soak.py idiom)
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.config:
        import yaml

        with open(args.config) as f:
            cfg_dict = yaml.safe_load(f.read())
    else:
        cfg_dict = flagship_config_dict()
    if args.wheel_slots is not None:
        # the wheel charges H x S event rows + the block caches; the
        # registry-driven byte model prices it like every other plane,
        # so the max-hosts/device prediction accounts for wheel bytes.
        # microstep_events pins to 1: the wheel rejects K > 1 (the
        # flagship config wires K=4 on TPU backends — exactly where the
        # planner runs), and K does not change state bytes, so the
        # priced shape is unaffected.
        ex = cfg_dict.setdefault("experimental", {})
        ex["timer_wheel"] = args.wheel_slots
        ex["microstep_events"] = 1

    if args.check_worker:
        return run_check(cfg_dict, args.tol)

    if args.check:
        # soak.py posture via the ONE shared scaffold
        # (tools/corruption.run_check_isolated): the compiled leg runs
        # in a fresh subprocess; the documented corruption signature
        # (with no verdict printed) classifies as SKIP rc 0 instead of
        # a false FAIL
        from tools.corruption import run_check_isolated

        cmd = [sys.executable, os.path.abspath(__file__), "--check-worker",
               "--tol", str(args.tol)]
        if args.config:
            cmd.append(args.config)
        return run_check_isolated(
            cmd, skip_what="a memory-model verdict", cwd=_REPO,
        )

    report = analyze(
        cfg_dict, replicas=args.replicas, ledger=not args.no_ledger
    )
    hbm_gib = args.hbm_gib
    if hbm_gib is None:
        # a true device allocator limit (TPU/GPU) IS the planning
        # budget; host MemAvailable is not (the chip is the question)
        if report.get("device_capacity_source") == "device":
            hbm_gib = report["device_capacity_bytes"] / (1 << 30)
        else:
            hbm_gib = DEFAULT_HBM_GIB
    planned = plan(report, hbm_gib, args.safety)
    if args.json:
        print(json.dumps({**report, "plan": planned}, indent=2))
    else:
        print_table(report, planned)
    return 0


if __name__ == "__main__":
    sys.exit(main())
