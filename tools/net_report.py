#!/usr/bin/env python3
"""Network-observatory analyzer: event-class shares, FCT distribution,
per-link hot spots, and safe-window critical path from a sim's exported
`network{}` block (shadow_tpu/obs/netobs.py).

Answers the ROADMAP item-2 gating question directly: *what fraction of
events are timers?* — the number the sort-free/timer-wheel rebuild is
justified (or not) by. Reads the artifact, not the simulation, so the
report mode runs anywhere.

Usage:
  python tools/net_report.py DATA_DIR_OR_SIM_STATS [--json]
  python tools/net_report.py --check            # reconciliation gate (CI)

--check builds a small tgen-TCP sim twice (observatory off / on) in a
worker subprocess and asserts the full observer contract:
  - digests and event counts bit-identical off vs on;
  - event-class totals == the event counter (timer+packet+app == events);
  - the flow ledger reconciles EXACTLY: drained record totals ==
    fl_done/fl_bytes/fl_rtx stats lanes == the model's flows_done;
  - safe-window bound counts sum to the round count.
Exit codes: 0 ok (or environment-classified SKIP on this box's
documented jaxlib corruption signature — hbm_report/soak posture),
2 violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# this box's documented jaxlib-0.4.37 corruption signatures live in ONE
# place (tools/corruption.py: taxonomy + the shared --check subprocess
# scaffold), imported lazily in the --check branch so a plain report
# run stays stdlib-only


def load_network_block(path: str) -> tuple[dict, dict]:
    """(sim_stats, network block) from a data dir or sim-stats.json."""
    if os.path.isdir(path):
        path = os.path.join(path, "sim-stats.json")
    with open(path) as f:
        stats = json.load(f)
    net = stats.get("network")
    if net is None:
        raise SystemExit(
            f"net_report: {path} carries no network{{}} block — run with "
            f"`observability.network: true`"
        )
    return stats, net


def print_report(stats: dict, net: dict, file=sys.stdout):
    ec = net.get("event_classes", {})
    total = ec.get("total", 0)
    print("# network observatory report", file=file)
    print(
        f"\n## event classes ({total} events)\n"
        f"  timer   {ec.get('timer', 0):>12}  "
        f"({(ec.get('timer_share') or 0) * 100:5.1f}%)\n"
        f"  packet  {ec.get('packet', 0):>12}  "
        f"({(ec.get('packet_share') or 0) * 100:5.1f}%)\n"
        f"  app     {ec.get('app', 0):>12}",
        file=file,
    )
    share = ec.get("timer_share")
    wheel = stats.get("wheel")
    if share is not None:
        if wheel is not None:
            # the wheel is ACTIVE: timers are no longer generic queue
            # events — break out where they actually lived instead of
            # re-arguing the rebuild the run already has
            slots = wheel.get("slots", 0)
            occ = wheel.get("occupancy_hwm", 0)
            spilled = wheel.get("spilled", 0)
            verdict = (
                "timer events ride the device wheel"
                if spilled == 0 else
                "timer events ride the device wheel but SPILL — size "
                "slots up (tools/bench_wheel.py sweeps S)"
            )
            print(
                f"  timer-vs-packet share: {share * 100:.1f}% timers vs "
                f"{(ec.get('packet_share') or 0) * 100:.1f}% packets — "
                f"{verdict}\n"
                f"  wheel: occupancy hwm {occ}/{slots} slots, "
                f"spilled {spilled}, dropped {wheel.get('dropped', 0)} "
                f"(must be 0)",
                file=file,
            )
        else:
            # the ROADMAP item-1 gate, stated as a sentence with a number
            verdict = (
                "timer events DOMINATE — enable experimental.timer_wheel"
                if share > 0.5 else
                "timer events do NOT dominate at this scale (the wheel "
                "still removes them from queue occupancy — "
                "experimental.timer_wheel)"
            )
            print(
                f"  timer-vs-packet share: {share * 100:.1f}% timers vs "
                f"{(ec.get('packet_share') or 0) * 100:.1f}% packets — "
                f"{verdict}",
                file=file,
            )
    flows = net.get("flows")
    if flows:
        fct = flows.get("fct") or {}
        print(
            f"\n## flows\n"
            f"  completed    {flows.get('completed', 0)}\n"
            f"  bytes        {flows.get('bytes', 0)}\n"
            f"  retransmits  {flows.get('retransmits', 0)}\n"
            f"  records      drained={flows.get('records_drained', 0)} "
            f"lost={flows.get('records_lost', 0)}\n"
            f"  fct          p50={fct.get('p50_ms')} ms  "
            f"p99={fct.get('p99_ms')} ms  mean={fct.get('mean_ms')} ms  "
            f"max={fct.get('max_ms')} ms",
            file=file,
        )
    links = net.get("links")
    if links:
        print("\n## links (per graph node)", file=file)
        hdr = (f"  {'node':<6} {'hosts':>6} {'sent':>10} {'deliv':>10} "
               f"{'loss':>8} {'codel':>8} {'budget':>8}")
        print(hdr, file=file)
        hot = sorted(
            links.items(),
            key=lambda kv: -kv[1].get("packets_sent", 0),
        )
        for node, link in hot[:20]:
            print(
                f"  {node:<6} {link.get('hosts', 0):>6} "
                f"{link.get('packets_sent', 0):>10} "
                f"{link.get('packets_delivered', 0):>10} "
                f"{link.get('drops_path_loss', 0):>8} "
                f"{link.get('drops_codel', 0):>8} "
                f"{link.get('drops_budget', 0):>8}",
                file=file,
            )
        if len(hot) > 20:
            print(f"  ... {len(hot) - 20} more nodes", file=file)
        hwm = net.get("link_hwm", {})
        print(f"  hot spot: packets={hwm.get('packets_sent', 0)} "
              f"bytes={hwm.get('bytes', 0)}", file=file)
    sw = net.get("safe_window")
    if sw:
        print(
            f"\n## safe window ({sw.get('rounds', 0)} rounds)\n"
            f"  bound per shard  {sw.get('bound_rounds_per_shard')}\n"
            f"  critical shard   {sw.get('critical_shard')} "
            f"({(sw.get('critical_share') or 0) * 100:.1f}% of rounds)",
            file=file,
        )
    fluid = stats.get("fluid")
    if fluid:
        # fluid traffic plane (net/fluid.py): the verdict's background-
        # share sentence — how much of the modeled traffic rode the
        # aggregate plane vs the packet-exact foreground
        from shadow_tpu.net.fluid import background_share_sentence

        fg_bytes = (net.get("flows") or {}).get("bytes")
        print(
            f"\n## fluid background plane ({fluid.get('classes', 0)} "
            f"classes over {fluid.get('links', 0)} links)\n"
            f"  {background_share_sentence(fluid, fg_bytes)}\n"
            f"  delivered share  {fluid.get('delivered_share')}\n"
            f"  link util max    {fluid.get('link_util_max')} "
            f"(coupling ramps from the configured threshold; latency "
            f"cap {fluid.get('latency_factor_max')}x, "
            f"loss cap {fluid.get('loss_max')})",
            file=file,
        )


def _check_config(tmp: str) -> dict:
    """Small tgen-TCP sim for the reconciliation gate: lossy enough to
    exercise retransmit timers, long enough for every flow to finish."""
    return {
        "general": {"stop_time": "4 s", "seed": 11, "data_directory": tmp,
                    "heartbeat_interval": None},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_queue_capacity": 32,
                         "sends_per_host_round": 16,
                         "rounds_per_chunk": 32},
        "observability": {"network": True, "network_flows": 64,
                          "trace": True},
        "hosts": {
            "node": {"count": 6, "network_node_id": 0,
                     "processes": [{
                         "model": "tgen_tcp",
                         "model_args": {"flows": 2, "flow_segs": 8,
                                        "cwnd_cap": 8,
                                        "rto_min": "100 ms"}}]},
        },
    }


def run_check(tmp_dir: str) -> int:
    """The reconciliation gate (see module docstring). rc 0 ok, 2 bad,
    3 poisoned-environment (see the scribble gate below)."""
    import jax
    import numpy as np

    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.sim import Simulation

    failures: list[str] = []

    def ck(ok: bool, msg: str):
        if not ok:
            failures.append(msg)

    cfg_on = _check_config(os.path.join(tmp_dir, "on"))
    cfg_off = json.loads(json.dumps(cfg_on))
    cfg_off["observability"] = {}
    cfg_off["general"]["data_directory"] = os.path.join(tmp_dir, "off")

    sim_off = Simulation(ConfigOptions.from_dict(cfg_off), world=1)
    rep_off = sim_off.run()
    sim_on = Simulation(ConfigOptions.from_dict(cfg_on), world=1)
    rep_on = sim_on.run()

    # scribble gate: this box's documented jaxlib-0.4.37 corruption has a
    # SILENT flavor that scrawls pointer-sized garbage over small device
    # buffers in in-process compiled-Simulation sequences (reproduced on
    # unmodified HEAD: tgen model counter lanes reading ~9e13 while the
    # digest stays intact; bench.py's solo-leg poison gate exists for the
    # same mode). A per-host flow counter above the configured flows-per-
    # client bound (or negative) is physically impossible — classify the
    # run as poisoned (rc 3: the parent retries, then SKIPs) instead of
    # reporting a false reconciliation failure.
    from tools.corruption import counters_scribbled

    flows_bound = 2  # flows per client in _check_config
    for label, sim in (("off", sim_off), ("on", sim_on)):
        fd = np.asarray(jax.device_get(sim.state.model["flows_done"]))
        if counters_scribbled(fd.tolist(), 0, flows_bound):
            print(
                f"POISONED: {label}-run model flow counters {fd.tolist()} "
                f"outside [0, {flows_bound}] — the documented silent-"
                f"scribble corruption, not an observatory verdict",
                file=sys.stderr,
            )
            return 3

    # observer exactness
    ck(rep_on["determinism_digest"] == rep_off["determinism_digest"],
       f"digest changed with observatory on: "
       f"{rep_off['determinism_digest']} -> {rep_on['determinism_digest']}")
    ck(rep_on["events_processed"] == rep_off["events_processed"],
       "event count changed with observatory on")
    net = rep_on.get("network")
    ck(net is not None, "no network block in gated sim-stats")
    if net is None:
        net = {}

    # event classes reconcile with the event counter
    ec = net.get("event_classes", {})
    ck(ec.get("total") == rep_on["events_processed"],
       f"event-class total {ec.get('total')} != events "
       f"{rep_on['events_processed']}")
    ck(ec.get("timer", 0) > 0, "no timer events classified on tgen-TCP")
    ck(ec.get("packet", 0) > 0, "no packet events classified")

    # flow ledger reconciles exactly (drained records vs stats lanes vs
    # the model's own counter)
    flows = net.get("flows", {})
    mr = rep_on["model_report"]
    ck(flows.get("completed") == mr["flows_completed"],
       f"ledger completions {flows.get('completed')} != model "
       f"{mr['flows_completed']}")
    ck(flows.get("records_drained", 0) + flows.get("records_lost", 0)
       == flows.get("completed"),
       f"drained {flows.get('records_drained')} + lost "
       f"{flows.get('records_lost')} != completed "
       f"{flows.get('completed')}")
    if flows.get("records_lost", 0) == 0:
        # nothing wrapped: the drained-record sums (ring path) must
        # equal the fl_* stats lanes (independent in-jit path) — the
        # real ledger-vs-counters cross-check
        ck(flows.get("drained_bytes") == flows.get("bytes"),
           f"drained record bytes {flows.get('drained_bytes')} != "
           f"fl_bytes lane {flows.get('bytes')}")
        ck(flows.get("drained_retransmits") == flows.get("retransmits"),
           f"drained record retransmits "
           f"{flows.get('drained_retransmits')} != fl_rtx lane "
           f"{flows.get('retransmits')}")
    ck(flows.get("retransmits", 0) <= mr["retransmits"],
       f"per-flow retransmits {flows.get('retransmits')} exceed the "
       f"model total {mr['retransmits']}")

    # safe window covers every round
    sw = net.get("safe_window", {})
    ck(sum(sw.get("bound_rounds_per_shard", [])) == rep_on["rounds"],
       f"safe-window bound counts {sw.get('bound_rounds_per_shard')} "
       f"do not sum to rounds {rep_on['rounds']}")

    share = ec.get("timer_share")
    print(
        f"timer share {share if share is not None else '-'} "
        f"(timer={ec.get('timer')} packet={ec.get('packet')} "
        f"app={ec.get('app')}), flows={flows.get('completed')}, "
        f"fct p50={((flows.get('fct') or {}).get('p50_ms'))} ms"
    )
    if failures:
        for f in failures:
            print(f"CHECK FAILED: {f}", file=sys.stderr)
        return 2
    print("net_report --check ok")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("path", nargs="?",
                   help="data dir or sim-stats.json with a network block")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true",
                   help="ledger-vs-counters reconciliation gate (CI "
                   "stage); runs the compiled leg in a worker subprocess "
                   "and classifies the known corruption signature as SKIP")
    p.add_argument("--check-worker", action="store_true",
                   help=argparse.SUPPRESS)  # internal: the isolated leg
    args = p.parse_args(argv)

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # this box's sitecustomize registers an axon TPU plugin and
        # overrides the env var; pin the backend back (soak.py idiom)
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.check_worker:
        import tempfile

        with tempfile.TemporaryDirectory() as tmp:
            return run_check(tmp)

    if args.check:
        # hbm_report posture via the ONE shared scaffold
        # (tools/corruption.run_check_isolated): the compiled leg runs
        # in a fresh subprocess; the documented corruption signature
        # (no verdict printed) classifies as SKIP rc 0 instead of a
        # false FAIL. rc 3 = the worker's scribble gate classified its
        # own device state as poisoned (silent-corruption flavor):
        # retried like an aborting worker, never reported as a verdict.
        from tools.corruption import run_check_isolated

        return run_check_isolated(
            [sys.executable, os.path.abspath(__file__), "--check-worker"],
            skip_what="an observatory verdict", cwd=_REPO,
            retry_rcs={3: "worker self-classified poisoned device state"},
        )

    if not args.path:
        p.error("a data dir / sim-stats.json path is required "
                "(or --check)")
    stats, net = load_network_block(args.path)
    if args.json:
        print(json.dumps(net, indent=2))
    else:
        print_report(stats, net)
    return 0


if __name__ == "__main__":
    sys.exit(main())
