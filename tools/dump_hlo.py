"""Dump optimized HLO + cost analysis for the bench chunk to find the
pathological op (all tunnel-side timing is unreliable; read the program)."""

import sys, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import collections
import re

import jax

from bench import baseline_config, bench_config
from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.sim import Simulation


def main():
    if len(sys.argv) > 1:
        cfg_dict, _, _ = baseline_config(int(sys.argv[1]), False)
        cfg = ConfigOptions.from_dict(cfg_dict)
    else:
        cfg = ConfigOptions.from_dict(bench_config(10_000, 100))
    sim = Simulation(cfg, world=1)
    lowered = sim.engine.run_chunk.lower(sim.state, sim.params)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        print("COST:", {k: v for k, v in sorted(ca.items()) if v > 1e6 or k in ("flops", "bytes accessed")})
    except Exception as e:
        print("cost_analysis failed:", e)
    txt = compiled.as_text()
    print("HLO bytes:", len(txt))
    ops = collections.Counter()
    for mline in re.finditer(r"= (\w+)\.?\d* ?\(?", txt):
        ops[mline.group(1)] += 1
    for op, n in ops.most_common(40):
        print(f"{op:30s} {n}")
    with open("/tmp/chunk_hlo.txt", "w") as f:
        f.write(txt)
    print("wrote /tmp/chunk_hlo.txt")


if __name__ == "__main__":
    main()
