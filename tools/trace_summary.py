#!/usr/bin/env python3
"""Print a per-phase round breakdown from an exported Chrome trace.

Input: the `trace.json` written by `observability.trace` (the
`obs/tracer.py` Chrome-trace exporter). Stdlib-only on purpose — this
reads the exported artifact, not the simulation, so it runs anywhere
(a laptop holding a trace scp'd off the TPU box included).

The breakdown groups rounds into behavioral phases:
  - exchange-active rounds (staged sends > 0) vs quiet rounds: how much
    of the run pays the merge sort;
  - deferral rounds (popk_deferred > 0): where the K-way guard bit;
  - shed/overflow rounds: loud-loss visibility.
plus wall-clock chunk statistics (rounds per dispatch, dispatch spans).

Usage: trace_summary.py TRACE_JSON [--json]
"""

from __future__ import annotations

import argparse
import json
import sys


def _stats(vals: list[int]) -> dict:
    if not vals:
        return {"n": 0, "sum": 0, "mean": 0.0, "max": 0}
    return {
        "n": len(vals),
        "sum": sum(vals),
        "mean": round(sum(vals) / len(vals), 2),
        "max": max(vals),
    }


def summarize(trace: dict) -> dict:
    rounds = [
        e["args"]
        for e in trace.get("traceEvents", [])
        if e.get("cat") == "round"
    ]
    chunks = [
        e
        for e in trace.get("traceEvents", [])
        if e.get("cat") == "chunk"
    ]
    phases = {
        "all": rounds,
        "exchange_active": [r for r in rounds if r.get("sends", 0) > 0],
        "quiet": [r for r in rounds if r.get("sends", 0) == 0],
        "popk_deferral": [r for r in rounds if r.get("popk_deferred", 0) > 0],
        "a2a_shed": [r for r in rounds if r.get("a2a_shed", 0) > 0],
    }
    out: dict = {"rounds": len(rounds), "phases": {}}
    for name, rs in phases.items():
        if not rs and name != "all":
            continue
        sim_ns = sum(
            r.get("window_end", 0) - r.get("window_start", 0) for r in rs
        )
        out["phases"][name] = {
            "rounds": len(rs),
            "sim_seconds": round(sim_ns / 1e9, 6),
            "events": _stats([r.get("events", 0) for r in rs]),
            "microsteps": _stats([r.get("microsteps", 0) for r in rs]),
            "sends": _stats([r.get("sends", 0) for r in rs]),
            "ici_bytes": sum(r.get("ici_bytes", 0) for r in rs),
            "occ_hwm": max((r.get("occ_hwm", 0) for r in rs), default=0),
        }
    if chunks:
        spans_ms = [c.get("dur", 0) / 1e3 for c in chunks]
        per_chunk = [c.get("args", {}).get("rounds", 0) for c in chunks]
        out["chunks"] = {
            "n": len(chunks),
            "wall_seconds": round(sum(spans_ms) / 1e3, 4),
            "rounds_per_chunk": _stats(per_chunk),
            "ms_per_chunk_mean": round(sum(spans_ms) / len(spans_ms), 2),
        }
    # per-event-class breakdown (network observatory, PR 10): the
    # timer/packet/app mix the timer-wheel decision (ROADMAP item 2)
    # gates on. Traces recorded before the observatory (or with it off)
    # carry no class counts — the section is omitted rather than lying
    # with zeros-as-measurement.
    ec = {
        "timer": sum(r.get("ec_timer", 0) for r in rounds),
        "packet": sum(r.get("ec_pkt", 0) for r in rounds),
        "app": sum(r.get("ec_app", 0) for r in rounds),
    }
    ec_total = sum(ec.values())
    if ec_total:
        out["event_classes"] = {
            **ec,
            "total": ec_total,
            "timer_share": round(ec["timer"] / ec_total, 4),
            "packet_share": round(ec["packet"] / ec_total, 4),
            "flows_completed": sum(r.get("flows", 0) for r in rounds),
        }
    other = trace.get("otherData", {})
    if other:
        out["rounds_lost"] = other.get("rounds_lost", 0)
    return out


def _print_table(s: dict, out=sys.stdout):
    print(f"rounds traced: {s['rounds']}  (lost: {s.get('rounds_lost', 0)})",
          file=out)
    hdr = (f"{'phase':<16} {'rounds':>7} {'sim_s':>10} {'events':>9} "
           f"{'ev/round':>9} {'msteps':>8} {'sends':>8} {'occ_hwm':>8}")
    print(hdr, file=out)
    print("-" * len(hdr), file=out)
    for name, p in s["phases"].items():
        print(
            f"{name:<16} {p['rounds']:>7} {p['sim_seconds']:>10.3f} "
            f"{p['events']['sum']:>9} {p['events']['mean']:>9.2f} "
            f"{p['microsteps']['sum']:>8} {p['sends']['sum']:>8} "
            f"{p['occ_hwm']:>8}",
            file=out,
        )
    ec = s.get("event_classes")
    if ec:
        print(
            f"event classes: timer={ec['timer']} "
            f"({ec['timer_share'] * 100:.1f}%)  "
            f"packet={ec['packet']} ({ec['packet_share'] * 100:.1f}%)  "
            f"app={ec['app']}  flows={ec['flows_completed']}",
            file=out,
        )
    c = s.get("chunks")
    if c:
        print(
            f"chunks: {c['n']}  wall={c['wall_seconds']}s  "
            f"rounds/chunk mean={c['rounds_per_chunk']['mean']} "
            f"max={c['rounds_per_chunk']['max']}  "
            f"ms/chunk={c['ms_per_chunk_mean']}",
            file=out,
        )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("trace", help="Chrome trace JSON from observability.trace")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of a table")
    args = p.parse_args(argv)
    with open(args.trace) as f:
        trace = json.load(f)
    s = summarize(trace)
    if args.json:
        print(json.dumps(s, indent=2))
    else:
        _print_table(s)
    return 0


if __name__ == "__main__":
    sys.exit(main())
