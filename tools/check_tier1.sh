#!/usr/bin/env bash
# One-command tier-1 runner — the EXACT verify line from ROADMAP.md, so
# builders and CI invoke the gate identically (pipefail, the CPU backend
# pin, the plugin opt-outs, and the DOTS_PASSED count that survives the
# known jaxlib heap-corruption aborts on some boxes: a corrupted worker
# can kill pytest's summary, but the dot lines it already streamed still
# count what passed).
#
# Usage: tools/check_tier1.sh [extra pytest args...]
#   e.g. tools/check_tier1.sh -k gears
# Exit code is pytest's; DOTS_PASSED=<n> is printed last either way.
#
# Optional second stage: TIER1_SOAK=1 additionally runs the 2-minute
# crash-recovery soak smoke (tools/soak.py --smoke: SIGKILL + resume +
# digest-exactness on a faulty scenario). Its failure is folded into the
# exit code only when the pytest stage passed, so the primary signal
# stays pytest's.
#
# Optional stage: TIER1_HBM=1 runs the memory-observatory cross-check
# (tools/hbm_report.py --check: the static byte model must agree with
# Compiled.memory_analysis within tolerance, and every registered lane's
# formula bytes must equal the live carry leaf's). The compiled leg runs
# in a worker subprocess and self-classifies the known jaxlib corruption
# signature as SKIP (soak.py posture).
#
# Optional stage: TIER1_NET=1 runs the network-observatory
# reconciliation check (tools/net_report.py --check: digests identical
# with the observatory on/off, event-class totals == the event counter,
# and the flow ledger reconciling exactly against the fl_* stats lanes
# and the model's own flow counts). Subprocess-isolated with the same
# corruption-signature SKIP posture as the hbm stage.
#
# Optional stage: TIER1_INTEGRITY=1 runs the integrity-sentinel soak
# (tools/soak.py --sentinel --smoke: N uninterrupted iterations with the
# in-jit invariant guards ON, asserting zero deterministic violations
# and digest-exactness, reporting the transient-SDC count — "every
# round's invariants held", not just "the final digest matched").
# Same corruption-signature SKIP posture as the soak stage.
#
# Optional stage: TIER1_RT=1 runs the runtime-observatory
# reconciliation check (tools/rt_report.py --check: digests identical
# with the observatory on/off, the WallLedger's attributed wall
# matching the driver's total within tolerance, the compile ledger
# recording exactly the programs the (gear, capacity, budget) cache
# compiled, and the cosim bridge split present). Subprocess-isolated
# with the same corruption-signature SKIP posture as the hbm stage.
#
# Optional stage: TIER1_SCALE=1 runs the weak-scaling smoke
# (tools/bench_scale.py --smoke: the 10k-hosts/device legs on 1 and 8
# virtual devices — the world-8 leg runs the hierarchical exchange with
# auto gears, and the gate asserts the BENCH-schema rows parsed with
# their hbm{}/network{} blocks, the rpc-valve columns, and the two-tier
# byte counters reconciling against the wire counter). Worker
# subprocesses with the same corruption-signature SKIP posture as the
# soak stage.
#
# Optional third stage: TIER1_CAMPAIGN=1 runs the ensemble-plane smoke
# (tools/campaign.py --smoke: an A/A control campaign that must hold +
# a forced-divergence A/B campaign whose bisection must agree with the
# linear digest scan). The smoke runs its compiled legs in a worker
# subprocess and self-classifies the known jaxlib corruption signature
# as SKIP, like the soak stage.
set -o pipefail
cd "$(dirname "$0")/.."
LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
# Pre-stage: shadowlint stage A + ruff. Runs BEFORE pytest and imports
# no JAX (`--ast-only`), so the known jaxlib heap corruption that can
# abort compiled runs on some boxes cannot kill this gate. Budgeted well
# under 30 s; its rc is folded into the final exit code only when the
# pytest stage passed (same posture as the soak stage), so the primary
# signal stays pytest's. Skip with TIER1_NO_LINT=1.
lint_rc=0
if [ -z "${TIER1_NO_LINT:-}" ]; then
  echo "== shadowlint pre-stage (stage A, no JAX) =="
  timeout -k 5 "${TIER1_LINT_TIMEOUT:-30}" python -m tools.lint --ast-only
  lint_rc=$?
  echo "LINT_RC=$lint_rc"
  if command -v ruff >/dev/null 2>&1; then
    ruff check .
    ruff_rc=$?
    echo "RUFF_RC=$ruff_rc"
    [ "$lint_rc" -eq 0 ] && lint_rc=$ruff_rc
  else
    echo "ruff: not installed; stage skipped"
  fi
fi
timeout -k 10 "${TIER1_TIMEOUT:-870}" \
  env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
  -p no:randomly "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
[ "$rc" -eq 0 ] && rc=$lint_rc
if [ -n "${TIER1_SOAK:-}" ]; then
  echo "== soak smoke (TIER1_SOAK) =="
  timeout -k 10 "${TIER1_SOAK_TIMEOUT:-150}" \
    env JAX_PLATFORMS=cpu python tools/soak.py --smoke
  soak_rc=$?
  echo "SOAK_RC=$soak_rc"
  [ "$rc" -eq 0 ] && rc=$soak_rc
fi
if [ -n "${TIER1_HBM:-}" ]; then
  echo "== hbm predicted-vs-measured check (TIER1_HBM) =="
  timeout -k 10 "${TIER1_HBM_TIMEOUT:-630}" \
    env JAX_PLATFORMS=cpu python tools/hbm_report.py --check
  hbm_rc=$?
  echo "HBM_RC=$hbm_rc"
  [ "$rc" -eq 0 ] && rc=$hbm_rc
fi
if [ -n "${TIER1_NET:-}" ]; then
  echo "== network-observatory reconciliation check (TIER1_NET) =="
  timeout -k 10 "${TIER1_NET_TIMEOUT:-330}" \
    env JAX_PLATFORMS=cpu python tools/net_report.py --check
  net_rc=$?
  echo "NET_RC=$net_rc"
  [ "$rc" -eq 0 ] && rc=$net_rc
fi
if [ -n "${TIER1_RT:-}" ]; then
  echo "== runtime-observatory reconciliation check (TIER1_RT) =="
  timeout -k 10 "${TIER1_RT_TIMEOUT:-630}" \
    env JAX_PLATFORMS=cpu python tools/rt_report.py --check
  rt_rc=$?
  echo "RT_RC=$rt_rc"
  [ "$rc" -eq 0 ] && rc=$rt_rc
fi
if [ -n "${TIER1_INTEGRITY:-}" ]; then
  echo "== integrity-sentinel soak (TIER1_INTEGRITY) =="
  timeout -k 10 "${TIER1_INTEGRITY_TIMEOUT:-150}" \
    env JAX_PLATFORMS=cpu python tools/soak.py --sentinel --smoke
  integrity_rc=$?
  echo "INTEGRITY_RC=$integrity_rc"
  [ "$rc" -eq 0 ] && rc=$integrity_rc
fi
if [ -n "${TIER1_SCALE:-}" ]; then
  echo "== weak-scaling smoke (TIER1_SCALE) =="
  timeout -k 10 "${TIER1_SCALE_TIMEOUT:-630}" \
    env JAX_PLATFORMS=cpu python tools/bench_scale.py --smoke -o /dev/null
  scale_rc=$?
  echo "SCALE_RC=$scale_rc"
  [ "$rc" -eq 0 ] && rc=$scale_rc
fi
if [ -n "${TIER1_CAMPAIGN:-}" ]; then
  echo "== campaign smoke (TIER1_CAMPAIGN) =="
  timeout -k 10 "${TIER1_CAMPAIGN_TIMEOUT:-330}" \
    env JAX_PLATFORMS=cpu python tools/campaign.py --smoke
  campaign_rc=$?
  echo "CAMPAIGN_RC=$campaign_rc"
  [ "$rc" -eq 0 ] && rc=$campaign_rc
fi
exit $rc
