#!/usr/bin/env python3
"""Managed-memory access microbenchmark (VERDICT r2 #9).

Measures the syscall-crossing cost of reading a managed process's memory:

  per-iovec : one process_vm_readv call per iovec (the pre-round-3 path
              for writev/sendmsg gathers)
  batched   : ONE process_vm_readv call carrying all remote iovecs (what
              native_plane._gather_write / _handle_msg do now)

The reference's MemoryMapper (memory_mapper.rs:84-110) removes the syscall
entirely via shared-memory remapping; batching is the measured middle
ground this plane ships. Run: python tools/membench.py [iovs] [size] [reps]
Prints one JSON line with both rates and the speedup.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from shadow_tpu.native_plane import _vm_read, _vm_read_multi  # noqa: E402


def find_readable_region(pid: int, need: int) -> int:
    with open(f"/proc/{pid}/maps") as f:
        for line in f:
            fields = line.split()
            if len(fields) < 2 or "r" not in fields[1]:
                continue
            lo, hi = (int(x, 16) for x in fields[0].split("-"))
            if hi - lo >= need:
                return lo
    raise RuntimeError("no readable region")


def main() -> int:
    iovs = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    size = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 2000

    child = subprocess.Popen(["sleep", "60"])
    try:
        time.sleep(0.05)  # let exec finish so maps are stable
        base = find_readable_region(child.pid, iovs * size)
        chunks = [(base + i * size, size) for i in range(iovs)]

        t0 = time.perf_counter()
        for _ in range(reps):
            for addr, n in chunks:
                _vm_read(child.pid, addr, n)
        per_iovec_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(reps):
            _vm_read_multi(child.pid, chunks)
        batched_s = time.perf_counter() - t0

        total_mb = reps * iovs * size / 1e6
        print(
            json.dumps(
                {
                    "metric": "vm_read_gather",
                    "iovs": iovs,
                    "size_bytes": size,
                    "reps": reps,
                    "per_iovec_us_per_gather": round(
                        per_iovec_s / reps * 1e6, 2
                    ),
                    "batched_us_per_gather": round(batched_s / reps * 1e6, 2),
                    "speedup": round(per_iovec_s / max(batched_s, 1e-12), 2),
                    "batched_MBps": round(total_mb / batched_s, 1),
                }
            )
        )
    finally:
        child.kill()
        child.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
