#!/usr/bin/env python3
"""Managed-memory access microbenchmark (VERDICT r2 #9).

Measures the syscall-crossing cost of reading a managed process's memory:

  per-iovec : one process_vm_readv call per iovec (the pre-round-3 path
              for writev/sendmsg gathers)
  batched   : ONE process_vm_readv call carrying all remote iovecs (what
              native_plane._gather_write / _handle_msg do now)

  mapped    : the MemoryMapper window (r4) — the shim remapped the managed
              heap onto a shared tmpfs file; reads are a local memcpy with
              ZERO kernel crossings (reference memory_mapper.rs:84-110).

Run: python tools/membench.py [iovs] [size] [reps]
Prints one JSON line with all three rates and the speedups.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from shadow_tpu.native_plane import _vm_read, _vm_read_multi  # noqa: E402


def find_readable_region(pid: int, need: int) -> int:
    with open(f"/proc/{pid}/maps") as f:
        for line in f:
            fields = line.split()
            if len(fields) < 2 or "r" not in fields[1]:
                continue
            lo, hi = (int(x, 16) for x in fields[0].split("-"))
            if hi - lo >= need:
                return lo
    raise RuntimeError("no readable region")


def main() -> int:
    iovs = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    size = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 2000

    child = subprocess.Popen(["sleep", "60"])
    try:
        time.sleep(0.05)  # let exec finish so maps are stable
        base = find_readable_region(child.pid, iovs * size)
        chunks = [(base + i * size, size) for i in range(iovs)]

        t0 = time.perf_counter()
        for _ in range(reps):
            for addr, n in chunks:
                _vm_read(child.pid, addr, n)
        per_iovec_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        for _ in range(reps):
            _vm_read_multi(child.pid, chunks)
        batched_s = time.perf_counter() - t0

        mapped_s = measure_mapped(iovs, size, reps)

        total_mb = reps * iovs * size / 1e6
        print(
            json.dumps(
                {
                    "metric": "vm_read_gather",
                    "iovs": iovs,
                    "size_bytes": size,
                    "reps": reps,
                    "per_iovec_us_per_gather": round(
                        per_iovec_s / reps * 1e6, 2
                    ),
                    "batched_us_per_gather": round(batched_s / reps * 1e6, 2),
                    "mapped_us_per_gather": (
                        round(mapped_s / reps * 1e6, 2) if mapped_s else None
                    ),
                    "speedup_batched": round(
                        per_iovec_s / max(batched_s, 1e-12), 2
                    ),
                    "speedup_mapped_vs_batched": (
                        round(batched_s / mapped_s, 2) if mapped_s else None
                    ),
                    "batched_MBps": round(total_mb / batched_s, 1),
                    "mapped_MBps": (
                        round(total_mb / mapped_s, 1) if mapped_s else None
                    ),
                }
            )
        )
    finally:
        child.kill()
        child.wait()
    return 0


def measure_mapped(iovs: int, size: int, reps: int) -> float | None:
    """Time the same gather against a shim-managed child whose heap rides
    the MemoryMapper window. The child mallocs a big heap buffer and
    sleeps; the window serves every read with no kernel crossing."""
    from shadow_tpu.host import CpuHost, HostConfig
    from shadow_tpu.native_plane import (
        _HEAP_WINDOWS,
        HEAP_START_OFF,
        _heap_loc,
        ensure_built,
        spawn_native,
    )

    if not ensure_built():
        return None
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = CpuHost(HostConfig(name="m1", ip="10.0.0.1", seed=1, host_id=0))
    # test_app parks in nanosleep; its glibc heap is window-backed
    p = spawn_native(h, [os.path.join(repo, "native", "build", "test_app"),
                         "1000"])
    h.execute(1)  # boot the process (it parks in nanosleep)
    cpid = p._child.pid
    w = _HEAP_WINDOWS.get(cpid)
    if w is None:
        p.kill()
        return None
    import struct as _struct

    start, cur = _struct.unpack_from("<QQ", w[0], HEAP_START_OFF)
    need = iovs * size
    if cur - start < need:  # window too small for the gather: grow check
        p.kill()
        return None
    chunks = [(start + i * size, size) for i in range(iovs)]
    assert all(_heap_loc(cpid, a, n) is not None for a, n in chunks)
    t0 = time.perf_counter()
    for _ in range(reps):
        _vm_read_multi(cpid, chunks)
    dt = time.perf_counter() - t0
    p.kill()
    return dt


if __name__ == "__main__":
    sys.exit(main())
