"""Measure raw TPU gather throughput: element gathers from vectors vs row
gathers from [N, W] matrices, at merge-relevant shapes. Indices are passed
as arguments (no constant folding); donate nothing; block on results."""

import time

import jax
import jax.numpy as jnp
import numpy as np

rng = np.random.default_rng(3)
K = 65_536


def timed(f, *args, n=50):
    g = jax.jit(f)
    jax.block_until_ready(g(*args))
    t0 = time.monotonic()
    for _ in range(n):
        o = g(*args)
    jax.block_until_ready(o)
    return (time.monotonic() - t0) / n * 1000


def main():
    vec32 = jnp.asarray(rng.integers(0, 2**31, K, np.int64), jnp.int32)
    vec64 = jnp.asarray(rng.integers(0, 2**62, K, np.int64), jnp.int64)
    for out_n in (10_000, 160_000, 320_000, 640_000):
        idx = jnp.asarray(rng.integers(0, K, out_n).astype(np.int32))
        t32 = timed(lambda v, i: v[i], vec32, idx)
        t64 = timed(lambda v, i: v[i], vec64, idx)
        print(
            f"element gather out={out_n:7d}: i32 {t32:7.3f} ms "
            f"({t32 * 1e6 / out_n:6.2f} ns/el)  i64 {t64:7.3f} ms"
        )

    # 7-field SoA gather at [H] x R ranks fused in one jit
    H, R = 10_000, 32
    first = jnp.asarray(np.sort(rng.integers(0, K - R, H)).astype(np.int32))

    def multi(v64a, v64b, v32a, p0, p1, p2, p3, first):
        outs = []
        for r in range(R):
            i = first + 1 + r
            outs.append(
                (v64a[i], v64b[i], v32a[i], p0[i], p1[i], p2[i], p3[i])
            )
        return outs

    args = (vec64, vec64, vec32, vec32, vec32, vec32, vec32, first)
    t = timed(multi, *args, n=10)
    print(f"SoA rank-loop gather 7 fields x R={R} x H={H}: {t:7.3f} ms")

    # row gather from [K, 9] for reference (with index as arg)
    mat = jnp.asarray(rng.integers(0, 2**31, (K, 9), np.int64), jnp.int32)
    idx = jnp.asarray(rng.integers(0, K, 640_000).astype(np.int32))
    t = timed(lambda m, i: m[i], mat, idx)
    print(f"row gather [640k, 9] from [K, 9]: {t:7.3f} ms")


if __name__ == "__main__":
    main()
