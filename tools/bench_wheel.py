"""Sweep timer-wheel slot counts (ISSUE 12 satellite).

Two legs, mirroring tools/bench_bucketq.py / bench_popk.py:

  pair leg  — the microstep-visible op pair in isolation: the merged
              queue∪wheel head-compare + pop + timer push against the
              queue-only pop + push it replaces, at H hosts, queue
              capacity C, and a ladder of wheel slot counts S. Shows the
              raw per-microstep delta the wheel costs/saves.

  e2e leg   — a small tgen-TCP engine run (the flagship model) end to
              end at each S (plus the wheel-off baseline), reporting
              wall-clock, wheel occupancy high-water, and spill counts —
              the slot-sizing signal: pick the smallest S whose spill
              count is zero (a spilled timer is exact but pays the queue
              path it was supposed to leave).

Usage:  python tools/bench_wheel.py [--hosts 10000] [--cap 28]
            [--slots 2,4,8,16] [--iters 50] [--e2e] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_enable_x64", True)


def _mk_queue(h: int, cap: int, fill: int, seed: int):
    from shadow_tpu.ops.events import make_queue, pack_order, push_one

    rng = np.random.default_rng(seed)
    q = make_queue(h, cap)
    for j in range(fill):
        t = rng.integers(1_000, 1_000_000, size=h).astype(np.int64)
        order = np.asarray(pack_order(1, np.arange(h), np.full(h, j)))
        q = push_one(
            q, jnp.ones((h,), bool), jnp.asarray(t), jnp.asarray(order),
            jnp.full((h,), 3, jnp.int32), jnp.zeros((h, 4), jnp.int32),
        )
    return q


def bench_pair(h: int, cap: int, slots: int, iters: int) -> dict:
    """Median wall of one jitted (pop + push) step: queue-only baseline
    vs merged queue∪wheel with the timer push routed to the wheel."""
    from shadow_tpu.core.engine import _pop_min_merged
    from shadow_tpu.ops.events import pack_order, q_pop_min, q_push_many
    from shadow_tpu.ops.wheel import make_wheel, wheel_push_many

    q = _mk_queue(h, cap, fill=max(cap // 2, 1), seed=1)
    limit = jnp.int64(2_000_000)
    t_new = jnp.full((h,), 500_000, jnp.int64)
    order_new = jnp.asarray(pack_order(1, jnp.arange(h), jnp.full((h,), 99)))
    kind = jnp.full((h,), 3, jnp.int32)
    payload = jnp.zeros((h, 4), jnp.int32)
    mask = jnp.ones((h,), bool)

    @jax.jit
    def base(queue):
        queue, ev, active = q_pop_min(queue, limit)
        return q_push_many(queue, [(mask, t_new, order_new, kind, payload)])

    w = make_wheel(h, slots)
    # pre-load the wheel halfway so pops/pushes touch realistic caches
    for j in range(max(slots // 2, 1)):
        o = jnp.asarray(pack_order(1, jnp.arange(h), jnp.full((h,), 50 + j)))
        w = wheel_push_many(
            w, [(mask, jnp.full((h,), 800_000 + j, jnp.int64), o, kind,
                 payload)]
        )

    @jax.jit
    def wheeled(queue, wheel):
        queue, wheel, ev, active = _pop_min_merged(queue, wheel, limit)
        wheel = wheel_push_many(
            wheel, [(mask, t_new, order_new, kind, payload)]
        )
        return queue, wheel

    def timeit(fn, *args):
        fn(*args)  # compile
        walls = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            walls.append(time.perf_counter() - t0)
        return float(np.median(walls))

    t_base = timeit(base, q)
    t_wheel = timeit(wheeled, q, w)
    return {
        "hosts": h, "cap": cap, "slots": slots, "iters": iters,
        "queue_only_us": round(t_base * 1e6, 1),
        "merged_us": round(t_wheel * 1e6, 1),
        "ratio": round(t_wheel / max(t_base, 1e-12), 3),
    }


def bench_e2e(slots: int, hosts: int = 60, stop_s: int = 20) -> dict:
    """Small tgen-TCP engine leg at one wheel size (0 = off baseline)."""
    from tests.engine_harness import build_sim, mk_hosts
    from shadow_tpu.core.engine import Engine

    cfg, model, params, mstate, events = build_sim(
        "tgen_tcp",
        mk_hosts(hosts, {"flow_segs": 12, "flows": 4, "cwnd_cap": 8,
                         "rto_min": "100 ms"}),
        stop_s * 1_000_000_000,
        loss=0.03, latency=10_000_000, sends_budget=16, qcap=28,
        queue_block=7, wheel_slots=slots, rounds_per_chunk=256,
    )
    eng = Engine(cfg, model)
    state, params = eng.init_state(params, mstate, events, seed=1)
    state = eng.run_chunk(state, params)  # compile + first chunk
    t0 = time.perf_counter()
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        if chunks > 2000:
            raise SystemExit("e2e leg failed to terminate")
    jax.block_until_ready(state.stats.events)
    wall = time.perf_counter() - t0
    s = jax.device_get(state.stats)
    out = {
        "slots": slots,
        "wall_s": round(wall, 3),
        "sim_s_per_wall_s": round(
            int(state.now) / 1e9 / max(wall, 1e-9), 2
        ),
        "events": int(np.asarray(s.events).sum()),
        "digest_xor": f"{int(np.bitwise_xor.reduce(s.digest)):016x}",
    }
    if slots:
        out["wheel_occ_hwm"] = int(np.asarray(s.wheel_occ_hwm).max())
        out["wheel_spilled"] = int(np.asarray(s.wheel_spilled).sum())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hosts", type=int, default=10_000)
    ap.add_argument("--cap", type=int, default=28)
    ap.add_argument("--slots", default="2,4,8,16")
    ap.add_argument("--iters", type=int, default=50)
    ap.add_argument("--e2e", action="store_true",
                    help="also run the small tgen end-to-end ladder")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    ladder = [int(s) for s in str(args.slots).split(",") if s]

    rows = {"pair": [], "e2e": []}
    for s in ladder:
        r = bench_pair(args.hosts, args.cap, s, args.iters)
        rows["pair"].append(r)
        if not args.json:
            print(
                f"pair H={r['hosts']} C={r['cap']} S={s}: "
                f"queue-only {r['queue_only_us']} us, merged "
                f"{r['merged_us']} us (x{r['ratio']})"
            )
    if args.e2e:
        base = bench_e2e(0)
        rows["e2e"].append(base)
        if not args.json:
            print(f"e2e S=off: {base['sim_s_per_wall_s']} sim-s/wall-s "
                  f"digest {base['digest_xor']}")
        for s in ladder:
            r = bench_e2e(s)
            rows["e2e"].append(r)
            if not args.json:
                match = "OK" if r["digest_xor"] == base["digest_xor"] else (
                    "DIGEST MISMATCH"
                )
                print(
                    f"e2e S={s}: {r['sim_s_per_wall_s']} sim-s/wall-s, "
                    f"occ_hwm {r['wheel_occ_hwm']}, spilled "
                    f"{r['wheel_spilled']} [{match}]"
                )
        bad = [r for r in rows["e2e"][1:]
               if r["digest_xor"] != base["digest_xor"]]
        if bad:
            print("FAIL: wheel digests diverged from the off baseline",
                  file=sys.stderr)
            return 1
    if args.json:
        print(json.dumps(rows, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
