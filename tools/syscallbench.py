"""Syscall round-trip rate through the managed-process plane (VERDICT r4
#5; reference managed_thread.rs:187-324 is the loop being measured).

Measures WALL syscalls/sec for emulated arms (full futex-channel round
trip: seccomp trap -> shim -> IPC futex -> Python dispatch -> reply ->
futex resume) against the shim-local clock_gettime baseline (answered
from shared memory with no context switch, the shim_sys.c:25-114
precedent). Usage:

    python tools/syscallbench.py [iters]

Prints one JSON line; numbers land in BASELINE.md.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from shadow_tpu.host import CpuHost, HostConfig  # noqa: E402
from shadow_tpu.native_plane import ensure_built, spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEC = 1_000_000_000


def run_mode(mode: str, iters: int) -> dict:
    h = CpuHost(HostConfig(name="bench", ip="10.0.0.1", seed=1, host_id=0))
    binpath = os.path.join(REPO, "native", "build", "bench_syscall")
    t0 = time.monotonic()
    p = spawn_native(h, [binpath, mode, str(iters)])
    h.execute(3600 * SEC)
    wall = time.monotonic() - t0
    out = b"".join(p.stdout).decode()
    err = b"".join(p.stderr).decode()
    assert p.exit_code == 0, (mode, out, err)
    calls = iters * (2 if mode == "pipe" else 1)
    return {
        "mode": mode,
        "iters": iters,
        "emulated_calls": calls if mode != "clock" else 0,
        "wall_s": round(wall, 3),
        "calls_per_s": round(calls / wall),
        "us_per_call": round(1e6 * wall / calls, 2),
    }


def main() -> int:
    assert ensure_built(), "native plane unavailable"
    iters = int(sys.argv[1]) if len(sys.argv) > 1 else 30_000
    rows = {
        m: run_mode(m, iters)
        for m in ("clock", "getpid", "stdout", "fcntl", "pipe")
    }
    # the clock mode's per-call time is the shim-local floor; the fcntl
    # round trip minus that floor is the IPC + Python dispatch cost
    rt = rows["fcntl"]["us_per_call"] - rows["clock"]["us_per_call"]
    print(
        json.dumps(
            {
                "clock_local_us": rows["clock"]["us_per_call"],
                "getpid_local_us": rows["getpid"]["us_per_call"],
                "stdout_write_us": rows["stdout"]["us_per_call"],
                "fcntl_roundtrip_us": rows["fcntl"]["us_per_call"],
                "pipe_rw_us": rows["pipe"]["us_per_call"],
                "roundtrip_minus_local_us": round(rt, 2),
                "fcntl_calls_per_s": rows["fcntl"]["calls_per_s"],
                "pipe_calls_per_s": rows["pipe"]["calls_per_s"],
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
