"""Time the engine's building blocks standalone at bench shapes
(H=10k hosts, C=16 queue slots, N=60k outbox entries)."""

import sys, pathlib
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import shadow_tpu  # noqa: F401  x64

import time

import jax
import jax.numpy as jnp
from jax import lax

from shadow_tpu.ops.events import EventQueue, pop_min, push_one, EVENT_PAYLOAD_WORDS
from shadow_tpu.ops.merge import merge_flat_events
from shadow_tpu.simtime import TIME_MAX

H, C, N = 10_000, 16, 60_000
P = EVENT_PAYLOAD_WORDS


def timeit(fn, *args, iters=20):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 8)
    q = EventQueue(
        t=jnp.where(jax.random.uniform(ks[0], (H, C)) < 0.3,
                    jax.random.randint(ks[1], (H, C), 0, 1 << 40, dtype=jnp.int64),
                    TIME_MAX),
        order=jax.random.randint(ks[2], (H, C), 0, 1 << 60, dtype=jnp.int64),
        kind=jnp.zeros((H, C), jnp.int32),
        payload=jnp.zeros((H, C, P), jnp.int32),
        dropped=jnp.zeros((H,), jnp.int64),
    )
    dst = jax.random.randint(ks[3], (N,), 0, H, dtype=jnp.int32)
    t = jax.random.randint(ks[4], (N,), 0, 1 << 40, dtype=jnp.int64)
    order = jax.random.randint(ks[5], (N,), 0, 1 << 60, dtype=jnp.int64)
    kind = jnp.ones((N,), jnp.int32)
    payload = jnp.zeros((N, P), jnp.int32)
    valid = jax.random.uniform(ks[6], (N,)) < 0.17  # ~10k live

    merge_u = jax.jit(lambda *a: merge_flat_events(*a, 16, shed_urgency=True))
    merge_a = jax.jit(lambda *a: merge_flat_events(*a, 16, shed_urgency=False))
    print("merge urgency :", timeit(merge_u, q, dst, t, order, kind, payload, valid), "ms")
    print("merge append  :", timeit(merge_a, q, dst, t, order, kind, payload, valid), "ms")

    popf = jax.jit(lambda q: pop_min(q, jnp.full((H,), 1 << 41, jnp.int64)))
    print("pop_min       :", timeit(popf, q), "ms")

    mask = jax.random.uniform(ks[7], (H,)) < 0.5
    tpush = jnp.full((H,), 123456789, jnp.int64)
    opush = jnp.arange(H, dtype=jnp.int64)
    kpush = jnp.ones((H,), jnp.int32)
    ppush = jnp.zeros((H, P), jnp.int32)
    pushf = jax.jit(lambda q: push_one(q, mask, tpush, opush, kpush, ppush))
    print("push_one      :", timeit(pushf, q), "ms")

    # merge internals
    @jax.jit
    def sort_phase(dst, t, order, valid):
        dst_key = jnp.where(valid, dst, jnp.int32(H))
        return lax.sort((dst_key, t, order, jnp.arange(N, dtype=jnp.int32)), num_keys=3)

    @jax.jit
    def rank_phase(s_dst):
        seg_start = jnp.searchsorted(s_dst, s_dst, side="left")
        return jnp.arange(N, dtype=jnp.int64) - seg_start

    @jax.jit
    def slotmap_phase(qt):
        free = qt == TIME_MAX
        free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
        scatter_r = jnp.where(free & (free_rank < 16), free_rank, 16)
        slot_of_rank = jnp.full((H, 16), -1, jnp.int32)
        hh = jnp.broadcast_to(jnp.arange(H)[:, None], free.shape)
        cc = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32)[None, :], free.shape)
        return slot_of_rank.at[hh, scatter_r].set(cc, mode="drop")

    @jax.jit
    def gather_phase(s_idx, kind, payload):
        return kind[s_idx], payload[s_idx]

    @jax.jit
    def final_scatter(qt, h_scatter, s_scatter, s_t):
        return qt.at[h_scatter, s_scatter].set(s_t, mode="drop")

    s = sort_phase(dst, t, order, valid)
    print("  sort3       :", timeit(sort_phase, dst, t, order, valid), "ms")
    print("  searchsorted:", timeit(rank_phase, s[0]), "ms")
    print("  slotmap     :", timeit(slotmap_phase, q.t), "ms")
    print("  gather kp   :", timeit(gather_phase, s[3], kind, payload), "ms")
    hs = jnp.clip(s[0], 0, H - 1)
    ss = jnp.zeros((N,), jnp.int32)
    print("  final scat  :", timeit(final_scatter, q.t, hs, ss, s[1]), "ms")


if __name__ == "__main__":
    main()
