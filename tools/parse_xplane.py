"""Minimal TPU profiler-trace analyzer (no tensorboard-plugin needed).

Hand-rolled protobuf wire parser for the xplane.pb files written by
`jax.profiler.start_trace`/`stop_trace` — the image's
tensorboard-plugin-profile is version-skewed against its tensorflow, so
this reads the XSpace wire format directly and prints per-op durations
for the TPU device plane. This is how every round-5 engine finding
(cond boundary copies, merge gather costs, routing-row DMAs) was
measured. Usage:

    python tools/parse_xplane.py /tmp/my_trace
"""

import glob, sys
from collections import defaultdict

def varint(buf, i):
    r = 0; s = 0
    while True:
        b = buf[i]; i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80: return r, i
        s += 7

def fields(buf):
    i = 0
    while i < len(buf):
        key, i = varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0: v, i = varint(buf, i)
        elif wt == 2:
            ln, i = varint(buf, i); v = buf[i:i+ln]; i += ln
        elif wt == 5: v = buf[i:i+4]; i += 4
        elif wt == 1: v = buf[i:i+8]; i += 8
        else: raise ValueError(wt)
        yield fn, wt, v

path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tgen_trace"
f = sorted(glob.glob(path + "/plugins/profile/*/vm.xplane.pb"))[-1]
sp = open(f, "rb").read()
for fn, wt, plane in fields(sp):
    if fn != 1: continue
    name = b""; evm = {}; lines = []
    for pfn, pwt, pv in fields(plane):
        if pfn == 2: name = pv
        elif pfn == 3: lines.append(pv)
        elif pfn == 4:
            k = None; meta = None
            for mfn, mwt, mv in fields(pv):
                if mfn == 1: k = mv
                elif mfn == 2: meta = mv
            if meta is not None:
                mname = b""
                for efn, ewt, ev in fields(meta):
                    if efn == 2: mname = ev
                evm[k] = mname.decode(errors="replace")
    if b"TPU" not in name and b"tpu" not in name: continue
    agg = defaultdict(float)
    for line in lines:
        lname = b""
        evs = []
        for lfn, lwt, lv in fields(line):
            if lfn == 2: lname = lv
            elif lfn == 4: evs.append(lv)
        for lv in evs:
            mid = 0; dur = 0
            for efn, ewt, ev in fields(lv):
                if efn == 1: mid = ev
                elif efn == 3: dur = ev
            agg[(lname.decode(errors="replace"), evm.get(mid, str(mid)))] += dur / 1e12
    rows = sorted(agg.items(), key=lambda kv: -kv[1])
    for (ln, n), s in rows[:30]:
        print(f"{s*1000:9.1f} ms  [{ln[:14]}] {n[:95]}")
