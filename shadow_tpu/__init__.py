"""shadow_tpu — a TPU-native conservative-PDES network simulation framework.

Capability target: the Shadow discrete-event network simulator
(reference: /root/reference, see SURVEY.md). The conservative
parallel-discrete-event core — per-host event queues, deterministic total event
ordering, safe-time (runahead) round barriers, and the packet relay plane
(latency / loss / bandwidth token buckets / CoDel) — runs on TPU as batched
JAX/XLA kernels sharded over a device mesh. Host models (timers, PHOLD,
tgen-style traffic, gossip) execute as vectorized handlers over all simulated
hosts at once.

Design notes (vs reference architecture, cited per SURVEY.md):
  - reference unit of parallelism: one OS thread per core with host work
    stealing (src/lib/scheduler/src/thread_per_core.rs). Here: the host axis is
    a sharded array dimension over a `jax.sharding.Mesh`; a "scheduling round"
    is one trace of `round_step` and the cross-thread min-reduction
    (src/main/core/manager.rs:459-464) is a `lax.pmin` over ICI.
  - reference event ordering (src/main/core/work/event.rs:102-155): total order
    by (time, packets-before-local, src host, per-src seqno). Here the same
    key is packed into (t:i64, order:i64) and used by every pop/merge kernel,
    which is what makes the simulation bit-deterministic under any sharding.
"""

import os as _os

import jax as _jax

# Simulated time is int64 nanoseconds (reference SimulationTime,
# src/lib/shadow-shim-helper-rs/src/simulation_time.rs). TPU emulates i64; the
# precision is required for deterministic event ordering.
_jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the reference starts instantly (main.c:11);
# our first-chunk XLA compiles cost 40-140 s per fresh process. Caching
# compiled executables on disk amortizes that across runs of the same
# config (second run: <5 s, see BASELINE.md "warm start"). Opt out with
# SHADOW_TPU_COMPILE_CACHE=off or point it elsewhere with =<dir>.
_cache = _os.environ.get("SHADOW_TPU_COMPILE_CACHE", "")
if _cache != "off":
    _jax.config.update(
        "jax_compilation_cache_dir",
        _cache
        or _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            ".xla_cache",
        ),
    )
    # cache every compile that takes noticeable time (default threshold
    # is 1 s; our engine compiles are the whole point of the cache)
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

from shadow_tpu.simtime import (  # noqa: E402
    NS_PER_SEC,
    NS_PER_MSEC,
    NS_PER_USEC,
    TIME_MAX,
    EMUTIME_EPOCH_UNIX_SEC,
)

__version__ = "0.1.0"

__all__ = [
    "NS_PER_SEC",
    "NS_PER_MSEC",
    "NS_PER_USEC",
    "TIME_MAX",
    "EMUTIME_EPOCH_UNIX_SEC",
    "__version__",
]
