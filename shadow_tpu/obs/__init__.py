"""Observability plane: pcap capture, strace logging, perf timers.

Reference: §5.1 of SURVEY.md — `utility/pcap_writer.rs:6-90` (per-interface
lo/eth0 captures), the strace formatter (`host/syscall/formatter.rs`,
modes off/standard/deterministic at configuration.rs:1162), and the
`perf_timers` feature (host.rs:721-729).
"""

from shadow_tpu.obs.pcap import PcapWriter, packet_bytes
from shadow_tpu.obs.strace import StraceLogger
from shadow_tpu.obs.perf import PerfTimers
from shadow_tpu.obs.simlog import SimLogger, format_sim_time
from shadow_tpu.obs.tracer import ReplicaTracer, RoundTracer, TraceRing
from shadow_tpu.obs.memory import MemoryGuard, MemoryMonitor
from shadow_tpu.obs.netobs import FlowCollector, FlowLedger

__all__ = [
    "FlowCollector",
    "FlowLedger",
    "MemoryGuard",
    "MemoryMonitor",
    "PcapWriter",
    "PerfTimers",
    "ReplicaTracer",
    "RoundTracer",
    "SimLogger",
    "StraceLogger",
    "TraceRing",
    "format_sim_time",
    "packet_bytes",
]
