"""Wall-clock phase timers for the simulation drivers.

Reference: the `perf_timers` feature wrapping host execution and each
syscall (host.rs:721-729, handler/mod.rs:169-195). Here the interesting
phases are the driver's: device window execution, host-plane execution,
inject/drain staging — reported in sim-stats for perf debugging.
"""

from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager


class PerfTimers:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self.totals: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextmanager
    def time(self, phase: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[phase] += time.perf_counter() - t0
            self.counts[phase] += 1

    def report(self) -> dict:
        return {
            phase: {
                "total_s": round(self.totals[phase], 4),
                "calls": self.counts[phase],
            }
            for phase in sorted(self.totals)
        }
