"""pcap capture with synthesized Ethernet/IPv4/UDP/TCP frames.

Reference: `src/main/utility/pcap_writer.rs:6-90` — classic pcap v2.4
global header + per-packet records, timestamps in *simulated* time, a
configurable snap length (`pcap_capture_size`), wired into the network
interface (network_interface.c) per host as `lo.pcap` / `eth0.pcap`.
The reference emits IP frames reconstructed from its packet headers; here
frames are synthesized from `NetPacket` (+ TCP `Segment` when present) —
enough for wireshark/tcpdump and for the determinism byte-compare gate
(determinism1_compare.cmake diffs these files).
"""

from __future__ import annotations

import socket
import struct

from shadow_tpu.host.sockets import NetPacket, PROTO_TCP, PROTO_UDP
from shadow_tpu.simtime import sim_to_emulated_ns

PCAP_MAGIC = 0xA1B2C3D4
LINKTYPE_ETHERNET = 1


def _ip(addr: str) -> bytes:
    try:
        return socket.inet_aton(addr)
    except OSError:
        return b"\x00\x00\x00\x00"


def _checksum(data: bytes) -> int:
    if len(data) % 2:
        data += b"\x00"
    s = sum(struct.unpack(f"!{len(data) // 2}H", data))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return (~s) & 0xFFFF


def packet_bytes(pkt: NetPacket) -> bytes:
    """Synthesize an Ethernet+IPv4+{UDP,TCP} frame for `pkt`.

    Payloads are truncated to what IPv4 length fields can carry — the
    capture path must never be able to abort a simulation."""
    payload = pkt.payload[:65495]
    if pkt.proto == PROTO_UDP:
        transport = struct.pack(
            "!HHHH", pkt.src_port, pkt.dst_port, 8 + len(payload), 0
        ) + payload
    else:
        seg = pkt.seg
        flags = seg.flags if seg is not None else 0
        seq = seg.seq if seg is not None else 0
        ack = seg.ack if seg is not None else 0
        wnd = seg.wnd if seg is not None else 0
        offset_flags = (5 << 12) | (flags & 0x3F)
        transport = struct.pack(
            "!HHIIHHHH",
            pkt.src_port,
            pkt.dst_port,
            seq & 0xFFFFFFFF,
            ack & 0xFFFFFFFF,
            offset_flags,
            min(wnd, 0xFFFF),
            0,
            0,
        ) + payload
    total = 20 + len(transport)
    ip_hdr = struct.pack(
        "!BBHHHBBH4s4s",
        0x45,
        0,
        total,
        0,
        0,
        64,
        pkt.proto,
        0,
        _ip(pkt.src_ip),
        _ip(pkt.dst_ip),
    )
    ip_hdr = ip_hdr[:10] + struct.pack("!H", _checksum(ip_hdr)) + ip_hdr[12:]
    eth = b"\x02" + b"\x00" * 5 + b"\x02" + b"\x00" * 5 + b"\x08\x00"
    return eth + ip_hdr + transport


class PcapWriter:
    """One capture file (per host interface, like the reference's)."""

    def __init__(self, path: str, snaplen: int = 65535):
        self.snaplen = snaplen
        self._f = open(path, "wb")
        self._f.write(
            struct.pack("<IHHiIII", PCAP_MAGIC, 2, 4, 0, 0, snaplen,
                        LINKTYPE_ETHERNET)
        )
        self.count = 0

    def write(self, t_ns: int, pkt: NetPacket):
        """`t_ns` is simulation time; stamps are EmulatedTime (epoch
        2000-01-01, emulated_time.rs:28-48) so captures read like the
        reference's."""
        full = packet_bytes(pkt)
        frame = full[: self.snaplen]
        emu = sim_to_emulated_ns(t_ns)
        self._f.write(
            struct.pack(
                "<IIII",
                emu // 1_000_000_000,
                (emu % 1_000_000_000) // 1000,
                len(frame),
                len(full),  # orig_len: untruncated size (pcap spec)
            )
        )
        self._f.write(frame)
        self.count += 1

    def close(self):
        if not self._f.closed:
            self._f.close()
