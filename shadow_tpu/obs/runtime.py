"""Runtime observatory: wall-clock attribution plane, compile-latency
ledger, and bridge-stall telemetry (`observability.runtime`).

Wall-clock time is the denominator of every BASELINE headline, yet until
now it was attributed by hand: BASELINE r6's "~83% of the CPU microstep
is handler dispatch" decomposition was a one-off manual exercise, the
drivers' scattered host timers (PR 3 PerfTimers, bench per-chunk walls,
supervisor snapshot spans) never reconciled against the run's total
wall, and cold jit compiles silently leaked into measured windows. This
module is the third observatory (after HBM, obs/memory.py, and network,
obs/netobs.py), and it follows the same observer contract: everything
here is HOST-SIDE — no traced code, digests/events/drops bit-identical
on or off, the default jaxpr fingerprints byte-unchanged
(tests/test_runtime.py is the gate).

Three instruments:

  `CompileLedger` — every jitted chunk program the engine caches (the
  base chunk, each merge-gear variant, each (gear, capacity, budget)
  pressure rung, the cosim prepare/guarded programs) records its
  lowering + backend-compile wall time (precise, via the
  jax.monitoring duration events emitted during the cold call), the
  TRIGGER that caused the compile (cold start, gear shift, pressure
  regrow), and cache hit counts. This is the number ROADMAP item 6's
  persistent/async compile cache must beat.

  `WallLedger` — unifies the drivers' host timers into one per-chunk
  attribution: each chunk's wall is split into named spans (compile /
  dispatch / host_python / snapshot / replay / export) whose sum equals
  the chunk wall EXACTLY (the residual not covered by an explicit span
  is host_python), paired with a per-chunk realtime factor
  (sim-seconds advanced per wall-second — Rain's serving-level metric,
  arxiv 2606.03352) surfaced as the heartbeat `rt=` field. Spans that
  overlap a dispatch (a replay's snapshot restore, a regrown program's
  compile) are RE-ATTRIBUTED out of the dispatch span rather than
  double-counted, so per-chunk sums always reconcile.

  `BridgeTelemetry` — the cosim bridge's per-window stall split:
  CPU-plane execute vs device-plane wall vs bridge (staging injection,
  capture draining, and the residual marshalling between them), plus a
  per-syscall-batch injection-latency histogram. This is ROADMAP item
  4's before/after instrument: the COREC-style lock-free bridge (arxiv
  2401.12815) is justified exactly when the bridge share dominates.

`tools/rt_report.py` reads the exported `runtime{}` block and prints the
attribution verdict; `tools/bench_compare.py` diffs the bench rows'
`runtime{}` blocks for realtime-factor and compile-wall regressions.
"""

from __future__ import annotations

import time
from contextlib import contextmanager, nullcontext
from typing import Any

# WallLedger span names. `host_python` is the residual: whatever part of
# a chunk's wall no explicit span covered (heartbeats, counter reads,
# controller bookkeeping) — which is why per-chunk span sums equal the
# chunk wall by construction.
SPAN_NAMES = (
    "compile", "dispatch", "host_python", "snapshot", "replay", "export",
)

# bounded in-memory series (a resident-service run must not grow
# unbounded Python lists; overflow is counted, never silent)
MAX_CHUNK_RECORDS = 4096
MAX_WINDOW_RECORDS = 4096
# rt series entries exported into sim-stats (the newest are kept — the
# steady-state tail is the serving-posture signal)
MAX_EXPORTED_SERIES = 512

# per-syscall-batch injection-latency histogram bucket edges (seconds).
# Decade-ish log spacing from 0.1 ms to 3 s; the last bucket is +inf.
INJECT_HIST_EDGES_S = (
    1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0,
)


# ---------------------------------------------------------------------------
# compile ledger
# ---------------------------------------------------------------------------

# jax.monitoring routes compile-pipeline durations to listeners; the ONE
# module-level listener dispatches to whichever ledger entry is armed
# (the drivers are single-threaded, so a stack suffices). Registered
# lazily and exactly once per process — jax 0.4.x has no unregister API,
# and re-registering per ledger would leak listeners across the many
# sims a test process builds.
_ACTIVE_ENTRIES: list[dict] = []
_LISTENER_ON = False


def _on_compile_duration(name: str, secs: float, **_kw) -> None:
    if not _ACTIVE_ENTRIES:
        return
    e = _ACTIVE_ENTRIES[-1]
    if name.endswith("jaxpr_trace_duration"):
        e["trace_s"] += secs
    elif name.endswith("jaxpr_to_mlir_module_duration"):
        e["lower_s"] += secs
    elif name.endswith("backend_compile_duration"):
        e["compile_s"] += secs


def _ensure_listener() -> bool:
    global _LISTENER_ON
    if _LISTENER_ON:
        return True
    try:
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(
            _on_compile_duration
        )
        _LISTENER_ON = True
    except Exception:
        # older/foreign jax without the monitoring API: the ledger still
        # records cold-call walls, only the lower/compile split is absent
        pass
    return _LISTENER_ON


class CompileLedger:
    """Per-program compile accounting for lazily-jitted chunk programs.

    `instrument(kind, label, trigger, fn)` wraps a jitted callable: the
    FIRST call (the one that traces, lowers, and compiles) is recorded
    as one ledger entry — cold-call wall, plus the precise trace/lower/
    backend-compile durations harvested from jax.monitoring while the
    call runs — and every later call counts as a cache hit. Wrapping is
    pure host-side observation: the callable's arguments and results
    pass through untouched, so the traced program cannot change.

    `wall` (optional, a WallLedger) receives a reattribution of the
    compile pipeline's seconds out of the enclosing dispatch span, so
    the attribution plane shows compiles as compile time, not as a
    mysteriously slow first dispatch.
    """

    def __init__(self, wall: "WallLedger | None" = None):
        self.entries: list[dict] = []
        self.cache_hits = 0
        self.wall = wall
        self.monitored = _ensure_listener()

    def instrument(self, kind: str, label: str, trigger: str, fn):
        entry_box: dict[str, Any] = {"e": None}

        def wrapped(*args, **kw):
            e = entry_box["e"]
            if e is not None:
                e["hits"] += 1
                self.cache_hits += 1
                return fn(*args, **kw)
            e = {
                "kind": kind, "label": label, "trigger": trigger,
                "trace_s": 0.0, "lower_s": 0.0, "compile_s": 0.0,
                "cold_s": 0.0, "t0": time.monotonic(), "hits": 0,
            }
            entry_box["e"] = e
            self.entries.append(e)
            _ACTIVE_ENTRIES.append(e)
            try:
                out = fn(*args, **kw)
            finally:
                _ACTIVE_ENTRIES.pop()
                e["cold_s"] = time.monotonic() - e["t0"]
                if self.wall is not None:
                    # in the finally: a cold call that compiles and then
                    # RAISES (a freshly regrown rung dying in-dispatch)
                    # must still show its pipeline as compile time, or
                    # the enclosing dispatch/replay spans absorb it and
                    # the controller's compile-delta subtraction sees 0
                    self.wall.reattribute(
                        "dispatch", "compile", self.pipeline_s(e)
                    )
            return out

        return wrapped

    @staticmethod
    def pipeline_s(e: dict) -> float:
        """One entry's trace+lower+compile pipeline seconds (the honest
        'what a warm cache would have saved' figure; cold_s additionally
        includes the first dispatch's enqueue)."""
        return e["trace_s"] + e["lower_s"] + e["compile_s"]

    def total_pipeline_s(self) -> float:
        return sum(self.pipeline_s(e) for e in self.entries)

    def compiles_in(self, t0: float, t1: float) -> float:
        """Pipeline seconds of entries whose cold call STARTED inside
        the [t0, t1) monotonic window — what bench.py subtracts so
        sim-s/wall-s never silently folds a mid-run compile in."""
        return sum(
            self.pipeline_s(e) for e in self.entries if t0 <= e["t0"] < t1
        )

    def events(self) -> list[tuple[str, float, float]]:
        """(label, t0_monotonic, duration_s) per compile — the Chrome
        trace's compile track (RoundTracer.note_compiles)."""
        return [
            (
                f"{e['kind']}:{e['label']} ({e['trigger']})",
                e["t0"],
                max(self.pipeline_s(e), e["cold_s"], 1e-6),
            )
            for e in self.entries
        ]

    def summary(self) -> dict:
        by_trigger: dict[str, int] = {}
        for e in self.entries:
            by_trigger[e["trigger"]] = by_trigger.get(e["trigger"], 0) + 1
        return {
            "programs": len(self.entries),
            "cache_hits": self.cache_hits,
            "monitored": self.monitored,
            "compile_wall_s": round(self.total_pipeline_s(), 4),
            "backend_compile_s": round(
                sum(e["compile_s"] for e in self.entries), 4
            ),
            "lower_s": round(
                sum(e["lower_s"] + e["trace_s"] for e in self.entries), 4
            ),
            "cold_wall_s": round(
                sum(e["cold_s"] for e in self.entries), 4
            ),
            "by_trigger": by_trigger,
            "entries": [
                {
                    "kind": e["kind"], "label": e["label"],
                    "trigger": e["trigger"],
                    "compile_s": round(e["compile_s"], 4),
                    "lower_s": round(e["lower_s"] + e["trace_s"], 4),
                    "cold_s": round(e["cold_s"], 4),
                    "hits": e["hits"],
                }
                for e in self.entries
            ],
        }


# ---------------------------------------------------------------------------
# wall-clock attribution plane
# ---------------------------------------------------------------------------


def span_or_null(wall: "WallLedger | None", name: str):
    """`with span_or_null(wall, "dispatch"):` — nullcontext when the
    observatory is off, so driver loops carry one code path."""
    return wall.span(name) if wall is not None else nullcontext()


class WallLedger:
    """Per-chunk wall-clock attribution with a realtime-factor series.

    Protocol (driver loop):
        wall.sync_sim(int(state.now))          # once, before the loop
        ...
        wall.chunk_start()
        with wall.span("dispatch"): ...        # dispatch + block
        with wall.span("export"): ...          # drains/samples
        wall.chunk_end(int(state.now))         # closes the chunk

    Per-chunk exactness: chunk wall == sum of its spans, because the
    residual no span covered is folded into `host_python` at
    `chunk_end`. Overlapping attribution (a compile inside a dispatch,
    a snapshot inside a supervised dispatch) goes through
    `reattribute(frm, to, sec)`, which MOVES seconds between spans at
    chunk close (clamped at the source span's balance) instead of
    counting them twice.
    """

    def __init__(self, max_chunks: int = MAX_CHUNK_RECORDS):
        self.totals = {s: 0.0 for s in SPAN_NAMES}
        self.chunks: list[dict] = []
        self.chunks_total = 0
        self.chunks_dropped = 0
        self.max_chunks = int(max_chunks)
        self.rt_last: float | None = None
        self.wall0: float | None = None
        self._cur: dict | None = None
        self._t0: float | None = None
        self._moves: list[tuple[str, str, float]] = []
        self._last_sim_ns = 0

    def sync_sim(self, sim_ns: int) -> None:
        """Adopt the state's current sim time as the rt baseline, so a
        resumed/restored run's first chunk is not credited with the
        whole pre-restore horizon."""
        self._last_sim_ns = int(sim_ns)

    def chunk_start(self) -> None:
        self._cur = {s: 0.0 for s in SPAN_NAMES}
        self._t0 = time.monotonic()
        self._moves = []
        if self.wall0 is None:
            self.wall0 = self._t0

    @contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            sec = time.perf_counter() - t0
            if self._cur is not None:
                self._cur[name] = self._cur.get(name, 0.0) + sec
            else:
                # outside a chunk (warm-up work): totals-only accounting
                self.totals[name] = self.totals.get(name, 0.0) + sec

    def reattribute(self, frm: str, to: str, sec: float) -> None:
        """Move `sec` from span `frm` to span `to` inside the open chunk
        (applied clamped at chunk close). No-op outside a chunk."""
        if self._cur is not None and sec > 0:
            self._moves.append((frm, to, float(sec)))

    def pending_to(self, name: str) -> float:
        """Seconds already queued for reattribution INTO `name` in the
        open chunk — lets a caller measuring an enclosing interval
        subtract what an inner instrument already claimed."""
        return sum(s for _f, t, s in self._moves if t == name)

    def chunk_end(self, sim_ns: int) -> float | None:
        """Close the open chunk; returns its realtime factor."""
        if self._cur is None:
            return None
        t1 = time.monotonic()
        cur, self._cur = self._cur, None
        for frm, to, sec in self._moves:
            sec = min(sec, cur.get(frm, 0.0))
            cur[frm] = cur.get(frm, 0.0) - sec
            cur[to] = cur.get(to, 0.0) + sec
        self._moves = []
        wall = max(t1 - (self._t0 or t1), 0.0)
        cur["host_python"] += max(wall - sum(cur.values()), 0.0)
        for k, v in cur.items():
            self.totals[k] = self.totals.get(k, 0.0) + v
        sim_delta = max(int(sim_ns) - self._last_sim_ns, 0)
        self._last_sim_ns = int(sim_ns)
        rt = (sim_delta / 1e9) / max(wall, 1e-9)
        self.rt_last = rt
        self.chunks_total += 1
        rec = {
            "wall_s": wall, "sim_ns": sim_delta, "rt": rt,
            "spans": {k: v for k, v in cur.items() if v > 0},
        }
        if len(self.chunks) < self.max_chunks:
            self.chunks.append(rec)
        else:
            self.chunks_dropped += 1
        return rt

    # ---- exporters ---------------------------------------------------------

    def rt_series(self) -> list[float]:
        return [c["rt"] for c in self.chunks]

    def summary(self, total_wall_s: float | None = None) -> dict:
        attributed = sum(self.totals.values())
        rts = sorted(self.rt_series())
        chunk_walls = sum(c["wall_s"] for c in self.chunks)
        out: dict[str, Any] = {
            "spans_s": {k: round(v, 4) for k, v in self.totals.items()},
            "chunks": self.chunks_total,
            "chunks_recorded": len(self.chunks),
            "attributed_wall_s": round(attributed, 4),
            "chunk_wall_s": round(chunk_walls, 4),
        }
        if total_wall_s:
            out["total_wall_s"] = round(float(total_wall_s), 4)
            out["attributed_share"] = round(
                attributed / max(float(total_wall_s), 1e-9), 4
            )
        if attributed > 0:
            out["shares"] = {
                k: round(v / attributed, 4)
                for k, v in self.totals.items() if v > 0
            }
        if rts:
            series = self.rt_series()[-MAX_EXPORTED_SERIES:]
            out["realtime_factor"] = {
                "overall": round(
                    sum(c["sim_ns"] for c in self.chunks) / 1e9
                    / max(chunk_walls, 1e-9), 4,
                ),
                "last": round(self.rt_last or 0.0, 4),
                "p50": round(rts[len(rts) // 2], 4),
                "min": round(rts[0], 4),
                "max": round(rts[-1], 4),
                "series": [round(r, 4) for r in series],
                **(
                    {"series_dropped": self.chunks_total - len(series)}
                    if self.chunks_total > len(series) else {}
                ),
            }
        return out


# ---------------------------------------------------------------------------
# bridge-stall telemetry (cosim)
# ---------------------------------------------------------------------------


class BridgeTelemetry:
    """Per-window wall split for the hybrid (cosim) bridge.

    Three lanes per window — `cpu_plane` (the CPU hosts' event loops),
    `device_plane` (the guarded device dispatch), and `bridge` (staging
    injection + capture draining + the residual marshalling between the
    planes) — plus a per-syscall-batch injection-latency histogram
    (`note_batch`). The split answers ROADMAP item 4's question: a
    bridge share that dominates the window wall is the COREC ring-buffer
    rebuild's justification; one that doesn't says the bottleneck is
    elsewhere. Host-side observation only."""

    LANES = ("cpu_plane", "device_plane", "bridge")

    def __init__(self, max_windows: int = MAX_WINDOW_RECORDS):
        self.totals = {k: 0.0 for k in self.LANES}
        self.windows: list[dict] = []
        self.windows_total = 0
        self.windows_dropped = 0
        self.max_windows = int(max_windows)
        self.rt_last: float | None = None
        self.batch_counts = [0] * (len(INJECT_HIST_EDGES_S) + 1)
        self.batches = 0
        self.batch_entries = 0
        self.batch_wall_s = 0.0
        self._cur: dict | None = None
        self._t0: float | None = None
        self._last_sim_ns = 0

    def sync_sim(self, sim_ns: int) -> None:
        self._last_sim_ns = int(sim_ns)

    def window_start(self) -> None:
        self._cur = {k: 0.0 for k in self.LANES}
        self._t0 = time.monotonic()

    def note(self, lane: str, sec: float) -> None:
        if self._cur is not None:
            self._cur[lane] += max(float(sec), 0.0)

    def note_batch(self, sec: float, entries: int) -> None:
        """One staged-send injection batch (one `_inject` dispatch): its
        wall latency lands in the log-spaced histogram, its seconds in
        the window's bridge lane."""
        self.batches += 1
        self.batch_entries += int(entries)
        self.batch_wall_s += max(float(sec), 0.0)
        i = 0
        while i < len(INJECT_HIST_EDGES_S) and sec > INJECT_HIST_EDGES_S[i]:
            i += 1
        self.batch_counts[i] += 1
        self.note("bridge", sec)

    def window_end(self, sim_ns: int) -> float | None:
        if self._cur is None:
            return None
        t1 = time.monotonic()
        cur, self._cur = self._cur, None
        wall = max(t1 - (self._t0 or t1), 0.0)
        # the residual — python marshalling between the measured lanes —
        # is bridge work by definition (it exists only to couple them)
        cur["bridge"] += max(wall - sum(cur.values()), 0.0)
        for k, v in cur.items():
            self.totals[k] += v
        sim_delta = max(int(sim_ns) - self._last_sim_ns, 0)
        self._last_sim_ns = int(sim_ns)
        rt = (sim_delta / 1e9) / max(wall, 1e-9)
        self.rt_last = rt
        self.windows_total += 1
        rec = {"wall_s": wall, "sim_ns": sim_delta, "rt": rt, **cur}
        if len(self.windows) < self.max_windows:
            self.windows.append(rec)
        else:
            self.windows_dropped += 1
        return rt

    def summary(self) -> dict:
        total = sum(self.totals.values())
        rts = sorted(w["rt"] for w in self.windows)
        out: dict[str, Any] = {
            "windows": self.windows_total,
            "windows_recorded": len(self.windows),
            "spans_s": {k: round(v, 4) for k, v in self.totals.items()},
            "syscall_batches": {
                "batches": self.batches,
                "entries": self.batch_entries,
                "wall_s": round(self.batch_wall_s, 4),
                "hist_edges_s": list(INJECT_HIST_EDGES_S),
                "hist_counts": list(self.batch_counts),
            },
        }
        if total > 0:
            out["shares"] = {
                k: round(v / total, 4) for k, v in self.totals.items()
            }
            out["bridge_share"] = out["shares"].get("bridge", 0.0)
        if rts:
            out["realtime_factor"] = {
                "last": round(self.rt_last or 0.0, 4),
                "p50": round(rts[len(rts) // 2], 4),
                "min": round(rts[0], 4),
                "max": round(rts[-1], 4),
            }
        return out


# ---------------------------------------------------------------------------
# shared report assembly
# ---------------------------------------------------------------------------


def assemble_runtime_report(
    *,
    wall: WallLedger | None = None,
    compiles: CompileLedger | None = None,
    bridge: BridgeTelemetry | None = None,
    total_wall_s: float | None = None,
) -> dict:
    """The ONE `runtime{}` block assembly every exporter shares (sim
    stats_report, the hybrid driver, bench rows) — the netobs
    `assemble_network_report` idiom, so the block's shape cannot drift
    between exporters."""
    out: dict[str, Any] = {}
    if wall is not None:
        out.update(wall.summary(total_wall_s))
    if bridge is not None:
        out["bridge"] = bridge.summary()
        if "realtime_factor" not in out and bridge.windows:
            rts = sorted(w["rt"] for w in bridge.windows)
            sim_s = sum(w["sim_ns"] for w in bridge.windows) / 1e9
            walls = sum(w["wall_s"] for w in bridge.windows)
            out["realtime_factor"] = {
                "overall": round(sim_s / max(walls, 1e-9), 4),
                "last": round(bridge.rt_last or 0.0, 4),
                "p50": round(rts[len(rts) // 2], 4),
                "min": round(rts[0], 4),
                "max": round(rts[-1], 4),
                "series": [
                    round(w["rt"], 4)
                    for w in bridge.windows[-MAX_EXPORTED_SERIES:]
                ],
            }
    if compiles is not None:
        out["compiles"] = compiles.summary()
    return out


def bench_runtime_block(
    compiles: CompileLedger | None,
    wall: WallLedger | None,
    sim_adv_s: float,
    wall_s: float,
    window: tuple[float, float] | None = None,
) -> dict:
    """The BENCH row's compact `runtime{}` block (the diffable shape
    tools/bench_compare.py gates on): total compile wall, the compile
    wall that landed INSIDE the measured window, the realtime factor,
    and the factor with in-window compiles excluded — so sim-s/wall-s
    never silently folds a cold compile in."""
    out: dict[str, Any] = {
        "realtime_factor": round(sim_adv_s / max(wall_s, 1e-9), 4),
    }
    if compiles is not None:
        cin = (
            compiles.compiles_in(*window) if window is not None else 0.0
        )
        out.update({
            "compile_wall_s": round(compiles.total_pipeline_s(), 4),
            "compile_in_window_s": round(cin, 4),
            "compile_programs": len(compiles.entries),
            "cache_hits": compiles.cache_hits,
            "realtime_factor_ex_compile": round(
                sim_adv_s / max(wall_s - cin, 1e-9), 4
            ),
        })
    if wall is not None:
        s = wall.summary()
        if "shares" in s:
            out["shares"] = s["shares"]
    return out
