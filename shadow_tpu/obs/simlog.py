"""Async simulation logger: sim-time-stamped, host-contexted records.

Reference: `src/main/core/logger/shadow_logger.rs:17-60` — producers send
records to per-thread channels; a dedicated flush thread writes; an async
flush kicks in at 100k queued lines and producers block (back-pressure) at
1M so an over-chatty simulation cannot exhaust memory. Every record carries
the SIMULATED time and the host context, so `tools/parse_shadow.py` can
attribute lines per host for debugging.

Record format (deterministic — no wall-clock content):

    HH:MM:SS.nnnnnnnnn [level] [host] message
"""

from __future__ import annotations

import threading
from collections import deque
from typing import IO

LEVELS = ("trace", "debug", "info", "warning", "error")
_LEVEL_NUM = {name: i for i, name in enumerate(LEVELS)}


def format_sim_time(t_ns: int) -> str:
    s, ns = divmod(max(int(t_ns), 0), 1_000_000_000)
    m, sec = divmod(s, 60)
    h, m = divmod(m, 60)
    return f"{h:02d}:{m:02d}:{sec:02d}.{ns:09d}"


class SimLogger:
    """Buffered async logger with a flush thread and bounded memory.

    `log()` never blocks below BACKPRESSURE_QLEN queued records; the flush
    thread drains opportunistically and is kicked eagerly once ASYNC_FLUSH
    records are pending (shadow_logger.rs's 100k/1M thresholds)."""

    ASYNC_FLUSH = 100_000
    BACKPRESSURE = 1_000_000

    def __init__(self, target: str | IO, level: str = "info"):
        if isinstance(target, str):
            self._fh: IO = open(target, "w")
            self._own = True
        else:
            self._fh = target
            self._own = False
        self.level = _LEVEL_NUM.get(level, 2)
        self._q: deque[str] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self.records = 0
        self.dropped_backpressure_waits = 0
        self._thread = threading.Thread(
            target=self._flush_loop, name="shadow-logger", daemon=True
        )
        self._thread.start()

    # ---- producer side -----------------------------------------------------

    def log(self, t_ns: int, host: str, level: str, msg: str):
        if _LEVEL_NUM.get(level, 2) < self.level:
            return
        line = f"{format_sim_time(t_ns)} [{level}] [{host}] {msg}\n"
        with self._cv:
            while len(self._q) >= self.BACKPRESSURE:
                # sync back-pressure: the producer waits for the flush
                # thread instead of growing without bound
                self.dropped_backpressure_waits += 1
                self._cv.wait(timeout=1.0)
            self._q.append(line)
            self.records += 1
            if len(self._q) == 1 or len(self._q) >= self.ASYNC_FLUSH:
                self._cv.notify_all()

    def info(self, t_ns: int, host: str, msg: str):
        self.log(t_ns, host, "info", msg)

    def warning(self, t_ns: int, host: str, msg: str):
        self.log(t_ns, host, "warning", msg)

    # ---- flush thread ------------------------------------------------------

    def _flush_loop(self):
        while True:
            with self._cv:
                if not self._q and self._stop:
                    return
                if not self._q:
                    self._cv.wait(timeout=0.1)
                batch = list(self._q)
                self._q.clear()
                self._cv.notify_all()  # wake back-pressured producers
            if batch:
                self._fh.writelines(batch)
                self._fh.flush()

    def close(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
        if self._own:
            self._fh.close()


def parse_log(path: str) -> dict:
    """Summarize a shadow.log: record counts per host and per level (the
    parse-shadow.py consumption contract)."""
    per_host: dict[str, int] = {}
    per_level: dict[str, int] = {}
    n = 0
    with open(path) as f:
        for line in f:
            parts = line.split(" ", 3)
            if len(parts) < 4 or not parts[1].startswith("["):
                continue
            level = parts[1].strip("[]")
            host = parts[2].strip("[]")
            per_level[level] = per_level.get(level, 0) + 1
            per_host[host] = per_host.get(host, 0) + 1
            n += 1
    return {"records": n, "per_host": per_host, "per_level": per_level}
