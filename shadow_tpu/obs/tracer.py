"""Device-resident round tracer: in-scan trace ring + host-side exporters.

The reference ships per-host trackers and heartbeats (tracker.c,
manager.rs:675-717) and wraps every host execution in perf_timers
(host.rs:721-729) — all of it host-side code observing host-side state.
Here the event loop lives inside a jitted `lax.scan`/`while_loop`, where
no Python observer can see; PRs 1-2 had to be diagnosed blind through
end-to-end digests. This module is the missing layer:

  device side — `TraceRing`, a fixed-size `int64[world, R, F]` ring (+
  a per-shard cursor) threaded through the engine's scan carry. The
  round loop appends ONE row per completed round (`core/engine.py
  _trace_round`) recording what that round did: window bounds, events,
  microsteps, counter deltas, exchange traffic, queue-occupancy
  high-water. The ring is an OBSERVER — rows are derived from values the
  round already computed and feed nothing back, so digests, event
  counts, and drop counters are bit-identical with tracing on or off
  (tests/test_tracer.py is the gate).

  host side — `RoundTracer` drains the ring at chunk boundaries (where
  control already returns to the host), pairs rounds with wall-clock
  chunk spans, and exports a Chrome-trace/Perfetto JSON timeline, a
  Prometheus-style text metrics file, and a summary dict for
  sim-stats.json.

Ring sizing: the driver sizes R = rounds_per_chunk, so a drain per chunk
can never wrap. A consumer that drains less often only loses the oldest
rows — counted in `RoundTracer.lost`, never silent.
"""

from __future__ import annotations

import json
import os
from typing import Any, NamedTuple

import numpy as np

# one ring row per round; column order is the engine's write order
# (core/engine.py _trace_round builds the row by these indices)
TRACE_FIELDS = (
    "round",          # global round index at entry (== stats.rounds before)
    "window_start",   # completed-up-to time at round entry (ns)
    "window_end",     # this round's window end (ns)
    "events",         # events executed this round (this shard's hosts)
    "microsteps",     # queue dispatches this round (this shard)
    "popk_deferred",  # K-way batch events peeked but deferred (delta)
    "bq_rebuilds",    # wholesale block-cache rebuilds (delta)
    "ici_bytes",      # exchange-collective bytes (delta, this shard)
    "sends",          # outbox entries staged this round (this shard)
    "a2a_shed",       # all-to-all block-overflow sheds (delta)
    "occ_hwm",        # max per-host queue occupancy after the exchange
    "next_time",      # min queue head after the round (TIME_MAX if empty)
    "ob_hwm",         # max sends any ONE host staged this round (gear signal)
    "gear",           # active merge gear (outbox columns sorted; B = full)
    "faults_dropped", # fault-plane drops this round (delta, this shard)
    "faults_delayed", # fault-plane delays this round (delta, this shard)
    "hosts_down",     # hosts inside a crash window at this round's end
    "cap",            # active per-host queue capacity (pressure plane)
    # network observatory (obs/netobs.py; zero unless observability.network)
    "ec_timer",       # timer-class events executed this round (delta)
    "ec_pkt",         # packet-class events executed this round (delta)
    "ec_app",         # app-class events executed this round (delta)
    "flows",          # flows completed this round (delta, this shard)
    "bind_shard",     # shard whose local min bound the barrier this round
    # hierarchical exchange tiers (core/engine.py _exchange_hierarchical;
    # zero unless experimental.exchange: hierarchical on a multi-device
    # mesh) — the xw= heartbeat pair, per round
    "xw_intra",       # intra-shard compaction staging bytes (delta)
    "xw_inter",       # inter-shard wire bytes (delta, this shard)
)
TRACE_COLS = len(TRACE_FIELDS)
(
    COL_ROUND,
    COL_WINDOW_START,
    COL_WINDOW_END,
    COL_EVENTS,
    COL_MICROSTEPS,
    COL_POPK_DEFERRED,
    COL_BQ_REBUILDS,
    COL_ICI_BYTES,
    COL_SENDS,
    COL_A2A_SHED,
    COL_OCC_HWM,
    COL_NEXT_TIME,
    COL_OB_HWM,
    COL_GEAR,
    COL_FAULTS_DROPPED,
    COL_FAULTS_DELAYED,
    COL_HOSTS_DOWN,
    COL_CAP,
    COL_EC_TIMER,
    COL_EC_PKT,
    COL_EC_APP,
    COL_FLOWS,
    COL_BIND_SHARD,
    COL_XW_INTRA,
    COL_XW_INTER,
) = range(TRACE_COLS)


# flow-track export cap (note_flows): complete events drawn per trace
MAX_FLOW_EVENTS = 20_000


class TraceRing(NamedTuple):
    """The device half: a bounded per-shard record buffer in the scan carry.

    Sharded like the per-shard stats counters: `rows` is [world, R, F]
    with the leading axis on the mesh (each shard owns one [1, R, F]
    plane), `cursor` is [world]. The cursor counts rounds recorded since
    simulation start and is NEVER reset — writes land at `cursor % R`, and
    the host-side drain reconstructs the new rows from (previous cursor,
    current cursor), which keeps the drain read-only (no reset dispatch,
    no donation hazard)."""

    rows: Any  # i64[world, R, F]
    cursor: Any  # i64[world] rounds recorded since start (monotone)


def make_trace_ring(world: int, rounds: int) -> TraceRing:
    import jax.numpy as jnp

    return TraceRing(
        rows=jnp.zeros((world, rounds, TRACE_COLS), jnp.int64),
        cursor=jnp.zeros((world,), jnp.int64),
    )


class RoundTracer:
    """Host-side collector/exporter for the device trace ring.

    Usage (the drivers wire this up when `observability.trace` is on):

        tracer = RoundTracer(ring_rounds=cfg.rounds_per_chunk)
        ...
        state = engine.run_chunk(state, params)   # rounds recorded in-jit
        jax.block_until_ready(state)
        tracer.drain(state.trace, wall_t0=t0, wall_t1=t1)
        ...
        tracer.write_chrome_trace("trace.json")
        tracer.write_metrics("metrics.prom")
    """

    def __init__(self, ring_rounds: int):
        if ring_rounds <= 0:
            raise ValueError(f"ring_rounds must be > 0, got {ring_rounds}")
        self.ring_rounds = int(ring_rounds)
        self._cursor = 0  # rounds drained so far (device-cursor value)
        self._origin = 0  # device-cursor value when this tracer started
        self.lost = 0  # rounds overwritten before a drain reached them
        self._chunks: list[dict] = []  # wall spans paired with round counts
        self._rows: list[np.ndarray] = []  # [world, n, F] per drain
        self._wall0: float | None = None  # wall origin for the trace
        # wall-clock HBM samples (obs/memory.py MemoryMonitor, sampled at
        # chunk boundaries): (wall_t, (per-shard bytes,)) — exported as a
        # counter track on the wall-clock timeline + Prometheus gauges
        self._memory: list[tuple[float, tuple[int, ...]]] = []
        # drained flow-ledger records (obs/netobs.py FlowCollector rows,
        # [n, FLOW_COLS]) — exported as a sim-time flow track. Bounded:
        # beyond MAX_FLOW_EVENTS the newest records are counted, not drawn
        # (a million-flow run must not grow a GB-scale trace JSON).
        self._flows: list[np.ndarray] = []
        self._flows_seen = 0
        # integrity-sentinel violation notes (core/integrity.py): the
        # deterministic-abort naming dicts the driver hands over. A SIDE
        # channel, deliberately not a ring column — appending a column
        # would widen every traced program's ring and churn the frozen
        # default jaxpr fingerprints, and a violating chunk records at
        # most ONE violating round per attempt anyway (the loop aborts
        # there), so per-abort notes are complete.
        self._violations: list[dict] = []
        # runtime-observatory compile records (obs/runtime.CompileLedger
        # .events(): (label, t0_monotonic, duration_s)) — exported as a
        # compile track on the wall-clock timeline. A side channel like
        # the violations above: compiles happen host-side between (or
        # before) chunks, never inside the ring.
        self._compiles: list[tuple[str, float, float]] = []

    # ---- collection --------------------------------------------------------

    def sync_cursor(self, ring: TraceRing) -> int:
        """Adopt the ring's CURRENT cursor as the drain origin without
        exporting anything. Drivers call this once before their loop so a
        state restored from a checkpoint (or re-run after a prior loop)
        does not replay rows recorded before this tracer existed — those
        would otherwise be mis-read as fresh rounds and mis-counted as
        ring losses."""
        import jax

        self._cursor = int(np.max(np.asarray(jax.device_get(ring.cursor))))
        self._origin = self._cursor
        return self._cursor

    def drain(self, ring: TraceRing, *, wall_t0: float | None = None,
              wall_t1: float | None = None) -> int:
        """Pull rounds recorded since the last drain; returns how many."""
        import jax

        cur = int(np.max(np.asarray(jax.device_get(ring.cursor))))
        n = cur - self._cursor
        lost = max(0, n - self.ring_rounds) if n > 0 else 0
        if n > 0:
            self.lost += lost
            rows = np.asarray(jax.device_get(ring.rows))  # [world, R, F]
            idx = [i % self.ring_rounds
                   for i in range(self._cursor + lost, cur)]
            self._rows.append(rows[:, idx, :])
            self._cursor = cur
        if wall_t0 is not None and wall_t1 is not None:
            if self._wall0 is None:
                self._wall0 = wall_t0
            # chunk records count EXPORTED rows only, so chunk totals always
            # reconcile with the round events in the trace (overwritten rows
            # are accounted in `lost`, not smeared into a chunk)
            self._chunks.append(
                {"t0": wall_t0, "t1": wall_t1, "rounds": max(n, 0) - lost}
            )
        return max(n, 0) - lost

    def truncate_to_round(self, rounds: int) -> int:
        """Drop drained rows whose global round index is >= `rounds`.

        The graceful-abort path exports state rewound to the supervisor's
        last snapshot, but chunks that SUCCEEDED after that snapshot were
        already drained — without this, trace totals would cover rounds
        the exported sim-stats prefix does not, breaking trace-vs-stats
        reconciliation in exactly the artifacts the abort path exists to
        keep trustworthy. The drivers call it with the rewound state's
        `stats.rounds`. Chunk records give their round counts back
        newest-first so chunk totals keep reconciling too. Returns how
        many rounds were dropped."""
        dropped = 0
        kept: list[np.ndarray] = []
        for seg in self._rows:
            # COL_ROUND is the global (replicated) round counter at round
            # entry, so shard 0's column is canonical for every shard
            mask = seg[0, :, COL_ROUND] < rounds
            n_drop = int((~mask).sum())
            if n_drop:
                dropped += n_drop
                seg = seg[:, mask, :]
            if seg.shape[1]:
                kept.append(seg)
        if dropped:
            self._rows = kept
            self._cursor -= dropped  # keep `rounds`/future drains coherent
            left = dropped
            for c in reversed(self._chunks):
                take = min(c["rounds"], left)
                c["rounds"] -= take
                left -= take
                if left <= 0:
                    break
        return dropped

    def note_memory(self, wall_t: float, per_shard_bytes) -> None:
        """Record one live-memory sample (per-shard bytes_in_use) against
        the wall clock. Pure observation — feeds only the exporters."""
        self._memory.append(
            (float(wall_t), tuple(int(b) for b in per_shard_bytes))
        )

    def note_flows(self, records: np.ndarray) -> None:
        """Adopt a batch of drained flow-ledger records ([n, FLOW_COLS],
        obs/netobs.py column order) for the sim-time flow track. Records
        beyond the export cap are counted in otherData, never silent."""
        n = int(records.shape[0])
        if n == 0:
            return
        kept = sum(r.shape[0] for r in self._flows)
        room = max(0, MAX_FLOW_EVENTS - kept)
        if room:
            self._flows.append(np.asarray(records[:room], np.int64))
        self._flows_seen += n

    def note_compiles(self, events) -> None:
        """Adopt the runtime observatory's compile records ((label, t0,
        duration_s) tuples against the monotonic clock) for the
        wall-clock compile track. Replaces any prior set — the drivers
        hand over the ledger's full event list at export time."""
        self._compiles = [
            (str(n), float(t0), float(d)) for n, t0, d in events
        ]

    def note_violation(self, info: dict) -> None:
        """Record a deterministic integrity violation (the controller's
        iv_deterministic dict: signature [(shard, round, mask)...] +
        detail text) for the exported trace — rendered as an instant
        event on the sim-time timeline and surfaced in summary()."""
        self._violations.append(dict(info))

    def reset_flows(self, records: np.ndarray) -> None:
        """Replace the flow track with exactly `records` — the abort
        paths call this with the FlowCollector's post-truncation record
        set so the drawn track covers exactly the exported prefix (the
        flow-track analogue of `truncate_to_round`; without it, flows
        drained from chunks the export rewound past would still be
        drawn)."""
        self._flows = []
        self._flows_seen = 0
        self.note_flows(np.asarray(records, np.int64))

    @property
    def rounds(self) -> int:
        return self._cursor - self._origin - self.lost

    def rows(self) -> np.ndarray:
        """All drained records, [world, N, F] (N = rounds traced)."""
        if not self._rows:
            return np.zeros((1, 0, TRACE_COLS), np.int64)
        return np.concatenate(self._rows, axis=1)

    # ---- exporters ---------------------------------------------------------

    def to_chrome_trace(self) -> dict:
        """Chrome-trace/Perfetto JSON (the `traceEvents` array format).

        Two timelines, distinguished by pid:
          pid 1 "sim-time"  — one complete ("X") event per ROUND, ts/dur in
            sim-time microseconds (1 sim ns -> 1 trace ns is too fine for
            the viewers; us keeps 120 sim-s runs navigable). Shard 0's row
            is the canonical record (cat "round", exactly one per completed
            round); other shards' rows ride on their own tids (cat
            "round_shard"). Rounds that staged exchange traffic add an
            instant event on the "exchange" track.
          pid 2 "wall-clock" — one X event per jitted CHUNK dispatch, ts in
            wall microseconds since the first chunk.
        """
        ev: list[dict] = [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "sim-time (rounds)"}},
            {"ph": "M", "name": "process_name", "pid": 2,
             "args": {"name": "wall-clock (chunks)"}},
        ]
        rows = self.rows()
        world = rows.shape[0]
        for s in range(world):
            ev.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": s + 1, "args": {"name": f"rounds shard {s}"}})
        ev.append({"ph": "M", "name": "thread_name", "pid": 1,
                   "tid": world + 1, "args": {"name": "exchange"}})
        if self._flows:
            ev.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": world + 2, "args": {"name": "flows"}})
        for s in range(world):
            for r in rows[s]:
                args = {f: int(v) for f, v in zip(TRACE_FIELDS, r)}
                ts = r[COL_WINDOW_START] / 1e3  # sim ns -> us
                dur = max(int(r[COL_WINDOW_END] - r[COL_WINDOW_START]), 1) / 1e3
                ev.append({
                    "name": f"round {int(r[COL_ROUND])}",
                    "cat": "round" if s == 0 else "round_shard",
                    "ph": "X", "ts": ts, "dur": dur,
                    "pid": 1, "tid": s + 1, "args": args,
                })
                if s == 0 and (r[COL_SENDS] > 0 or r[COL_A2A_SHED] > 0):
                    ev.append({
                        "name": f"exchange {int(r[COL_SENDS])} sends",
                        "cat": "exchange", "ph": "i", "s": "t",
                        "ts": r[COL_WINDOW_END] / 1e3,
                        "pid": 1, "tid": world + 1,
                        "args": {"sends": int(r[COL_SENDS]),
                                 "a2a_shed": int(r[COL_A2A_SHED]),
                                 "ici_bytes": int(r[COL_ICI_BYTES])},
                    })
        # flow track (obs/netobs.py ledger records): one complete event per
        # drained flow, spanning [t_start, t_end) on the sim-time timeline
        if self._flows:
            from shadow_tpu.obs.netobs import (
                FCOL_BYTES, FCOL_DST, FCOL_FLOW, FCOL_RETRANSMITS,
                FCOL_SRC, FCOL_T_END, FCOL_T_START,
            )

            for rec in np.concatenate(self._flows, axis=0):
                ts = rec[FCOL_T_START] / 1e3  # sim ns -> us
                dur = max(int(rec[FCOL_T_END] - rec[FCOL_T_START]), 1) / 1e3
                ev.append({
                    "name": f"flow {int(rec[FCOL_SRC])}"
                            f"->{int(rec[FCOL_DST])}",
                    "cat": "flow", "ph": "X", "ts": ts, "dur": dur,
                    "pid": 1, "tid": world + 2,
                    "args": {
                        "flow": int(rec[FCOL_FLOW]),
                        "bytes": int(rec[FCOL_BYTES]),
                        "retransmits": int(rec[FCOL_RETRANSMITS]),
                    },
                })
        # wall-clock anchor: the earliest of the first chunk's start,
        # the first memory sample, and the first compile's t0 — the base
        # program compiles BEFORE the first chunk dispatch, and an
        # anchor after it would put the compile track at negative ts
        wall0 = self._wall0
        if self._memory and wall0 is None:
            wall0 = self._memory[0][0]
        if self._compiles:
            c0 = min(t0 for _n, t0, _d in self._compiles)
            wall0 = c0 if wall0 is None else min(wall0, c0)
        for i, c in enumerate(self._chunks):
            ev.append({
                "name": f"chunk {i}", "cat": "chunk", "ph": "X",
                "ts": (c["t0"] - (wall0 or 0.0)) * 1e6,
                "dur": max((c["t1"] - c["t0"]) * 1e6, 1.0),
                "pid": 2, "tid": 1,
                "args": {"rounds": c["rounds"]},
            })
        # wall-clock HBM counter track (obs/memory.py samples): Chrome's
        # "C" events render a stacked per-shard area under the chunk track
        for t, shards in self._memory:
            ev.append({
                "name": "hbm_bytes", "cat": "memory", "ph": "C",
                "ts": (t - (wall0 or 0.0)) * 1e6,
                "pid": 2, "tid": 1,
                "args": {f"shard{s}": b for s, b in enumerate(shards)},
            })
        # runtime-observatory compile track (obs/runtime.CompileLedger):
        # one X event per recorded program compile, under the chunk
        # track — a cold compile inside a chunk's wall reads directly
        # against that chunk's span
        if self._compiles:
            ev.append({"ph": "M", "name": "thread_name", "pid": 2,
                       "tid": 2, "args": {"name": "compiles"}})
            for name, t0, dur in self._compiles:
                ev.append({
                    "name": name, "cat": "compile", "ph": "X",
                    "ts": (t0 - (wall0 or 0.0)) * 1e6,
                    "dur": max(dur * 1e6, 1.0),
                    "pid": 2, "tid": 2,
                })
        # integrity-violation track: one instant event per recorded
        # deterministic violation, anchored to the violating round's
        # window when its row was traced (violating attempts are usually
        # discarded pre-drain, so fall back to the last traced window)
        if self._violations:
            ev.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": world + 3, "args": {"name": "integrity"}})
            last_ts = (
                rows[0, -1, COL_WINDOW_END] / 1e3 if rows.shape[1] else 0.0
            )
            for v in self._violations:
                ts = last_ts
                for _shard, rnd, _mask in v.get("signature", []):
                    hit = rows[0][rows[0][:, COL_ROUND] == rnd]
                    if hit.shape[0]:
                        ts = hit[0][COL_WINDOW_START] / 1e3
                    break
                ev.append({
                    "name": "integrity violation", "cat": "integrity",
                    "ph": "i", "s": "g", "ts": ts,
                    "pid": 1, "tid": world + 3,
                    "args": {k: v[k] for k in ("signature", "detail")
                             if k in v},
                })
        other = {
            "rounds_traced": self.rounds,
            "rounds_lost": self.lost,
            "trace_fields": list(TRACE_FIELDS),
        }
        if self._flows_seen:
            drawn = sum(r.shape[0] for r in self._flows)
            other["flows_drawn"] = drawn
            other["flows_not_drawn"] = self._flows_seen - drawn
        return {
            "traceEvents": ev,
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def totals(self) -> dict:
        """Summed/maxed counters over every traced round (all shards).
        The empty-trace case returns zeros under the SAME keys, so the
        sim-stats `trace` block's schema never depends on whether any
        round was drained."""
        rows = self.rows()
        flat = rows.reshape(-1, TRACE_COLS)
        empty = flat.shape[0] == 0

        def _sum(col):
            return 0 if empty else int(flat[:, col].sum())

        def _max(col):
            return 0 if empty else int(flat[:, col].max())

        return {
            "events": _sum(COL_EVENTS),
            "microsteps": _sum(COL_MICROSTEPS),
            "popk_deferred": _sum(COL_POPK_DEFERRED),
            "bq_rebuilds": _sum(COL_BQ_REBUILDS),
            "ici_bytes": _sum(COL_ICI_BYTES),
            "sends": _sum(COL_SENDS),
            "a2a_shed": _sum(COL_A2A_SHED),
            "occ_hwm": _max(COL_OCC_HWM),
            "next_time": _max(COL_NEXT_TIME),
            "ob_hwm": _max(COL_OB_HWM),
            "faults_dropped": _sum(COL_FAULTS_DROPPED),
            "faults_delayed": _sum(COL_FAULTS_DELAYED),
            "hosts_down_max": _max(COL_HOSTS_DOWN),
            "cap_max": _max(COL_CAP),
            # network-observatory columns (zero on untraced-netobs runs)
            "ec_timer": _sum(COL_EC_TIMER),
            "ec_pkt": _sum(COL_EC_PKT),
            "ec_app": _sum(COL_EC_APP),
            "flows": _sum(COL_FLOWS),
        }

    def gear_histogram(self) -> dict:
        """Rounds traced per active merge gear, {gear_cols: rounds}.
        Shard 0's rows are the canonical record (the gear is a chunk-wide
        static, identical on every shard). Empty when nothing is traced."""
        rows = self.rows()
        if rows.shape[1] == 0:
            return {}
        gears, counts = np.unique(rows[0, :, COL_GEAR], return_counts=True)
        return {int(g): int(c) for g, c in zip(gears, counts)}

    def summary(self) -> dict:
        """Compact digest for sim-stats.json embedding."""
        chunks = [c for c in self._chunks if c["rounds"] > 0]
        wall = sum(c["t1"] - c["t0"] for c in chunks)
        t = self.totals()
        return {
            "rounds_traced": self.rounds,
            "rounds_lost": self.lost,
            "chunks": len(chunks),
            "rounds_per_chunk": round(
                self.rounds / max(len(chunks), 1), 2
            ),
            "wall_seconds_traced": round(wall, 4),
            "events": t["events"],
            "microsteps": t["microsteps"],
            "queue_occupancy_hwm": t["occ_hwm"],
            "ici_bytes": t["ici_bytes"],
            **(
                {"integrity_violations": [dict(v) for v in self._violations]}
                if self._violations else {}
            ),
        }

    def to_metrics_text(self, extra: dict | None = None) -> str:
        """Prometheus text exposition format (one final scrape's worth):
        counters totalled over the run, gauges for the high-water marks.
        `extra` adds flat {name: number} gauges (e.g. report fields)."""
        t = self.totals()
        rows = self.rows()
        lines: list[str] = []
        seen: set[str] = set()

        def metric(name, kind, value, help_txt, labels=""):
            if name in seen:  # one HELP/TYPE block per metric name, or the
                return  # exposition file is unscrapeable
            seen.add(name)
            lines.append(f"# HELP shadow_tpu_{name} {help_txt}")
            lines.append(f"# TYPE shadow_tpu_{name} {kind}")
            lines.append(f"shadow_tpu_{name}{labels} {value}")

        metric("rounds_total", "counter", self.rounds,
               "scheduling rounds traced")
        metric("rounds_lost_total", "counter", self.lost,
               "rounds overwritten in the ring before a drain")
        metric("events_total", "counter", t["events"],
               "events executed in traced rounds")
        metric("microsteps_total", "counter", t["microsteps"],
               "queue dispatches in traced rounds")
        metric("popk_deferred_total", "counter", t["popk_deferred"],
               "K-way batch events peeked but deferred")
        metric("bq_rebuilds_total", "counter", t["bq_rebuilds"],
               "wholesale bucket-cache rebuilds")
        metric("ici_bytes_total", "counter", t["ici_bytes"],
               "exchange-collective bytes moved")
        metric("exchange_sends_total", "counter", t["sends"],
               "outbox entries exchanged")
        metric("a2a_shed_total", "counter", t["a2a_shed"],
               "all-to-all block-overflow sheds")
        metric("queue_occupancy_hwm", "gauge", t["occ_hwm"],
               "max per-host queue occupancy observed after any exchange")
        metric("faults_dropped_total", "counter", t["faults_dropped"],
               "events/packets discarded by injected faults")
        metric("faults_delayed_total", "counter", t["faults_delayed"],
               "events/packets delayed by injected faults")
        metric("hosts_down_max", "gauge", t["hosts_down_max"],
               "max hosts simultaneously inside a crash window")
        if self._memory:
            last = self._memory[-1][1]
            peak = [
                max(s[i] for _, s in self._memory)
                for i in range(len(last))
            ]
            metric("hbm_bytes_in_use", "gauge", max(last),
                   "per-shard live bytes at the last memory sample (max)")
            metric("hbm_peak_bytes", "gauge", max(peak),
                   "per-shard HBM high-water across the run (max)")
            for s in range(len(last)):
                lines.append(
                    f'shadow_tpu_shard_hbm_bytes_in_use{{shard="{s}"}} '
                    f"{last[s]}"
                )
                lines.append(
                    f'shadow_tpu_shard_hbm_peak_bytes{{shard="{s}"}} '
                    f"{peak[s]}"
                )
        if rows.shape[1] > 0:
            metric("sim_time_ns", "gauge",
                   int(rows[0, -1, COL_WINDOW_END]),
                   "simulated time completed by the last traced round")
        for s in range(rows.shape[0]):
            if rows.shape[1] == 0:
                break
            lines.append(
                f'shadow_tpu_shard_events_total{{shard="{s}"}} '
                f"{int(rows[s, :, COL_EVENTS].sum())}"
            )
            lines.append(
                f'shadow_tpu_shard_occupancy_hwm{{shard="{s}"}} '
                f"{int(rows[s, :, COL_OCC_HWM].max())}"
            )
        for k, v in (extra or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metric(k, "gauge", v, "driver-report field")
        return "\n".join(lines) + "\n"

    def write_metrics(self, path: str, extra: dict | None = None) -> str:
        with open(path, "w") as f:
            f.write(self.to_metrics_text(extra))
        return path

    def write_artifacts(self, data_dir: str, obs, report: dict | None = None):
        """Export everything `observability:` asked for into the data dir —
        the one code path both drivers (sim.py / cosim.py) share. `obs` is
        the ObservabilityOptions block; `report` feeds extra gauges into
        the metrics file (to_metrics_text keeps only the numeric fields)."""
        if obs.trace_file:
            self.write_chrome_trace(os.path.join(data_dir, obs.trace_file))
        if obs.metrics_file:
            self.write_metrics(
                os.path.join(data_dir, obs.metrics_file), extra=report
            )


# the per-replica reduction below sums these ring columns and maxes those
_REPLICA_SUM_COLS = {
    "events": COL_EVENTS,
    "microsteps": COL_MICROSTEPS,
    "popk_deferred": COL_POPK_DEFERRED,
    "bq_rebuilds": COL_BQ_REBUILDS,
    "ici_bytes": COL_ICI_BYTES,
    "sends": COL_SENDS,
    "a2a_shed": COL_A2A_SHED,
    "faults_dropped": COL_FAULTS_DROPPED,
    "faults_delayed": COL_FAULTS_DELAYED,
    "ec_timer": COL_EC_TIMER,
    "ec_pkt": COL_EC_PKT,
    "ec_app": COL_EC_APP,
    "flows": COL_FLOWS,
}
_REPLICA_MAX_COLS = {
    "occ_hwm": COL_OCC_HWM,
    "ob_hwm": COL_OB_HWM,
    "hosts_down_max": COL_HOSTS_DOWN,
}


class ReplicaTracer:
    """Per-replica totals reduction for ensemble campaign runs.

    A stacked campaign state's trace ring is [R, world, Rr, F] with a
    per-replica cursor [R, world]: replicas record rounds at their OWN
    pace (a finished replica's frozen lane stops appending), so the
    single-cursor `RoundTracer` drain cannot be reused — each replica's
    new rows must be located by ITS cursor. This class drains per replica
    at chunk boundaries (ring sized to rounds_per_chunk, so a drain per
    chunk never wraps for any replica) and folds running per-replica
    totals — sums for the counter columns, maxes for the high-water
    columns — which the campaign ledger cross-checks against the
    per-replica device stats. Like the ring itself, pure observation."""

    def __init__(self, ring_rounds: int, num_replicas: int):
        if ring_rounds <= 0:
            raise ValueError(f"ring_rounds must be > 0, got {ring_rounds}")
        if num_replicas <= 0:
            raise ValueError(
                f"num_replicas must be > 0, got {num_replicas}"
            )
        self.ring_rounds = int(ring_rounds)
        self.num_replicas = int(num_replicas)
        self._cursor = np.zeros((num_replicas,), np.int64)
        self._origin = np.zeros((num_replicas,), np.int64)
        self.lost = np.zeros((num_replicas,), np.int64)
        self._sums = np.zeros((num_replicas, TRACE_COLS), np.int64)
        self._maxs = np.zeros((num_replicas, TRACE_COLS), np.int64)

    def _cursors_of(self, ring: TraceRing) -> np.ndarray:
        import jax

        cur = np.asarray(jax.device_get(ring.cursor))  # [R, world]
        if cur.ndim != 2 or cur.shape[0] != self.num_replicas:
            raise ValueError(
                f"expected a stacked [R={self.num_replicas}, world] ring "
                f"cursor, got shape {cur.shape}"
            )
        return cur.max(axis=1)

    def sync_cursor(self, ring: TraceRing) -> np.ndarray:
        """Adopt each replica's current cursor as its drain origin (same
        contract as RoundTracer.sync_cursor, per replica)."""
        cur = self._cursors_of(ring)
        self._cursor = cur.copy()
        self._origin = cur.copy()
        return cur

    def drain(self, ring: TraceRing) -> int:
        """Fold rounds recorded since the last drain into the running
        per-replica totals; returns how many rows were folded (all
        replicas, wrap losses excluded — those count in `.lost`)."""
        import jax

        cur = self._cursors_of(ring)
        if not (cur > self._cursor).any():
            return 0
        rows = np.asarray(jax.device_get(ring.rows))  # [R, world, Rr, F]
        folded = 0
        for r in range(self.num_replicas):
            n = int(cur[r] - self._cursor[r])
            if n <= 0:
                continue
            lost = max(0, n - self.ring_rounds)
            self.lost[r] += lost
            idx = [
                i % self.ring_rounds
                for i in range(int(self._cursor[r]) + lost, int(cur[r]))
            ]
            flat = rows[r][:, idx, :].reshape(-1, TRACE_COLS)
            self._sums[r] += flat.sum(axis=0)
            self._maxs[r] = np.maximum(self._maxs[r], flat.max(axis=0))
            self._cursor[r] = cur[r]
            folded += n - lost
        return folded

    @property
    def rounds(self) -> np.ndarray:
        """Rounds folded per replica, i64[R]."""
        return self._cursor - self._origin - self.lost

    def replica_totals(self) -> list[dict]:
        """One totals dict per replica (RoundTracer.totals key naming)."""
        out = []
        for r in range(self.num_replicas):
            t = {"rounds": int(self.rounds[r]),
                 "rounds_lost": int(self.lost[r])}
            for k, c in _REPLICA_SUM_COLS.items():
                t[k] = int(self._sums[r, c])
            for k, c in _REPLICA_MAX_COLS.items():
                t[k] = int(self._maxs[r, c])
            out.append(t)
        return out

    def totals(self) -> dict:
        """Campaign-wide aggregate: sums summed, high-waters maxed."""
        t = {"rounds": int(self.rounds.sum()),
             "rounds_lost": int(self.lost.sum())}
        for k, c in _REPLICA_SUM_COLS.items():
            t[k] = int(self._sums[:, c].sum())
        for k, c in _REPLICA_MAX_COLS.items():
            t[k] = int(self._maxs[:, c].max()) if self.num_replicas else 0
        return t
