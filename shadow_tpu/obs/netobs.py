"""Network observatory: flow ledger, event-class accounting, per-link
counters, and safe-window critical-path telemetry.

The round tracer (obs/tracer.py) made time visible and the HBM
observatory (obs/memory.py) made memory visible; this module lights up
the network plane itself — the device-plane sibling of the reference
Shadow's tracker/heartbeat + per-host pcap observability. Four
instruments behind ONE knob (`observability.network`), all following the
established observer contract: digests, events, and every drop counter
are bit-identical with the observatory on or off, and with it OFF no new
code is traced at all (the default jaxpr fingerprint is byte-unchanged —
tools/lint/jaxpr_audit.py pins the program-level claim).

  event-class accounting — every executed event is classified in-jit as
  timer / packet / app (packet = the engine's KIND_PKT flag; timer = the
  model's declared `timer_kinds`; app = the rest) into three per-shard
  i64 stats lanes plus per-round trace-ring columns. This is the
  instrument that DECIDES ROADMAP item 2: the timer-wheel rebuild is
  justified iff the measured timer share confirms timer dominance.

  flow ledger — `FlowLedger`, a fixed-size per-shard flow-record ring
  appended in-jit at model flow completion (tgen FIN-ACK) and drained at
  chunk boundaries exactly like the trace ring (monotone cursor, writes
  at cursor % R, overwrite-lost accounting, `sync_cursor` checkpoint
  semantics). Drained records yield the FCT distribution and a Perfetto
  flow track; three gated stats lanes (fl_done/fl_bytes/fl_rtx) carry
  the cumulative totals independently of the ring so reconciliation is
  exact even across wraps.

  per-link / per-host counters — a host-side fold of the engine's
  per-host packet/drop lanes (plus the model's `per_host_network` hook)
  over the host->graph-node map into sim-stats `network.links{}`.

  safe-window telemetry — per-round, the shard whose local min event
  time bound the all-reduce-min barrier (the critical-path shard), as a
  trace-ring column and a per-shard `win_bound` round count: the
  straggler view the weak-scaling push needs.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

# one ledger row per completed flow; column order is the engine's write
# order (core/engine.py _flow_append builds rows by these indices).
# APPEND-ONLY, like TRACE_FIELDS: recorded ledgers index by position.
FLOW_FIELDS = (
    "src",          # completing (client) host's global id
    "dst",          # peer (server) host's global id
    "flow",         # model flow index (tgen: the completed phase)
    "t_start",      # flow start sim-time (ns)
    "t_end",        # completion sim-time (ns)
    "bytes",        # application payload bytes transferred
    "retransmits",  # segments retransmitted within this flow
)
FLOW_COLS = len(FLOW_FIELDS)
(
    FCOL_SRC,
    FCOL_DST,
    FCOL_FLOW,
    FCOL_T_START,
    FCOL_T_END,
    FCOL_BYTES,
    FCOL_RETRANSMITS,
) = range(FLOW_COLS)


class FlowLedger(NamedTuple):
    """The device half: a bounded per-shard flow-record ring in the scan
    carry, sharded like the trace ring (`rows` is [world, R, F] with the
    leading axis on the mesh; `cursor` is [world]). Each shard's cursor
    counts ITS OWN completions since simulation start and is never reset:
    writes land at `cursor % R`, the host drain reconstructs new rows
    from (previous cursor, current cursor) per shard — unlike the trace
    ring's replicated round cursor, shard cursors genuinely differ, so
    the drain is per-shard (the ReplicaTracer pattern)."""

    rows: Any  # i64[world, R, F]
    cursor: Any  # i64[world] flows recorded since start (monotone)


def make_flow_ledger(world: int, records: int) -> FlowLedger:
    import jax.numpy as jnp

    return FlowLedger(
        rows=jnp.zeros((world, records, FLOW_COLS), jnp.int64),
        cursor=jnp.zeros((world,), jnp.int64),
    )


class FlowCollector:
    """Host-side drain/accumulator for the device flow ledger.

    Mirrors RoundTracer's cursor contract per shard: `sync_cursor` adopts
    the ring's current cursors (checkpoint-resume never replays
    pre-snapshot records), `drain` pulls records appended since the last
    drain and counts wrap-overwritten ones in `lost` — never silently.
    The drivers drain at every chunk boundary with the ring sized so a
    typical chunk cannot wrap; a burst beyond capacity loses the OLDEST
    records and the stats lanes (fl_*) still carry exact totals."""

    def __init__(self, ring_records: int):
        if ring_records <= 0:
            raise ValueError(
                f"ring_records must be > 0, got {ring_records}"
            )
        self.ring_records = int(ring_records)
        self._cursor: np.ndarray | None = None  # i64[world]
        self._origin: np.ndarray | None = None
        self._lost: np.ndarray | None = None
        # per shard: (base_cursor, rows) segments — rows cover the global
        # cursor range [base, base + len). Bases make truncation exact:
        # a record's global index says whether an exported prefix saw it.
        self._rows: list[list[tuple[int, np.ndarray]]] | None = None
        # per shard: (start_cursor, n) wrap-loss ranges, same global
        # indexing — so truncation can recount losses within a prefix
        self._lost_ranges: list[list[tuple[int, int]]] | None = None
        self.last_drained = np.zeros((0, FLOW_COLS), np.int64)

    def _ensure(self, world: int):
        if self._cursor is None:
            self._cursor = np.zeros((world,), np.int64)
            self._origin = np.zeros((world,), np.int64)
            self._lost = np.zeros((world,), np.int64)
            self._rows = [[] for _ in range(world)]
            self._lost_ranges = [[] for _ in range(world)]

    def _cursors_of(self, ledger: FlowLedger) -> np.ndarray:
        import jax

        cur = np.asarray(jax.device_get(ledger.cursor))
        self._ensure(cur.shape[0])
        if cur.shape[0] != self._cursor.shape[0]:
            raise ValueError(
                f"ledger world {cur.shape[0]} != collector world "
                f"{self._cursor.shape[0]}"
            )
        return cur

    def sync_cursor(self, ledger: FlowLedger) -> np.ndarray:
        """Adopt each shard's current cursor as its drain origin without
        exporting anything (RoundTracer.sync_cursor contract, per shard:
        a restored checkpoint's pre-existing records are not fresh
        completions and must not be replayed or counted as losses)."""
        cur = self._cursors_of(ledger)
        self._cursor = cur.copy()
        self._origin = cur.copy()
        return cur

    def drain(self, ledger: FlowLedger) -> int:
        """Pull records appended since the last drain; returns how many
        (all shards, wrap losses excluded — those count in `lost`)."""
        import jax

        cur = self._cursors_of(ledger)
        if not (cur > self._cursor).any():
            self.last_drained = np.zeros((0, FLOW_COLS), np.int64)
            return 0
        rows = np.asarray(jax.device_get(ledger.rows))  # [world, R, F]
        pulled = 0
        new: list[np.ndarray] = []
        for s in range(cur.shape[0]):
            n = int(cur[s] - self._cursor[s])
            if n <= 0:
                continue
            lost = max(0, n - self.ring_records)
            if lost:
                self._lost[s] += lost
                self._lost_ranges[s].append((int(self._cursor[s]), lost))
            base = int(self._cursor[s]) + lost
            idx = np.arange(base, int(cur[s])) % self.ring_records
            self._rows[s].append((base, rows[s][idx, :]))
            new.append(rows[s][idx, :])
            self._cursor[s] = cur[s]
            pulled += n - lost
        # this drain's records (all shards), for exporters that stream
        # (the tracer's flow track) — records() keeps the full history
        self.last_drained = (
            np.concatenate(new, axis=0) if new
            else np.zeros((0, FLOW_COLS), np.int64)
        )
        return pulled

    def truncate_to_cursor(self, cursors) -> int:
        """Drop the NEWEST drained records of each shard beyond the given
        cursor values — the graceful-abort shape (RoundTracer.
        truncate_to_round's sibling): the exported state was rewound to a
        snapshot, and its OWN `flows.cursor` says exactly how many
        completions the exported prefix saw, so records drained from
        post-snapshot chunks must not outlive it. Returns the drop
        count."""
        if self._cursor is None:
            return 0
        cursors = np.asarray(cursors, np.int64)
        dropped = 0
        for s in range(self._cursor.shape[0]):
            # an export cursor below the sync origin cannot un-see the
            # origin (the collector never held those records)
            tc = max(int(cursors[s]), int(self._origin[s]))
            if tc >= int(self._cursor[s]):
                continue
            dropped += int(self._cursor[s]) - tc
            self._cursor[s] = tc
            # held rows: keep exactly the global indices < tc (segment
            # bases make this exact even across wrap-loss gaps)
            kept: list[tuple[int, np.ndarray]] = []
            for base, seg in self._rows[s]:
                if base >= tc:
                    continue
                keep_n = min(seg.shape[0], tc - base)
                kept.append((base, seg[:keep_n]))
            self._rows[s] = kept
            # recount wrap losses within the kept prefix (a loss range
            # past tc never happened as far as the exported state saw)
            kept_ranges: list[tuple[int, int]] = []
            lost_total = 0
            for start, ln in self._lost_ranges[s]:
                if start >= tc:
                    continue
                ln = min(ln, tc - start)
                kept_ranges.append((start, ln))
                lost_total += ln
            self._lost_ranges[s] = kept_ranges
            self._lost[s] = lost_total
        return dropped

    @property
    def lost(self) -> int:
        return int(self._lost.sum()) if self._lost is not None else 0

    @property
    def count(self) -> int:
        if self._cursor is None:
            return 0
        return int((self._cursor - self._origin - self._lost).sum())

    def records(self) -> np.ndarray:
        """All drained records, [N, FLOW_COLS] (shards concatenated)."""
        if not self._rows or not any(self._rows):
            return np.zeros((0, FLOW_COLS), np.int64)
        segs = [
            seg for shard in self._rows for _, seg in shard if seg.shape[0]
        ]
        if not segs:
            return np.zeros((0, FLOW_COLS), np.int64)
        return np.concatenate(segs, axis=0)

    def fct_ns(self) -> np.ndarray:
        r = self.records()
        return r[:, FCOL_T_END] - r[:, FCOL_T_START]

    def summary(self) -> dict:
        """The collector's contribution to the sim-stats
        `network.flows{}` block. Empty drains return zeros under the
        same keys (stable schema). The byte/retransmit sums carry
        `drained_` prefixes deliberately: the UNPREFIXED `bytes`/
        `retransmits` in the flows block are the exact fl_* stats-lane
        totals (exact across ring wraps), and the drained sums must
        never shadow them — when records_lost is 0 the two pairs agree
        exactly, which is the real ledger-vs-lanes cross-check
        net_report --check enforces."""
        r = self.records()
        out: dict[str, Any] = {
            "records_drained": int(r.shape[0]),
            "records_lost": self.lost,
            "drained_bytes": int(r[:, FCOL_BYTES].sum()),
            "drained_retransmits": int(r[:, FCOL_RETRANSMITS].sum()),
        }
        out["fct"] = fct_stats(self.fct_ns())
        return out


def fct_stats(fct_ns: np.ndarray) -> dict:
    """Flow-completion-time distribution figures (ms)."""
    if fct_ns.size == 0:
        return {"n": 0, "p50_ms": None, "p99_ms": None, "mean_ms": None,
                "max_ms": None}
    f = np.asarray(fct_ns, np.float64) / 1e6
    return {
        "n": int(fct_ns.size),
        "p50_ms": round(float(np.percentile(f, 50)), 3),
        "p99_ms": round(float(np.percentile(f, 99)), 3),
        "mean_ms": round(float(f.mean()), 3),
        "max_ms": round(float(f.max()), 3),
    }


def event_class_report(timer: int, pkt: int, app: int) -> dict:
    """The `network.event_classes{}` block — the timer-share number
    ROADMAP item 2's timer-wheel decision gates on."""
    total = timer + pkt + app
    return {
        "timer": int(timer),
        "packet": int(pkt),
        "app": int(app),
        "total": int(total),
        "timer_share": round(timer / total, 4) if total else None,
        "packet_share": round(pkt / total, 4) if total else None,
    }


def safe_window_report(win_bound, rounds: int) -> dict:
    """The `network.safe_window{}` block: which shard bound the
    all-reduce-min barrier, per round. `win_bound[s]` counts the rounds
    shard s was the argmin (ties to the lowest shard id); on world=1 the
    single shard trivially binds every round."""
    bound = [int(x) for x in np.asarray(win_bound).reshape(-1)]
    total = sum(bound)
    argmax = int(np.argmax(bound)) if bound else 0
    return {
        "rounds": int(rounds),
        "bound_rounds_per_shard": bound,
        "critical_shard": argmax,
        "critical_share": (
            round(bound[argmax] / total, 4) if total else None
        ),
    }


# engine per-host drop lanes folded into the per-link report, by cause
_LINK_ENGINE_LANES = (
    ("pkts_sent", "packets_sent"),
    ("pkts_delivered", "packets_delivered"),
    ("pkts_lost", "drops_path_loss"),
    ("pkts_unreachable", "drops_unreachable"),
    ("pkts_codel_dropped", "drops_codel"),
    ("pkts_budget_dropped", "drops_budget"),
    ("faults_dropped", "drops_faults"),
)


def links_report(
    node_of, stats, num_real: int, model_per_host: dict | None = None
) -> dict:
    """Fold the per-host engine lanes (and the model's per-host network
    counters) over the host->graph-node map into per-link aggregates —
    the device-plane sibling of the CPU plane's per-interface tracker.
    `node_of` is the [num_real] host->node index map; keys are node
    indices as strings (JSON-stable)."""
    node_of = np.asarray(node_of)[:num_real]
    nodes = np.unique(node_of)
    per_host: dict[str, np.ndarray] = {}
    for lane, out_name in _LINK_ENGINE_LANES:
        per_host[out_name] = np.asarray(getattr(stats, lane))[:num_real]
    for k, v in (model_per_host or {}).items():
        per_host[k] = np.asarray(v)[:num_real]
    links: dict[str, dict] = {}
    for n in nodes:
        m = node_of == n
        links[str(int(n))] = {
            "hosts": int(m.sum()),
            **{k: int(v[m].sum()) for k, v in per_host.items()},
        }
    return links


def link_hwm(links: dict) -> dict:
    """Hot-spot maxima over the per-link fold (the bench-diff figures):
    the busiest link's packet and byte counts."""
    if not links:
        return {"packets_sent": 0, "bytes": 0}
    return {
        "packets_sent": max(
            link.get("packets_sent", 0) for link in links.values()
        ),
        "bytes": max(link.get("bytes", 0) for link in links.values()),
    }


def network_report(
    *,
    ec_timer: int,
    ec_pkt: int,
    ec_app: int,
    win_bound,
    rounds: int,
    fl: tuple[int, int, int] | None = None,
    collector: FlowCollector | None = None,
    links: dict | None = None,
) -> dict:
    """Assemble the sim-stats `network{}` block from the gated stats
    lanes (read by the caller so shadowlint R3 sees the exports), the
    drained flow collector, and the host-side per-link fold. `fl` is
    (fl_done, fl_bytes, fl_rtx) when the flow ledger ran."""
    out: dict[str, Any] = {
        "event_classes": event_class_report(ec_timer, ec_pkt, ec_app),
        "safe_window": safe_window_report(win_bound, rounds),
    }
    if fl is not None:
        done, fbytes, frtx = fl
        flows: dict[str, Any] = {
            "completed": int(done),
            "bytes": int(fbytes),
            "retransmits": int(frtx),
        }
        if collector is not None:
            flows.update(collector.summary())
        out["flows"] = flows
    if links is not None:
        out["links"] = links
        out["link_hwm"] = link_hwm(links)
    return out


def node_map(specs, num_real: int) -> np.ndarray:
    """host -> graph-node index map from a list of HostSpecs (the links
    fold's key space)."""
    node_of = np.zeros((num_real,), np.int32)
    for spec in specs:
        if spec.host_id < num_real:
            node_of[spec.host_id] = spec.node_index
    return node_of


def assemble_network_report(
    *,
    stats,
    num_real: int,
    rounds: int,
    node_of,
    model=None,
    model_state=None,
    flow_ledger: bool = False,
    collector: FlowCollector | None = None,
) -> dict:
    """The ONE driver-side assembly of the sim-stats `network{}` block,
    shared by sim.py's stats_report, cosim's hybrid report, and bench.py
    rows — so the block's shape cannot drift between them. `stats` is
    the device-got Stats tuple (this helper reads the gated ec_*/fl_*/
    win_bound lanes; the lanes are therefore listed in
    lanes.STATS_EXPORT_EXEMPT with this function as the export path);
    `model_state` is a HOST-SIDE model tree already sliced to the real
    hosts (the caller fetches it ONCE and shares it with any other
    exporter — Simulation._model_host_view memoizes exactly that, so a
    gated report never pulls the model state off the device twice)."""
    model_ph = None
    if model is not None and model_state is not None and hasattr(
        model, "per_host_network"
    ):
        model_ph = model.per_host_network(model_state)
    fl = None
    if flow_ledger:
        fl = (
            int(np.asarray(stats.fl_done).sum()),
            int(np.asarray(stats.fl_bytes).sum()),
            int(np.asarray(stats.fl_rtx).sum()),
        )
    return network_report(
        ec_timer=int(np.asarray(stats.ec_timer).sum()),
        ec_pkt=int(np.asarray(stats.ec_pkt).sum()),
        ec_app=int(np.asarray(stats.ec_app).sum()),
        win_bound=np.asarray(stats.win_bound),
        rounds=int(rounds),
        fl=fl,
        collector=collector,
        links=links_report(node_of, stats, num_real, model_ph),
    )


def bench_network_block(report_network: dict) -> dict:
    """The compact `network{}` block BENCH rows carry (and
    tools/bench_compare.py diffs): the timer-vs-packet event share, the
    FCT distribution figures, and the link hot-spot maxima."""
    out: dict[str, Any] = {
        "event_classes": report_network.get("event_classes", {}),
    }
    flows = report_network.get("flows")
    if flows:
        out["fct"] = flows.get("fct", {})
        out["retransmits"] = flows.get("retransmits", 0)
        out["flows_completed"] = flows.get("completed", 0)
    if "link_hwm" in report_network:
        out["link_hwm"] = report_network["link_hwm"]
    return out
