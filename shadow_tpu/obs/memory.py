"""HBM & capacity observatory: a memory-accounting plane for the engine.

The reference Shadow heartbeats per-host allocated memory through its
tracker (tracker.c) because capacity-sized structures are the scaling
bottleneck; this port's capacity guards were blind until now — the
pressure plane discovered HBM limits by catching RESOURCE_EXHAUSTED
after a wasted compile+dispatch, and the ensemble `max_replicas` guard
was a comment. This module gives every capacity decision numbers, from
THREE independent sources:

  (a) static byte model — derived from the single-source lane registry
      (core/lanes.py STATE_LANES widths x STATE_LANE_SHAPES formulas):
      per-component bytes per shard and per host for ANY (capacity,
      outbox, gear, K, replicas, trace) shape, without touching a
      device. Components the registry does not cover (model pytree,
      token buckets, CoDel, routing params) are measured EXACTLY from
      pytree leaf metadata (shape x dtype — still no device transfer).

  (b) compiled-program ledger — `Compiled.memory_analysis()` (argument/
      output/temp/generated-code bytes) for every chunk program a run's
      engine holds: the base program plus each (gear x capacity x
      budget) rung `Engine.run_chunk_resized` cached, and the ensemble
      program. XLA's own accounting, so it includes what the model
      cannot see (temporaries, fusion buffers).

  (c) live device sampling — `device.memory_stats()` (bytes_in_use /
      peak_bytes_in_use / bytes_limit) at chunk boundaries, folded into
      a per-shard HBM high-water. CPU backends report no allocator
      stats (memory_stats() is None); the monitor then falls back to
      the MODELED live bytes (source (a)'s exact pytree accounting) so
      per-shard high-water telemetry is never silently zero.

Everything here is an OBSERVER on the host side: no traced code changes
whether the observatory is on or off, so digests, events, and every
drop/pressure counter are bit-identical by construction — and the
default jaxpr fingerprint is byte-unchanged (tests/test_memory.py +
tools/lint/jaxpr_audit.py are the gates). The one feedback path is
deliberate and drop-free-safe: `MemoryGuard` lets the pressure plane
REFUSE a grown rung whose predicted footprint exceeds measured headroom
(x a safety factor) BEFORE dispatch, replacing an OOM round-trip with a
poisoned rung — a refusal can cost a PressureAbort the OOM would have
forced anyway, never a drop.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from shadow_tpu.core import lanes

# replay/migration concurrency: while the pressure plane grows a rung it
# holds the pre-chunk snapshot AND the migrated live state, so admission
# charges every grown byte twice (MemoryGuard.copies)
DEFAULT_GUARD_COPIES = 2
DEFAULT_SAFETY_FACTOR = 1.25


# --------------------------------------------------------------------------
# (a) static byte model — lane registry formulas + exact pytree metadata
# --------------------------------------------------------------------------


def _dtype_bytes(dt: str) -> int:
    """Registry dtype string -> bytes per element (bool is stored as one
    byte even though lanes.BITS counts it as one bit)."""
    return 1 if dt == "bool" else np.dtype(dt).itemsize


def dims_of(
    *,
    hosts_per_shard: int,
    queue_capacity: int,
    queue_block: int = 0,
    send_budget: int = 8,
    trace_rounds: int = 0,
    pressure: bool = False,
    netobs: bool = False,
    flow_records: int = 0,
    integrity: bool = False,
    integrity_dual: bool = False,
    wheel_slots: int = 0,
    wheel_block: int = 0,
    fluid_classes: int = 0,
    fluid_links: int = 0,
    hier: bool = False,
    payload_words: int | None = None,
    trace_cols: int | None = None,
    flow_cols: int | None = None,
) -> dict[str, int]:
    """Resolve the STATE_LANE_SHAPES dimension tokens for one shape.

    `payload_words`/`trace_cols`/`flow_cols` default to the live
    constants (ops.events.EVENT_PAYLOAD_WORDS / len(tracer.TRACE_FIELDS)
    / len(netobs.FLOW_FIELDS)) — pass them explicitly only when modeling
    a foreign layout."""
    if payload_words is None:
        from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS

        payload_words = EVENT_PAYLOAD_WORDS
    if trace_cols is None:
        from shadow_tpu.obs.tracer import TRACE_COLS

        trace_cols = TRACE_COLS
    if flow_cols is None:
        from shadow_tpu.obs.netobs import FLOW_COLS

        flow_cols = FLOW_COLS
    if wheel_slots:
        from shadow_tpu.ops.wheel import resolve_wheel_block

        wnb = int(wheel_slots) // resolve_wheel_block(wheel_slots, wheel_block)
    else:
        wnb = 0
    return {
        "H": int(hosts_per_shard),
        "C": int(queue_capacity),
        "NB": int(queue_capacity) // queue_block if queue_block else 0,
        "P": int(payload_words),
        "SB": int(send_budget),
        "S": 1,
        "R": int(trace_rounds),
        "F": int(trace_cols),
        "FR": int(flow_records) if netobs else 0,
        "FF": int(flow_cols),
        "WS": int(wheel_slots),
        "WNB": wnb,
        "FK": int(fluid_classes),
        "FN": int(fluid_links) if fluid_classes else 0,
        "pressure": 1 if pressure else 0,
        "netobs": 1 if netobs else 0,
        # hierarchical exchange (core/engine.py _exchange_hierarchical):
        # gates the two-tier byte counters (stats.ici_intra/ici_inter)
        "hier": 1 if hier else 0,
        "integrity": 1 if integrity else 0,
        "integrity_dual": 1 if integrity_dual else 0,
    }


def dims_of_config(cfg) -> dict[str, int]:
    """Dimension tokens for an EngineConfig (per-SHARD accounting)."""
    return dims_of(
        hosts_per_shard=cfg.hosts_per_shard,
        queue_capacity=cfg.queue_capacity,
        queue_block=cfg.queue_block,
        send_budget=cfg.sends_per_host_round,
        trace_rounds=cfg.trace_rounds,
        pressure=cfg.pressure_abort,
        netobs=cfg.netobs,
        flow_records=cfg.flow_records,
        integrity=cfg.integrity,
        integrity_dual=cfg.integrity_dual,
        wheel_slots=cfg.wheel_slots,
        wheel_block=cfg.wheel_block,
        fluid_classes=cfg.fluid_classes,
        fluid_links=cfg.fluid_links,
        hier=cfg.hier_active,
    )


def dims_of_state(cfg, state) -> dict[str, int]:
    """Dimension tokens read off a LIVE state's shapes: under an
    escalate pressure policy the queue/outbox may have been regrown past
    the configured base, and the model must price what is actually in
    HBM (the shapes are the truth — the same rule the pressure
    controller's rewind path follows)."""
    q = state.queue
    cap = int(q.t.shape[-1])
    block = cap // int(q.bt.shape[-1]) if hasattr(q, "bt") else 0
    return dims_of(
        hosts_per_shard=cfg.hosts_per_shard,
        queue_capacity=cap,
        queue_block=block,
        send_budget=int(state.outbox.t.shape[-1]),
        trace_rounds=(
            int(state.trace.rows.shape[-2]) if state.trace is not None else 0
        ),
        pressure=state.stats.pressure is not None,
        netobs=state.stats.ec_timer is not None,
        flow_records=(
            int(state.flows.rows.shape[-2]) if state.flows is not None else 0
        ),
        integrity=state.stats.integrity is not None,
        integrity_dual=state.stats.digest2 is not None,
        wheel_slots=(
            int(state.wheel.t.shape[-1]) if state.wheel is not None else 0
        ),
        wheel_block=(
            int(state.wheel.block) if state.wheel is not None else 0
        ),
        fluid_classes=(
            int(state.fluid.rates.shape[-1])
            if getattr(state, "fluid", None) is not None else 0
        ),
        fluid_links=(
            int(state.fluid.link_util.shape[-1])
            if getattr(state, "fluid", None) is not None else 0
        ),
        hier=state.stats.ici_intra is not None,
    )


def lane_plane_bytes(path: str, dims: dict[str, int]) -> int | None:
    """Per-shard bytes of one registered carry plane at `dims`, or None
    when the plane is absent from the carry at this shape (flat queue
    drops the bucket caches, trace_rounds 0 drops the ring, the default
    drop policy carries no stats.pressure)."""
    shape = lanes.STATE_LANE_SHAPES[path]
    if path.startswith("queue.b") and dims["NB"] == 0:
        return None
    if path.startswith("trace.") and dims["R"] == 0:
        return None
    if path == "stats.pressure" and not dims["pressure"]:
        return None
    # integrity-sentinel planes (core/integrity.py): the violation/
    # signature lanes ride the sentinel knob, the dual digest its own
    if path in ("stats.integrity", "stats.iv_mask", "stats.iv_round") and (
        not dims.get("integrity")
    ):
        return None
    if path == "stats.digest2" and not dims.get("integrity_dual"):
        return None
    # network-observatory planes: class/safe-window lanes ride with the
    # knob, flow lanes additionally require an active ledger ring
    if path in (
        "stats.ec_timer", "stats.ec_pkt", "stats.ec_app", "stats.win_bound"
    ) and not dims.get("netobs"):
        return None
    if path in ("stats.fl_done", "stats.fl_bytes", "stats.fl_rtx") and (
        not dims.get("netobs") or dims.get("FR", 0) == 0
    ):
        return None
    if path.startswith("flows.") and dims.get("FR", 0) == 0:
        return None
    # timer-wheel planes (ops/wheel.py): absent unless the wheel is on
    if (
        path.startswith("wheel.")
        or path in ("stats.wheel_spilled", "stats.wheel_occ_hwm")
    ) and dims.get("WS", 0) == 0:
        return None
    # fluid planes (net/fluid.py): absent unless classes are declared
    if (
        path.startswith("fluid.")
        or path in ("stats.fl_bg_bytes", "stats.fl_bg_dropped")
    ) and dims.get("FK", 0) == 0:
        return None
    # hierarchical-exchange tier counters: absent off the hierarchical path
    if path in ("stats.ici_intra", "stats.ici_inter") and not dims.get(
        "hier"
    ):
        return None
    n = 1
    for tok in shape:
        n *= tok if isinstance(tok, int) else dims[tok]
    return n * _dtype_bytes(lanes.STATE_LANES[path])


def registered_component_bytes(dims: dict[str, int]) -> dict[str, dict[str, int]]:
    """Per-shard bytes of every registered carry plane, grouped by
    component (the SimState top-level field, with bare paths under
    "scalars"). The single-source static model: widths from STATE_LANES,
    shapes from STATE_LANE_SHAPES, nothing else."""
    out: dict[str, dict[str, int]] = {}
    for path in lanes.STATE_LANES:
        b = lane_plane_bytes(path, dims)
        if b is None:
            continue
        comp = path.split(".")[0] if "." in path else "scalars"
        out.setdefault(comp, {})[path] = b
    return out


def component_totals(comps: dict[str, dict[str, int]]) -> dict[str, int]:
    return {k: sum(v.values()) for k, v in sorted(comps.items())}


def leaf_nbytes(leaf) -> int:
    """Bytes of one pytree leaf from METADATA only (shape x dtype — no
    device transfer; works on jax arrays, numpy arrays, and
    ShapeDtypeStructs alike)."""
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def tree_bytes(tree) -> int:
    """Total bytes of a pytree, metadata-only."""
    import jax

    return sum(leaf_nbytes(x) for x in jax.tree_util.tree_leaves(tree))


def modeled_shard_bytes(state, params=None, world: int = 1) -> int:
    """The monitor's modeled-fallback figure: exact metadata bytes of
    the live device pytrees, per shard. The ONE formula every driver
    passes to `MemoryMonitor.sample(modeled_bytes=...)` — metadata-only
    (shape x dtype), so it is safe even on donation-consumed arrays."""
    total = tree_bytes(state)
    if params is not None:
        total += tree_bytes(params)
    return total // max(int(world), 1)


def state_field_bytes(state) -> dict[str, int]:
    """Bytes per top-level field of a NamedTuple state pytree (the exact
    counterpart of the formula model — covers the unregistered planes:
    model state, token buckets, CoDel)."""
    import jax

    out: dict[str, int] = {}
    for name, sub in zip(type(state)._fields, state):
        b = sum(leaf_nbytes(x) for x in jax.tree_util.tree_leaves(sub))
        if b:
            out[name] = b
    return out


def per_host_split(tree, num_hosts: int) -> tuple[int, int]:
    """(per_host_slope_bytes, fixed_bytes): leaves whose LEADING axis is
    the host axis scale with host count; everything else (replicated
    tables, per-shard counters, scalars) is fixed. The capacity
    planner's affine decomposition — heuristic only where a replicated
    table's leading dim happens to equal the host count."""
    import jax

    per_host = fixed = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        b = leaf_nbytes(leaf)
        shape = getattr(leaf, "shape", ())
        if shape and int(shape[0]) == num_hosts:
            per_host += b
        else:
            fixed += b
    return per_host // max(num_hosts, 1), fixed


def static_model(cfg, state=None, params=None, replicas: int = 1) -> dict:
    """The full source-(a) report for one engine shape.

    Registered components come from the lane-registry formulas —
    dimensioned from the STATE's actual shapes when one is provided
    (escalation regrows them past the config's base). `state`/`params`
    (metadata-only) add the exact bytes of the unregistered planes and
    a consistency figure the tests pin: formula bytes == actual
    carry-leaf bytes for every registered component. `replicas` scales
    the per-shard state for the ensemble plane (params broadcast via
    in_axes=None and are NOT scaled)."""
    dims = dims_of_state(cfg, state) if state is not None else (
        dims_of_config(cfg)
    )
    comps = registered_component_bytes(dims)
    totals = component_totals(comps)
    registered = sum(totals.values())
    out: dict[str, Any] = {
        "components": totals,
        "registered_bytes": registered,
        "replicas": int(replicas),
    }
    world = max(int(getattr(cfg, "world", 1)), 1)
    state_shard = registered
    if state is not None:
        fields = state_field_bytes(state)
        measured_total = sum(fields.values())
        covered = {
            "queue", "outbox", "stats", "trace", "flows", "rng", "now",
            "done", "seq", "sent_round", "cpu_busy_until", "min_used_lat",
        }
        unreg = {
            k: v // world for k, v in fields.items() if k not in covered
        }
        out["unregistered"] = unreg
        out["components"] = {**totals, **unreg}
        state_shard = registered + sum(unreg.values())
        out["state_bytes_measured"] = measured_total // world
    out["state_bytes"] = state_shard * int(replicas)
    if params is not None:
        pb = tree_bytes(params)
        out["params_bytes"] = pb // world
        out["total_bytes"] = out["state_bytes"] + pb // world
    else:
        out["total_bytes"] = out["state_bytes"]
    h = dims["H"]
    out["per_host_bytes"] = out["total_bytes"] // max(h, 1)
    return out


def state_bytes_at(cfg, capacity: int, send_budget: int) -> int:
    """Per-shard REGISTERED state bytes at an escalated
    (capacity, send_budget) shape — the pressure plane's pre-dispatch
    footprint predictor (the unregistered planes do not scale with
    either axis, so the delta between two shapes is exact)."""
    dims = dims_of(
        hosts_per_shard=cfg.hosts_per_shard,
        queue_capacity=capacity or cfg.queue_capacity,
        queue_block=cfg.queue_block,
        send_budget=send_budget or cfg.sends_per_host_round,
        trace_rounds=cfg.trace_rounds,
        pressure=cfg.pressure_abort,
        netobs=cfg.netobs,
        flow_records=cfg.flow_records,
        integrity=cfg.integrity,
        integrity_dual=cfg.integrity_dual,
        wheel_slots=cfg.wheel_slots,
        wheel_block=cfg.wheel_block,
        hier=cfg.hier_active,
    )
    return sum(component_totals(registered_component_bytes(dims)).values())


# --------------------------------------------------------------------------
# (b) compiled-program ledger — XLA's own accounting per cached rung
# --------------------------------------------------------------------------

_MA_FIELDS = (
    "generated_code_size_in_bytes",
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
)


def memory_analysis_dict(compiled) -> dict | None:
    """`Compiled.memory_analysis()` -> plain dict, or None when the
    backend provides no analysis. `peak_bytes` is the standard XLA
    decomposition: arguments + outputs + temps + code, minus the
    donation-aliased bytes counted twice."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {
        f.replace("_size_in_bytes", "_bytes"): int(getattr(ma, f))
        for f in _MA_FIELDS
        if hasattr(ma, f)
    }
    if not out:
        return None
    out["peak_bytes"] = (
        out.get("argument_bytes", 0)
        + out.get("output_bytes", 0)
        + out.get("temp_bytes", 0)
        + out.get("generated_code_bytes", 0)
        - out.get("alias_bytes", 0)
    )
    return out


def resized_avals(state, capacity: int, send_budget: int, queue_block: int):
    """ShapeDtypeStruct pytree of `state` re-seated at (capacity,
    send_budget), via the SAME migration ops the pressure plane uses —
    `jax.eval_shape` only, nothing runs."""
    import jax

    from shadow_tpu.core.engine import make_empty_outbox
    from shadow_tpu.ops.events import migrate_queue

    def f(st):
        q, ob = st.queue, st.outbox
        if capacity and capacity != q.t.shape[1]:
            q = migrate_queue(q, capacity, queue_block)
        if send_budget and send_budget != ob.t.shape[1]:
            ob = make_empty_outbox(ob.t.shape[0], send_budget, ob.count)
        return st._replace(queue=q, outbox=ob)

    return jax.eval_shape(f, state)


def ledger_entries(engine) -> dict[str, Any]:
    """key -> EngineConfig for every chunk program this engine's run
    touched: the base program plus each cached gear / resized rung."""
    out = {"base": engine.cfg}
    for g in sorted(engine._gear_chunks):
        out[f"gear={g}"] = dataclasses.replace(engine.cfg, gear_cols=g)
    for (g, c, b) in sorted(engine._resized_chunks):
        out[f"cap={c or engine.cfg.queue_capacity}/"
            f"box={b or engine.cfg.sends_per_host_round}/gear={g}"] = (
            engine.resized_cfg(g, c, b)
        )
    return out


def compiled_ledger(engine, state, params) -> dict[str, dict]:
    """Source (b): memory_analysis for every program in
    `ledger_entries`. Each entry is lowered against avals at ITS OWN
    shape (resized_avals re-seats the live state's tree), then compiled
    — reading the analysis needs a Compiled object, and jax's jit cache
    does not expose the one the run used, so this recompiles. Cost is
    paid only when the observatory is asked for a ledger (opt-in
    reporting, never the run loop)."""
    out: dict[str, dict] = {}
    for key, cfg in ledger_entries(engine).items():
        try:
            avals = resized_avals(
                state, cfg.queue_capacity, cfg.sends_per_host_round,
                cfg.queue_block,
            )
            compiled = engine._jit_chunk(cfg).lower(avals, params).compile()
        except Exception as e:
            # a rung that cannot lower/compile is a FINDING in the
            # ledger, never a reason to lose the rest of the report
            out[key] = {"error": f"{type(e).__name__}: {e}"}
            continue
        md = memory_analysis_dict(compiled)
        out[key] = md if md is not None else {"unavailable": True}
    return out


# --------------------------------------------------------------------------
# (c) live device sampling — per-shard HBM high-water
# --------------------------------------------------------------------------


def device_memory_stats(device) -> dict | None:
    """`device.memory_stats()`, defensively: CPU backends return None,
    some return {} — both mean "no allocator stats here"."""
    try:
        st = device.memory_stats()
    except Exception:
        return None
    return st or None


def device_capacity_bytes(device=None) -> int | None:
    """Best-known memory capacity of a device: the allocator's
    bytes_limit (TPU/GPU), else — for host-backed devices — the box's
    MemAvailable, else None (capacity unknown; guards that need one
    stay inert)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    st = device_memory_stats(device)
    if st:
        for key in ("bytes_limit", "bytes_reservable_limit"):
            if st.get(key):
                return int(st[key])
    if getattr(device, "platform", None) == "cpu":
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable:"):
                        return int(line.split()[1]) * 1024
        except OSError:
            return None
    return None


# bound the retained sample list (one sample per chunk; a week-long run
# must not grow an unbounded Python list) — the hwm fold is unaffected
MAX_SAMPLES = 8192


class MemoryMonitor:
    """Per-shard live HBM telemetry, sampled at chunk boundaries.

    `devices` is the mesh's device list (one entry per shard; world=1
    passes the single device). `stats_fn` injects a fake
    `memory_stats` for tests (the pre-dispatch-refusal gates run
    against synthetic headroom). When no device reports allocator
    stats, `sample(modeled_bytes=...)` falls back to the static model's
    exact live-state accounting so the high-water is honest, not zero —
    `source` says which world the numbers came from."""

    def __init__(self, devices=None, stats_fn=None):
        if devices is None:
            import jax

            devices = [jax.devices()[0]]
        self.devices = list(devices)
        self._stats_fn = stats_fn or device_memory_stats
        n = len(self.devices)
        self.peak = [0] * n  # per-shard high-water (bytes)
        self.last = [0] * n  # per-shard bytes at the last sample
        self.limit_bytes: int | None = None
        self.source: str | None = None
        self.samples: list[tuple[float | None, tuple[int, ...]]] = []
        self.samples_lost = 0
        self.count = 0

    def sample(
        self, *, modeled_bytes: int | None = None, wall_t: float | None = None
    ) -> list[int]:
        """One sample across the shard devices; returns per-shard
        bytes_in_use. `modeled_bytes` is the PER-SHARD modeled live
        total used when a device has no allocator stats."""
        per_shard: list[int] = []
        source = None
        for i, d in enumerate(self.devices):
            st = self._stats_fn(d)
            if st and st.get("bytes_in_use") is not None:
                used = int(st["bytes_in_use"])
                peak = int(st.get("peak_bytes_in_use", used))
                if st.get("bytes_limit"):
                    self.limit_bytes = int(st["bytes_limit"])
                source = source or "device"
            elif modeled_bytes is not None:
                used = peak = int(modeled_bytes)
                source = source or "modeled"
            else:
                used = peak = 0
            per_shard.append(used)
            self.peak[i] = max(self.peak[i], peak, used)
            self.last[i] = used
        if source is not None:
            self.source = self.source or source
        self.count += 1
        if len(self.samples) >= MAX_SAMPLES:
            self.samples_lost += 1
        else:
            self.samples.append((wall_t, tuple(per_shard)))
        return per_shard

    def headroom_bytes(self) -> int | None:
        """Worst-shard headroom against the allocator limit at the last
        sample, or None when no limit is known (the informed guard is
        then inert — there is nothing to refuse against)."""
        if self.limit_bytes is None or self.count == 0:
            return None
        return self.limit_bytes - max(self.last)

    def hwm_bytes(self) -> int:
        """Run high-water across shards (the heartbeat `hbm=` value)."""
        return max(self.peak) if self.peak else 0

    def report(self) -> dict:
        out: dict[str, Any] = {
            "source": self.source,
            "samples": self.count,
            "per_shard_hwm_bytes": list(self.peak),
            "bytes_in_use": list(self.last),
        }
        if self.limit_bytes is not None:
            out["limit_bytes"] = self.limit_bytes
            out["headroom_bytes"] = self.headroom_bytes()
        if self.samples_lost:
            out["samples_dropped"] = self.samples_lost
        return out


class MemoryGuard:
    """Pre-dispatch admission control for the pressure plane's grown
    rungs (threaded into core/pressure.py ResilienceController).

    A candidate (capacity, budget) rung is admitted only when the extra
    bytes it needs — the registered-state delta, charged `copies` times
    for the snapshot+migrated-state concurrency of a replay, times the
    configured safety factor — fit inside the monitor's measured
    headroom. Unknown headroom (no allocator limit: CPU backends, or no
    sample yet) admits everything: the guard exists to SAVE an OOM
    round-trip where measurement exists, never to invent limits where
    it doesn't."""

    def __init__(
        self,
        cfg,
        monitor: MemoryMonitor | None,
        safety_factor: float = DEFAULT_SAFETY_FACTOR,
        copies: int = DEFAULT_GUARD_COPIES,
    ):
        self.cfg = cfg
        self.monitor = monitor
        self.safety_factor = float(safety_factor)
        self.copies = int(copies)

    def predicted_need_bytes(
        self, cur_cap: int, cur_box: int, new_cap: int, new_box: int
    ) -> int:
        delta = state_bytes_at(self.cfg, new_cap, new_box) - state_bytes_at(
            self.cfg, cur_cap, cur_box
        )
        return max(int(delta * self.copies * self.safety_factor), 0)

    def admit(
        self, cur_cap: int, cur_box: int, new_cap: int, new_box: int
    ) -> tuple[bool, int, int | None]:
        """(ok, predicted_need_bytes, headroom_bytes)."""
        need = self.predicted_need_bytes(cur_cap, cur_box, new_cap, new_box)
        headroom = (
            self.monitor.headroom_bytes() if self.monitor is not None else None
        )
        if headroom is None:
            return True, need, None
        return need <= headroom, need, headroom


# --------------------------------------------------------------------------
# capacity planning + driver report assembly
# --------------------------------------------------------------------------


def plan_max_hosts(
    per_host_bytes: float, fixed_bytes: float, hbm_bytes: float,
    safety_factor: float = DEFAULT_SAFETY_FACTOR,
) -> int:
    """Max hosts one device fits: solve
    (fixed + hosts * per_host) * safety <= hbm. The ROADMAP question
    ("given this config, what is max hosts/device before OOM?") in one
    line — callers derive per_host/fixed from the static model plus the
    compiled ledger's temp slope."""
    if per_host_bytes <= 0:
        return 0
    budget = hbm_bytes / max(safety_factor, 1e-9) - fixed_bytes
    return max(int(budget // per_host_bytes), 0)


def observatory_report(
    engine, state, params, monitor: MemoryMonitor | None = None,
    *, replicas: int = 1, ledger: bool = True,
) -> dict:
    """The sim-stats `memory{}` block both drivers and bench share:
    model (a) + ledger (b) + live sampling (c)."""
    out: dict[str, Any] = {
        "model": static_model(engine.cfg, state, params, replicas=replicas),
    }
    if ledger:
        out["ledger"] = compiled_ledger(engine, state, params)
    if monitor is not None:
        out.update(monitor.report())
    return out
