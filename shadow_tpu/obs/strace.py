"""strace-style per-process syscall logs.

Reference: the strace hook wrapping every emulated syscall
(handler/mod.rs:348-369), formatter (host/syscall/formatter.rs), and
`StraceLoggingMode` off/standard/deterministic (configuration.rs:1162).
Deterministic mode prints only simulation-derived values so two runs (or
two schedulers) produce byte-identical files — the determinism suite
diffs them (determinism1_compare.cmake).
"""

from __future__ import annotations

from typing import IO

MAX_REPR = 64


def _fmt_val(v, deterministic: bool) -> str:
    if isinstance(v, bytes):
        body = v[:MAX_REPR]
        suffix = "..." if len(v) > MAX_REPR else ""
        return f"{body!r}{suffix}"
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt_val(x, deterministic) for x in v) + "]"
    if deterministic and isinstance(v, float):
        return "<float>"
    if isinstance(v, BaseException):
        return f"{type(v).__name__}({v})"
    return repr(v)


class StraceLogger:
    """Collects one process's syscall lines; attach via `Process.strace`."""

    def __init__(self, out: IO[str], mode: str = "standard"):
        if mode not in ("standard", "deterministic"):
            raise ValueError(f"strace mode {mode!r}")
        self.out = out
        self.mode = mode

    def __call__(self, t_ns: int, pid: int, name: str, args: tuple, result):
        det = self.mode == "deterministic"
        secs, ns = divmod(t_ns, 1_000_000_000)
        argstr = ", ".join(_fmt_val(a, det) for a in args)
        res = _fmt_val(result, det)
        self.out.write(f"{secs:02d}.{ns:09d} [{pid}] {name}({argstr}) = {res}\n")
