"""Pipes over a shared byte buffer.

Reference: `host/descriptor/pipe.rs` (475 LoC) on top of
`shared_buf.rs` — reader and writer ends share one bounded buffer; state
bits flip as it fills/drains; closing the peer end raises HUP/EPIPE.
"""

from __future__ import annotations

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState

PIPE_BUF_SIZE = 65536  # Linux default pipe capacity


class _SharedBuf:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.data = bytearray()
        self.readers = 0
        self.writers = 0

    def space(self) -> int:
        return self.capacity - len(self.data)


class PipeEnd(File):
    def __init__(self, buf: _SharedBuf, writable: bool):
        super().__init__()
        self.buf = buf
        self.is_writer = writable
        self.peer: "PipeEnd | None" = None
        if writable:
            buf.writers += 1
            self._set_state(on=FileState.WRITABLE)
        else:
            buf.readers += 1

    def _sync(self):
        """Recompute state bits from buffer + peer liveness."""
        if self.closed:
            return
        if self.is_writer:
            if self.buf.readers == 0:
                self._set_state(on=FileState.ERROR | FileState.HUP, off=FileState.WRITABLE)
            elif self.buf.space() > 0:
                self._set_state(on=FileState.WRITABLE)
            else:
                self._set_state(off=FileState.WRITABLE)
        else:
            readable = len(self.buf.data) > 0
            hup = self.buf.writers == 0
            on = FileState.NONE
            off = FileState.NONE
            if readable:
                on |= FileState.READABLE
            else:
                off |= FileState.READABLE
            if hup:
                on |= FileState.HUP
                if not readable:
                    on |= FileState.READABLE  # EOF is readable (read -> b"")
            self._set_state(on=on, off=off)

    def read(self, n: int) -> bytes | None:
        if self.is_writer:
            raise OSError("EBADF: read on write end")
        if self.buf.data:
            out = bytes(self.buf.data[:n])
            del self.buf.data[: len(out)]
            self._sync()
            if self.peer is not None:
                self.peer._sync()
            return out
        if self.buf.writers == 0:
            return b""  # EOF
        return None  # would block

    def write(self, data: bytes) -> int | None:
        if not self.is_writer:
            raise OSError("EBADF: write on read end")
        if self.buf.readers == 0:
            raise BrokenPipeError("EPIPE: no readers")  # + SIGPIPE in reference
        space = self.buf.space()
        if space == 0:
            return None  # would block
        took = bytes(data[:space])
        self.buf.data += took
        self._sync()
        if self.peer is not None:
            self.peer._sync()
        return len(took)

    def close(self):
        if self.closed:
            return
        if self.is_writer:
            self.buf.writers -= 1
        else:
            self.buf.readers -= 1
        super().close()
        if self.peer is not None:
            self.peer._sync()


Pipe = PipeEnd  # exported name


def create_pipe(capacity: int = PIPE_BUF_SIZE) -> tuple[PipeEnd, PipeEnd]:
    """Returns (read_end, write_end) like pipe(2)."""
    buf = _SharedBuf(capacity)
    r = PipeEnd(buf, writable=False)
    w = PipeEnd(buf, writable=True)
    r.peer = w
    w.peer = r
    return r, w
