"""Pipes over a shared byte buffer + the shared stream-end base.

Reference: `host/descriptor/pipe.rs` (475 LoC) on top of
`shared_buf.rs` — reader and writer ends share one bounded buffer; state
bits flip as it fills/drains; closing the peer end raises HUP/EPIPE.
`StreamEnd` is the generic (rx?, tx?) half over `_SharedBuf`s, reused by
unix-domain stream sockets (`host/unix.py`), which are exactly a crossed
pair of these buffers in the reference too.
"""

from __future__ import annotations

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState

PIPE_BUF_SIZE = 65536  # Linux default pipe capacity


class _SharedBuf:
    def __init__(self, capacity: int):
        self.capacity = capacity
        self.data = bytearray()
        self.readers = 0
        self.writers = 0

    def space(self) -> int:
        return self.capacity - len(self.data)


class StreamEnd(File):
    """One endpoint with an optional read buffer and optional write buffer.

    Subclasses set `_rx` (we read from it) and/or `_tx` (we write into it)
    plus `peer` for cross-end state refresh. `_err_on_peer_close` controls
    whether a dead reader marks the writer with ERROR (pipes do: EPIPE is
    an error condition; unix sockets report plain HUP like Linux)."""

    _err_on_peer_close = False

    def __init__(self):
        super().__init__()
        self._rx: _SharedBuf | None = None
        self._tx: _SharedBuf | None = None
        self.peer: "StreamEnd | None" = None

    # ---- state -------------------------------------------------------------

    def _sync(self):
        if self.closed:
            return
        on = FileState.NONE
        off = FileState.NONE
        if self._rx is not None:
            if len(self._rx.data) > 0:
                on |= FileState.READABLE
            else:
                off |= FileState.READABLE
            if self._rx.writers == 0:
                on |= FileState.HUP | FileState.READABLE  # EOF is readable
        if self._tx is not None:
            if self._tx.readers == 0:
                on |= FileState.HUP
                if self._err_on_peer_close:
                    on |= FileState.ERROR
                off |= FileState.WRITABLE
            elif self._tx.space() >= self._writable_min():
                on |= FileState.WRITABLE
            else:
                off |= FileState.WRITABLE
        # `on` wins over `off` (EOF marks an empty buffer readable)
        self._set_state(on=on, off=off & ~on)

    def _writable_min(self) -> int:
        """Free space needed before WRITABLE is raised. Streams: any byte.
        Pipes override to PIPE_BUF — pipe(7)'s POLLOUT contract — which is
        also what re-wakes a writer parked on an atomic small write."""
        return 1

    def _sync_both(self):
        self._sync()
        if self.peer is not None:
            self.peer._sync()

    # ---- I/O ---------------------------------------------------------------

    def read(self, n: int) -> bytes | None:
        if self._rx is None:
            raise OSError("EBADF: not readable")
        if self._rx.data:
            out = bytes(self._rx.data[:n])
            del self._rx.data[: len(out)]
            self._sync_both()
            return out
        if self._rx.writers == 0:
            return b""  # EOF
        return None  # would block

    def peek(self, n: int) -> bytes | None:
        """MSG_PEEK: same result contract as read() without consuming."""
        if self._rx is None:
            raise OSError("EBADF: not readable")
        if self._rx.data:
            return bytes(self._rx.data[:n])
        if self._rx.writers == 0:
            return b""  # EOF
        return None  # would block

    def write(self, data: bytes) -> int | None:
        if self._tx is None:
            raise OSError("EBADF: not writable")
        if self._tx.readers == 0:
            raise BrokenPipeError("EPIPE: no readers")
        space = self._tx.space()
        if space == 0:
            return None  # would block
        took = bytes(data[:space])
        self._tx.data += took
        self._sync_both()
        return len(took)

    def shutdown_write(self):
        """Half-close the write direction (unix SHUT_WR; pipes via close)."""
        if self._tx is not None:
            self._tx.writers -= 1
            self._tx = None
            self._sync_both()

    def close(self):
        if self.closed:
            return
        if self._tx is not None:
            self._tx.writers -= 1
            self._tx = None
        if self._rx is not None:
            self._rx.readers -= 1
            self._rx = None
        peer = self.peer
        super().close()
        if peer is not None:
            peer._sync()


class PipeEnd(StreamEnd):
    _err_on_peer_close = True  # EPIPE surfaces as ERROR on the write end

    PIPE_BUF = 4096  # pipe(7): writes <= PIPE_BUF are atomic

    def __init__(self, buf: _SharedBuf, writable: bool):
        super().__init__()
        self.is_writer = writable
        if writable:
            self._tx = buf
            buf.writers += 1
            self._set_state(on=FileState.WRITABLE)
        else:
            self._rx = buf
            buf.readers += 1

    def _writable_min(self) -> int:
        if self._tx is None:
            return 1
        return min(self.PIPE_BUF, self._tx.capacity)

    def write(self, data: bytes) -> int | None:
        if (
            self._tx is not None
            and self._tx.readers != 0
            and len(data) <= min(self.PIPE_BUF, self._tx.capacity)
            and self._tx.space() < len(data)
        ):
            # atomicity: a small write must land whole or not at all —
            # the kernel never tears records <= PIPE_BUF across
            # interleaved writers (O_NONBLOCK gets EAGAIN, blockers wait)
            return None
        return super().write(data)


Pipe = PipeEnd  # exported name


def create_pipe(capacity: int = PIPE_BUF_SIZE) -> tuple[PipeEnd, PipeEnd]:
    """Returns (read_end, write_end) like pipe(2)."""
    buf = _SharedBuf(capacity)
    r = PipeEnd(buf, writable=False)
    w = PipeEnd(buf, writable=True)
    r.peer = w
    w.peer = r
    return r, w
