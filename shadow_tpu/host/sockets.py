"""Socket files: UDP datagrams and TCP streams over `shadow_tpu.tcp`.

Reference: `host/descriptor/socket/inet/` — `udp.rs` (1157 LoC),
`tcp.rs` (the adapter binding the sans-I/O TCP crate to socket/file
semantics, 1135 LoC) and the listener/accept-queue handling inside it.
A socket talks to the world through its `NetworkNamespace` (port demux)
and the host's packet egress (`CpuHost.send_packet`).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState
from shadow_tpu.tcp import Segment, State, TcpConfig, TcpState
from shadow_tpu.tcp.state import rst_for

PROTO_UDP = 17
PROTO_TCP = 6

UDP_RCVBUF_PACKETS = 256
UDP_MAX_PAYLOAD = 65507  # IPv4 datagram limit (65535 - 20 IP - 8 UDP)


@dataclass
class NetPacket:
    """A packet on the simulated wire (CPU plane). For TCP, `seg` carries
    the full segment; `payload` mirrors seg.payload for size accounting."""

    src_ip: str
    src_port: int
    dst_ip: str
    dst_port: int
    proto: int
    payload: bytes = b""
    seg: Segment | None = None
    # delivery-status breadcrumbs (reference packet.rs:16-39): when the
    # owning host enables them (HostConfig.breadcrumbs), every hop appends
    # (sim_time_ns, status) so a dropped packet's DROP SITE is readable
    # from host.packet_drops — digests say THAT histories diverged,
    # breadcrumbs say WHERE a packet died. None = disabled (zero cost).
    trail: list | None = None

    def crumb(self, t_ns: int, status: str):
        if self.trail is not None:
            self.trail.append((t_ns, status))

    @property
    def size_bytes(self) -> int:
        # IP+transport header burden like the reference's packet sizing
        return len(self.payload) + (28 if self.proto == PROTO_UDP else 40)


class _SocketBase(File):
    def __init__(self, netns):
        super().__init__()
        self.netns = netns
        self.local_ip: str | None = None
        self.local_port: int | None = None
        self.peer_ip: str | None = None
        self.peer_port: int | None = None
        # per-socket wire counters (reference tracker.c:24-80); attributed
        # centrally in CpuHost.send_packet/deliver_packet by port lookup
        self.sock_id = netns.next_sock_id()
        self.stat = {"tx_pkts": 0, "tx_bytes": 0, "rx_pkts": 0, "rx_bytes": 0}

    @property
    def host(self):
        return self.netns.host

    def stat_record(self) -> dict:
        peer = (
            f"{self.peer_ip}:{self.peer_port}"
            if self.peer_port is not None
            else None
        )
        return {
            "id": self.sock_id,
            "proto": "tcp" if self.PROTO == PROTO_TCP else "udp",
            "local": f"{self.local_ip or '*'}:{self.local_port or 0}",
            "peer": peer,
            **self.stat,
        }

    def bind(self, ip: str, port: int):
        if self.local_port is not None:
            raise OSError("EINVAL: already bound")
        self.netns.bind(self, ip, port)

    def _autobind(self):
        if self.local_port is None:
            self.netns.bind(self, self.netns.default_ip, 0)

    def close(self):
        if self.closed:
            return
        # final stat capture happens in netns.unbind — the teardown point
        # ALL socket types funnel through (TcpSocket.close does not call
        # super(); its flow unbinds from _after_tcp when fully closed)
        self.netns.unbind(self)
        super().close()


class UdpSocket(_SocketBase):
    PROTO = PROTO_UDP

    def __init__(self, netns):
        super().__init__(netns)
        self._rcv: list[tuple[str, int, bytes]] = []  # (src_ip, src_port, data)
        self._set_state(on=FileState.WRITABLE)

    def connect(self, ip: str, port: int):
        self._autobind()
        self.peer_ip = ip
        self.peer_port = port

    def sendto(self, data: bytes, addr: tuple[str, int] | None = None) -> int:
        if len(data) > UDP_MAX_PAYLOAD:
            raise OSError(f"EMSGSIZE: datagram of {len(data)} bytes")
        if addr is None:
            if self.peer_ip is None:
                raise OSError("EDESTADDRREQ")
            addr = (self.peer_ip, self.peer_port)
        self._autobind()
        self.host.send_packet(
            NetPacket(
                src_ip=self.local_ip,
                src_port=self.local_port,
                dst_ip=addr[0],
                dst_port=addr[1],
                proto=PROTO_UDP,
                payload=bytes(data),
            )
        )
        return len(data)

    def recvfrom(self, n: int) -> tuple[bytes, tuple[str, int]] | None:
        if not self._rcv:
            return None  # would block
        src_ip, src_port, data = self._rcv.pop(0)
        if not self._rcv:
            self._set_state(off=FileState.READABLE)
        return data[:n], (src_ip, src_port)

    def peekfrom(self, n: int) -> tuple[bytes, tuple[str, int]] | None:
        """MSG_PEEK: next datagram without popping it."""
        if not self._rcv:
            return None
        src_ip, src_port, data = self._rcv[0]
        return data[:n], (src_ip, src_port)

    def read(self, n: int) -> bytes | None:
        r = self.recvfrom(n)
        return None if r is None else r[0]

    def write(self, data: bytes) -> int | None:
        return self.sendto(data)

    # netns delivery
    def deliver(self, pkt: NetPacket):
        if self.peer_ip is not None and (
            pkt.src_ip != self.peer_ip or pkt.src_port != self.peer_port
        ):
            # connected socket filters other peers
            self.host.drop_packet(pkt, "rcv_udp_peer_filtered")
            return
        if len(self._rcv) >= UDP_RCVBUF_PACKETS:
            # rcvbuf overflow: silently dropped (on the wire), like real
            # UDP — but the breadcrumb trail names this exact site
            self.host.drop_packet(pkt, "rcv_udp_buffer_full")
            return
        pkt.crumb(self.host.now(), "rcv_socket_delivered")
        self._rcv.append((pkt.src_ip, pkt.src_port, pkt.payload))
        self._set_state(on=FileState.READABLE)


class TcpSocket(_SocketBase):
    """A connection-mode TCP socket wrapping one `TcpState`."""

    PROTO = PROTO_TCP

    def __init__(self, netns, tcp: TcpState | None = None, cfg: TcpConfig | None = None):
        super().__init__(netns)
        self.cfg = cfg or getattr(netns.host.cfg, "tcp", None) or TcpConfig()
        self.tcp = tcp or TcpState(self.cfg, iss=netns.host.next_iss())
        self._timer_token = None
        self._sync()

    # ---- app surface -------------------------------------------------------

    def connect(self, ip: str, port: int):
        self._autobind()
        self.peer_ip = ip
        self.peer_port = port
        self.netns.register_flow(self)
        self.tcp.connect(self.host.now())
        self._after_tcp()

    def write(self, data: bytes) -> int | None:
        if self.tcp.error is not None:
            raise ConnectionResetError(self.tcp.error.value)
        n = self.tcp.send(bytes(data))
        self._after_tcp()
        if n == 0:
            return None  # send buffer full: would block
        return n

    def read(self, n: int) -> bytes | None:
        out = self.tcp.recv(n)
        self._after_tcp()
        return out

    def peek(self, n: int) -> bytes | None:
        """MSG_PEEK: read() contract (None=block, b''=EOF) w/o consuming.
        Real clients (wget's persistent-connection probe) peek response
        headers before reading them."""
        buf = self.tcp.rcv_buf
        if buf.readable():
            return bytes(buf._ready[:n])
        if self.tcp.rcv_fin_seen or self.tcp.error is not None:
            return b""
        from shadow_tpu.tcp import State as TS

        if self.tcp.state in (TS.CLOSED, TS.LISTEN):
            return b""
        return None

    def shutdown_write(self):
        self.tcp.shutdown_write(self.host.now())
        self._after_tcp()

    def close(self):
        """App close. The flow stays registered in the netns until TCP
        reaches CLOSED so in-flight FIN/ACK/TIME_WAIT traffic still demuxes
        here (the reference keeps its socket alive the same way)."""
        if self.closed:
            return
        if not self.tcp.is_closed():
            self.tcp.close(self.host.now())
        self._set_state(on=FileState.CLOSED, off=FileState.ACTIVE)
        self._after_tcp()

    # ---- wire surface ------------------------------------------------------

    def deliver(self, pkt: NetPacket):
        if pkt.seg is None:
            return
        self.tcp.on_segment(self.host.now(), pkt.seg)
        self._after_tcp()

    _listener: "TcpListenerSocket | None" = None  # set for accept()ed children

    def _after_tcp(self):
        """Flush segments, re-arm the TCP timer, refresh state bits."""
        now = self.host.now()
        for seg in self.tcp.poll_segments(now):
            self._emit(seg)
        self._rearm_timer()
        self._sync()
        if self._listener is not None and self.tcp.state == State.ESTABLISHED:
            lst, self._listener = self._listener, None
            lst._reap(self)
        if self.tcp.state == State.CLOSED and self.closed:
            self._rearm_timer()  # clears any residual token
            self.netns.unbind(self)

    def _emit(self, seg: Segment):
        seg = dataclasses.replace(
            seg,
            src_port=self.local_port or 0,
            dst_port=self.peer_port or 0,
        )
        self.host.send_packet(
            NetPacket(
                src_ip=self.local_ip or self.netns.default_ip,
                src_port=self.local_port or 0,
                dst_ip=self.peer_ip,
                dst_port=self.peer_port,
                proto=PROTO_TCP,
                payload=seg.payload,
                seg=seg,
            )
        )

    def _rearm_timer(self):
        if self._timer_token is not None:
            self.host.cancel(self._timer_token)
            self._timer_token = None
        t = self.tcp.next_timer()
        if t is not None:
            self._timer_token = self.host.schedule(t, self._on_timer)

    def _on_timer(self):
        self._timer_token = None
        self.tcp.on_timer(self.host.now())
        self._after_tcp()

    def _sync(self):
        on = FileState.NONE
        off = FileState.NONE
        if self.tcp.readable():
            on |= FileState.READABLE
        else:
            off |= FileState.READABLE
        if self.tcp.writable():
            on |= FileState.WRITABLE
        else:
            off |= FileState.WRITABLE
        if self.tcp.error is not None:
            on |= FileState.ERROR
        if self.tcp.rcv_fin_seen:
            on |= FileState.HUP
        self._set_state(on=on, off=off)


class TcpListenerSocket(_SocketBase):
    """listen(2) socket: forks a child TcpSocket per SYN, queues established
    children for accept (reference tcp.rs accept-queue handling)."""

    PROTO = PROTO_TCP

    def __init__(self, netns, cfg: TcpConfig | None = None, backlog: int = 128):
        super().__init__(netns)
        self.cfg = cfg or getattr(netns.host.cfg, "tcp", None) or TcpConfig()
        self.backlog = backlog
        self.tcp = TcpState(self.cfg, iss=0)
        self.tcp.listen()
        self._pending: list[TcpSocket] = []  # handshaking children
        self._accept_q: list[TcpSocket] = []  # ESTABLISHED, ready to accept

    def accept(self) -> TcpSocket | None:
        if not self._accept_q:
            return None  # would block
        child = self._accept_q.pop(0)
        if not self._accept_q:
            self._set_state(off=FileState.ACCEPTABLE | FileState.READABLE)
        return child

    def deliver(self, pkt: NetPacket):
        if pkt.seg is None:
            return
        now = self.host.now()
        if len(self._pending) + len(self._accept_q) >= self.backlog:
            # backlog full: drop SYN (peer retries), like Linux
            self.host.drop_packet(pkt, "rcv_tcp_backlog_full")
            return
        child_tcp = self.tcp.accept_segment(
            now, pkt.seg, child_iss=self.host.next_iss()
        )
        if child_tcp is None:
            rst = rst_for(pkt.seg)
            if rst is not None:
                self.host.send_packet(
                    NetPacket(
                        src_ip=self.local_ip,
                        src_port=self.local_port,
                        dst_ip=pkt.src_ip,
                        dst_port=pkt.src_port,
                        proto=PROTO_TCP,
                        seg=rst,
                    )
                )
            return
        child = TcpSocket(self.netns, tcp=child_tcp, cfg=self.cfg)
        child.local_ip = self.local_ip
        child.local_port = self.local_port
        child.peer_ip = pkt.src_ip
        child.peer_port = pkt.src_port
        child._listener = self
        self.netns.register_flow(child)
        self._pending.append(child)
        child._after_tcp()  # emits the SYN-ACK

    def _reap(self, child: TcpSocket):
        """Move children that completed the handshake to the accept queue."""
        if child in self._pending and child.tcp.state == State.ESTABLISHED:
            self._pending.remove(child)
            self._accept_q.append(child)
            self._set_state(on=FileState.ACCEPTABLE | FileState.READABLE)

    def poll_children(self):
        for child in list(self._pending):
            self._reap(child)

    def close(self):
        if self.closed:
            return
        for child in self._pending + self._accept_q:
            child.tcp.abort(self.host.now())
            child._after_tcp()
        self._pending.clear()
        self._accept_q.clear()
        super().close()
