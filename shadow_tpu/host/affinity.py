"""CPU pinning / NUMA affinity for CPU-host-plane workers.

Reference: `src/main/core/affinity.c` — parses platform topology (logical
CPU -> core -> socket -> node), tracks how many workers were assigned to
each level, and gives the next worker the logical CPU whose (node, socket,
core, cpu) load vector is smallest, so workers pack distinct physical
cores first and spill onto hyperthread siblings last. The knob is
`experimental.use_cpu_pinning` (configuration.rs ExperimentalOptions).

Python recast: topology comes from sysfs
(`/sys/devices/system/cpu/cpu*/topology/{core_id,physical_package_id}`,
`/sys/devices/system/node/node*/cpulist`), restricted to the process's
inherited affinity mask (the reference honors the initial mask the same
way). Pinning itself is `os.sched_setaffinity(0, {cpu})`: on Linux, pid 0
means the *calling thread*, so each pool worker pins itself at startup.
On a single-CPU box every worker legally lands on the one CPU — the
assignment degrades to a no-op rather than failing.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class CpuInfo:
    """One logical CPU and its position in the machine (affinity.c CPUInfo)."""

    cpu: int
    core: int
    socket: int
    node: int


def _read_int(path: str, default: int = 0) -> int:
    try:
        with open(path) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return default


def _parse_cpulist(text: str) -> set[int]:
    """Parse a sysfs cpulist ("0-3,8,10-11") into a set of cpu numbers."""
    cpus: set[int] = set()
    for part in text.strip().split(","):
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            cpus.update(range(int(lo), int(hi) + 1))
        else:
            cpus.add(int(part))
    return cpus


def topology(allowed: set[int] | None = None) -> list[CpuInfo]:
    """The machine's logical CPUs, restricted to `allowed` (defaults to the
    process's current affinity mask, matching affinity.c's use of the
    initial mask as the universe)."""
    if allowed is None:
        try:
            allowed = set(os.sched_getaffinity(0))
        except (AttributeError, OSError):  # non-Linux
            allowed = set(range(os.cpu_count() or 1))
    node_of: dict[int, int] = {}
    try:
        for entry in os.listdir("/sys/devices/system/node"):
            if not (entry.startswith("node") and entry[4:].isdigit()):
                continue
            nid = int(entry[4:])
            try:
                with open(f"/sys/devices/system/node/{entry}/cpulist") as f:
                    for cpu in _parse_cpulist(f.read()):
                        node_of[cpu] = nid
            except OSError:
                continue
    except OSError:
        pass
    infos = []
    for cpu in sorted(allowed):
        base = f"/sys/devices/system/cpu/cpu{cpu}/topology"
        infos.append(
            CpuInfo(
                cpu=cpu,
                core=_read_int(f"{base}/core_id", cpu),
                socket=_read_int(f"{base}/physical_package_id", 0),
                node=node_of.get(cpu, 0),
            )
        )
    return infos


def assign(n_workers: int, cpus: list[CpuInfo] | None = None) -> list[int]:
    """Pick a logical CPU for each of `n_workers` workers.

    affinity.c's greedy: each worker goes to the CPU minimizing the load
    vector (node_load, socket_load, core_load, cpu_load, cpu_num) — i.e.
    stay on one NUMA node while it has idle cores, use every physical core
    before doubling up on SMT siblings, and break ties by lowest cpu
    number for determinism."""
    if cpus is None:
        cpus = topology()
    if not cpus:
        return [0] * n_workers
    node_load: dict[int, int] = {}
    socket_load: dict[tuple, int] = {}
    core_load: dict[tuple, int] = {}
    cpu_load: dict[int, int] = {}
    out = []
    for _ in range(n_workers):
        best = min(
            cpus,
            key=lambda c: (
                node_load.get(c.node, 0),
                socket_load.get((c.node, c.socket), 0),
                core_load.get((c.node, c.socket, c.core), 0),
                cpu_load.get(c.cpu, 0),
                c.cpu,
            ),
        )
        node_load[best.node] = node_load.get(best.node, 0) + 1
        sk = (best.node, best.socket)
        socket_load[sk] = socket_load.get(sk, 0) + 1
        ck = (best.node, best.socket, best.core)
        core_load[ck] = core_load.get(ck, 0) + 1
        cpu_load[best.cpu] = cpu_load.get(best.cpu, 0) + 1
        out.append(best.cpu)
    return out


def pin_current(cpu: int) -> bool:
    """Pin the calling thread to `cpu`. Returns False (never raises) when
    the platform refuses — pinning is a performance hint, not a
    correctness requirement (affinity.c logs and continues the same way)."""
    try:
        os.sched_setaffinity(0, {cpu})
        return True
    except (AttributeError, OSError, ValueError):
        return False
