"""timerfd(2) emulation (reference `host/descriptor/timerfd.rs`, 294 LoC,
over the host Timer; expiration bumps a counter read as 8 bytes)."""

from __future__ import annotations

from typing import Protocol

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState


class Scheduler(Protocol):
    """What a TimerFd needs from its host: the simulated clock and one-shot
    task scheduling (reference Host::schedule_task_at_emulated_time)."""

    def now(self) -> int: ...
    def schedule(self, t_ns: int, fn) -> object: ...
    def cancel(self, token: object) -> None: ...


class TimerFd(File):
    def __init__(self, sched: Scheduler):
        super().__init__()
        self.sched = sched
        self.expirations = 0
        self.deadline: int | None = None  # absolute ns
        self.interval: int = 0  # 0 = one-shot
        self._token: object | None = None

    # ---- timerfd_settime / gettime ----------------------------------------

    def settime(self, deadline_ns: int | None, interval_ns: int = 0) -> tuple[int, int]:
        """Arm (absolute deadline) or disarm (None). Returns previous
        (remaining_ns, interval_ns) like timerfd_settime's old_value."""
        old = self.gettime()
        if self._token is not None:
            self.sched.cancel(self._token)
            self._token = None
        self.expirations = 0
        self._set_state(off=FileState.READABLE)
        self.deadline = deadline_ns
        self.interval = interval_ns
        if deadline_ns is not None:
            self._token = self.sched.schedule(deadline_ns, self._fire)
        return old

    def gettime(self) -> tuple[int, int]:
        if self.deadline is None:
            return (0, self.interval)
        return (max(0, self.deadline - self.sched.now()), self.interval)

    def _fire(self):
        self.expirations += 1
        self._set_state(on=FileState.READABLE)
        if self.interval > 0:
            self.deadline = self.sched.now() + self.interval
            self._token = self.sched.schedule(self.deadline, self._fire)
        else:
            self.deadline = None
            self._token = None

    # ---- file surface ------------------------------------------------------

    def read(self, n: int) -> bytes | None:
        if n < 8:
            raise OSError("EINVAL: timerfd reads need 8 bytes")
        if self.expirations == 0:
            return None  # would block
        val = self.expirations
        self.expirations = 0
        self._set_state(off=FileState.READABLE)
        return val.to_bytes(8, "little")

    def close(self):
        if self._token is not None:
            self.sched.cancel(self._token)
            self._token = None
        super().close()
