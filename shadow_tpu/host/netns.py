"""Per-host network namespace: interfaces, port association, packet demux.

Reference: `host/network/namespace.rs` (399 LoC — localhost + eth0 and the
AssociatedPorts registry) and the socket demux inside
`network_interface.c` (find socket by (proto, local port, peer)). Flows
(connected TCP 4-tuples) take precedence over wildcard port bindings
(listeners / unconnected UDP), like the reference's most-specific-match.
"""

from __future__ import annotations

from shadow_tpu.host.sockets import (
    NetPacket,
    PROTO_TCP,
    TcpListenerSocket,
    UdpSocket,
)
from shadow_tpu.tcp.state import rst_for

EPHEMERAL_START = 49152
EPHEMERAL_END = 65535


class NetworkNamespace:
    def __init__(self, host, ip: str):
        self.host = host
        self.default_ip = ip
        # (proto, local_port) -> socket  [listeners + UDP binds]
        self._ports: dict[tuple[int, int], object] = {}
        # (proto, local_port, peer_ip, peer_port) -> TcpSocket [flows]
        self._flows: dict[tuple[int, int, str, int], object] = {}
        self._next_ephemeral = EPHEMERAL_START
        # abstract unix-domain namespace (reference abstract_unix_ns.rs)
        self.abstract_unix: dict[str, object] = {}
        self._sock_serial = 0

    # ---- tracker support (tracker.c per-socket counters) -------------------

    def next_sock_id(self) -> int:
        self._sock_serial += 1
        return self._sock_serial

    def socket_for_local(self, proto: int, local_port: int,
                         remote_ip: str, remote_port: int):
        """Most-specific socket owning (proto, local_port) traffic with the
        given remote endpoint — flow first, then port binding (the demux
        rule, reused for counter attribution)."""
        if proto == PROTO_TCP:
            flow = self._flows.get(
                (PROTO_TCP, local_port, remote_ip, remote_port)
            )
            if flow is not None:
                return flow
        return self._ports.get((proto, local_port))

    def live_sockets(self):
        seen = set()
        for sock in list(self._ports.values()) + list(self._flows.values()):
            if id(sock) not in seen:
                seen.add(id(sock))
                yield sock

    # ---- binding -----------------------------------------------------------

    def bind(self, sock, ip: str, port: int):
        if port == 0:
            port = self._alloc_ephemeral(sock.PROTO)
        key = (sock.PROTO, port)
        if key in self._ports:
            raise OSError(f"EADDRINUSE: port {port}")
        self._ports[key] = sock
        sock.local_ip = ip if ip not in ("0.0.0.0", "") else self.default_ip
        sock.local_port = port

    def _alloc_ephemeral(self, proto: int) -> int:
        for _ in range(EPHEMERAL_END - EPHEMERAL_START + 1):
            p = self._next_ephemeral
            self._next_ephemeral += 1
            if self._next_ephemeral > EPHEMERAL_END:
                self._next_ephemeral = EPHEMERAL_START
            if (proto, p) not in self._ports:
                return p
        raise OSError("EADDRNOTAVAIL: ephemeral ports exhausted")

    def register_flow(self, sock):
        """Track a connected TCP socket by its 4-tuple."""
        key = (PROTO_TCP, sock.local_port, sock.peer_ip, sock.peer_port)
        self._flows[key] = sock

    def unbind(self, sock):
        if sock.local_port is not None:
            key = (sock.PROTO, sock.local_port)
            if self._ports.get(key) is sock:
                del self._ports[key]
        if getattr(sock, "peer_ip", None) is not None:
            fkey = (PROTO_TCP, sock.local_port, sock.peer_ip, sock.peer_port)
            if self._flows.get(fkey) is sock:
                del self._flows[fkey]
        # tracker: keep the totals of any socket that saw traffic (the
        # reference reports until-close activity, not just live sockets).
        # Here because every socket type funnels through unbind at
        # teardown, including TcpSocket whose close() bypasses the base.
        if not getattr(sock, "_stats_recorded", False) and any(
            sock.stat.values()
        ):
            sock._stats_recorded = True
            self.host.closed_socket_stats.append(sock.stat_record())

    # ---- demux -------------------------------------------------------------

    def deliver(self, pkt: NetPacket):
        """Incoming packet -> most specific matching socket."""
        if pkt.proto == PROTO_TCP:
            flow = self._flows.get(
                (PROTO_TCP, pkt.dst_port, pkt.src_ip, pkt.src_port)
            )
            if flow is not None:
                pkt.crumb(self.host.now(), "rcv_flow_delivered")
                flow.deliver(pkt)
                return
        sock = self._ports.get((pkt.proto, pkt.dst_port))
        if sock is not None:
            sock.deliver(pkt)
            return
        # no receiver: TCP answers RST (reference closed-port behavior),
        # UDP drops (ICMP unreachable is out of scope, as in the reference)
        self.host.drop_packet(pkt, "rcv_no_listener")
        if pkt.proto == PROTO_TCP and pkt.seg is not None:
            rst = rst_for(pkt.seg)
            if rst is not None:
                self.host.send_packet(
                    NetPacket(
                        src_ip=pkt.dst_ip,
                        src_port=pkt.dst_port,
                        dst_ip=pkt.src_ip,
                        dst_port=pkt.src_port,
                        proto=PROTO_TCP,
                        seg=rst,
                    )
                )

    # ---- stats -------------------------------------------------------------

    def socket_count(self) -> int:
        return len(self._ports) + len(self._flows)
