"""epoll(7) emulation.

Reference: `host/descriptor/epoll.rs` wrapper + `epoll.c` (775 LoC): an
interest list of watched files, a ready set maintained by status listeners,
level- and edge-triggered modes, and the epoll fd itself being pollable
(readable when the ready set is non-empty) so epolls nest.
"""

from __future__ import annotations

from dataclasses import dataclass

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState, StatusListener

EPOLLIN = 0x001
EPOLLOUT = 0x004
EPOLLERR = 0x008
EPOLLHUP = 0x010
EPOLLET = 1 << 31


def _interest_to_state(events: int) -> FileState:
    s = FileState.ERROR | FileState.HUP | FileState.CLOSED  # always reported
    if events & EPOLLIN:
        s |= FileState.READABLE | FileState.ACCEPTABLE
    if events & EPOLLOUT:
        s |= FileState.WRITABLE
    return s


def _state_to_events(state: FileState, interest: int) -> int:
    ev = 0
    if state & (FileState.READABLE | FileState.ACCEPTABLE) and interest & EPOLLIN:
        ev |= EPOLLIN
    if state & FileState.WRITABLE and interest & EPOLLOUT:
        ev |= EPOLLOUT
    if state & FileState.ERROR:
        ev |= EPOLLERR
    if state & (FileState.HUP | FileState.CLOSED):
        ev |= EPOLLHUP
    return ev


@dataclass
class EpollEvent:
    fd: int
    events: int
    data: int  # epoll_data (opaque u64)


class _Watch:
    def __init__(self, epoll: "Epoll", fd: int, file: File, events: int, data: int):
        self.epoll = epoll
        self.fd = fd
        self.file = file
        self.events = events
        self.data = data
        self.ready_events = 0  # edge-trigger: armed on transitions
        self.listener = StatusListener(
            _interest_to_state(events), self._on_change, level=True
        )
        file.add_listener(self.listener)

    def _on_change(self, state: FileState, changed: FileState):
        if state & FileState.CLOSED:
            # Linux removes a file from every epoll interest list when its
            # last fd closes — no event is delivered for the closed file.
            # (Deferred callbacks may fire after an explicit remove/close,
            # hence the membership check.)
            if self.epoll._watches.get(self.fd) is self:
                self.epoll.remove(self.fd)
            return
        ev = _state_to_events(state, self.events)
        if ev:
            self.ready_events |= ev
            self.epoll._mark_ready(self)
        elif not (self.events & EPOLLET):
            self.ready_events = 0
            self.epoll._mark_unready(self)


class Epoll(File):
    def __init__(self):
        super().__init__()
        self._watches: dict[int, _Watch] = {}
        self._ready: dict[int, _Watch] = {}  # insertion-ordered ready "set"

    # ---- epoll_ctl ---------------------------------------------------------

    def add(self, fd: int, file: File, events: int, data: int | None = None):
        if fd in self._watches:
            raise OSError("EEXIST")
        w = _Watch(self, fd, file, events, data if data is not None else fd)
        self._watches[fd] = w
        self._refresh(w)

    def modify(self, fd: int, events: int, data: int | None = None):
        w = self._watches.get(fd)
        if w is None:
            raise OSError("ENOENT")
        w.events = events
        if data is not None:
            w.data = data
        w.listener.interest = _interest_to_state(events)
        w.ready_events = 0
        self._mark_unready(w)
        self._refresh(w)

    def remove(self, fd: int):
        w = self._watches.pop(fd, None)
        if w is None:
            raise OSError("ENOENT")
        w.file.remove_listener(w.listener)
        self._mark_unready(w)

    def _refresh(self, w: _Watch):
        ev = _state_to_events(w.file.state, w.events)
        if ev:
            w.ready_events |= ev
            self._mark_ready(w)

    # ---- ready tracking ----------------------------------------------------

    def _mark_ready(self, w: _Watch):
        self._ready.setdefault(w.fd, w)
        self._set_state(on=FileState.READABLE)

    def _mark_unready(self, w: _Watch):
        self._ready.pop(w.fd, None)
        if not self._ready:
            self._set_state(off=FileState.READABLE)

    # ---- epoll_wait --------------------------------------------------------

    def wait(self, max_events: int) -> list[EpollEvent] | None:
        """Collect ready events; None = would block (no ready fds)."""
        out: list[EpollEvent] = []
        for fd in list(self._ready):
            if len(out) >= max_events:
                break
            w = self._ready[fd]
            if w.events & EPOLLET:
                ev = w.ready_events  # consume the edge
                w.ready_events = 0
                self._mark_unready(w)
            else:
                ev = _state_to_events(w.file.state, w.events)
                if not ev:
                    self._mark_unready(w)
                    continue
            out.append(EpollEvent(fd=w.fd, events=ev, data=w.data))
        if not out:
            return None
        return out

    def close(self):
        for fd in list(self._watches):
            self.remove(fd)
        super().close()
