"""Managed programs: coroutine processes + the syscall dispatch layer.

Reference: `host/process.rs` + `host/thread.rs` (virtual pids/tids, blocked
`SyscallCondition`, resume re-runs the same syscall —
`thread.rs:471-511`), and the syscall handler dispatch
(`host/syscall/handler/mod.rs:371-539`). Programs here are Python
generators that `yield` syscall tuples — the sans-I/O equivalent of a
managed process trapping into the simulator; a blocked syscall parks the
process on a (file-state mask | timeout) trigger and is re-executed when
the condition fires, exactly the reference's blocking model
(`syscall_condition.c`).

A program:

    def client(ctx):
        fd = yield ("socket", "tcp")
        yield ("connect", fd, ("10.0.0.2", 80))
        n = yield ("send", fd, b"GET /")
        data = yield ("recv", fd, 4096)
        yield ("exit", 0)

`ctx` carries host identity and process args. The syscall surface covers the
core families the reference's test corpus exercises (SURVEY.md §4.2):
sockets, pipes, epoll, eventfd, timerfd, time, sleep, random, dup, stdio.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from shadow_tpu.host.descriptor import DescriptorTable, File
from shadow_tpu.host.epoll import Epoll
from shadow_tpu.host.eventfd import EventFd
from shadow_tpu.host.filestate import CallbackQueue, FileState, StatusListener
from shadow_tpu.host.pipe import create_pipe
from shadow_tpu.host.sockets import TcpListenerSocket, TcpSocket, UdpSocket
from shadow_tpu.host.timerfd import TimerFd
from shadow_tpu.host.unix import UnixStreamSocket

NS_PER_SEC = 1_000_000_000


@dataclass
class Syscall:
    name: str
    args: tuple

    @classmethod
    def of(cls, req) -> "Syscall":
        if isinstance(req, Syscall):
            return req
        if isinstance(req, tuple) and req and isinstance(req[0], str):
            return cls(req[0], tuple(req[1:]))
        raise TypeError(f"program yielded {req!r}; expected (name, *args)")


@dataclass
class Blocked:
    """Syscall result meaning: park until `file` shows `mask` bits (or the
    absolute-ns `timeout`). `on_timeout` is delivered as the syscall result
    if the timer fires first; otherwise the syscall is re-executed."""

    file: File | None = None
    mask: FileState = FileState.NONE
    timeout: int | None = None
    on_timeout: Any = None
    has_timeout_result: bool = False


class ProcState(enum.Enum):
    RUNNING = "running"
    BLOCKED = "blocked"
    ZOMBIE = "zombie"


_WAIT_READ = FileState.READABLE | FileState.HUP | FileState.ERROR | FileState.CLOSED
_WAIT_WRITE = FileState.WRITABLE | FileState.HUP | FileState.ERROR | FileState.CLOSED
_WAIT_ACCEPT = FileState.ACCEPTABLE | FileState.ERROR | FileState.CLOSED


@dataclass
class ProgramCtx:
    host_name: str
    ip: str
    pid: int
    args: dict


class Process:
    """One managed process (single-threaded; the reference's thread-group
    structure collapses to process==thread here, the common case)."""

    def __init__(self, host, pid: int, name: str, program, args: dict | None = None):
        self.host = host
        self.pid = pid
        self.name = name
        self.fds = DescriptorTable()
        self.state = ProcState.RUNNING
        self.exit_code: int | None = None
        self.stdout: list[bytes] = []
        self.stderr: list[bytes] = []
        self.ctx = ProgramCtx(host.name, host.ip, pid, args or {})
        self._gen: Iterator = program(self.ctx)
        self._send_value: Any = None
        self._current: Syscall | None = None
        self._wake_listener: tuple[File, StatusListener] | None = None
        self._wake_timer: object | None = None
        # strace hook (observability plane): fn(time_ns, pid, name, args, result)
        self.strace: Callable[[int, int, str, tuple, Any], None] | None = None

    # ---- lifecycle ---------------------------------------------------------

    def resume(self):
        """Run until the program blocks or exits (Thread::resume)."""
        CallbackQueue.run(lambda q: self._resume_inner())

    _unblocked_run = 0  # consecutive syscalls completed without blocking

    def _resume_inner(self):
        cfg = self.host.cfg
        while self.state == ProcState.RUNNING:
            if self._current is None:
                self._current = self._advance(self._send_value, None)
                if self._current is None:
                    return
                self._send_value = None
            if (
                cfg.model_unblocked_latency
                and self._unblocked_run >= cfg.unblocked_syscall_limit
            ):
                # charge CPU latency: park, then re-run this same syscall
                self._unblocked_run = 0
                self._block(
                    Blocked(timeout=self.host.now() + cfg.unblocked_syscall_latency_ns)
                )
                return
            try:
                res = self.host.syscalls.execute(self, self._current)
            except OSError as e:
                # errno surfaces in the program as a raised exception; it
                # still counts toward the unblocked-syscall charge (an
                # error-polling retry loop is exactly the busy loop the
                # latency model exists to escape)
                self._unblocked_run += 1
                if self.strace is not None:
                    self.strace(
                        self.host.now(), self.pid, self._current.name,
                        self._current.args, e,
                    )
                self._current = self._advance(None, e)
                continue
            if isinstance(res, Blocked):
                self._unblocked_run = 0
                self._block(res)
                return
            self._unblocked_run += 1
            if self.strace is not None:
                self.strace(
                    self.host.now(), self.pid, self._current.name,
                    self._current.args, res,
                )
            if self._current.name == "exit":
                return
            self._current = None
            self._send_value = res

    def _advance(self, value, exc) -> Syscall | None:
        """Step the generator; returns the next syscall or None if exited."""
        try:
            req = self._gen.throw(exc) if exc is not None else self._gen.send(value)
        except StopIteration:
            self._exit(0)
            return None
        except OSError as e:
            self.stderr.append(f"uncaught: {e!r}\n".encode())
            self._exit(1)
            return None
        except Exception as e:
            self.stderr.append(f"uncaught: {e!r}\n".encode())
            self._exit(1)
            return None
        return Syscall.of(req)

    def _block(self, b: Blocked):
        self.state = ProcState.BLOCKED
        if b.file is not None:
            listener = StatusListener(b.mask, lambda s, c: self._wake(None))
            b.file.add_listener(listener)
            self._wake_listener = (b.file, listener)
        if b.timeout is not None:
            result = b.on_timeout if b.has_timeout_result else None
            self._wake_timer = self.host.schedule(
                b.timeout, lambda: self._wake_timeout(b, result)
            )

    def _clear_wakeups(self):
        if self._wake_listener is not None:
            f, l = self._wake_listener
            f.remove_listener(l)
            self._wake_listener = None
        if self._wake_timer is not None:
            self.host.cancel(self._wake_timer)
            self._wake_timer = None

    def _wake(self, _):
        """Condition fired: re-execute the same syscall (reference re-runs
        the SAME syscall after wakeup, handler/mod.rs + thread.rs)."""
        if self.state != ProcState.BLOCKED:
            return
        self._clear_wakeups()
        self.state = ProcState.RUNNING
        self.host.schedule(self.host.now(), self.resume)

    def _wake_timeout(self, b: Blocked, result):
        if self.state != ProcState.BLOCKED:
            return
        self._wake_timer = None
        self._clear_wakeups()
        self.state = ProcState.RUNNING
        if b.has_timeout_result:
            # timeout substitutes the syscall result instead of re-running
            if self.strace is not None and self._current is not None:
                self.strace(
                    self.host.now(), self.pid, self._current.name,
                    self._current.args, result,
                )
            self._current = None
            self._send_value = result
        self.host.schedule(self.host.now(), self.resume)

    def _exit(self, code: int):
        self.state = ProcState.ZOMBIE
        self.exit_code = code
        self._clear_wakeups()
        self.fds.close_all()
        self.host.on_process_exit(self)

    def kill(self):
        if self.state != ProcState.ZOMBIE:
            self._gen.close()
            self._exit(137)


ManagedProgram = Callable  # a program is just `def prog(ctx): yield ...`


class SyscallHandler:
    """Dispatch table (reference handler/mod.rs:371-539). Each op returns a
    result or `Blocked`. OSError propagates into the program as the raised
    exception (programs may try/except like checking errno)."""

    def __init__(self, host):
        self.host = host

    def execute(self, proc: Process, call: Syscall):
        fn = getattr(self, f"sys_{call.name}", None)
        if fn is None:
            raise OSError(f"ENOSYS: {call.name}")
        self.host.counters["syscalls"] += 1
        return fn(proc, *call.args)

    # ---- time --------------------------------------------------------------

    def sys_clock_gettime(self, proc):
        return self.host.now()

    def sys_gettimeofday(self, proc):
        t = self.host.now()
        return (t // NS_PER_SEC, (t % NS_PER_SEC) // 1000)

    def sys_time(self, proc):
        return self.host.now() // NS_PER_SEC

    def sys_nanosleep(self, proc, duration_ns: int):
        return Blocked(
            timeout=self.host.now() + max(int(duration_ns), 0),
            on_timeout=0,
            has_timeout_result=True,
        )

    # ---- random ------------------------------------------------------------

    def sys_getrandom(self, proc, n: int):
        return bytes(self.host.rng.getrandbits(8) for _ in range(n))

    # ---- stdio -------------------------------------------------------------

    def sys_write_stdout(self, proc, data: bytes):
        proc.stdout.append(bytes(data))
        return len(data)

    def sys_write_stderr(self, proc, data: bytes):
        proc.stderr.append(bytes(data))
        return len(data)

    # ---- descriptors -------------------------------------------------------

    def sys_close(self, proc, fd: int):
        proc.fds.close(fd)
        return 0

    def sys_dup(self, proc, fd: int):
        return proc.fds.dup(fd)

    def sys_dup2(self, proc, old: int, new: int):
        return proc.fds.dup2(old, new)

    def sys_pipe(self, proc):
        r, w = create_pipe()
        return (proc.fds.register(r), proc.fds.register(w))

    def sys_read(self, proc, fd: int, n: int):
        f = proc.fds.get(fd)
        out = f.read(n)
        if out is None:
            return Blocked(file=f, mask=_WAIT_READ)
        return out

    def sys_write(self, proc, fd: int, data: bytes):
        f = proc.fds.get(fd)
        n = f.write(data)
        if n is None:
            return Blocked(file=f, mask=_WAIT_WRITE)
        return n

    def sys_read_nonblock(self, proc, fd: int, n: int):
        return proc.fds.get(fd).read(n)  # None = EAGAIN

    def sys_write_nonblock(self, proc, fd: int, data: bytes):
        return proc.fds.get(fd).write(data)

    # ---- eventfd / timerfd / epoll ----------------------------------------

    def sys_eventfd(self, proc, initval: int = 0, semaphore: bool = False):
        return proc.fds.register(EventFd(initval, semaphore))

    def sys_timerfd_create(self, proc):
        return proc.fds.register(TimerFd(self.host))

    def sys_timerfd_settime(self, proc, fd: int, deadline_ns, interval_ns: int = 0):
        f = proc.fds.get(fd)
        if not isinstance(f, TimerFd):
            raise OSError("EINVAL: not a timerfd")
        return f.settime(deadline_ns, interval_ns)

    def sys_timerfd_gettime(self, proc, fd: int):
        f = proc.fds.get(fd)
        if not isinstance(f, TimerFd):
            raise OSError("EINVAL: not a timerfd")
        return f.gettime()

    def sys_epoll_create(self, proc):
        return proc.fds.register(Epoll())

    def sys_epoll_ctl(self, proc, epfd: int, op: str, fd: int, events: int = 0,
                      data: int | None = None):
        ep = proc.fds.get(epfd)
        if not isinstance(ep, Epoll):
            raise OSError("EINVAL: not an epoll fd")
        if op == "add":
            ep.add(fd, proc.fds.get(fd), events, data)
        elif op == "mod":
            ep.modify(fd, events, data)
        elif op == "del":
            ep.remove(fd)
        else:
            raise OSError(f"EINVAL: epoll op {op!r}")
        return 0

    def sys_epoll_wait(self, proc, epfd: int, max_events: int = 64,
                       timeout_ns: int | None = None):
        ep = proc.fds.get(epfd)
        if not isinstance(ep, Epoll):
            raise OSError("EINVAL: not an epoll fd")
        evs = ep.wait(max_events)
        if evs is not None:
            return [(e.fd, e.events, e.data) for e in evs]
        if timeout_ns == 0:
            return []
        return Blocked(
            file=ep,
            mask=FileState.READABLE,
            timeout=None if timeout_ns is None else self.host.now() + timeout_ns,
            on_timeout=[],
            has_timeout_result=timeout_ns is not None,
        )

    # ---- sockets -----------------------------------------------------------

    def sys_socket(self, proc, kind: str):
        if kind == "udp":
            return proc.fds.register(UdpSocket(self.host.netns))
        if kind == "tcp":
            return proc.fds.register(TcpSocket(self.host.netns))
        if kind == "unix":
            return proc.fds.register(UnixStreamSocket())
        raise OSError(f"EINVAL: socket kind {kind!r}")

    def sys_socketpair(self, proc):
        a, b = UnixStreamSocket.make_pair()
        return (proc.fds.register(a), proc.fds.register(b))

    def sys_bind(self, proc, fd: int, addr):
        f = proc.fds.get(fd)
        if isinstance(f, UnixStreamSocket):
            name = addr if isinstance(addr, str) else addr[0]
            f.bind_abstract(self.host.netns.abstract_unix, name.removeprefix("@"))
            return 0
        f.bind(addr[0], addr[1])
        return 0

    def sys_listen(self, proc, fd: int, backlog: int = 128):
        f = proc.fds.get(fd)
        if isinstance(f, UnixStreamSocket):
            f.listen()
            return 0
        if isinstance(f, TcpListenerSocket):
            return 0
        if not isinstance(f, TcpSocket):
            raise OSError("EOPNOTSUPP: listen on non-TCP socket")
        # rebind the same fd slot as a listener (reference converts the
        # socket's protocol state the same way)
        lst = TcpListenerSocket(self.host.netns, cfg=f.cfg, backlog=backlog)
        lst.local_ip, lst.local_port = f.local_ip, f.local_port
        if lst.local_port is None:
            raise OSError("EINVAL: listen before bind")
        self.host.netns._ports[(lst.PROTO, lst.local_port)] = lst
        for slot_fd in proc.fds.fds():
            if proc.fds.get(slot_fd) is f:
                proc.fds.register_at(slot_fd, lst)
        return 0

    def sys_accept(self, proc, fd: int):
        f = proc.fds.get(fd)
        if isinstance(f, UnixStreamSocket):
            child = f.accept()
            if child is None:
                return Blocked(file=f, mask=_WAIT_ACCEPT)
            return (proc.fds.register(child), ("unix", 0))
        if not isinstance(f, TcpListenerSocket):
            raise OSError("EINVAL: accept on non-listener")
        child = f.accept()
        if child is None:
            return Blocked(file=f, mask=_WAIT_ACCEPT)
        cfd = proc.fds.register(child)
        return (cfd, (child.peer_ip, child.peer_port))

    def sys_connect(self, proc, fd: int, addr):
        f = proc.fds.get(fd)
        if isinstance(f, UnixStreamSocket):
            name = (addr if isinstance(addr, str) else addr[0]).removeprefix("@")
            listener = self.host.netns.abstract_unix.get(name)
            if listener is None:
                raise ConnectionRefusedError(f"ECONNREFUSED: @{name}")
            f.connect_to(listener)
            return 0
        if isinstance(f, UdpSocket):
            f.connect(addr[0], addr[1])
            return 0
        if not isinstance(f, TcpSocket):
            raise OSError("EINVAL")
        from shadow_tpu.tcp import State as TS

        if f.tcp.state == TS.ESTABLISHED:
            return 0
        if f.tcp.error is not None:
            raise ConnectionRefusedError(f.tcp.error.value)
        if f.tcp.state == TS.CLOSED and f.peer_ip is None:
            f.connect(addr[0], addr[1])
        return Blocked(file=f, mask=_WAIT_WRITE)

    def sys_sendto(self, proc, fd: int, data: bytes, addr: tuple | None = None):
        f = proc.fds.get(fd)
        if isinstance(f, UdpSocket):
            return f.sendto(data, addr)
        return self.sys_write(proc, fd, data)

    def sys_recvfrom(self, proc, fd: int, n: int):
        f = proc.fds.get(fd)
        if isinstance(f, UdpSocket):
            r = f.recvfrom(n)
            if r is None:
                return Blocked(file=f, mask=_WAIT_READ)
            return r
        data = f.read(n)
        if data is None:
            return Blocked(file=f, mask=_WAIT_READ)
        return (data, (f.peer_ip, f.peer_port))

    sys_send = sys_write
    sys_recv = sys_read

    def sys_shutdown(self, proc, fd: int):
        f = proc.fds.get(fd)
        if isinstance(f, UnixStreamSocket):
            if not f.connected:
                raise OSError("ENOTCONN")
            f.shutdown_write()
            return 0
        if not isinstance(f, TcpSocket):
            raise OSError("ENOTSOCK")
        f.shutdown_write()
        return 0

    def sys_getsockname(self, proc, fd: int):
        f = proc.fds.get(fd)
        return (f.local_ip, f.local_port)

    def sys_getpeername(self, proc, fd: int):
        f = proc.fds.get(fd)
        if isinstance(f, UnixStreamSocket):
            if not f.connected:
                raise OSError("ENOTCONN")
            return ("unix", 0)
        if f.peer_ip is None:
            raise OSError("ENOTCONN")
        return (f.peer_ip, f.peer_port)

    def sys_gethostname(self, proc):
        return self.host.name

    def sys_resolve(self, proc, name: str):
        """shadow_hostname_to_addr_ipv4 equivalent (handler/mod.rs:513-517)."""
        return self.host.resolve(name)

    # ---- process -----------------------------------------------------------

    def sys_getpid(self, proc):
        return proc.pid

    def sys_exit(self, proc, code: int = 0):
        proc._exit(int(code))
        return code
