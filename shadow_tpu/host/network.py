"""The CPU wire: latency/loss fabric + conservative round loop for CpuHosts.

Reference: this is the host-plane counterpart of `Worker::send_packet`
(worker.rs:330-425 — latency lookup, loss draw from the *source* host RNG,
cross-host event push) plus the Manager round loop (manager.rs:392-478) in
miniature. The device engine implements the same contract on TPU; this
fabric exists so emulated hosts can also run self-contained (and as the
oracle for dual-target tests, SURVEY.md §4.8).
"""

from __future__ import annotations

from typing import Callable

from shadow_tpu.host.host import CpuHost, TIME_MAX
from shadow_tpu.host.sockets import NetPacket


class CpuNetwork:
    def __init__(
        self,
        hosts: list[CpuHost],
        latency_ns: Callable[[int, int], int],
        loss: Callable[[int, int], float] | None = None,
        names: dict[str, str] | None = None,
    ):
        self.hosts = hosts
        self.by_ip = {h.ip: h for h in hosts}
        self.latency_ns = latency_ns
        self.loss = loss or (lambda s, d: 0.0)
        self.min_latency = (
            min(
                latency_ns(a.host_id, b.host_id)
                for a in hosts
                for b in hosts
                if a is not b
            )
            if len(hosts) > 1
            else 1_000_000
        )
        names = names or {h.name: h.ip for h in hosts}
        for h in hosts:
            h.egress = self._egress
            h.resolver = names.get
        self.pkts_dropped = 0
        self.pkts_relayed = 0

    def _egress(self, src: CpuHost, pkt: NetPacket):
        dst = self.by_ip.get(pkt.dst_ip)
        if dst is None:
            return  # unreachable: dropped (reference counts + drops too)
        lat = self.latency_ns(src.host_id, dst.host_id)
        p = self.loss(src.host_id, dst.host_id)
        # loss drawn from the source host's RNG (worker.rs:374-390)
        if p > 0.0 and src.rng.random() < p:
            self.pkts_dropped += 1
            return
        self.pkts_relayed += 1
        dst.schedule(src.now() + lat, lambda: dst.deliver_packet(pkt))

    # ---- conservative round loop ------------------------------------------

    def run(self, stop_ns: int, *, runahead_ns: int | None = None) -> int:
        """Advance all hosts to stop_ns in lookahead-bounded rounds.
        Returns the number of rounds executed."""
        runahead = max(runahead_ns or self.min_latency, 1)
        rounds = 0
        while True:
            nxt = min(h.next_event_time() for h in self.hosts)
            if nxt >= stop_ns:
                break
            window_end = min(nxt + runahead, stop_ns)
            for h in self.hosts:  # deterministic host order
                h.execute(window_end)
            rounds += 1
        for h in self.hosts:
            h.execute(stop_ns)
        return rounds
