"""The CPU wire: latency/loss fabric + conservative round loop for CpuHosts.

Reference: this is the host-plane counterpart of `Worker::send_packet`
(worker.rs:330-425 — latency lookup, loss draw from the *source* host RNG,
cross-host event push) plus the Manager round loop (manager.rs:392-478) in
miniature. The device engine implements the same contract on TPU; this
fabric exists so emulated hosts can also run self-contained (and as the
oracle for dual-target tests, SURVEY.md §4.8).
"""

from __future__ import annotations

from typing import Callable

from shadow_tpu.host.host import CpuHost, TIME_MAX
from shadow_tpu.host.sockets import NetPacket


class CpuNetwork:
    def __init__(
        self,
        hosts: list[CpuHost],
        latency_ns: Callable[[int, int], int],
        loss: Callable[[int, int], float] | None = None,
        names: dict[str, str] | None = None,
        workers: int = 1,
        scheduler: str = "steal",  # "steal" | "per-host" (thread_per_host.rs)
        pin_cpus: list[int] | None = None,
    ):
        self.hosts = hosts
        self.by_ip = {h.ip: h for h in hosts}
        self.latency_ns = latency_ns
        self.loss = loss or (lambda s, d: 0.0)
        self.min_latency = (
            min(
                latency_ns(a.host_id, b.host_id)
                for a in hosts
                for b in hosts
                if a is not b
            )
            if len(hosts) > 1
            else 1_000_000
        )
        names = names or {h.name: h.ip for h in hosts}
        rev = {ip: name for name, ip in names.items()}
        for h in hosts:
            h.egress = self._egress
            h.resolver = names.get
            h.rev_resolver = rev.get
        # parallel host execution (reference thread_per_core.rs:25-210):
        # hosts share nothing inside a window, so N pool threads can run
        # them concurrently. Cross-host deliveries are STAGED per source and
        # merged after the window in host-id order — conservative lookahead
        # guarantees every arrival lands >= window_end, so staging changes
        # nothing observable and keeps the merge order deterministic.
        # (CPython's GIL serializes pure-Python hosts; the win is native
        # hosts, whose service loops block in futex waits outside the GIL.)
        self.workers = max(1, workers)
        self._staged: list[list] = [[] for _ in hosts]
        self._pool = None
        if scheduler not in ("steal", "per-host"):
            raise ValueError(
                f"scheduler must be steal|per-host, got {scheduler!r}"
            )
        if self.workers > 1 or scheduler == "per-host":
            from shadow_tpu.host.scheduler import make_pool

            self._pool = make_pool(scheduler, self.workers, pin_cpus)
        # per-source counters summed on read: parallel sources must not race
        # on shared ints
        self._dropped = [0] * len(hosts)
        self._relayed = [0] * len(hosts)

    @property
    def pkts_dropped(self) -> int:
        return sum(self._dropped)

    @property
    def pkts_relayed(self) -> int:
        return sum(self._relayed)

    def _egress(self, src: CpuHost, pkt: NetPacket):
        dst = self.by_ip.get(pkt.dst_ip)
        if dst is None:
            # unreachable: dropped (reference counts + drops too)
            src.drop_packet(pkt, "inet_no_route")
            return
        lat = self.latency_ns(src.host_id, dst.host_id)
        p = self.loss(src.host_id, dst.host_id)
        # loss drawn from the source host's RNG (worker.rs:374-390)
        if p > 0.0 and src.rng.random() < p:
            self._dropped[src.host_id] += 1
            src.drop_packet(pkt, "inet_loss_draw")
            return
        self._relayed[src.host_id] += 1
        pkt.crumb(src.now(), "inet_relayed")
        self._staged[src.host_id].append((src.now() + lat, dst, pkt))

    def _flush_staged(self):
        """Deliver staged packets in source-host-id order (the reference
        pushes into each dst's mutex'd queue; here the post-window merge
        IS the deterministic ordering point, worker.rs:644-654)."""
        for buf in self._staged:
            for t, dst, pkt in buf:
                dst.schedule(t, _mk_delivery(dst, pkt))
            buf.clear()

    def _execute_all(self, until: int):
        if self._pool is not None:
            # run() joins: every host finishes before the staged merge
            self._pool.run(self.hosts, lambda h: h.execute(until))
        else:
            for h in self.hosts:  # deterministic host order
                h.execute(until)
        self._flush_staged()

    # ---- conservative round loop ------------------------------------------

    def run(self, stop_ns: int, *, runahead_ns: int | None = None) -> int:
        """Advance all hosts to stop_ns in lookahead-bounded rounds.
        Returns the number of rounds executed."""
        runahead = max(runahead_ns or self.min_latency, 1)
        rounds = 0
        while True:
            nxt = min(h.next_event_time() for h in self.hosts)
            if nxt >= stop_ns:
                break
            window_end = min(nxt + runahead, stop_ns)
            self._execute_all(window_end)
            rounds += 1
        self._execute_all(stop_ns)
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        return rounds


def _mk_delivery(dst: CpuHost, pkt: NetPacket):
    return lambda: dst.deliver_packet(pkt)
