"""Unix-domain stream sockets + socketpair.

Reference: `host/descriptor/socket/unix/` (2419 LoC — connection-oriented
unix sockets over shared buffers, plus the abstract-name namespace in
`socket/abstract_unix_ns.rs`). A connected unix stream socket is a crossed
pair of bounded byte buffers — the generic `StreamEnd` from
`host/pipe.py` provides the whole stream I/O surface; this module adds
connection setup (pair/bind/listen/connect/accept) and the abstract
namespace.
"""

from __future__ import annotations

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState
from shadow_tpu.host.pipe import StreamEnd, _SharedBuf

UNIX_BUF = 212992  # Linux default unix-socket buffer


def _drop_ref(obj):
    """Refcounted release of an in-flight SCM_RIGHTS object (mirrors the
    native plane's _drop_vfd: fork-shared descriptors die with their last
    holder; an unclaimed passed fd is one dropped reference)."""
    refs = getattr(obj, "_nrefs", 1)
    if refs > 1:
        obj._nrefs = refs - 1
    else:
        obj.close()


class UnixStreamSocket(StreamEnd):
    """One end of a connected unix stream pair (or a listener)."""

    def __init__(self):
        super().__init__()
        # listener state (bound to an abstract name)
        self.listening = False
        self.bound_name: str | None = None
        self.peer_name: str | None = None  # the address connect()ed to
        self._accept_q: list["UnixStreamSocket"] = []
        self._ns: dict | None = None  # abstract namespace (host-owned)
        # SCM_RIGHTS in transit to THIS end (reference socket/unix.rs
        # ancillary support): one entry per sendmsg that carried fds.
        # Divergence from the kernel: entries are not pinned to byte
        # positions in the stream — a recvmsg claims the oldest entry.
        self.anc_rx: list[list] = []

    @property
    def connected(self) -> bool:
        return self._rx is not None or self._tx is not None

    # ---- connection setup --------------------------------------------------

    @staticmethod
    def make_pair() -> tuple["UnixStreamSocket", "UnixStreamSocket"]:
        """socketpair(2): two connected ends."""
        a, b = UnixStreamSocket(), UnixStreamSocket()
        ab, ba = _SharedBuf(UNIX_BUF), _SharedBuf(UNIX_BUF)
        for buf in (ab, ba):
            buf.readers = buf.writers = 1
        a._tx, a._rx = ab, ba
        b._tx, b._rx = ba, ab
        a.peer, b.peer = b, a
        a._set_state(on=FileState.WRITABLE)
        b._set_state(on=FileState.WRITABLE)
        return a, b

    def bind_abstract(self, ns: dict, name: str):
        if name in ns:
            raise OSError(f"EADDRINUSE: @{name}")
        ns[name] = self
        self._ns = ns
        self.bound_name = name

    def listen(self):
        if self.bound_name is None:
            raise OSError("EINVAL: listen before bind")
        self.listening = True

    def connect_to(self, listener: "UnixStreamSocket") -> None:
        """Connect to a listening socket: forks a server-side end into the
        listener's accept queue (unix connects are immediate — no network
        latency — same as the reference)."""
        if self.connected:
            raise OSError("EISCONN: already connected")
        if not listener.listening:
            raise OSError("ECONNREFUSED")
        server_end, client_end = UnixStreamSocket.make_pair()
        # graft the client_end's plumbing into *this* socket
        self._tx, self._rx = client_end._tx, client_end._rx
        self.peer = server_end
        server_end.peer = self
        # getpeername: the client's peer is the LISTENER's address; the
        # accepted server end's peer (this client) is unnamed
        self.peer_name = listener.bound_name
        server_end.bound_name = listener.bound_name
        self._set_state(on=FileState.WRITABLE)
        listener._accept_q.append(server_end)
        listener._set_state(on=FileState.ACCEPTABLE | FileState.READABLE)

    def accept(self) -> "UnixStreamSocket | None":
        if not self._accept_q:
            return None
        child = self._accept_q.pop(0)
        if not self._accept_q:
            self._set_state(off=FileState.ACCEPTABLE | FileState.READABLE)
        return child

    # ---- I/O: StreamEnd provides read/write/shutdown_write/_sync ----------

    def read(self, n: int):
        if not self.connected and not self.listening:
            raise OSError("ENOTCONN")
        return super().read(n)

    def write(self, data: bytes):
        if not self.connected:
            raise OSError("ENOTCONN")
        return super().write(data)

    def close(self):
        if self.closed:
            return
        if self.bound_name is not None and self._ns is not None:
            self._ns.pop(self.bound_name, None)
        for child in self._accept_q:
            child.close()
        self._accept_q.clear()
        for ent in self.anc_rx:  # unclaimed passed fds die with the socket
            for obj in ent:
                _drop_ref(obj)
        self.anc_rx.clear()
        super().close()


UNIX_DGRAM_QUEUE = 512  # datagrams buffered per receiving socket


class UnixDgramSocket(File):
    """Unix-domain DATAGRAM socket: message boundaries preserved, sendto by
    bound name or connected peer (glibc syslog()'s /dev/log transport;
    reference socket/unix.rs dgram support). Delivery is immediate and
    reliable within a host; a full receive queue rejects the send with
    ENOBUFS (the kernel blocks or drops depending on flags — rejecting
    loudly keeps the plane deterministic)."""

    def __init__(self):
        super().__init__()
        self.bound_name: str | None = None
        self.peer_name: str | None = None
        self._ns: dict | None = None
        # (src name or "", data, SCM_RIGHTS objects or None) — rights ride
        # WITH their datagram (kernel semantics for dgram ancillary)
        self._rcv: list[tuple[str, bytes, list | None]] = []
        self._pending_rights: list | None = None  # set by sendmsg
        self.last_rights: list | None = None  # popped with the last recv
        self._set_state(on=FileState.WRITABLE)

    @staticmethod
    def make_pair() -> tuple["UnixDgramSocket", "UnixDgramSocket"]:
        a, b = UnixDgramSocket(), UnixDgramSocket()
        a.peer, b.peer = b, a
        return a, b

    peer: "UnixDgramSocket | None" = None

    def bind_abstract(self, ns: dict, name: str):
        if name in ns:
            raise OSError(f"EADDRINUSE: @{name}")
        ns[name] = self
        self._ns = ns
        self.bound_name = name

    def connect_name(self, ns: dict, name: str):
        if name not in ns or not isinstance(ns[name], UnixDgramSocket):
            raise OSError("ECONNREFUSED")
        self.peer_name = name
        self._ns = ns if self._ns is None else self._ns

    def _deliver(self, src_name: str, data: bytes,
                 rights: list | None = None) -> None:
        if len(self._rcv) >= UNIX_DGRAM_QUEUE:
            if rights:
                for obj in rights:
                    _drop_ref(obj)
            raise OSError("ENOBUFS: receive queue full")
        self._rcv.append((src_name, data, rights))
        self._set_state(on=FileState.READABLE)

    def send_to(self, ns: dict, name: str | None, data: bytes) -> int:
        """sendto: explicit name wins; otherwise the connected peer (by
        name) or the socketpair peer object."""
        rights, self._pending_rights = self._pending_rights, None
        target = None
        if name is not None:
            target = ns.get(name)
        elif self.peer_name is not None:
            target = ns.get(self.peer_name)
        elif self.peer is not None and not self.peer.closed:
            target = self.peer
        if not isinstance(target, UnixDgramSocket) or target.closed:
            if rights:
                for obj in rights:
                    _drop_ref(obj)
            raise OSError("ECONNREFUSED")
        target._deliver(self.bound_name or "", bytes(data), rights)
        return len(data)

    def recv_from(self, n: int) -> tuple[bytes, str] | None:
        if not self._rcv:
            return None
        src, data, rights = self._rcv.pop(0)
        if self.last_rights:  # previous receive's rights went unclaimed
            for obj in self.last_rights:
                _drop_ref(obj)
        self.last_rights = rights
        if not self._rcv:
            self._set_state(off=FileState.READABLE)
        return data[:n], src  # short buffer truncates, like SOCK_DGRAM

    def claim_rights(self) -> list | None:
        """recvmsg collects the rights attached to the datagram just
        popped; any other receive path leaves them to be dropped on the
        next pop (read(2)/recvfrom(2) discard ancillary, like the kernel)."""
        r, self.last_rights = self.last_rights, None
        return r

    def read(self, n: int) -> bytes | None:
        r = self.recv_from(n)
        return None if r is None else r[0]

    def peek(self, n: int) -> bytes | None:
        if not self._rcv:
            return None
        return self._rcv[0][1][:n]

    def write(self, data: bytes) -> int:
        return self.send_to(self._ns or {}, None, data)

    def close(self):
        if self.bound_name is not None and self._ns is not None:
            self._ns.pop(self.bound_name, None)
        for _, _, rights in self._rcv:
            if rights:
                for obj in rights:
                    _drop_ref(obj)
        self._rcv.clear()
        if self.last_rights:
            for obj in self.last_rights:
                _drop_ref(obj)
            self.last_rights = None
        super().close()
