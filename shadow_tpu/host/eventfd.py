"""eventfd(2) emulation (reference `host/descriptor/eventfd.rs`, 281 LoC)."""

from __future__ import annotations

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState

_MAX = (1 << 64) - 1


class EventFd(File):
    def __init__(self, initval: int = 0, semaphore: bool = False):
        super().__init__()
        self.count = initval
        self.semaphore = semaphore
        self._sync()

    def _sync(self):
        on = FileState.NONE
        off = FileState.NONE
        if self.count > 0:
            on |= FileState.READABLE
        else:
            off |= FileState.READABLE
        if self.count < _MAX - 1:
            on |= FileState.WRITABLE
        else:
            off |= FileState.WRITABLE
        self._set_state(on=on, off=off)

    def read(self, n: int) -> bytes | None:
        if n < 8:
            raise OSError("EINVAL: eventfd reads need 8 bytes")
        if self.count == 0:
            return None  # would block
        val = 1 if self.semaphore else self.count
        self.count -= val
        # pulse WRITABLE so a writer blocked on an overflowing add (whose
        # write would now fit) sees a transition and retries — the bit alone
        # can stay set across the whole episode
        self._set_state(off=FileState.WRITABLE)
        self._sync()
        return val.to_bytes(8, "little")

    def write(self, data: bytes) -> int | None:
        if len(data) < 8:
            raise OSError("EINVAL: eventfd writes need 8 bytes")
        add = int.from_bytes(data[:8], "little")
        if add == _MAX:
            raise OSError("EINVAL")
        if self.count + add > _MAX - 1:
            return None  # would block until read
        self.count += add
        self._sync()
        return 8
