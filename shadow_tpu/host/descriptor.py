"""File objects and the per-process descriptor table.

Reference: `host/descriptor/mod.rs` (File enum + state), `descriptor.c`,
and `descriptor_table.rs` (fd allocation, dup, close-on-exec). Files here
are plain Python objects with a state bitmask and listener list; every
state mutation goes through `_set_state` which defers notifications via the
active `CallbackQueue`.
"""

from __future__ import annotations

from shadow_tpu.host.filestate import CallbackQueue, FileState, StatusListener


class File:
    """Base of everything a descriptor can point at."""

    def __init__(self):
        self.state = FileState.ACTIVE
        self._listeners: list[StatusListener] = []

    # ---- state & listeners -------------------------------------------------

    def add_listener(self, listener: StatusListener):
        self._listeners.append(listener)
        if listener.level and listener.wants(self.state, FileState.NONE):
            q = CallbackQueue.current()
            st = self.state
            if q is not None:
                q.push(lambda: listener.callback(st, FileState.NONE))
            else:
                listener.callback(st, FileState.NONE)

    def remove_listener(self, listener: StatusListener):
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def _set_state(self, on: FileState = FileState.NONE, off: FileState = FileState.NONE):
        new = (self.state | on) & ~off
        changed = new ^ self.state
        if not changed:
            return
        self.state = new
        snapshot = list(self._listeners)
        q = CallbackQueue.current()
        for lst in snapshot:
            if lst.wants(new, FileState(changed)):
                if q is not None:
                    q.push(
                        lambda l=lst, s=new, c=FileState(changed): l.callback(s, c)
                    )
                else:
                    lst.callback(new, FileState(changed))

    # ---- lifecycle ---------------------------------------------------------

    def close(self):
        if self.state & FileState.CLOSED:
            return
        self._set_state(on=FileState.CLOSED, off=FileState.ACTIVE)

    @property
    def closed(self) -> bool:
        return bool(self.state & FileState.CLOSED)

    # default I/O surface: subclasses override what they support
    def read(self, n: int) -> bytes | None:  # None = would block
        raise OSError("not readable")

    def write(self, data: bytes) -> int | None:
        raise OSError("not writable")


class Descriptor:
    """An fd-table slot: file reference + per-descriptor flags (CLOEXEC)."""

    def __init__(self, file: File, cloexec: bool = False):
        self.file = file
        self.cloexec = cloexec


class DescriptorTable:
    """Per-process fd table (reference descriptor_table.rs: lowest-free fd
    allocation, dup to explicit slots, bulk close on exit)."""

    def __init__(self, max_fds: int = 1024):
        self.max_fds = max_fds
        self._slots: dict[int, Descriptor] = {}
        self._next_probe = 0

    def register(self, file: File, *, min_fd: int = 0) -> int:
        fd = min_fd
        while fd in self._slots:
            fd += 1
        if fd >= self.max_fds:
            raise OSError("EMFILE: descriptor table full")
        self._slots[fd] = Descriptor(file)
        return fd

    def register_at(self, fd: int, file: File):
        if fd < 0 or fd >= self.max_fds:
            raise OSError("EBADF: fd out of range")
        self._slots[fd] = Descriptor(file)

    def get(self, fd: int) -> File:
        d = self._slots.get(fd)
        if d is None:
            raise OSError(f"EBADF: fd {fd} not open")
        return d.file

    def dup(self, fd: int, min_fd: int = 0) -> int:
        file = self.get(fd)
        return self.register(file, min_fd=min_fd)

    def dup2(self, old: int, new: int) -> int:
        file = self.get(old)
        if old == new:
            return new
        if new in self._slots:
            self.close(new)
        self.register_at(new, file)
        return new

    def close(self, fd: int):
        d = self._slots.pop(fd, None)
        if d is None:
            raise OSError(f"EBADF: fd {fd} not open")
        # last reference in this table closes the file if no other slot holds it
        if not any(s.file is d.file for s in self._slots.values()):
            d.file.close()

    def close_all(self):
        for fd in sorted(self._slots):
            try:
                self.close(fd)
            except OSError:
                pass

    def fds(self) -> list[int]:
        return sorted(self._slots)
