"""CpuHost: the emulated machine and its event loop.

Reference: `host/host.rs` (1452 LoC) — per-host event queue, deterministic
per-host RNG, boot/shutdown, `execute(until)` popping events in
deterministic order, and packet ingress/egress hooks. This host runs
coroutine processes (`shadow_tpu.host.process`) instead of co-opted Linux
binaries; the C++ managed-process plane (`native/`) plugs real binaries
into the same structure.

Egress: `send_packet` hands loopback traffic straight back to this host
(scheduled, never re-entrant) and everything else to `self.egress`, wired
by the CPU wire (`host.network.CpuNetwork`) or the device bridge
(`shadow_tpu.cosim`).
"""

from __future__ import annotations

import heapq
import random
from dataclasses import dataclass, field
from typing import Any, Callable

from shadow_tpu.host.filestate import CallbackQueue
from shadow_tpu.host.netns import NetworkNamespace
from shadow_tpu.host.process import Process, SyscallHandler
from shadow_tpu.host.sockets import NetPacket

TIME_MAX = (1 << 63) - 1


@dataclass
class HostConfig:
    name: str
    ip: str
    seed: int = 0
    host_id: int = 0
    loopback_latency_ns: int = 0  # loopback relays same-round in reference
    # unblocked-syscall CPU-latency model (reference handler/mod.rs:268-318 +
    # `model_unblocked_syscall_latency`): after `unblocked_syscall_limit`
    # consecutive non-blocking syscalls a process is charged
    # `unblocked_syscall_latency_ns` of simulated time, so busy-loops that
    # poll without blocking cannot freeze the simulated clock
    model_unblocked_latency: bool = False
    unblocked_syscall_limit: int = 1024
    unblocked_syscall_latency_ns: int = 1_000
    # per-host TCP socket defaults (reference HostDefaultOptions socket
    # buffer/autotune knobs); None = TcpConfig() defaults
    tcp: Any = None
    # packet delivery-status breadcrumbs (reference packet.rs:16-39),
    # debug-only: every wire hop stamps the packet; drops are collected
    # with their full trail in `packet_drops`
    breadcrumbs: bool = False


class CpuHost:
    def __init__(self, cfg: HostConfig):
        self.cfg = cfg
        self.name = cfg.name
        self.ip = cfg.ip
        self.host_id = cfg.host_id
        self._now = 0
        self._seq = 0  # deterministic tiebreak (host.rs event ids)
        self._q: list[tuple[int, int, Callable]] = []
        self._cancelled: set[int] = set()
        self.rng = random.Random((cfg.seed << 16) ^ cfg.host_id)
        self.netns = NetworkNamespace(self, cfg.ip)
        self.syscalls = SyscallHandler(self)
        self.processes: dict[int, Process] = {}
        self._next_pid = 1000
        # wired by the network layer: fn(host, NetPacket)
        self.egress: Callable[["CpuHost", NetPacket], None] | None = None
        # pcap capture per interface (reference lo.pcap/eth0.pcap,
        # pcap_writer.rs + network_interface.c); set by the sim driver
        self.pcap_lo = None
        self.pcap_eth = None
        # name -> ip resolution (DNS); wired by the simulation driver
        self.resolver: Callable[[str], str] | None = None
        # ip -> name reverse resolution (gethostbyaddr/getnameinfo)
        self.rev_resolver: Callable[[str], str | None] | None = None
        # counters (tracker.c analogue)
        self.counters = {
            "events": 0,
            "pkts_sent": 0,
            "pkts_recv": 0,
            "bytes_sent": 0,
            "bytes_recv": 0,
            "syscalls": 0,
            # of which answered inside the shim from the descriptor fast
            # table (native_plane._fast_drain folds them back in)
            "syscalls_fast": 0,
        }
        # per-interface + per-socket byte/packet counters
        # (tracker.c:24-80 — the reference tracker reports both per
        # heartbeat interval; sockets are attributed by (proto, port)
        # lookup at send/deliver time, closed sockets keep their totals)
        self.if_counters = {
            name: {"tx_pkts": 0, "tx_bytes": 0, "rx_pkts": 0, "rx_bytes": 0}
            for name in ("lo", "eth0")
        }
        self.closed_socket_stats: list[dict] = []
        self.heartbeats: list[dict] = []
        # breadcrumb drop log (bounded; debug flag HostConfig.breadcrumbs)
        self.packet_drops: list[dict] = []
        self._hb_prev: dict | None = None
        self._hb_closed_seen: set[int] = set()

    # ---- tracker heartbeats (tracker.c:24-80) ------------------------------

    def socket_stats(self) -> list[dict]:
        """Per-socket cumulative tx/rx counters, live + closed."""
        out = list(self.closed_socket_stats)
        for sock in self.netns.live_sockets():
            out.append(sock.stat_record())
        return out

    def record_heartbeat(self, t_ns: int) -> dict:
        """Snapshot per-interface and per-socket counters as DELTAS since
        the previous heartbeat (the reference tracker logs per-interval
        numbers, not cumulative ones). A closed socket appears in exactly
        ONE interval record (its final delta) and is then excluded from
        the baseline — otherwise long many-connection runs would re-scan
        every socket ever closed on each heartbeat."""
        live = {
            s["id"]: s
            for s in (sk.stat_record() for sk in self.netns.live_sockets())
        }
        closed_new = {
            s["id"]: s
            for s in self.closed_socket_stats
            if s["id"] not in self._hb_closed_seen
        }
        cur = {
            "interfaces": {k: dict(v) for k, v in self.if_counters.items()},
            "sockets": {**closed_new, **live},
        }
        prev = self._hb_prev or {"interfaces": {}, "sockets": {}}

        def delta(now_d, prev_d):
            return {
                k: now_d[k] - prev_d.get(k, 0)
                for k in ("tx_pkts", "tx_bytes", "rx_pkts", "rx_bytes")
            }

        rec = {
            "t_ns": t_ns,
            "interfaces": {
                k: delta(v, prev["interfaces"].get(k, {}))
                for k, v in cur["interfaces"].items()
            },
            "sockets": [
                {**{f: s[f] for f in ("id", "proto", "local", "peer")},
                 **delta(s, prev["sockets"].get(s["id"], {}))}
                for s in cur["sockets"].values()
            ],
        }
        # drop all-zero socket rows: long-lived idle sockets would bloat
        # every interval record
        rec["sockets"] = [
            s for s in rec["sockets"]
            if s["tx_pkts"] or s["rx_pkts"] or s["tx_bytes"] or s["rx_bytes"]
        ]
        self._hb_closed_seen.update(closed_new)
        # baseline keeps only LIVE sockets: closed ones were just reported
        # for the last time and can never change again
        self._hb_prev = {"interfaces": cur["interfaces"], "sockets": live}
        self.heartbeats.append(rec)
        return rec

    # ---- clock & scheduling (TimerFd Scheduler protocol) -------------------

    def now(self) -> int:
        return self._now

    def schedule(self, t_ns: int, fn: Callable) -> object:
        if t_ns < self._now:
            t_ns = self._now
        self._seq += 1
        token = (t_ns, self._seq)
        heapq.heappush(self._q, (t_ns, self._seq, fn))
        return token

    def cancel(self, token: object):
        self._cancelled.add(token[1])

    def next_event_time(self) -> int:
        while self._q and self._q[0][1] in self._cancelled:
            self._cancelled.discard(self._q[0][1])
            heapq.heappop(self._q)
        return self._q[0][0] if self._q else TIME_MAX

    # ---- processes ---------------------------------------------------------

    def spawn(self, program, name: str | None = None, args: dict | None = None,
              start_time: int = 0) -> Process:
        self._next_pid += 1
        proc = Process(self, self._next_pid, name or program.__name__, program, args)
        self.processes[proc.pid] = proc
        self.schedule(max(start_time, self._now), proc.resume)
        return proc

    def on_process_exit(self, proc: Process):
        pass  # hook for the simulation driver (expected_final_state checks)

    def resolve(self, name: str) -> str:
        if self.resolver is None:
            raise OSError(f"EAI_NONAME: no resolver for {name!r}")
        return self.resolver(name)

    def rev_resolve(self, ip: str) -> str | None:
        """IPv4 -> simulated hostname (reverse DNS); the host always knows
        itself and loopback even without a wired registry."""
        if self.rev_resolver is not None:
            name = self.rev_resolver(ip)
            if name is not None:
                return name
        if ip == self.ip:
            return self.name
        if ip == "127.0.0.1":
            return "localhost"
        return None

    def next_iss(self) -> int:
        return self.rng.getrandbits(32)

    # ---- packets -----------------------------------------------------------

    def drop_packet(self, pkt: NetPacket, status: str):
        """Terminal breadcrumb: record WHERE the packet died (bounded so a
        pathological workload cannot eat the heap)."""
        pkt.crumb(self._now, status)
        if pkt.trail is not None and len(self.packet_drops) < 10_000:
            self.packet_drops.append(
                {
                    "t_ns": self._now,
                    "src": f"{pkt.src_ip}:{pkt.src_port}",
                    "dst": f"{pkt.dst_ip}:{pkt.dst_port}",
                    "proto": pkt.proto,
                    "dropped_at": status,
                    "trail": list(pkt.trail),
                }
            )

    def send_packet(self, pkt: NetPacket):
        if self.cfg.breadcrumbs and pkt.trail is None:
            pkt.trail = []
        self.counters["pkts_sent"] += 1
        self.counters["bytes_sent"] += pkt.size_bytes
        iface = "lo" if pkt.dst_ip in ("127.0.0.1", self.ip) else "eth0"
        ifc = self.if_counters[iface]
        ifc["tx_pkts"] += 1
        ifc["tx_bytes"] += pkt.size_bytes
        sock = self.netns.socket_for_local(pkt.proto, pkt.src_port,
                                           pkt.dst_ip, pkt.dst_port)
        if sock is not None:
            sock.stat["tx_pkts"] += 1
            sock.stat["tx_bytes"] += pkt.size_bytes
        if pkt.trail is not None:  # guard: no f-string on the hot path
            pkt.crumb(self._now, f"snd_{self.name}_{iface}")
        if pkt.dst_ip in ("127.0.0.1", self.ip):
            if self.pcap_lo is not None:
                self.pcap_lo.write(self._now, pkt)
            self.schedule(
                self._now + self.cfg.loopback_latency_ns,
                lambda: self.deliver_packet(pkt, iface="lo"),
            )
            return
        if self.pcap_eth is not None:
            self.pcap_eth.write(self._now, pkt)
        if self.egress is None:
            raise RuntimeError(f"host {self.name}: no egress wired for {pkt}")
        self.egress(self, pkt)

    def deliver_packet(self, pkt: NetPacket, iface: str = "eth"):
        """`iface` is set by the delivery path (loopback tags itself "lo"),
        not re-derived from headers — a socket bound to 127.0.0.1 must never
        show up on the eth0 capture."""
        self.counters["pkts_recv"] += 1
        self.counters["bytes_recv"] += pkt.size_bytes
        if pkt.trail is not None:  # guard: no f-string on the hot path
            pkt.crumb(self._now, f"rcv_{self.name}_{iface}")
        ifc = self.if_counters["lo" if iface == "lo" else "eth0"]
        ifc["rx_pkts"] += 1
        ifc["rx_bytes"] += pkt.size_bytes
        sock = self.netns.socket_for_local(pkt.proto, pkt.dst_port,
                                           pkt.src_ip, pkt.src_port)
        if sock is not None:
            sock.stat["rx_pkts"] += 1
            sock.stat["rx_bytes"] += pkt.size_bytes
        if iface == "eth" and self.pcap_eth is not None:
            self.pcap_eth.write(self._now, pkt)
        CallbackQueue.run(lambda q: self.netns.deliver(pkt))

    # ---- the event loop ----------------------------------------------------

    def execute(self, until_ns: int):
        """Run all events with t < until_ns (Host::execute, host.rs:809)."""
        while True:
            t = self.next_event_time()
            if t >= until_ns:
                break
            _, seq, fn = heapq.heappop(self._q)
            if seq in self._cancelled:
                self._cancelled.discard(seq)
                continue
            self._now = t
            self.counters["events"] += 1
            fn()
        self._now = max(self._now, min(until_ns, TIME_MAX))

    def shutdown(self):
        for proc in list(self.processes.values()):
            proc.kill()
