"""File status bits, listeners, and the deferred callback queue.

Reference: `FileState` bitflags (`host/descriptor/mod.rs:111-140`),
`StatusListener` (`host/descriptor/listener.rs`), and `CallbackQueue`
(`utility/callback_queue.rs`) — the mechanism that breaks borrow cycles by
deferring "state changed" notifications until the triggering operation has
fully unwound. Here the queue plays the same role for Python re-entrancy:
listener callbacks never run inside the mutation that caused them.
"""

from __future__ import annotations

import enum
from typing import Callable


class FileState(enum.IntFlag):
    NONE = 0
    ACTIVE = 1 << 0  # open and usable
    READABLE = 1 << 1
    WRITABLE = 1 << 2
    CLOSED = 1 << 3
    ERROR = 1 << 4
    HUP = 1 << 5  # peer closed (EPOLLHUP analogue)
    # listen sockets: a connection is ready to accept (maps to READABLE in
    # poll semantics, kept distinct for introspection like the reference's
    # socket-specific bits)
    ACCEPTABLE = 1 << 6
    CHILD_EVENT = 1 << 7  # process exit notification (pidfd-style)


class StatusListener:
    """Watches a file for transitions of selected state bits.

    `notify(state, changed)` fires when any watched bit changes (or, for
    level-listeners, is set). Identity-hashable so files can deregister."""

    def __init__(
        self,
        interest: FileState,
        callback: Callable[[FileState, FileState], None],
        *,
        level: bool = False,
    ):
        self.interest = interest
        self.callback = callback
        self.level = level  # fire on "set" even without a transition

    def wants(self, state: FileState, changed: FileState) -> bool:
        if self.level:
            return bool(state & self.interest)
        return bool(changed & self.interest)


class CallbackQueue:
    """Deferred-callback runner. Mutations enqueue listener notifications;
    the outermost caller drains. `CallbackQueue.run(fn)` is the reference's
    `CallbackQueue::queue_and_run` entry point."""

    _active: "CallbackQueue | None" = None

    def __init__(self):
        self._q: list[Callable[[], None]] = []

    def push(self, cb: Callable[[], None]):
        self._q.append(cb)

    def drain(self):
        while self._q:
            self._q.pop(0)()

    @classmethod
    def current(cls) -> "CallbackQueue | None":
        return cls._active

    @classmethod
    def run(cls, fn: Callable[["CallbackQueue"], object]):
        """Run fn with an active queue, draining afterwards. Nested calls
        reuse the outer queue (callbacks still run only at the outermost
        unwind, preserving no-reentrancy)."""
        if cls._active is not None:
            return fn(cls._active)
        q = cls()
        cls._active = q
        try:
            out = fn(q)
            q.drain()
            return out
        finally:
            cls._active = None
