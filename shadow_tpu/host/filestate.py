"""File status bits, listeners, and the deferred callback queue.

Reference: `FileState` bitflags (`host/descriptor/mod.rs:111-140`),
`StatusListener` (`host/descriptor/listener.rs`), and `CallbackQueue`
(`utility/callback_queue.rs`) — the mechanism that breaks borrow cycles by
deferring "state changed" notifications until the triggering operation has
fully unwound. Here the queue plays the same role for Python re-entrancy:
listener callbacks never run inside the mutation that caused them.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable


class FileState(enum.IntFlag):
    NONE = 0
    ACTIVE = 1 << 0  # open and usable
    READABLE = 1 << 1
    WRITABLE = 1 << 2
    CLOSED = 1 << 3
    ERROR = 1 << 4
    HUP = 1 << 5  # peer closed (EPOLLHUP analogue)
    # listen sockets: a connection is ready to accept (maps to READABLE in
    # poll semantics, kept distinct for introspection like the reference's
    # socket-specific bits)
    ACCEPTABLE = 1 << 6
    CHILD_EVENT = 1 << 7  # process exit notification (pidfd-style)


class StatusListener:
    """Watches a file for transitions of selected state bits.

    `notify(state, changed)` fires when any watched bit changes (or, for
    level-listeners, is set). Identity-hashable so files can deregister."""

    def __init__(
        self,
        interest: FileState,
        callback: Callable[[FileState, FileState], None],
        *,
        level: bool = False,
    ):
        self.interest = interest
        self.callback = callback
        self.level = level  # fire on "set" even without a transition

    def wants(self, state: FileState, changed: FileState) -> bool:
        if self.level:
            return bool(state & self.interest)
        return bool(changed & self.interest)


class CallbackQueue:
    """Deferred-callback runner. Mutations enqueue listener notifications;
    the outermost caller drains. `CallbackQueue.run(fn)` is the reference's
    `CallbackQueue::queue_and_run` entry point."""

    # PER-THREAD active queue: the parallel host plane runs hosts on pool
    # threads and hosts share nothing inside a window — a class-global here
    # would let thread A drain host B's callbacks mid-mutation (and clear
    # the queue under B's feet). threading.local restores the invariant.
    _tls = threading.local()

    def __init__(self):
        self._q: list[Callable[[], None]] = []

    def push(self, cb: Callable[[], None]):
        self._q.append(cb)

    def drain(self):
        while self._q:
            self._q.pop(0)()

    @classmethod
    def current(cls) -> "CallbackQueue | None":
        return getattr(cls._tls, "active", None)

    @classmethod
    def run(cls, fn: Callable[["CallbackQueue"], object]):
        """Run fn with an active queue, draining afterwards. Nested calls
        reuse the outer queue (callbacks still run only at the outermost
        unwind, preserving no-reentrancy)."""
        active = getattr(cls._tls, "active", None)
        if active is not None:
            return fn(active)
        q = cls()
        cls._tls.active = q
        try:
            out = fn(q)
            q.drain()
            return out
        finally:
            cls._tls.active = None
