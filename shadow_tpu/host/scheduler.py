"""Work-stealing host scheduler for the CPU host plane.

Reference: `src/lib/scheduler/src/thread_per_core.rs:25-210` — N worker
threads, hosts round-robined into per-thread queues, and an idle worker
STEALS from the other threads' queues by cycling them (`:192-210`). The
reference credits its custom pools with >10x over a naive task-per-host
pool (`scheduler/src/lib.rs:8-11`).

Python recast: persistent threads parked on a condition variable between
rounds (the reference's latch pair), per-worker `deque`s, owner pops from
the head and thieves from the tail (Chase-Lev shape; the GIL makes the
individual deque ops atomic). Determinism does not depend on execution
order at all: hosts share nothing inside a window and cross-host sends
are staged per SOURCE and merged in host-id order after the round
(CpuNetwork._flush_staged / HybridSimulation._flush_stage_buf), so the
steal schedule cannot reorder anything observable — asserted by the
serial-vs-parallel byte-compare gate in tests/test_scheduler_pool.py.

GIL caveat (same as the prior plain pool): pure-Python hosts serialize;
the win is hosts whose managed processes block in futex waits off-GIL.
Stealing fixes the SKEW problem the round-robin split has there: one
busy host no longer pins its whole queue behind it.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable


class WorkStealingPool:
    def __init__(self, workers: int):
        self.n = max(1, workers)
        self._qs: list[deque] = [deque() for _ in range(self.n)]
        self._steals = [0] * self.n  # per-worker: no racy shared increment
        self._cv = threading.Condition()
        self._fn: Callable | None = None
        self._pending = 0
        self._round_id = 0
        self._error: BaseException | None = None
        self._shutdown = False
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,), daemon=True,
                name=f"host-worker-{i}",
            )
            for i in range(self.n)
        ]
        for t in self._threads:
            t.start()

    @property
    def steals(self) -> int:
        return sum(self._steals)

    def run(self, items, fn: Callable) -> None:
        """One scheduling round: `fn(item)` for every item, any worker.
        Raises the first exception any worker hit (matching the replaced
        ThreadPoolExecutor.map semantics — a raising host must surface,
        not hang the barrier)."""
        items = list(items)
        if not items:
            return
        with self._cv:
            # round-robin assignment (thread_per_core.rs:86-93); stealing
            # rebalances whatever this split gets wrong. Items are TAGGED
            # with the round id: a worker that lingers past the end of
            # round N (it decremented the last _pending, releasing run(),
            # but has not re-checked the round counter yet) would
            # otherwise pop round N+1's items and run them under round
            # N's closure — with a stale `until` horizon here.
            self._round_id += 1
            rid = self._round_id
            for i, it in enumerate(items):
                self._qs[i % self.n].append((rid, it))
            self._fn = fn
            self._pending = len(items)
            self._error = None
            self._cv.notify_all()
            while self._pending > 0:
                self._cv.wait()
            if self._error is not None:
                err, self._error = self._error, None
                raise err

    def _worker(self, wid: int):
        seen_round = 0
        while True:
            with self._cv:
                while self._round_id == seen_round and not self._shutdown:
                    self._cv.wait()
                if self._shutdown:
                    return
                seen_round = self._round_id
                fn = self._fn
            while True:
                tagged = None
                stolen_from = wid
                try:
                    tagged = self._qs[wid].popleft()  # own queue: head
                except IndexError:
                    # idle: cycle the other workers' queues and steal from
                    # the TAIL (thread_per_core.rs:192-210)
                    for k in range(1, self.n):
                        j = (wid + k) % self.n
                        try:
                            tagged = self._qs[j].pop()
                            stolen_from = j
                            break
                        except IndexError:
                            continue
                if tagged is None:
                    break  # round drained (items in flight finish elsewhere)
                rid, item = tagged
                if rid != seen_round:
                    # a NEWER round's item reached a stale worker: put it
                    # back and go (re)synchronize on the round counter
                    self._qs[stolen_from].append(tagged)
                    break
                if stolen_from != wid:
                    self._steals[wid] += 1
                try:
                    fn(item)
                except BaseException as e:  # noqa: BLE001 — must not hang
                    with self._cv:
                        if self._error is None:
                            self._error = e
                        self._pending -= 1
                        if self._pending <= 0:
                            self._cv.notify_all()
                    continue
                with self._cv:
                    self._pending -= 1
                    if self._pending <= 0:
                        self._cv.notify_all()

    def shutdown(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
