"""Work-stealing host scheduler for the CPU host plane.

Reference: `src/lib/scheduler/src/thread_per_core.rs:25-210` — N worker
threads, hosts round-robined into per-thread queues, and an idle worker
STEALS from the other threads' queues by cycling them (`:192-210`). The
reference credits its custom pools with >10x over a naive task-per-host
pool (`scheduler/src/lib.rs:8-11`).

Python recast: persistent threads parked on a condition variable between
rounds (the reference's latch pair), per-worker `deque`s, owner pops from
the head and thieves from the tail (Chase-Lev shape; the GIL makes the
individual deque ops atomic). Determinism does not depend on execution
order at all: hosts share nothing inside a window and cross-host sends
are staged per SOURCE and merged in host-id order after the round
(CpuNetwork._flush_staged / HybridSimulation._flush_stage_buf), so the
steal schedule cannot reorder anything observable — asserted by the
serial-vs-parallel byte-compare gate in tests/test_scheduler_pool.py.

GIL caveat (same as the prior plain pool): pure-Python hosts serialize;
the win is hosts whose managed processes block in futex waits off-GIL.
Stealing fixes the SKEW problem the round-robin split has there: one
busy host no longer pins its whole queue behind it.
"""

from __future__ import annotations

import queue
import threading
from collections import deque
from typing import Callable

from shadow_tpu.host import affinity


class WorkStealingPool:
    def __init__(self, workers: int, pin_cpus: list[int] | None = None):
        self.n = max(1, workers)
        self._pin_cpus = pin_cpus
        self._qs: list[deque] = [deque() for _ in range(self.n)]
        self._steals = [0] * self.n  # per-worker: no racy shared increment
        self._cv = threading.Condition()
        self._fn: Callable | None = None
        self._pending = 0
        self._round_id = 0
        self._error: BaseException | None = None
        self._shutdown = False
        self._threads = [
            threading.Thread(
                target=self._worker, args=(i,), daemon=True,
                name=f"host-worker-{i}",
            )
            for i in range(self.n)
        ]
        for t in self._threads:
            t.start()

    @property
    def steals(self) -> int:
        return sum(self._steals)

    def run(self, items, fn: Callable) -> None:
        """One scheduling round: `fn(item)` for every item, any worker.
        Raises the first exception any worker hit (matching the replaced
        ThreadPoolExecutor.map semantics — a raising host must surface,
        not hang the barrier)."""
        items = list(items)
        if not items:
            return
        with self._cv:
            # round-robin assignment (thread_per_core.rs:86-93); stealing
            # rebalances whatever this split gets wrong. Items are TAGGED
            # with the round id: a worker that lingers past the end of
            # round N (it decremented the last _pending, releasing run(),
            # but has not re-checked the round counter yet) would
            # otherwise pop round N+1's items and run them under round
            # N's closure — with a stale `until` horizon here.
            self._round_id += 1
            rid = self._round_id
            for i, it in enumerate(items):
                self._qs[i % self.n].append((rid, it))
            self._fn = fn
            self._pending = len(items)
            self._error = None
            self._cv.notify_all()
            while self._pending > 0:
                self._cv.wait()
            if self._error is not None:
                err, self._error = self._error, None
                raise err

    def _worker(self, wid: int):
        if self._pin_cpus:
            affinity.pin_current(self._pin_cpus[wid % len(self._pin_cpus)])
        seen_round = 0
        while True:
            with self._cv:
                while self._round_id == seen_round and not self._shutdown:
                    self._cv.wait()
                if self._shutdown:
                    return
                seen_round = self._round_id
                fn = self._fn
            while True:
                tagged = None
                stolen_from = wid
                try:
                    tagged = self._qs[wid].popleft()  # own queue: head
                except IndexError:
                    # idle: cycle the other workers' queues and steal from
                    # the TAIL (thread_per_core.rs:192-210)
                    for k in range(1, self.n):
                        j = (wid + k) % self.n
                        try:
                            tagged = self._qs[j].pop()
                            stolen_from = j
                            break
                        except IndexError:
                            continue
                if tagged is None:
                    break  # round drained (items in flight finish elsewhere)
                rid, item = tagged
                if rid != seen_round:
                    # a NEWER round's item reached a stale worker: put it
                    # back and go (re)synchronize on the round counter
                    self._qs[stolen_from].append(tagged)
                    break
                if stolen_from != wid:
                    self._steals[wid] += 1
                try:
                    fn(item)
                except BaseException as e:  # noqa: BLE001 — must not hang
                    with self._cv:
                        if self._error is None:
                            self._error = e
                        self._pending -= 1
                        if self._pending <= 0:
                            self._cv.notify_all()
                    continue
                with self._cv:
                    self._pending -= 1
                    if self._pending <= 0:
                        self._cv.notify_all()

    def shutdown(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)


class ThreadPerHostPool:
    """Thread-per-host scheduling policy (`thread_per_host.rs:25-60`).

    The reference spawns ONE OS thread per host, parks the host in that
    thread's TLS, and bounds how many run at once with a
    ParallelismBoundedThreadPool pinned over the logical processors. The
    payoff is that a host's state never migrates threads: thread-local
    caches, errno, and (here) any thread-affine guest state a managed
    process leans on stay put for the host's whole lifetime.

    Python recast: a dedicated worker thread is created the first time a
    host is scheduled (keyed by host object identity — NOT `host_id`,
    which collapses distinct hosts carrying a default/duplicate id onto
    one thread) and every subsequent round runs that host on the SAME
    thread — the
    TLS-stability guarantee, asserted by tests. A semaphore bounds
    concurrent execution to `parallelism` (the reference's bounded pool);
    blocked-in-futex native hosts release the GIL, so the bound governs
    genuine concurrency, not just thread count. Determinism is the same
    argument as WorkStealingPool: per-source staging merged in host-id
    order makes the execution schedule unobservable.
    """

    def __init__(self, parallelism: int, pin_cpus: list[int] | None = None):
        self.parallelism = max(1, parallelism)
        self._sem = threading.Semaphore(self.parallelism)
        # pinning follows the RUNNING slot, not the host thread: a host
        # thread that wins a semaphore slot takes a CPU from this free
        # list, pins, runs, and returns it — so the `parallelism` hosts
        # running at any instant occupy distinct CPUs (the reference pins
        # its bounded pool's N workers to N distinct LPs; pinning the
        # unbounded host threads round-robin would let two admitted hosts
        # share a CPU while assigned CPUs sit idle). deque append/popleft
        # are GIL-atomic.
        self._free_cpus: deque | None = (
            deque(pin_cpus[: self.parallelism]) if pin_cpus else None
        )
        # run() is single-caller (the window loop); _get_queue mutates
        # _workers/_threads without a lock on that contract
        self._workers: dict[object, queue.SimpleQueue] = {}
        self._threads: list[threading.Thread] = []
        self._cv = threading.Condition()
        self._pending = 0
        self._error: BaseException | None = None

    @property
    def thread_count(self) -> int:
        return len(self._threads)

    @staticmethod
    def _key(item) -> object:
        # object identity, NOT host_id: ids default to 0, and two
        # default-id hosts keyed by id would silently share one thread.
        # Host objects persist for the simulation, so id() is stable.
        return id(item)

    def _get_queue(self, item) -> queue.SimpleQueue:
        key = self._key(item)
        q = self._workers.get(key)
        if q is None:
            q = queue.SimpleQueue()
            self._workers[key] = q
            label = getattr(item, "host_id", None)
            t = threading.Thread(
                target=self._worker,
                args=(q,),
                daemon=True,
                name=f"host-{key if label is None else label}",
            )
            self._threads.append(t)
            t.start()
        return q

    def run(self, items, fn: Callable) -> None:
        items = list(items)
        if not items:
            return
        with self._cv:
            self._pending = len(items)
            self._error = None
        for it in items:
            self._get_queue(it).put((fn, it))
        with self._cv:
            while self._pending > 0:
                self._cv.wait()
            if self._error is not None:
                err, self._error = self._error, None
                raise err

    def _worker(self, q: queue.SimpleQueue):
        while True:
            task = q.get()
            if task is None:
                return
            fn, item = task
            with self._sem:
                cpu = None
                if self._free_cpus:
                    try:
                        cpu = self._free_cpus.popleft()
                        affinity.pin_current(cpu)
                    except IndexError:
                        cpu = None
                try:
                    fn(item)
                except BaseException as e:  # noqa: BLE001 — must not hang
                    with self._cv:
                        if self._error is None:
                            self._error = e
                finally:
                    if cpu is not None:
                        self._free_cpus.append(cpu)
                    with self._cv:
                        self._pending -= 1
                        if self._pending <= 0:
                            self._cv.notify_all()

    def shutdown(self):
        for q in self._workers.values():
            q.put(None)
        for t in self._threads:
            t.join(timeout=2)


def make_pool(
    scheduler: str, workers: int, pin_cpus: list[int] | None = None
):
    """The one scheduler-policy dispatch point (reference
    Scheduler::new, scheduler/src/lib.rs): "steal" = WorkStealingPool,
    "per-host" = ThreadPerHostPool; anything else raises."""
    if scheduler == "per-host":
        return ThreadPerHostPool(workers, pin_cpus)
    if scheduler == "steal":
        return WorkStealingPool(workers, pin_cpus)
    raise ValueError(
        f"host scheduler must be steal|per-host, got {scheduler!r}"
    )
