"""Netlink route sockets (minimal NETLINK_ROUTE emulation).

Reference: `host/descriptor/socket/netlink.rs` (~1290 LoC). Real
applications open an AF_NETLINK socket at startup to enumerate interfaces
and addresses (glibc getifaddrs does RTM_GETLINK + RTM_GETADDR dumps; the
shim interposes getifaddrs for the common path, this socket covers binaries
that speak rtnetlink directly). Supported: bind, getsockname, RTM_GETLINK
and RTM_GETADDR dump requests answered with the canonical two interfaces
(lo + eth0 with the host's simulated address); everything else gets
NLMSG_ERROR(-EOPNOTSUPP) — loud, never silent.
"""

from __future__ import annotations

import socket as _socket
import struct

from shadow_tpu.host.descriptor import File
from shadow_tpu.host.filestate import FileState

AF_NETLINK = 16
NETLINK_ROUTE = 0

NLMSG_ERROR = 2
NLMSG_DONE = 3
NLM_F_MULTI = 2
NLM_F_REQUEST = 1
NLM_F_DUMP = 0x100 | 0x200  # ROOT|MATCH

RTM_NEWLINK = 16
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_GETADDR = 22

IFLA_IFNAME = 3
IFA_ADDRESS = 1
IFA_LOCAL = 2
IFA_LABEL = 3

ARPHRD_LOOPBACK = 772
ARPHRD_ETHER = 1
IFF_UP = 1
IFF_LOOPBACK = 8
IFF_RUNNING = 0x40


def _align4(b: bytes) -> bytes:
    pad = (-len(b)) % 4
    return b + b"\0" * pad


def _nlmsg(mtype: int, flags: int, seq: int, pid: int, payload: bytes) -> bytes:
    hdr = struct.pack("<IHHII", 16 + len(payload), mtype, flags, seq, pid)
    return _align4(hdr + payload)


def _attr(atype: int, data: bytes) -> bytes:
    return _align4(struct.pack("<HH", 4 + len(data), atype) + data)


class NetlinkSocket(File):
    """One emulated rtnetlink socket: request in, queued datagrams out."""

    def __init__(self, host):
        super().__init__()
        self.host = host
        self.pid = 0  # netlink port id (bind or kernel-assigned)
        self._rcv: list[bytes] = []
        self._set_state(on=FileState.WRITABLE)

    # ---- interface inventory (mirrors the shim's getifaddrs pair) ---------

    def _links(self):
        return [
            (1, "lo", ARPHRD_LOOPBACK, IFF_UP | IFF_LOOPBACK | IFF_RUNNING,
             "127.0.0.1", 8),
            (2, "eth0", ARPHRD_ETHER, IFF_UP | IFF_RUNNING,
             self.host.cfg.ip, 24),
        ]

    # ---- request handling --------------------------------------------------

    def submit(self, data: bytes) -> int:
        """One sendto/sendmsg worth of netlink request(s)."""
        n = len(data)
        off = 0
        while off + 16 <= len(data):
            mlen, mtype, flags, seq, _pid = struct.unpack_from("<IHHII", data, off)
            if mlen < 16 or off + mlen > len(data):
                break
            self._handle_req(mtype, flags, seq)
            off += (mlen + 3) & ~3
        if self._rcv:
            self._set_state(on=FileState.READABLE)
        return n

    def _handle_req(self, mtype: int, flags: int, seq: int):
        out = b""
        if mtype == RTM_GETLINK and flags & NLM_F_DUMP:
            for idx, name, hwtype, ifflags, _ip, _plen in self._links():
                ifi = struct.pack("<BxHiII", 0, hwtype, idx, ifflags, 0)
                out += _nlmsg(RTM_NEWLINK, NLM_F_MULTI, seq, self.pid,
                              ifi + _attr(IFLA_IFNAME, name.encode() + b"\0"))
            out += _nlmsg(NLMSG_DONE, NLM_F_MULTI, seq, self.pid,
                          struct.pack("<i", 0))
        elif mtype == RTM_GETADDR and flags & NLM_F_DUMP:
            for idx, name, _hw, _fl, ip, plen in self._links():
                ifa = struct.pack("<BBBBi", _socket.AF_INET, plen, 0, 0, idx)
                addr = _socket.inet_aton(ip)
                out += _nlmsg(
                    RTM_NEWADDR, NLM_F_MULTI, seq, self.pid,
                    ifa + _attr(IFA_ADDRESS, addr) + _attr(IFA_LOCAL, addr)
                    + _attr(IFA_LABEL, name.encode() + b"\0"),
                )
            out += _nlmsg(NLMSG_DONE, NLM_F_MULTI, seq, self.pid,
                          struct.pack("<i", 0))
        else:
            # loud refusal: NLMSG_ERROR carrying -EOPNOTSUPP + echoed header
            err = struct.pack("<i", -95) + struct.pack(
                "<IHHII", 16, mtype, flags, seq, self.pid
            )
            out = _nlmsg(NLMSG_ERROR, 0, seq, self.pid, err)
        self._rcv.append(out)

    # ---- read side ---------------------------------------------------------

    def read(self, n: int) -> bytes | None:
        """One queued response datagram (netlink reads are message-wise;
        a short buffer truncates, like the kernel with MSG_TRUNC unset)."""
        if not self._rcv:
            return None
        data = self._rcv.pop(0)
        if not self._rcv:
            self._set_state(off=FileState.READABLE)
        return data[:n]

    def peek(self, n: int) -> bytes | None:
        if not self._rcv:
            return None
        return self._rcv[0][:n]

    def write(self, data: bytes) -> int:
        return self.submit(bytes(data))

    def close(self):
        self._rcv.clear()
        super().close()
