"""CPU-side host emulation plane (reference L2, `src/main/host/`).

This package is the simulated-Linux-kernel half of the framework: file
descriptors with observable state bits, pipes/eventfds/timerfds/epoll, UDP
and TCP sockets (TCP backed by `shadow_tpu.tcp`), a per-host network
namespace with port demux, and *managed programs* — coroutine processes
driven by a per-host event loop in simulated time, blocking on syscall
conditions exactly like the reference's `SyscallCondition` web
(`host/syscall/condition.rs`, `syscall_condition.c`, `listener.rs`,
`callback_queue.rs`).

The device engine (`shadow_tpu.core.engine`) simulates *modeled* hosts fully
on TPU; this plane simulates *emulated* hosts — ones running program logic
too irregular for vectorized dispatch — and couples to the same network
fabric either through the pure-CPU wire (`host.network`) or the device
co-simulation bridge (`shadow_tpu.cosim`).
"""

from shadow_tpu.host.filestate import CallbackQueue, FileState, StatusListener
from shadow_tpu.host.descriptor import Descriptor, DescriptorTable, File
from shadow_tpu.host.pipe import Pipe, create_pipe
from shadow_tpu.host.eventfd import EventFd
from shadow_tpu.host.timerfd import TimerFd
from shadow_tpu.host.epoll import Epoll, EpollEvent
from shadow_tpu.host.sockets import TcpListenerSocket, TcpSocket, UdpSocket
from shadow_tpu.host.netns import NetworkNamespace
from shadow_tpu.host.process import Blocked, ManagedProgram, Syscall
from shadow_tpu.host.host import CpuHost, HostConfig

__all__ = [
    "Blocked",
    "CallbackQueue",
    "CpuHost",
    "Descriptor",
    "DescriptorTable",
    "Epoll",
    "EpollEvent",
    "EventFd",
    "File",
    "FileState",
    "HostConfig",
    "ManagedProgram",
    "NetworkNamespace",
    "Pipe",
    "StatusListener",
    "Syscall",
    "TcpListenerSocket",
    "TcpSocket",
    "TimerFd",
    "UdpSocket",
    "create_pipe",
]
