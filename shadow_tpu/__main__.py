"""CLI entry point: `python -m shadow_tpu [OPTIONS] <config.yaml | ->`.

Mirrors the reference binary's interface (src/main/shadow.rs:30-66: clap
parse, YAML load, CLI-over-file merge, run, exit code from plugin errors):
every `--section.key=value` flag overrides the matching config field, CLI
winning (configuration.rs:19-24). `-` reads the config from stdin
(src/test/config read-from-stdin behavior).
"""

from __future__ import annotations

import argparse
import json
import sys

import yaml

from shadow_tpu import __version__
from shadow_tpu.config.options import ConfigError, load_config, merge_cli_overrides


def _split_overrides(extra: list[str]) -> dict[str, str]:
    out: dict[str, str] = {}
    i = 0
    while i < len(extra):
        a = extra[i]
        if not a.startswith("--"):
            raise ConfigError(f"unexpected argument {a!r}")
        body = a[2:]
        if "=" in body:
            k, v = body.split("=", 1)
        else:
            if i + 1 >= len(extra):
                raise ConfigError(f"flag {a!r} needs a value")
            k, v = body, extra[i + 1]
            i += 1
        out[k.replace("-", "_")] = v
        i += 1
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="shadow_tpu",
        description="TPU-native conservative-PDES network simulator",
        epilog=(
            "Any config field can be overridden with --section.key=value, "
            "e.g. --general.stop_time='10 s' --experimental.rounds_per_chunk=128"
        ),
    )
    p.add_argument(
        "config", nargs="?", help="YAML simulation config ('-' = stdin)"
    )
    p.add_argument(
        "--shm-cleanup", action="store_true",
        help="remove orphaned shadow-ipc shared-memory files and exit "
             "(reference: shadow --shm-cleanup, utility/shm_cleanup.rs)",
    )
    p.add_argument("--version", action="version", version=__version__)
    p.add_argument("--progress", action="store_true", help="print a progress line")
    p.add_argument(
        "--dry-run", action="store_true",
        help="parse config + build the simulation, run nothing (config check)",
    )
    p.add_argument(
        "--print-stats", action="store_true",
        help="print the sim-stats JSON to stdout after the run",
    )
    args, extra = p.parse_known_args(argv)

    if args.shm_cleanup:
        from shadow_tpu.native_plane import shm_cleanup

        print(f"removed {shm_cleanup()} orphaned shm file(s)", file=sys.stderr)
        return 0
    if args.config is None:
        p.error("config is required (or use --shm-cleanup)")

    try:
        cfg = load_config(args.config)
        overrides = _split_overrides(extra)
        if overrides:
            cfg = merge_cli_overrides(cfg, overrides)
        if args.progress:
            cfg.general.progress = True
        from shadow_tpu.sim import build_simulation  # deferred: jax init is slow

        sim = build_simulation(cfg)
    except (ConfigError, OSError, yaml.YAMLError) as e:
        # Only the config-build phase maps to exit 2. GraphError subclasses
        # ConfigError; OSError covers missing/unreadable config + graph files
        # (reference: bad config exits with an error, not a backtrace).
        print(f"config error: {e}", file=sys.stderr)
        return 2
    if args.dry_run:
        # specs counts every simulated host; `hosts` on the co-sim plane
        # holds only the CPU-backed (program) subset of a mixed config
        n = len(getattr(sim, "specs", None) or sim.hosts)
        print(
            f"config ok: {n} hosts, "
            f"{sim.graph.num_nodes} graph nodes, "
            f"world={sim.engine_cfg.world}",
            file=sys.stderr,
        )
        return 0

    report = sim.run()
    data_dir = sim.write_outputs(report=report)
    if args.print_stats:
        json.dump(report, sys.stdout, indent=2)
        print()
    print(
        f"done: simulated {report['simulated_seconds']:.3f}s in "
        f"{report['wall_seconds']:.2f}s "
        f"({report['sim_wall_ratio']:.2f}x), "
        f"{report['events_processed']} events, "
        f"{report['packets_delivered']} packets; outputs in {data_dir}/",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
