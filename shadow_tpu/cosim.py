"""Co-simulation bridge: CPU-emulated hosts over the device network plane.

The reference couples managed Linux processes to its simulated network
through shared-memory syscall channels (SURVEY.md §3.3-3.4, §5.8). The TPU
recast replaces that hop with per-window host↔device staging (SURVEY.md §7
hard part 6):

  every window [start, end):
    1. joint barrier: t_next = min(CPU plane, device plane) next event time;
       window_end = min(t_next + runahead, stop)  (controller.rs:88-112)
    2. CPU hosts run their event loops to window_end; socket egress is
       *staged* — (src, t, dst, size, key) — with the real bytes parked
       host-side in a by-(src, key) store
    3. one jitted `prepare` op: reset capture rings + merge the staged
       send-requests into the device queues (sorted deterministic scatter)
    4. one jitted guarded round loop: engine rounds — microsteps + the full
       egress pipeline (budget, token bucket, loss, latency, clamp) +
       exchange — run back to back on device until a round captures
       host-bound deliveries (the CPU plane must react) or the device
       catches up to the CPU plane's next event
    5. drain capture rings; map (src, key) back to bytes; schedule socket
       delivery on each destination CPU host at the captured arrival time

  Conservative lookahead makes this exact: every cross-host arrival lands
  at >= window_end, so a packet staged in window N is always delivered into
  window N+1 or later on both planes.

Multi-device: with `general.parallelism > 1` the device plane is
shard-mapped over the mesh exactly like `Engine.run_chunk` — staged sends
arrive replicated, each shard merges only its own hosts' rows, and capture
rings are gathered back (mesh-invariance: `tests/test_cosim.py`,
`tests/test_mixed.py`). The CPU plane stays one Python process; its
parallelism is `experimental.host_workers`.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import yaml

from shadow_tpu.config.options import ConfigError, ConfigOptions
from shadow_tpu.core import engine as eng
from shadow_tpu.core.engine import Engine, EngineParams
from shadow_tpu.core.integrity import IntegrityAbort
from shadow_tpu.core.pressure import PressureAbort
from shadow_tpu.core.supervisor import SupervisorAbort
from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.sockets import NetPacket
from shadow_tpu.models.hybrid import (
    HybridModel,
    KIND_SENDREQ,
    PW_DST_OR_SRC,
    PW_KEY,
    PW_SIZE,
)
from shadow_tpu.net.dns import Dns
from shadow_tpu.obs import PcapWriter, PerfTimers, SimLogger, StraceLogger
from shadow_tpu.ops import merge_flat_events, pack_order, q_next_time
from shadow_tpu.programs import get_program
from shadow_tpu.simtime import NS_PER_SEC, TIME_MAX
from shadow_tpu import sim as simmod

_BYTES_GC_WINDOWS = 1024  # sweep horizon for lost-packet payloads

# magic value in the hybrid payload's flags word marking "the byte store
# holds this key" — the echo-reconstruction in _drain_captures must fire
# ONLY for payloads that originated as bridge send-requests (a model's own
# payload words can collide with small live keys)
BYTES_KEY_MAGIC = 0x53484457  # "SHDW"


def _pad_tree(tree, pad: int):
    """Zero-pad every [H_real, ...] leaf to [H_real + pad, ...] (the mixed
    plane's analogue of sim.Simulation._pad)."""

    def f(a):
        a = np.asarray(a)
        if pad == 0:
            return jnp.asarray(a)
        width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return jnp.asarray(np.pad(a, width))

    return jax.tree.map(f, tree)


class HybridSimulation:
    """Config-driven co-simulation (CLI-compatible with `Simulation`)."""

    def __init__(
        self,
        cfg: ConfigOptions,
        *,
        staging_cap: int = 4096,
        world: int | None = None,
    ):
        self.cfg = cfg
        self.graph = simmod.load_graph(cfg.network.graph)
        self.specs = simmod.expand_hosts_hybrid(cfg, self.graph)
        if not self.specs:
            raise ConfigError("config defines no hosts")
        if cfg.fluid.active:
            # the fluid plane's coupling rides the device engine's send
            # path; the CPU host plane's packets never see it, so a
            # hybrid run would model background congestion for HALF the
            # traffic — reject loudly instead of silently under-coupling
            raise ConfigError(
                "fluid: the hybrid (managed-process) driver does not "
                "support the fluid traffic plane yet — the CPU plane's "
                "packets would bypass the background coupling; run a "
                "pure device-model sim or drop the fluid block"
            )
        self.staging_cap = staging_cap
        # mixed simulations: any spec carrying a device model makes the
        # lane plane heterogeneous (models/mixed.py); pure-program configs
        # keep the plain hybrid proxy
        model_names = {s.model for s in self.specs if s.model != "hybrid"}
        if model_names:
            from shadow_tpu.models.base import get_model
            from shadow_tpu.models.mixed import MixedModel

            if len(model_names) > 1:
                raise ConfigError(
                    f"mixed simulation supports one device model, got "
                    f"{sorted(model_names)}"
                )
            inner_name = model_names.pop()
            self.model = MixedModel(get_model(inner_name)(), inner_name)
        else:
            self.model = HybridModel()
        ex = cfg.experimental
        world = (
            simmod.resolve_world(cfg.general.parallelism)
            if world is None
            else world
        )
        # device plane pads the host count to a multiple of the mesh size
        # with inert hosts (same scheme as the modeled sim); the CPU plane
        # only ever touches the real prefix
        self._num_real = len(self.specs)
        num_hosts = -(-self._num_real // world) * world
        # emulated TCP bursts land many events per host per window; keep the
        # per-host slab roomy (overflow is counted, never silent — see
        # stats_report queue_overflow_dropped)
        auto_qcap, auto_budget, auto_rpc = ex.resolve_shapes(num_hosts)
        qcap = max(auto_qcap, 256)
        # fault plane: link-fault (loss/latency) windows act below the
        # bridge — in the device engine's egress pipeline — so they ride
        # along on hybrid sims unchanged. Host crashes do NOT: the CPU
        # plane's processes have live Python/native state no up/down mask
        # can pause, so a crash schedule here is a config error, not a
        # silent no-op.
        from shadow_tpu.core.faults import FaultSchedule, compile_faults

        if cfg.faults.crashes or (
            cfg.faults.host_churn is not None and cfg.faults.host_churn.prob > 0
        ):
            raise ConfigError(
                "faults: host crashes/churn are not supported on hybrid "
                "(program) simulations — the CPU plane cannot pause live "
                "processes; use loss_windows, or model the hosts"
            )
        # pressure plane: the hybrid driver supports drop (default) and
        # abort (first-drop stop with honest artifacts). Escalate is
        # rejected loudly — the bridge's injection programs and byte
        # stores are compiled/keyed against the device queue shape, and a
        # mid-window capacity migration cannot re-seat the CPU plane's
        # staged state; model the hosts (the Simulation driver escalates)
        # or size the hybrid slab up front (it already auto-rooms to
        # >= 256 slots).
        if cfg.pressure.policy == "escalate":
            raise ConfigError(
                "pressure: escalate is not supported on hybrid (program) "
                "simulations — the CPU bridge cannot migrate staged state "
                "across queue shapes; use policy drop/abort or model the "
                "hosts"
            )
        if ex.timer_wheel:
            # the hybrid device plane runs the bridge model, which has no
            # timer_kinds (timers live in the real CPU processes) — a
            # wheel would be dead HBM; reject loudly rather than carry it
            raise ConfigError(
                "experimental.timer_wheel is not supported on hybrid "
                "(program) simulations — the bridge model declares no "
                "timer_kinds; drop the knob or model the hosts"
            )
        if (cfg.faults.supervisor.enabled
                and cfg.faults.supervisor.checkpoint_file is not None):
            # same principle as crashes above: the hybrid supervisor runs
            # per-dispatch snapshots only (the CPU plane's live processes
            # cannot be restored from an on-disk device checkpoint), so a
            # durability knob it cannot honor is a config error, not a
            # silent drop the user discovers at crash time
            raise ConfigError(
                "faults.supervisor.checkpoint_file is not supported on "
                "hybrid (program) simulations — the CPU plane cannot "
                "resume from a device checkpoint; remove it or model the "
                "hosts"
            )
        try:
            self._fault_sched = (
                compile_faults(
                    cfg.faults,
                    num_hosts=num_hosts,
                    num_real=self._num_real,
                    stop_time=cfg.general.stop_time,
                    bootstrap_end=cfg.general.bootstrap_end_time,
                    default_seed=cfg.general.seed,
                )
                if cfg.faults.injecting
                else FaultSchedule(0, 0, False, None)
            )
        except ValueError as e:
            raise ConfigError(f"faults: {e}") from e
        self.engine_cfg = eng.EngineConfig(
            num_hosts=num_hosts,
            stop_time=cfg.general.stop_time,
            bootstrap_end_time=cfg.general.bootstrap_end_time,
            runahead_floor=ex.runahead,
            static_min_latency=max(self.graph.min_latency_ns_opt or 0, 1),
            use_jitter=self.graph.has_jitter,
            use_dynamic_runahead=False,
            use_codel=ex.use_codel,
            queue_capacity=qcap,
            # the bucketed queue rides along on hybrid sims too (merge and
            # pop/push dispatch on queue type); a block that does not divide
            # the roomier hybrid capacity fails loudly in EngineConfig
            queue_block=ex.event_queue_block,
            sends_per_host_round=max(auto_budget, 32),
            max_round_inserts=ex.max_round_inserts or qcap,
            # bounds the guarded round loop — the ONLY device execution path,
            # so it must be >= 1 or nothing would ever advance
            rounds_per_chunk=max(auto_rpc, 1),
            # round tracer ring sized to the guarded chunk bound; drained
            # after every guarded dispatch so it can never wrap
            trace_rounds=(
                max(auto_rpc, 1) if cfg.observability.trace else 0
            ),
            # network observatory: event-class + safe-window accounting
            # ride along on the hybrid device plane (the hybrid model has
            # no flow port, so no flow ledger here)
            netobs=cfg.observability.network,
            microstep_limit=ex.microstep_limit,
            # the K-way fold and the flipped multi-device exchange default
            # ride along on hybrid sims: both act below the bridge (the
            # microstep loop / the cross-shard merge), so the CPU plane
            # sees identical deliveries either way
            microstep_events=ex.microstep_events,
            # the sort-free calendar merge acts below the bridge (the
            # cross-shard merge), so it rides along like the K-way fold
            merge_scatter=ex.merge_scatter,
            exchange=ex.resolve_exchange(world),
            a2a_block=ex.a2a_block,
            world=world,
            shaping=any(
                s.bw_up_bits > 0 or s.bw_down_bits > 0 for s in self.specs
            ),
            fault_loss_windows=self._fault_sched.loss_windows,
            # pressure plane: abort policy traces the first-drop stop
            # into the guarded loop (escalate was rejected above)
            pressure_abort=cfg.pressure.active,
            # integrity sentinel: device-plane guards ride along on the
            # hybrid plane, with the first-violation stop in the guarded
            # loop. The bridge cannot roll the CPU plane back, so there
            # is no quarantine-and-replay classification here — a
            # violation raises IntegrityAbort directly (treated
            # deterministic; see _device_rounds). The strict
            # window-monotonicity sub-check is relaxed: CPU-plane
            # injections' conservative arrival bound can legally sit
            # below the device's last guarded window_end (the
            # EngineConfig.integrity_strict_time docstring derives
            # this); the host-side _bridge_guard covers the bridge's
            # own clock/staging invariants instead.
            integrity=cfg.integrity.enabled,
            integrity_dual=(
                cfg.integrity.enabled and cfg.integrity.dual_digest
            ),
            integrity_strict_time=False,
        )
        self.mesh = None
        if world > 1:
            self.mesh = jax.sharding.Mesh(
                np.array(jax.devices()[:world]), (eng.AXIS,)
            )
        self.engine = Engine(self.engine_cfg, self.model, self.mesh)
        self._build()

    # ---- build -------------------------------------------------------------

    def _build(self):
        cfg, ecfg = self.cfg, self.engine_cfg
        # device side (reuses the modeled-sim param construction)
        node_of = np.zeros((ecfg.num_hosts,), np.int32)
        bw_up = np.zeros((ecfg.num_hosts,), np.int64)
        bw_down = np.zeros((ecfg.num_hosts,), np.int64)
        for h in self.specs:
            node_of[h.host_id] = h.node_index
            bw_up[h.host_id] = h.bw_up_bits
            bw_down[h.host_id] = h.bw_down_bits
        from shadow_tpu.models.mixed import MixedModel

        if isinstance(self.model, MixedModel):
            # build over the REAL lanes only, then zero-pad to the mesh
            # size (exactly sim.py's _pad): building the inner model at the
            # padded width would make results world-dependent — phold's
            # num_hosts and gossip's neighbor draws change with H
            lane_hosts = [
                {
                    "host_id": s.host_id,
                    "name": s.name,
                    "plane": "native" if s.programs else "model",
                    "model_args": dict(s.model_args) if not s.programs else {},
                    "start_time": s.start_time,
                }
                for s in self.specs
            ]
        else:
            lane_hosts = [{"host_id": i} for i in range(self._num_real)]
        mparams, mstate, initial_events = self.model.build(
            lane_hosts, cfg.general.seed
        )
        mparams = _pad_tree(mparams, ecfg.num_hosts - self._num_real)
        mstate = _pad_tree(mstate, ecfg.num_hosts - self._num_real)
        with eng.host_build_context():
            params = EngineParams(
                node_of=jnp.asarray(node_of),
                lat_ns=jnp.asarray(self.graph.lat_ns),
                loss=jnp.asarray(self.graph.loss),
                jitter_ns=jnp.asarray(self.graph.jitter_ns),
                eg_tb=simmod._tb_params(bw_up, ecfg.tb_interval_ns),
                in_tb=simmod._tb_params(bw_down, ecfg.tb_interval_ns),
                model=jax.tree.map(jnp.asarray, mparams),
                faults=self._fault_sched.params,
            )
            mstate_dev = jax.tree.map(jnp.asarray, mstate)
        self.state, self.params = self.engine.init_state(
            params, mstate_dev, initial_events, seed=cfg.general.seed
        )

        # CPU side: one CpuHost per PROGRAM spec; modeled specs live only
        # on device (but are registered in DNS and the IP map, so real
        # processes can address them by name or IP)
        self.ip_to_gid: dict[str, int] = {}
        self.dns = Dns()
        for s in self.specs:
            self.dns.register(s.name, s.ip)
            self.ip_to_gid[s.ip] = s.host_id
        self.native_specs = [s for s in self.specs if s.programs]
        self._model_gids = {s.host_id for s in self.specs if not s.programs}
        self.hosts: list[CpuHost] = []
        self._host_by_gid: dict[int, CpuHost] = {}
        for s in self.native_specs:
            h = CpuHost(
                HostConfig(
                    name=s.name,
                    ip=s.ip,
                    seed=cfg.general.seed,
                    host_id=s.host_id,
                    model_unblocked_latency=cfg.general.model_unblocked_syscall_latency,
                    tcp=s.tcp_cfg,
                    breadcrumbs=cfg.experimental.packet_breadcrumbs,
                )
            )
            h.egress = self._stage_send
            h.resolver = self.dns.resolve
            h.rev_resolver = self.dns.reverse
            self.hosts.append(h)
            self._host_by_gid[s.host_id] = h
        self.procs = []
        for s, h in zip(self.native_specs, self.hosts):
            for p in s.programs:
                args = dict(p.get("args") or {})
                if "/" in p["path"]:
                    # real binary under the C++ shim (native plane)
                    from shadow_tpu.native_plane import ensure_built, spawn_native

                    if not ensure_built():
                        raise ConfigError(
                            f"native plane unavailable (no C++ toolchain?) "
                            f"for binary {p['path']!r}"
                        )
                    proc = spawn_native(
                        h,
                        [p["path"], *p.get("argv_raw", [])],
                        start_time=p.get("start_time", 0),
                        env=p.get("environment") or {},
                    )
                else:
                    prog = get_program(p["path"])
                    proc = h.spawn(
                        prog,
                        name=p["path"],
                        args=args,
                        start_time=p.get("start_time", 0),
                    )
                proc.expected_final_state = p.get("expected_final_state", "running")
                if p.get("shutdown_time") is not None:
                    h.schedule(p["shutdown_time"], proc.kill)
                self.procs.append(proc)

        # observability (reference §5.1: pcap per interface, strace per
        # process, perf timers around the hot phases; §5.5: async
        # sim-time-stamped logger, shadow_logger.rs:17-60)
        self.perf = PerfTimers()
        self._tracer = None
        if self.engine_cfg.trace_rounds:
            from shadow_tpu.obs import RoundTracer

            self._tracer = RoundTracer(self.engine_cfg.trace_rounds)
        # runtime observatory (obs/runtime.py): per-window bridge-stall
        # split (ROADMAP item 4's before/after instrument) + the compile
        # ledger over the bridge's jitted programs. Host-side only —
        # must exist before the jitted ops below are built so their
        # cold compiles are recorded.
        self._bridge_rt = None
        self._rt_compiles = None
        if cfg.observability.runtime:
            from shadow_tpu.obs.runtime import BridgeTelemetry, CompileLedger

            self._bridge_rt = BridgeTelemetry()
            self._rt_compiles = CompileLedger()
        self._pcaps = []
        self._strace_files = []
        data_dir = cfg.general.data_directory
        self.log = None
        if cfg.general.log_file:
            path = cfg.general.log_file
            if not os.path.isabs(path):
                path = os.path.join(data_dir, path)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self.log = SimLogger(path, level=cfg.general.log_level)
            for s, h in zip(self.native_specs, self.hosts):
                h.on_process_exit = functools.partial(
                    _log_process_exit, self.log, h
                )
        strace_mode = cfg.experimental.strace_logging_mode
        for s, h in zip(self.native_specs, self.hosts):
            host_dir = os.path.join(data_dir, "hosts", s.name)
            if s.pcap_enabled:
                os.makedirs(host_dir, exist_ok=True)
                h.pcap_lo = PcapWriter(
                    os.path.join(host_dir, "lo.pcap"), s.pcap_capture_size
                )
                h.pcap_eth = PcapWriter(
                    os.path.join(host_dir, "eth0.pcap"), s.pcap_capture_size
                )
                self._pcaps += [h.pcap_lo, h.pcap_eth]
            if strace_mode != "off":
                os.makedirs(host_dir, exist_ok=True)
                for p in h.processes.values():
                    f = open(
                        os.path.join(host_dir, f"{p.name}.{p.pid}.strace"), "w"
                    )
                    self._strace_files.append(f)
                    p.strace = StraceLogger(f, strace_mode)

        # staging + payload store; tuples are (src, t, dst, size, key, sock).
        # Sends land in PER-SOURCE buffers (each written only by its own
        # host, so window execution can be parallel) and are flushed into
        # `_staged` in host-id order — identical to serial execution order.
        self._staged: list[tuple[int, int, int, int, int, int]] = []
        self._stage_buf: list[list] = [[] for _ in self.specs]
        self._qdisc = cfg.experimental.interface_qdisc
        self._send_seq = np.zeros((ecfg.num_hosts,), np.int64)
        self._bytes: list[dict[int, tuple[int, NetPacket]]] = [
            {} for _ in self.specs
        ]
        self._window_idx = 0
        self._unreach = [0] * len(self.specs)
        self._model_pkts_unrouted = 0  # model->native with no UDP listener
        # parallel CPU host plane (reference thread_per_core.rs; see
        # CpuNetwork for the staging argument). GIL caveat: pure-Python
        # hosts serialize; native hosts block in futex waits off-GIL.
        self._host_pool = None
        ex = cfg.experimental
        if ex.host_workers > 1 or ex.host_scheduler == "per-host":
            from shadow_tpu.host import affinity
            from shadow_tpu.host.scheduler import make_pool

            pin = affinity.assign(ex.host_workers) if ex.use_cpu_pinning else None
            self._host_pool = make_pool(ex.host_scheduler, ex.host_workers, pin)

        # jitted ops (shard-mapped over the mesh when world > 1, exactly
        # like Engine.run_chunk — staged-send arrays ride in replicated and
        # each shard merges only its own hosts' rows)
        axis = eng.AXIS if self.mesh is not None else None
        prepare = functools.partial(
            _prepare_window, self.engine_cfg, self.model, axis
        )
        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P

            rep = P()
            from shadow_tpu.core.compat import shard_map_compat as _shard_map

            prepare = _shard_map(
                prepare, self.mesh,
                (self.engine.state_specs(), rep, rep, rep, rep, rep, rep),
                self.engine.state_specs(),
            )
        self._prepare = jax.jit(prepare, donate_argnums=0)
        if self._rt_compiles is not None:
            self._prepare = self._rt_compiles.instrument(
                "prepare", "base", "cold_start", self._prepare
            )

        def _mk_guarded(ecfg):
            """The guarded round loop jitted at one engine config —
            called once for the full-width program and lazily per merge
            gear (`dataclasses.replace(cfg, gear_cols=g)`: same state
            shapes, truncated exchange sort + first-shed abort)."""
            g = functools.partial(
                eng._run_guarded_chunk,
                ecfg,
                self.model,
                axis,
                lambda ms: jnp.any(ms["cap_n"] > 0),
            )
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                from shadow_tpu.core.compat import shard_map_compat as _shard_map

                state_spec = self.engine.state_specs()
                g = _shard_map(
                    g, self.mesh,
                    (state_spec, self.engine.param_specs(), P()), state_spec,
                )
            fn = jax.jit(g, donate_argnums=0)
            if self._rt_compiles is not None:
                gear = getattr(ecfg, "gear_cols", 0)
                fn = self._rt_compiles.instrument(
                    "guarded",
                    f"gear={gear}" if gear else "base",
                    "gear_shift" if gear else "cold_start", fn,
                )
            return fn

        self._mk_guarded = _mk_guarded
        self._guarded = _mk_guarded(self.engine_cfg)
        self._guarded_gears: dict[int, Any] = {}
        # occupancy-adaptive merge gears on the device plane (core/gears.py;
        # the bridge's CPU plane is unaffected — gears act below it, in the
        # exchange merge, and accepted chunks are bit-identical to full
        # width by the shed-exact replay)
        from shadow_tpu.core.gears import GearController, resolve_gear_ladder

        try:
            ladder = resolve_gear_ladder(
                cfg.experimental.merge_gears,
                self.engine_cfg.sends_per_host_round,
            )
        except ValueError as e:
            raise ConfigError(f"experimental.merge_gears: {e}") from e
        self._gearctl = GearController(ladder) if ladder else None
        self._last_gear = None
        self._ob_hwm_run = 0
        # HBM observatory (obs/memory.py): per-shard live sampling after
        # each guarded device dispatch. Host-side observer only — the
        # traced programs are byte-identical with this on or off.
        self._memmon = None
        if cfg.observability.memory:
            from shadow_tpu.obs.memory import MemoryMonitor

            devs = (
                list(self.mesh.devices.flat) if self.mesh is not None
                else [jax.devices()[0]]
            )
            self._memmon = MemoryMonitor(devs)
        self._clear_caps = jax.jit(_clear_caps, donate_argnums=0)
        # crash-resilient supervisor, per-dispatch mode: the CPU plane
        # advances between device dispatches and cannot roll back, so
        # every guarded dispatch snapshots the DEVICE state first and only
        # the failing dispatch retries (no cross-window replay, no on-disk
        # checkpoint — hybrid durable checkpoints keep their own
        # end-of-run constraints, core/checkpoint.save_checkpoint_hybrid)
        self._supervisor = None
        self._aborted = False
        self._pressure_aborted = False
        # integrity sentinel: the committed joint time horizon the
        # host-side bridge guards check against (str detail once aborted)
        self._integrity_aborted: str | None = None
        self._iv_horizon = 0
        if cfg.faults.supervisor.enabled:
            from shadow_tpu.core.supervisor import ChunkSupervisor

            self._supervisor = ChunkSupervisor(
                snapshot_every_chunks=1,
                max_retries=cfg.faults.supervisor.max_retries,
                backoff_base_s=cfg.faults.supervisor.backoff_base_ms / 1000.0,
                pre_dispatch_snapshot=True,
                log=sys.stderr,
                memory=self._memmon,
                memory_modeled_fn=(
                    self._modeled_shard_bytes if self._memmon is not None
                    else None
                ),
            )

    # ---- egress staging ----------------------------------------------------

    def _stage_send(self, host: CpuHost, pkt: NetPacket):
        gid = host.host_id
        dst_gid = self.ip_to_gid.get(pkt.dst_ip)
        if dst_gid is None:
            self._unreach[gid] += 1
            host.drop_packet(pkt, "inet_no_route")
            return
        key = int(self._send_seq[gid] % (1 << 31))
        self._send_seq[gid] += 1
        self._bytes[gid][key] = (self._window_idx, pkt)
        sock = (int(pkt.proto) << 16) | (int(pkt.src_port) & 0xFFFF)
        self._stage_buf[gid].append(
            (gid, host.now(), dst_gid, pkt.size_bytes, key, sock)
        )

    def _flush_stage_buf(self):
        """Move per-source buffers into the flat staging list in host-id
        order (the deterministic merge point; worker.rs:644-654 analogue)."""
        for buf in self._stage_buf:
            if buf:
                self._staged.extend(buf)
                buf.clear()

    def _execute_hosts(self, until: int):
        if self._host_pool is not None:
            self._host_pool.run(self.hosts, lambda h: h.execute(until))
        else:
            for h in self.hosts:  # deterministic host order
                h.execute(until)
        self._flush_stage_buf()

    # ---- window loop -------------------------------------------------------

    def _cpu_min_next(self) -> int:
        return min(
            (h.next_event_time() for h in self.hosts), default=TIME_MAX
        )

    def run(self, *, progress: bool | None = None, log=sys.stderr) -> dict:
        try:
            return self._run(progress=progress, log=log)
        finally:
            # flush observability artifacts even when a window raises, so a
            # determinism byte-compare never sees a truncated file
            for w in self._pcaps:
                w.close()
            for f in self._strace_files:
                if not f.closed:
                    f.close()
            if self.log is not None:
                self.log.close()

    def _run(self, *, progress: bool | None = None, log=sys.stderr) -> dict:
        cfg = self.cfg
        stop = cfg.general.stop_time
        show_progress = cfg.general.progress if progress is None else progress
        hb_ns = cfg.general.heartbeat_interval
        next_hb = hb_ns or 0
        if self._tracer is not None and not (
            self._tracer.rounds or self._tracer.lost
        ):
            # nothing drained yet: adopt the ring's current cursor so a
            # state restored from a hybrid checkpoint is not replayed
            self._tracer.sync_cursor(self.state.trace)
        profiling = bool(cfg.observability.profile_dir)
        if profiling:
            os.makedirs(cfg.observability.profile_dir, exist_ok=True)
            jax.profiler.start_trace(cfg.observability.profile_dir)
        # wall clock starts AFTER observability setup so _wall_seconds and
        # heartbeat ratios measure the simulation, not the trace session
        t0 = time.monotonic()
        try:
            windows = self._window_loop(
                stop, show_progress, t0, hb_ns, next_hb, log
            )
        finally:
            if profiling:
                jax.profiler.stop_trace()
        self._execute_hosts(stop)
        if self._host_pool is not None:
            self._host_pool.shutdown()
            self._host_pool = None
        # snapshot final states BEFORE reaping: a daemon alive at stop_time
        # satisfies expected_final_state: running even though shutdown kills
        # it (reference free_all_applications semantics, host.rs:791-807)
        for p in self.procs:
            p.state_at_stop = getattr(p.state, "value", p.state)
        for h in self.hosts:  # reap live processes + native IPC resources
            h.shutdown()
        if show_progress:
            print(file=log)
        self._wall_seconds = time.monotonic() - t0
        self._windows = windows
        return self.stats_report()

    def _window_loop(self, stop, show_progress, t0, hb_ns, next_hb, log):
        cfg = self.cfg
        runahead = max(
            self.engine_cfg.runahead_floor, self.engine_cfg.static_min_latency, 1
        )
        windows = 0
        bt = self._bridge_rt  # obs/runtime.BridgeTelemetry | None
        while True:
            if bt is not None:
                # the joint-barrier computation below is bridge work:
                # it lands in the window's bridge residual
                bt.window_start()
            dev_min = int(jnp.min(q_next_time(self.state.queue)))
            t_next = min(self._cpu_min_next(), dev_min)
            if t_next >= stop:
                break
            window_end = min(t_next + runahead, stop)
            try:
                if self.engine_cfg.integrity:
                    self._bridge_guard_clock(t_next)
                t_host = time.monotonic()
                with self.perf.time("host_plane"):
                    self._execute_hosts(window_end)
                if bt is not None:
                    bt.note("cpu_plane", time.monotonic() - t_host)
                if self.engine_cfg.integrity:
                    # judged while the window's staged sends actually
                    # EXIST (post host execution, pre injection) — at
                    # the top of the loop the previous window's inject
                    # loop has always drained the staging list
                    self._bridge_guard_staging()
            except IntegrityAbort as e:
                print(f"[integrity] aborting run: {e}", file=log)
                self._integrity_aborted = str(e)
                break
            # inject ALL staged sends (multiple merges under staging-cap
            # overflow — BEFORE any device rounds run, so a tiny cap only
            # costs extra merge dispatches and cannot shift packet timing),
            # then run device rounds until the first round that captures
            # host-bound deliveries (the CPU plane must react) or the
            # device catches up to the CPU plane's next event.
            with self.perf.time("device_inject"):
                if bt is None:
                    self.state = self._inject()
                    while self._staged:
                        self.state = self._inject()
                    # settle the staged merge BEFORE the timer stops: jax
                    # dispatch is async, so without the block this phase
                    # timed only the enqueue and the device work leaked
                    # into whichever phase synced first — perf.report()
                    # under-reported the device plane (the reference's
                    # perf_timers wrap the actual work, host.rs:721-729)
                    jax.block_until_ready(self.state)
                else:
                    # per-syscall-batch latency: each staged merge is one
                    # batch, blocked individually so its histogram entry
                    # is a true round-trip latency (the instrument's cost
                    # when on; the off path above keeps one block total)
                    while True:
                        n_batch = min(len(self._staged), self.staging_cap)
                        t_b = time.monotonic()
                        self.state = self._inject()
                        jax.block_until_ready(self.state)
                        if n_batch > 0:
                            bt.note_batch(time.monotonic() - t_b, n_batch)
                        else:
                            # the off path's unconditional first _inject
                            # on an empty staging list: bridge wall, but
                            # NOT a syscall batch — an empty merge in the
                            # histogram would dilute the round-trip
                            # latencies ROADMAP item 4 reads
                            bt.note("bridge", time.monotonic() - t_b)
                        if not self._staged:
                            break
            until = min(self._cpu_min_next(), stop)
            t_rounds = time.monotonic()
            try:
                with self.perf.time("device_rounds"):
                    self._device_rounds(
                        jnp.asarray(max(until, window_end), jnp.int64)
                    )
            except SupervisorAbort as e:
                # graceful abort: export the completed prefix from the
                # pre-dispatch device snapshot, not the in-hand state
                # (abort_export_state docs the poisoned/donation rationale)
                print(f"[supervisor] aborting run: {e}", file=log)
                good = self._supervisor.abort_export_state()
                if good is not None:
                    self.state = good
                self._aborted = True
                break
            except PressureAbort as e:
                # pressure abort policy: the in-hand state IS the honest
                # record (the guarded loop stopped at the dropping round;
                # the drop is in the exported counters)
                print(f"[pressure] aborting run: {e}", file=log)
                if self._tracer is not None:
                    self._tracer.drain(
                        self.state.trace,
                        wall_t0=t_rounds, wall_t1=time.monotonic(),
                    )
                self._pressure_aborted = True
                break
            except IntegrityAbort as e:
                # an in-jit invariant tripped on the device plane. The
                # CPU plane cannot roll back, so there is no replay
                # classification — the run stops loudly, the report
                # names the invariant/round/shard, and the artifacts
                # carry `integrity_aborted` so the violating state's
                # counters never read as a trustworthy record.
                print(f"[integrity] aborting run: {e}", file=log)
                if self._tracer is not None:
                    self._tracer.drain(
                        self.state.trace,
                        wall_t0=t_rounds, wall_t1=time.monotonic(),
                    )
                self._integrity_aborted = str(e)
                break
            if bt is not None:
                bt.note("device_plane", time.monotonic() - t_rounds)
            if self._tracer is not None:
                self._tracer.drain(
                    self.state.trace,
                    wall_t0=t_rounds, wall_t1=time.monotonic(),
                )
            if self._memmon is not None:
                t_s = time.monotonic()
                shard_bytes = self._memmon.sample(
                    modeled_bytes=self._modeled_shard_bytes(), wall_t=t_s
                )
                if self._tracer is not None:
                    self._tracer.note_memory(t_s, shard_bytes)
            t_drain = time.monotonic()
            with self.perf.time("drain_captures"):
                self._drain_captures()
            if bt is not None:
                # capture draining is bridge marshalling, like staging
                bt.note("bridge", time.monotonic() - t_drain)
                bt.window_end(window_end)
            windows += 1
            if self.log is not None and hb_ns and window_end >= next_hb:
                self.log.info(
                    window_end, "manager",
                    f"heartbeat windows={windows}",
                )
            if hb_ns and window_end >= next_hb:
                wall = time.monotonic() - t0
                gear_f = (
                    f"gear={self._last_gear} "
                    if self._last_gear is not None else ""
                )
                fault_f = ""
                if self.engine_cfg.faults_active:
                    _s = self.state.stats
                    fault_f = (
                        f"faults="
                        f"{int(np.asarray(_s.faults_dropped).sum())}/"
                        f"{int(np.asarray(_s.faults_delayed).sum())} "
                    )
                hbm_f = (
                    f"hbm={self._memmon.hwm_bytes()} "
                    if self._memmon is not None else ""
                )
                ek_f = ""
                if self.engine_cfg.netobs:
                    _s = self.state.stats
                    ek_f = (
                        f"ek={int(np.asarray(_s.ec_timer).sum())}/"
                        f"{int(np.asarray(_s.ec_pkt).sum())} "
                    )
                # rt= rides along only on runtime-observatory runs: the
                # LAST window's realtime factor (sim-s/wall-s)
                rt_f = (
                    f"rt={bt.rt_last:.2f} "
                    if bt is not None and bt.rt_last is not None else ""
                )
                print(
                    f"[heartbeat] sim_time={window_end / NS_PER_SEC:.3f}s "
                    f"wall={wall:.2f}s windows={windows} "
                    f"{fault_f}"
                    f"{gear_f}"
                    f"{hbm_f}"
                    f"{ek_f}"
                    f"{rt_f}"
                    f"ratio={window_end / NS_PER_SEC / max(wall, 1e-9):.2f}x "
                    f"{simmod.resource_heartbeat()}",
                    file=log,
                )
                # per-host tracker interval (per-socket/per-interface
                # deltas, reference tracker.c heartbeats)
                for h in self.hosts:
                    h.record_heartbeat(window_end)
                next_hb = (window_end // hb_ns + 1) * hb_ns
            if show_progress:
                pct = min(100.0, 100.0 * window_end / max(stop, 1))
                print(f"\rprogress: {pct:5.1f}% ", end="", file=log, flush=True)
            if self._window_idx % 256 == 0:
                self._gc_bytes()
        return windows

    def _bridge_guard_clock(self, t_next: int):
        """Host-side bridge-clock invariant (the integrity sentinel's
        hybrid half — the in-jit guards cover the device plane, these
        cover the clock/staging state only Python can see): the
        (CPU plane, device plane) joint next-event time never regresses
        below the previously committed horizon — both planes completed
        everything under it, and every new event (CPU injection,
        conservative arrival bound) lands at or above it by the
        lookahead argument, so a regression means a scribbled
        queue/time value. Raises IntegrityAbort (no replay
        classification on the bridge)."""
        horizon = self._iv_horizon
        if t_next < horizon:
            raise IntegrityAbort(
                f"integrity: bridge clock regressed — joint next-event "
                f"time {t_next} fell below the committed horizon "
                f"{horizon} (a scribbled queue/time plane, or an engine "
                f"bug breaking conservative lookahead)"
            )
        self._iv_horizon = t_next

    def _bridge_guard_staging(self):
        """Staging-floor invariant, judged POST host execution while the
        window's staged sends exist (the top-of-loop point always sees
        an empty list — the previous window's inject loop drains it):
        no staged send's event time sits below the committed horizon —
        its originating host already executed past it."""
        horizon = self._iv_horizon
        below = [s for s in self._staged if s[1] < horizon]
        if below:
            raise IntegrityAbort(
                f"integrity: bridge staging holds {len(below)} "
                f"send(s) below the committed horizon {horizon} "
                f"(earliest t={min(s[1] for s in below)}) — staged "
                f"state corrupted"
            )

    def _guarded_at(self, gear: int):
        """The guarded-chunk program for a merge gear (lazily jitted and
        cached, exactly like Engine.run_chunk_gear)."""
        if gear <= 0 or gear >= self.engine_cfg.sends_per_host_round:
            return self._guarded
        fn = self._guarded_gears.get(gear)
        if fn is None:
            import dataclasses

            fn = self._mk_guarded(
                dataclasses.replace(self.engine_cfg, gear_cols=gear)
            )
            self._guarded_gears[gear] = fn
        return fn

    def _device_rounds(self, until_arr):
        """One guarded device dispatch — at the adaptive merge gear with
        shed-exact replay when gears are on, the plain full-width program
        otherwise; wrapped in the supervisor's per-dispatch retry when
        `faults.supervisor` is enabled. The block_until_ready keeps the
        perf phase honest (jax dispatch is async; see the device_inject
        comment above).

        Cost note: below the top gear every window pays a device-side
        SimState copy (the replay snapshot). Guarded windows can be a
        handful of rounds, so on CPU-plane-chatty workloads at large H
        the copy can eat the narrower sort's savings — merge gears on
        hybrid sims are for device-dominant phases; leave the knob off
        when the CPU plane sets the pace."""

        def run(st):
            if self._gearctl is None:
                st = self._guarded(st, self.params, until_arr)
                jax.block_until_ready(st)
                return st
            from shadow_tpu.core.gears import run_adaptive_chunk

            def dispatch(s, gear):
                s = self._guarded_at(gear)(s, self.params, until_arr)
                jax.block_until_ready(s)
                return s

            # rounds0: a guarded window can legitimately retire ZERO
            # rounds (probe fires immediately / device already at the
            # horizon) — such windows must not feed the controller an
            # hwm of 0
            st, self._last_gear, hwm = run_adaptive_chunk(
                self._gearctl, st, dispatch,
                rounds0=int(st.stats.rounds),
            )
            self._ob_hwm_run = max(self._ob_hwm_run, hwm)
            return st

        if self._supervisor is None:
            self.state = run(self.state)
        else:
            self.state = self._supervisor.run_chunk(self.state, run)
        if self.engine_cfg.integrity:
            # integrity sentinel, checked BEFORE the pressure read: a
            # violating attempt's other counters may themselves be
            # scribbled. The guarded loop stopped at the first violating
            # round; the bridge cannot replay-classify (the CPU plane
            # advanced), so any violation is a loud stop.
            from shadow_tpu.core.integrity import raise_if_violated

            raise_if_violated(
                self.state,
                context="hybrid device plane (unclassifiable — the CPU "
                "plane cannot roll back for a replay)",
            )
        if self.cfg.pressure.active:
            # abort policy (the only active pressure policy the hybrid
            # driver admits): the guarded loop stopped at the first
            # dropping round — stop the run with the drop in the record
            # (the shared formatter keeps the two drivers' reports equal)
            from shadow_tpu.core.pressure import ResilienceController

            ResilienceController.raise_if_dropped(self.state)

    def _order_seq(self, gid: int) -> int:
        """Fresh per-host order counter for qdisc-reordered injections."""
        v = int(self._send_seq[gid] % (1 << 31))
        self._send_seq[gid] += 1
        return v

    def _inject(self):
        """Merge up to staging_cap staged sends into the device queues (and
        clear the capture rings); the guarded round loop computes its own
        windows from the queue contents."""
        cap = self.staging_cap
        staged = self._staged[:cap]
        overflow = self._staged[cap:]
        self._staged = overflow  # carried to next window (bounded staging)
        if self._qdisc == "round-robin":
            staged = _rr_reorder(staged)
        n = cap
        src = np.zeros((n,), np.int64)
        t = np.full((n,), TIME_MAX, np.int64)
        dstw = np.zeros((n,), np.int32)
        order = np.zeros((n,), np.int64)
        kind = np.zeros((n,), np.int32)
        payload = np.zeros((n, 4), np.int32)
        valid = np.zeros((n,), bool)
        # order keys are packed in NUMPY for the whole batch: the jax
        # pack_order builds traced scalars and int() forces a sync PER
        # PACKET — profiled at ~4 s of a 21 s tor-minimal run (the same
        # per-event-jax pathology seed_queue hit at 1M hosts)
        from shadow_tpu.ops.events import _LOCAL_SHIFT, _SRC_SHIFT, SEQ_MASK

        for i, (gid, t_ns, dst_gid, size, key, _sock) in enumerate(staged):
            src[i] = gid
            t[i] = t_ns
            dstw[i] = gid  # send-request is a LOCAL event on the source host
            # flags word: marks "bytes stored under (src, key)" so the echo
            # reconstruction can trust the key (see BYTES_KEY_MAGIC)
            payload[i, 3] = BYTES_KEY_MAGIC
            # key doubles as the order tiebreak: under round-robin the list
            # order changed, so re-sequence (the payload keeps the original
            # key for the byte-store lookup)
            seq = key if self._qdisc == "fifo" else self._order_seq(gid)
            order[i] = (
                (np.int64(1) << _LOCAL_SHIFT)
                | (np.int64(gid) << _SRC_SHIFT)
                | (np.int64(seq) & SEQ_MASK)
            )
            kind[i] = KIND_SENDREQ
            payload[i, PW_SIZE] = size
            payload[i, PW_DST_OR_SRC] = dst_gid
            payload[i, PW_KEY] = key
            valid[i] = True
        self._window_idx += 1
        return self._prepare(
            self.state,
            jnp.asarray(dstw),
            jnp.asarray(t),
            jnp.asarray(order),
            jnp.asarray(kind),
            jnp.asarray(payload),
            jnp.asarray(valid),
        )

    def _drain_captures(self):
        # cheap guard first: the count vector is H ints vs the full rings
        # being H x cap x 4 words — most windows deliver nothing
        cap_n = np.asarray(jax.device_get(self.state.model["cap_n"]))
        if not cap_n.any():
            return
        m = self.state.model
        ms = dict(
            zip(
                ("cap_t", "cap_src", "cap_key", "cap_size", "cap_flags"),
                jax.device_get(
                    (m["cap_t"], m["cap_src"], m["cap_key"], m["cap_size"],
                     m["cap_flags"])
                ),
            )
        )
        # rings are drained: clear the device-side counters so the guarded
        # batch's probe sees a clean slate and nothing is delivered twice
        self.state = self._clear_caps(self.state)
        for gid in np.nonzero(cap_n > 0)[0]:
            host = self._host_by_gid.get(int(gid))
            if host is None:
                continue  # modeled or mesh-padding lane: no CPU plane

            for j in range(int(cap_n[gid])):
                t = int(ms["cap_t"][gid, j])
                src = int(ms["cap_src"][gid, j])
                key = int(ms["cap_key"][gid, j])
                pkt = None
                if src in self._model_gids:
                    # model-plane origin: there is no byte store. If the
                    # payload still carries our send-request magic, the
                    # modeled peer ECHOED our request payload verbatim
                    # (udp_echo does): reconstruct the endpoint-swapped
                    # reply from our own bytes — exact echo semantics
                    # including ports. Without the magic, the key is just a
                    # model payload word (possibly colliding with a live
                    # key): synthesize a zero-filled datagram instead.
                    echoed = int(ms["cap_flags"][gid, j]) == BYTES_KEY_MAGIC
                    own = self._bytes[gid].pop(key, None) if echoed else None
                    src_ip = self.specs[src].ip
                    if own is not None:
                        q = own[1]
                        pkt = NetPacket(
                            src_ip=src_ip, src_port=q.dst_port,
                            dst_ip=q.src_ip, dst_port=q.src_port,
                            proto=q.proto, payload=q.payload,
                        )
                    else:
                        # no byte store for model-plane origins: synthesize
                        # a zero-filled datagram. Aim it at the host's
                        # lowest bound UDP port (deterministic) so modeled-
                        # initiated traffic actually reaches a native
                        # listener; with none bound, fall back to 40000 and
                        # count it (visible in stats, not a silent drop)
                        size = max(int(ms["cap_size"][gid, j]), 0)
                        # LISTENERS only: explicit binds below the ephemeral
                        # range and not connected to a peer (a connected
                        # client socket would filter our src anyway, and an
                        # autobound client port is not a service endpoint)
                        from shadow_tpu.host.netns import EPHEMERAL_START

                        udp_ports = sorted(
                            port
                            for (proto, port), s in host.netns._ports.items()
                            if proto == 17 and port < EPHEMERAL_START
                            and getattr(s, "peer_ip", None) is None
                        )
                        if udp_ports:
                            dst_port = udp_ports[0]
                        else:
                            dst_port = 40000
                            self._model_pkts_unrouted += 1
                        pkt = NetPacket(
                            src_ip=src_ip, src_port=40000,
                            dst_ip=host.ip, dst_port=dst_port,
                            proto=17, payload=b"\0" * size,
                        )
                else:
                    entry = (
                        self._bytes[src].pop(key, None)
                        if 0 <= src < len(self._bytes)
                        else None
                    )
                    if entry is None:
                        continue  # duplicate capture (cannot happen) or GC'd
                    pkt = entry[1]
                host.schedule(t, functools.partial(host.deliver_packet, pkt))

    def _gc_bytes(self):
        horizon = self._window_idx - _BYTES_GC_WINDOWS
        if horizon <= 0:
            return
        for store in self._bytes:
            dead = [k for k, (w, _) in store.items() if w < horizon]
            for k in dead:  # lost to device-side drop (loss/budget/codel)
                del store[k]

    def _modeled_shard_bytes(self) -> int:
        """The memory monitor's modeled fallback where the backend
        reports no allocator stats (obs/memory.py owns the formula)."""
        from shadow_tpu.obs.memory import modeled_shard_bytes

        return modeled_shard_bytes(
            self.state, self.params, self.engine_cfg.world
        )

    # ---- outputs -----------------------------------------------------------

    def stats_report(self) -> dict:
        s = jax.device_get(self.state.stats)
        n = self._num_real  # exclude mesh-padding hosts
        wall = getattr(self, "_wall_seconds", None)
        sim_s = self.cfg.general.stop_time / NS_PER_SEC
        def pstate(p):  # coroutine procs use ProcState, native procs a str
            snap = getattr(p, "state_at_stop", None)
            return snap if snap is not None else getattr(p.state, "value", p.state)

        runtime_block: dict[str, Any] = {}
        if self.cfg.observability.runtime:
            # runtime observatory block (obs/runtime.py): the bridge-
            # stall split + compile ledger, assembled by the ONE shared
            # helper the modeled driver and bench rows use
            from shadow_tpu.obs.runtime import assemble_runtime_report

            runtime_block = {
                "runtime": assemble_runtime_report(
                    bridge=getattr(self, "_bridge_rt", None),
                    compiles=getattr(self, "_rt_compiles", None),
                    total_wall_s=wall,
                )
            }
        zombies = [p for p in self.procs if pstate(p) == "zombie"]
        failures = sum(
            1
            for p in self.procs
            if (p.expected_final_state == "running" and pstate(p) == "zombie")
            or (
                isinstance(p.expected_final_state, dict)
                and p.expected_final_state.get("exited") is not None
                and p.exit_code != p.expected_final_state["exited"]
            )
        )
        return {
            "simulated_seconds": sim_s,
            "wall_seconds": wall,
            "sim_wall_ratio": (sim_s / wall) if wall else None,
            "windows": getattr(self, "_windows", 0),
            "device_rounds": int(s.rounds),
            "events_processed": int(s.events[:n].sum())
            + sum(h.counters["events"] for h in self.hosts),
            "packets_sent": int(s.pkts_sent[:n].sum()),
            "packets_delivered": int(s.pkts_delivered[:n].sum()),
            "packets_lost": int(s.pkts_lost[:n].sum()),
            "packets_budget_dropped": int(s.pkts_budget_dropped[:n].sum()),
            "packets_codel_dropped": int(s.pkts_codel_dropped[:n].sum()),
            "faults_dropped": int(s.faults_dropped[:n].sum()),
            "faults_delayed": int(s.faults_delayed[:n].sum()),
            "queue_overflow_dropped": int(
                np.asarray(jax.device_get(self.state.queue.dropped))[:n].sum()
            ),
            "queue_occupancy_hwm": int(np.asarray(s.q_occ_hwm)[:n].max())
            if n
            else 0,
            "outbox_send_hwm": max(
                int(np.asarray(s.outbox_hwm).max()), self._ob_hwm_run
            ),
            "unreachable_ips": sum(self._unreach),
            "model_pkts_unrouted": self._model_pkts_unrouted,
            "syscalls": sum(h.counters["syscalls"] for h in self.hosts),
            "process_failures": failures,
            "processes_exited": len(zombies),
            "determinism_digest": f"{int(np.bitwise_xor.reduce(jax.device_get(self.state.stats.digest)[:n])):016x}",
            "perf": self.perf.report(),
            **runtime_block,
            "model_report": self.model.report(
                jax.device_get(self.state.model), None
            ),
            **(
                {"gears": self._gearctl.report()}
                if self._gearctl is not None
                else {}
            ),
            **(
                {"supervisor": self._supervisor.report()}
                if self._supervisor is not None
                else {}
            ),
            **({"aborted": True} if self._aborted else {}),
            **(
                {
                    "pressure": {
                        "policy": self.cfg.pressure.policy,
                        "capacity": self.state.queue.t.shape[1],
                        "outbox": self.state.outbox.t.shape[1],
                        **(
                            {"aborted": True}
                            if self._pressure_aborted else {}
                        ),
                    },
                    "pressure_regrows": 0,
                    "pressure_replays": 0,
                }
                if self.cfg.pressure.active
                else {}
            ),
            **(
                {"pressure_aborted": True, "aborted": True}
                if self._pressure_aborted else {}
            ),
            # integrity sentinel block (core/integrity.py): the hybrid
            # plane has no replay classifier, so the block carries the
            # dual digest fold plus — after an abort — the violation's
            # naming; integrity_aborted keeps a violating state's
            # counters from reading as a trustworthy record
            **(
                {
                    "integrity": {
                        "transients": 0,
                        "replays": 0,
                        "max_replays": self.cfg.integrity.max_replays,
                        **(
                            {"deterministic": {
                                "detail": self._integrity_aborted,
                            }}
                            if self._integrity_aborted else {}
                        ),
                        **(
                            {"determinism_digest2": (
                                f"{int(np.bitwise_xor.reduce(jax.device_get(self.state.stats.digest2)[:n])):016x}"
                            )}
                            if self.engine_cfg.integrity_dual else {}
                        ),
                    },
                }
                if self.engine_cfg.integrity else {}
            ),
            **(
                {"integrity_aborted": True, "aborted": True}
                if self._integrity_aborted else {}
            ),
            **(
                {"poisoned": True}
                if self._supervisor is not None and self._supervisor.poisoned
                else {}
            ),
            **(
                {"trace": self._tracer.summary()}
                if self._tracer is not None
                else {}
            ),
            **(
                {"network": self._network_report(s, n)}
                if self.engine_cfg.netobs
                else {}
            ),
            **(
                {"memory": self._memory_report()}
                if self._memmon is not None
                else {}
            ),
        }

    def _network_report(self, s, n: int) -> dict:
        """Network-observatory block for the hybrid device plane: event
        classes + safe-window telemetry + the per-link fold over the
        modeled lanes (the CPU plane's per-socket/interface counters
        already live in host-stats.json). The hybrid model carries no
        flow port and no per-host hook, so no ledger/model fields."""
        from shadow_tpu.obs.netobs import assemble_network_report, node_map

        return assemble_network_report(
            stats=s,
            num_real=n,
            rounds=int(s.rounds),
            node_of=node_map(self.specs, n),
        )

    def _memory_report(self) -> dict:
        from shadow_tpu.obs.memory import observatory_report

        return observatory_report(
            self.engine, self.state, self.params, self._memmon,
            ledger=self.cfg.observability.memory_ledger,
        )

    def write_outputs(self, data_dir: str | None = None, report: dict | None = None) -> str:
        data_dir = data_dir or self.cfg.general.data_directory
        os.makedirs(data_dir, exist_ok=True)
        with open(os.path.join(data_dir, "processed-config.yaml"), "w") as f:
            yaml.safe_dump(self.cfg.to_dict(), f, sort_keys=False)
        with open(os.path.join(data_dir, "sim-stats.json"), "w") as f:
            json.dump(report or self.stats_report(), f, indent=2)
        with open(os.path.join(data_dir, "hosts.txt"), "w") as f:
            f.write(self.dns.hosts_file())  # reference per-host hostname files
        for spec, host in zip(self.native_specs, self.hosts):
            hd = os.path.join(data_dir, "hosts", spec.name)
            os.makedirs(hd, exist_ok=True)
            for p in host.processes.values():
                base = os.path.join(hd, f"{p.name}.{p.pid}")
                with open(base + ".stdout", "wb") as f:
                    f.write(b"".join(p.stdout))
                with open(base + ".stderr", "wb") as f:
                    f.write(b"".join(p.stderr))
            with open(os.path.join(hd, "host-stats.json"), "w") as f:
                json.dump(
                    {
                        "name": spec.name,
                        "ip": spec.ip,
                        **host.counters,
                        # tracker.c:24-80 analogue: cumulative per-interface
                        # + per-socket wire counters and the per-heartbeat
                        # interval deltas recorded during the run
                        "interfaces": host.if_counters,
                        "sockets": host.socket_stats(),
                        "heartbeats": host.heartbeats,
                        **(
                            {"packet_drops": host.packet_drops}
                            if host.cfg.breadcrumbs
                            else {}
                        ),
                    },
                    f,
                )
        if self._tracer is not None:
            if self._rt_compiles is not None:
                self._tracer.note_compiles(self._rt_compiles.events())
            self._tracer.write_artifacts(
                data_dir, self.cfg.observability, report
            )
        return data_dir


def _log_process_exit(log: SimLogger, host, proc):
    """Per-host process-lifecycle record (the reference stamps every log
    line with sim time + host context; process exits are the load-bearing
    events when debugging a failed expected_final_state)."""
    code = getattr(proc, "exit_code", None)
    sig = getattr(proc, "term_signal", None)
    how = f"signal {sig}" if sig else f"code {code}"
    log.info(
        host.now(), host.name,
        f"process {getattr(proc, 'name', '?')} (pid {proc.pid}) exited with {how}",
    )


def _rr_reorder(staged):
    """Round-robin qdisc (reference QDiscMode::RoundRobin wired into
    network_interface.c): within each source host, interleave this window's
    packets one per originating socket (sockets in first-seen order) instead
    of strict emit-FIFO. Deterministic: depends only on the staged list."""
    by_host: dict[int, dict[int, list]] = {}
    host_order: list[int] = []
    for e in staged:
        gid, sock = e[0], e[5]
        if gid not in by_host:
            by_host[gid] = {}
            host_order.append(gid)
        by_host[gid].setdefault(sock, []).append(e)
    out = []
    for gid in host_order:
        socks = list(by_host[gid].values())
        while any(socks):
            for q in socks:
                if q:
                    out.append(q.pop(0))
    return out


def _clear_caps(state):
    ms = dict(state.model)
    ms["cap_n"] = jnp.zeros_like(ms["cap_n"])
    return state._replace(model=ms)


def _prepare_window(cfg, model, axis, state, dst, t, order, kind, payload, valid):
    """Jitted: clear capture rings + merge staged send-requests. Under a
    mesh the staged arrays arrive replicated with GLOBAL host ids; each
    shard keeps only its own rows and rebases them to shard-local ids."""
    state = _clear_caps(state)
    if axis:
        import jax.lax as _lax

        h_local = state.queue.t.shape[0]
        start = _lax.axis_index(axis).astype(jnp.int64) * h_local
        mine = (dst >= start) & (dst < start + h_local)
        valid = valid & mine
        dst = jnp.clip(dst - start, 0, h_local - 1)
    queue = merge_flat_events(
        state.queue, dst, t, order, kind, payload, valid, cfg.max_round_inserts
    )
    return state._replace(queue=queue)


def run_hybrid(cfg: ConfigOptions, **kw) -> tuple[HybridSimulation, dict]:
    sim = HybridSimulation(cfg, **kw)
    report = sim.run()
    return sim, report
