"""Golden reference engine: sequential CPU PDES with the device contract.

The reference validates itself by running the same workload under Linux and
under Shadow and diffing the results (SURVEY.md §4.2: `add_linux_tests` /
`add_shadow_tests` dual registration — the real OS is the oracle). The device
engine needs the same kind of oracle: this module is an INDEPENDENT
implementation of the engine semantics — per-host binary heaps (the
reference's `BinaryHeap<Reverse<Event>>`, event_queue.rs:10-55), scalar
integer token buckets, a scalar CoDel control law, Python-loop rounds — that
must produce bit-identical per-host digests and counters to
`shadow_tpu.core.engine` for any workload. Any divergence is a bug in one of
the two (tests/test_golden.py is the gate, the analogue of the reference's
determinism suite diffing two schedulers, src/test/determinism/).

Shared on purpose: the vectorized model handlers and the per-host RNG lanes
(`ops.rng`) — models are the workload, not the engine under test. Golden
calls the same `model.handle` once per microstep with the same batch masks,
so model arithmetic is common-mode; what differs is everything the engine
does around it: queue order, window computation, shaping, budget, exchange.

Deliberately slow (pure Python loops): use small host counts / short sims.

Queue-layout independence: golden keeps per-host `heapq` heaps and never
models the device slab, so `cfg.queue_block` (flat vs two-level bucketed
EventQueue, ops/events.py) is invisible here BY DESIGN — the same golden
digests and counters gate both layouts, which is what makes this module the
oracle for the bucket-equivalence determinism tests (tests/test_bucketq.py):
flat engine == bucketed engine == golden, or one of the three is wrong.

Microstep-shape independence, same principle: golden pops and executes
EXACTLY ONE event per host per microstep — `cfg.microstep_events` (the
engine's K-way fold, core/engine.py `_microstep_k`) is likewise invisible
here by design. The K-way path's contract is "bit-identical to K=1", and
K=1 is what this loop IS, so golden is the equivalence reference for every
K (tests/test_popk.py gates engine-K == engine-1 == golden).
"""

from __future__ import annotations

import dataclasses
import heapq
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core.engine import EngineConfig, EngineParams
from shadow_tpu.models.base import (
    HandlerCtx,
    KIND_INGRESS_DONE,
    KIND_MASK,
    KIND_PKT,
    PAYLOAD_SIZE_WORD,
)
from shadow_tpu.net.codel import INTERVAL_NS as CODEL_INTERVAL_NS
from shadow_tpu.net.codel import TARGET_NS as CODEL_TARGET_NS
from shadow_tpu.ops.events import (
    EVENT_PAYLOAD_WORDS,
    ORDER_MAX,
    pack_order,
    unpack_order_src,
)
from shadow_tpu.ops.rng import rng_init, rng_uniform
from shadow_tpu.simtime import TIME_MAX

_U64 = (1 << 64) - 1
_FNV_PRIME = 1099511628211
_FNV_OFFSET = 0xCBF29CE484222325
_MIX1 = 0x9E3779B97F4A7C15
_MIX2 = 0xC2B2AE3D27D4EB4F


def _pack(is_local: int, src: int, seq: int) -> int:
    return int(pack_order(is_local, src, seq))


# --------------------------------------------------------------------------
# scalar shaping lanes (independent reimplementations of net/tokenbucket.py
# and net/codel.py — integer / f64 math identical by construction)
# --------------------------------------------------------------------------


class _TokenBucket:
    """One lane; mirrors tb_conforming_remove bit-for-bit in Python ints."""

    def __init__(self, capacity: int, refill: int, interval_ns: int):
        self.cap = int(capacity)
        self.refill = int(refill)
        self.interval = int(interval_ns)
        self.tokens = int(capacity)
        self.last_itv = 0

    def _depart(self, t: int, size: int) -> tuple[int, int, int]:
        """(depart, new_tokens, new_itv) without mutating."""
        if self.refill <= 0:
            return t, self.tokens, self.last_itv
        itv = max(t // self.interval, self.last_itv)
        elapsed = itv - self.last_itv
        gain = elapsed * self.refill if elapsed < (1 << 20) else self.cap
        tokens = min(self.cap, self.tokens + gain)
        if tokens >= size:
            return max(t, itv * self.interval), tokens - size, itv
        k = (size - tokens + self.refill - 1) // self.refill
        return (itv + k) * self.interval, tokens + k * self.refill - size, itv + k

    def probe(self, t: int, size: int) -> int:
        return self._depart(t, size)[0]

    def charge(self, t: int, size: int) -> int:
        depart, tokens, itv = self._depart(t, size)
        if self.refill > 0:
            self.tokens, self.last_itv = tokens, itv
        return depart


class _Codel:
    """One control-law lane; mirrors codel_on_packet (RFC 8289 constants)."""

    def __init__(self):
        self.first_above = 0
        self.drop_next = 0
        self.count = 0
        self.dropping = False

    @staticmethod
    def _law(now: int, count: int) -> int:
        c = np.float64(max(count, 1))
        return now + int(np.round(np.float64(CODEL_INTERVAL_NS) / np.sqrt(c)))

    def on_packet(self, now: int, sojourn: int) -> bool:
        below = sojourn < CODEL_TARGET_NS
        fa_unset = self.first_above == 0
        ok_to_drop = (not below) and (not fa_unset) and now >= self.first_above
        self.first_above = (
            0 if below else (now + CODEL_INTERVAL_NS if fa_unset else self.first_above)
        )
        if self.dropping:
            if not ok_to_drop:
                self.dropping = False
                return False
            if now >= self.drop_next:
                self.count += 1
                self.drop_next = self._law(self.drop_next, self.count)
                return True
            return False
        if ok_to_drop:
            recent = (now - self.drop_next) < 16 * CODEL_INTERVAL_NS
            self.count = self.count - 2 if (recent and self.count > 2) else 1
            self.drop_next = self._law(now, self.count)
            self.dropping = True
            return True
        return False


# --------------------------------------------------------------------------
# the golden engine
# --------------------------------------------------------------------------


@dataclasses.dataclass
class GoldenResult:
    digests: np.ndarray  # u64[H]
    stats: dict[str, np.ndarray]  # per-host counters mirroring Stats
    model_state: Any
    now: int
    rounds: int
    microsteps: int


def run_golden(
    cfg: EngineConfig,
    model,
    params: EngineParams,
    model_state,
    initial_events: list[tuple[int, int, int, tuple]],
    seed: int,
) -> GoldenResult:
    h = cfg.num_hosts
    node_of = np.asarray(params.node_of)
    lat_ns = np.asarray(params.lat_ns, np.int64)
    loss = np.asarray(params.loss)
    jitter_ns = np.asarray(params.jitter_ns, np.int64)
    eg = [
        _TokenBucket(c, r, cfg.tb_interval_ns)
        for c, r in zip(np.asarray(params.eg_tb.capacity), np.asarray(params.eg_tb.refill))
    ]
    ing = [
        _TokenBucket(c, r, cfg.tb_interval_ns)
        for c, r in zip(np.asarray(params.in_tb.capacity), np.asarray(params.in_tb.refill))
    ]
    codel = [_Codel() for _ in range(h)]
    rng = rng_init(h, seed)
    mparams_dev = jax.tree.map(jnp.asarray, params.model)
    mstate_dev = jax.tree.map(jnp.asarray, model_state)

    # per-host heaps of (t, order, kind, payload-tuple); capacity-bounded
    heaps: list[list] = [[] for _ in range(h)]
    seq = [0] * h
    digests = [_FNV_OFFSET] * h
    st = {
        k: np.zeros(h, np.int64)
        for k in (
            "events",
            "pkts_sent",
            "pkts_lost",
            "pkts_unreachable",
            "pkts_codel_dropped",
            "pkts_delivered",
            "monotonic_violations",
            "pkts_budget_dropped",
            "dropped",
        )
    }
    for host, t_ns, k, pl in initial_events:
        payload = np.zeros(EVENT_PAYLOAD_WORDS, np.int32)
        payload[: len(pl)] = pl
        heapq.heappush(heaps[host], (int(t_ns), _pack(1, host, seq[host]), int(k), payload))
        seq[host] += 1

    def qpush(host: int, t: int, order: int, kind: int, payload) -> None:
        if len(heaps[host]) >= cfg.queue_capacity:
            st["dropped"][host] += 1
            return
        heapq.heappush(heaps[host], (t, order, kind, payload))

    min_used_lat = cfg.static_min_latency
    now = 0
    rounds = 0
    microsteps = 0
    limit = cfg.effective_microstep_limit
    r_cap = min(cfg.max_round_inserts, cfg.queue_capacity)
    # CPU model (engine.py _effective_next/_microstep): events execute at
    # max(t, busy_until); each handled event charges cpu_delay_ns
    busy = [0] * h
    delay_ns = cfg.cpu_delay_ns

    def eff_next(i: int) -> int:
        if not heaps[i]:
            return TIME_MAX
        t = heaps[i][0][0]
        return max(t, busy[i]) if delay_ns > 0 else t

    while True:
        gmin = min((eff_next(i) for i in range(h)), default=TIME_MAX)
        if gmin >= cfg.stop_time:
            break
        runahead = (
            max(cfg.runahead_floor, min_used_lat)
            if cfg.use_dynamic_runahead
            else max(cfg.runahead_floor, cfg.static_min_latency)
        )
        window_end = min(min(gmin, cfg.stop_time) + max(runahead, 1), cfg.stop_time)

        staged: list[tuple[int, int, int, int, np.ndarray]] = []  # dst,t,order,kind,pl
        sent_round = np.zeros(h, np.int32)
        steps = 0
        while steps < limit:
            # ---- batch pop: each host's earliest event < window_end
            ev_t = np.full(h, TIME_MAX, np.int64)
            ev_order = np.full(h, ORDER_MAX, np.int64)
            ev_kind = np.zeros(h, np.int32)
            ev_payload = np.zeros((h, EVENT_PAYLOAD_WORDS), np.int32)
            active = np.zeros(h, bool)
            for i in range(h):
                if not heaps[i] or heaps[i][0][0] >= window_end:
                    continue
                if delay_ns > 0 and busy[i] >= window_end:
                    continue  # CPU busy past the window: events stay queued
                t, order, k, pl = heapq.heappop(heaps[i])
                if delay_ns > 0:
                    t = max(t, busy[i])  # busy-shifted execution time
                    busy[i] = t + delay_ns
                ev_t[i], ev_order[i], ev_kind[i] = t, order, k
                ev_payload[i] = pl
                active[i] = True
            if not active.any():
                break
            steps += 1

            is_pkt = (ev_kind & KIND_PKT) != 0
            needs_ingress = active & is_pkt & ((ev_kind & KIND_INGRESS_DONE) == 0)
            dispatch = active.copy()
            for i in np.nonzero(active)[0]:
                st["events"][i] += 1
                x = (int(ev_t[i]) * _MIX1) & _U64
                x ^= (int(ev_kind[i]) * _MIX2) & _U64
                x ^= int(ev_order[i])
                digests[i] = ((digests[i] ^ x) * _FNV_PRIME) & _U64
                if needs_ingress[i]:
                    t = int(ev_t[i])
                    size_bits = int(ev_payload[i, PAYLOAD_SIZE_WORD]) * 8
                    sojourn = ing[i].probe(t, size_bits) - t
                    drop = codel[i].on_packet(t, sojourn) if cfg.use_codel else False
                    if drop:
                        st["pkts_codel_dropped"][i] += 1
                        dispatch[i] = False
                        continue
                    depart = ing[i].charge(t, size_bits)
                    if depart > t:  # delayed: requeue past shaping, same order
                        qpush(
                            i,
                            depart,
                            int(ev_order[i]),
                            int(ev_kind[i]) | KIND_INGRESS_DONE,
                            ev_payload[i].copy(),
                        )
                        dispatch[i] = False
            st["pkts_delivered"] += dispatch & is_pkt

            # ---- model dispatch: the SAME vectorized handler as the device
            ctx = HandlerCtx(
                t=jnp.asarray(ev_t, jnp.int64),
                window_end=jnp.asarray(window_end, jnp.int64),
                kind=jnp.asarray(ev_kind & KIND_MASK, jnp.int32),
                payload=jnp.asarray(ev_payload, jnp.int32),
                active=jnp.asarray(dispatch),
                is_packet=jnp.asarray(is_pkt),
                src=unpack_order_src(jnp.asarray(ev_order)),
                host_id=jnp.arange(h, dtype=jnp.int64),
                state=mstate_dev,
                params=mparams_dev,
                rng=rng,
            )
            out = model.handle(ctx)
            rng, mstate_dev = out.rng, out.state

            for p in out.pushes:
                mask = np.asarray(p.mask) & dispatch
                t_req = np.asarray(p.t, np.int64)
                kind = np.asarray(p.kind, np.int32)
                payload = np.asarray(p.payload, np.int32)
                for i in np.nonzero(mask)[0]:
                    if t_req[i] < ev_t[i]:
                        st["monotonic_violations"][i] += 1
                    qpush(
                        i,
                        int(max(t_req[i], ev_t[i])),
                        _pack(1, i, seq[i]),
                        int(kind[i]) & KIND_MASK,
                        payload[i].copy(),
                    )
                    seq[i] += 1

            for s in out.sends:
                cmax = int(getattr(s, "count_max", 1) or 1)
                mask0 = np.asarray(s.mask) & dispatch
                if getattr(s, "count", None) is not None:
                    counts = np.where(mask0, np.asarray(s.count, np.int32), 0)
                else:
                    counts = mask0.astype(np.int32)
                pinc = (
                    np.asarray(s.payload_inc, np.int32)
                    if getattr(s, "payload_inc", None) is not None
                    else None
                )
                dst_arr = np.asarray(s.dst, np.int64)
                sz_arr = np.asarray(s.size_bytes, np.int32)
                kind = np.asarray(s.kind, np.int32)
                payload0 = np.asarray(s.payload, np.int32)
                for seg_j in range(cmax):
                    mask = mask0 & (counts > seg_j)
                    payload = payload0 if seg_j == 0 or pinc is None else (
                        payload0 + seg_j * pinc
                    )
                    if cfg.use_jitter:
                        # device draws jitter BEFORE the loss draw per
                        # segment: same order
                        rng, uj_arr = rng_uniform(rng, jnp.asarray(mask))
                        uj = np.asarray(uj_arr, np.float32)
                    rng, u_arr = rng_uniform(rng, jnp.asarray(mask))
                    u = np.asarray(u_arr)
                    for i in np.nonzero(mask)[0]:
                        st["pkts_sent"][i] += 1
                        order = _pack(0, i, seq[i])
                        seq[i] += 1
                        over_budget = sent_round[i] >= cfg.sends_per_host_round
                        t = int(ev_t[i])
                        size_bits = int(sz_arr[i]) * 8
                        if not over_budget:
                            eg_depart = eg[i].charge(t, size_bits)
                        dst = int(dst_arr[i])
                        bad = dst < 0 or dst >= h
                        dn = node_of[min(max(dst, 0), h - 1)]
                        lat = int(lat_ns[node_of[i], dn])
                        lossp = float(loss[node_of[i], dn])
                        lat_bound = lat
                        if cfg.use_jitter:
                            jit = int(jitter_ns[node_of[i], dn])
                            # identical float math to the device path
                            lat = lat + int(np.int64(
                                np.float32(uj[i] * np.float32(2.0) - np.float32(1.0))
                                * np.float32(jit)
                            ))
                            lat_bound = lat_bound - jit
                        if lat_bound < 0 or bad:
                            st["pkts_unreachable"][i] += 1
                            continue
                        if u[i] < lossp and t >= cfg.bootstrap_end_time:
                            st["pkts_lost"][i] += 1
                            continue
                        if over_budget:
                            st["pkts_budget_dropped"][i] += 1
                            continue
                        sent_round[i] += 1
                        min_used_lat = min(min_used_lat, lat_bound)
                        pl = payload[i].copy()
                        pl[PAYLOAD_SIZE_WORD] = sz_arr[i]
                        arrive = max(eg_depart + max(lat, 0), window_end)
                        staged.append((dst, arrive, order, int(kind[i]) | KIND_PKT, pl))

        microsteps += steps
        rounds += 1
        # ---- exchange: sorted (dst, t, order) insert, capacity + r_cap bounded
        staged.sort(key=lambda e: (e[0], e[1], e[2]))
        inserted_for: dict[int, int] = {}
        for dst, t, order, kind, pl in staged:
            n_in = inserted_for.get(dst, 0)
            if n_in >= r_cap or len(heaps[dst]) >= cfg.queue_capacity:
                st["dropped"][dst] += 1
                continue
            heapq.heappush(heaps[dst], (t, order, kind, pl))
            inserted_for[dst] = n_in + 1
        now = window_end

    return GoldenResult(
        digests=np.array(digests, np.uint64),
        stats={k: v.copy() for k, v in st.items()},
        model_state=jax.device_get(mstate_dev),
        now=now,
        rounds=rounds,
        microsteps=microsteps,
    )
