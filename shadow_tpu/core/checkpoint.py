"""Simulation checkpoint / resume.

The reference has none (SURVEY.md §5.4 — a dead manager is a dead
simulation; determinism-as-reproducibility is its only recovery story).
On TPU the entire simulation state is a pytree of device arrays, so
snapshotting is a flatten + savez; this is a genuine capability the
rebuild adds on top of reference parity.

Format: one .npz with the flattened SimState leaves plus a guard record
(engine-config fingerprint + treedef repr + model-param digest) so
restoring into a mismatched simulation build fails loudly instead of
corrupting silently. Hybrid checkpoints add the bridge's CPU half.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np


class CheckpointError(Exception):
    pass


def snapshot_state(state):
    """Cheap in-memory pre-chunk snapshot: a device-resident copy of every
    leaf. The full .npz checkpoint path (below) is for durability; this is
    the gear replay loop's working copy — the jitted chunk DONATES its
    input buffers, so a plain reference to the pre-chunk pytree would be
    invalidated by the dispatch. `jnp.copy` stays on device (no host
    round-trip) and copies only HBM-to-HBM, microseconds against a
    multi-round chunk; no guard record is needed because the snapshot
    never leaves this process or this engine build."""
    return jax.tree.map(jnp.copy, state)


def restore_snapshot(snap):
    """A fresh donation-safe copy of a `snapshot_state` result. The copy
    (rather than the snapshot itself) is handed to the replay dispatch so
    the snapshot survives — a replay at a mid-ladder gear can shed again
    and need yet another restore."""
    return snapshot_state(snap)


def _params_digest(params) -> str:
    """Digest of the model/routing parameter leaves: same-shaped states
    driven by DIFFERENT params (model_args, graph latencies) must not
    pass the guard. The derived routing rows are excluded — they are a
    deterministic function of node_of/lat/loss/jitter (already hashed)
    and can reach hundreds of MB."""
    if hasattr(params, "lat_rows"):  # EngineParams
        params = params._replace(
            lat_rows=None, loss_rows=None, jit_rows=None
        )
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(params):
        h.update(np.ascontiguousarray(jax.device_get(leaf)).tobytes())
    return h.hexdigest()


def _dump_leaves(state) -> tuple[dict, object]:
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {
        f"leaf_{i}": np.asarray(jax.device_get(x))
        for i, x in enumerate(leaves)
    }
    return arrays, treedef


def _restore_leaves(data, state, engine=None):
    """Validate the stored leaves against `state`'s tree and rebuild it,
    re-sharding onto the engine's mesh when present (ensemble restores
    pass engine=None: world=1, no mesh to re-shard onto)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    new_leaves = []
    for i in range(len(leaves)):
        arr = data[f"leaf_{i}"]
        ref = leaves[i]
        if arr.shape != ref.shape or arr.dtype != np.asarray(ref).dtype:
            raise CheckpointError(f"leaf {i}: shape/dtype mismatch")
        new_leaves.append(jnp.asarray(arr))
    out = jax.tree_util.tree_unflatten(treedef, new_leaves)
    out = _refresh_queue_caches(out)
    if engine is not None and engine.mesh is not None:
        specs = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(engine.mesh, s),
            engine.state_specs(),
        )
        out = jax.device_put(out, specs)
    return out


def _refresh_queue_caches(state):
    """Checkpoint restore is a block-cache REBUILD point (like the exchange
    merge): a bucketed queue's (bt, bo, bfill) minima are derived state, so
    they are recomputed from the restored slab rather than trusted from the
    file — a hand-edited or bit-rotted .npz can desynchronize the caches but
    never the simulation. Ensemble states carry a leading replica axis on
    every plane; the rebuild vmaps over it (same derivation per replica)."""
    from shadow_tpu.ops.events import BucketQueue, bucket_rebuild

    q = getattr(state, "queue", None)
    if isinstance(q, BucketQueue):
        if q.t.ndim == 3:  # [R, H, C]: stacked ensemble queue
            block = q.t.shape[2] // q.bt.shape[2]
            state = state._replace(
                queue=jax.vmap(lambda qq: bucket_rebuild(qq, block))(q)
            )
        else:
            state = state._replace(queue=bucket_rebuild(q, q.block))
    # the timer wheel IS the BucketQueue machinery — same derived-cache
    # rule on restore (ops/wheel.py)
    w = getattr(state, "wheel", None)
    if isinstance(w, BucketQueue):
        if w.t.ndim == 3:
            block = w.t.shape[2] // w.bt.shape[2]
            state = state._replace(
                wheel=jax.vmap(lambda ww: bucket_rebuild(ww, block))(w)
            )
        else:
            state = state._replace(wheel=bucket_rebuild(w, w.block))
    return state


def _fingerprint(engine_cfg, treedef, params) -> str:
    """The full EngineConfig participates via asdict — so a checkpoint
    written under one `microstep_events` (or queue layout, exchange, ...)
    refuses to restore into a sim built with another. For K specifically
    this is stricter than strictly necessary (K>1 histories are
    bit-identical to K=1), but mid-simulation the PEEKED batch state is
    never part of SimState, so cross-K restores would be safe only by an
    argument the guard cannot check; refusing loudly is the contract."""
    blob = json.dumps(
        {
            "cfg": dataclasses.asdict(engine_cfg),
            "treedef": str(treedef),
            "params": _params_digest(params),
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


# shape-defining EngineConfig fields normalized out of the migration
# fingerprint: a checkpoint differing from the target sim ONLY in these
# (plus the derived auto-sizes) can be re-seated through the exactness-
# gated migration ops instead of refused. Everything else — model,
# params, policies, queue LAYOUT KIND (bucket-ness changes the treedef),
# mesh/world — still refuses loudly.
_MIGRATABLE_CFG_FIELDS = (
    "queue_capacity",
    "queue_block",
    "sends_per_host_round",
    "max_round_inserts",
    "microstep_limit",
    "a2a_block",
    # timer-wheel shape (ops/wheel.py): slots/block migrate through the
    # same exactness-gated ops as the queue capacity. Wheel PRESENCE
    # (on vs off) changes the state treedef, which both fingerprints
    # carry — an on/off cross-restore still refuses loudly.
    "wheel_slots",
    "wheel_block",
)


def _migration_fingerprint(engine_cfg, treedef, params) -> str:
    """`_fingerprint` with the capacity-shape fields normalized to 0 —
    the secondary guard the cross-capacity restore path compares."""
    cfgd = dataclasses.asdict(engine_cfg)
    for f in _MIGRATABLE_CFG_FIELDS:
        cfgd[f] = 0
    blob = json.dumps(
        {
            "cfg": cfgd,
            "treedef": str(treedef),
            "params": _params_digest(params),
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def _state_shape_meta(state) -> dict:
    """The state's actual capacity shapes — recorded at save time so a
    checkpoint written MID-ESCALATION (pressure plane regrew the slab
    past the configured base) still restores: leaf shapes are validated
    against these, not the builder's config."""
    from shadow_tpu.ops.events import BucketQueue

    q = state.queue
    meta = {
        "queue_capacity": int(q.t.shape[-1]),
        "queue_block": int(q.block) if isinstance(q, BucketQueue) else 0,
        "sends_per_host_round": int(state.outbox.t.shape[-1]),
    }
    # wheel keys only when a wheel exists: wheel-off checkpoints keep the
    # pre-wheel meta byte-for-byte, so older checkpoints (no wheel keys)
    # still compare equal against wheel-off sims and load the exact path
    w = getattr(state, "wheel", None)
    if w is not None:
        meta["wheel_slots"] = int(w.t.shape[-1])
        meta["wheel_block"] = int(w.block)
    return meta


def _shaped_template(state, meta: dict):
    """`state`'s pytree with the queue/outbox planes re-shaped to the
    checkpoint's recorded capacity — the shape/dtype reference
    `_restore_leaves` validates stored leaves against."""
    from shadow_tpu.core.engine import make_empty_outbox
    from shadow_tpu.ops.events import (
        BucketQueue, make_bucket_queue, make_queue,
    )

    if (meta["queue_block"] > 0) != isinstance(state.queue, BucketQueue):
        raise CheckpointError(
            "checkpoint queue layout (flat vs bucketed) does not match "
            "this simulation; migration cannot cross layout kinds"
        )
    wheel_slots = int(meta.get("wheel_slots", 0))
    if (wheel_slots > 0) != (getattr(state, "wheel", None) is not None):
        raise CheckpointError(
            "checkpoint timer-wheel presence (on vs off) does not match "
            "this simulation; migration cannot cross the wheel boundary "
            "— rebuild with the same experimental.timer_wheel setting"
        )
    h = state.queue.t.shape[0]
    queue = (
        make_bucket_queue(h, meta["queue_capacity"], meta["queue_block"])
        if meta["queue_block"]
        else make_queue(h, meta["queue_capacity"])
    )
    outbox = make_empty_outbox(
        h, meta["sends_per_host_round"], state.outbox.count
    )
    state = state._replace(queue=queue, outbox=outbox)
    if wheel_slots:
        from shadow_tpu.ops.wheel import make_wheel

        state = state._replace(
            wheel=make_wheel(h, wheel_slots, int(meta.get("wheel_block", 0)))
        )
    return state


def _migrate_restored(state, sim):
    """Re-seat a source-shaped restored state at the target sim's shapes
    through the pressure plane's migration ops. Refuses (loudly) exactly
    when migration would lose information: live events that cannot fit
    the target capacity, or in-flight outbox entries (chunk-boundary
    checkpoints never carry any; anything else cannot re-seat)."""
    import jax.numpy as jnp

    from shadow_tpu.core.engine import make_empty_outbox
    from shadow_tpu.ops.events import migrate_queue, migration_fits
    from shadow_tpu.simtime import TIME_MAX

    cfg = sim.engine_cfg
    # under an escalate pressure policy a checkpoint written at a GROWN
    # shape resumes at that shape (shrinking just to re-escalate would
    # cost a refusal risk and replays for nothing); every other policy
    # gets exactly the configured shapes
    escalating = (
        getattr(getattr(sim.cfg, "pressure", None), "policy", "drop")
        == "escalate"
    )
    cap = int(state.queue.t.shape[-1])
    budget = int(state.outbox.t.shape[-1])
    target_cap = max(cfg.queue_capacity, cap) if escalating else (
        cfg.queue_capacity
    )
    target_budget = (
        max(cfg.sends_per_host_round, budget) if escalating
        else cfg.sends_per_host_round
    )
    if cap != target_cap or (
        getattr(state.queue, "block", 0) or 0
    ) != cfg.queue_block:
        if cap > target_cap and not bool(
            jnp.all(migration_fits(state.queue, target_cap))
        ):
            occ = int(jnp.max(jnp.sum(
                (state.queue.t != TIME_MAX).astype(jnp.int32), axis=-1
            )))
            raise CheckpointError(
                f"cannot resume at queue capacity {target_cap}: "
                f"the checkpoint holds up to {occ} live events per host "
                f"(written at capacity {cap}) — resume at >= {occ} slots"
            )
        state = state._replace(
            queue=migrate_queue(state.queue, target_cap, cfg.queue_block)
        )
    if budget != target_budget:
        if bool(jnp.any(state.outbox.t != TIME_MAX)):
            raise CheckpointError(
                "checkpoint carries in-flight outbox entries; a different "
                "send budget cannot re-seat them (this never happens for "
                "chunk-boundary checkpoints)"
            )
        state = state._replace(
            outbox=make_empty_outbox(
                state.outbox.t.shape[0], target_budget, state.outbox.count
            )
        )
    # timer wheel: same exactness-gated migration as the queue (slot
    # positions unobservable; live timers must fit the target). Presence
    # was already matched by _shaped_template / the treedef guard.
    w = getattr(state, "wheel", None)
    if w is not None:
        from shadow_tpu.ops.wheel import migrate_wheel, resolve_wheel_block

        target_slots = cfg.wheel_slots
        target_block = resolve_wheel_block(target_slots, cfg.wheel_block)
        if (
            int(w.t.shape[-1]) != target_slots
            or int(w.block) != target_block
        ):
            if int(w.t.shape[-1]) > target_slots and not bool(
                jnp.all(migration_fits(w, target_slots))
            ):
                occ = int(jnp.max(jnp.sum(
                    (w.t != TIME_MAX).astype(jnp.int32), axis=-1
                )))
                raise CheckpointError(
                    f"cannot resume at wheel_slots {target_slots}: the "
                    f"checkpoint holds up to {occ} live timers per host "
                    f"(written at {int(w.t.shape[-1])} slots) — resume "
                    f"at >= {occ} slots"
                )
            state = state._replace(
                wheel=migrate_wheel(w, target_slots, cfg.wheel_block)
            )
    if sim.engine.mesh is not None:
        specs = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(sim.engine.mesh, s),
            sim.engine.state_specs(),
        )
        state = jax.device_put(state, specs)
    return state


def save_checkpoint(path: str, sim) -> str:
    """Snapshot a `Simulation` (modeled sims; hybrid/mixed sims go through
    `save_checkpoint_hybrid`)."""
    arrays, treedef = _dump_leaves(sim.state)
    arrays["__guard__"] = np.frombuffer(
        _fingerprint(sim.engine_cfg, treedef, sim.params).encode(),
        dtype=np.uint8,
    )
    # cross-capacity restore metadata (pressure plane): the secondary
    # guard matches across capacity-shape config changes, and __shape__
    # records the state's ACTUAL shapes (escalation may have regrown
    # them past the configured base) so the loader can rebuild the
    # source template and migrate. Older checkpoints lack both and keep
    # loading through the exact path unchanged.
    arrays["__guard_migrate__"] = np.frombuffer(
        _migration_fingerprint(sim.engine_cfg, treedef, sim.params).encode(),
        dtype=np.uint8,
    )
    arrays["__shape__"] = np.frombuffer(
        json.dumps(_state_shape_meta(sim.state), sort_keys=True).encode(),
        dtype=np.uint8,
    )
    if not path.endswith(".npz"):
        path += ".npz"  # savez appends it anyway; return the real filename
    np.savez_compressed(path, **arrays)
    return path


def load_checkpoint(path: str, sim) -> None:
    """Restore state into a freshly built `Simulation`. The config must
    match exactly EXCEPT the capacity shapes (queue capacity/block, send
    budget — `_MIGRATABLE_CFG_FIELDS`): a checkpoint written at capacity
    C resumes into a sim built at C' through the pressure plane's
    exactness-gated migration ops, refusing only when migration is
    impossible (live events past C', in-flight outbox entries, or a
    queue-layout-kind change)."""
    data = np.load(path)
    _, treedef = jax.tree_util.tree_flatten(sim.state)
    want = _fingerprint(sim.engine_cfg, treedef, sim.params)
    got = bytes(data["__guard__"]).decode()
    meta = None
    if "__shape__" in data.files:
        meta = json.loads(bytes(data["__shape__"]).decode())
    if got == want and (
        meta is None or meta == _state_shape_meta(sim.state)
    ):
        sim.state = _restore_leaves(data, sim.state, sim.engine)
        return
    # exact guard failed (config differs) or shapes differ (escalated
    # checkpoint): try the migration path
    if meta is None or "__guard_migrate__" not in data.files:
        raise CheckpointError(
            "checkpoint does not match this simulation (different config, "
            "model, or engine version; pre-migration checkpoints carry no "
            "shape record to migrate from)"
        )
    want_m = _migration_fingerprint(sim.engine_cfg, treedef, sim.params)
    got_m = bytes(data["__guard_migrate__"]).decode()
    if got_m != want_m:
        raise CheckpointError(
            "checkpoint does not match this simulation (different config, "
            "model, or engine version — beyond the migratable capacity "
            "shapes)"
        )
    template = _shaped_template(sim.state, meta)
    restored = _restore_leaves(data, template, engine=None)
    sim.state = _migrate_restored(restored, sim)


# ---------------------------------------------------------------- ensemble


def ensemble_fingerprint(engine_cfg, state, params, replica_meta) -> str:
    """Guard record for campaign checkpoints: the reconciled EngineConfig,
    the STACKED state treedef (carries R in every leaf shape via the
    treedef + leaf validation), the stacked params digest, and the
    replica metadata (labels/seeds/schedule descriptors from the campaign
    expansion) — so a checkpoint written by one campaign refuses to
    restore into a differently-composed one, even when shapes happen to
    match."""
    _, treedef = jax.tree_util.tree_flatten(state)
    blob = json.dumps(
        {
            "cfg": dataclasses.asdict(engine_cfg),
            "treedef": str(treedef),
            "params": _params_digest(params),
            "replicas": replica_meta,
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def save_ensemble_checkpoint(path: str, state, fingerprint: str) -> str:
    """Snapshot a stacked ensemble SimState (every leaf [R, ...]). The
    campaign supervisor's periodic on-disk durability point — same .npz
    layout as the solo checkpoints, guarded by `ensemble_fingerprint`."""
    arrays, _ = _dump_leaves(state)
    arrays["__guard__"] = np.frombuffer(
        fingerprint.encode(), dtype=np.uint8
    )
    if not path.endswith(".npz"):
        path += ".npz"
    np.savez_compressed(path, **arrays)
    return path


def load_ensemble_checkpoint(path: str, state, fingerprint: str):
    """Restore a stacked ensemble state saved by `save_ensemble_checkpoint`
    into a freshly built campaign of the same composition. `state` is the
    fresh stacked state (tree/shape template); returns the restored one
    (bucket caches rebuilt per replica, like the solo path)."""
    data = np.load(path)
    got = bytes(data["__guard__"]).decode()
    if got != fingerprint:
        raise CheckpointError(
            "ensemble checkpoint does not match this campaign (different "
            "config, replica composition, or engine version)"
        )
    return _restore_leaves(data, state, engine=None)


# ---------------------------------------------------------------- hybrid

from shadow_tpu.simtime import TIME_MAX  # noqa: E402

_SEG_FIELDS = ("flags", "seq", "ack", "wnd", "mss", "wscale",
               "sack_ok", "sack", "src_port", "dst_port")


def _pack_byte_stores(stores) -> tuple[bytes, bytes]:
    """Flatten `HybridSimulation._bytes` (per-gid {key: (window, NetPacket)})
    into (JSON index, concatenated payload buffer). NetPacket/Segment are
    flat int/str/bytes dataclasses, so no object serialization is needed —
    and none is wanted: pickle here would hand code execution to whoever
    can write the checkpoint file (the sha256 guard is data, not auth)."""
    recs, chunks, off = [], [], 0

    def put(b: bytes) -> tuple[int, int]:
        nonlocal off
        chunks.append(b)
        start = off
        off += len(b)
        return start, len(b)

    for gid, store in enumerate(stores):
        for key, (widx, pkt) in store.items():
            rec = {
                "gid": gid, "key": key, "w": widx,
                "sip": pkt.src_ip, "sp": pkt.src_port,
                "dip": pkt.dst_ip, "dp": pkt.dst_port,
                "pr": pkt.proto, "pl": put(pkt.payload),
            }
            if pkt.seg is not None:
                rec["seg"] = {f: getattr(pkt.seg, f) for f in _SEG_FIELDS}
                # pkt.payload mirrors seg.payload for TCP (sockets.py:29-30):
                # store the bytes once and share the slice on restore
                rec["segpl"] = (rec["pl"] if pkt.seg.payload == pkt.payload
                                else put(pkt.seg.payload))
            recs.append(rec)
    return json.dumps(recs).encode(), b"".join(chunks)


def _unpack_byte_stores(idx_json: bytes, buf: bytes, n_hosts: int):
    from shadow_tpu.host.sockets import NetPacket
    from shadow_tpu.tcp.segment import Segment

    stores: list[dict] = [{} for _ in range(n_hosts)]
    for rec in json.loads(idx_json.decode()):
        start, length = rec["pl"]
        payload = buf[start:start + length]
        seg = None
        if "seg" in rec:
            s0, sl = rec["segpl"]
            segpl = payload if [s0, sl] == rec["pl"] else buf[s0:s0 + sl]
            kw = rec["seg"]
            # JSON round-trips tuples as lists; Segment carries SACK blocks
            # as a tuple of (start, end) pairs
            kw["sack"] = tuple(tuple(b) for b in kw.get("sack", ()))
            seg = Segment(payload=segpl, **kw)
        pkt = NetPacket(
            src_ip=rec["sip"], src_port=rec["sp"],
            dst_ip=rec["dip"], dst_port=rec["dp"],
            proto=rec["pr"], payload=payload, seg=seg,
        )
        stores[rec["gid"]][rec["key"]] = (rec["w"], pkt)
    return stores


def _hybrid_fingerprint(hsim, treedef) -> str:
    cfgd = dataclasses.asdict(hsim.engine_cfg)
    # a resumed run legitimately extends the horizon; everything else
    # must match exactly
    cfgd.pop("stop_time", None)
    blob = json.dumps(
        {
            "cfg": cfgd,
            "treedef": str(treedef),
            "params": _params_digest(hsim.params),
            # process specs: same host names running different programs
            # or model args are a different simulation
            "specs": [
                (s.name, s.model, sorted(map(str, s.model_args.items())),
                 str(s.programs))
                for s in hsim.specs
            ],
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def save_checkpoint_hybrid(path: str, hsim) -> str:
    """Snapshot a `HybridSimulation` (VERDICT r3 missing #5): the device
    plane plus the bridge's CPU half — host clocks, event-order counters,
    per-host stat counters, process outcomes, staging cursors, and the
    parked payload byte stores (in-flight device packets may still need
    their bytes at delivery).

    Scope (enforced loudly): every process must have finished ON ITS OWN
    — a daemon reaped by the stop-time shutdown was still alive at the
    horizon (state_at_stop == "running") and its live state is already
    lost, so the snapshot refuses. Pending host events (deliveries or
    timers scheduled past the horizon) likewise refuse: the resume path
    rebuilds host queues empty and cannot reconstruct them."""
    for h in hsim.hosts:
        for p in h.processes.values():
            state = getattr(p.state, "value", p.state)
            at_stop = getattr(p, "state_at_stop", state)
            if state != "zombie" or at_stop != "zombie":
                raise CheckpointError(
                    f"process {p.name} on {h.name} was {at_stop!r} at the "
                    "horizon: hybrid checkpoints require every process to "
                    "have exited on its own (live process state cannot "
                    "snapshot)"
                )
        if h.next_event_time() != TIME_MAX:
            raise CheckpointError(
                f"host {h.name} has events pending past the horizon; "
                "cannot snapshot (they would be lost on resume)"
            )
    if hsim._staged or any(hsim._stage_buf):
        raise CheckpointError("staged sends in flight; cannot snapshot")
    arrays, treedef = _dump_leaves(hsim.state)
    arrays["__guard__"] = np.frombuffer(
        _hybrid_fingerprint(hsim, treedef).encode(), dtype=np.uint8
    )
    bridge = {
        "window_idx": hsim._window_idx,
        "unreach": hsim._unreach,
        "model_pkts_unrouted": hsim._model_pkts_unrouted,
        "hosts": [
            {
                "name": h.name,
                "now": h.now(),
                "seq": h._seq,
                "counters": h.counters,
                "if_counters": h.if_counters,
                "closed_socket_stats": h.closed_socket_stats,
                "heartbeats": h.heartbeats,
                "hb_prev": h._hb_prev,
                "hb_closed_seen": sorted(h._hb_closed_seen),
                "procs": [
                    {
                        "pid": p.pid,
                        "name": getattr(p, "name", "?"),
                        "exit_code": getattr(p, "exit_code", None),
                        "term_signal": getattr(p, "term_signal", None),
                    }
                    for p in h.processes.values()
                ],
            }
            for h in hsim.hosts
        ],
    }
    arrays["__bridge__"] = np.frombuffer(
        json.dumps(bridge).encode(), dtype=np.uint8
    )
    # payload byte stores: packets already injected into the device plane
    # carry only (src, key); the bytes must survive the resume or their
    # eventual capture degrades (echo reconstruction, delivery counters).
    # Serialized WITHOUT pickle (a tampered checkpoint must not be able to
    # execute code on load): flat JSON records + one payload byte buffer.
    recs_json, payload_buf = _pack_byte_stores(hsim._bytes)
    arrays["__bytes_idx__"] = np.frombuffer(recs_json, dtype=np.uint8)
    arrays["__bytes_buf__"] = np.frombuffer(payload_buf, dtype=np.uint8)
    arrays["__send_seq__"] = np.asarray(hsim._send_seq)
    if not path.endswith(".npz"):
        path += ".npz"
    np.savez_compressed(path, **arrays)
    return path


def load_checkpoint_hybrid(path: str, hsim) -> None:
    """Restore into a freshly built `HybridSimulation` of the same config
    (stop_time may differ — that is the point of resuming)."""
    from shadow_tpu.host.process import ProcState

    data = np.load(path, allow_pickle=False)
    if "__bytes_idx__" not in data.files:
        raise CheckpointError(
            "checkpoint uses an older byte-store format; re-create it with "
            "this version (loading would leave the simulation half-restored)"
        )
    _, treedef = jax.tree_util.tree_flatten(hsim.state)
    want = _hybrid_fingerprint(hsim, treedef)
    got = bytes(data["__guard__"]).decode()
    if got != want:
        raise CheckpointError(
            "checkpoint does not match this simulation (different config, "
            "model, or engine version)"
        )
    state = _restore_leaves(data, hsim.state, hsim.engine)
    hsim.state = state._replace(
        done=jnp.zeros((), bool)  # resume the horizon
    )
    bridge = json.loads(bytes(data["__bridge__"]).decode())
    hsim._window_idx = bridge["window_idx"]
    hsim._unreach = bridge["unreach"]
    hsim._model_pkts_unrouted = bridge.get("model_pkts_unrouted", 0)
    hsim._send_seq = np.asarray(data["__send_seq__"]).copy()
    hsim._bytes = _unpack_byte_stores(
        bytes(data["__bytes_idx__"]),
        bytes(data["__bytes_buf__"]),
        len(hsim._bytes),
    )
    by_name = {h["name"]: h for h in bridge["hosts"]}
    for h in hsim.hosts:
        rec = by_name.get(h.name)
        if rec is None:
            raise CheckpointError(f"host {h.name} missing from checkpoint")
        # the freshly built host scheduled its processes' start events:
        # those processes already RAN to completion before the snapshot —
        # drop the pending events and adopt the recorded outcomes instead
        h._q.clear()
        h._cancelled.clear()
        h._now = rec["now"]
        h._seq = rec["seq"]
        h.counters.update(rec["counters"])
        for k, v in rec.get("if_counters", {}).items():
            h.if_counters[k].update(v)
        h.closed_socket_stats = list(rec.get("closed_socket_stats", []))
        h.heartbeats = list(rec.get("heartbeats", []))
        h._hb_prev = rec.get("hb_prev")
        h._hb_closed_seen = set(rec.get("hb_closed_seen", []))
        recs = {pr["pid"]: pr for pr in rec["procs"]}
        for p in h.processes.values():
            pr = recs.get(p.pid)
            if pr is None:
                raise CheckpointError(
                    f"process {p.pid} on {h.name} missing from checkpoint"
                )
            # match each plane's own state type: coroutine processes
            # compare against the ProcState enum (kill() would re-kill a
            # plain string), native ones use strings
            p.state = (
                ProcState.ZOMBIE
                if isinstance(p.state, ProcState)
                else "zombie"
            )
            p.state_at_stop = "zombie"
            p.exit_code = pr["exit_code"]
            p.term_signal = pr["term_signal"]
