"""Simulation checkpoint / resume.

The reference has none (SURVEY.md §5.4 — a dead manager is a dead
simulation; determinism-as-reproducibility is its only recovery story).
On TPU the entire simulation state is a pytree of device arrays, so
snapshotting is a flatten + savez; this is a genuine capability the
rebuild adds on top of reference parity.

Format: one .npz with the flattened SimState leaves plus a guard record
(engine-config fingerprint + treedef repr) so restoring into a mismatched
simulation build fails loudly instead of corrupting silently.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np


class CheckpointError(Exception):
    pass


def _fingerprint(engine_cfg, treedef) -> str:
    blob = json.dumps(
        {"cfg": dataclasses.asdict(engine_cfg), "treedef": str(treedef)},
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def save_checkpoint(path: str, sim) -> str:
    """Snapshot a `Simulation` (modeled sims; the hybrid plane's CPU half
    holds Python coroutines, which don't snapshot — wire format reserved)."""
    leaves, treedef = jax.tree_util.tree_flatten(sim.state)
    arrays = {f"leaf_{i}": np.asarray(jax.device_get(x)) for i, x in enumerate(leaves)}
    arrays["__guard__"] = np.frombuffer(
        _fingerprint(sim.engine_cfg, treedef).encode(), dtype=np.uint8
    )
    if not path.endswith(".npz"):
        path += ".npz"  # savez appends it anyway; return the real filename
    np.savez_compressed(path, **arrays)
    return path


def load_checkpoint(path: str, sim) -> None:
    """Restore state into a freshly built `Simulation` of the same config."""
    data = np.load(path)
    leaves, treedef = jax.tree_util.tree_flatten(sim.state)
    want = _fingerprint(sim.engine_cfg, treedef)
    got = bytes(data["__guard__"]).decode()
    if got != want:
        raise CheckpointError(
            "checkpoint does not match this simulation (different config, "
            "model, or engine version)"
        )
    n = len(leaves)
    new_leaves = []
    for i in range(n):
        arr = data[f"leaf_{i}"]
        ref = leaves[i]
        if arr.shape != ref.shape or arr.dtype != np.asarray(ref).dtype:
            raise CheckpointError(f"leaf {i}: shape/dtype mismatch")
        new_leaves.append(jnp.asarray(arr))
    sim.state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    if sim.engine.mesh is not None:
        specs = jax.tree.map(
            lambda s: jax.sharding.NamedSharding(sim.engine.mesh, s),
            sim.engine.state_specs(),
        )
        sim.state = jax.device_put(sim.state, specs)
