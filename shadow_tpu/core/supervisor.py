"""Crash-resilient run supervisor: retry chunk dispatch from device snapshots.

The simulator must survive its own infrastructure failing mid-run — this
box's documented jaxlib-0.4.37 heap corruption aborts whole runs (CHANGES.md
PR 1/2 env notes), and a production mesh adds preemptions, XLA runtime
errors, and transient dispatch failures on top. The reference has no story
here at all (a dead manager is a dead simulation, SURVEY.md §5.4); PR 4's
snapshot machinery (`core/checkpoint.snapshot_state`/`restore_snapshot` —
donation-safe device copies built for the gear replay loop) already gives
us exact chunk-granular recovery, so the supervisor is a thin, driver-shared
state machine on top:

  RUNNING --dispatch ok--> RUNNING (periodic snapshot + optional on-disk
                                    checkpoint every `snapshot_every_chunks`)
  RUNNING --dispatch raises--> BACKOFF (exponential: base * 2^attempt)
  BACKOFF --> RESTORE (fresh copy of the last good snapshot; a digest
              cross-check against the value recorded at snapshot time
              detects the silent-divergence corruption mode — the
              wrong-digest flavor PR 2's env note documents — instead of
              resuming from poisoned state)
  RESTORE --> RUNNING (the deterministic engine replays the lost chunks
              bit-identically; trace-ring drains self-deduplicate because
              the cursor regresses with the state)
  after `max_retries` failures on one chunk --> ABORT (SupervisorAbort);
  the drivers catch it, keep the last good state, and still export
  sim-stats/trace artifacts for the completed prefix.

Retry exactness: the jitted chunk DONATES its input buffers, so a failed
dispatch may have invalidated them — the supervisor never reuses a failed
input; it always replays from an independent snapshot copy. Because the
engine is deterministic, a retried run's final digest is bit-identical to
an uninterrupted one (tests/test_faults.py + tools/soak.py are the gates).

On-disk checkpoints are written atomically (tmp + os.replace) so a SIGKILL
mid-write — the soak tool injects exactly that — can never leave a
truncated file for the resume path to trip over.
"""

from __future__ import annotations

import os
import signal
import time

import numpy as np


class SupervisorAbort(RuntimeError):
    """Bounded retries exhausted (or restored state failed its digest
    cross-check): the run cannot make progress. The driver still owns the
    last good state and writes artifacts for the completed prefix."""


def state_digest_sig(state) -> tuple[int, ...]:
    """Cheap integrity signature of a SimState: (rounds, xor of the
    per-host event digests) — plus a third element, the dual-digest
    fold, on integrity-sentinel states (compare signatures opaquely, not
    by unpacking). Recorded at snapshot time and re-checked at
    restore time — a mismatch means device memory silently diverged
    between the copy and the replay (the known wrong-digest corruption
    mode), which replaying would only launder into believable results.

    Replica-axis-aware: an ensemble state's `stats.rounds` is [R] (one
    counter per replica) and its digest plane [R, H]; the signature sums
    the rounds and folds the whole plane, so the same supervisor wraps
    solo and campaign dispatches unchanged.

    Integrity-sentinel states (core/integrity.py) carry a SECOND,
    independently-folded digest plane; the signature folds it too, so a
    scribble confined to one digest plane between snapshot and restore
    cannot slip past the cross-check."""
    import jax

    digest = int(np.bitwise_xor.reduce(
        np.asarray(jax.device_get(state.stats.digest)).reshape(-1)
    ))
    rounds = int(np.asarray(jax.device_get(state.stats.rounds)).sum())
    d2 = getattr(state.stats, "digest2", None)
    if d2 is None:
        return rounds, digest
    digest2 = int(np.bitwise_xor.reduce(
        np.asarray(jax.device_get(d2)).reshape(-1)
    ))
    return rounds, digest, digest2


class ChunkSupervisor:
    """Wraps a driver's chunk dispatch in snapshot/retry/abort handling.

    Modeled drivers (`sim.py`, `bench.py`) use periodic snapshots: a failed
    chunk replays every chunk since the last snapshot (deterministic, so
    bit-identical). The hybrid driver (`cosim.py`) passes
    `pre_dispatch_snapshot=True`: its CPU plane advances between device
    dispatches and cannot roll back, so every dispatch snapshots first and
    only the failing dispatch itself retries.

    `save_fn` (optional) writes the on-disk checkpoint after each periodic
    snapshot; it receives a path and must write atomically-renamable
    output there (the drivers pass `core.checkpoint.save_checkpoint`).
    """

    def __init__(
        self,
        *,
        snapshot_every_chunks: int = 1,
        max_retries: int = 3,
        backoff_base_s: float = 0.05,
        checkpoint_path: str | None = None,
        save_fn=None,
        pre_dispatch_snapshot: bool = False,
        log=None,
        memory=None,
        memory_modeled_fn=None,
        wall=None,
    ):
        # optional obs.runtime.WallLedger: snapshot copies and retry
        # (replay) wall re-attribute out of the driver's enclosing
        # dispatch span. Observation only — never consulted.
        self.wall = wall
        # optional obs.memory.MemoryMonitor: sampled at the moment a
        # dispatch FAILS, so the retry log and report() pin each failure
        # against the live HBM picture (an OOM-flavored failure with the
        # allocator near its limit reads very differently from one with
        # headroom to spare). `memory_modeled_fn` () -> int supplies the
        # modeled per-shard bytes where the backend has no allocator
        # stats (obs/memory.modeled_shard_bytes — metadata-only, so it
        # is safe even when the failed dispatch consumed buffers by
        # donation); without it a stat-less failure sample would record
        # zeros and clobber the monitor's last-sample telemetry.
        self.memory = memory
        self._memory_modeled_fn = memory_modeled_fn
        self.failure_memory: dict | None = None
        self.snapshot_every = max(int(snapshot_every_chunks), 1)
        self.max_retries = int(max_retries)
        # clamp: a negative base would make time.sleep raise mid-recovery
        self.backoff_base_s = max(float(backoff_base_s), 0.0)
        self.checkpoint_path = checkpoint_path
        self._save_fn = save_fn
        self.pre_dispatch = bool(pre_dispatch_snapshot)
        self._log = log
        self._snap = None
        self._snap_sig: tuple[int, int] | None = None
        self._chunks_since_snap = 0
        # counters for sim-stats / BENCH
        self.retries = 0  # failed dispatches retried
        self.restores = 0  # snapshot restores performed
        self.snapshots = 0  # device snapshots taken
        self.checkpoints = 0  # on-disk checkpoints written
        self.aborted = False
        self.poisoned = False  # snapshot failed its digest cross-check
        self.last_error: str | None = None

    # ---- snapshots ---------------------------------------------------------

    def _say(self, msg: str):
        if self._log is not None:
            print(f"[supervisor] {msg}", file=self._log)

    def _wall_move(self, to: str, sec: float):
        if self.wall is not None:
            self.wall.reattribute("dispatch", to, sec)

    def _wall_pending_inner(self) -> float:
        """Seconds already claimed by inner instruments (a nested
        pressure controller's snapshot/replay moves, a compile) in the
        open chunk — subtracted so the supervisor's own replay move
        cannot double-count them."""
        if self.wall is None:
            return 0.0
        return sum(
            self.wall.pending_to(n)
            for n in ("compile", "snapshot", "replay")
        )

    def _take_snapshot(self, state):
        from shadow_tpu.core.checkpoint import snapshot_state

        t0 = time.perf_counter()
        self._snap = snapshot_state(state)
        self._snap_sig = state_digest_sig(self._snap)
        self._wall_move("snapshot", time.perf_counter() - t0)
        self._chunks_since_snap = 0
        self.snapshots += 1

    def _write_checkpoint(self):
        if self.checkpoint_path is None or self._save_fn is None:
            return
        tmp = self.checkpoint_path + ".tmp"
        real = self._save_fn(tmp, self._snap)  # save fn may append .npz
        final = self.checkpoint_path
        if real.endswith(".npz") and not final.endswith(".npz"):
            final += ".npz"
        os.replace(real, final)
        self.checkpoints += 1
        self._say(f"checkpoint written: {final}")
        # test/soak hook: die by SIGKILL right after the Nth on-disk
        # checkpoint lands — the hard-crash the resume path must survive
        kill_at = os.environ.get("SHADOW_TPU_TEST_KILL_AT_CHECKPOINT")
        if kill_at and self.checkpoints >= int(kill_at):
            os.kill(os.getpid(), signal.SIGKILL)

    def note_state(self, state):
        """Adopt `state` as the recovery point (drivers call once before
        their loop, and the periodic refresh goes through run_chunk)."""
        self._take_snapshot(state)
        self._write_checkpoint()

    # ---- the retry loop ----------------------------------------------------

    def run_chunk(self, state, dispatch):
        """Run `dispatch(state) -> state` with bounded-retry recovery.

        Returns the new state. Raises SupervisorAbort after max_retries
        consecutive failures of this chunk (or on a restore whose digest
        cross-check fails) — with the supervisor's snapshot as the last
        good state (`.last_good()`)."""
        if self._snap is None or self.pre_dispatch:
            self._take_snapshot(state)
        attempt = 0
        while True:
            t_disp = time.perf_counter()
            inner0 = self._wall_pending_inner()
            try:
                out = dispatch(state)
                # block here so an async dispatch failure surfaces inside
                # the try (jax errors often materialize at the first use
                # of the result, which would otherwise escape the retry)
                import jax

                jax.block_until_ready(out)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:  # XlaRuntimeError, aborts, anything
                from shadow_tpu.core.integrity import IntegrityAbort
                from shadow_tpu.core.pressure import PressureAbort

                if isinstance(e, (PressureAbort, IntegrityAbort)):
                    # a pressure-policy stop or an integrity-sentinel
                    # classification is a deterministic DECISION, not a
                    # transient dispatch failure: retrying would
                    # reproduce it max_retries times and then launder it
                    # into a SupervisorAbort — let the driver's handler
                    # see it instead (the sentinel already did its own
                    # quarantine-and-replay before deciding)
                    raise
                self.last_error = f"{type(e).__name__}: {e}"
                if self.memory is not None:
                    try:
                        modeled = (
                            self._memory_modeled_fn()
                            if self._memory_modeled_fn is not None else None
                        )
                        self.memory.sample(modeled_bytes=modeled)
                        self.failure_memory = {
                            "bytes_in_use": list(self.memory.last),
                            "headroom_bytes": self.memory.headroom_bytes(),
                        }
                    except Exception:  # telemetry must never mask the
                        pass  # failure being handled
                attempt += 1
                self.retries += 1
                if attempt > self.max_retries:
                    self.aborted = True
                    self._say(
                        f"giving up after {self.max_retries} retries: "
                        f"{self.last_error}"
                    )
                    raise SupervisorAbort(
                        f"chunk dispatch failed {attempt} times; last: "
                        f"{self.last_error}"
                    ) from e
                delay = self.backoff_base_s * (2 ** (attempt - 1))
                self._say(
                    f"dispatch failed ({self.last_error}); retry "
                    f"{attempt}/{self.max_retries} in {delay:.2f}s"
                )
                # the backoff sleep is idle time, not replay work — it
                # stays in the enclosing dispatch span so the replay
                # share measures only the restore + re-dispatch cost
                time.sleep(delay)
                t_rec = time.perf_counter()
                state = self._restore_checked()
                self._wall_move("replay", time.perf_counter() - t_rec)
                continue
            if attempt > 0:
                # a retried dispatch IS the replay (minus whatever inner
                # instruments — compile, a nested controller's snapshot
                # or replay — already claimed from this interval)
                self._wall_move(
                    "replay",
                    (time.perf_counter() - t_disp)
                    - (self._wall_pending_inner() - inner0),
                )
            self._chunks_since_snap += 1
            if not self.pre_dispatch and (
                self._chunks_since_snap >= self.snapshot_every
            ):
                self._take_snapshot(out)
                self._write_checkpoint()
            return out

    def _restore_checked(self):
        from shadow_tpu.core.checkpoint import restore_snapshot

        restored = restore_snapshot(self._snap)
        sig = state_digest_sig(restored)
        if sig != self._snap_sig:
            self.aborted = True
            self.poisoned = True
            raise SupervisorAbort(
                f"snapshot digest cross-check failed (recorded "
                f"{self._snap_sig}, restored {sig}): device state silently "
                f"diverged — refusing to replay from poisoned memory"
            )
        self.restores += 1
        # progress rewound to the snapshot point: restart the snapshot
        # cadence from zero, or the first replayed chunk would trip the
        # `>= snapshot_every` threshold early (extra HBM copy + on-disk
        # write per recovery)
        self._chunks_since_snap = 0
        return restored

    def last_good(self):
        """A fresh copy of the last good snapshot (for the graceful-abort
        path: report/export the completed prefix, not the failed state).
        Returns None once the snapshot failed its digest cross-check —
        handing out state from poisoned memory would launder the very
        corruption the check exists to catch."""
        from shadow_tpu.core.checkpoint import restore_snapshot

        if self.poisoned or self._snap is None:
            return None
        return restore_snapshot(self._snap)

    def poisoned_state(self):
        """Copy of the refused snapshot for the graceful-abort EXPORT path
        only. When the cross-check fails, the driver's in-hand state may
        hold buffers the failed dispatch already consumed by donation —
        exporting artifacts from it would crash on deleted arrays. The
        refused copy is at least materializable, and the artifacts' own
        top-level `poisoned: true` flag keeps its counters from reading as
        a trustworthy prefix. Returns None when there is no snapshot or
        the supervisor is not poisoned (use `last_good()` then)."""
        from shadow_tpu.core.checkpoint import restore_snapshot

        if not self.poisoned or self._snap is None:
            return None
        return restore_snapshot(self._snap)

    def abort_export_state(self):
        """State the driver should export artifacts from after a graceful
        abort: a fresh copy of the last good snapshot, or — when that
        snapshot failed its digest cross-check — the refused copy. The
        driver's in-hand state may hold buffers the failed dispatch
        already consumed by donation (exporting from it would crash on
        deleted arrays), and `report()`'s `poisoned` flag keeps a refused
        snapshot's counters from reading as a trustworthy prefix. Returns
        None only when no snapshot was ever taken — then the in-hand
        state is all there is."""
        good = self.last_good()
        return good if good is not None else self.poisoned_state()

    def report(self) -> dict:
        """JSON-able summary for sim-stats / BENCH rows."""
        return {
            "retries": self.retries,
            "restores": self.restores,
            "snapshots": self.snapshots,
            "checkpoints": self.checkpoints,
            "snapshot_every_chunks": self.snapshot_every,
            "aborted": self.aborted,
            **({"poisoned": True} if self.poisoned else {}),
            **({"last_error": self.last_error} if self.last_error else {}),
            **(
                {"failure_memory": self.failure_memory}
                if self.failure_memory else {}
            ),
        }
