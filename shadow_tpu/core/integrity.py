"""Integrity sentinel: in-jit invariant guards + host-side SDC classification.

This box's own history (CHANGES.md PR 9/10 env notes) documents silent
data corruption waves — device buffers scribbled with pointer garbage,
digests flipping with no crash — and until now every defense was
after-the-fact: the supervisor's digest cross-check fires only at
snapshot boundaries, and the subprocess classifiers only see a run's
final artifacts. The sentinel moves detection INTO the jitted round
body: a set of conservation laws the state must satisfy on every round
regardless of workload, compiled in only when `integrity.enabled` is on
(default OFF traces zero sentinel code — the default echo/phold jaxpr
fingerprints are byte-unchanged, the gate tests/test_integrity.py pins).

The invariant set (bit positions in the per-shard `stats.iv_mask` lane;
every check is unconditional — an invariant that a legal engine
trajectory could violate would turn the sentinel into a false-abort
machine, so each one's derivation is written out at the check site in
core/engine.py `_integrity_round_check`):

  IV_TIME     safe-window/time monotonicity: the new window never
              regresses past the committed time, and no queue slot ever
              holds a time below the round-entry global minimum.
  IV_EC       event-class reconciliation (network observatory on):
              ec_timer + ec_pkt + ec_app == events — the netobs
              reconciliation CHECK promoted to a hard in-round guard.
  IV_QFILL    bucketed-queue occupancy agreement: the incrementally
              maintained per-block fill caches sum to the slab's true
              non-empty slot count.
  IV_COUNTER  counter monotonicity: event/drop/fault counters never
              decrease within a round and never go negative.
  IV_OUTBOX   outbox bounds: no host stages more than the send budget in
              a round, cursors stay non-negative, the count word stays
              inside [0, H x B].
  IV_DIGEST   dual-digest virginity: a host with zero executed events
              still carries both digest lanes' initial values (the
              second, independently-folded lane makes a scribble on the
              digest plane itself detectable — see classify_digest_pair).

Detection feeds the snapshot-replay machinery PR 8 built
(core/pressure.ResilienceController): the chunk while_loop aborts
mesh-uniformly at the first violating round (same mechanism as
gear_shed/pressure), the controller restores the pre-chunk snapshot and
replays; a violation that REPRODUCES at the same round with the same
bitmask is deterministic — a real engine bug — and raises
`IntegrityAbort` naming the invariant, round, and shard, with last-good
artifacts exported poisoned-style. A violation that does NOT reproduce
is transient SDC: counted in sim-stats `integrity{transients,replays}`,
logged, and the run continues — the documented scribble waves turn from
silent poison into counted, survived events.
"""

from __future__ import annotations

from typing import Any

import numpy as np

# invariant bit positions (stats.iv_mask); append-only — recorded masks
# in logs/artifacts are read by these positions
IV_TIME = 0
IV_EC = 1
IV_QFILL = 2
IV_COUNTER = 3
IV_OUTBOX = 4
IV_DIGEST = 5

IV_NAMES = (
    "time_monotonic",
    "event_class_reconcile",
    "queue_fill_cache",
    "counter_monotonic",
    "outbox_budget",
    "dual_digest_virgin",
)

# second digest lane's fold constants (core/engine._digest_update2):
# deliberately DIFFERENT offset basis, mix multipliers, and fold prime
# from the primary FNV-1a fold so a scribble cannot satisfy both lanes
# by accident — the planes share no constants.
DIGEST2_OFFSET = 0x9AE16A3B2F90404F  # (cityhash k2)
# distinct ODD fold multiplier (the PCG-64 LCG constant): an even
# multiplier would shift one bit of history out of the fold per event,
# leaving digest2 a function of only a host's last ~63 events — which
# would let genuinely divergent trajectories misclassify as
# "digest-plane" scribbles (classify_digest_pair's central guarantee)
DIGEST2_PRIME = 0x5851F42D4C957F2D


class IntegrityAbort(RuntimeError):
    """A deterministic invariant violation (reproduced at the same round
    with the same bitmask across a snapshot replay), or a hybrid-plane
    violation the bridge cannot replay-classify. The driver exports
    last-good artifacts poisoned-style: the violating attempt's state is
    discarded and the report names the invariant, round, and shard."""


def mask_names(mask: int) -> list[str]:
    """The invariant names a violation bitmask encodes."""
    out = [name for bit, name in enumerate(IV_NAMES) if mask & (1 << bit)]
    if mask >> len(IV_NAMES):
        out.append(f"unknown_bits=0x{mask >> len(IV_NAMES):x}")
    return out


def violation_total(state) -> int:
    """The psum'd global cumulative violation count, read host-side
    (uniform across shards; max guards against a scribbled replica)."""
    import jax

    lane = getattr(state.stats, "integrity", None)
    if lane is None:
        return 0
    return int(np.asarray(jax.device_get(lane)).max())


def violation_signature(state) -> tuple:
    """Canonical (shard, round, mask) tuple per violating shard — the
    reproduction key the quarantine-and-replay classifier compares: a
    replayed chunk reproducing the SAME signature is deterministic, a
    differing/absent one is transient SDC."""
    import jax

    masks = np.asarray(jax.device_get(state.stats.iv_mask))
    first_round = np.asarray(jax.device_get(state.stats.iv_round))
    return tuple(
        (int(shard), int(first_round[shard]), int(masks[shard]))
        for shard in range(masks.shape[0])
        if int(masks[shard]) != 0
    )


def describe_signature(sig: tuple) -> str:
    """Human-readable violation naming: invariant(s), round, shard."""
    if not sig:
        return "no violating shard recorded"
    return "; ".join(
        f"shard {shard}: invariant(s) {'+'.join(mask_names(mask))} "
        f"(mask 0x{mask:x}) at round {rnd}"
        for shard, rnd, mask in sig
    )


def raise_if_violated(state, baseline: int = 0, context: str = ""):
    """Loud stop on any violation past `baseline` — the hybrid driver's
    path (the CPU plane cannot roll back, so a violation there is
    unclassifiable by replay and treated as deterministic)."""
    total = violation_total(state)
    if total <= baseline:
        return
    sig = violation_signature(state)
    prefix = f"{context}: " if context else ""
    raise IntegrityAbort(
        f"integrity: {prefix}invariant violated ({total - baseline} new "
        f"violation(s)) — {describe_signature(sig)}"
    )


def classify_digest_pair(
    primary_a: int, dual_a: Any, primary_b: int, dual_b: Any
) -> str:
    """Classify two completed runs' (primary, dual) digest folds:

      "clean"        — both lanes agree: same trajectory.
      "digest-plane" — primary lanes disagree but the independently-
                       folded dual lanes agree: the trajectories were
                       identical and one PRIMARY digest plane was
                       scribbled (the SDC flavor a single digest cannot
                       see — the wrong-digest corruption mode the
                       CHANGES.md env notes document).
      "divergent"    — the dual lanes disagree: the trajectories really
                       differed (primary agreement with dual divergence
                       is the mirror scribble on a dual plane).

    Dual folds may be None (sentinel off / old artifacts): then only
    "clean"/"divergent" are distinguishable from the primary lane."""
    if dual_a is None or dual_b is None:
        return "clean" if primary_a == primary_b else "divergent"
    if int(dual_a) == int(dual_b):
        return "clean" if primary_a == primary_b else "digest-plane"
    return "divergent"
