"""Shared jax-version compatibility shims for the device plane.

One home for the API-drift adapters every driver needs, so call sites
(`core/engine.py`, `cosim.py`) import ONE public helper instead of
reaching into another module's privates — `cosim.py` used to import
`engine._shard_map` at two call sites, which coupled the bridge to an
engine-internal name.
"""

from __future__ import annotations

import jax


def shard_map_compat(f, mesh, in_specs, out_specs):
    """jax.shard_map with a fallback for older jax (< 0.5: the API lives
    in jax.experimental.shard_map and the replication check is
    `check_rep`)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )
