"""PDES core: the round-based conservative event loop on device.

TPU recast of the reference's L3-L5 (SURVEY.md §1): Controller window
computation (src/main/core/controller.rs:88-112), Manager scheduling loop
(manager.rs:392-478), per-thread min-next-event reduction (manager.rs:459-464
→ lax.pmin over the mesh), and Host::execute's event dispatch
(host.rs:809-864 → vectorized microsteps).
"""

from shadow_tpu.core.engine import (
    Engine,
    EngineConfig,
    EngineParams,
    SimState,
    Stats,
    Outbox,
)
from shadow_tpu.core.faults import FaultParams, FaultSchedule, compile_faults
from shadow_tpu.core.supervisor import ChunkSupervisor, SupervisorAbort
from shadow_tpu.core.ensemble import (
    EnsembleEngine,
    bisect_divergence,
    build_ensemble,
)

__all__ = [
    "ChunkSupervisor",
    "Engine",
    "EngineConfig",
    "EngineParams",
    "EnsembleEngine",
    "FaultParams",
    "FaultSchedule",
    "Outbox",
    "SimState",
    "Stats",
    "SupervisorAbort",
    "bisect_divergence",
    "build_ensemble",
    "compile_faults",
]
