"""The ensemble plane: vmapped replica campaigns over one jitted round loop.

PR 5 made every scenario a pure function of (seed, fault schedule); this
module exploits that purity at the program level. BASELINE.md r6 measured
~83% of the CPU microstep as full-width handler dispatch — per-dispatch
cost that is IDENTICAL work for every independent replica of a workload.
Stacking R replicas' variable state/param leaves along a leading axis and
`jax.vmap`-ing the chunk body (`core/engine._run_chunk`) into one jitted
program advances R seed sweeps / fault-schedule sweeps / A/B config pairs
per dispatch, amortizing that fixed cost across the whole campaign — the
paper's "run many experiments over a simulated network" use case at
hardware speed (Rain's microsecond-scale-workload economics in PAPERS.md
is the same argument: keep the hot loop dense, move orchestration off it).

Exactness contract (tests/test_ensemble.py is the gate): replica r of a
vmapped run is BIT-IDENTICAL — digest, event count, every drop and fault
counter — to a solo run of the same (seed, fault schedule, params).
Nothing crosses the replica axis: vmap adds a batch dimension to every
per-replica op, `lax.while_loop`'s batching rule runs the loop while ANY
replica's condition holds and select-masks finished replicas' carries
(a frozen lane is exactly a solo run that stopped), and all cross-host
reductions stay within a replica. Leaves identical across replicas
(routing tables, static model params) are NOT stacked — they broadcast
via `in_axes=None`, so a campaign's HBM cost is R x (state + varying
params), not R x everything.

What may vary per replica: array VALUES only — RNG seeds, model
state/params built from different seeds or model args, fault schedules
(padded to common static dims, see `reconcile_fault_statics`), numeric
EngineParams leaves (latencies, loss, token buckets). What may NOT vary:
anything the trace specializes on — every EngineConfig static (shapes,
queue layout, K, exchange, policies). `build_ensemble` enforces this
loudly.

Scope this round: world=1 only (a replica axis on top of a device mesh
is a 2-D mesh program — a later PR). `EnsembleEngine` raises ConfigError
for world > 1.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.config.options import ConfigError
from shadow_tpu.core.checkpoint import restore_snapshot, snapshot_state
from shadow_tpu.core.engine import (
    EngineConfig,
    EngineParams,
    SimState,
    _run_chunk,
)
from shadow_tpu.core.faults import FaultParams, LAT_SCALE
from shadow_tpu.simtime import TIME_MAX

# fields EngineConfig may legitimately differ in across replicas BEFORE
# reconciliation: the fault static dims, which reconcile_fault_statics
# pads to a common maximum (crash-window padding with never-firing
# TIME_MAX windows is exact; see the loss-window rule below), and the
# restart-queue policy, which is value-inert for replicas without crash
# windows and must merely agree among those WITH them (checked there)
_RECONCILED_FIELDS = (
    "fault_crash_windows",
    "fault_loss_windows",
    "fault_queue_clear",
)


def tree_stack(trees: Sequence[Any]):
    """Stack R same-structure pytrees along a new leading replica axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def tree_index(tree, r: int):
    """Extract replica r's slice of a stacked pytree (host-side view)."""
    return jax.tree.map(lambda a: a[r], tree)


def _leaves_equal(*xs) -> bool:
    a0 = np.asarray(xs[0])
    return all(np.array_equal(a0, np.asarray(x)) for x in xs[1:])


_BCAST = object()  # per-leaf marker: identical across replicas, broadcast


def stack_params(params_list: Sequence[EngineParams]):
    """(stacked_params, in_axes_tree): leaves identical across replicas
    stay single-copy and broadcast (`in_axes=None`); differing leaves are
    stacked along axis 0. The equality check runs host-side ONCE per leaf
    at build time (the marker tree below feeds both outputs — `None`
    itself cannot carry through `tree.map`, it reads as an empty
    subtree) — campaign builds are seconds-scale, and the payoff is that
    the replicated routing tables (the dominant EngineParams bytes on
    multi-node graphs) are never duplicated R times in HBM."""
    marks = jax.tree.map(
        lambda *xs: _BCAST if _leaves_equal(*xs) else 0, *params_list
    )
    stacked = jax.tree.map(
        lambda m, *xs: xs[0] if m is _BCAST else jnp.stack(xs),
        marks,
        *params_list,
    )
    axes = jax.tree.map(lambda m: None if m is _BCAST else 0, marks)
    return stacked, axes


# ------------------------------------------------------ fault reconciliation


def _pad_fault_params(
    fp: FaultParams | None, w: int, l: int, num_hosts: int
) -> FaultParams | None:
    """Pad one replica's fault arrays to common static dims (W crash
    windows, L loss windows). Padding is EXACT by construction:

      crash windows — a [TIME_MAX, TIME_MAX) window contains no time, so
      the down mask, resume floor, and every hold/clear decision are
      unchanged; a replica with no crashes at all gets an all-TIME_MAX
      [H, W] pair, and the traced hold/clear plumbing is value-inert for
      it (resume floor 0, no down event ever).

      loss windows — a [0, 0) window is never active and pads with
      loss 0 / latency x1.0, so `window_effects`' max-reductions are
      unchanged. Crucially the per-send fault-loss RNG DRAW count depends
      only on L > 0 (one draw per send), not on L's value — so padding
      L upward never shifts a replica's RNG stream.
    """
    if w == 0 and l == 0:
        return None
    down_t = up_t = win_start = win_end = win_loss = win_lat = None
    if w:
        if fp is not None and fp.down_t is not None:
            have = fp.down_t.shape[1]
            if have < w:
                pad = jnp.full((num_hosts, w - have), TIME_MAX, jnp.int64)
                down_t = jnp.concatenate([fp.down_t, pad], axis=1)
                up_t = jnp.concatenate([fp.up_t, pad], axis=1)
            else:
                down_t, up_t = fp.down_t, fp.up_t
        else:
            down_t = jnp.full((num_hosts, w), TIME_MAX, jnp.int64)
            up_t = jnp.full((num_hosts, w), TIME_MAX, jnp.int64)
    if l:
        # the L > 0 mixing rule is enforced upstream; here every replica
        # has at least one real window, so only upward padding remains
        have = fp.win_start.shape[0]
        if have < l:
            pad = l - have
            win_start = jnp.concatenate(
                [fp.win_start, jnp.zeros((pad,), jnp.int64)]
            )
            win_end = jnp.concatenate(
                [fp.win_end, jnp.zeros((pad,), jnp.int64)]
            )
            win_loss = jnp.concatenate(
                [fp.win_loss, jnp.zeros((pad,), jnp.float32)]
            )
            win_lat = jnp.concatenate(
                [fp.win_lat, jnp.full((pad,), LAT_SCALE, jnp.int64)]
            )
        else:
            win_start, win_end = fp.win_start, fp.win_end
            win_loss, win_lat = fp.win_loss, fp.win_lat
    return FaultParams(down_t, up_t, win_start, win_end, win_loss, win_lat)


def reconcile_fault_statics(
    cfgs: Sequence[EngineConfig], params_list: Sequence[EngineParams]
) -> tuple[EngineConfig, list[EngineParams]]:
    """One EngineConfig + per-replica padded params for a mixed-schedule
    campaign. Crash-window dims pad freely (0 -> W is exact: the hold
    floor of a never-down host is 0 and clear mode never fires, with no
    RNG consequences). Loss windows may NOT mix presence: L > 0 traces
    one extra RNG draw per send into the program, so a replica with no
    loss windows can never be bit-identical to its solo build inside a
    program that has them — the campaign must be split, or the replica
    given a real (possibly far-future) window explicitly."""
    base = cfgs[0]
    for i, c in enumerate(cfgs[1:], start=1):
        norm = {f: 0 for f in _RECONCILED_FIELDS}
        if dataclasses.replace(c, **norm) != dataclasses.replace(base, **norm):
            diffs = [
                f.name
                for f in dataclasses.fields(base)
                if f.name not in _RECONCILED_FIELDS
                and getattr(c, f.name) != getattr(base, f.name)
            ]
            raise ConfigError(
                f"ensemble replicas must share every EngineConfig static "
                f"(replica {i} differs from replica 0 in {diffs}); "
                f"per-replica variation is array VALUES only — seeds, "
                f"fault schedules, numeric params"
            )
    ls = [c.fault_loss_windows for c in cfgs]
    if any(ls) and not all(ls):
        raise ConfigError(
            "ensemble replicas must agree on loss-window PRESENCE: "
            "fault_loss_windows > 0 traces one extra RNG draw per send, "
            "so mixing faulty and fault-free link schedules in one "
            "vmapped program would shift the fault-free replicas' RNG "
            "streams off their solo runs — split the campaign, or give "
            "every replica at least one loss window"
        )
    w = max(c.fault_crash_windows for c in cfgs)
    l = max(ls)
    clears = {
        c.fault_queue_clear for c in cfgs if c.fault_crash_windows > 0
    }
    if len(clears) > 1:
        raise ConfigError(
            "ensemble replicas with crash windows must share one "
            "restart_queue policy (hold vs clear is a trace-time static)"
        )
    clear = clears.pop() if clears else base.fault_queue_clear
    common = dataclasses.replace(
        base,
        fault_crash_windows=w,
        fault_loss_windows=l,
        fault_queue_clear=clear if w else base.fault_queue_clear,
    )
    h = common.num_hosts
    padded = [
        p._replace(faults=_pad_fault_params(p.faults, w, l, h))
        for p in params_list
    ]
    return common, padded


# ------------------------------------------------------------ the engine


class EnsembleEngine:
    """R replicas of one EngineConfig advanced by a single vmapped chunk
    program. Built via `build_ensemble` (which reconciles configs and
    stacks the leaves); `run_chunk(state)` then advances every replica
    one chunk per dispatch, donating the stacked state exactly like the
    solo engine. Per-replica stats/digests stay separate end-to-end —
    every Stats leaf simply grows a leading [R] axis."""

    def __init__(self, cfg: EngineConfig, model):
        if cfg.world != 1:
            raise ConfigError(
                f"the ensemble plane runs world=1 this round (got world="
                f"{cfg.world}): a replica axis over a device mesh is a 2-D "
                f"mesh program — shard the campaign across processes, or "
                f"drop general.parallelism to 1"
            )
        self.cfg = cfg
        self.model = model
        self.num_replicas = 0
        self._params = None
        self._chunk = None

    def build(
        self,
        states: Sequence[SimState],
        params_list: Sequence[EngineParams],
    ) -> SimState:
        """Stack R per-replica (state, params) pairs and jit the vmapped
        chunk. Returns the stacked SimState (every leaf [R, ...])."""
        if len(states) != len(params_list) or not states:
            raise ConfigError("ensemble needs >= 1 (state, params) pair")
        self.num_replicas = len(states)
        self._params, axes = stack_params(params_list)
        chunk = functools.partial(_run_chunk, self.cfg, self.model, None)
        self._chunk = jax.jit(
            jax.vmap(chunk, in_axes=(0, axes)), donate_argnums=0
        )
        return tree_stack(states)

    def attach_compile_ledger(self, ledger):
        """Runtime-observatory hook (obs/runtime.CompileLedger — same
        contract as core.Engine.attach_compile_ledger): wrap the vmapped
        chunk program so its cold compile is recorded with hit counts.
        Host-side observation only; attach after `build`, before the
        first dispatch."""
        if ledger is not None and self._chunk is not None:
            self._chunk = ledger.instrument(
                "ensemble", f"R={self.num_replicas}", "cold_start",
                self._chunk,
            )

    def run_chunk(self, state: SimState) -> SimState:
        """Advance every replica one chunk (frozen replicas — done, or
        out of rounds — keep their carries bit-exactly via the while-loop
        batching select)."""
        return self._chunk(state, self._params)


def build_ensemble(
    model,
    replicas: Sequence[tuple[EngineConfig, SimState, EngineParams]],
) -> tuple[EnsembleEngine, SimState]:
    """(EnsembleEngine, stacked state) from per-replica built sims.

    Each tuple is one replica's (engine config, initialized SimState,
    initialized EngineParams) — the exact objects `Engine.init_state`
    returns for a solo run, so a campaign replica IS its solo run, just
    stacked. Fault statics are reconciled (padded) here; every other
    config static must already match."""
    cfgs = [c for c, _, _ in replicas]
    states = [s for _, s, _ in replicas]
    params_list = [p for _, _, p in replicas]
    common, padded = reconcile_fault_statics(cfgs, params_list)
    ens = EnsembleEngine(common, model)
    stacked = ens.build(states, padded)
    return ens, stacked


# ------------------------------------------------------------ ledger helpers


def replica_digest_arrays(state: SimState, num_real: int | None = None):
    """Per-replica per-host digest planes, np.uint64[R, n]."""
    d = np.asarray(jax.device_get(state.stats.digest))
    return d[:, : (num_real or d.shape[1])]


def replica_digest_sigs(state: SimState, num_real: int | None = None):
    """Per-replica xor-folded digest signatures, np.uint64[R] — the
    cheap per-chunk ledger entry (full-array comparison remains the
    authoritative divergence test; xor is a summary, not a proof)."""
    d = replica_digest_arrays(state, num_real)
    return np.bitwise_xor.reduce(d, axis=1)


def replica_ledger(
    state: SimState, num_real: int | None = None, labels=None
) -> list[dict]:
    """Per-replica digest-ledger rows: the solo `stats_report` counters,
    one dict per replica, read from the stacked state in one device_get."""
    s = jax.device_get(state.stats)
    qdrop = np.asarray(jax.device_get(state.queue.dropped))
    now = np.asarray(jax.device_get(state.now), np.int64)
    done = np.asarray(jax.device_get(state.done))
    r_count = np.asarray(s.digest).shape[0]
    n = num_real or np.asarray(s.digest).shape[1]
    rows = []
    for r in range(r_count):
        def tot(field):
            return int(np.asarray(getattr(s, field))[r, :n].sum())

        rows.append(
            {
                "replica": r,
                **({"label": labels[r]} if labels else {}),
                "digest": f"{int(np.bitwise_xor.reduce(np.asarray(s.digest)[r, :n])):016x}",
                "rounds": int(np.asarray(s.rounds)[r]),
                "done": bool(done[r]),
                "simulated_seconds": float(now[r]) / 1e9,
                "events_processed": tot("events"),
                "packets_sent": tot("pkts_sent"),
                "packets_delivered": tot("pkts_delivered"),
                "packets_lost": tot("pkts_lost"),
                "packets_unreachable": tot("pkts_unreachable"),
                "packets_codel_dropped": tot("pkts_codel_dropped"),
                "packets_budget_dropped": tot("pkts_budget_dropped"),
                "queue_overflow_dropped": int(qdrop[r, :n].sum()),
                "faults_dropped": tot("faults_dropped"),
                "faults_delayed": tot("faults_delayed"),
                "monotonic_violations": tot("monotonic_violations"),
                "microsteps": int(np.asarray(s.microsteps)[r].sum()),
            }
        )
    return rows


# ------------------------------------------------------------ bisection


def pair_digests_equal(
    state: SimState, pair: tuple[int, int], num_real: int | None = None
) -> bool:
    """Full-array digest equality between two replicas of a stacked
    state — the authoritative expected-identical check (per-host arrays,
    not the xor fold, so a compensating two-host collision cannot hide a
    divergence)."""
    d = replica_digest_arrays(state, num_real)
    i, j = pair
    return bool(np.array_equal(d[i], d[j]))


def bisect_divergence(
    run_chunk,
    state0: SimState,
    pair: tuple[int, int],
    *,
    hi: int,
    num_real: int | None = None,
    log=None,
) -> int:
    """First chunk (1-based) after which replicas `pair` carry different
    digests, by binary search over chunk boundaries from a pre-run device
    snapshot.

    Preconditions: the pair's digests are EQUAL in `state0` (chunk 0) and
    DIVERGENT after `hi` chunks. Invariant exploited: the engine is
    deterministic, so re-running k chunks from the chunk-0 snapshot
    reproduces the original prefix bit-exactly, and once the pair's
    per-host digest arrays differ they never re-converge (each replica's
    digest is a rolling fold over its own — now different — event
    history; equality after divergence would need a fold collision
    across every host simultaneously). The search keeps a device
    snapshot at the highest chunk known-equal, so each probe replays
    only the gap from there: total replay work is <= 2 x hi chunks, and
    the state machine is

        lo (snapshot, pair equal) --run (mid-lo) chunks--> probe(mid)
        probe equal     -> adopt: lo = mid, snapshot advances
        probe divergent -> hi = mid
        until hi - lo == 1; answer = hi.

    `run_chunk` may donate its input (the probes run on fresh
    `restore_snapshot` copies). Returns the 1-based index of the first
    divergent chunk."""
    if not pair_digests_equal(state0, pair, num_real):
        raise ValueError(
            f"bisect_divergence: pair {pair} already divergent at chunk 0"
        )
    lo, hi_k = 0, int(hi)
    snap_lo = snapshot_state(state0)
    probes = 0
    while hi_k - lo > 1:
        mid = (lo + hi_k) // 2
        st = restore_snapshot(snap_lo)
        for _ in range(mid - lo):
            st = run_chunk(st)
        probes += 1
        if pair_digests_equal(st, pair, num_real):
            lo = mid
            snap_lo = snapshot_state(st)
        else:
            hi_k = mid
        if log is not None:
            print(
                f"[bisect] pair {pair}: chunk {mid} "
                f"{'equal' if lo == mid else 'divergent'} "
                f"(window now ({lo}, {hi_k}])",
                file=log,
            )
    return hi_k
