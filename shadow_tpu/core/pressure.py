"""Pressure plane: drop-free operation under capacity pressure.

Every fixed-shape lane in the engine sheds under pressure — per-host
event queues count push overflow into `queue.dropped` (ops/events.py),
the exchange merge and the alltoall blocks shed into `queue.dropped` /
`stats.a2a_shed`, and the per-host send budget drops into
`stats.pkts_budget_dropped`. At the host counts ROADMAP item 1 targets,
silent capacity pressure becomes the dominant failure mode, while the
reference Shadow never drops an event. This module makes pressure a
POLICY instead of a fate (`pressure:` config block, options.py):

  drop      — today's semantics (default). No pressure code is traced;
              the program is bit-identical to the pre-pressure engine.
  escalate  — drop-free by construction. The chunk while_loop aborts
              uniformly across the mesh at the first round where any
              host would drop (the psum'd `stats.pressure` total, same
              mechanism as `stats.gear_shed`); the driver restores the
              pre-chunk device snapshot, migrates the state to a grown
              shape — queue capacity C -> C' via the exactness-gated
              `ops.events.migrate_queue`, and/or a wider outbox B' —
              and replays the chunk. Accepted chunks carry ZERO drops,
              so the accepted trajectory is bit-identical to a run
              launched at the final shape (with the valve pins
              `Engine.run_chunk_resized` documents).
  abort     — loud failure. The same first-drop abort stops the run at
              the dropping round; the driver exports honest artifacts
              (the state INCLUDING the drop, flagged `pressure.aborted`)
              instead of silently shedding for the rest of the horizon.

`ResilienceController` below generalizes `core/gears.run_adaptive_chunk`
into ONE snapshot-replay loop arbitrating both axes: merge-gear shifts
(a too-narrow gear is a transient perf choice — replay one gear up) and
capacity regrows (a too-small shape is a correctness hazard — replay at
a grown shape). One cached jitted program exists per (gear, capacity,
budget) triple (`Engine.run_chunk_resized`), the ladders are bounded
(`max_capacity` is the HBM guard), and regrow is also PROACTIVE: at
chunk boundaries the always-on `stats.q_occ_hwm` / `stats.outbox_hwm`
high-waters trigger a grow BEFORE anything drops, so steady pressure
costs one migration, not a replayed chunk.

The hierarchical exchange rides both axes for free: an escalated outbox
width B' flows through `Engine.resized_cfg`'s dataclasses.replace, so the
auto inter-shard block size (`EngineConfig.hier_block_size`, derived from
hosts_per_shard x effective_gear_cols) re-derives at the regrown shape —
a wider outbox also widens the alltoall blocks, and the grown program
stays shed-free for the same traffic that grew it. An EXPLICIT a2a_block
is pinned across regrows (explicit settings always win); if a regrow
outgrows it, the block overflow stays loud via the usual gear_shed /
a2a_shed accounting rather than silently resizing the wire format.

Graceful degradation when escalation itself fails: a grown program's
compile/dispatch dying of RESOURCE_EXHAUSTED / XlaRuntimeError marks
that rung (and everything above it) unusable and falls back one rung;
when cornered — drops persist but no usable rung remains — the
controller raises `PressureAbort` with the last good pre-chunk snapshot
kept, so the drivers still export sim-stats/trace artifacts for the
completed prefix (the PR 5 supervisor's graceful-abort posture).

Determinism note (shadowlint control-plane rules apply): decisions here
read CONCRETE device counters between dispatches and feed deterministic
replay — no wall-clock, no RNG. A controller bug can cost replays or
migrations, never correctness: accepted chunks are gated by the in-jit
zero-drop condition, not by anything this module computes.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from shadow_tpu.core.integrity import (
    IntegrityAbort,
    describe_signature,
    violation_signature,
    violation_total,
)

DEFAULT_MAX_CAPACITY_FACTOR = 8  # auto max_capacity = 8x the base slab
DEFAULT_MAX_OUTBOX_FACTOR = 4  # auto max_outbox = 4x the base budget


class PressureAbort(RuntimeError):
    """The pressure policy stopped the run: `abort` saw its first drop,
    or `escalate` was cornered (drops persist with no usable rung left).
    The driver still owns a state to export honest artifacts from —
    `ResilienceController.abort_export_state` documents which one."""


def _is_oom(e: BaseException) -> bool:
    """The grown-program failure signature: XLA's allocation failures
    carry RESOURCE_EXHAUSTED (jaxlib raises the status name in the
    message) or an out-of-memory text. Deliberately MESSAGE-based, not
    type-based: every XlaRuntimeError flavor shares one Python type, and
    treating a non-memory failure (INVALID_ARGUMENT, internal errors) as
    an OOM would launder a real bug into rung-poisoning fallbacks — such
    failures must propagate to the supervisor/driver instead."""
    msg = str(e)
    return "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower()


def resolve_ladder(base: int, ceiling: int, growth: int) -> list[int]:
    """Geometric shape ladder [base, base*g, ...] bounded by `ceiling`
    (inclusive). The base rung is always present; a ceiling below the
    base is a config error the options parser rejects upstream."""
    base, ceiling, growth = int(base), int(ceiling), int(growth)
    ladder = [base]
    while ladder[-1] * growth <= ceiling:
        ladder.append(ladder[-1] * growth)
    return ladder


class ResilienceController:
    """The drivers' shared chunk loop: gear shifts + capacity regrows
    from one snapshot-replay seam.

    Construction:
      gearctl   — a `core.gears.GearController` (or None: full width
                  always). Gear decisions and accounting stay in the
                  gear controller; this class only arbitrates WHEN a
                  replay is a gear problem vs a capacity problem.
      pressure  — a `config.options.PressureOptions` with an active
                  policy (escalate/abort), or None (gears only — the
                  exact `run_adaptive_chunk` behavior PR 4 shipped).
      reshard   — optional callable(state) -> state applied after a
                  migration (the mesh drivers pass a device_put onto
                  their NamedSharding specs; eager-op outputs keep
                  axis-0 sharding in simple cases but the specs are the
                  contract).
      memory    — optional `obs.memory.MemoryGuard`: pre-dispatch
                  admission for grown rungs. A candidate whose
                  predicted extra footprint (static-model delta x
                  replay concurrency x safety factor) exceeds the
                  device's MEASURED headroom is refused/poisoned
                  BEFORE dispatch — the escalation then corners into
                  the same loud PressureAbort an OOM round-trip would
                  have forced, minus the wasted compile+dispatch.
                  Inert when no allocator limit is measurable.

    `run_chunk(state, dispatch, rounds0=None)` mirrors
    `run_adaptive_chunk`: dispatch(state, gear, capacity, budget) runs
    one chunk program at that shape and may consume its input (the
    pre-chunk snapshot is an independent device copy). Returns
    (state, accepted_gear, chunk_outbox_hwm)."""

    def __init__(
        self,
        *,
        gearctl=None,
        pressure=None,
        integrity=None,
        queue_block: int = 0,
        reshard=None,
        log=None,
        memory=None,
        wall=None,
    ):
        self.gearctl = gearctl
        self.pressure = pressure
        # integrity sentinel (core/integrity.py): the third arbitration
        # branch — a chunk whose in-jit invariant guards tripped is
        # restored from the pre-chunk snapshot and replayed AT THE SAME
        # SHAPE; a violation reproducing with the same (shard, round,
        # bitmask) signature is deterministic (IntegrityAbort), one that
        # does not reproduce is transient SDC (counted, survived).
        self.integrity = integrity  # config.options.IntegrityOptions | None
        self.integrity_on = bool(
            integrity is not None and getattr(integrity, "enabled", False)
        )
        self.iv_transients = 0  # violations that did not reproduce
        self.iv_replays = 0  # chunk replays the sentinel forced
        self.iv_deterministic: dict | None = None  # the abort's naming
        # test-only state-mutation hook: callable(state, attempt) -> state
        # applied AFTER the pre-chunk snapshot, before each dispatch
        # attempt — the seam tests/test_integrity.py uses to emulate
        # in-flight SDC (a one-shot scribble must not survive into the
        # replay, exactly like real corruption of in-dispatch buffers).
        # None in production.
        self.test_scribble = None
        self.queue_block = int(queue_block)
        self._reshard = reshard
        self._log = log
        self.memory = memory  # obs.memory.MemoryGuard | None
        # runtime observatory (obs/runtime.WallLedger | None): snapshot
        # copies, restores, and replay attempts re-attribute their wall
        # out of the driver's enclosing dispatch span. Host-side only —
        # never consulted for any decision.
        self.wall = wall
        self.policy = pressure.policy if pressure is not None else "drop"
        self.escalate = self.policy == "escalate"
        self.abort_on_drop = self.policy == "abort"
        # ladders resolve lazily from the FIRST state seen (the base
        # shape lives in the state, and under a supervisor rewind the
        # state is the only truth — see run_chunk's shape derivation)
        self._cap_ladder: list[int] | None = None
        self._box_ladder: list[int] | None = None
        self._cap_poisoned: set[int] = set()  # rungs that OOM'd
        self._box_poisoned: set[int] = set()
        # accounting for sim-stats / BENCH
        self.regrows = 0  # reactive shape migrations (drop -> replay)
        self.proactive_regrows = 0  # headroom-driven boundary migrations
        self.replays = 0  # chunks replayed after a pressure abort
        self.oom_fallbacks = 0  # grown programs that OOM'd and fell back
        self.memory_refusals = 0  # rungs the memory guard refused pre-dispatch
        # last fully-refused proactive pick (cap, budget, headroom) — a
        # near-limit run re-triggers the same pick every boundary, and
        # an unchanged refusal must not re-count/re-log per chunk
        self._proactive_refused: tuple | None = None
        self.aborted = False
        self.last_error: str | None = None
        self.ob_hwm_run = 0  # run-wide outbox high-water (per-chunk resets)
        self._abort_state = None  # abort policy: the dropping state
        self._last_snap = None  # escalate: last good pre-chunk snapshot

    # ---- host-side counter reads ------------------------------------------

    @staticmethod
    def _pressure_total(state) -> int:
        """Cumulative global capacity-drop total, read host-side. Uses
        the psum'd device signal when present (policies escalate/abort)
        and falls back to summing the category counters."""
        import jax

        s = state.stats
        if getattr(s, "pressure", None) is not None:
            return int(np.asarray(jax.device_get(s.pressure)).max())
        return sum(ResilienceController._pressure_categories(state).values())

    @staticmethod
    def _pressure_categories(state) -> dict[str, int]:
        """Per-category cumulative drop totals — the growth decision's
        input (queue-side pressure grows the slab, outbox-side pressure
        grows the send budget)."""
        import jax

        s = state.stats
        return {
            "queue": int(
                np.asarray(jax.device_get(state.queue.dropped)).sum()
            ),
            "budget": int(
                np.asarray(jax.device_get(s.pkts_budget_dropped)).sum()
            ),
            "a2a": int(np.asarray(jax.device_get(s.a2a_shed)).sum()),
            "outbox": int(np.asarray(jax.device_get(s.ob_dropped)).sum()),
        }

    @classmethod
    def raise_if_dropped(cls, state, baseline: dict | None = None):
        """Raise PressureAbort naming the per-category drop deltas when
        `state` carries capacity drops past `baseline` (None = zero) —
        the one formatter the abort policy's two drivers share (the
        modeled controller's in-loop check and the hybrid driver's
        post-window check must report identically)."""
        if cls._pressure_total(state) <= (
            sum(baseline.values()) if baseline else 0
        ):
            return
        cats = cls._pressure_categories(state)
        base = baseline or {k: 0 for k in cats}
        detail = ", ".join(
            f"{k}+{v - base[k]}"
            for k, v in sorted(cats.items())
            if v > base[k]
        )
        raise PressureAbort(
            f"pressure: abort policy hit its first capacity drop ({detail})"
        )

    # ---- ladders -----------------------------------------------------------

    def _ensure_ladders(self, cap: int, budget: int):
        if self._cap_ladder is not None:
            return
        p = self.pressure
        max_cap = p.max_capacity or cap * DEFAULT_MAX_CAPACITY_FACTOR
        max_box = p.max_outbox or budget * DEFAULT_MAX_OUTBOX_FACTOR
        self._cap_ladder = resolve_ladder(cap, max_cap, p.growth_factor)
        self._box_ladder = resolve_ladder(budget, max_box, p.growth_factor)

    def _next_rung(self, ladder: list[int], cur: int, poisoned=()) -> int | None:
        for rung in ladder:
            if rung > cur and rung not in poisoned:
                return rung
        return None

    def _say(self, msg: str):
        if self._log is not None:
            print(f"[pressure] {msg}", file=self._log)

    # ---- wall attribution (obs/runtime.py; no-ops without a ledger) --------

    def _wall_move(self, to: str, sec: float):
        """Re-attribute `sec` of the driver's enclosing dispatch span to
        the snapshot/replay span — observation only."""
        if self.wall is not None:
            self.wall.reattribute("dispatch", to, sec)

    def _snap_timed(self, state):
        from shadow_tpu.core.checkpoint import snapshot_state

        t0 = time.perf_counter()
        snap = snapshot_state(state)
        self._wall_move("snapshot", time.perf_counter() - t0)
        return snap

    def _restore_timed(self, snap):
        from shadow_tpu.core.checkpoint import restore_snapshot

        t0 = time.perf_counter()
        out = restore_snapshot(snap)
        self._wall_move("replay", time.perf_counter() - t0)
        return out

    # ---- migration ---------------------------------------------------------

    def migrate(self, state, new_cap: int, new_budget: int):
        """Re-seat `state` at (new_cap, new_budget): queue planes through
        the exactness-gated grow ops, a fresh (empty) outbox at the new
        width — migrations happen at chunk boundaries, where the
        exchange has always just cleared the outbox, asserted here via
        the cheap per-shard count word. The gear ladder follows a budget
        change (the new full width becomes the ladder top, so the replay
        loop keeps its cannot-shed terminal rung)."""
        import jax

        from shadow_tpu.core.engine import make_empty_outbox
        from shadow_tpu.ops.events import migrate_queue

        cap = state.queue.t.shape[1]
        budget = state.outbox.t.shape[1]
        if new_cap != cap:
            state = state._replace(
                queue=migrate_queue(state.queue, new_cap, self.queue_block)
            )
        if new_budget != budget:
            assert (
                int(np.asarray(jax.device_get(state.outbox.count)).sum()) == 0
            ), "outbox migration outside a chunk boundary"
            state = state._replace(
                outbox=make_empty_outbox(
                    state.outbox.t.shape[0], new_budget, state.outbox.count
                )
            )
            if self.gearctl is not None:
                g = self.gearctl
                g.ladder = sorted(set(g.ladder) | {int(new_budget)})
                if g.gear not in g.ladder:
                    g.gear = g.top
        if self._reshard is not None:
            state = self._reshard(state)
        return state

    # ---- the chunk loop ----------------------------------------------------

    def run_chunk(self, state, dispatch, rounds0=None):
        """One ACCEPTED chunk, with shed-exact gear replay and drop-exact
        capacity escalation from a single pre-chunk snapshot.

        `dispatch(state, gear, capacity, budget)` runs one chunk program
        at that shape (donation-safe). `rounds0` keeps the hybrid
        drivers' zero-round guarded windows out of the gear controller,
        exactly as `run_adaptive_chunk` documents.

        Shapes are derived from the STATE, not from controller memory: a
        supervisor rewind can hand back a pre-migration state, and the
        state's own shapes are the only truth about which program runs."""
        import jax

        gearctl = self.gearctl
        gear = gearctl.gear if gearctl is not None else 0
        pressured = self.pressure is not None
        if pressured:
            cap = state.queue.t.shape[1]
            budget = state.outbox.t.shape[1]
            if self.escalate:
                self._ensure_ladders(cap, budget)
        else:
            cap = budget = 0
        need_snap = (
            (gearctl is not None and gear < gearctl.top)
            or self.escalate
            or self.integrity_on
        )
        snap = self._snap_timed(state) if need_snap else None
        self._last_snap = snap
        # integrity classifier state, chunk-scoped: the last violating
        # attempt's (shard, round, mask) signature and how many
        # sentinel-forced replays this chunk has eaten
        iv_last_sig = None
        iv_attempts = 0
        attempt_i = 0
        while True:
            shed0 = int(
                np.asarray(jax.device_get(state.stats.gear_shed)).max()
            )
            press0 = self._pressure_total(state) if pressured else 0
            cats0 = self._pressure_categories(state) if pressured else None
            iv0 = violation_total(state) if self.integrity_on else 0
            if self.test_scribble is not None:
                state = self.test_scribble(state, attempt_i)
            attempt_i += 1
            t_disp = time.perf_counter()
            comp0 = (
                self.wall.pending_to("compile")
                if self.wall is not None else 0.0
            )
            try:
                out = dispatch(state, gear, cap, budget)
                jax.block_until_ready(out)
            except (KeyboardInterrupt, SystemExit, PressureAbort,
                    IntegrityAbort):
                raise
            except Exception as e:
                grown_cap = (
                    self.escalate and cap > self._cap_ladder[0]
                )
                grown_box = (
                    self.escalate and budget > self._box_ladder[0]
                )
                if (grown_cap or grown_box) and _is_oom(e):
                    # a GROWN program could not compile/dispatch: which
                    # axis blew the budget is unknowable from here, so
                    # every axis currently above base falls back one
                    # rung and its abandoned rungs (and everything
                    # above — bigger only) are poisoned. The shrink is
                    # fits-checked against the restored snapshot (the
                    # state we actually rewind to): a lower rung the
                    # live events no longer fit would silently truncate
                    # them — the exact loss this plane exists to prevent
                    # — so an unfitting fallback corners into a loud
                    # PressureAbort instead (migrate_queue's shrink
                    # contract, ops/events.py).
                    self.oom_fallbacks += 1
                    self.last_error = f"{type(e).__name__}: {e}"
                    restored = self._restore_timed(snap)
                    lower_cap, lower_box = cap, budget
                    if grown_cap:
                        import jax.numpy as jnp

                        from shadow_tpu.ops.events import migration_fits

                        for rung in self._cap_ladder:
                            if rung >= cap:
                                self._cap_poisoned.add(rung)
                        lower_cap = next(
                            (
                                r
                                for r in sorted(self._cap_ladder, reverse=True)
                                if r < cap
                                and r not in self._cap_poisoned
                                and bool(jnp.all(
                                    migration_fits(restored.queue, r)
                                ))
                            ),
                            None,
                        )
                        if lower_cap is None:
                            self.aborted = True
                            raise PressureAbort(
                                f"pressure: cornered — grown program "
                                f"failed at capacity {cap} "
                                f"({self.last_error}) and the live events "
                                f"no longer fit any usable lower rung "
                                f"(shrinking would silently truncate them)"
                            ) from e
                    if grown_box:
                        for rung in self._box_ladder:
                            if rung >= budget:
                                self._box_poisoned.add(rung)
                        lower_box = max(
                            r for r in self._box_ladder
                            if r < budget and r not in self._box_poisoned
                        )
                    self._say(
                        f"grown program failed at (cap={cap}, "
                        f"outbox={budget}) ({self.last_error}); falling "
                        f"back to (cap={lower_cap}, outbox={lower_box})"
                    )
                    state = self.migrate(restored, lower_cap, lower_box)
                    cap, budget = lower_cap, lower_box
                    snap = self._snap_timed(state)
                    self._last_snap = snap
                    continue
                raise
            if self.wall is not None and attempt_i > 1:
                # a replay attempt's wall, minus whatever compile
                # pipeline the regrown program just paid (that part is
                # already bound for the compile span — moving it twice
                # would double-count)
                sec = time.perf_counter() - t_disp
                sec -= self.wall.pending_to("compile") - comp0
                self._wall_move("replay", sec)
            if self.integrity_on:
                # integrity arbitration FIRST: a violating attempt's
                # other counters (shed/pressure) may themselves be
                # scribbled — the attempt is discarded wholesale either
                # way, so nothing below may act on it
                ivd = violation_total(out) - iv0
                if ivd > 0:
                    sig = violation_signature(out)
                    detail = describe_signature(sig)
                    if iv_last_sig is not None and sig == iv_last_sig:
                        # reproduced at the same round with the same
                        # bitmask across a snapshot replay: the engine
                        # deterministically violates its own invariant —
                        # a real bug, never survivable
                        self.aborted = True
                        self.iv_deterministic = {
                            "signature": [list(s) for s in sig],
                            "detail": detail,
                        }
                        self.last_error = (
                            f"deterministic integrity violation: {detail}"
                        )
                        raise IntegrityAbort(
                            f"integrity: violation REPRODUCED across a "
                            f"snapshot replay (deterministic engine bug, "
                            f"not SDC) — {detail}"
                        )
                    if iv_last_sig is not None:
                        # the previous violation did not reproduce at
                        # its signature: transient SDC, counted
                        self.iv_transients += 1
                    iv_last_sig = sig
                    iv_attempts += 1
                    if iv_attempts > self.integrity.max_replays:
                        # cornered WITHOUT dispatching another replay:
                        # iv_replays counts replays that actually ran
                        # (iv_attempts - 1 here), not this refusal
                        self.aborted = True
                        self.iv_deterministic = {
                            "signature": [list(s) for s in sig],
                            "detail": detail,
                            "nonreproducing": True,
                        }
                        self.last_error = (
                            f"integrity violations persist without "
                            f"reproducing after {iv_attempts - 1} "
                            f"replays; last: {detail}"
                        )
                        raise IntegrityAbort(
                            f"integrity: cornered — {self.last_error}"
                        )
                    self.iv_replays += 1
                    self._say(
                        f"invariant violation ({detail}); restoring "
                        f"pre-chunk snapshot and replaying to classify "
                        f"(attempt {iv_attempts}/"
                        f"{self.integrity.max_replays})"
                    )
                    state = self._restore_timed(snap)
                    continue
                if iv_last_sig is not None:
                    # the replay came back clean: the violation was
                    # transient SDC — counted, logged, survived
                    self.iv_transients += 1
                    self._say(
                        "transient SDC survived: the violation did not "
                        "reproduce on replay; continuing with the clean "
                        "chunk"
                    )
                    iv_last_sig = None
            shed = (
                int(np.asarray(jax.device_get(out.stats.gear_shed)).max())
                - shed0
            )
            if shed > 0:
                # gear problem: the discarded attempt's high-water names
                # the burst that shed it (read BEFORE the restore)
                seen = int(
                    np.asarray(jax.device_get(out.stats.outbox_hwm)).max()
                )
                gear = gearctl.note_shed(seen)
                state = self._restore_timed(snap)
                continue
            if pressured:
                delta = self._pressure_total(out) - press0
                if delta > 0:
                    if self.abort_on_drop:
                        # honest stop AT the drop: the exported state
                        # includes the dropping round, counters and all
                        self.aborted = True
                        self._abort_state = out
                        self.raise_if_dropped(out, cats0)
                    state, gear, cap, budget, snap = self._escalate_replay(
                        out, cats0, snap, gear, cap, budget
                    )
                    continue
            break
        state = out
        hwm = int(np.asarray(jax.device_get(state.stats.outbox_hwm)).max())
        self.ob_hwm_run = max(self.ob_hwm_run, hwm)
        advanced = rounds0 is None or int(state.stats.rounds) > rounds0
        if gearctl is not None and advanced:
            gearctl.note_chunk(gear, hwm)
        state = state._replace(
            stats=state.stats._replace(
                outbox_hwm=state.stats.outbox_hwm * 0
            )
        )
        if self.escalate:
            state = self._proactive(state, hwm)
        self._last_snap = None
        # the gear this chunk was ACCEPTED at — note_chunk above may have
        # already moved the controller for the NEXT chunk (heartbeats and
        # gear histograms pair against what actually ran)
        return state, gear, hwm

    def _escalate_replay(self, aborted, cats0, snap, gear, cap, budget):
        """A chunk attempt dropped: pick the grown shape from the aborted
        attempt's per-category deltas, restore the pre-chunk snapshot,
        migrate, and hand the loop the new shape. Raises PressureAbort
        when cornered (a dropping axis cannot grow)."""
        cats = self._pressure_categories(aborted)
        queue_side = cats["queue"] > cats0["queue"]
        box_side = (
            cats["budget"] > cats0["budget"]
            or cats["a2a"] > cats0["a2a"]
            or cats["outbox"] > cats0["outbox"]
        )
        new_cap, new_budget = cap, budget
        if queue_side:
            up = self._next_rung(self._cap_ladder, cap, self._cap_poisoned)
            if up is None:
                self.aborted = True
                self.last_error = (
                    f"queue pressure at capacity {cap} with no usable rung "
                    f"left (ladder {self._cap_ladder}, poisoned "
                    f"{sorted(self._cap_poisoned)})"
                )
                raise PressureAbort(f"pressure: cornered — {self.last_error}")
            new_cap = up
        if box_side:
            up = self._next_rung(self._box_ladder, budget, self._box_poisoned)
            if up is None:
                self.aborted = True
                self.last_error = (
                    f"outbox pressure at budget {budget} with no usable "
                    f"rung left (ladder {self._box_ladder}, poisoned "
                    f"{sorted(self._box_poisoned)})"
                )
                raise PressureAbort(f"pressure: cornered — {self.last_error}")
            new_budget = up
        if (new_cap, new_budget) == (cap, budget):
            # drops grew but no category moved past its entry value —
            # cannot happen by construction (delta > 0 implies some
            # category grew); guard against it anyway, loudly
            self.aborted = True
            raise PressureAbort(
                "pressure: drop detected but no growth axis identified"
            )
        self._admit_or_corner(cap, budget, new_cap, new_budget)
        self.regrows += 1
        self.replays += 1
        self._say(
            f"capacity drop at (cap={cap}, outbox={budget}); replaying "
            f"chunk at (cap={new_cap}, outbox={new_budget})"
        )
        state = self.migrate(self._restore_timed(snap), new_cap, new_budget)
        snap = self._snap_timed(state)
        self._last_snap = snap
        return state, gear, new_cap, new_budget, snap

    def _admit_or_corner(self, cap, budget, new_cap, new_budget):
        """Memory-informed pre-dispatch admission (obs/memory.MemoryGuard):
        a grown rung whose predicted footprint exceeds measured headroom
        is poisoned BEFORE its compile+dispatch — and since every higher
        rung needs strictly more bytes, a refusal corners the escalation
        immediately, exactly as an exhausted ladder does. No-op without a
        guard or without a measured allocator limit."""
        if self.memory is None or (new_cap, new_budget) == (cap, budget):
            return
        ok, need, headroom = self.memory.admit(
            cap, budget, new_cap, new_budget
        )
        if ok:
            return
        self.memory_refusals += 1
        if new_cap != cap:
            for rung in self._cap_ladder:
                if rung >= new_cap:
                    self._cap_poisoned.add(rung)
        if new_budget != budget:
            for rung in self._box_ladder:
                if rung >= new_budget:
                    self._box_poisoned.add(rung)
        self.aborted = True
        self.last_error = (
            f"memory guard refused rung (cap={new_cap}, "
            f"outbox={new_budget}) before dispatch: predicted need "
            f"{need} bytes (static-model delta x replay concurrency x "
            f"safety {self.memory.safety_factor}) exceeds measured "
            f"headroom {headroom} bytes"
        )
        raise PressureAbort(f"pressure: cornered — {self.last_error}")

    def _admitted_proactive(self, cap, budget, new_cap, new_budget):
        """Proactive-growth admission. Unlike the reactive case (both
        axes DROPPED, so partial growth would just drop again), a
        proactive regrow is purely opportunistic — when the combined
        growth does not fit measured headroom, each single axis is
        retried alone, so an affordable queue-only (or outbox-only)
        migration still happens instead of the run later eating a
        reactive drop + replayed chunk. A full refusal just skips the
        boundary regrow (nothing has dropped yet). Returns the admitted
        shape."""
        if self.memory is None or (new_cap, new_budget) == (cap, budget):
            return new_cap, new_budget
        candidates = [(new_cap, new_budget)]
        for cand in ((new_cap, budget), (cap, new_budget)):
            if cand != (cap, budget) and cand not in candidates:
                candidates.append(cand)
        need_all = headroom = None
        for i, cand in enumerate(candidates):
            ok, need, headroom = self.memory.admit(cap, budget, *cand)
            if i == 0:
                need_all = need  # the COMBINED requirement, for the log
            if ok:
                if cand != (new_cap, new_budget):
                    self.memory_refusals += 1
                    self._say(
                        f"memory guard trimmed proactive regrow "
                        f"(cap={new_cap}, outbox={new_budget}) -> "
                        f"(cap={cand[0]}, outbox={cand[1]}): the combined "
                        f"growth exceeds measured headroom"
                    )
                return cand
        # full refusal. A near-limit run re-triggers the same proactive
        # pick at EVERY boundary; count/log the refusal only when the
        # situation changed (new shape, or headroom moved) so
        # memory_refusals stays a decision count, not a chunk count.
        key = (new_cap, new_budget, headroom)
        if key != self._proactive_refused:
            self._proactive_refused = key
            self.memory_refusals += 1
            self._say(
                f"memory guard skipped proactive regrow to "
                f"(cap={new_cap}, outbox={new_budget}): predicted need "
                f"{need_all} bytes > measured headroom {headroom} bytes"
            )
        return cap, budget

    def _proactive(self, state, chunk_hwm: int):
        """Boundary regrow BEFORE anything drops: the always-on
        occupancy high-water crossing the headroom threshold grows the
        queue; a chunk whose outbox high-water FILLED the budget grows
        the outbox (hwm == budget means one more send next chunk would
        be a budget drop — the gear controller's exactly-filled rule,
        applied to the shape)."""
        import jax
        import math

        p = self.pressure
        if not p.headroom:
            return state
        cap = state.queue.t.shape[1]
        budget = state.outbox.t.shape[1]
        new_cap, new_budget = cap, budget
        occ = int(np.asarray(jax.device_get(state.stats.q_occ_hwm)).max())
        if occ >= math.ceil(p.headroom * cap):
            up = self._next_rung(self._cap_ladder, cap, self._cap_poisoned)
            if up is not None:
                new_cap = up
        if chunk_hwm >= budget:
            up = self._next_rung(self._box_ladder, budget, self._box_poisoned)
            if up is not None:
                new_budget = up
        new_cap, new_budget = self._admitted_proactive(
            cap, budget, new_cap, new_budget
        )
        if (new_cap, new_budget) != (cap, budget):
            self.proactive_regrows += 1
            self._say(
                f"proactive regrow: occupancy hwm {occ}/{cap}, outbox hwm "
                f"{chunk_hwm}/{budget} -> (cap={new_cap}, "
                f"outbox={new_budget})"
            )
            state = self.migrate(state, new_cap, new_budget)
        return state

    # ---- abort/export ------------------------------------------------------

    def abort_export_state(self):
        """State the driver should export artifacts from after a
        PressureAbort: under the abort policy, the dropping state itself
        (the honest record — it includes the drop that stopped the run);
        under escalate-cornered, a fresh copy of the last good pre-chunk
        snapshot (the failed attempts were discarded). None when neither
        exists (abort before any chunk ran) — then the in-hand state is
        all there is."""
        from shadow_tpu.core.checkpoint import restore_snapshot

        if self._abort_state is not None:
            return self._abort_state
        if self._last_snap is not None:
            return restore_snapshot(self._last_snap)
        return None

    def current_shape(self, state) -> tuple[int, int]:
        """(queue_capacity, send_budget) of a state — the heartbeat's
        `cap=` source."""
        return state.queue.t.shape[1], state.outbox.t.shape[1]

    def integrity_report(self) -> dict:
        """JSON-able integrity{} accounting for sim-stats / BENCH rows:
        the transient/replay counts plus — after an IntegrityAbort — the
        deterministic violation's naming (invariants, round, shard)."""
        out: dict[str, Any] = {
            "transients": self.iv_transients,
            "replays": self.iv_replays,
            "max_replays": (
                self.integrity.max_replays if self.integrity is not None
                else 0
            ),
        }
        if self.iv_deterministic is not None:
            out["deterministic"] = self.iv_deterministic
        return out

    def report(self) -> dict:
        """JSON-able summary for sim-stats / BENCH rows."""
        out: dict[str, Any] = {
            "policy": self.policy,
            "regrows": self.regrows,
            "proactive_regrows": self.proactive_regrows,
            "replays": self.replays,
            "oom_fallbacks": self.oom_fallbacks,
        }
        if self.memory_refusals:
            out["memory_refusals"] = self.memory_refusals
        if self.memory is not None and self.memory.monitor is not None:
            hb = self.memory.monitor.headroom_bytes()
            if hb is not None:
                out["headroom_bytes"] = hb
        if self._cap_ladder is not None:
            out["capacity_ladder"] = list(self._cap_ladder)
            out["outbox_ladder"] = list(self._box_ladder)
        if self._cap_poisoned:
            out["capacity_poisoned"] = sorted(self._cap_poisoned)
        if self._box_poisoned:
            out["outbox_poisoned"] = sorted(self._box_poisoned)
        if self.aborted:
            out["aborted"] = True
        if self.last_error:
            out["last_error"] = self.last_error
        return out
